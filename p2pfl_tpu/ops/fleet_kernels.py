"""Megafleet kernels: the async fleet as ONE jitted array program.

:mod:`~p2pfl_tpu.federation.simfleet` drives the async plane as a Python
event heap — exact, but ~10⁴ heap pops/sec caps it three orders of
magnitude short of "heavy traffic from millions of users". This module
re-expresses the same run as a single ``lax.scan`` over the
chronologically sorted contribution arrivals, with the whole edge
population held as dense per-client arrays. The scan body reuses the
REAL aggregation math — :func:`~p2pfl_tpu.ops.aggregation.fedavg` over
effective weights ``num_samples · w(τ)`` and
:func:`~p2pfl_tpu.ops.aggregation.server_merge`, the exact kernels
:class:`~p2pfl_tpu.federation.buffer.BufferedAggregator` folds with
(inlined when traced inside the scan), and
:func:`staleness_weight_arr`, the elementwise twin of
:func:`~p2pfl_tpu.federation.staleness.staleness_weight` — so a
vectorized run is the same algorithm, not a lookalike.

Why a scan over sorted arrivals is EXACT for the flat topology: every
quantity the heap driver derives from event interleaving is a function
of *time* —

- a client's adoption base at a train completion ``t`` is the number of
  global versions whose push had ARRIVED by then, i.e.
  ``searchsorted(mint_times, t − adopt_delay)`` (one binary search
  against the carry's mint-time array replaces the heap's
  ``model_arrive`` events entirely);
- the buffer window an arrival joins is determined by processing
  arrivals in ``t_arr`` order — exactly the heap's pop order;
- and every mint time is the ``K``-th accepted arrival's time, which the
  scan knows at the step that fires the flush.

Because the scan is sorted by arrival time and an update's training time
precedes its arrival, every ``searchsorted`` read only ever sees mint
times that are already final — causality is the sort order. The
hierarchical program extends the same carry with vectorized per-regional
windows (one scatter row per arrival); its one deliberate approximation
is that a regional flush's aggregate is *processed* at the flush step
while its ``link_delay`` shows up only in the recorded mint time and the
adoption bookkeeping — aggregates from different regionals that would
interleave inside one in-flight window can order differently than the
heap's, which is the documented tolerance of the hierarchical parity
anchor (``docs/design.md`` "megafleet").

**Branch-free by design.** The body contains no ``lax.cond``: XLA
double-buffers carry arrays that cross a conditional boundary, and a
per-step copy of the ``[R, K, dim]`` regional windows turns a 4M-event
scan into terabytes of memcpy (measured: 5× the per-event cost at 1M
clients vs 100k before this layout). Instead every step executes the
same straight-line program — predicated scatters into the big carries
(in-place under ``scan``) and an unconditionally computed window fold
whose result is ``where``-masked by the flush predicate. A not-yet-full
window's fold is garbage (even ``0/0`` when empty) that the mask
discards; the extra fold per event is ~100 flops on a ``[K, dim]``
window — noise next to the copies it replaces.

**The cross-buffer copy law** (measured on XLA:CPU, jax 0.4.37; every
rule below is worth ~3 orders of magnitude at 1M clients):

- writing carry ``A`` with a value that reads carry ``B``'s *pre-update*
  state while ``B`` is also written in the same step makes XLA preserve
  ``B`` with a full copy per step — a read→write pair it cannot
  linearize. Copies of an ``[N, …]`` buffer per event are catastrophic.
- Fix 1 — *re-gather*: when the dependent write wants the POST-update
  value, read it back from the already-updated carry (``w_cur``,
  ``agg_params`` below) instead of reusing the temporary that also fed
  the first write. The dataflow becomes linear and everything updates in
  place.
- Fix 2 — *pack coupled state into one buffer*: the adoption bookkeeping
  (``base_seen``) is read to pick the train branch and written every
  step; as a separate ``[N]`` carry it pairs with the ``w`` write and
  re-copies itself per event. It rides as column ``dim`` of the ``w``
  rows instead (f32 — exact for versions < 2²⁴), making adopt+train a
  single-buffer read-modify-write.
- Residual pairs are left where ``B`` is small and R-bounded (``rcount``
  / ``radopt`` / ``mint`` / ``G``): their per-step copies are KB-scale
  in the hierarchical shape. This is also why the FLAT program is the
  1k-parity anchor rather than the fleet-scale engine — its ``G``/
  ``mint`` histories grow with total merges, and the copy law would
  re-copy them per event at 1M clients; the hierarchical shape (the
  production topology) keeps them at the global-version count.

The jit-staleness contract: nothing in a scan body reads ``Settings`` or
mutable module state — every knob (α, η, K, staleness bound, rate gaps)
arrives through the static :class:`FleetConfig`, so a config change
provably re-traces.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp

from p2pfl_tpu.ops.aggregation import fedavg, server_merge

Pytree = Any

#: sort key for empty window slots — pads order last and carry weight 0,
#: so they add exact +0.0 terms to the fold (see fold_window)
PAD_KEY = jnp.iinfo(jnp.int32).max


class FleetConfig(NamedTuple):
    """Static shape/knob tuple baked into one compiled fleet program.

    Everything here participates in the trace (a changed value compiles a
    new program — the jit-staleness rule's explicit-argument contract).
    ``rate_gap_*`` are the Bonawitz per-tier rate limits in virtual
    seconds between *accepted* offers (0 disables the gate and compiles
    it out); ``hist_bins`` sizes the staleness histograms (the last bin
    absorbs the tail).
    """

    hier: bool  #: two-tier (regional windows + global) vs flat
    n_clients: int
    dim: int  #: consensus-task parameter dimension
    n_regionals: int  #: R (1 in flat mode; regional 0 is the global root)
    k_global: int  #: global window size (flat: the only window)
    k_reg_max: int  #: widest regional window (per-regional K in reg["k"])
    v_cap: int  #: global version capacity (host-computed upper bound)
    alpha: float  #: FedBuff staleness exponent
    server_lr: float  #: η of the server merge
    local_lr: float  #: consensus-task pull rate toward the private target
    max_staleness: int
    rate_gap_reg: float
    rate_gap_glob: float
    hist_bins: int
    agg_key_stride: int  #: fold-key stride for (regional, up_seq) keys
    unroll: int  #: lax.scan unroll factor


def staleness_weight_arr(tau: jax.Array, alpha: float) -> jax.Array:
    """Elementwise FedBuff weight ``w(τ) = 1/(1+τ)^α`` — the array twin
    of :func:`p2pfl_tpu.federation.staleness.staleness_weight` (same
    clamp, same formula, f32; pointwise parity pinned by test). ``alpha``
    is static: 0 compiles to ones like the scalar's early-out."""
    t = jnp.maximum(tau.astype(jnp.float32), 0.0)
    if float(alpha) == 0.0:
        return jnp.ones_like(t)
    return 1.0 / (1.0 + t) ** jnp.float32(alpha)


def fold_window(
    rows: jax.Array,
    weights: jax.Array,
    keys: jax.Array,
    prev: jax.Array,
    server_lr: float,
) -> jax.Array:
    """One buffer flush on a dense window — exactly the live
    :meth:`BufferedAggregator._merge_locked` math: sort the window by its
    ``(origin, seq)`` fold keys, :func:`fedavg` over the effective
    weights, :func:`server_merge` into ``prev``. Empty pad slots
    (``weights == 0``, ``keys == PAD_KEY``) sort last and contribute
    exact ``+0.0`` terms, so a clamped-K regional window folds
    bit-identically to a dense K-length fold. (An ALL-empty window
    divides 0/0 — callers inside the scan mask the result with the flush
    predicate, which is False exactly then.)

    ``rows [K, dim]``, ``weights [K]``, ``prev [dim]``; ``server_lr`` is
    static. Reuses the SAME jitted kernels the live buffer calls — under
    an outer trace they inline, standalone they dispatch once each.
    """
    order = jnp.argsort(keys)
    sorted_rows = jnp.take(rows, order, axis=0)
    sorted_w = jnp.take(weights, order)
    avg = fedavg({"p": sorted_rows}, sorted_w, agg_dtype="float32")["p"]
    return server_merge({"p": prev}, {"p": avg}, lr=server_lr, agg_dtype="float32")["p"]


def _init_carry(cfg: FleetConfig, init_params) -> Dict[str, jax.Array]:
    n, dim, r = cfg.n_clients, cfg.dim, cfg.n_regionals
    row0 = jnp.concatenate(
        [jnp.asarray(init_params, jnp.float32), jnp.zeros((1,), jnp.float32)]
    )
    carry = {
        # per-client lazy state: current params, with the highest adopted
        # version packed as column `dim` (the cross-buffer copy law — a
        # separate [N] base_seen carry would be re-copied per event)
        "w": jnp.broadcast_to(row0, (n, dim + 1)).astype(jnp.float32),
        # global model history: G[v] = params of version v (G[0] = init);
        # mint[v-1] = virtual time version v was minted (+inf = unminted)
        "G": jnp.zeros((cfg.v_cap + 1, dim), jnp.float32).at[0].set(init_params),
        "mint": jnp.full((cfg.v_cap,), jnp.inf, jnp.float32),
        "last_mint": jnp.float32(-jnp.inf),
        "version": jnp.int32(0),
        # global window
        "gbuf": jnp.zeros((cfg.k_global, dim), jnp.float32),
        "gwt": jnp.zeros((cfg.k_global,), jnp.float32),
        "gkey": jnp.full((cfg.k_global,), PAD_KEY, jnp.int32),
        "gcount": jnp.int32(0),
        "last_acc_g": jnp.float32(-jnp.inf),
        # counters + staleness histograms, split by seam: "edge" = where
        # client updates enter a window (the regional tier, or the global
        # window in flat mode), "agg" = where regional aggregates enter
        # the global window (hier only)
        "merges": jnp.int32(0),
        "stale_edge": jnp.int32(0),
        "rate_edge": jnp.int32(0),
        "stale_agg": jnp.int32(0),
        "rate_agg": jnp.int32(0),
        "hist_edge": jnp.zeros((cfg.hist_bins,), jnp.int32),
        "hist_glob": jnp.zeros((cfg.hist_bins,), jnp.int32),
    }
    if cfg.hier:
        carry.update(
            {
                # vectorized regional tier: one window + lazily-adopted
                # params per regional, all scatter-addressed by r
                "rbuf": jnp.zeros((r, cfg.k_reg_max, dim), jnp.float32),
                "rwt": jnp.zeros((r, cfg.k_reg_max), jnp.float32),
                "rsamp": jnp.zeros((r, cfg.k_reg_max), jnp.float32),
                "rkey": jnp.full((r, cfg.k_reg_max), PAD_KEY, jnp.int32),
                "rcount": jnp.zeros((r,), jnp.int32),
                "rparams": jnp.broadcast_to(init_params, (r, dim)).astype(jnp.float32),
                "radopt": jnp.zeros((r,), jnp.int32),
                "up_seq": jnp.zeros((r,), jnp.int32),
                "last_acc_r": jnp.full((r,), -jnp.inf, jnp.float32),
                "rmerges": jnp.int32(0),
                "agg_drop": jnp.int32(0),
            }
        )
    return carry


def run_fleet_program(
    cfg: FleetConfig,
    events: Dict[str, jax.Array],
    clients: Dict[str, jax.Array],
    reg: Dict[str, jax.Array],
    init_params: jax.Array,
) -> Dict[str, Any]:
    """Compile and run the fleet scan. ``events`` are the pre-sorted
    arrival rows (``client/key/t_train/t_arr/send_ok``, each ``[E]``);
    ``clients`` holds ``targets [N, dim]``, ``samples [N]``,
    ``adopt_delay [N]`` and (hier) ``regional_of [N]``; ``reg`` holds the
    per-regional ``k``, ``adopt_delay`` and ``agg_delay`` arrays. Returns
    the final carry (host-side consumers slice ``G``/``mint`` by
    ``version``). One compile per :class:`FleetConfig`.
    """

    def offer_global(c, accept, params, wgt, key, tau, t_evt, seam):
        """Predicated offer into the global window + masked flush.
        ``seam`` ("edge" | "agg") is a trace-time label selecting which
        counter/histogram family the admission feeds."""
        fresh = tau <= cfg.max_staleness
        if cfg.rate_gap_glob > 0.0:
            rate_ok = (t_evt - c["last_acc_g"]) >= cfg.rate_gap_glob
        else:
            rate_ok = jnp.bool_(True)
        ins = accept & fresh & rate_ok
        hist = "hist_edge" if seam == "edge" else "hist_glob"
        c[f"stale_{seam}"] = c[f"stale_{seam}"] + (accept & ~fresh).astype(jnp.int32)
        c[f"rate_{seam}"] = c[f"rate_{seam}"] + (
            accept & fresh & ~rate_ok
        ).astype(jnp.int32)

        slot = c["gcount"]
        c["gbuf"] = c["gbuf"].at[slot].set(jnp.where(ins, params, c["gbuf"][slot]))
        c["gwt"] = c["gwt"].at[slot].set(jnp.where(ins, wgt, c["gwt"][slot]))
        c["gkey"] = c["gkey"].at[slot].set(jnp.where(ins, key, c["gkey"][slot]))
        c["last_acc_g"] = jnp.where(ins, t_evt, c["last_acc_g"])
        c[hist] = c[hist].at[jnp.clip(tau, 0, cfg.hist_bins - 1)].add(
            ins.astype(jnp.int32)
        )
        count = c["gcount"] + ins.astype(jnp.int32)
        flush = ins & (count == cfg.k_global)
        c["gcount"] = jnp.where(flush, 0, count)

        # the fold runs every step (garbage when not flushing, masked
        # below) — cheaper than letting the window cross a cond boundary
        new_g = fold_window(
            c["gbuf"], c["gwt"], c["gkey"], c["G"][c["version"]], cfg.server_lr
        )
        v = c["version"] + flush.astype(jnp.int32)
        c["G"] = c["G"].at[v].set(jnp.where(flush, new_g, c["G"][v]))
        # the recorded mint time is clamped monotone: out-of-order
        # aggregate arrival times (the hier ordering tolerance) must not
        # make the searchsorted axis non-ascending
        t_mint = jnp.maximum(t_evt, c["last_mint"])
        mi = jnp.where(flush, v - 1, 0)
        c["mint"] = c["mint"].at[mi].set(jnp.where(flush, t_mint, c["mint"][mi]))
        c["last_mint"] = jnp.where(flush, t_mint, c["last_mint"])
        c["version"] = v
        c["merges"] = c["merges"] + flush.astype(jnp.int32)
        empty_w = jnp.zeros((cfg.k_global,), jnp.float32)
        empty_k = jnp.full((cfg.k_global,), PAD_KEY, jnp.int32)
        c["gwt"] = jnp.where(flush, empty_w, c["gwt"])
        c["gkey"] = jnp.where(flush, empty_k, c["gkey"])
        return c

    def offer_regional(c, accept, r, params, raw_samples, wgt, key, tau, rv, t_arr):
        """Predicated offer into regional ``r``; a full window flushes
        into the regional params and sends the aggregate up."""
        fresh = tau <= cfg.max_staleness
        if cfg.rate_gap_reg > 0.0:
            rate_ok = (t_arr - c["last_acc_r"][r]) >= cfg.rate_gap_reg
        else:
            rate_ok = jnp.bool_(True)
        ins = accept & fresh & rate_ok
        c["stale_edge"] = c["stale_edge"] + (accept & ~fresh).astype(jnp.int32)
        c["rate_edge"] = c["rate_edge"] + (accept & fresh & ~rate_ok).astype(jnp.int32)

        slot = c["rcount"][r]
        c["rbuf"] = c["rbuf"].at[r, slot].set(jnp.where(ins, params, c["rbuf"][r, slot]))
        c["rwt"] = c["rwt"].at[r, slot].set(jnp.where(ins, wgt, c["rwt"][r, slot]))
        c["rsamp"] = c["rsamp"].at[r, slot].set(
            jnp.where(ins, raw_samples, c["rsamp"][r, slot])
        )
        c["rkey"] = c["rkey"].at[r, slot].set(jnp.where(ins, key, c["rkey"][r, slot]))
        c["last_acc_r"] = c["last_acc_r"].at[r].set(
            jnp.where(ins, t_arr, c["last_acc_r"][r])
        )
        c["hist_edge"] = c["hist_edge"].at[jnp.clip(tau, 0, cfg.hist_bins - 1)].add(
            ins.astype(jnp.int32)
        )
        count = c["rcount"][r] + ins.astype(jnp.int32)
        flush = ins & (count == reg["k"][r])
        c["rcount"] = c["rcount"].at[r].set(jnp.where(flush, 0, count))

        # regional flush (masked): current params = lazily-adopted
        # freshest arrived global (set_global semantics — only the last
        # adoption before the flush matters), fold, push the aggregate up
        cur = jnp.where(rv > c["radopt"][r], c["G"][rv], c["rparams"][r])
        merged = fold_window(c["rbuf"][r], c["rwt"][r], c["rkey"][r], cur, cfg.server_lr)
        raw = jnp.sum(c["rsamp"][r])
        c["rparams"] = c["rparams"].at[r].set(jnp.where(flush, merged, c["rparams"][r]))
        # same re-gather trick as w_cur: the aggregate pushed upward reads
        # the updated rparams row (== merged whenever flush, the only
        # predicate under which offer_global consumes it) so `merged`
        # never feeds two carry buffers
        agg_params = c["rparams"][r]
        c["radopt"] = c["radopt"].at[r].set(
            jnp.where(flush, jnp.maximum(c["radopt"][r], rv), c["radopt"][r])
        )
        c["rmerges"] = c["rmerges"] + flush.astype(jnp.int32)
        up = c["up_seq"][r] + flush.astype(jnp.int32)
        c["up_seq"] = c["up_seq"].at[r].set(up)
        empty_w = jnp.zeros((cfg.k_reg_max,), jnp.float32)
        empty_k = jnp.full((cfg.k_reg_max,), PAD_KEY, jnp.int32)
        c["rwt"] = c["rwt"].at[r].set(jnp.where(flush, empty_w, c["rwt"][r]))
        c["rsamp"] = c["rsamp"].at[r].set(jnp.where(flush, empty_w, c["rsamp"][r]))
        c["rkey"] = c["rkey"].at[r].set(jnp.where(flush, empty_k, c["rkey"][r]))

        # the upward aggregate: version triple (r, up, rv) with effective
        # weight raw_samples · w(τ_g) — processed now, arrival-time
        # bookkeeping via the regional's agg_delay (0 for the root's own
        # cluster: a direct offer). The regional→root hop is a real wire
        # in the heap driver, so it sees the fault plan too: per-send
        # drop verdicts and jitter from the host-precomputed
        # (regional, up_seq) grids (all-pass / zero when no plan).
        sidx = jnp.clip(up - 1, 0, reg["send_ok"].shape[1] - 1)
        agg_ok = reg["send_ok"][r, sidx]
        t_agg = t_arr + reg["agg_delay"][r] + reg["jit"][r, sidx]
        c["agg_drop"] = c["agg_drop"] + (flush & ~agg_ok).astype(jnp.int32)
        tau_g = jnp.maximum(c["version"] - rv, 0)
        gwgt = raw * staleness_weight_arr(tau_g, cfg.alpha)
        gkey = r * cfg.agg_key_stride + up
        return offer_global(
            c, flush & agg_ok, agg_params, gwgt, gkey, tau_g, t_agg, "agg"
        )

    def body(c, e):
        i = e["client"]
        # ---- adopt + train (always: a wire drop loses the SEND, not the
        # local step — heap semantics). The train step is distributed
        # into the two adoption branches with the heap's exact arithmetic
        # order (x + lr·(t − x)) so each branch is bit-identical to the
        # event driver's numpy step.
        base = jnp.searchsorted(
            c["mint"], e["t_train"] - clients["adopt_delay"][i]
        ).astype(jnp.int32)
        row = c["w"][i]
        wvec, prev = row[: cfg.dim], row[cfg.dim]
        base_f = base.astype(jnp.float32)
        adopt = base_f > prev
        g = c["G"][base]
        ti = clients["targets"][i]
        lr = jnp.float32(cfg.local_lr)
        new_vec = jnp.where(adopt, g + lr * (ti - g), wvec + lr * (ti - wvec))
        new_base = jnp.maximum(base_f, prev)
        c["w"] = c["w"].at[i].set(jnp.concatenate([new_vec, new_base[None]]))
        # re-gather from the UPDATED carry instead of reusing the new_vec
        # temporary: one value feeding two carry buffers (the w scatter
        # above + a window scatter below) defeats XLA's in-place buffer
        # reuse and re-copies the whole [N, dim] state per step —
        # measured 1000× the per-event cost at 100k clients
        row_cur = c["w"][i]
        w_cur = row_cur[: cfg.dim]
        base_eff = row_cur[cfg.dim].astype(jnp.int32)

        ok = e["send_ok"]
        samples = clients["samples"][i]
        if cfg.hier:
            r = clients["regional_of"][i]
            rv = jnp.searchsorted(
                c["mint"], e["t_arr"] - reg["adopt_delay"][r]
            ).astype(jnp.int32)
            tau = jnp.maximum(rv - base_eff, 0)
            wgt = samples * staleness_weight_arr(tau, cfg.alpha)
            c = offer_regional(
                c, ok, r, w_cur, samples, wgt, e["key"], tau, rv, e["t_arr"]
            )
        else:
            tau = jnp.maximum(c["version"] - base_eff, 0)
            wgt = samples * staleness_weight_arr(tau, cfg.alpha)
            c = offer_global(c, ok, w_cur, wgt, e["key"], tau, e["t_arr"], "edge")
        return c, None

    @jax.jit
    def program(events, carry):
        carry, _ = jax.lax.scan(body, carry, events, unroll=cfg.unroll)
        return carry

    carry = _init_carry(cfg, init_params)
    return program(events, carry)
