"""Flash-attention kernel autotuner: per-shape config search + caches.

Resolution order for ``flash_attention(config=None)`` — every step is a
pure lookup safe to run at trace time:

1. **Pinned** configs (:func:`pin_flash_config`) — the explicit override.
2. **In-process cache** — results of :func:`autotune_flash` this process,
   plus anything already loaded from disk.
3. **On-disk cache** — JSON at ``Settings.FLASH_TUNE_CACHE`` (default
   ``~/.cache/p2pfl_tpu/flash_tune.json``), loaded once per process.
   Entries are keyed on **device kind** (``TPU v4`` / ``TPU v5 lite`` /
   ``cpu`` …) plus (head_dim, seq_len, dtype, causal), so a cache written
   on one platform never mis-tunes another.
4. **Shipped defaults tables** (:data:`DEFAULTS`) — the measured
   per-device-family block recipes, clamped to divide the actual sequence
   length.

:func:`autotune_flash` is the only step that runs kernels: it sweeps
candidate ``(block_q, block_k, q_span)`` forward schedules, then
``(bwd_mode, backward blocks)`` on the winner, timing real fwd / fwd+bwd
executions, and writes the result into both caches. It must be called
OUTSIDE any jit trace (it compiles and runs programs); everything else is
trace-safe.

The reference has no kernels to tune (SURVEY §2.9); this exists so the
flash forward's work partitioning is chosen per (D, seq, dtype) the way
FlashAttention-2-style partitioning is, instead of hard-coded blocks being
lucky on one shape and 1.5× off on another (round-5 verdict).
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from p2pfl_tpu.ops.flash_attention import FlashConfig

# in-process config cache: key (see _key) -> FlashConfig
_MEM_CACHE: dict[str, FlashConfig] = {}
# explicit pins (pin_flash_config): session-only overrides that win over
# everything and are NEVER persisted — a pin is an experiment, not a
# measurement, and must not masquerade as tuned data in the disk cache
_PINNED: dict[str, FlashConfig] = {}
_DISK_LOADED: set[str] = set()  # cache paths already merged into _MEM_CACHE


def device_kind() -> str:
    """The tuning-cache platform key: TPU device kind, else backend name."""
    try:
        dev = jax.devices()[0]
        if dev.platform == "tpu":
            return dev.device_kind
        return dev.platform  # "cpu" / "gpu" — interpret-mode territory
    except Exception:  # pragma: no cover — no backend at all
        return "cpu"


def _dtype_tag(dtype) -> str:
    return jnp.dtype(dtype).name


def _key(kind: str, d: int, t: int, dtype, causal: bool) -> str:
    return f"{kind}|d={d}|t={t}|{_dtype_tag(dtype)}|{'causal' if causal else 'full'}"


def cache_path() -> Path:
    from p2pfl_tpu.settings import Settings

    p = getattr(Settings, "FLASH_TUNE_CACHE", "") or os.environ.get(
        "P2PFL_FLASH_TUNE_CACHE", ""
    )
    if p:
        return Path(p).expanduser()
    return Path.home() / ".cache" / "p2pfl_tpu" / "flash_tune.json"


def _fit(t: int, n: int) -> int:
    """Largest divisor of t that is <= n and a multiple of 8 (Mosaic's
    tiling rule), falling back to t itself (block == T always tiles)."""
    got = next((b for b in range(min(n, t), 7, -1) if t % b == 0 and b % 8 == 0), None)
    return got or t


def _clamped(t: int, block_q: int, block_k: int, q_span: int = 1, **kw) -> FlashConfig:
    from p2pfl_tpu.ops.flash_attention import _fit_q_span

    bq, bk = _fit(t, block_q), _fit(t, block_k)
    return FlashConfig(block_q=bq, block_k=bk, q_span=_fit_q_span(t, bq, q_span), **kw)


# Shipped per-device-family recipes (functions of (t, d) → FlashConfig).
# v4/v5e numbers come from the bench config-7 sweeps (block 512 beat 256 at
# every measured length; fused bwd keeps the forward's blocks); narrow heads
# (D <= 64) take q_span=2 — each program owning two q sub-tiles amortizes
# the grid bookkeeping that dominates when the per-block matmuls are small,
# while per-sub-tile causal frontiers keep the masked-work fraction of the
# single-block schedule. CPU/interpret keeps small blocks so the unrolled
# interpret grid stays compilable.
DEFAULTS = {
    "v4": lambda t, d: _clamped(t, 512, 512, q_span=2 if d <= 64 else 1),
    "v5e": lambda t, d: _clamped(t, 512, 512, q_span=2 if d <= 64 else 1),
    "cpu": lambda t, d: _clamped(t, 128, 128),
}


def _family(kind: str) -> str:
    k = kind.lower()
    if "v5 lite" in k or "v5e" in k or "v5lite" in k:
        return "v5e"
    if "v4" in k:
        return "v4"
    if "tpu" in k:  # unknown TPU generation: the v5e recipe is the safer bet
        return "v5e"
    return "cpu"


def default_flash_config(
    t: int, d: int, dtype=jnp.bfloat16, causal: bool = True, kind: Optional[str] = None
) -> FlashConfig:
    """The shipped defaults-table config for this shape (no caches)."""
    del dtype, causal  # tables are currently shape-driven only
    return DEFAULTS[_family(kind or device_kind())](t, d)


def _load_disk(path: Path) -> None:
    tag = str(path)
    if tag in _DISK_LOADED:
        return
    _DISK_LOADED.add(tag)
    try:
        raw = json.loads(path.read_text())
    except (OSError, ValueError):
        return
    for key, fields in raw.items():
        try:
            _MEM_CACHE.setdefault(key, FlashConfig(**fields))
        except (TypeError, ValueError):
            continue  # unknown/garbage entry: defaults still apply


def _save_disk(path: Path) -> None:
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        data = {k: dataclasses_asdict(v) for k, v in sorted(_MEM_CACHE.items())}
        path.write_text(json.dumps(data, indent=2, sort_keys=True))
    except OSError:  # read-only home etc. — tuning still works in-process
        pass


def dataclasses_asdict(cfg: FlashConfig) -> dict:
    import dataclasses

    return dataclasses.asdict(cfg)


def clear_memory_cache() -> None:
    """Drop in-process tuning state (tests; disk cache files are kept)."""
    _MEM_CACHE.clear()
    _PINNED.clear()
    _DISK_LOADED.clear()


def pin_flash_config(
    t: int, d: int, config: FlashConfig, dtype=jnp.bfloat16, causal: bool = True,
    kind: Optional[str] = None,
) -> None:
    """Pin an explicit config for a shape — wins over tuned/default.
    Session-only: pins are never written to the on-disk tuning cache."""
    _PINNED[_key(kind or device_kind(), d, t, dtype, causal)] = config


def get_flash_config(
    t: int, d: int, dtype=jnp.bfloat16, causal: bool = True, kind: Optional[str] = None
) -> FlashConfig:
    """Trace-safe config lookup: pinned → tuned (memory → disk) → defaults."""
    kind = kind or device_kind()
    key = _key(kind, d, t, dtype, causal)
    got = _PINNED.get(key) or _MEM_CACHE.get(key)
    if got is not None:
        return got
    _load_disk(cache_path())
    got = _MEM_CACHE.get(key)
    if got is not None:
        return got
    return default_flash_config(t, d, dtype, causal, kind)


def candidate_configs(t: int, d: int, max_candidates: int = 12) -> list[FlashConfig]:
    """The forward sweep space: (block_q, block_k, q_span) combinations that
    divide t, tile on Mosaic, and keep the q-residency reasonable."""
    blocks = sorted({_fit(t, b) for b in (128, 256, 512)})
    spans = (1, 2, 4)
    out: list[FlashConfig] = []
    seen = set()
    for bq in blocks:
        for bk in blocks:
            for span in spans:
                if (t // bq) % span != 0:
                    continue
                if bq * span > t:
                    continue
                cfg = FlashConfig(block_q=bq, block_k=bk, q_span=span)
                sig = (cfg.block_q, cfg.block_k, cfg.q_span)
                if sig in seen:
                    continue
                seen.add(sig)
                out.append(cfg)
    # prefer larger tiles first (the measured winners) so a truncated sweep
    # still sees the likely-best region
    out.sort(key=lambda c: (-c.block_q * c.q_span, -c.block_k))
    return out[:max_candidates]


def _time_fn(fn, args, repeats: int) -> float:
    from p2pfl_tpu.management.profiling import force_execution

    out = fn(*args)
    force_execution(out)  # compile + warm (real device-to-host fetch)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fn(*args)
        force_execution(out)
        best = min(best, time.perf_counter() - t0)
    return best


def amortize_iters(t: int) -> int:
    """Kernel executions chained per timed dispatch. Production runs the
    kernel inside a compiled train step, so candidates must be scored
    dispatch-amortized too — un-amortized, every small-shape measurement
    reads ~the same per-dispatch overhead and sweeps pick noise (the same
    correction bench_suite's _fused_timer applies; design.md "Measurement
    methodology")."""
    return max(2, 4096 // t)


def time_flash_fwd(
    q, k, v, config: FlashConfig, *, causal: bool = True,
    interpret: bool = False, iters: int = 1, repeats: int = 2,
) -> float:
    """Seconds per forward execution: ``iters`` data-chained kernel calls
    inside ONE jitted scan, min over ``repeats``, ending on a device fetch.
    The ONE flash timing harness — the autotuner scores candidates with it
    and bench_kernels.py reports with it, so the two stay comparable."""
    from jax import lax

    from p2pfl_tpu.ops.flash_attention import flash_attention

    @jax.jit
    def many(q, k, v):
        def body(q, _):
            o = flash_attention(q, k, v, causal, config, interpret)
            # data-dependent chain (a *0.0 chain folds to identity and the
            # loop gets DCE'd — measured 0.0 ms in bench_suite)
            return q + (o * 1e-30).astype(q.dtype), None

        q, _ = lax.scan(body, q, None, length=iters)
        return q

    return _time_fn(many, (q, k, v), repeats) / iters


def time_flash_train(
    q, k, v, config: FlashConfig, *, causal: bool = True,
    interpret: bool = False, iters: int = 1, repeats: int = 2,
) -> float:
    """Seconds per fwd+bwd execution (grad of a scalar loss), chained and
    timed like :func:`time_flash_fwd`. The loss is sum(out²), NOT sum(out):
    a constant all-ones cotangent lets XLA const-fold the dO·Vᵀ block
    matmuls into reductions at some block shapes — measured 2× "backwards"
    that weren't executing the backward's matmul count."""
    from jax import lax

    from p2pfl_tpu.ops.flash_attention import flash_attention

    def loss(q, k, v):
        o = flash_attention(q, k, v, causal, config, interpret)
        return jnp.sum(o * o)  # dO = 2·out: data-dependent cotangent

    grad = jax.grad(loss, argnums=(0, 1, 2))

    @jax.jit
    def many(q, k, v):
        def body(carry, _):
            q_, k_, v_ = carry
            dq, dk, dv = grad(q_, k_, v_)
            return (
                q_ + (dq * 1e-30).astype(q_.dtype),
                k_ + (dk * 1e-30).astype(k_.dtype),
                v_ + (dv * 1e-30).astype(v_.dtype),
            ), None

        carry, _ = lax.scan(body, (q, k, v), None, length=iters)
        return carry

    return _time_fn(many, (q, k, v), repeats) / iters


def autotune_flash(
    t: int,
    d: int,
    dtype=None,
    causal: bool = True,
    *,
    batch: int = 1,
    heads: int = 2,
    repeats: int = 2,
    iters: Optional[int] = None,
    candidates: Optional[Sequence[FlashConfig]] = None,
    tune_bwd: bool = True,
    interpret: Optional[bool] = None,
    cache: bool = True,
    force: bool = False,
    kind: Optional[str] = None,
) -> FlashConfig:
    """Sweep kernel schedules for one (T, D, dtype, causal) shape and cache
    the winner. An existing tuned entry (in-process or on-disk) is returned
    WITHOUT re-sweeping unless ``force=True`` — so FLASH_AUTOTUNE model
    builds pay the sweep once per shape per cache lifetime, not per build.
    Two stages: forward over ``candidates`` (default
    :func:`candidate_configs`), then backward mode/blocks on the forward
    winner (fused-with-fwd-blocks vs split-with-upsized-blocks). Scores
    come from :func:`time_flash_fwd` / :func:`time_flash_train`
    (dispatch-amortized — see :func:`amortize_iters`). NOT trace-safe —
    call from setup code, never inside jit.
    """
    on_tpu = jax.default_backend() == "tpu"
    interpret = (not on_tpu) if interpret is None else interpret
    dtype = dtype if dtype is not None else (jnp.bfloat16 if on_tpu else jnp.float32)
    kind = kind or device_kind()
    iters = iters if iters is not None else amortize_iters(t)

    if cache and not force:
        key = _key(kind, d, t, dtype, causal)
        _load_disk(cache_path())
        got = _PINNED.get(key) or _MEM_CACHE.get(key)
        if got is not None:
            return got

    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (
        jax.random.normal(s, (batch, t, heads, d)).astype(dtype) for s in keys
    )

    def fwd_time(cfg: FlashConfig) -> float:
        return time_flash_fwd(
            q, k, v, cfg, causal=causal, interpret=interpret,
            iters=iters, repeats=repeats,
        )

    def train_time(cfg: FlashConfig) -> float:
        return time_flash_train(
            q, k, v, cfg, causal=causal, interpret=interpret,
            iters=iters, repeats=repeats,
        )

    cands = list(candidates) if candidates is not None else candidate_configs(t, d)
    timed = [(fwd_time(c), c) for c in cands]
    _, best_fwd = min(timed, key=lambda x: x[0])

    best = best_fwd
    if tune_bwd:
        import dataclasses

        bwd_cands = [
            dataclasses.replace(best_fwd, bwd_mode="fused"),
            dataclasses.replace(best_fwd, bwd_mode="split"),
        ]
        big = _fit(t, 1024)
        if big > best_fwd.block_q:
            bwd_cands.append(
                dataclasses.replace(
                    best_fwd, bwd_mode="split", block_q_bwd=big, block_k_bwd=big
                )
            )
        _, best = min(((train_time(c), c) for c in bwd_cands), key=lambda x: x[0])

    if cache:
        _MEM_CACHE[_key(kind, d, t, dtype, causal)] = best
        # merge existing on-disk entries before writing: a force=True tune
        # skips the read path above, and saving bare _MEM_CACHE would clobber
        # every other shape/device entry the file holds (_load_disk's
        # setdefault keeps the fresh winner over the stale disk copy)
        _load_disk(cache_path())
        _save_disk(cache_path())
    return best
