"""Pytree arithmetic used by aggregators and learners.

The reference performs aggregation as a Python loop over ``state_dict``
layers (``p2pfl/learning/aggregators/fedavg.py:43-60``). Here every
aggregation is a single jitted function over the whole pytree, so XLA fuses
the per-layer arithmetic into a handful of kernels and the data never leaves
the device.

Accumulation happens in ``Settings.AGG_DTYPE`` (float32) regardless of the
storage dtype (typically bfloat16), then is cast back — bf16 gossip payloads
with fp32-exact averaging.
"""

from __future__ import annotations

import threading
from typing import Any, Sequence

import jax
import jax.numpy as jnp

Pytree = Any


def tree_zeros_like(tree: Pytree) -> Pytree:
    return jax.tree.map(jnp.zeros_like, tree)


def tree_add(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree.map(jnp.add, a, b)


def tree_sub(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree.map(jnp.subtract, a, b)


def tree_scale(tree: Pytree, s) -> Pytree:
    return jax.tree.map(lambda x: x * jnp.asarray(s, dtype=x.dtype), tree)


def tree_stack(trees: Sequence[Pytree]) -> Pytree:
    """Stack N structurally-identical pytrees into one pytree of [N, ...] arrays."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


#: per-THREAD count of leaves :func:`tree_align_devices` actually had to
#: re-place (one ``device_put`` each). The shard-native ICI weights plane
#: (``communication/ici.py``) delivers payloads already on the receiver's
#: shardings, so its no-fix-up contract is *measurable*: the counter stays
#: flat across an ICI round while the zero-copy memory transport's cross-
#: slice deliveries still count theirs (FedAvg logs the per-aggregate delta
#: as the ``tree_align_copies`` comm metric). Thread-local deliberately:
#: every consumer measures a before/after DELTA around its own call on its
#: own thread — a process-global counter would let one gossip worker's
#: copies land inside another node's open delta window (a multi-node
#: in-process fleet runs many senders concurrently) and flag phantom
#: violations.
_align_tls = threading.local()


def tree_align_copy_count() -> int:
    """Leaves re-placed by :func:`tree_align_devices` on THIS thread."""
    return getattr(_align_tls, "copies", 0)


def tree_align_devices(tree: Pytree, like: Pytree) -> Pytree:
    """Re-place ``tree``'s committed arrays onto ``like``'s shardings.

    The zero-copy in-memory transport hands aggregators the sender's
    actual device buffers; when learners are submesh-placed
    (``JaxLearner(mesh=...)``) those live on ANOTHER node's slice, and a
    jit mixing them with local state refuses with "incompatible devices".
    One ``device_put`` per differing leaf re-places them (device-to-device
    over ICI on a pod). Host numpy leaves and already-aligned arrays pass
    through untouched.

    Fast path: when every leaf already sits on ``like``'s sharding — the
    common single-device case, and the *contract* on the shard-native ICI
    weights plane — the input tree is returned unchanged and the copy
    counter does not move (zero per-leaf ``device_put`` dispatches, zero
    allocations). The ICI plane asserts exactly this after each transfer.
    """
    la = jax.tree.leaves(tree)
    ll = jax.tree.leaves(like)

    def differs(x, l):  # noqa: E741 — like-leaf
        if not (isinstance(x, jax.Array) and isinstance(l, jax.Array)):
            return False
        if x.sharding == l.sharding:
            return False
        # sharding-TYPE-blind placement equivalence: a NamedSharding over
        # a one-device mesh and a SingleDeviceSharding of that device put
        # every byte in the same place — jits mix them freely, so a
        # device_put here would be pure churn (the ICI plane's decode
        # programs legitimately produce the former against templates
        # committed as the latter)
        ds_x, ds_l = x.sharding.device_set, l.sharding.device_set
        return not (len(ds_x) == 1 and ds_x == ds_l)

    if not any(differs(x, l) for x, l in zip(la, ll)):
        return tree

    def one(x, l):  # noqa: E741 — like-leaf
        if differs(x, l):
            _align_tls.copies = tree_align_copy_count() + 1
            return jax.device_put(x, l.sharding)
        return x

    return jax.tree.map(one, tree, like)


def tree_unstack(stacked: Pytree, n: int) -> list[Pytree]:
    """Inverse of :func:`tree_stack`."""
    return [jax.tree.map(lambda x, i=i: x[i], stacked) for i in range(n)]


def tree_weighted_mean(
    trees: Sequence[Pytree],
    weights: Sequence[float],
    agg_dtype: str = "float32",
) -> Pytree:
    """Sample-weighted mean of N pytrees (the FedAvg core).

    Normalizes ``weights`` internally, accumulates in ``agg_dtype`` and casts
    back to each leaf's dtype. One jitted program for the whole tree.
    """
    from p2pfl_tpu.ops.aggregation import fedavg

    return fedavg(tree_stack(trees), jnp.asarray(list(weights)), agg_dtype)


def tree_bytes(tree: Pytree) -> int:
    """Total payload size in bytes (for gossip accounting / bench)."""
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(tree))


def tree_num_params(tree: Pytree) -> int:
    return sum(x.size for x in jax.tree.leaves(tree))


def tree_allclose(a: Pytree, b: Pytree, atol: float = 1e-1) -> bool:
    """Structural + numeric equality (reference: ``p2pfl/utils.py:112-138``)."""
    la, ta = jax.tree.flatten(a)
    lb, tb = jax.tree.flatten(b)
    if ta != tb:
        return False
    import numpy as np

    return all(
        np.allclose(np.asarray(x, dtype="float32"), np.asarray(y, dtype="float32"), atol=atol)
        for x, y in zip(la, lb)
    )
