"""Pytree arithmetic used by aggregators and learners.

The reference performs aggregation as a Python loop over ``state_dict``
layers (``p2pfl/learning/aggregators/fedavg.py:43-60``). Here every
aggregation is a single jitted function over the whole pytree, so XLA fuses
the per-layer arithmetic into a handful of kernels and the data never leaves
the device.

Accumulation happens in ``Settings.AGG_DTYPE`` (float32) regardless of the
storage dtype (typically bfloat16), then is cast back — bf16 gossip payloads
with fp32-exact averaging.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp

Pytree = Any


def tree_zeros_like(tree: Pytree) -> Pytree:
    return jax.tree.map(jnp.zeros_like, tree)


def tree_add(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree.map(jnp.add, a, b)


def tree_sub(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree.map(jnp.subtract, a, b)


def tree_scale(tree: Pytree, s) -> Pytree:
    return jax.tree.map(lambda x: x * jnp.asarray(s, dtype=x.dtype), tree)


def tree_stack(trees: Sequence[Pytree]) -> Pytree:
    """Stack N structurally-identical pytrees into one pytree of [N, ...] arrays."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


def tree_align_devices(tree: Pytree, like: Pytree) -> Pytree:
    """Re-place ``tree``'s committed arrays onto ``like``'s shardings.

    The zero-copy in-memory transport hands aggregators the sender's
    actual device buffers; when learners are submesh-placed
    (``JaxLearner(mesh=...)``) those live on ANOTHER node's slice, and a
    jit mixing them with local state refuses with "incompatible devices".
    One ``device_put`` per differing leaf re-places them (device-to-device
    over ICI on a pod). Host numpy leaves and already-aligned arrays pass
    through untouched, so the common single-device path pays nothing.
    """

    def one(x, l):  # noqa: E741 — like-leaf
        if (
            isinstance(x, jax.Array)
            and isinstance(l, jax.Array)
            and x.sharding != l.sharding
        ):
            return jax.device_put(x, l.sharding)
        return x

    return jax.tree.map(one, tree, like)


def tree_unstack(stacked: Pytree, n: int) -> list[Pytree]:
    """Inverse of :func:`tree_stack`."""
    return [jax.tree.map(lambda x, i=i: x[i], stacked) for i in range(n)]


def tree_weighted_mean(
    trees: Sequence[Pytree],
    weights: Sequence[float],
    agg_dtype: str = "float32",
) -> Pytree:
    """Sample-weighted mean of N pytrees (the FedAvg core).

    Normalizes ``weights`` internally, accumulates in ``agg_dtype`` and casts
    back to each leaf's dtype. One jitted program for the whole tree.
    """
    from p2pfl_tpu.ops.aggregation import fedavg

    return fedavg(tree_stack(trees), jnp.asarray(list(weights)), agg_dtype)


def tree_bytes(tree: Pytree) -> int:
    """Total payload size in bytes (for gossip accounting / bench)."""
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(tree))


def tree_num_params(tree: Pytree) -> int:
    return sum(x.size for x in jax.tree.leaves(tree))


def tree_allclose(a: Pytree, b: Pytree, atol: float = 1e-1) -> bool:
    """Structural + numeric equality (reference: ``p2pfl/utils.py:112-138``)."""
    la, ta = jax.tree.flatten(a)
    lb, tb = jax.tree.flatten(b)
    if ta != tb:
        return False
    import numpy as np

    return all(
        np.allclose(np.asarray(x, dtype="float32"), np.asarray(y, dtype="float32"), atol=atol)
        for x, y in zip(la, lb)
    )
