"""Megafleet chunk-size autotuner: measure-once, replay-from-cache.

The chunked fleet engine's throughput is a function of its chunk size,
and the sweet spot is DEVICE-dependent (ROADMAP open item 1): XLA:CPU
wants chunks big enough to amortize per-op dispatch, TPU gather/scatter
wants a different balance, and the sharded engine shifts the optimum
again (per-shard lanes shrink with the shard count while the replicated
admission scan does not). Hand-picking one number per platform does not
survive a fleet that runs on all of them.

This module is the :mod:`~p2pfl_tpu.ops.autotune` pattern applied to
that knob — the same three-layer resolution, the same cache discipline:

1. **Pinned** (:func:`pin_fleet_chunk`) — explicit session-only
   override; never persisted (a pin is an experiment, not a
   measurement).
2. **In-process cache** — winners measured this process, plus anything
   loaded from disk.
3. **On-disk cache** — JSON at ``Settings.FLEET_TUNE_CACHE`` (default
   ``$P2PFL_FLEET_TUNE_CACHE`` or ``~/.cache/p2pfl_tpu/
   fleet_tune.json``), loaded once per process. Entries are keyed on
   **device kind** + **shard count** + a caller workload tag
   (task/dim/topology/K/population scale), so a cache written on one
   platform or mesh never mis-tunes another.

Cache entry format (one JSON object per key)::

    {"<kind>|shards=P|<extra>": {"chunk": 256,
                                 "timings": {"64": 0.41, ...}}}

``timings`` records every candidate's measured seconds — kept so a
bench or a human can audit WHY the winner won; only ``chunk`` is read
back. :func:`autotune_fleet_chunk` is the only function that runs
programs (the caller supplies the ``measure`` closure — typically one
warmed engine run over a bounded event prefix); everything else is a
pure lookup safe at trace time.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Callable, Dict, Optional, Sequence

#: chunk sizes swept by default — spans the dispatch-amortization knee
#: on CPU and stays under the per-chunk admission scan's compile blowup
DEFAULT_CANDIDATES = (64, 128, 256, 512)

# in-process winner cache: key (see _key) -> {"chunk": int, "timings": {...}}
_MEM_CACHE: Dict[str, dict] = {}
# explicit pins: session-only, win over everything, NEVER persisted
_PINNED: Dict[str, dict] = {}
_DISK_LOADED: set = set()  # cache paths already merged into _MEM_CACHE


def device_kind() -> str:
    """The tuning-cache platform key: TPU device kind, else backend name
    (same rule as :func:`p2pfl_tpu.ops.autotune.device_kind`)."""
    try:
        import jax

        dev = jax.devices()[0]
        if dev.platform == "tpu":
            return dev.device_kind
        return dev.platform
    except Exception:  # pragma: no cover — no backend at all
        return "cpu"


def _key(kind: str, n_shards: int, extra: str) -> str:
    return f"{kind}|shards={int(n_shards)}|{extra}"


def cache_path() -> Path:
    from p2pfl_tpu.settings import Settings

    p = getattr(Settings, "FLEET_TUNE_CACHE", "") or os.environ.get(
        "P2PFL_FLEET_TUNE_CACHE", ""
    )
    if p:
        return Path(p).expanduser()
    return Path.home() / ".cache" / "p2pfl_tpu" / "fleet_tune.json"


def _load_disk(path: Path) -> None:
    tag = str(path)
    if tag in _DISK_LOADED:
        return
    _DISK_LOADED.add(tag)
    try:
        raw = json.loads(path.read_text())
    except (OSError, ValueError):
        return
    for key, entry in raw.items():
        if isinstance(entry, dict) and isinstance(entry.get("chunk"), int):
            _MEM_CACHE.setdefault(key, entry)
        # unknown/garbage entry: skipped, measurement still applies


def _save_disk(path: Path) -> None:
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(json.dumps(dict(sorted(_MEM_CACHE.items())), indent=2,
                                   sort_keys=True))
    except OSError:  # read-only home etc. — tuning still works in-process
        pass


def clear_memory_cache() -> None:
    """Drop in-process tuning state (tests; disk cache files are kept)."""
    _MEM_CACHE.clear()
    _PINNED.clear()
    _DISK_LOADED.clear()


def pin_fleet_chunk(
    chunk: int, *, n_shards: int = 1, extra: str = "", kind: Optional[str] = None
) -> None:
    """Pin an explicit chunk size for a workload key — wins over tuned.
    Session-only: pins are never written to the on-disk tuning cache."""
    _PINNED[_key(kind or device_kind(), n_shards, extra)] = {"chunk": int(chunk)}


def get_fleet_chunk(
    *, n_shards: int = 1, extra: str = "", kind: Optional[str] = None
) -> Optional[int]:
    """Trace-safe lookup: pinned → tuned (memory → disk) → ``None``
    (the caller falls back to measuring, or to the Settings default)."""
    key = _key(kind or device_kind(), n_shards, extra)
    got = _PINNED.get(key) or _MEM_CACHE.get(key)
    if got is not None:
        return int(got["chunk"])
    _load_disk(cache_path())
    got = _MEM_CACHE.get(key)
    if got is not None:
        return int(got["chunk"])
    return None


def autotune_fleet_chunk(
    measure: Callable[[int], float],
    candidates: Sequence[int] = DEFAULT_CANDIDATES,
    *,
    n_shards: int = 1,
    extra: str = "",
    kind: Optional[str] = None,
    cache: bool = True,
    force: bool = False,
) -> int:
    """Resolve the chunk size for one workload key, measuring at most
    once per cache lifetime. ``measure(chunk) -> seconds`` is supplied
    by the caller (MegaFleet times a warmed engine run over a bounded
    event prefix) and is only invoked on a cache miss or ``force=True``
    — so a pinned or previously tuned key replays deterministically
    with NO engine runs. NOT trace-safe on the miss path."""
    kind = kind or device_kind()
    key = _key(kind, n_shards, extra)
    if cache and not force:
        got = _PINNED.get(key) or _MEM_CACHE.get(key)
        if got is None:
            _load_disk(cache_path())
            got = _MEM_CACHE.get(key)
        if got is not None:
            return int(got["chunk"])

    timings = {int(c): float(measure(int(c))) for c in candidates}
    best = min(timings, key=timings.get)
    if cache:
        _MEM_CACHE[key] = {
            "chunk": int(best),
            "timings": {str(c): t for c, t in sorted(timings.items())},
        }
        # merge existing on-disk entries before writing (a force=True
        # tune skips the read path above; _load_disk's setdefault keeps
        # the fresh winner over the stale disk copy)
        _load_disk(cache_path())
        _save_disk(cache_path())
    return int(best)
