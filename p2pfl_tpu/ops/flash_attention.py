"""Pallas flash attention (single chip), forward AND backward, explicitly
configured.

Blockwise causal attention with online softmax: O(T·D) VMEM per program
instead of the O(T²) logits matrix. Grid is (batch, heads, q-groups); each
program owns ``q_span`` consecutive q blocks (wider q ownership amortizes
grid/bookkeeping overhead while each sub-tile keeps its OWN causal
frontier — one big block would stream every k block up to the LAST row's
frontier for all rows) and streams K/V blocks up to each sub-tile's
frontier, keeping running (max, denom, accumulator) statistics in fp32
while the matmuls feed the MXU in the input dtype (bf16 K/V loads in
production; casting operands to f32 first runs the systolic array at its
slow f32 rate — measured 5× at D=32).

Every schedule knob lives in :class:`FlashConfig` — a frozen, hashable
dataclass that rides jit/custom_vjp STATIC arguments, so flipping any knob
(block shapes, q ownership, backward mode) after a step has compiled
provably re-traces. There are no module-global kernel knobs (the old
``BWD_MODE`` global was read at trace time with no cache-key participation
— flipping it after compilation silently did nothing, ADVICE r5).
``config=None`` resolves through :mod:`p2pfl_tpu.ops.autotune`: pinned
config → autotune cache (in-process, then on-disk, keyed on device kind) →
shipped defaults table for v4 / v5e / CPU-interpret.

Training: the custom VJP is backed by Pallas kernels (the standard
flash-attention backward split):

- ``_dq_kernel``  — grid (B, H, q-blocks): recomputes P from the saved
  log-sum-exp and accumulates ``dQ_i += (P ∘ (dO V^T − Δ)) K · scale``;
- ``_dkv_kernel`` — grid (B, H, k-blocks): streams the q blocks at or past
  the causal frontier and accumulates ``dV_j += P^T dO`` and
  ``dK_j += (P ∘ (dO V^T − Δ))^T Q · scale``;
- ``_dkvq_kernel`` — the fused single-pass alternative (see its docstring):
  dK/dV per k-block AND dQ in one sweep via a persistent VMEM scratch,
  5 block matmuls instead of the split pair's 7. Selected by
  ``FlashConfig.bwd_mode`` (``"auto"`` picks fused whenever the fp32 dQ
  scratch fits comfortably in VMEM).

Residuals are just ``(q, k, v, o, lse)`` — the attention matrix is never
materialized in either direction, so training long sequences stays O(T·D)
memory end-to-end. The log-sum-exp is saved in a block-size-INDEPENDENT
``[B, H, 1, T]`` row layout (always mapped as the full ``(1, T)`` block,
which satisfies Mosaic's block==array tiling rule for any T): the backward
can pick any block shape without the old per-block-layout reshuffle, and
every kernel ref stays 2D (this environment's Mosaic compiler rejects
1D/`.at[]` ref views). Δ = rowsum(dO∘O) is a cheap elementwise XLA op
computed outside the kernels in the same row layout.

Grid dimension semantics are pinned explicitly on every ``pallas_call``
(``_compiler_params``): batch/head dims are ``parallel``; the forward's
q-group dim is ``arbitrary`` (all programs of one (b, h) write rows of the
SAME full lse block — a megacore split over that dim would race the block
flush); the fused backward's k-block dim is ``arbitrary`` because the
``dq_acc`` scratch accumulation REQUIRES sequential k blocks (this used to
be an accident of the default semantics — advisor round-5); the split
backward kernels write disjoint blocks and read shared blocks read-only,
so their grid is fully ``parallel``.

The reference has no attention anywhere (SURVEY §2.9) — this exists for the
BASELINE config-5 model family and the long-context path.

Playbook: /opt/skills/guides/pallas_guide.md (grid/BlockSpec, online
softmax accumulation, broadcasted_iota masking, @pl.when).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

NEG_INF = -1e30


@dataclasses.dataclass(frozen=True)
class FlashConfig:
    """Static schedule of the flash kernels — hashable, jit-cache-key safe.

    Forward: ``block_q`` × ``block_k`` tiles, ``q_span`` q blocks owned per
    program (wider ownership amortizes grid bookkeeping; each sub-tile
    keeps its own causal frontier). Backward: ``block_q_bwd``/``block_k_bwd``
    override the backward tile shapes (``None`` → :func:`_bwd_blocks`
    decides: fused keeps the forward's, split upsizes at wide heads);
    ``bwd_mode`` picks the kernel structure — ``"fused"`` = one sweep with
    a persistent dQ scratch (5 block matmuls, the MFU-accounted minimum),
    ``"split"`` = separate dq/dkv kernels (7 — recomputes S and dP twice),
    ``"auto"`` = fused whenever the fp32 [T, D] dQ scratch fits comfortably
    in VMEM next to resident q/do.

    Pass it through ``flash_attention(config=...)``,
    ``TransformerConfig(flash_config=...)`` or
    ``resolve_attention(config=...)``; ``None`` anywhere resolves through
    :func:`p2pfl_tpu.ops.autotune.get_flash_config` (pinned → tune cache →
    defaults table). Because instances compare/hash by value, passing an
    EQUAL config re-uses the compiled program and passing a DIFFERENT one
    re-traces — the contract the old ``BWD_MODE`` module global broke.
    """

    block_q: int = 128
    block_k: int = 128
    q_span: int = 1
    block_q_bwd: Optional[int] = None
    block_k_bwd: Optional[int] = None
    bwd_mode: str = "auto"  # auto | fused | split

    def __post_init__(self) -> None:
        if self.bwd_mode not in ("auto", "fused", "split"):
            raise ValueError(f"bwd_mode {self.bwd_mode!r} (auto|fused|split)")
        if self.block_q < 1 or self.block_k < 1 or self.q_span < 1:
            raise ValueError("block_q/block_k/q_span must be >= 1")


def _resolve(config: Optional[FlashConfig], t: int, d: int, dtype, causal: bool) -> FlashConfig:
    """``config=None`` → the tuned/default config for this shape."""
    if config is not None:
        return config
    from p2pfl_tpu.ops.autotune import get_flash_config

    return get_flash_config(t, d, dtype=dtype, causal=causal)


def _compiler_params(*dims: str):
    """Pin grid ``dimension_semantics`` ('parallel' dims may be split across
    megacore; 'arbitrary' dims MUST run sequentially on one core). Returns
    None on non-TPU pallas builds (and is ignored in interpret mode)."""
    try:
        from jax.experimental.pallas import tpu as pltpu

        return pltpu.TPUCompilerParams(dimension_semantics=dims)
    except (ImportError, AttributeError, TypeError):  # pragma: no cover
        return None


def _fwd_tile(q, k_ref, v_ref, qi, *, block_q, block_k, causal, scale, t):
    """Online-softmax accumulation of ONE q sub-tile against its visible
    K/V stream. Returns (acc [BQ, D] f32, m [BQ, 1] f32, l [BQ, 1] f32)."""
    dt = q.dtype

    acc = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    m = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)

    def body(j, carry, *, masked):
        acc, m, l = carry
        k = k_ref[pl.ds(j * block_k, block_k), :]
        v = v_ref[pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [BQ, BK]
        if masked:
            rows = qi * block_q + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if masked:
            p = jnp.where(m_new <= NEG_INF / 2, 0.0, p)
            alpha = jnp.where(m <= NEG_INF / 2, 0.0, jnp.exp(m - m_new))
        else:
            # s is finite, so m_new is too; a NEG_INF m (first block) gives
            # alpha = exp(-inf) = 0 without the select
            alpha = jnp.exp(m - m_new)
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(dt), v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        return acc, m_new, l

    if causal:
        # split the stream at the causal frontier: blocks fully below the
        # diagonal skip the iota/select mask work (half the VPU ops for the
        # majority of blocks — measured 4× at D=32 where the mask dominates)
        n_full = lax.div(qi * block_q, block_k)
        n_all = lax.div((qi + 1) * block_q + block_k - 1, block_k)
        acc, m, l = lax.fori_loop(0, n_full, partial(body, masked=False), (acc, m, l))
        acc, m, l = lax.fori_loop(n_full, n_all, partial(body, masked=True), (acc, m, l))
    else:
        acc, m, l = lax.fori_loop(
            0, t // block_k, partial(body, masked=False), (acc, m, l)
        )
    return acc, m, l


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_q, block_k, q_span, causal, scale
):
    t = k_ref.shape[0]
    for s in range(q_span):  # static unroll: q_span consecutive sub-tiles
        qi = pl.program_id(2) * q_span + s
        q = q_ref[pl.ds(s * block_q, block_q), :]  # [BQ, D]
        acc, m, l = _fwd_tile(
            q, k_ref, v_ref, qi, block_q=block_q, block_k=block_k,
            causal=causal, scale=scale, t=t,
        )
        o_ref[pl.ds(s * block_q, block_q), :] = (
            acc / jnp.maximum(l, 1e-30)
        ).astype(o_ref.dtype)
        # log-sum-exp per row; fully-masked rows keep NEG_INF (exp
        # underflows to 0). lse_ref is the block-size-INDEPENDENT [1, T]
        # row (full-array block — block == array dims satisfies Mosaic's
        # tiling rule); each sub-tile owns its T-slice.
        lse = jnp.where(m <= NEG_INF / 2, NEG_INF, m + jnp.log(jnp.maximum(l, 1e-30)))
        lse_ref[pl.ds(0, 1), pl.ds(qi * block_q, block_q)] = lse.reshape(1, block_q)


def _row(ref, i, block_q):
    """Read rows [i·BQ, (i+1)·BQ) of a [1, T] row-layout ref as [BQ, 1]."""
    return ref[pl.ds(0, 1), pl.ds(i * block_q, block_q)].reshape(block_q, 1)


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *, block_q, block_k, causal, scale
):
    qi = pl.program_id(2)
    t = k_ref.shape[0]
    dt = q_ref.dtype
    q = q_ref[:]  # [BQ, D]
    do = do_ref[:]  # [BQ, D]
    lse = _row(lse_ref, qi, block_q)  # [BQ, 1]
    delta = _row(delta_ref, qi, block_q)  # [BQ, 1]

    dq = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    def body(j, dq, *, masked):
        k = k_ref[pl.ds(j * block_k, block_k), :]
        v = v_ref[pl.ds(j * block_k, block_k), :]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BQ, BK]
        if masked:
            rows = qi * block_q + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)  # masked entries underflow to 0
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BQ, BK]
        ds = p * (dp - delta)
        return dq + scale * jax.lax.dot_general(
            ds.astype(dt), k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    if causal:
        n_full = lax.div(qi * block_q, block_k)
        n_all = lax.div((qi + 1) * block_q + block_k - 1, block_k)
        dq = lax.fori_loop(0, n_full, partial(body, masked=False), dq)
        dq = lax.fori_loop(n_full, n_all, partial(body, masked=True), dq)
    else:
        dq = lax.fori_loop(0, t // block_k, partial(body, masked=False), dq)
    dq_ref[:] = dq.astype(dq_ref.dtype)


def _dkv_step(
    i, dk, dv, *, q_ref, do_ref, lse_ref, delta_ref, k, v, kj,
    block_q, block_k, scale, dt, masked, dq_acc=None,
):
    """One q-block's contribution to (dK_j, dV_j) — the body shared by the
    split ``_dkv_kernel`` and the fused ``_dkvq_kernel``, which adds only
    the ``dq_acc`` accumulation on top of identical S/P/dP/ds math."""
    q = q_ref[pl.ds(i * block_q, block_q), :]
    do = do_ref[pl.ds(i * block_q, block_q), :]
    lse = _row(lse_ref, i, block_q)
    delta = _row(delta_ref, i, block_q)
    s = scale * jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [BQ, BK]
    if masked:
        rows = i * block_q + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        cols = kj * block_k + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        s = jnp.where(rows >= cols, s, NEG_INF)
    p = jnp.exp(s - lse)  # [BQ, BK]
    dv = dv + jax.lax.dot_general(
        p.astype(dt), do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [BQ, BK]
    ds = (p * (dp - delta)).astype(dt)
    dk = dk + scale * jax.lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    if dq_acc is not None:
        dq_acc[pl.ds(i * block_q, block_q), :] += scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
    return dk, dv


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, block_q, block_k, causal, scale,
):
    kj = pl.program_id(2)
    t = q_ref.shape[0]
    k = k_ref[:]  # [BK, D]
    v = v_ref[:]  # [BK, D]

    dk = jnp.zeros((block_k, k.shape[-1]), jnp.float32)
    dv = jnp.zeros((block_k, k.shape[-1]), jnp.float32)
    n_blocks = t // block_q

    def body(i, carry, *, masked):
        return _dkv_step(
            i, *carry, q_ref=q_ref, do_ref=do_ref, lse_ref=lse_ref,
            delta_ref=delta_ref, k=k, v=v, kj=kj, block_q=block_q,
            block_k=block_k, scale=scale, dt=q_ref.dtype, masked=masked,
        )

    if causal:
        # q blocks strictly before the frontier never see this K block; q
        # blocks fully past the diagonal band see all of it (no mask needed)
        start = lax.div(kj * block_k, block_q)
        full = lax.div((kj + 1) * block_k + block_q - 1, block_q)
        dk, dv = lax.fori_loop(start, full, partial(body, masked=True), (dk, dv))
        dk, dv = lax.fori_loop(full, n_blocks, partial(body, masked=False), (dk, dv))
    else:
        dk, dv = lax.fori_loop(0, n_blocks, partial(body, masked=False), (dk, dv))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _dkvq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dq_ref,
    dq_acc, *, block_q, block_k, causal, scale,
):
    """Single-pass backward: dK/dV per k-block AND dQ in one sweep.

    The split backward (``_dq_kernel`` + ``_dkv_kernel``) recomputes
    S = QK^T and dP = dO V^T in BOTH passes — 7 block matmuls executed for
    the 5 the MFU accounting counts (measured: bwd trailed fwd by exactly
    that ~1.4× on a v5e at D=128). Here the grid's k-block dimension runs
    sequentially on the core (pinned via dimension_semantics — see the
    pallas_call site), so dQ accumulates across grid steps in a persistent
    fp32 VMEM scratch: S and dP are computed ONCE and all five products
    (dV, dK, dQ + the two recomputes) come out of one sweep. Scratch is
    zeroed at the first k-block and flushed to ``dq_ref`` at the last;
    q/do stay VMEM-resident (same full-block residency the split dkv
    kernel already required).
    """
    kj = pl.program_id(2)
    nk = pl.num_programs(2)
    t = q_ref.shape[0]
    k = k_ref[:]  # [BK, D]
    v = v_ref[:]  # [BK, D]

    @pl.when(kj == 0)
    def _zero():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    dk = jnp.zeros((block_k, k.shape[-1]), jnp.float32)
    dv = jnp.zeros((block_k, k.shape[-1]), jnp.float32)
    nq = t // block_q

    def body(i, carry, *, masked):
        return _dkv_step(
            i, *carry, q_ref=q_ref, do_ref=do_ref, lse_ref=lse_ref,
            delta_ref=delta_ref, k=k, v=v, kj=kj, block_q=block_q,
            block_k=block_k, scale=scale, dt=q_ref.dtype, masked=masked,
            dq_acc=dq_acc,
        )

    if causal:
        start = lax.div(kj * block_k, block_q)
        full = lax.div((kj + 1) * block_k + block_q - 1, block_q)
        dk, dv = lax.fori_loop(start, full, partial(body, masked=True), (dk, dv))
        dk, dv = lax.fori_loop(full, nq, partial(body, masked=False), (dk, dv))
    else:
        dk, dv = lax.fori_loop(0, nq, partial(body, masked=False), (dk, dv))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)

    @pl.when(kj == nk - 1)
    def _flush():
        dq_ref[:] = dq_acc[...].astype(dq_ref.dtype)


def _specs(block_q, block_k, t, d, q_span: int = 1):
    qspec = pl.BlockSpec(
        (None, None, block_q * q_span, d), lambda bi, hi, i: (bi, hi, i, 0)
    )
    kvfull = pl.BlockSpec((None, None, t, d), lambda bi, hi, i: (bi, hi, 0, 0))
    # lse/delta live in the block-size-independent [B, H, 1, T] row layout;
    # always mapped as the FULL (1, T) block — block == array dims satisfies
    # Mosaic's tiling rule for any T, and programs slice their own rows, so
    # the backward re-blocks freely with NO relayout of the saved lse
    lse_row = pl.BlockSpec((None, None, 1, t), lambda bi, hi, i: (bi, hi, 0, 0))
    return qspec, kvfull, lse_row


def _flash_fwd_bthd(q, k, v, *, block_q, block_k, q_span, causal, interpret):
    """q,k,v: [B, H, T, D] → (out [B, H, T, D], lse [B, H, 1, T] f32)."""
    b, h, t, d = q.shape
    scale = d ** -0.5
    grid = (b, h, t // (block_q * q_span))
    qspec, kvfull, lse_row = _specs(block_q, block_k, t, d, q_span)
    kernel = partial(
        _flash_kernel, block_q=block_q, block_k=block_k, q_span=q_span,
        causal=causal, scale=scale,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[qspec, kvfull, kvfull],
        out_specs=[qspec, lse_row],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, 1, t), jnp.float32),
        ],
        # every program of one (b, h) writes rows of the SAME full lse
        # block: the q-group dim must not be megacore-split ('arbitrary')
        compiler_params=_compiler_params("parallel", "parallel", "arbitrary"),
        interpret=interpret,
    )(q, k, v)


_FUSED_SCRATCH_LIMIT = 4 * 1024 * 1024  # bytes of fp32 [T, D] dQ scratch


def _bwd_use_fused(t: int, d: int, mode: str) -> bool:
    if mode == "fused":
        return True
    if mode == "split":
        return False
    return t * d * 4 <= _FUSED_SCRATCH_LIMIT


def _dq_scratch(t: int, d: int):
    """The fused backward's persistent fp32 [T, D] dQ accumulator — the one
    place the VMEM-scratch spec (and its non-TPU fallback) is defined."""
    try:
        from jax.experimental.pallas import tpu as pltpu

        return [pltpu.VMEM((t, d), jnp.float32)]
    except ImportError:  # pragma: no cover — non-TPU pallas build
        return [pl.MemorySpace.ANY((t, d), jnp.float32)]


def _flash_bwd_bthd(q, k, v, do, lse, delta, *, block_q, block_k, causal, interpret, bwd_mode):
    b, h, t, d = q.shape
    scale = d ** -0.5
    qspec, kvfull, lse_row = _specs(block_q, block_k, t, d)
    qfull = pl.BlockSpec((None, None, t, d), lambda bi, hi, i: (bi, hi, 0, 0))
    kvspec = pl.BlockSpec((None, None, block_k, d), lambda bi, hi, j: (bi, hi, j, 0))

    if _bwd_use_fused(t, d, bwd_mode):
        dk, dv, dq = pl.pallas_call(
            partial(
                _dkvq_kernel, block_q=block_q, block_k=block_k, causal=causal, scale=scale
            ),
            grid=(b, h, t // block_k),
            in_specs=[qfull, kvspec, kvspec, qfull, lse_row, lse_row],
            out_specs=[kvspec, kvspec, qfull],
            out_shape=[
                jax.ShapeDtypeStruct(k.shape, k.dtype),
                jax.ShapeDtypeStruct(v.shape, v.dtype),
                jax.ShapeDtypeStruct(q.shape, q.dtype),
            ],
            scratch_shapes=_dq_scratch(t, d),
            # the k-block dim MUST run sequentially: dq_acc accumulates
            # across its grid steps (and dq_ref flushes at the last) — this
            # encodes the requirement instead of relying on the default
            # semantics happening to serialize (advisor round-5)
            compiler_params=_compiler_params("parallel", "parallel", "arbitrary"),
            interpret=interpret,
        )(q, k, v, do, lse, delta)
        return dq, dk, dv

    # split kernels write disjoint output blocks and only read the shared
    # full blocks — every grid dim is safely parallel (megacore-splittable)
    split_params = _compiler_params("parallel", "parallel", "parallel")
    dq = pl.pallas_call(
        partial(_dq_kernel, block_q=block_q, block_k=block_k, causal=causal, scale=scale),
        grid=(b, h, t // block_q),
        in_specs=[qspec, kvfull, kvfull, qspec, lse_row, lse_row],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        compiler_params=split_params,
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        partial(_dkv_kernel, block_q=block_q, block_k=block_k, causal=causal, scale=scale),
        grid=(b, h, t // block_k),
        in_specs=[qfull, kvspec, kvspec, qfull, lse_row, lse_row],
        out_specs=[kvspec, kvspec],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        compiler_params=split_params,
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(
    q, k, v, causal: bool = True, config: Optional[FlashConfig] = None,
    interpret: bool = False,
):
    """Flash attention. q,k,v: [B, T, H, D] (GQA heads pre-repeated).

    ``config`` is the STATIC kernel schedule (:class:`FlashConfig` —
    forward/backward block shapes, q ownership, backward mode); it is a
    ``custom_vjp`` nondiff argument, so it participates in every enclosing
    jit's cache key and flipping any knob re-traces. ``None`` resolves the
    tuned/default config for this (T, D, dtype, causal) through
    :func:`p2pfl_tpu.ops.autotune.get_flash_config` — but note that this
    resolution happens at TRACE time against the autotune caches, and the
    enclosing jit's cache key then contains only ``None``: pinning or
    autotuning AFTER such a step has compiled does not re-trace it. To
    keep the schedule live-switchable, resolve the config BEFORE the jit
    boundary and pass it explicitly (``tiny_transformer`` does exactly
    this at model-build time). The saved log-sum-exp lives in a
    block-size-independent ``[B, H, 1, T]`` row layout, so the backward
    re-blocks freely without relayout.
    """
    out, _ = _fwd(q, k, v, causal, config, interpret)
    return out


def _clamp_blocks(t, block_q, block_k):
    block_q, block_k = min(block_q, t), min(block_k, t)
    assert t % block_q == 0 and t % block_k == 0, "T must divide the block sizes"
    return block_q, block_k


def _fit_q_span(t: int, block_q: int, q_span: int) -> int:
    """Largest span <= q_span that divides the q-block count (a schedule
    knob degrades gracefully instead of asserting)."""
    nq = t // block_q
    return next(s for s in range(min(q_span, nq), 0, -1) if nq % s == 0)


def _fwd(q, k, v, causal, config, interpret):
    t, d = q.shape[1], q.shape[-1]
    cfg = _resolve(config, t, d, q.dtype, causal)
    block_q, block_k = _clamp_blocks(t, cfg.block_q, cfg.block_k)
    q_span = _fit_q_span(t, block_q, cfg.q_span)
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    out, lse = _flash_fwd_bthd(
        qt, kt, vt, block_q=block_q, block_k=block_k, q_span=q_span,
        causal=causal, interpret=interpret,
    )
    return out.transpose(0, 2, 1, 3), (q, k, v, out, lse)


def _bwd_blocks(t: int, d: int, cfg: FlashConfig) -> tuple[int, int]:
    """The ONE place backward block sizes are decided (``block_q_bwd`` /
    ``block_k_bwd`` only override). Fused single-pass: the forward's own
    blocks are fastest (measured D=128/T=4096: 66.7% MFU at 512 vs 57.5%
    at 1024). Split two-pass at wide heads: the largest block <= 1024
    (measured 56% vs 45% at 512)."""
    if cfg.block_q_bwd is not None or cfg.block_k_bwd is not None:
        return _clamp_blocks(
            t, cfg.block_q_bwd or cfg.block_q, cfg.block_k_bwd or cfg.block_k
        )
    bq, bk = _clamp_blocks(t, cfg.block_q, cfg.block_k)
    if _bwd_use_fused(t, d, cfg.bwd_mode):
        return bq, bk
    if d >= 128:
        big = next(
            (b for b in range(min(1024, t), bq, -1) if t % b == 0 and b % 8 == 0),
            None,
        )
        if big:
            return big, big
    return bq, bk


def _bwd(causal, config, interpret, res, g):
    q, k, v, out_bhtd, lse = res
    t, d = q.shape[1], q.shape[-1]
    cfg = _resolve(config, t, d, q.dtype, causal)
    bq, bk = _bwd_blocks(t, d, cfg)
    b, h = out_bhtd.shape[:2]
    do = g.transpose(0, 2, 1, 3)  # [B, H, T, D]
    # Δ_i = Σ_d dO_id · O_id, in the same [B, H, 1, T] row layout as lse
    # (block-size independent — no relayout whatever blocks the bwd picks)
    delta = jnp.sum(
        do.astype(jnp.float32) * out_bhtd.astype(jnp.float32), axis=-1
    )[:, :, None, :]
    dq, dk, dv = _flash_bwd_bthd(
        q.transpose(0, 2, 1, 3),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        do,
        lse,
        delta,
        block_q=bq,
        block_k=bk,
        causal=causal,
        interpret=interpret,
        bwd_mode=cfg.bwd_mode,
    )
    return tuple(x.transpose(0, 2, 1, 3) for x in (dq, dk, dv))


flash_attention.defvjp(_fwd, _bwd)


# ---- offset-aware variants: flash blocks inside ring attention ----
#
# Ring attention hands each device K/V blocks from OTHER sequence shards;
# causal masking then depends on the blocks' global offsets, which are
# traced values (lax.axis_index) under shard_map. The offsets ride into the
# kernels as int32 scalars in SMEM — the causal frontier becomes a traced
# fori_loop bound and the mask compares global row/col indices.

try:
    from jax.experimental.pallas import tpu as pltpu

    _SMEM_SPEC = pl.BlockSpec(memory_space=pltpu.SMEM)
except ImportError:  # non-TPU pallas build
    _SMEM_SPEC = pl.BlockSpec(memory_space=None)


def _fwd_tile_offs(q, k_ref, v_ref, qi, q_off, k_off, *, block_q, block_k, scale, t):
    """Offset-aware sibling of :func:`_fwd_tile`: the causal frontier is in
    GLOBAL coordinates (traced offsets), so the loop bounds are traced."""
    dt = q.dtype
    acc = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    m = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)

    # causal frontier in global coordinates: stream k blocks whose first
    # column is <= this q sub-tile's last row; blocks whose last column is
    # <= this sub-tile's first row are fully visible and skip the mask
    last_row = q_off + (qi + 1) * block_q - 1
    n_blocks = jnp.clip(lax.div(last_row - k_off, block_k) + 1, 0, t // block_k)
    n_full = jnp.clip(
        lax.div(q_off + qi * block_q - k_off + 1, block_k), 0, n_blocks
    )

    def body(j, carry, *, masked):
        acc, m, l = carry
        k = k_ref[pl.ds(j * block_k, block_k), :]
        v = v_ref[pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if masked:
            rows = q_off + qi * block_q + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = k_off + j * block_k + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if masked:
            p = jnp.where(m_new <= NEG_INF / 2, 0.0, p)
            alpha = jnp.where(m <= NEG_INF / 2, 0.0, jnp.exp(m - m_new))
        else:
            alpha = jnp.exp(m - m_new)
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(dt), v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        return acc, m_new, l

    acc, m, l = lax.fori_loop(0, n_full, partial(body, masked=False), (acc, m, l))
    acc, m, l = lax.fori_loop(n_full, n_blocks, partial(body, masked=True), (acc, m, l))
    return acc, m, l


def _flash_kernel_offs(
    offs_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_q, block_k, q_span, scale
):
    t = k_ref.shape[0]
    q_off, k_off = offs_ref[0], offs_ref[1]
    for s in range(q_span):
        qi = pl.program_id(2) * q_span + s
        q = q_ref[pl.ds(s * block_q, block_q), :]
        acc, m, l = _fwd_tile_offs(
            q, k_ref, v_ref, qi, q_off, k_off,
            block_q=block_q, block_k=block_k, scale=scale, t=t,
        )
        o_ref[pl.ds(s * block_q, block_q), :] = (
            acc / jnp.maximum(l, 1e-30)
        ).astype(o_ref.dtype)
        lse = jnp.where(m <= NEG_INF / 2, NEG_INF, m + jnp.log(jnp.maximum(l, 1e-30)))
        lse_ref[pl.ds(0, 1), pl.ds(qi * block_q, block_q)] = lse.reshape(1, block_q)


def _dq_kernel_offs(
    offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, glse_ref, dq_ref,
    *, block_q, block_k, scale,
):
    qi = pl.program_id(2)
    t = k_ref.shape[0]
    dt = q_ref.dtype
    q_off, k_off = offs_ref[0], offs_ref[1]
    q = q_ref[:]
    do = do_ref[:]
    lse = _row(lse_ref, qi, block_q)
    delta = _row(delta_ref, qi, block_q)
    # d lse / d s = softmax row, so the lse cotangent adds into ds
    glse = _row(glse_ref, qi, block_q)

    dq = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    last_row = q_off + (qi + 1) * block_q - 1
    n_blocks = jnp.clip(lax.div(last_row - k_off, block_k) + 1, 0, t // block_k)
    n_full = jnp.clip(
        lax.div(q_off + qi * block_q - k_off + 1, block_k), 0, n_blocks
    )

    def body(j, dq, *, masked):
        k = k_ref[pl.ds(j * block_k, block_k), :]
        v = v_ref[pl.ds(j * block_k, block_k), :]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if masked:
            rows = q_off + qi * block_q + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = k_off + j * block_k + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        # rows invisible in this hop have lse = -inf: p must be 0, not nan
        p = jnp.where(lse <= NEG_INF / 2, 0.0, jnp.exp(s - lse))
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta + glse)
        return dq + scale * jax.lax.dot_general(
            ds.astype(dt), k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    dq = lax.fori_loop(0, n_full, partial(body, masked=False), dq)
    dq = lax.fori_loop(n_full, n_blocks, partial(body, masked=True), dq)
    dq_ref[:] = dq.astype(dq_ref.dtype)


def _dkv_step_offs(
    i, dk, dv, *, q_ref, do_ref, lse_ref, delta_ref, glse_ref, k, v, kj,
    q_off, k_off, block_q, block_k, scale, dt, masked, dq_acc=None,
):
    """Offset-aware sibling of :func:`_dkv_step` (global-coordinate mask,
    lse sentinel guard, lse-cotangent term), shared by the split and fused
    offset backward kernels."""
    q = q_ref[pl.ds(i * block_q, block_q), :]
    do = do_ref[pl.ds(i * block_q, block_q), :]
    lse = _row(lse_ref, i, block_q)
    delta = _row(delta_ref, i, block_q)
    glse = _row(glse_ref, i, block_q)
    s = scale * jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    if masked:
        rows = q_off + i * block_q + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        cols = k_off + kj * block_k + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        s = jnp.where(rows >= cols, s, NEG_INF)
    p = jnp.where(lse <= NEG_INF / 2, 0.0, jnp.exp(s - lse))
    dv = dv + jax.lax.dot_general(
        p.astype(dt), do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = (p * (dp - delta + glse)).astype(dt)
    dk = dk + scale * jax.lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    if dq_acc is not None:
        dq_acc[pl.ds(i * block_q, block_q), :] += scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
    return dk, dv


def _offs_kv_bounds(kj, q_off, k_off, block_q, block_k, nq):
    """(start, full): first q block whose last global row reaches this k
    block's first col, and first q block whose FIRST row clears its last
    col (q blocks past that see the whole k block — no mask)."""
    first_col = k_off + kj * block_k
    start = jnp.clip(lax.div(first_col - q_off, block_q), 0, nq)
    full = jnp.clip(
        lax.div(k_off + (kj + 1) * block_k - 1 - q_off + block_q - 1, block_q),
        start,
        nq,
    )
    return start, full


def _dkv_kernel_offs(
    offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, glse_ref, dk_ref, dv_ref,
    *, block_q, block_k, scale,
):
    kj = pl.program_id(2)
    t = q_ref.shape[0]
    q_off, k_off = offs_ref[0], offs_ref[1]
    k = k_ref[:]
    v = v_ref[:]

    dk = jnp.zeros((block_k, k.shape[-1]), jnp.float32)
    dv = jnp.zeros((block_k, k.shape[-1]), jnp.float32)
    nq = t // block_q
    start, full = _offs_kv_bounds(kj, q_off, k_off, block_q, block_k, nq)

    def body(i, carry, *, masked):
        return _dkv_step_offs(
            i, *carry, q_ref=q_ref, do_ref=do_ref, lse_ref=lse_ref,
            delta_ref=delta_ref, glse_ref=glse_ref, k=k, v=v, kj=kj,
            q_off=q_off, k_off=k_off, block_q=block_q, block_k=block_k,
            scale=scale, dt=q_ref.dtype, masked=masked,
        )

    dk, dv = lax.fori_loop(start, full, partial(body, masked=True), (dk, dv))
    dk, dv = lax.fori_loop(full, nq, partial(body, masked=False), (dk, dv))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _dkvq_kernel_offs(
    offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, glse_ref,
    dk_ref, dv_ref, dq_ref, dq_acc, *, block_q, block_k, scale,
):
    """Offset-aware single-pass backward (see :func:`_dkvq_kernel`): dQ
    accumulates across the sequential k-block grid steps in a persistent
    fp32 scratch, so S and dP are computed once per (i, j) pair. q blocks
    invisible to every k block in this hop keep their zeroed scratch —
    the correct zero cotangent for rows the hop never attends."""
    kj = pl.program_id(2)
    nk = pl.num_programs(2)
    t = q_ref.shape[0]
    q_off, k_off = offs_ref[0], offs_ref[1]
    k = k_ref[:]
    v = v_ref[:]

    @pl.when(kj == 0)
    def _zero():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    dk = jnp.zeros((block_k, k.shape[-1]), jnp.float32)
    dv = jnp.zeros((block_k, k.shape[-1]), jnp.float32)
    nq = t // block_q
    start, full = _offs_kv_bounds(kj, q_off, k_off, block_q, block_k, nq)

    def body(i, carry, *, masked):
        return _dkv_step_offs(
            i, *carry, q_ref=q_ref, do_ref=do_ref, lse_ref=lse_ref,
            delta_ref=delta_ref, glse_ref=glse_ref, k=k, v=v, kj=kj,
            q_off=q_off, k_off=k_off, block_q=block_q, block_k=block_k,
            scale=scale, dt=q_ref.dtype, masked=masked, dq_acc=dq_acc,
        )

    dk, dv = lax.fori_loop(start, full, partial(body, masked=True), (dk, dv))
    dk, dv = lax.fori_loop(full, nq, partial(body, masked=False), (dk, dv))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)

    @pl.when(kj == nk - 1)
    def _flush():
        dq_ref[:] = dq_acc[...].astype(dq_ref.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def flash_attention_block(
    q, k, v, q_off, k_off, config: Optional[FlashConfig] = None,
    interpret: bool = False,
):
    """One causal-by-global-offset attention block: q attends k/v where
    ``q_off + i >= k_off + j``. q,k,v: [B, T, H, D] (T = local shard).
    ``q_off``/``k_off`` are traced int32 scalars (e.g. ``axis_index * T``
    under ``shard_map``). Returns ``(out, lse)`` — the ``[B, H, 1, T]``
    log-sum-exp makes results mergeable across blocks (ring attention
    hops). ``config`` is the same static :class:`FlashConfig` schedule as
    :func:`flash_attention` (None resolves the tuned/default)."""
    out, lse, _ = _fab_fwd_impl(q, k, v, q_off, k_off, config, interpret)
    return out, lse


def _fab_fwd_impl(q, k, v, q_off, k_off, config, interpret):
    b, t, h, d = q.shape
    cfg = _resolve(config, t, d, q.dtype, True)
    block_q, block_k = _clamp_blocks(t, cfg.block_q, cfg.block_k)
    q_span = _fit_q_span(t, block_q, cfg.q_span)
    scale = d ** -0.5
    offs = jnp.stack([q_off, k_off]).astype(jnp.int32)
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    qspec, kvfull, lse_row = _specs(block_q, block_k, t, d, q_span)
    out, lse = pl.pallas_call(
        partial(
            _flash_kernel_offs, block_q=block_q, block_k=block_k,
            q_span=q_span, scale=scale,
        ),
        grid=(b, h, t // (block_q * q_span)),
        in_specs=[_SMEM_SPEC, qspec, kvfull, kvfull],
        out_specs=[qspec, lse_row],
        out_shape=[
            jax.ShapeDtypeStruct(qt.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, 1, t), jnp.float32),
        ],
        # shared-write lse row block — same reason as _flash_fwd_bthd
        compiler_params=_compiler_params("parallel", "parallel", "arbitrary"),
        interpret=interpret,
    )(offs, qt, kt, vt)
    return out.transpose(0, 2, 1, 3), lse, out


def _fab_fwd(q, k, v, q_off, k_off, config, interpret):
    out, lse, out_bhtd = _fab_fwd_impl(q, k, v, q_off, k_off, config, interpret)
    return (out, lse), (q, k, v, q_off, k_off, out_bhtd, lse)


def _fab_bwd(config, interpret, res, cts):
    g, g_lse = cts  # the ring merge differentiates through lse too
    q, k, v, q_off, k_off, out_bhtd, lse = res
    b, t, h, d = q.shape
    cfg = _resolve(config, t, d, q.dtype, True)
    block_q, block_k = _bwd_blocks(t, d, cfg)
    scale = d ** -0.5
    offs = jnp.stack([q_off, k_off]).astype(jnp.int32)
    do = g.transpose(0, 2, 1, 3)
    delta = jnp.sum(
        do.astype(jnp.float32) * out_bhtd.astype(jnp.float32), axis=-1
    )[:, :, None, :]
    qspec, kvfull, lse_row = _specs(block_q, block_k, t, d)
    qfull = pl.BlockSpec((None, None, t, d), lambda bi, hi, i: (bi, hi, 0, 0))
    kvspec = pl.BlockSpec((None, None, block_k, d), lambda bi, hi, j: (bi, hi, j, 0))
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))

    # rows invisible in this hop (lse at the -1e30 sentinel) carry no lse
    # gradient; NEG_INF is finite, so compare, don't isfinite
    g_lse = jnp.where(lse <= NEG_INF / 2, 0.0, g_lse.astype(jnp.float32))
    if _bwd_use_fused(t, d, cfg.bwd_mode):
        dk, dv, dq = pl.pallas_call(
            partial(_dkvq_kernel_offs, block_q=block_q, block_k=block_k, scale=scale),
            grid=(b, h, t // block_k),
            in_specs=[
                _SMEM_SPEC, qfull, kvspec, kvspec, qfull, lse_row, lse_row, lse_row,
            ],
            out_specs=[kvspec, kvspec, qfull],
            out_shape=[
                jax.ShapeDtypeStruct(kt.shape, k.dtype),
                jax.ShapeDtypeStruct(vt.shape, v.dtype),
                jax.ShapeDtypeStruct(qt.shape, q.dtype),
            ],
            scratch_shapes=_dq_scratch(t, d),
            # sequential k-block accumulation into dq_acc — see _dkvq_kernel
            compiler_params=_compiler_params("parallel", "parallel", "arbitrary"),
            interpret=interpret,
        )(offs, qt, kt, vt, do, lse, delta, g_lse)
    else:
        split_params = _compiler_params("parallel", "parallel", "parallel")
        dq = pl.pallas_call(
            partial(_dq_kernel_offs, block_q=block_q, block_k=block_k, scale=scale),
            grid=(b, h, t // block_q),
            in_specs=[_SMEM_SPEC, qspec, kvfull, kvfull, qspec, lse_row, lse_row, lse_row],
            out_specs=qspec,
            out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
            compiler_params=split_params,
            interpret=interpret,
        )(offs, qt, kt, vt, do, lse, delta, g_lse)
        dk, dv = pl.pallas_call(
            partial(_dkv_kernel_offs, block_q=block_q, block_k=block_k, scale=scale),
            grid=(b, h, t // block_k),
            in_specs=[_SMEM_SPEC, qfull, kvspec, kvspec, qfull, lse_row, lse_row, lse_row],
            out_specs=[kvspec, kvspec],
            out_shape=[
                jax.ShapeDtypeStruct(kt.shape, k.dtype),
                jax.ShapeDtypeStruct(vt.shape, v.dtype),
            ],
            compiler_params=split_params,
            interpret=interpret,
        )(offs, qt, kt, vt, do, lse, delta, g_lse)
    dq, dk, dv = (x.transpose(0, 2, 1, 3) for x in (dq, dk, dv))
    zero = jnp.zeros((), jnp.float32)  # int offsets carry no gradient
    return dq, dk, dv, zero, zero


flash_attention_block.defvjp(_fab_fwd, _fab_bwd)
