"""Pallas flash attention (single chip).

Blockwise causal attention with online softmax: O(T·D) VMEM per program
instead of the O(T²) logits matrix. Grid is (batch, heads, q-blocks); each
program streams K/V blocks up to its causal frontier, keeping running
(max, denom, accumulator) statistics in fp32 while the matmuls feed the MXU
in the input dtype.

Training: ``flash_attention`` carries a custom VJP whose backward pass
recomputes attention with the standard XLA path (rematerialization — the
fused forward is where the memory win matters; the backward stays
compiler-scheduled). Inference/eval uses the kernel alone.

Playbook: /opt/skills/guides/pallas_guide.md (grid/BlockSpec, online
softmax accumulation, broadcasted_iota masking, @pl.when).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_q, block_k, causal, scale):
    qi = pl.program_id(2)
    t = k_ref.shape[0]
    q = q_ref[:].astype(jnp.float32) * scale  # [BQ, D]

    acc = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    m = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)

    n_blocks = t // block_k
    if causal:
        # only stream K/V blocks that intersect the causal frontier
        n_blocks = lax.div((qi + 1) * block_q + block_k - 1, block_k)

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [BQ, BK]
        if causal:
            rows = qi * block_q + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(m_new <= NEG_INF / 2, 0.0, p)
        alpha = jnp.where(m <= NEG_INF / 2, 0.0, jnp.exp(m - m_new))
        acc = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        return acc, m_new, l

    acc, m, l = lax.fori_loop(0, n_blocks, body, (acc, m, l))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_bthd(q, k, v, *, block_q, block_k, causal, interpret):
    """q,k,v: [B, H, T, D] → [B, H, T, D]."""
    b, h, t, d = q.shape
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    assert t % block_q == 0 and t % block_k == 0, "T must divide the block sizes"
    scale = d ** -0.5
    grid = (b, h, t // block_q)
    # None-squeezed leading dims: kernel refs arrive 2D ([BQ, D] / [T, D]).
    # (.at[] ref views are rejected by this environment's Mosaic compiler.)
    qspec = pl.BlockSpec((None, None, block_q, d), lambda bi, hi, i: (bi, hi, i, 0))
    kvspec = pl.BlockSpec((None, None, t, d), lambda bi, hi, i: (bi, hi, 0, 0))

    kernel = partial(
        _flash_kernel, block_q=block_q, block_k=block_k, causal=causal, scale=scale
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[qspec, kvspec, kvspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q, k, v, causal: bool = True, block_q: int = 128, block_k: int = 128, interpret: bool = False
):
    """Flash attention. q,k,v: [B, T, H, D] (GQA heads pre-repeated)."""
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = _flash_bthd(
        qt, kt, vt, block_q=block_q, block_k=block_k, causal=causal, interpret=interpret
    )
    return out.transpose(0, 2, 1, 3)


def _fwd(q, k, v, causal, block_q, block_k, interpret):
    return flash_attention(q, k, v, causal, block_q, block_k, interpret), (q, k, v)


def _bwd(causal, block_q, block_k, interpret, res, g):
    """Rematerialized backward through the reference XLA attention."""
    from p2pfl_tpu.ops.attention import causal_attention

    q, k, v = res
    if causal:
        _, vjp = jax.vjp(causal_attention, q, k, v)
    else:

        def dense(q_, k_, v_):
            d = q_.shape[-1]
            s = jnp.einsum("bqhd,bkhd->bhqk", q_, k_, preferred_element_type=jnp.float32)
            p = jax.nn.softmax(s * (d ** -0.5), axis=-1).astype(q_.dtype)
            return jnp.einsum("bhqk,bkhd->bqhd", p, v_)

        _, vjp = jax.vjp(dense, q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
