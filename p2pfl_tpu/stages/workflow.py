"""Workflow loop (reference ``p2pfl/stages/workflows.py:28-47``)."""

from __future__ import annotations

from typing import TYPE_CHECKING

from p2pfl_tpu.management.logger import logger

if TYPE_CHECKING:
    from p2pfl_tpu.node import Node


class LearningWorkflow:
    """Runs stages until one returns ``None``. Exceptions end the experiment."""

    def run(self, node: "Node") -> None:
        import time

        from p2pfl_tpu.communication.faults import FaultCrash
        from p2pfl_tpu.stages.learning_stages import StartLearningStage

        def flush_pending_metrics() -> None:
            # a round that trained but never reached RoundFinishedStage
            # (interrupt mid-gossip, stage failure) must not silently drop
            # its already-computed metrics — the staged path would have
            # logged/broadcast them inside TrainStage. Best-effort: the
            # transport may already be stopping. MUST run before
            # state.clear() so the metrics keep their experiment identity.
            try:
                from p2pfl_tpu.stages.learning_stages import RoundFinishedStage

                RoundFinishedStage._flush_round_metrics(node)
            except Exception:  # noqa: BLE001 — abort-path flush never masks the exit
                pass

        from p2pfl_tpu.management.telemetry import telemetry

        stage = StartLearningStage
        try:
            while stage is not None:
                logger.debug(node.addr, f"── stage: {stage.name}")
                # stall-watchdog instrumentation (management/watchdog.py)
                node.state.current_stage = stage.name
                node.state.last_transition = time.monotonic()
                state = node.state
                # flight recorder: every FSM stage is a span on the node's
                # "stage" plane, tagged with the round so RoundReport can
                # attribute round wall-clock per stage. The trace id is
                # DETERMINISTIC per (experiment epoch, round) — every node
                # derives the same one, so all nodes' spans of one round
                # form one trace without any coordination, and wire ctx
                # stamped under this span links the cross-node edges.
                trace_id = (
                    f"{state.experiment_name or 'exp'}:"
                    f"{getattr(state, 'experiment_epoch', 0)}:r{state.round or 0}"
                )
                try:
                    # crash-at-stage seam (communication/faults.py): hooks run on
                    # every transition and may raise FaultCrash to kill the node
                    for hook in node.stage_hooks:
                        hook(node, stage.name)
                    with telemetry.span(
                        node.addr,
                        stage.name,
                        kind="stage",
                        attrs={
                            "round": state.round,
                            "experiment": state.experiment_name,
                        },
                        trace_id=trace_id,
                    ):
                        stage = stage.execute(node)
                except FaultCrash as exc:
                    # injected hard crash: the node is already torn down with no
                    # goodbyes; just stop executing, like a killed process —
                    # including the pending metric stash (a dead process
                    # publishes nothing)
                    if node.learner is not None:
                        node.learner.pop_round_metrics()
                    logger.info(node.addr, f"{exc}")
                    return
                except Exception as exc:  # noqa: BLE001 — stage failure ends learning, not the node
                    flush_pending_metrics()
                    if node.learning_interrupted():
                        logger.info(node.addr, f"Learning interrupted during {stage.name}")
                    else:
                        logger.error(node.addr, f"Stage {stage.name} failed: {exc!r}")
                        # a failed stage must not leave experiment state latched:
                        # the monotone control-plane merges (commands/control.py)
                        # assume nei_status/models_aggregated reset at experiment
                        # boundaries, and a stale "peer is at round N" entry would
                        # exclude that peer from the next experiment's diffusion
                        # forever (interrupt path already clears via _stop_learning)
                        node.state.clear()
                        # same for the aggregator: a stage that died between
                        # set_nodes_to_aggregate() and the aggregation resolving
                        # leaves _complete cleared, and the NEXT experiment's
                        # set_nodes_to_aggregate would raise "already in
                        # progress" — failing every subsequent experiment one
                        # stage in until an explicit stop_learning
                        node.aggregator.clear()
                    return
        finally:
            # covers the remaining exits: a stage returning None mid-round
            # (interrupt during gossip/diffusion). No-op when a flush
            # already ran — the stash pops on read.
            flush_pending_metrics()
