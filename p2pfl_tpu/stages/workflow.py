"""Workflow loop (reference ``p2pfl/stages/workflows.py:28-47``)."""

from __future__ import annotations

from typing import TYPE_CHECKING

from p2pfl_tpu.management.logger import logger

if TYPE_CHECKING:
    from p2pfl_tpu.node import Node


class LearningWorkflow:
    """Runs stages until one returns ``None``. Exceptions end the experiment."""

    def run(self, node: "Node") -> None:
        import time

        from p2pfl_tpu.communication.faults import FaultCrash
        from p2pfl_tpu.stages.learning_stages import StartLearningStage

        stage = StartLearningStage
        while stage is not None:
            logger.debug(node.addr, f"── stage: {stage.name}")
            # stall-watchdog instrumentation (management/watchdog.py)
            node.state.current_stage = stage.name
            node.state.last_transition = time.monotonic()
            try:
                # crash-at-stage seam (communication/faults.py): hooks run on
                # every transition and may raise FaultCrash to kill the node
                for hook in node.stage_hooks:
                    hook(node, stage.name)
                stage = stage.execute(node)
            except FaultCrash as exc:
                # injected hard crash: the node is already torn down with no
                # goodbyes; just stop executing, like a killed process
                logger.info(node.addr, f"{exc}")
                return
            except Exception as exc:  # noqa: BLE001 — stage failure ends learning, not the node
                if node.learning_interrupted():
                    logger.info(node.addr, f"Learning interrupted during {stage.name}")
                else:
                    logger.error(node.addr, f"Stage {stage.name} failed: {exc!r}")
                    # a failed stage must not leave experiment state latched:
                    # the monotone control-plane merges (commands/control.py)
                    # assume nei_status/models_aggregated reset at experiment
                    # boundaries, and a stale "peer is at round N" entry would
                    # exclude that peer from the next experiment's diffusion
                    # forever (interrupt path already clears via _stop_learning)
                    node.state.clear()
                    # same for the aggregator: a stage that died between
                    # set_nodes_to_aggregate() and the aggregation resolving
                    # leaves _complete cleared, and the NEXT experiment's
                    # set_nodes_to_aggregate would raise "already in
                    # progress" — failing every subsequent experiment one
                    # stage in until an explicit stop_learning
                    node.aggregator.clear()
                return
