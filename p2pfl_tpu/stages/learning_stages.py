"""The six stages of a federated round.

Reference: ``p2pfl/stages/base_node/*.py`` (SURVEY §2.2, call stack §3.3).
Semantics replicated 1:1 including the documented quirks (voting happens only
in round 0; the elected train set is reused for all rounds —
``round_finished_stage.py:69-70``). Device work (fit / evaluate / aggregate)
happens inside the learner & aggregator as jitted pure functions; every
``wait`` here is a host-side event.
"""

from __future__ import annotations

import math
import random
import time
from typing import TYPE_CHECKING, Optional, Type

from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.management.telemetry import telemetry
from p2pfl_tpu.settings import Settings
from p2pfl_tpu.stages.stage import Stage


def _wait_span(node: "Node", name: str):
    """A sub-span on the stage plane (nested under the FSM stage span) for
    the waits that gate a round — RoundReport lists these separately from
    the top-level stage split so e.g. aggregation-wait burn is visible."""
    return telemetry.span(
        node.addr,
        name,
        kind="stage",
        attrs={"round": node.state.round, "experiment": node.state.experiment_name},
    )

if TYPE_CHECKING:
    from p2pfl_tpu.node import Node


def broadcast_metrics(node: "Node", metrics: dict) -> None:
    """The ONE builder of the ``metrics`` wire message.

    Both publishers — the staged path's pre-train evaluate and the fused
    round's batched flush — must emit provably identical messages, so the
    flatten + ``build_msg`` lives exactly once.
    """
    if not metrics:
        return
    flat: list[str] = []
    for k, v in metrics.items():
        flat += [k, str(float(v))]
    node.protocol.broadcast(
        node.protocol.build_msg("metrics", flat, round=node.state.round or 0)
    )


def sync_initial_model(node: "Node") -> bool:
    """Synchronize the experiment's initial weights across the overlay.

    The shared first act of BOTH control planes (the sync FSM's
    ``StartLearningStage`` and the async workflow in
    ``federation/workflow.py``): consume an init_model that raced ahead of
    ``start_learning``, wait for the ``model_initialized`` latch, apply
    the pending init, then push init weights to peers that have not
    announced initialization. Returns False when the experiment cannot
    proceed (init timeout → graceful abort with ``state.clear()``;
    architecture mismatch → ``stop_async``; interrupt) — side effects
    identical to the historical in-stage behavior.
    """
    state = node.state
    # an init_model may have raced ahead of our start_learning (weights
    # plane vs TTL-flooded control broadcast): consume the fresh stash
    # (commands/learning.py InitModelCommand) instead of waiting for a
    # redelivery the initiator's exited push loop will never make
    early = node.take_early_init()
    if early is not None and not state.model_initialized_event.is_set():
        try:
            if early.params is None:
                early = node.learner.materialize(early)
            node.pending_init_update = early
            state.model_initialized_event.set()
            node.protocol.broadcast(node.protocol.build_msg("model_initialized"))
        except Exception as exc:  # noqa: BLE001 — a bad stash falls back to the normal wait
            logger.info(
                node.addr,
                f"Stashed early init_model unusable ({exc!r}) — waiting for redelivery",
            )

    # wait for initial weights: the initiator's event was set by
    # set_start_learning(); everyone else blocks until init_model arrives
    # (reference blocks on model_initialized_lock, start_learning_stage.py:78)
    if not state.model_initialized_event.wait(timeout=Settings.AGGREGATION_TIMEOUT):
        # graceful abort, not an escaping TimeoutError: the initiator may
        # have died before its init_model reached us — this node clears
        # the experiment and keeps serving the overlay (it can join the
        # next start_learning normally)
        logger.error(
            node.addr,
            "Initial model never arrived within AGGREGATION_TIMEOUT — "
            "aborting the experiment (node keeps serving)",
        )
        # an init that straggles in DURING the abort is this (dead)
        # experiment's — it must not sit in the stash and seed the
        # next one (anything later than this is bounded by the
        # EARLY_INIT_TTL freshness check)
        node.take_early_init()
        state.clear()
        return False
    if node.pending_init_update is not None:
        try:
            node.learner.set_parameters(node.pending_init_update.params)
        except Exception as exc:  # noqa: BLE001 — mismatched init stops the node (reference :106-117)
            logger.error(node.addr, f"Initial model does not match architecture: {exc} — stopping")
            node.stop_async()
            return False
        node.pending_init_update = None

    # push init weights to peers that haven't announced initialization
    # (reference start_learning_stage.py:80,94-136)
    def candidates() -> list[str]:
        neis = node.protocol.get_neighbors(only_direct=True)
        return [n for n in neis if state.nei_status.get(n, 0) != -1]

    def model_fn(nei: str):
        # encode-once: the update carries the learner's payload cache,
        # so byte transports serialize once per model version — not once
        # per candidate per tick (learning/weights.py)
        update = node.learner.get_model_update()
        return node.protocol.build_weights("init_model", 0, update)

    node.protocol.gossip_weights(
        early_stopping_fn=node.learning_interrupted,
        get_candidates_fn=candidates,
        status_fn=lambda: sorted(candidates()),
        model_fn=model_fn,
    )
    return not node.learning_interrupted()


class StartLearningStage(Stage):
    """Set up the experiment, synchronize initial weights across the overlay."""

    name = "StartLearningStage"

    @staticmethod
    def execute(node: "Node") -> Optional[Type[Stage]]:
        state = node.state
        state.set_experiment(
            node.experiment_name, node.total_rounds, xid=node._pending_xid
        )
        # stamp the experiment identity on every outgoing frame from here
        # on (the optional "xp" wire header — receivers filter stale
        # cross-experiment traffic on it exactly)
        node.protocol.experiment_xid = state.experiment_xid
        logger.experiment_started(node.addr)
        # fresh experiment: cross-round strategy state (FedOpt moments,
        # CenteredClip center) from any previous experiment must not leak in
        node.aggregator.reset_experiment()
        node.learner.set_epochs(node.epochs)
        node.learner.set_addr(node.addr)
        # a metric stash left by an aborted round must not flush into THIS
        # experiment's round 0 (fused path batches metrics per round)
        node.learner.pop_round_metrics()

        if Settings.SECURE_AGGREGATION:
            from p2pfl_tpu.learning import secagg

            # fail the misconfigurations loudly BEFORE any training: masks
            # only cancel through a lossless, linear aggregation path
            if Settings.WIRE_COMPRESSION != "none":
                logger.error(
                    node.addr,
                    f"SECURE_AGGREGATION is incompatible with WIRE_COMPRESSION="
                    f"{Settings.WIRE_COMPRESSION!r}: per-node quantization of the "
                    "masks breaks exact cancellation — aborting the experiment",
                )
                state.clear()
                return None
            if not getattr(node.aggregator, "MASK_COMPATIBLE", False):
                logger.error(
                    node.addr,
                    f"SECURE_AGGREGATION requires a linear aggregator (FedAvg "
                    f"family); {type(node.aggregator).__name__} would operate on "
                    "masked noise — aborting the experiment",
                )
                state.clear()
                return None
            # announce this experiment's DH public key (+ sample count, which
            # peers need for the pair mask scales) so any later train set can
            # derive pairwise mask seeds (learning/secagg.py)
            state.secagg_priv, pub = secagg.dh_keypair()
            # latch the announced count: masking later checks the actual
            # num_samples against it — peers scale their half of each pair
            # mask with THIS value, so a silent divergence would break
            # cancellation undetectably
            state.secagg_samples = node.learner.get_num_samples()
            node.protocol.broadcast(
                node.protocol.build_msg(
                    "secagg_pub",
                    [f"{pub:x}", str(state.secagg_samples)],
                    round=0,
                )
            )

        # init-weights sync (shared with the async control plane): early
        # stash consume → latch wait → apply → init gossip push
        if not sync_initial_model(node):
            return None

        # every node now holds the round's shared init weights: pin them as
        # the delta-coding anchor for this round's wire payloads (topk8)
        node.learner.set_wire_anchor(
            node.learner.get_parameters(),
            tag=f"{state.experiment_epoch}:{state.round or 0}",
        )

        # let heartbeats flood so the full membership is known before voting
        time.sleep(Settings.WAIT_HEARTBEATS_CONVERGENCE)
        return VoteTrainSetStage


class VoteTrainSetStage(Stage):
    """Elect the train set by weighted random voting (§2.2 VoteTrainSetStage)."""

    name = "VoteTrainSetStage"

    @staticmethod
    def execute(node: "Node") -> Optional[Type[Stage]]:
        state = node.state
        candidates = list(node.protocol.get_neighbors(only_direct=False)) + [node.addr]

        # cast: up to TRAIN_SET_SIZE random picks, weight ~ floor(U(0,1000)/(i+1))
        # (reference vote_train_set_stage.py:78-81 — random weights by design)
        samples = min(Settings.TRAIN_SET_SIZE, len(candidates))
        picks = random.sample(candidates, samples)
        my_votes = {n: math.floor(random.randint(0, 1000) / (i + 1)) for i, n in enumerate(picks)}
        with state.train_set_votes_lock:
            state.train_set_votes[node.addr] = dict(my_votes)
        flat: list[str] = []
        for n, w in my_votes.items():
            flat += [n, str(w)]
        node.protocol.broadcast(
            node.protocol.build_msg("vote_train_set", flat, round=state.round or 0)
        )

        # collect until every LIVE candidate voted or VOTE_TIMEOUT
        # (reference poll loop :107-165). Liveness is re-checked every
        # iteration, NOT snapshotted at stage entry: a candidate killed
        # mid-startup (crashed after start_learning, before voting) is
        # heartbeat-evicted within ~HEARTBEAT_TIMEOUT, and waiting out the
        # full VOTE_TIMEOUT for a corpse's vote was the root cause of the
        # kill-a-node-mid-startup wedge — every survivor sat in
        # VoteTrainSetStage for the whole window (60 s at defaults) while
        # the flight recorder showed the eviction landing in the first
        # two seconds. Votes that DID arrive from a since-evicted node
        # still count in the tally (same as the timeout path).
        deadline = time.monotonic() + Settings.VOTE_TIMEOUT
        while not node.learning_interrupted():
            with state.train_set_votes_lock:
                voted = set(state.train_set_votes)
            live = set(node.protocol.get_neighbors(only_direct=False)) | {node.addr}
            waiting = (set(candidates) & live) - voted
            if not waiting:
                dead = sorted(set(candidates) - live)
                if dead:
                    logger.info(
                        node.addr,
                        f"Vote: all live candidates voted — proceeding without "
                        f"evicted candidate(s) {dead}",
                    )
                break
            if time.monotonic() >= deadline:
                logger.info(
                    node.addr,
                    f"Vote timeout — proceeding with {len(voted)}/{len(candidates)} votes",
                )
                break
            # woken by arriving votes AND by evictions (Node._on_peer_evicted
            # sets the event so a corpse releases this wait immediately)
            state.votes_ready_event.wait(timeout=2)
            state.votes_ready_event.clear()
        if node.learning_interrupted():
            return None

        # tally with deterministic tie-break (votes desc, then name desc —
        # reference :152-155) so every node elects the same set; consume the
        # votes atomically (reference resets to {} at :160) so a later
        # election never tallies this round's stale entries
        with state.train_set_votes_lock:
            all_votes = {v: dict(w) for v, w in state.train_set_votes.items()}
            state.train_set_votes.clear()
        results: dict[str, int] = {}
        for votes in all_votes.values():
            for n, w in votes.items():
                results[n] = results.get(n, 0) + int(w)
        ranked = sorted(results.items(), key=lambda kv: (kv[1], kv[0]), reverse=True)
        train_set = [n for n, _ in ranked[: Settings.TRAIN_SET_SIZE]]

        # drop elected nodes that died since (reference :167-178); the live
        # snapshot and the assignment run under train_set_lock so an
        # eviction listener's concurrent read-filter-write
        # (Node._on_peer_evicted, heartbeater thread) cannot interleave
        # and replace the fresh election with a stale filtered list
        with state.train_set_lock:
            live = set(node.protocol.get_neighbors(only_direct=False)) | {node.addr}
            state.train_set = [n for n in train_set if n in live]
            state.train_set_evicted = set()  # fresh election: repairs reset
        logger.info(node.addr, f"Train set: {state.train_set}")

        return TrainStage if node.addr in state.train_set else WaitAggregatedModelsStage


class TrainStage(Stage):
    """Local training + partial-aggregation gossip within the train set."""

    name = "TrainStage"

    @staticmethod
    def execute(node: "Node") -> Optional[Type[Stage]]:
        state = node.state
        # the FULL elected set opens the window (an already-evicted member's
        # contributions that reached peers must stay aggregatable), then
        # earlier rounds'/pre-stage evictions shrink the coverage target —
        # the same repair Node._on_peer_evicted applies mid-round
        # pin what the Byzantine admission screen compares contributions
        # against: the round-start global every train-set member shares
        # (by reference — no copy, no host sync; federation/defense.py)
        node.aggregator.set_screen_reference(node.learner.get_parameters())
        node.aggregator.set_nodes_to_aggregate(state.train_set)
        for gone in list(state.train_set_evicted):
            node.aggregator.discard_member(gone)
        if Settings.SECURE_AGGREGATION:
            # stash the round-start global: if a dropout makes the round's
            # masked aggregate unrecoverable, the round is discarded back to
            # this model instead of applying noise (GossipModelStage)
            node.round_start_params = node.learner.get_parameters()

        # local compute. Fused (Settings.ROUND_FUSED): eval + all local
        # epochs + the node's own weighted fp32 partial fold run as ONE
        # donated dispatch (parallel/spmd.py fused_node_round) — metrics
        # come back as device scalars batched into RoundFinishedStage's
        # single flush, and the own update below carries device-resident
        # params + partial_acc, so nothing on the model plane syncs to
        # host between here and the aggregate. Learners that cannot fuse
        # (Dummy/LoRA/personalized, DP-SGD) return None and take the
        # staged path — kept verbatim as the bit-parity baseline
        # (tests/test_fused_round.py).
        own = None
        if Settings.ROUND_FUSED and not node.learning_interrupted():
            own = node.learner.fused_round()
        if own is None:
            # evaluate current model, share metrics (reference train_stage.py:59-60,95-112)
            TrainStage._evaluate(node)
            if node.learning_interrupted():
                return None

            # local training — the hot loop; one jitted train step per batch
            node.learner.fit()
            if node.learning_interrupted():
                return None

            # contribute own model (masked when secure aggregation is on)
            own = node.learner.get_model_update()
        if node.learning_interrupted():
            return None
        if (
            Settings.WIRE_COMPRESSION == "topk8"
            and Settings.TOPK_ERROR_FEEDBACK
            and not Settings.SECURE_AGGREGATION
        ):
            # error feedback rides ONLY on the own train-stage contribution
            # — exactly one encode per round updates the residual store
            own.ef_residual = node.learner.ef_residual_store()
        if Settings.SECURE_AGGREGATION and len(state.train_set) > 1:
            own = TrainStage._secagg_mask(node, own)
        if own is not None and not node.aggregator.SUPPORTS_PARTIALS:
            # robust strategies fold INDIVIDUAL models: the fused round's
            # pre-averaged (psum, wsum) accumulator must never reach them
            # — add_model raises loudly on it (the defense-in-depth half
            # of this contract); own.params is the individual model either
            # way, so stripping loses nothing
            own.partial_acc = None
        if own is not None:
            covered = node.aggregator.add_model(own)
            node.protocol.broadcast(
                node.protocol.build_msg("models_aggregated", covered, round=state.round or 0)
            )

        TrainStage._gossip_partial_aggregations(node)
        if node.learning_interrupted():
            return None
        return GossipModelStage

    @staticmethod
    def _secagg_mask(node: "Node", own):
        """Pairwise-mask the node's contribution (``learning/secagg.py``).

        Peers' DH keys were flooded at experiment start; a short poll covers
        gossip propagation lag. If masking still cannot be done safely,
        returns None — the contribution is SKIPPED, never sent unmasked
        (peers' halves of the pairwise masks would go uncancelled and turn a
        full-coverage aggregate into undetected noise; incomplete coverage
        is detected and reported by ``wait_and_get_aggregation`` instead).
        """
        from p2pfl_tpu.exceptions import SecAggError
        from p2pfl_tpu.learning import secagg

        state = node.state
        peers = [n for n in state.train_set if n != node.addr]
        deadline = time.monotonic() + Settings.VOTE_TIMEOUT
        while (
            any(n not in state.secagg_pubs for n in peers)
            and time.monotonic() < deadline
            and not node.learning_interrupted()
        ):
            time.sleep(0.1)
        round_no = state.round or 0
        # snapshot ONCE: the gossip thread keeps latching pubs while we run;
        # a key arriving between the double-mask gate and mask_update would
        # otherwise produce a pair-masked contribution with NO self mask and
        # no distributed shares — unresolvable for every peer, a guaranteed
        # federation-wide no-op round
        pubs = dict(state.secagg_pubs)
        self_seed = None
        if Settings.SECAGG_DOUBLE_MASK and peers and all(n in pubs for n in peers):
            # Bonawitz double mask: fresh per-round self seed, t-of-n
            # Shamir-shared with the train-set peers BEFORE contributing —
            # if we crash after our masked update lands, the surviving
            # majority reconstructs b^r and unsticks the aggregate, while
            # a wire snoop (who never gets t shares' plaintext — each is
            # encrypted to its holder) cannot strip the self mask
            import secrets as _secrets

            self_seed = _secrets.randbits(256)
            state.secagg_self_seed[round_no] = self_seed
            holders = sorted(peers)
            t = secagg.share_threshold(len(state.train_set))
            shares = secagg.shamir_split(self_seed, len(holders), t)
            exp = state.experiment_name or ""
            payload: list[str] = [exp]
            for holder, (x, y) in zip(holders, shares):
                key = secagg.dh_share_key(
                    state.secagg_priv, pubs[holder][0], exp
                )
                payload += [
                    holder,
                    str(x),
                    secagg.encrypt_share(y, key, round_no, node.addr, holder).hex(),
                ]
            node.protocol.broadcast(
                node.protocol.build_msg("secagg_share", payload, round=round_no)
            )
        try:
            return secagg.mask_update(
                own,
                node.addr,
                state.train_set,
                state.secagg_priv,
                pubs,
                state.experiment_name or "",
                round_no,
                announced_samples=state.secagg_samples,
                self_seed=self_seed,
            )
        except SecAggError as exc:
            logger.error(node.addr, f"SecAgg: {exc} — skipping this round's contribution")
            # peers hold shares of our self seed but our masked update never
            # entered the aggregate: make sure WE never reveal b^r either
            state.secagg_self_seed.pop(round_no, None)
            return None

    @staticmethod
    def _evaluate(node: "Node") -> None:
        broadcast_metrics(node, node.learner.evaluate())

    @staticmethod
    def _gossip_partial_aggregations(node: "Node") -> None:
        """Push partials to train-set peers until everyone has full coverage.

        Reference ``train_stage.py:83,114-177``: candidates are train-set
        peers whose announced coverage is incomplete; each gets exactly the
        contributions it misses; ad-hoc connections are allowed because
        train-set members may not be direct neighbors.
        """
        state = node.state

        def early_stop() -> bool:
            return node.learning_interrupted()

        # re-read the train set EVERY tick, not once at stage entry:
        # mid-round repair (Node._on_peer_evicted) records evicted members
        # in state.train_set_evicted, and a snapshot here would keep
        # gossiping at — and waiting on coverage announcements from — a
        # dead peer until the convergence detector gave up on its own
        def live_train() -> set:
            return set(state.train_set) - state.train_set_evicted

        def candidates() -> list[str]:
            train = live_train()
            out = []
            for n in train - {node.addr}:
                if not (train <= set(state.models_aggregated.get(n, []))):
                    out.append(n)
            return out

        def status():
            train = live_train()
            return {n: tuple(sorted(state.models_aggregated.get(n, []))) for n in sorted(train)}

        def model_fn(nei: str):
            # the aggregator memoizes the combined partial per source-group
            # set and returns the same instance, so repeat candidates reuse
            # both the aggregation and (on byte transports) its encode
            peer_has = state.models_aggregated.get(nei, [])
            partial = node.aggregator.get_partial_aggregation(peer_has)
            if partial is None:
                # robust strategies (SUPPORTS_PARTIALS=False) ship individual
                # models instead of a pre-average; one per tick, the peer's
                # coverage broadcasts advance the queue
                todo = node.aggregator.get_models_to_send(peer_has)
                if not todo:
                    return None
                partial = todo[0]
            return node.protocol.build_weights("add_model", state.round or 0, partial)

        with _wait_span(node, "gossip_partials"):
            node.protocol.gossip_weights(
                early_stopping_fn=early_stop,
                get_candidates_fn=candidates,
                status_fn=status,
                model_fn=model_fn,
                create_connection=True,
            )


class WaitAggregatedModelsStage(Stage):
    """Non-train-set path: wait for the aggregated model to be pushed to us."""

    name = "WaitAggregatedModelsStage"

    @staticmethod
    def execute(node: "Node") -> Optional[Type[Stage]]:
        # full elected set, then apply pre-stage evictions — mirrors
        # TrainStage so the acceptance interval stays
        # [survivors, full train set] on both paths
        node.aggregator.set_waiting_aggregated_model(node.state.train_set)
        for gone in list(node.state.train_set_evicted):
            node.aggregator.discard_member(gone)
        return GossipModelStage



def _noop_round_update(node: "Node", train: set):
    """The shared failed-recovery fallback: keep the round-start globals,
    flagged ``noop_round`` so GossipModelStage never diffuses them as the
    round's aggregate (ADVICE r3). One definition — three recovery paths
    (pair seeds, self seeds, missing weights) must stay in sync."""
    from p2pfl_tpu.learning.weights import ModelUpdate

    prev = getattr(node, "round_start_params", None)
    if prev is None:
        prev = node.learner.get_parameters()
    return ModelUpdate(prev, sorted(train), 1, noop_round=True)


class GossipModelStage(Stage):
    """Close the round's aggregation and diffuse the result outward."""

    name = "GossipModelStage"

    @staticmethod
    def execute(node: "Node") -> Optional[Type[Stage]]:
        state = node.state
        timeout = None
        if Settings.SECURE_AGGREGATION and node.addr not in state.train_set:
            # non-train-set nodes only accept a full-coverage diffusion;
            # leave headroom for the train set's seed-recovery round to
            # finish before giving up on that diffusion arriving
            timeout = Settings.AGGREGATION_TIMEOUT + Settings.SECAGG_RECOVERY_TIMEOUT
        with _wait_span(node, "aggregation_wait") as sp:
            agg = node.aggregator.wait_and_get_aggregation(timeout=timeout)
            if sp is not None:
                # partial coverage here means the wait closed by timeout or
                # repair, not full arrival — the report's timeout-burn signal
                sp.attrs["contributors"] = len(agg.contributors)
        if Settings.SECURE_AGGREGATION:
            agg = GossipModelStage._secagg_finalize(node, agg)
        node.learner.set_parameters(agg.params)
        if node.learning_interrupted():
            return None
        node.protocol.broadcast(
            node.protocol.build_msg("models_ready", [], round=state.round or 0)
        )
        if agg.noop_round:
            # failed secagg recovery: our params are the round-start global,
            # NOT this round's aggregate — diffusing them with the full
            # train set as contributors would let behind neighbors adopt
            # stale params as round-r consensus while recovered peers
            # diffuse the real aggregate. Finish the round quietly; behind
            # neighbors get the aggregate from a recovered peer (or no-op
            # this round exactly as we did).
            logger.warning(
                node.addr,
                "SecAgg: no-op round — skipping outward diffusion of the "
                "round-start globals (not this round's aggregate)",
            )
            return RoundFinishedStage

        # diffusion: push the aggregated model to direct neighbors that are
        # behind on this round (reference gossip_model_stage.py:100-124)
        def candidates() -> list[str]:
            neis = node.protocol.get_neighbors(only_direct=True)
            return [n for n in neis if state.nei_status.get(n, -1) < (state.round or 0)]

        def model_fn(nei: str):
            # encode-once applies here too: contributors ride the envelope
            # header, not the encoded tensor bytes, so rewriting them below
            # never invalidates the cached payload
            update = node.learner.get_model_update()
            # claim the survivors, not the full elected set: after repair
            # the round's aggregate genuinely lacks the evicted members
            update.contributors = [
                n for n in state.train_set if n not in state.train_set_evicted
            ]
            if Settings.SECURE_AGGREGATION and Settings.SECAGG_DOUBLE_MASK:
                # mark the diffusion as FINALIZED (self-mask-free): a
                # receiver's aggregator may otherwise hold a bit-different
                # full-coverage sum assembled from still-masked partials
                from p2pfl_tpu.learning.secagg import CLEAN_MARKER

                update.contributors = [*update.contributors, CLEAN_MARKER]
            return node.protocol.build_weights("add_model", state.round or 0, update)

        with _wait_span(node, "diffusion"):
            node.protocol.gossip_weights(
                early_stopping_fn=node.learning_interrupted,
                get_candidates_fn=candidates,
                status_fn=lambda: sorted(candidates()),
                model_fn=model_fn,
            )
        if node.learning_interrupted():
            return None
        return RoundFinishedStage

    @staticmethod
    def _secagg_finalize(node: "Node", agg):
        """Strip whatever masks remain on the round's resolved aggregate.

        Three layers, each a no-op when not applicable:

        1. PAIR recovery (partial coverage): the Bonawitz-style seed
           re-disclosure round (:meth:`_secagg_pair_recovery`).
        2. SELF-mask removal (``Settings.SECAGG_DOUBLE_MASK``): every
           contributor's per-round self mask is subtracted once its seed is
           revealed by its owner — or reconstructed from t-of-n Shamir
           shares when the owner contributed and then crashed
           (:meth:`_secagg_self_unmask`).
        3. Aggregates a peer diffused AFTER finalizing (``secagg_clean``
           flag from the wire marker) are already mask-free and pass
           through.

        Any failure resolves to a no-op round (round-start global kept)
        rather than applying a noised model.
        """
        state = node.state
        train = set(state.train_set)
        covered = set(agg.contributors)
        if len(train) <= 1 or agg.secagg_clean or agg.noop_round:
            return agg
        if covered != train:
            agg = GossipModelStage._secagg_pair_recovery(node, agg)
            if agg.noop_round or agg.secagg_clean:
                # secagg_clean: the split-brain rescue adopted a recovered
                # peer's finalized diffusion — already self-mask-free
                return agg
        elif node.addr not in train:
            # waiting-mode nodes only ever accept full-coverage diffusions;
            # an unmarked one predates double masking (or it is off) —
            # nothing to strip here either way
            return agg
        if Settings.SECAGG_DOUBLE_MASK:
            agg = GossipModelStage._secagg_self_unmask(node, agg)
        return agg

    @staticmethod
    def _secagg_pair_recovery(node: "Node", agg):
        """Dropout recovery: strip uncancelled PAIR masks from a partial
        aggregate.

        Partial coverage (some train-set member died before contributing) →
        the Bonawitz-style seed-recovery round (``learning/secagg.py``
        module docs): every survivor re-discloses its pair seeds *for the
        missing members only* (``secagg_recover`` broadcast), then everyone
        subtracts the exact uncancelled mask sum and continues with the
        survivors' clean partial aggregate — the same graceful degradation
        the reference's plain path has
        (``p2pfl/learning/aggregators/aggregator.py:236-242``). If the
        disclosures do not complete in ``Settings.SECAGG_RECOVERY_TIMEOUT``,
        the noised aggregate is DISCARDED and the round resolves to the
        round-start global (a no-op round) rather than destroying the model.
        """
        from p2pfl_tpu.learning import secagg
        from p2pfl_tpu.learning.weights import ModelUpdate

        state = node.state
        train = set(state.train_set)
        covered = set(agg.contributors)
        round_no = state.round or 0
        missing = sorted(train - covered)
        for j in missing:
            # Bonawitz invariant: members whose pair seeds this round may
            # get disclosed must never have their self seed reconstructed
            state.secagg_round_dropped.add((round_no, j))
        survivors = sorted(covered)
        logger.warning(
            node.addr,
            f"SecAgg: round {round_no} aggregate covers {survivors} — "
            f"recovering from dropout of {missing}",
        )

        weights: dict[str, int] = {n: pk[1] for n, pk in state.secagg_pubs.items()}
        if state.secagg_samples is not None:
            weights[node.addr] = state.secagg_samples
        recoverable = all(n in weights for n in set(survivors) | set(missing))

        # Recovery is request/response: broadcast WHICH members' masks we
        # cannot cancel (secagg_need) — every train-set member answers with
        # its pair seed for exactly those members (SecAggNeedCommand),
        # INCLUDING peers whose own coverage reached full and finalized
        # early (coverage views can differ at timeout: a partial that
        # reached us may have been lost to a peer). Proactively disclose our
        # own seeds for our own missing set too — peers recovering the same
        # view get them without a round trip. A LONE survivor never
        # discloses (its "aggregate" is its own model; the seeds would let
        # a wire snoop unmask it, and no peer holds anything that needs
        # them). Divergence note: if a needed disclosure is still lost,
        # some nodes recover while others no-op the round — they briefly
        # hold different models, exactly like the reference's plain
        # partial-timeout path, and the next round's aggregation
        # re-converges them.
        # pairs involving this node are locally computable by DH symmetry —
        # only the strictly-foreign pairs need the gossip plane, and only
        # when some exist is a secagg_need broadcast justified (a lone
        # survivor asking would solicit disclosures nobody uses)
        needed = {
            (i, j) for i in survivors for j in missing if node.addr not in (i, j)
        }
        exp = state.experiment_name or ""
        if recoverable and needed:
            node.protocol.broadcast(
                node.protocol.build_msg(
                    "secagg_need",
                    [exp] + sorted({j for _i, j in needed}),
                    round=round_no,
                )
            )
        live = set(node.protocol.get_neighbors(only_direct=False))
        if recoverable and node.addr in covered and len(survivors) > 1:
            # same standard of evidence as the secagg_need ANSWER path
            # (SecAggNeedCommand's liveness check): a member merely missing
            # from OUR coverage view may have contributed elsewhere and
            # already revealed its self seed on that evidence — proactively
            # disclosing its pair seeds while it is still live on the
            # overlay would publish both seed types for one (node, round)
            for j in missing:
                if j in live:
                    logger.warning(
                        node.addr,
                        f"SecAgg: {j} is missing from our coverage but still "
                        "live — withholding its pair seeds (a peer may hold "
                        "its contribution)",
                    )
                    continue
                if (round_no, j, j) in state.secagg_share_reveals:
                    # j's SELF seed is already public this round (it
                    # contributed somewhere and revealed before dying):
                    # disclosing its pair seeds too would publish both seed
                    # types for one (node, round) — the exact breach double
                    # masking exists to prevent. Privacy over availability.
                    logger.warning(
                        node.addr,
                        f"SecAgg: {j} already revealed its self seed this "
                        "round — withholding its pair seeds",
                    )
                    continue
                if j not in state.secagg_pubs or (round_no, j) in state.secagg_disclosure_sent:
                    continue
                state.secagg_disclosure_sent.add((round_no, j))
                seed = secagg.dh_pair_seed(state.secagg_priv, state.secagg_pubs[j][0], exp)
                node.protocol.broadcast(
                    node.protocol.build_msg("secagg_recover", [j, f"{seed:x}"], round=round_no)
                )
        if recoverable and any(j in live for j in missing):
            # a LIVE "missing" member means every honest peer (us included)
            # refuses to disclose its pair seeds — this seed recovery
            # provably cannot complete. Its contribution reached somebody
            # (that is why it is alive and un-evicted), so skip the futile
            # disclosure wait and adopt the recovered peers' finalized
            # diffusion instead — entering waiting mode NOW, while their
            # diffusion gossip is still retrying against us.
            rescued = GossipModelStage._secagg_split_brain_rescue(node, train, missing)
            if rescued is not None:
                return rescued
            logger.error(
                node.addr,
                "SecAgg: split-brain with a live missing member and no "
                "finalized diffusion arrived — no-op round",
            )
            return _noop_round_update(node, train)

        deadline = time.monotonic() + Settings.SECAGG_RECOVERY_TIMEOUT
        while (
            recoverable
            and not all((round_no, j, i) in state.secagg_disclosed for i, j in needed)
            and time.monotonic() < deadline
            and not node.learning_interrupted()
        ):
            time.sleep(0.1)

        seeds: dict[tuple[str, str], int] = {}
        if recoverable:
            for i, j in needed:
                v = state.secagg_disclosed.get((round_no, j, i))
                if v is None:
                    recoverable = False
                    break
                seeds[(i, j)] = v
        if recoverable:
            for i in survivors:
                for j in missing:
                    if node.addr == i:
                        seeds[(i, j)] = secagg.dh_pair_seed(
                            state.secagg_priv, state.secagg_pubs[j][0], exp
                        )
                    elif node.addr == j:
                        seeds[(i, j)] = secagg.dh_pair_seed(
                            state.secagg_priv, state.secagg_pubs[i][0], exp
                        )

        if not recoverable:
            rescued = GossipModelStage._secagg_split_brain_rescue(
                node, train, missing
            )
            if rescued is not None:
                return rescued
            # ADVICE r2: never apply or diffuse a known-noised model — give
            # the round up instead, keeping the round-start global
            logger.error(
                node.addr,
                "SecAgg: seed recovery incomplete — discarding the noised "
                "aggregate; this round is a no-op (round-start global kept)",
            )
            return _noop_round_update(node, train)

        correction = secagg.dropout_correction(
            agg.params, survivors, missing, seeds, weights, round_no
        )
        params = secagg.apply_dropout_correction(
            agg.params, correction, float(agg.num_samples)
        )
        logger.info(
            node.addr,
            f"SecAgg: recovered the survivors' clean aggregate ({len(survivors)} "
            f"of {len(train)} members, {len(missing)} seed set(s) disclosed)",
        )
        return ModelUpdate(params, list(agg.contributors), agg.num_samples)

    @staticmethod
    def _secagg_split_brain_rescue(node: "Node", train: set, missing: list):
        """Pair recovery failed but a "missing" member is still LIVE: it
        contributed to peers whose coverage view includes it (that is WHY
        everyone refuses to disclose its pair seeds — the refusal protects
        a real contribution). Those peers therefore hold the round's clean
        aggregate and their diffusion targets us — we have not announced
        ``models_ready`` yet, so we count as behind. Wait for the finalized
        diffusion like a non-train-set node instead of no-opping a round
        whose result demonstrably exists. Returns the adopted update, or
        None when no (trustably finalized) diffusion arrives in time.
        """
        state = node.state
        live = set(node.protocol.get_neighbors(only_direct=False))
        if not any(j in live for j in missing):
            return None  # genuinely dead members: nothing to wait for
        logger.warning(
            node.addr,
            "SecAgg: a missing member is still live (split-brain coverage) "
            "— waiting for a recovered peer's finalized diffusion instead "
            "of no-opping",
        )
        node.aggregator.set_waiting_aggregated_model(list(train))
        try:
            rescued = node.aggregator.wait_and_get_aggregation(
                timeout=Settings.SECAGG_RECOVERY_TIMEOUT
            )
        except Exception:  # noqa: BLE001 — nothing arrived: fall through to no-op
            return None
        if set(rescued.contributors) == train:
            # a still-MASKED full-coverage aggregate (a peer's partial
            # gossip covering the whole train set, no CLEAN_MARKER) is just
            # as good: pair masks cancel at full coverage and the caller's
            # finalize flow runs the normal self-unmask pass on anything
            # not flagged clean — rejecting it would throw away the round's
            # result AND burn the one-shot waiting window
            logger.info(
                node.addr,
                "SecAgg: adopted a peer's full-coverage aggregate "
                f"(split-brain rescue, finalized={rescued.secagg_clean})",
            )
            return rescued
        return None

    @staticmethod
    def _secagg_self_unmask(node: "Node", agg):
        """Bonawitz double masking, unmask phase (VERDICT r3 #8).

        Every contributor's ``STD·PRG_self(b_i^r)`` still rides on the
        aggregate. This node (a) discloses its OWN per-round seed — unless
        any pair-seed disclosure about it was observed this round (the
        at-most-one-of-{pair,self} invariant); (b) waits for every
        contributor's seed, revealing its held Shamir shares ONLY for
        owners whose direct reveal hasn't landed after a grace period (the
        crash backstop — flooding all n−1 shares every round would be
        O(n²) control traffic for nothing in the no-crash common case);
        then (c) subtracts the summed self masks. Incomplete ⇒ no-op
        round, exactly like pair recovery: privacy over availability.
        """
        from p2pfl_tpu.learning import secagg
        from p2pfl_tpu.learning.weights import ModelUpdate

        state = node.state
        train = set(state.train_set)
        round_no = state.round or 0
        contributors = sorted(set(agg.contributors))
        exp = state.experiment_name or ""
        my_b = state.secagg_self_seed.get(round_no)

        if node.addr in contributors:
            secagg.maybe_reveal_self_seed(node, round_no)

        t = secagg.share_threshold(len(train))

        def resolve_seeds():
            """(seeds or None, owners still unresolved)."""
            # shares that arrived for THIS round while the node was still in
            # the previous one were stashed un-judged (the holder list
            # hadn't latched); the train set is live now, so re-validate and
            # promote them before reading the reveal table
            from p2pfl_tpu.commands.control import promote_early_reveals

            promote_early_reveals(state)
            seeds: dict[str, int] = {}
            unresolved: list[str] = []
            for i in contributors:
                if i == node.addr and my_b is not None:
                    seeds[i] = my_b
                    continue
                direct = state.secagg_share_reveals.get((round_no, i, i))
                if direct is not None and direct[0] == 0:
                    seeds[i] = direct[1]
                    continue
                distinct = {
                    xy[0]: xy[1]
                    for (r, o, _src), xy in list(state.secagg_share_reveals.items())
                    if r == round_no and o == i and xy[0] >= 1
                }
                own_share = state.secagg_shares_held.get((round_no, i))
                if own_share is not None:
                    # our own held share never rides the broadcast back to
                    # us (protocol.broadcast is neighbors-only) — without it
                    # a single crash is unrecoverable for n <= 5
                    distinct.setdefault(own_share[0], own_share[1])
                if len(distinct) >= t:
                    b = secagg.shamir_reconstruct(list(distinct.items()))
                    if b < (1 << 256):  # corrupted shares reconstruct garbage
                        seeds[i] = b
                        continue
                unresolved.append(i)
            return (None if unresolved else seeds), unresolved

        def reveal_shares_for(owners: list[str]) -> None:
            for i in owners:
                if i == node.addr or (round_no, i) in state.secagg_round_dropped:
                    continue
                if (round_no, i) in state.secagg_reveal_sent:
                    continue
                share = state.secagg_shares_held.get((round_no, i))
                if share is None:
                    continue
                state.secagg_reveal_sent.add((round_no, i))
                node.protocol.broadcast(
                    node.protocol.build_msg(
                        "secagg_reveal",
                        [exp, i, str(share[0]), f"{share[1]:x}"],
                        round=round_no,
                    )
                )

        deadline = time.monotonic() + Settings.SECAGG_RECOVERY_TIMEOUT
        grace = time.monotonic() + min(2.0, Settings.SECAGG_RECOVERY_TIMEOUT / 3)
        seeds, unresolved = resolve_seeds()
        while seeds is None and time.monotonic() < deadline and not node.learning_interrupted():
            if time.monotonic() >= grace and unresolved:
                reveal_shares_for(unresolved)  # latched: re-calls are no-ops
            time.sleep(0.1)
            seeds, unresolved = resolve_seeds()

        if seeds is None:
            logger.error(
                node.addr,
                "SecAgg: self-mask seeds unresolved — discarding the masked "
                "aggregate; this round is a no-op (round-start global kept)",
            )
            return _noop_round_update(node, train)

        weights: dict[str, int] = {n: pk[1] for n, pk in state.secagg_pubs.items()}
        if state.secagg_samples is not None:
            weights[node.addr] = state.secagg_samples
        if any(i not in weights for i in contributors):
            logger.error(
                node.addr,
                "SecAgg: missing announced weights for a contributor — "
                "cannot scale self-mask correction; no-op round",
            )
            return _noop_round_update(node, train)
        correction = secagg.self_mask_correction(
            agg.params, contributors, seeds, weights, round_no
        )
        params = secagg.apply_dropout_correction(
            agg.params, correction, float(agg.num_samples)
        )
        logger.info(
            node.addr,
            f"SecAgg: self masks removed for {len(contributors)} contributor(s) "
            f"(round {round_no})",
        )
        return ModelUpdate(params, list(agg.contributors), agg.num_samples)


class RoundFinishedStage(Stage):
    """Advance or finish.

    NOTE: next round skips voting — the round-0 train set is reused for all
    rounds, replicating the reference (``round_finished_stage.py:69-70``).
    Documented divergence: the reference sends *every* node (train-set or
    not) to TrainStage on rounds ≥ 1, so non-elected nodes burn a full local
    fit whose contribution the aggregator then rejects as foreign; here
    non-elected nodes return to WaitAggregatedModelsStage, preserving the
    round-0 split and round outcomes while skipping the dead work.
    """

    name = "RoundFinishedStage"

    @staticmethod
    def _flush_round_metrics(node: "Node") -> None:
        """Batched metric flush: the fused round's ONE host callback.

        The staged path floats every metric where it is produced (an eval
        sync before training, a ``float(loss)`` after every epoch); the
        fused round instead stashes device scalars and this flush converts
        and publishes them once per round — after aggregation already
        forced the program, so the conversions are free. Mirrors the
        staged path's observable behavior: the per-epoch ``train_loss``
        series into the local metric store (same step numbers fit() would
        log), eval metrics broadcast as the ``metrics`` message (peers log
        them via ``MetricsCommand``), same round number.
        """
        metrics = node.learner.pop_round_metrics()
        if not metrics:
            return
        series = metrics.pop("train_loss_series", None)
        if series is not None:
            import numpy as np

            losses, steps = series
            for step, loss in zip(steps, np.asarray(losses)):
                logger.log_metric(node.addr, "train_loss", float(loss), step=step)
        broadcast_metrics(node, metrics)

    @staticmethod
    def execute(node: "Node") -> Optional[Type[Stage]]:
        state = node.state
        if node.learning_interrupted():
            logger.info(node.addr, "Early stopping.")
            return None
        RoundFinishedStage._flush_round_metrics(node)
        node.aggregator.clear()
        state.increase_round()
        # round boundary: the just-diffused aggregate is the next round's
        # shared model — re-pin the delta-coding anchor here, NOT inside
        # set_parameters (this round's remaining diffusion sends must still
        # delta-code against the anchor the behind nodes hold)
        node.learner.set_wire_anchor(
            node.learner.get_parameters(),
            tag=f"{state.experiment_epoch}:{state.round}",
        )
        logger.round_finished(node.addr)
        if state.round is not None and state.total_rounds is not None and state.round < state.total_rounds:
            if Settings.VOTE_EVERY_ROUND:
                return VoteTrainSetStage
            return TrainStage if node.addr in state.train_set else WaitAggregatedModelsStage
        # experiment over: final evaluation, clear state
        metrics = node.learner.evaluate()
        for k, v in (metrics or {}).items():
            logger.log_metric(node.addr, k, float(v), round=state.round, experiment=state.experiment_name)
        logger.experiment_finished(node.addr)
        # NOTE: cross-round strategy state (FedOpt moments, clip centers) is
        # NOT wiped here — it stays inspectable after the run; the next
        # experiment's StartLearningStage resets it before anything happens
        state.clear()
        return None
