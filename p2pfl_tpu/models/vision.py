"""Vision models: MLP, CNN (reference parity) and ResNet-18/50 (BASELINE).

Compute runs in bfloat16 (MXU-friendly), parameters and logits stay float32
— the standard TPU mixed-precision recipe. Reference shapes:
MLP 784-256-128-10 (``mlp.py:53-56``), 2-conv CNN (``cnn.py:55-71``).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from p2pfl_tpu.models.base import FlaxModel


class MLP(nn.Module):
    """784-256-128-10 MLP, the reference's default MNIST model."""

    hidden: Sequence[int] = (256, 128)
    num_classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for h in self.hidden:
            x = nn.Dense(h, dtype=self.dtype)(x)
            x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class CNN(nn.Module):
    """Two-conv CNN over 28x28x1, matching the reference CNN's capability."""

    channels: Sequence[int] = (32, 64)
    num_classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        for ch in self.channels:
            x = nn.Conv(ch, (3, 3), padding="SAME", dtype=self.dtype)(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class ResBlock(nn.Module):
    filters: int
    strides: tuple[int, int] = (1, 1)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (3, 3), self.strides, padding="SAME", use_bias=False, dtype=self.dtype)(x)
        y = nn.GroupNorm(num_groups=8, dtype=self.dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False, dtype=self.dtype)(y)
        y = nn.GroupNorm(num_groups=8, dtype=self.dtype)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.filters, (1, 1), self.strides, use_bias=False, dtype=self.dtype
            )(residual)
            residual = nn.GroupNorm(num_groups=8, dtype=self.dtype)(residual)
        return nn.relu(y + residual)


class BottleneckBlock(nn.Module):
    filters: int
    strides: tuple[int, int] = (1, 1)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype)(x)
        y = nn.GroupNorm(num_groups=8, dtype=self.dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), self.strides, padding="SAME", use_bias=False, dtype=self.dtype)(y)
        y = nn.GroupNorm(num_groups=8, dtype=self.dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters * 4, (1, 1), use_bias=False, dtype=self.dtype)(y)
        y = nn.GroupNorm(num_groups=8, dtype=self.dtype)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.filters * 4, (1, 1), self.strides, use_bias=False, dtype=self.dtype
            )(residual)
            residual = nn.GroupNorm(num_groups=8, dtype=self.dtype)(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """ResNet for CIFAR-scale inputs.

    GroupNorm instead of BatchNorm: federated averaging of BatchNorm running
    statistics is ill-defined across non-IID shards (a known FL failure
    mode); GroupNorm keeps every parameter a plain weight that FedAvg can
    average soundly — and avoids mutable state in the train step.
    """

    stage_sizes: Sequence[int] = (2, 2, 2, 2)
    bottleneck: bool = False
    num_classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(64, (3, 3), padding="SAME", use_bias=False, dtype=self.dtype)(x)
        x = nn.GroupNorm(num_groups=8, dtype=self.dtype)(x)
        x = nn.relu(x)
        block = BottleneckBlock if self.bottleneck else ResBlock
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = block(64 * 2**i, strides, dtype=self.dtype)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


# ---- constructors (bound to concrete params) ----


def mlp(seed: int = 0, num_classes: int = 10, input_shape=(28, 28, 1)) -> FlaxModel:
    return FlaxModel.create(MLP(num_classes=num_classes), input_shape, seed, num_classes)


def cnn(seed: int = 0, num_classes: int = 10, input_shape=(28, 28, 1)) -> FlaxModel:
    return FlaxModel.create(CNN(num_classes=num_classes), input_shape, seed, num_classes)


def resnet18(seed: int = 0, num_classes: int = 10, input_shape=(32, 32, 3)) -> FlaxModel:
    return FlaxModel.create(
        ResNet(stage_sizes=(2, 2, 2, 2), num_classes=num_classes), input_shape, seed, num_classes
    )


def resnet50(seed: int = 0, num_classes: int = 100, input_shape=(32, 32, 3)) -> FlaxModel:
    return FlaxModel.create(
        ResNet(stage_sizes=(3, 4, 6, 3), bottleneck=True, num_classes=num_classes),
        input_shape,
        seed,
        num_classes,
    )
