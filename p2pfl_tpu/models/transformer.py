"""Decoder-only transformer (TinyLlama-style) with LoRA adapters.

BASELINE config 5: federated LoRA fine-tuning — nodes train and exchange
ONLY the low-rank adapters, so a round's gossip payload drops from the full
model to a few MB. Architecture follows the Llama recipe (RMSNorm → GQA
attention with RoPE → SwiGLU), all matmuls in bfloat16 on the MXU, norms and
softmax statistics in float32.

Attention backends — pick with ``tiny_transformer(attn=...)``:

- ``"dense"`` (default): fused XLA causal attention (``ops/attention.py``);
- ``"flash"``: the Pallas flash kernel with its Pallas backward
  (``ops/flash_attention.py``) — O(T·D) memory in both directions;
- ``"ring"``: ring attention over a mesh axis (pass ``mesh=``) — the
  sequence is sharded across chips, K/V rotate via ``ppermute``.

Power users can instead pass any ``attn_fn(q, k, v) -> out`` directly.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from p2pfl_tpu.models.base import FlaxModel
from p2pfl_tpu.ops.attention import causal_attention
from p2pfl_tpu.ops.flash_attention import FlashConfig


_REMAT_SAVE_NAMES = {
    "mlp": ("ffn_gate", "ffn_up"),
    "mlp_qkv": ("ffn_gate", "ffn_up", "attn_q", "attn_k", "attn_v"),
}


def _remat_policy(name: Optional[str]):
    """Map ``TransformerConfig.remat_policy`` to a jax.checkpoint policy."""
    if name is None:
        return None  # full per-block remat: save nothing inside the block
    try:
        names = _REMAT_SAVE_NAMES[name]
    except KeyError:
        raise ValueError(
            f"unknown remat_policy {name!r} (None|{'|'.join(_REMAT_SAVE_NAMES)})"
        ) from None
    return jax.checkpoint_policies.save_only_these_names(*names)


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 2048
    dim: int = 256
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 4
    ffn_hidden: int = 688  # ~8/3 * dim rounded
    rope_theta: float = 10000.0
    lora_rank: int = 8
    lora_alpha: float = 16.0
    lora_mlp: bool = False
    dtype: Any = jnp.bfloat16
    # Mixture-of-experts FFN (n_experts=0 => dense SwiGLU everywhere).
    # Experts stack on a leading [E, ...] axis that shards over the mesh's
    # model axis for expert parallelism (parallel/sharding.py EP rules).
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity: float = 1.25  # capacity factor: C = ceil(k*S/E * factor)
    moe_aux_coef: float = 1e-2  # Switch load-balance loss coefficient
    moe_zloss_coef: float = 1e-3  # router z-loss coefficient
    # per-block rematerialization: the backward pass keeps activations only
    # at block boundaries and recomputes the interior — the standard TPU
    # recipe for fitting big-model / long-sequence training in HBM. Coarser
    # than wrapping the WHOLE loss in jax.checkpoint (which re-runs the
    # full forward and still stashes every layer during the recompute);
    # per-block boundaries bound peak activation memory at one block.
    remat: bool = False
    # selective rematerialization policy (only meaningful with remat=True):
    #   None       — full per-block remat: nothing inside a block is saved,
    #                the backward re-runs the whole block forward (max
    #                memory savings, ~1/3 extra executed FLOPs);
    #   "mlp"      — save the FFN gate/up activations (the FFN is ~70% of a
    #                block's FLOPs) so the backward recomputes only the
    #                attention side;
    #   "mlp_qkv"  — additionally save post-RoPE q/k/v (k/v pre-GQA-repeat,
    #                so 2·kv_heads·head_dim + dim per token): the backward
    #                recomputes only the flash kernel forward (for its lse
    #                residual) and elementwise glue.
    # Memory cost per token-layer (bf16): mlp = 2·ffn_hidden, mlp_qkv adds
    # dim + 2·(kv/heads)·dim. Pick the richest policy that fits HBM —
    # bench config5_nameplate_1b measures the ladder at 0.98B.
    remat_policy: Optional[str] = None
    # lax.scan over the block stack instead of Python-unrolled layers:
    # params stack on a leading [L, ...] axis and the compiled program
    # contains ONE block body regardless of depth — compile time and
    # program size stop scaling with n_layers (the unrolled 16L/768d
    # model's MLIR is big enough to overflow intermediaries; the scanned
    # one is ~1 layer's worth). The XLA-idiomatic deep-model form.
    # Incompatible with n_experts>0 for now (sown MoE aux losses don't
    # thread through nn.scan broadcasts here).
    scan_layers: bool = False
    # Static flash-kernel schedule (ops/flash_attention.FlashConfig): when
    # set, any Block built from this config WITHOUT an explicit attn_fn
    # (the pipeline stages, spmd train steps, tiny_transformer(attn="flash"))
    # runs the Pallas flash kernel under exactly this schedule. Because the
    # config is a frozen, hashable field of this (frozen, hashable) config,
    # it participates in every jit cache key that treats the module/config
    # as static — flipping block shapes or bwd_mode after a compiled step
    # provably re-traces (the guarantee the old BWD_MODE global broke).
    # None = dense XLA attention unless the caller overrides attn/attn_fn.
    flash_config: Optional[FlashConfig] = None

    def __post_init__(self) -> None:
        if self.remat_policy is not None:
            _remat_policy(self.remat_policy)  # raises on an unknown name
            if not self.remat:
                raise ValueError(
                    "remat_policy is only meaningful with remat=True — a "
                    "policy on a no-remat model would silently change the "
                    "memory/FLOPs profile the caller asked for"
                )


class RMSNorm(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        xf = x.astype(jnp.float32)
        norm = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + 1e-6)
        return (norm * scale).astype(self.dtype)


class LoRADense(nn.Module):
    """Dense with optional low-rank adapter: ``y = xW + (alpha/r)·xAB``.

    ``A`` is normal-initialized, ``B`` zeros — adapters start as identity.
    Param names carry the ``lora_`` prefix the federated layer filters on.
    """

    features: int
    rank: int = 0
    alpha: float = 16.0
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(), (x.shape[-1], self.features)
        )
        y = jnp.dot(x.astype(self.dtype), kernel.astype(self.dtype))
        if self.rank > 0:
            a = self.param(
                "lora_a", nn.initializers.normal(0.02), (x.shape[-1], self.rank)
            )
            b = self.param("lora_b", nn.initializers.zeros, (self.rank, self.features))
            y = y + jnp.dot(
                jnp.dot(x.astype(self.dtype), a.astype(self.dtype)), b.astype(self.dtype)
            ) * (self.alpha / self.rank)
        return y


def rope(x: jax.Array, theta: float) -> jax.Array:
    """Rotary position embedding over [B, T, H, D] (D even)."""
    b, t, h, d = x.shape
    half = d // 2
    freqs = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    ang = jnp.arange(t, dtype=jnp.float32)[:, None] * freqs[None, :]  # [T, half]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    cos, sin = cos[None, :, None, :], sin[None, :, None, :]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1).astype(x.dtype)


class Attention(nn.Module):
    cfg: TransformerConfig
    attn_fn: Optional[Callable] = None  # (q, k, v) -> out; default fused causal

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        head_dim = cfg.dim // cfg.n_heads
        dense = partial(LoRADense, rank=cfg.lora_rank, alpha=cfg.lora_alpha, dtype=cfg.dtype)
        q = dense(cfg.n_heads * head_dim, name="wq")(x)
        k = dense(cfg.n_kv_heads * head_dim, name="wk")(x)
        v = dense(cfg.n_kv_heads * head_dim, name="wv")(x)
        b, t = x.shape[:2]
        q = rope(q.reshape(b, t, cfg.n_heads, head_dim), cfg.rope_theta)
        k = rope(k.reshape(b, t, cfg.n_kv_heads, head_dim), cfg.rope_theta)
        v = v.reshape(b, t, cfg.n_kv_heads, head_dim)
        # selective-remat tags: saved pre-GQA-repeat (kv_heads wide, the
        # repeat is a cheap broadcast to recompute)
        q = checkpoint_name(q, "attn_q")
        k = checkpoint_name(k, "attn_k")
        v = checkpoint_name(v, "attn_v")
        # GQA: repeat K/V heads to match Q heads
        rep = cfg.n_heads // cfg.n_kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
        if self.attn_fn is not None:
            attend = self.attn_fn
        elif cfg.flash_config is not None:
            # cfg-pinned flash schedule: every path that builds Blocks from
            # the config alone (pipeline stages, spmd train steps) picks up
            # the SAME statically-keyed kernel without threading a callable
            from p2pfl_tpu.ops.flash_attention import flash_attention

            attend = partial(
                flash_attention,
                causal=True,
                config=cfg.flash_config,
                interpret=jax.default_backend() != "tpu",
            )
        else:
            attend = causal_attention
        out = attend(q, k, v).reshape(b, t, cfg.dim)
        return dense(cfg.dim, name="wo")(out)


class MLP(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        rank = cfg.lora_rank if cfg.lora_mlp else 0
        dense = partial(LoRADense, rank=rank, alpha=cfg.lora_alpha, dtype=cfg.dtype)
        gate = checkpoint_name(dense(cfg.ffn_hidden, name="w1")(x), "ffn_gate")
        up = checkpoint_name(dense(cfg.ffn_hidden, name="w3")(x), "ffn_up")
        return dense(cfg.dim, name="w2")(nn.silu(gate) * up)


class MoEMLP(nn.Module):
    """Mixture-of-experts SwiGLU FFN with capacity-based dense dispatch.

    The GShard/Switch formulation: routing becomes two einsums against a
    [S, E, C] dispatch tensor, so the whole layer is MXU matmuls with
    static shapes — no gather/scatter, no dynamic shapes, nothing XLA
    can't tile. Expert weights stack on a leading [E, ...] axis; sharding
    that axis over the ``model`` mesh axis is expert parallelism (XLA
    turns the dispatch/combine einsums into the token all-to-alls).

    Tokens beyond an expert's capacity ``C = ceil(k·S/E · capacity)`` are
    dropped (their combine weight is zero — the residual stream carries
    them unchanged, the standard Switch behavior).

    Two auxiliary scalars are sown into the ``"moe_losses"`` collection
    (read back via :func:`p2pfl_tpu.models.base.apply_with_aux`):
    the Switch load-balance loss ``E · Σ_e f_e · p̄_e`` and the router
    z-loss ``mean(logsumexp(logits)²)``.

    The reference has no MoE anywhere (its models are MLP/CNN,
    SURVEY §2.7) — this extends the transformer family for the
    expert-parallel axis of the multi-chip design.
    """

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        e, k = cfg.n_experts, cfg.moe_top_k
        b, t, d = x.shape
        s = b * t
        f = cfg.ffn_hidden
        xs = x.reshape(s, d)

        router = self.param("router", nn.initializers.normal(0.02), (d, e))
        logits = jnp.dot(xs.astype(jnp.float32), router.astype(jnp.float32))  # [S, E]
        probs = jax.nn.softmax(logits, axis=-1)

        capacity = max(1, int(-(-k * s // e) * cfg.moe_capacity))

        # iterative top-k dispatch with a running per-expert fill count
        combine = jnp.zeros((s, e, capacity), jnp.float32)
        counts = jnp.zeros((e,), jnp.float32)
        p = probs
        top1_onehot = None
        for _ in range(k):
            idx = jnp.argmax(p, axis=-1)  # [S]
            onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)  # [S, E]
            if top1_onehot is None:
                top1_onehot = onehot
            gate = jnp.sum(p * onehot, axis=-1)  # [S]
            # position of each token within its chosen expert's buffer
            pos = jnp.cumsum(onehot, axis=0) - onehot + counts[None, :]  # [S, E]
            pos_in_e = jnp.sum(pos * onehot, axis=-1)  # [S]
            keep = (pos_in_e < capacity).astype(jnp.float32)
            slot = jax.nn.one_hot(
                jnp.minimum(pos_in_e, capacity - 1).astype(jnp.int32),
                capacity,
                dtype=jnp.float32,
            )  # [S, C]
            combine = combine + (gate * keep)[:, None, None] * onehot[:, :, None] * slot[:, None, :]
            counts = counts + jnp.sum(onehot, axis=0)
            p = p * (1.0 - onehot)  # mask the chosen expert for the next pass

        # renormalize the selected gates so each routed token's weights sum to 1
        total = jnp.sum(combine, axis=(1, 2), keepdims=True)
        combine = combine / jnp.maximum(total, 1e-9)
        dispatch = (combine > 0.0).astype(cfg.dtype)  # [S, E, C]

        w1 = self.param("w1", nn.initializers.lecun_normal(), (e, d, f))
        w3 = self.param("w3", nn.initializers.lecun_normal(), (e, d, f))
        w2 = self.param("w2", nn.initializers.lecun_normal(), (e, f, d))

        xe = jnp.einsum("sec,sd->ecd", dispatch, xs.astype(cfg.dtype))  # [E, C, D]
        # same selective-remat tags as the dense MLP: the "mlp" policy
        # saves the expert hidden activations so the backward skips the
        # two big expert einsums (the layer's dominant FLOPs)
        gate_h = checkpoint_name(
            jnp.einsum("ecd,edf->ecf", xe, w1.astype(cfg.dtype)), "ffn_gate"
        )
        up_h = checkpoint_name(
            jnp.einsum("ecd,edf->ecf", xe, w3.astype(cfg.dtype)), "ffn_up"
        )
        ye = jnp.einsum("ecf,efd->ecd", nn.silu(gate_h) * up_h, w2.astype(cfg.dtype))
        out = jnp.einsum("sec,ecd->sd", combine.astype(cfg.dtype), ye)  # [S, D]

        # Switch load-balance loss: E · Σ_e (top-1 token fraction · mean prob)
        frac = jnp.mean(top1_onehot, axis=0)  # [E]
        mean_p = jnp.mean(probs, axis=0)  # [E]
        balance = e * jnp.sum(frac * mean_p)
        zloss = jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)
        self.sow(
            "moe_losses",
            "aux",
            cfg.moe_aux_coef * balance + cfg.moe_zloss_coef * zloss,
        )
        return out.reshape(b, t, d)


class Block(nn.Module):
    cfg: TransformerConfig
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x):
        x = x + Attention(self.cfg, self.attn_fn, name="attn")(
            RMSNorm(self.cfg.dtype, name="attn_norm")(x)
        )
        ffn = MoEMLP if self.cfg.n_experts > 0 else MLP
        x = x + ffn(self.cfg, name="mlp")(RMSNorm(self.cfg.dtype, name="mlp_norm")(x))
        return x


class _ScanBlock(nn.Module):
    """nn.scan body: one Block step with the (carry, xs) -> (carry, ys)
    signature lax.scan wants. Params gain a leading [L] axis via
    ``variable_axes={"params": 0}``."""

    cfg: TransformerConfig
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, _):
        return Block(self.cfg, self.attn_fn, name="block")(x), None


class CausalLM(nn.Module):
    cfg: TransformerConfig
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, tokens):  # [B, T] int32 -> [B, T, vocab] f32 logits
        cfg = self.cfg
        emb = self.param(
            "embed", nn.initializers.normal(0.02), (cfg.vocab_size, cfg.dim)
        )
        x = emb[tokens].astype(cfg.dtype)
        if cfg.scan_layers:
            if cfg.n_experts > 0:
                raise NotImplementedError(
                    "scan_layers with MoE: sown aux losses don't thread "
                    "through this scan — use unrolled layers for MoE"
                )
            body = _ScanBlock
            if cfg.remat:
                # prevent_cse=False: inside lax.scan the remat thunk can't
                # be CSE'd across iterations anyway, and True blocks the
                # scan lowering (flax's documented scan-over-remat recipe)
                body = nn.remat(
                    body, prevent_cse=False, policy=_remat_policy(cfg.remat_policy)
                )
            scan = nn.scan(
                body,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                length=cfg.n_layers,
            )
            x, _ = scan(cfg, self.attn_fn, name="layers")(x, None)
        else:
            block_cls = (
                nn.remat(Block, policy=_remat_policy(cfg.remat_policy))
                if cfg.remat
                else Block
            )
            for i in range(cfg.n_layers):
                x = block_cls(cfg, self.attn_fn, name=f"layer_{i}")(x)
        x = RMSNorm(cfg.dtype, name="final_norm")(x)
        logits = jnp.dot(x, emb.T.astype(cfg.dtype))  # tied embeddings
        return logits.astype(jnp.float32)


def pick_attention(seq_len: int, backend: Optional[str] = None) -> str:
    """The ``attn="auto"`` policy: dense vs flash by sequence length.

    Uses the crossover measured on real hardware by bench config 7
    (``Settings.FLASH_MIN_SEQ_LEN``): fused dense XLA attention wins at
    short lengths (the O(T²) logits still fit in VMEM-friendly fusions and
    the Pallas kernel's block bookkeeping costs more than it saves), flash
    wins once the logits matrix stops fitting. TPU-only: on any other
    backend the Pallas kernel runs in interpret mode (orders of magnitude
    slower — a correctness path, not a performance one), so "auto" always
    answers dense there. Single-chip policy — the ring variants shard the
    sequence over a mesh and are chosen explicitly.
    """
    from p2pfl_tpu.settings import Settings

    backend = jax.default_backend() if backend is None else backend
    if backend != "tpu":
        return "dense"
    return "flash" if seq_len >= Settings.FLASH_MIN_SEQ_LEN else "dense"


def resolve_attention(
    attn: str,
    mesh: Any = None,
    axis_name: str = "model",
    block: Optional[int] = None,
    seq_len: Optional[int] = None,
    block_bwd: Optional[int] = None,
    config: Optional[FlashConfig] = None,
) -> Optional[Callable]:
    """Map an attention backend name to an ``(q, k, v) -> out`` callable.

    ``config`` pins the full static kernel schedule
    (:class:`~p2pfl_tpu.ops.flash_attention.FlashConfig`); the legacy
    ``block``/``block_bwd`` square-block shorthands build one when no
    config is given. With neither, the kernel resolves the tuned/default
    config for its shape at trace time
    (:func:`p2pfl_tpu.ops.autotune.get_flash_config`).
    """
    if attn == "auto":
        if seq_len is None:
            raise ValueError("attn='auto' needs seq_len to pick a backend")
        attn = pick_attention(seq_len)
    if config is None and block is not None:
        config = FlashConfig(
            block_q=block, block_k=block,
            block_q_bwd=block_bwd, block_k_bwd=block_bwd,
        )
    if attn == "dense":
        return None  # Attention falls back to the fused causal path
    if attn == "flash":
        from p2pfl_tpu.ops.flash_attention import flash_attention

        # Pallas runs natively on TPU; anywhere else use interpret mode
        interpret = jax.default_backend() != "tpu"
        return partial(
            flash_attention, causal=True, config=config, interpret=interpret
        )
    if attn in ("ring", "ring_flash"):
        if mesh is None:
            raise ValueError(f"attn={attn!r} needs a mesh (sequence is sharded over it)")
        from p2pfl_tpu.ops.attention import ring_attention

        impl = "flash" if attn == "ring_flash" else "dense"
        return partial(
            ring_attention, mesh=mesh, axis_name=axis_name, impl=impl,
            block=block or 128, flash_config=config if attn == "ring_flash" else None,
        )
    raise ValueError(f"unknown attention backend {attn!r} (dense|flash|ring|ring_flash)")


def tiny_transformer(
    seq_len: int = 128,
    seed: int = 0,
    cfg: Optional[TransformerConfig] = None,
    attn_fn: Optional[Callable] = None,
    attn: str = "dense",
    mesh: Any = None,
) -> FlaxModel:
    """A small LoRA-ready causal LM bound to concrete params.

    ``attn`` selects the attention backend
    (``"auto" | "dense" | "flash" | "ring" | "ring_flash"``); ``"auto"``
    picks dense vs flash from the sequence length using the measured
    crossover (:func:`pick_attention`). ``attn_fn`` overrides it with an
    explicit callable.
    """
    cfg = cfg or TransformerConfig()
    if attn == "auto":
        attn = pick_attention(seq_len)
    if attn_fn is None:
        # flash blocks must divide the attended length: the GLOBAL sequence
        # for attn="flash", but the PER-DEVICE shard for "ring_flash" (each
        # hop's kernel sees T_local)
        basis = seq_len
        if attn == "ring_flash":
            if mesh is None:
                raise ValueError("attn='ring_flash' needs a mesh")
            from p2pfl_tpu.settings import Settings

            basis = seq_len // mesh.shape[Settings.MESH_MODEL_AXIS]
        if attn in ("flash", "ring_flash"):
            from p2pfl_tpu.ops.autotune import _fit

            # the one tiling rule (autotune._fit): blocks must divide the
            # basis and be a multiple of 8, with block == basis always
            # acceptable. Lengths <= 512 therefore always work (one full
            # block); longer lengths need SOME multiple-of-8 divisor or the
            # whole sequence becomes one VMEM-hostile block — reject those.
            if _fit(basis, 512) > 512:
                raise ValueError(
                    f"attn={attn!r} needs a flash block <= 512 dividing the "
                    f"attended length: {basis} (seq_len per shard) has no "
                    "multiple-of-8 divisor"
                )
            # kernel schedule resolution: an explicit cfg.flash_config pin
            # wins; otherwise Settings.FLASH_AUTOTUNE sweeps and caches the
            # schedule for this (T, D, dtype) here — at model-build time,
            # outside any trace — and get_flash_config serves it (pinned →
            # tune cache → shipped per-device-kind defaults table)
            from p2pfl_tpu.ops import autotune
            from p2pfl_tpu.settings import Settings

            head_dim = cfg.dim // cfg.n_heads
            flash_cfg = cfg.flash_config
            if flash_cfg is None and Settings.FLASH_AUTOTUNE:
                flash_cfg = autotune.autotune_flash(basis, head_dim, dtype=cfg.dtype)
            if flash_cfg is None:
                flash_cfg = autotune.get_flash_config(basis, head_dim, dtype=cfg.dtype)
            attn_fn = resolve_attention(attn, mesh=mesh, config=flash_cfg)
        else:
            attn_fn = resolve_attention(attn, mesh=mesh)
    module = CausalLM(cfg, attn_fn)
    rng = jax.random.PRNGKey(seed)
    dummy = jnp.zeros((1, seq_len), dtype=jnp.int32)
    variables = module.init(rng, dummy)
    model = FlaxModel(module, variables["params"], (seq_len,), cfg.vocab_size)
    model.extra["config"] = cfg
    return model
