"""Decoder-only transformer (TinyLlama-style) with LoRA adapters.

BASELINE config 5: federated LoRA fine-tuning — nodes train and exchange
ONLY the low-rank adapters, so a round's gossip payload drops from the full
model to a few MB. Architecture follows the Llama recipe (RMSNorm → GQA
attention with RoPE → SwiGLU), all matmuls in bfloat16 on the MXU, norms and
softmax statistics in float32.

Attention backends — pick with ``tiny_transformer(attn=...)``:

- ``"dense"`` (default): fused XLA causal attention (``ops/attention.py``);
- ``"flash"``: the Pallas flash kernel with its Pallas backward
  (``ops/flash_attention.py``) — O(T·D) memory in both directions;
- ``"ring"``: ring attention over a mesh axis (pass ``mesh=``) — the
  sequence is sharded across chips, K/V rotate via ``ppermute``.

Power users can instead pass any ``attn_fn(q, k, v) -> out`` directly.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from p2pfl_tpu.models.base import FlaxModel
from p2pfl_tpu.ops.attention import causal_attention


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 2048
    dim: int = 256
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 4
    ffn_hidden: int = 688  # ~8/3 * dim rounded
    rope_theta: float = 10000.0
    lora_rank: int = 8
    lora_alpha: float = 16.0
    lora_mlp: bool = False
    dtype: Any = jnp.bfloat16


class RMSNorm(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        xf = x.astype(jnp.float32)
        norm = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + 1e-6)
        return (norm * scale).astype(self.dtype)


class LoRADense(nn.Module):
    """Dense with optional low-rank adapter: ``y = xW + (alpha/r)·xAB``.

    ``A`` is normal-initialized, ``B`` zeros — adapters start as identity.
    Param names carry the ``lora_`` prefix the federated layer filters on.
    """

    features: int
    rank: int = 0
    alpha: float = 16.0
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(), (x.shape[-1], self.features)
        )
        y = jnp.dot(x.astype(self.dtype), kernel.astype(self.dtype))
        if self.rank > 0:
            a = self.param(
                "lora_a", nn.initializers.normal(0.02), (x.shape[-1], self.rank)
            )
            b = self.param("lora_b", nn.initializers.zeros, (self.rank, self.features))
            y = y + jnp.dot(
                jnp.dot(x.astype(self.dtype), a.astype(self.dtype)), b.astype(self.dtype)
            ) * (self.alpha / self.rank)
        return y


def rope(x: jax.Array, theta: float) -> jax.Array:
    """Rotary position embedding over [B, T, H, D] (D even)."""
    b, t, h, d = x.shape
    half = d // 2
    freqs = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    ang = jnp.arange(t, dtype=jnp.float32)[:, None] * freqs[None, :]  # [T, half]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    cos, sin = cos[None, :, None, :], sin[None, :, None, :]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1).astype(x.dtype)


class Attention(nn.Module):
    cfg: TransformerConfig
    attn_fn: Optional[Callable] = None  # (q, k, v) -> out; default fused causal

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        head_dim = cfg.dim // cfg.n_heads
        dense = partial(LoRADense, rank=cfg.lora_rank, alpha=cfg.lora_alpha, dtype=cfg.dtype)
        q = dense(cfg.n_heads * head_dim, name="wq")(x)
        k = dense(cfg.n_kv_heads * head_dim, name="wk")(x)
        v = dense(cfg.n_kv_heads * head_dim, name="wv")(x)
        b, t = x.shape[:2]
        q = rope(q.reshape(b, t, cfg.n_heads, head_dim), cfg.rope_theta)
        k = rope(k.reshape(b, t, cfg.n_kv_heads, head_dim), cfg.rope_theta)
        v = v.reshape(b, t, cfg.n_kv_heads, head_dim)
        # GQA: repeat K/V heads to match Q heads
        rep = cfg.n_heads // cfg.n_kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
        attend = self.attn_fn or causal_attention
        out = attend(q, k, v).reshape(b, t, cfg.dim)
        return dense(cfg.dim, name="wo")(out)


class MLP(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        rank = cfg.lora_rank if cfg.lora_mlp else 0
        dense = partial(LoRADense, rank=rank, alpha=cfg.lora_alpha, dtype=cfg.dtype)
        gate = dense(cfg.ffn_hidden, name="w1")(x)
        up = dense(cfg.ffn_hidden, name="w3")(x)
        return dense(cfg.dim, name="w2")(nn.silu(gate) * up)


class Block(nn.Module):
    cfg: TransformerConfig
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x):
        x = x + Attention(self.cfg, self.attn_fn, name="attn")(
            RMSNorm(self.cfg.dtype, name="attn_norm")(x)
        )
        x = x + MLP(self.cfg, name="mlp")(RMSNorm(self.cfg.dtype, name="mlp_norm")(x))
        return x


class CausalLM(nn.Module):
    cfg: TransformerConfig
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, tokens):  # [B, T] int32 -> [B, T, vocab] f32 logits
        cfg = self.cfg
        emb = self.param(
            "embed", nn.initializers.normal(0.02), (cfg.vocab_size, cfg.dim)
        )
        x = emb[tokens].astype(cfg.dtype)
        for i in range(cfg.n_layers):
            x = Block(cfg, self.attn_fn, name=f"layer_{i}")(x)
        x = RMSNorm(cfg.dtype, name="final_norm")(x)
        logits = jnp.dot(x, emb.T.astype(cfg.dtype))  # tied embeddings
        return logits.astype(jnp.float32)


def resolve_attention(
    attn: str,
    mesh: Any = None,
    axis_name: str = "model",
    block: int = 128,
) -> Optional[Callable]:
    """Map an attention backend name to an ``(q, k, v) -> out`` callable."""
    if attn == "dense":
        return None  # Attention falls back to the fused causal path
    if attn == "flash":
        from p2pfl_tpu.ops.flash_attention import flash_attention

        # Pallas runs natively on TPU; anywhere else use interpret mode
        interpret = jax.default_backend() != "tpu"
        return partial(
            flash_attention, causal=True, block_q=block, block_k=block, interpret=interpret
        )
    if attn == "ring":
        if mesh is None:
            raise ValueError("attn='ring' needs a mesh (sequence is sharded over it)")
        from p2pfl_tpu.ops.attention import ring_attention

        return partial(ring_attention, mesh=mesh, axis_name=axis_name)
    raise ValueError(f"unknown attention backend {attn!r} (dense|flash|ring)")


def tiny_transformer(
    seq_len: int = 128,
    seed: int = 0,
    cfg: Optional[TransformerConfig] = None,
    attn_fn: Optional[Callable] = None,
    attn: str = "dense",
    mesh: Any = None,
) -> FlaxModel:
    """A small LoRA-ready causal LM bound to concrete params.

    ``attn`` selects the attention backend (``"dense" | "flash" | "ring"``);
    ``attn_fn`` overrides it with an explicit callable.
    """
    cfg = cfg or TransformerConfig()
    if attn_fn is None:
        if seq_len <= 128:
            block = seq_len  # block == T always satisfies the TPU tiling rule
        else:
            # blocks must divide T and (on TPU Mosaic) be a multiple of 8
            block = next(
                (b for b in range(128, 7, -1) if seq_len % b == 0 and b % 8 == 0), None
            )
            if block is None and attn == "flash":
                raise ValueError(
                    f"attn='flash' needs seq_len with a divisor <=128 that is a "
                    f"multiple of 8; seq_len={seq_len} has none (use attn='dense')"
                )
        attn_fn = resolve_attention(attn, mesh=mesh, block=block)
    module = CausalLM(cfg, attn_fn)
    rng = jax.random.PRNGKey(seed)
    dummy = jnp.zeros((1, seq_len), dtype=jnp.int32)
    variables = module.init(rng, dummy)
    model = FlaxModel(module, variables["params"], (seq_len,), cfg.vocab_size)
    model.extra["config"] = cfg
    return model
