"""Per-node mutable learning state.

Reference: ``p2pfl/node_state.py:26-115``. The reference synchronizes with
four ``threading.Lock`` objects used as latches (created acquired, released
to signal); here those are real :class:`threading.Event` objects per
SURVEY §5's recommendation — same semantics, no lock-as-event hazards.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional


class NodeState:
    def __init__(self, addr: str, simulation: bool = False) -> None:
        self.addr = addr
        self.simulation = simulation
        self.status = "Idle"
        self.experiment_name: Optional[str] = None
        #: fleet-wide experiment identity (minted by the start_learning
        #: initiator, carried in its broadcast and stamped as the wire's
        #: optional "xp" header). None on a joiner until it adopts the id
        #: from its bootstrap global, and on fleets with pre-xp initiators.
        self.experiment_xid: Optional[str] = None
        self.round: Optional[int] = None
        self.total_rounds: Optional[int] = None
        self.simulation = simulation

        self.learner: Optional[Any] = None

        # addr -> list of contributors that addr has already aggregated
        self.models_aggregated: Dict[str, List[str]] = {}
        # addr -> last round that addr reported finishing (-1 = model init'd)
        self.nei_status: Dict[str, int] = {}

        self.train_set: List[str] = []
        # mid-round train-set repair (Node._on_peer_evicted): members
        # evicted from the overlay. train_set itself stays the FULL elected
        # set — the aggregator must keep accepting an evicted member's
        # contributions that reached peers (its acceptance interval is
        # [train_set - removed, train_set]); shrinking the list here would
        # turn those into "foreign contributors" and make every aggregate
        # naming the member unacceptable for the rest of the experiment.
        # Gossip targeting subtracts this set instead. Guarded by
        # train_set_lock; writers REPLACE the set, never mutate in place.
        self.train_set_evicted: set = set()
        self.train_set_votes: Dict[str, Dict[str, int]] = {}

        # secure aggregation (learning/secagg.py): this node's DH private key
        # for the current experiment + peers' announced (public key, sample
        # count) pairs. Keys are latched: the FIRST announcement per peer
        # per experiment wins (commands/control.py SecAggPubCommand).
        self.secagg_priv: Optional[int] = None
        self.secagg_pubs: Dict[str, tuple] = {}
        # the sample count THIS node announced with its key — masking must
        # use exactly this weight or pair masks stop cancelling
        self.secagg_samples: Optional[int] = None
        # dropout recovery: (round, dropped_addr, survivor_addr) -> pair
        # seed the survivor re-disclosed via secagg_recover
        self.secagg_disclosed: Dict[tuple, int] = {}
        # (round, dropped_addr) pairs THIS node already disclosed its seed
        # for (proactively or answering secagg_need) — disclose once
        self.secagg_disclosure_sent: set = set()
        # Bonawitz double masking (learning/secagg.py self_mask):
        # round -> this node's own self-mask seed b_i^r
        self.secagg_self_seed: Dict[int, int] = {}
        # (round, owner) -> this node's decrypted Shamir share (x, y) of
        # owner's b^r (from owner's secagg_share broadcast)
        self.secagg_shares_held: Dict[tuple, tuple] = {}
        # (round, owner, revealer) -> revealed (x, y); x == 0 means the
        # owner's DIRECT seed disclosure (y is b^r itself)
        self.secagg_share_reveals: Dict[tuple, tuple] = {}
        # ahead-of-round share reveals (round > st.round at arrival): the
        # holder list for that round hasn't latched, so standing/index
        # can't be judged yet — promote_early_reveals (commands/control.py)
        # re-validates these once the set latches
        self.secagg_early_reveals: Dict[tuple, tuple] = {}
        # (round, owner) reveals THIS node already broadcast — send once
        self.secagg_reveal_sent: set = set()
        # (round, addr) members treated as DROPPED this round (own missing
        # set, a peer's secagg_need, or an observed pair-seed disclosure):
        # the Bonawitz invariant — never help reconstruct b^r for a node
        # whose pair seeds round r may have been disclosed
        self.secagg_round_dropped: set = set()

        # async federation (federation/workflow.py): peers that announced
        # their local update budget is spent (async_done, TTL-flooded) —
        # releases aggregators' drain waits. Union-merged under
        # status_merge_lock like every control-plane lattice.
        self.async_done_peers: set = set()

        # monotonically counts experiments entered; lets harnesses distinguish
        # "never started" from "finished" (both have round None)
        self.experiment_epoch = 0

        # stall-watchdog instrumentation (management/watchdog.py): stamped
        # by the workflow loop on every stage transition
        self.last_transition: Optional[float] = None
        self.current_stage: str = ""

        # synchronization (reference: four lock-latches, node_state.py:77-81)
        # train_set has two writers on different threads: the vote tally
        # (learning thread) and mid-round repair (heartbeater eviction
        # listener, Node._on_peer_evicted) — both must hold this lock for
        # their read-filter-write, or one silently overwrites the other.
        # Readers take the list reference unlocked (writers always REPLACE
        # the list, never mutate it in place).
        self.train_set_lock = threading.Lock()
        # serializes the control handlers' monotone read-merge-writes on
        # models_aggregated / nei_status: handlers run on whatever thread
        # delivers the message (sender gossip workers, duplicate-delivery
        # timers), and two unlocked merges for the same source could still
        # clobber each other — the exact stale-overwrite the monotone
        # merges exist to prevent, surviving as a race window
        self.status_merge_lock = threading.Lock()
        self.train_set_votes_lock = threading.Lock()
        self.start_thread_lock = threading.Lock()
        self.votes_ready_event = threading.Event()
        self.model_initialized_event = threading.Event()

    def set_experiment(self, exp_name: str, total_rounds: int, xid: Optional[str] = None) -> None:
        """Enter learning mode (reference ``node_state.py:83``)."""
        self.status = "Learning"
        self.experiment_name = exp_name
        self.experiment_xid = xid
        self.total_rounds = total_rounds
        self.round = 0
        self.experiment_epoch += 1
        # a late async_done (slow peer's broadcast, TTL-relayed duplicate)
        # landing AFTER the previous experiment's clear() must not mark
        # that peer done for THIS experiment — the drain would skip the
        # window that merges its tail updates
        with self.status_merge_lock:
            self.async_done_peers = set()

    def increase_round(self) -> None:
        """Advance the round; clears per-round caches (``node_state.py:97``)."""
        if self.round is None:
            raise ValueError("round not initialized")
        # ORDER MATTERS: bump the round BEFORE replacing models_aggregated.
        # ModelsAggregatedCommand captures the dict and then checks the
        # round — seeing the new dict must imply the new round is already
        # visible, or a raced stale entry leaks into the next round's view.
        self.round += 1
        self.models_aggregated = {}

    def clear(self) -> None:
        """Back to idle (``node_state.py:110``)."""
        self.status = "Idle"
        self.experiment_name = None
        self.experiment_xid = None
        self.round = None
        self.total_rounds = None
        self.models_aggregated = {}
        self.nei_status = {}
        with self.train_set_lock:
            self.train_set = []
            self.train_set_evicted = set()
        self.train_set_votes = {}
        self.secagg_priv = None
        self.secagg_pubs = {}
        self.secagg_samples = None
        self.secagg_disclosed = {}
        self.secagg_disclosure_sent = set()
        self.secagg_self_seed = {}
        self.secagg_shares_held = {}
        self.secagg_share_reveals = {}
        self.secagg_early_reveals = {}
        self.secagg_reveal_sent = set()
        self.secagg_round_dropped = set()
        with self.status_merge_lock:
            self.async_done_peers = set()
        self.votes_ready_event.clear()
        self.model_initialized_event.clear()
