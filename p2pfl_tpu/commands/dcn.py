"""DCN rendezvous verbs — the control-plane half of the DCN weights plane.

Six thin handlers (``dcn_offer``/``dcn_accept``/``dcn_nack``/``dcn_ready``/
``dcn_done``/``dcn_abort``) that parse one JSON metadata argument and hand
it to the process-global :class:`~p2pfl_tpu.communication.dcn.DcnPlane`.
These are ordinary byte-plane control messages (direct, ``ttl=1``) — they
carry rendezvous METADATA only, never weights; the model payload itself
crosses as device arrays over the XLA collective the plane co-dispatches.
Unknown or stale transfer ids are ignored by the plane (rendezvous verbs
can outlive the transfer they describe — a late nack/abort for an already
finished transfer is normal, not an error).
"""

from __future__ import annotations

import json
from typing import TYPE_CHECKING

from p2pfl_tpu.commands.command import Command
from p2pfl_tpu.management.logger import logger

if TYPE_CHECKING:
    from p2pfl_tpu.node import Node


class _DcnVerbCommand(Command):
    """Shared plumbing: parse ``args[0]`` as JSON, dispatch to the plane."""

    #: name of the DcnPlane handler method, set by subclasses
    _handler = ""

    def __init__(self, node: "Node") -> None:
        self._node = node

    def execute(self, source: str, round: int, *args, **kwargs) -> None:  # noqa: A002
        if not args:
            logger.error(self._node.addr, f"Malformed {self.get_name()} from {source}: no metadata")
            return
        try:
            meta = json.loads(args[0])
        except (ValueError, TypeError):
            logger.error(self._node.addr, f"Malformed {self.get_name()} from {source}: bad JSON")
            return
        if not isinstance(meta, dict) or "tid" not in meta:
            logger.error(self._node.addr, f"Malformed {self.get_name()} from {source}: no tid")
            return
        from p2pfl_tpu.communication.dcn import DcnPlane

        getattr(DcnPlane.instance(), self._handler)(self._node, source, meta)


class DcnOfferCommand(_DcnVerbCommand):
    """Sender proposes a transfer: leaf/codec metadata + its mesh ids."""

    _handler = "on_offer"

    @staticmethod
    def get_name() -> str:
        return "dcn_offer"


class DcnAcceptCommand(_DcnVerbCommand):
    """Receiver agreed: its mesh ids + the pair-monotone sequence number."""

    _handler = "on_accept"

    @staticmethod
    def get_name() -> str:
        return "dcn_accept"


class DcnNackCommand(_DcnVerbCommand):
    """Receiver refused the offer — sender falls back to the byte path."""

    _handler = "on_nack"

    @staticmethod
    def get_name() -> str:
        return "dcn_nack"


class DcnReadyCommand(_DcnVerbCommand):
    """Peer holds its dispatch lock and is about to enter the collective."""

    _handler = "on_ready"

    @staticmethod
    def get_name() -> str:
        return "dcn_ready"


class DcnDoneCommand(_DcnVerbCommand):
    """Receiver finished decode + delivery; ``ok`` is the final verdict."""

    _handler = "on_done"

    @staticmethod
    def get_name() -> str:
        return "dcn_done"


class DcnAbortCommand(_DcnVerbCommand):
    """Either side tore the rendezvous down (timeout, teardown, error)."""

    _handler = "on_abort"

    @staticmethod
    def get_name() -> str:
        return "dcn_abort"


DCN_COMMANDS = (
    DcnOfferCommand,
    DcnAcceptCommand,
    DcnNackCommand,
    DcnReadyCommand,
    DcnDoneCommand,
    DcnAbortCommand,
)
