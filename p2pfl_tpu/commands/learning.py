"""Learning-lifecycle commands: start/stop, init weights, model ingestion.

Reference files: ``start_learning_command.py``, ``stop_learning_command.py``,
``init_model_command.py``, ``add_model_command.py``. These are the only
commands that touch the node facade (thread spawn / teardown) or carry
weight payloads.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from p2pfl_tpu.commands.command import Command
from p2pfl_tpu.exceptions import AnchorMismatchError, DecodingParamsError, ModelNotMatchingError
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.management.logger import logger

if TYPE_CHECKING:
    from p2pfl_tpu.node import Node


class StartLearningCommand(Command):
    """Spawn the learning thread with (rounds, epochs) (reference :134-155)."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "start_learning"

    def execute(self, source: str, round: int, *args, **kwargs) -> None:  # noqa: A002
        rounds = int(args[0]) if args else 1
        epochs = int(args[1]) if len(args) > 1 else 1
        # optional third arg: the initiator's experiment identity (old
        # initiators send two args — everything downstream treats a None
        # id as "filter by heuristics instead")
        self._node._pending_xid = args[2] if len(args) > 2 else None
        self._node._start_learning_thread(rounds, epochs)


class StopLearningCommand(Command):
    """Interrupt the learner, clear aggregator + state, release latches."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "stop_learning"

    def execute(self, source: str, round: int, *args, **kwargs) -> None:  # noqa: A002
        self._node._stop_learning()


class InitModelCommand(Command):
    """Initial weights payload: store → signal → re-announce.

    The update is stashed on the node (``pending_init_update``) and applied by
    the stage after its latch fires, which removes the reference's race
    between learner construction and early weight arrival
    (``init_model_command.py:30-117``). Malformed payloads stop the node, as
    in the reference (:106-117).
    """

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "init_model"

    def execute(self, source: str, round: int, *args, update: ModelUpdate = None, **kwargs) -> None:  # noqa: A002
        node = self._node
        state = node.state
        if not node.learning_active() or state.round is None:
            # no experiment running on this node: a late init_model (e.g.
            # delivered after StartLearningStage's graceful timeout abort)
            # must not latch the initialized event, or the NEXT experiment
            # would train from the aborted experiment's init and discard
            # its real one. The round check closes the teardown window the
            # thread-liveness check alone leaves open: state.clear() runs
            # WHILE the learning thread is still unwinding (the graceful
            # abort clears before the workflow loop returns, stop_learning
            # clears on the command thread mid-stage), and a straggler
            # latching the event after that clear() would poison the next
            # experiment, whose set_experiment cannot re-clear the event
            # (the initiator legitimately pre-sets it before its thread
            # starts). An experiment that IS waiting for init always has
            # round == 0 (set_experiment runs at stage entry, before the
            # wait). But an init_model racing AHEAD of this node's
            # start_learning (weights plane vs TTL-flooded control
            # broadcast) cannot simply be dropped either — the initiator's
            # push loop exits once its status view stops changing, so a
            # redelivery may never come. Stash it unlatched;
            # StartLearningStage consumes the stash iff the experiment
            # starts within Settings.EARLY_INIT_TTL.
            node.stash_early_init(update)
            logger.debug(
                state.addr,
                f"init_model from {source} stashed — no experiment running yet",
            )
            return
        if state.model_initialized_event.is_set():
            logger.debug(state.addr, f"init_model from {source} ignored — already initialized")
            return
        try:
            if update.params is None:
                update = node.learner.materialize(update)
        except (DecodingParamsError, ModelNotMatchingError) as exc:
            logger.error(state.addr, f"init_model decode failed: {exc} — stopping node")
            node.stop_async()
            return
        node.pending_init_update = update
        state.model_initialized_event.set()
        node.protocol.broadcast(node.protocol.build_msg(ModelInitializedName))


class AddModelCommand(Command):
    """Model/partial-aggregation ingestion → aggregator (reference :26-104)."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "add_model"

    def execute(self, source: str, round: int, *args, update: ModelUpdate = None, **kwargs) -> None:  # noqa: A002
        node = self._node
        state = node.state
        if not state.model_initialized_event.is_set():
            logger.debug(state.addr, f"add_model from {source} before init — ignored")
            return
        if update is not None and update.contributors:
            from p2pfl_tpu.learning.secagg import CLEAN_MARKER

            if CLEAN_MARKER in update.contributors:
                # Bonawitz double masking: the diffuser marked this as a
                # FINALIZED (self-mask-free) aggregate — strip the pseudo-
                # contributor before any coverage comparison and remember
                # the cleanliness for GossipModelStage._secagg_finalize
                update.contributors = [c for c in update.contributors if c != CLEAN_MARKER]
                update.secagg_clean = True
        if state.round is not None and round < state.round:
            # stale payload from a peer still finishing an older round —
            # most often the previous round's aggregate diffused to a node
            # whose models_ready hadn't reached the sender yet. Because the
            # train set is reused across rounds (round-0 vote quirk), its
            # contributor set matches OUR window exactly and the aggregator
            # would accept it as this round's full aggregate, silently
            # discarding the round's training. The reference shares this
            # race (its add_model has no round check either); gating here
            # is a documented divergence that closes it.
            logger.debug(
                state.addr,
                f"add_model from {source} for stale round {round} (at {state.round}) — ignored",
            )
            return
        if state.round is not None and round > state.round:
            # future-round payload from a peer that finished ahead of us:
            # accept only a FULL-coverage aggregate (the catch-up/liveness
            # case — the behind node adopts the consensus and moves on). A
            # future-round individual or partial contribution must not fold
            # into THIS round's window: the train set is reused across
            # rounds, so the aggregator would accept it as a disjoint
            # round-r contributor and mix two rounds' models. Under
            # VOTE_EVERY_ROUND a future aggregate from a re-voted DIFFERENT
            # train set is rejected here too — no loss: the aggregator's
            # own contributor checks (waiting mode requires an exact
            # train-set match) would reject it anyway, and the behind node
            # recovers via its normal timeout path.
            # same acceptance interval as the aggregator's waiting mode:
            # anything from the survivors' partial up to the full elected
            # set counts as "full" after mid-round repair (the sender's
            # eviction view may differ from ours)
            full = set(state.train_set)
            survivors = full - state.train_set_evicted
            if not survivors or not (survivors <= set(update.contributors) <= full):
                logger.debug(
                    state.addr,
                    f"add_model from {source} for future round {round} (at "
                    f"{state.round}) is not a full aggregate — ignored",
                )
                return
        try:
            if update.params is None:
                update = node.learner.materialize(update)
            # source = the delivering peer, for Byzantine screen
            # attribution (gossip relays other nodes' models verbatim)
            covered = node.aggregator.add_model(update, source=source)
        except AnchorMismatchError as exc:
            # a delta-coded payload against an anchor we don't hold (we are
            # a round behind/ahead of the sender): skip it and wait for one
            # we can reconstruct — NOT fatal, unlike a corrupt payload
            logger.info(state.addr, f"add_model from {source} skipped: {exc}")
            return
        except (DecodingParamsError, ModelNotMatchingError) as exc:
            logger.error(state.addr, f"add_model decode failed: {exc} — stopping node")
            node.stop_async()
            return
        if covered:
            node.protocol.broadcast(
                node.protocol.build_msg("models_aggregated", covered, round=state.round or 0)
            )


ModelInitializedName = "model_initialized"
