"""Async-federation commands: update/model push, done/leave/pull verbs.

The async control plane's wire verbs (``federation/workflow.py``):

- ``async_update`` (weights plane) — a node's training update, or a
  regional's merged aggregate, pushed to the next aggregation tier up;
- ``async_model`` (weights plane) — a freshly minted global model pushed
  down the tiers (also the reply to an ``async_pull``);
- ``async_done`` (control plane, TTL-flooded) — a node announcing its
  local update budget is spent, releasing aggregators' drain waits;
- ``async_join`` (control plane, TTL-flooded) — a joiner announcing it
  is ENTERING the running experiment: members fold it into the topology
  on this announcement (mere overlay presence is not membership — a
  monitor connecting mid-run must not be elected aggregator);
- ``async_pull`` (control plane, direct) — a joiner asking its nearest
  aggregator for the current global (the elastic-membership bootstrap);
- ``async_leave`` (control plane, TTL-flooded) — a member announcing a
  GRACEFUL departure: receivers mark it done AND dead, re-deriving the
  topology around the hole immediately instead of waiting a heartbeat
  eviction window.

Both weights handlers drop (never stop the node) on malformed payloads:
an async fleet is long-running by design, and one garbage frame from a
flaky peer must not take an *aggregator* down with it — the sync plane's
stop-on-decode-failure matches its initiator-seeded trust model, not this
one. Drops are loud (``async_decode_fail`` metric + error log). The
``async_pull``/``async_view`` control verbs hold the same contract
(``async_ctl_malformed``): a pull or view carrying a weights payload, a
view missing its member lists, or any frame whose handling raises is
dropped and counted, never allowed to feed the topology derivation or
unwind the serving thread.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from p2pfl_tpu.commands.command import Command
from p2pfl_tpu.exceptions import DecodingParamsError, ModelNotMatchingError
from p2pfl_tpu.federation.staleness import xp_mismatch
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.management.logger import logger

if TYPE_CHECKING:
    from p2pfl_tpu.node import Node


def materialize_or_drop(node: "Node", update: ModelUpdate, cmd: str):
    """Decode a wire payload, or None (counted + logged) when malformed."""
    try:
        if update.params is None:
            update = node.learner.materialize(update)
        return update
    except (DecodingParamsError, ModelNotMatchingError) as exc:
        logger.log_comm_metric(node.addr, "async_decode_fail")
        logger.error(node.addr, f"{cmd} decode failed: {exc} — dropped")
        return None


def drain_async_stash(node: "Node", ctx) -> None:
    """Feed every stashed early async_update into the context — the ONE
    drain routine (the workflow's post-install drain and the command
    side's race-close both call it; ``take_async_stash`` pops atomically,
    so each entry is processed exactly once whichever side wins). Entries
    carry their delivering peer so the Byzantine screen attributes a
    stashed poison exactly like a direct delivery."""
    for early, src in node.take_async_stash():
        early = materialize_or_drop(node, early, "async_update(stash)")
        if early is not None:
            ctx.execute_actions(ctx.handle_update(early, source=src))


class AsyncUpdateCommand(Command):
    """A contribution arriving at an aggregation tier → buffer offer."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "async_update"

    def execute(self, source: str, round: int, *args, update: ModelUpdate = None, **kwargs) -> None:  # noqa: A002
        node = self._node
        ctx = node.async_ctx
        if ctx is None:
            if node.learning_active():
                # a fast edge's update beat this aggregator's context
                # creation (it is still in init gossip / topology
                # derivation): stash for the workflow to drain — the async
                # twin of the early-init stash
                node.stash_async_update(update, source)
                logger.log_comm_metric(node.addr, "async_update_stashed")
                # close the install race: if the context landed between our
                # None-read and the stash append, the workflow's one-shot
                # drain may already have run — drain again ourselves
                ctx = node.async_ctx
                if ctx is not None and ctx.accepting:
                    drain_async_stash(node, ctx)
                return
            logger.log_comm_metric(node.addr, "async_update_dropped")
            logger.debug(node.addr, f"async_update from {source} with no async context — dropped")
            return
        if not ctx.accepting:
            logger.log_comm_metric(node.addr, "async_update_dropped")
            return
        update = materialize_or_drop(node, update, "async_update")
        if update is None:
            return
        # handlers run on whatever thread delivered the message; the
        # context computes under its locks and returns the sends, which
        # run here OUTSIDE every lock (deadlock contract — workflow docs).
        # source rides along for the Byzantine screen's attribution: a
        # poisoned payload indicts its DELIVERER, not the (attacker-
        # controlled) origin named in its version triple
        ctx.execute_actions(ctx.handle_update(update, source=source))


class AsyncModelCommand(Command):
    """A fresh global model pushed down a tier → adopt + forward."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "async_model"

    def execute(self, source: str, round: int, *args, update: ModelUpdate = None, **kwargs) -> None:  # noqa: A002
        node = self._node
        ctx = node.async_ctx
        if ctx is None or not ctx.accepting:
            logger.log_comm_metric(node.addr, "async_model_dropped")
            return
        update = materialize_or_drop(node, update, "async_model")
        if update is None:
            return
        ctx.execute_actions(ctx.handle_model(update, source))


class AsyncDoneCommand(Command):
    """Peer spent its local update budget (TTL-flooded announcement)."""

    def __init__(self, state) -> None:  # NodeState
        self._state = state

    @staticmethod
    def get_name() -> str:
        return "async_done"

    def execute(self, source: str, round: int, *args, **kwargs) -> None:  # noqa: A002
        st = self._state
        # experiment-identity gate: a slow peer's done broadcast from the
        # PREVIOUS experiment (TTL-relayed duplicate landing after our
        # set_experiment) must not pre-mark it done for THIS one — the
        # drain would skip the window that merges its tail. Frames
        # without the header fall back to the set-reset at experiment
        # boundaries alone.
        if xp_mismatch(st.addr, kwargs.get("xp"), st.experiment_xid):
            return
        # monotone set-union under the same merge lock as the other
        # control-plane lattices; cleared at experiment boundaries
        with st.status_merge_lock:
            st.async_done_peers.add(source)


class AsyncJoinCommand(Command):
    """A joiner announced itself: membership grows, topology re-derives."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "async_join"

    def execute(self, source: str, round: int, *args, **kwargs) -> None:  # noqa: A002
        node = self._node
        ctx = node.async_ctx
        if ctx is None or not ctx.accepting:
            return
        if xp_mismatch(node.addr, kwargs.get("xp"), node.state.experiment_xid):
            return
        ctx.execute_actions(ctx.add_member(source))
        if ctx.accepting and ctx.take_stash_dirty():
            drain_async_stash(node, ctx)


class AsyncPullCommand(Command):
    """A joiner's bootstrap request: push it the current global."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "async_pull"

    def execute(self, source: str, round: int, *args, **kwargs) -> None:  # noqa: A002
        node = self._node
        if kwargs.get("update") is not None:
            # a weights frame hijacking a control verb (fuzzed/garbage
            # wire input): drop loudly — parity with async_update's
            # decode-or-drop, a long-running fleet must absorb it
            logger.log_comm_metric(node.addr, "async_ctl_malformed")
            logger.error(
                node.addr,
                f"async_pull from {source} carried a weights payload — dropped",
            )
            return
        try:
            self._serve(source)
        except Exception as exc:  # noqa: BLE001 — one garbage frame must not kill a serving node
            logger.log_comm_metric(node.addr, "async_ctl_malformed")
            logger.error(node.addr, f"async_pull from {source} failed: {exc!r} — dropped")

    def _serve(self, source: str) -> None:
        node = self._node
        ctx = node.async_ctx
        if ctx is not None and ctx.accepting:
            logger.log_comm_metric(node.addr, "async_pull_served")
            # ship our (members, dead) view alongside the global: the
            # puller (a joiner) derives its topology from a live overlay
            # view that lacks the dead members everyone else keeps as
            # cluster holes — without the merge its chunking would
            # diverge from the fleet's for the rest of the run
            members, dead = ctx.view_snapshot()
            node.protocol.send(
                source,
                node.protocol.build_msg("async_view", [";".join(members), ";".join(dead)]),
                create_connection=True,
            )
            ctx.execute_actions(ctx.bootstrap_reply(source))
            return
        # the workflow already exited: serve the finished experiment's
        # canonical result (a peer's EXIT pull — its every inbound push
        # targeted a corpse — may arrive after our teardown; exit timing
        # across the fleet is jittered by per-node eviction clocks)
        last = node._last_async_global
        if last is not None:
            params, version, xid = last
            upd = ModelUpdate(params, [node.addr], 1)
            upd.version = (node.addr, version, version)
            upd.xp = xid
            env = node.protocol.build_weights("async_model", version, upd)
            node.protocol.send(source, env, create_connection=True)
            logger.log_comm_metric(node.addr, "async_pull_served")
            return
        logger.log_comm_metric(node.addr, "async_pull_dropped")


class AsyncViewCommand(Command):
    """A peer's (members, dead) membership view — merged monotonically."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "async_view"

    def execute(self, source: str, round: int, *args, **kwargs) -> None:  # noqa: A002
        node = self._node
        if kwargs.get("update") is not None or len(args) < 2:
            # missing member/dead lists, or a weights frame hijacking the
            # verb: a malformed view must not feed the topology derivation
            # (and must not kill the node) — drop loudly, parity with
            # async_update's decode-or-drop
            logger.log_comm_metric(node.addr, "async_ctl_malformed")
            logger.error(node.addr, f"malformed async_view from {source} — dropped")
            return
        ctx = node.async_ctx
        if ctx is None or not ctx.accepting:
            return
        if xp_mismatch(node.addr, kwargs.get("xp"), node.state.experiment_xid):
            return
        try:
            members = [m for m in str(args[0]).split(";") if m]
            dead = [d for d in str(args[1]).split(";") if d]
            ctx.execute_actions(ctx.merge_view(members, dead))
        except Exception as exc:  # noqa: BLE001 — one garbage frame must not kill a serving node
            logger.log_comm_metric(node.addr, "async_ctl_malformed")
            logger.error(node.addr, f"async_view from {source} failed: {exc!r} — dropped")
            return
        if ctx.accepting and ctx.take_stash_dirty():
            drain_async_stash(node, ctx)


class AsyncLeaveCommand(Command):
    """A member left gracefully: done + dead in one announcement."""

    def __init__(self, node: "Node") -> None:
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "async_leave"

    def execute(self, source: str, round: int, *args, **kwargs) -> None:  # noqa: A002
        node = self._node
        st = node.state
        if xp_mismatch(st.addr, kwargs.get("xp"), st.experiment_xid):
            return
        with st.status_merge_lock:
            st.async_done_peers.add(source)
        ctx = node.async_ctx
        if ctx is None or not ctx.accepting:
            return
        # same membership event as an eviction, minus the detection
        # latency (the leaver TOLD us); may promote this node / fire the
        # flush the leaver's contributions were part of
        ctx.execute_actions(ctx.mark_dead(source, reason="left"))
        if ctx.accepting and ctx.take_stash_dirty():
            drain_async_stash(node, ctx)
