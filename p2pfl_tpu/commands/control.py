"""Small control-plane commands: votes, round status, metrics.

Reference files: ``vote_train_set_command.py``, ``models_agregated_command.py``,
``models_ready_command.py``, ``metrics_command.py``, ``model_initialized_command.py``.
All mutate :class:`~p2pfl_tpu.node_state.NodeState` under its locks/events.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from p2pfl_tpu.commands.command import Command
from p2pfl_tpu.management.logger import logger

if TYPE_CHECKING:
    from p2pfl_tpu.node_state import NodeState


class ModelInitializedCommand(Command):
    """Peer announced its model is initialized → ``nei_status[source] = -1``.

    Monotone: a stale redelivery (TTL relay that outlived the dedup ring)
    must not regress a peer that already reported finishing a round back
    to "merely initialized" — peer status only ever moves forward.
    """

    def __init__(self, state: "NodeState") -> None:
        self._state = state

    @staticmethod
    def get_name() -> str:
        return "model_initialized"

    def execute(self, source: str, round: int, *args, **kwargs) -> None:  # noqa: A002
        # -1 is the floor of the status lattice: only record it for a peer
        # with no status yet (nei_status is reset at experiment boundaries).
        # Same merge lock as models_ready's max-merge: this handler and
        # that one race on whatever threads deliver the two announcements,
        # and the lattice contract is that every nei_status merge is
        # serialized, not just individually GIL-atomic.
        with self._state.status_merge_lock:
            self._state.nei_status.setdefault(source, -1)


class SecAggPubCommand(Command):
    """Peer announced its DH public key + sample count for secure aggregation.

    Args: ``[pub_hex, num_samples]``; flooded over the message gossip at
    experiment start (``learning/secagg.py`` — the sample counts set the
    pairwise mask scales). No round check — keys are per-experiment.
    """

    def __init__(self, state: "NodeState") -> None:
        self._state = state

    @staticmethod
    def get_name() -> str:
        return "secagg_pub"

    def execute(self, source: str, round: int, *args, **kwargs) -> None:  # noqa: A002
        if len(args) < 2:
            logger.error(self._state.addr, f"Malformed secagg_pub from {source}: need key + samples")
            return
        try:
            pub = int(args[0], 16)
            samples = int(args[1])
        except ValueError:
            logger.error(self._state.addr, f"Malformed secagg_pub from {source}: bad values")
            return
        from p2pfl_tpu.learning.secagg import valid_public_key

        if not valid_public_key(pub):
            # 0/1/p-1 make the pair's shared secret trivially computable —
            # an active attacker spoofing this message could strip the
            # victim's masks; never store a degenerate key
            logger.error(self._state.addr, f"Degenerate DH key from {source} — rejected")
            return
        if samples <= 0:
            logger.error(self._state.addr, f"Non-positive sample count from {source} — rejected")
            return
        held = self._state.secagg_pubs.get(source)
        if held is not None:
            # latch the FIRST key per (source, experiment): the gossip plane
            # is unauthenticated, so a later re-broadcast with a spoofed
            # source must not replace the key a victim's peers already use
            # (an attacker-controlled key would let them derive all of the
            # victim's pair seeds and strip its masks). Identical
            # re-deliveries are normal gossip redundancy.
            if held != (pub, samples):
                logger.error(
                    self._state.addr,
                    f"secagg_pub from {source} tried to replace an already-"
                    "latched key — rejected (possible spoofing)",
                )
            return
        self._state.secagg_pubs[source] = (pub, samples)


class SecAggRecoverCommand(Command):
    """A survivor re-disclosed its pair seed for a dropped train-set member.

    Args: ``[dropped_addr, seed_hex]``; the message's round field pins the
    round being recovered. Stored under (round, dropped, source) — the
    recovery routine in ``stages/learning_stages.py`` waits until every
    survivor's seed for every missing member is present, then subtracts
    the uncancelled mask sum (``learning/secagg.py:dropout_correction``).
    """

    def __init__(self, state: "NodeState") -> None:
        self._state = state

    @staticmethod
    def get_name() -> str:
        return "secagg_recover"

    def execute(self, source: str, round: int, *args, **kwargs) -> None:  # noqa: A002
        st = self._state
        if len(args) < 2:
            logger.error(st.addr, f"Malformed secagg_recover from {source}")
            return
        try:
            seed = int(args[1], 16)
        except ValueError:
            logger.error(st.addr, f"Malformed secagg_recover seed from {source}")
            return
        if not 0 <= seed < (1 << 256):
            # an out-of-range stored seed would make _leaf_mask's
            # to_bytes(32) raise mid-recovery and kill the experiment on
            # every survivor — one malformed message must not do that
            logger.error(st.addr, f"Out-of-range secagg_recover seed from {source} — rejected")
            return
        if st.round is not None and round != st.round:
            logger.debug(st.addr, f"secagg_recover from {source} for round {round} (at {st.round}) — ignored")
            return
        key = (round, args[0], source)
        # first disclosure wins, same latch rationale as secagg_pub
        st.secagg_disclosed.setdefault(key, seed)
        # Bonawitz invariant: once ANY pair-seed disclosure about a member
        # is observed this round, never help reconstruct its self seed
        st.secagg_round_dropped.add((round, args[0]))


class SecAggNeedCommand(Command):
    """A recovering peer announced which members' masks it cannot cancel.

    Args: ``[experiment_name, missing...]``. A train-set member answers by
    re-disclosing its pair seed for the named members — INCLUDING when its
    own coverage reached full (early finalizers would otherwise never
    disclose, leaving a peer with a smaller coverage view to burn its
    recovery timeout for nothing) and INCLUDING when it already disclosed
    for an earlier request (a lagging requester drops disclosures for
    rounds it has not reached yet; re-broadcasts are idempotent because
    receivers latch first-wins). Pair seeds are per-experiment, so
    answering for the previous round is safe; the experiment name in the
    request guards against latching a wrong-experiment seed.

    A request is a claim, not proof — the responder demands its OWN
    evidence before disclosing anything: it only answers for members that
    are no longer live on the overlay (heartbeat-evicted; a genuinely
    dropped node disappears within HEARTBEAT_TIMEOUT, long before any
    AGGREGATION_TIMEOUT fires). A forged secagg_need naming a live member
    is refused — the requester then no-ops its round (availability
    sacrificed, the live member's masks kept). Requests must also come
    from a train-set member. Under VOTE_EVERY_ROUND a re-voted train set
    can make cross-round requests unanswerable (``j not in train``) — the
    requester degrades to a no-op round.
    """

    def __init__(self, node) -> None:  # "Node"; untyped to avoid the import cycle
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "secagg_need"

    def execute(self, source: str, round: int, *args, **kwargs) -> None:  # noqa: A002
        from p2pfl_tpu.learning import secagg

        node = self._node
        st = node.state
        if st.secagg_priv is None or len(args) < 2 or st.round is None:
            return
        if round not in (st.round - 1, st.round):
            return
        exp = st.experiment_name or ""
        if args[0] != exp:
            logger.debug(st.addr, f"secagg_need from {source} for experiment {args[0]!r} — ignored")
            return
        train = set(st.train_set)
        if node.addr not in train or source not in train or len(train) <= 2:
            # non-members have no standing to request; in a 2-member train
            # set the only pair seed IS the full mask of the other member's
            # update — never disclose it
            return
        live = set(node.protocol.get_neighbors(only_direct=False))
        for j in args[1:]:
            if j in train and j != node.addr:
                # a need CLAIM alone poisons j's self-seed reconstruction
                # for this round (Bonawitz invariant: some peer may answer
                # it even if we refuse) — conservative, costs availability
                # only in the forged/split-brain case. NOT for ourselves:
                # while we are alive, honest peers refuse to disclose our
                # pair seeds regardless of claims (their liveness check),
                # so our own reveal stays safe — self-poisoning here would
                # let any split-brain need starve a round whose clean
                # aggregate exists (the rescue path depends on our reveal)
                st.secagg_round_dropped.add((round, j))
            if j == node.addr or j == source or j not in train or j not in st.secagg_pubs:
                continue
            if j in live:
                logger.warning(
                    st.addr,
                    f"secagg_need from {source} names {j}, which is still live "
                    "here — refusing to disclose its pair seed",
                )
                continue
            if (round, j, j) in st.secagg_share_reveals:
                # the invariant's OTHER direction: j already revealed its
                # SELF seed this round (it contributed somewhere, then
                # died) — disclosing its pair seeds too would publish both
                # seed types and unmask its captured update. Our aggregate
                # stays stuck instead (no-op round): privacy > availability.
                logger.warning(
                    st.addr,
                    f"secagg_need from {source} names {j}, whose self seed "
                    "is already revealed this round — refusing to disclose "
                    "its pair seeds (it contributed before dying)",
                )
                continue
            # Latch per (round, j, REQUESTER), not per (round, j): a lagging
            # requester may have dropped an earlier broadcast triggered by a
            # different peer's request (SecAggRecoverCommand ignores frames
            # whose round != st.round), so a global send-once latch would
            # leave it burning SECAGG_RECOVERY_TIMEOUT for nothing —
            # re-broadcasting the same seed is idempotent (receivers latch
            # first-wins). Keying by requester keeps amplification bounded:
            # a replaying attacker must be a train-set member (standing
            # check above), so the worst case is one broadcast per
            # (accepted round — st.round-1 and st.round both qualify —
            # × missing member × requesting member), fixed per experiment
            # round; replays beyond that are absorbed by the latch.
            if (round, j, source) in st.secagg_disclosure_sent:
                continue
            st.secagg_disclosure_sent.add((round, j, source))
            # the 2-tuple key still lets the proactive disclosure path
            # (learning_stages._secagg_finalize) skip its redundant send
            st.secagg_disclosure_sent.add((round, j))
            seed = secagg.dh_pair_seed(st.secagg_priv, st.secagg_pubs[j][0], exp)
            node.protocol.broadcast(
                node.protocol.build_msg("secagg_recover", [j, f"{seed:x}"], round=round)
            )


class SecAggShareCommand(Command):
    """A contributor distributed Shamir shares of its per-round self-mask
    seed (Bonawitz double masking, ``learning/secagg.py``).

    Args: ``[experiment, holder1, x1, ct1_hex, holder2, x2, ct2_hex, ...]``
    — one encrypted share per train-set peer, all in one broadcast; each
    holder decrypts only its own entry (stream-keyed by the DH pair seed
    and the round). Stored under (round, owner); first delivery wins.
    """

    def __init__(self, state: "NodeState") -> None:
        self._state = state

    @staticmethod
    def get_name() -> str:
        return "secagg_share"

    def execute(self, source: str, round: int, *args, **kwargs) -> None:  # noqa: A002
        from p2pfl_tpu.exceptions import SecAggError
        from p2pfl_tpu.learning import secagg

        st = self._state
        if st.secagg_priv is None or len(args) < 4 or (len(args) - 1) % 3 != 0:
            return
        if st.round is None or round not in (st.round - 1, st.round, st.round + 1):
            # same window discipline as secagg_reveal/_recover, plus one
            # round AHEAD (shares are distributed during TrainStage, where
            # a fast peer can be a round past us); without a window a noisy
            # peer could grow secagg_shares_held unboundedly with
            # fabricated round numbers
            return
        if (round, source) in st.secagg_shares_held:
            return  # gossip redundancy / replay: first delivery latched
        exp = st.experiment_name or ""
        if args[0] != exp:
            return
        if source not in st.secagg_pubs:
            logger.debug(st.addr, f"secagg_share from {source} before its key — ignored")
            return
        # share indices run 1..len(holders) over the SENDER's sorted holder
        # list, and this very message carries that whole list (one triple
        # per holder) — so the index bound comes from the MESSAGE, not from
        # our instantaneous train set. The old cap
        # max(2*len(st.train_set), 1024) mis-scored exactly the r±1 shares
        # this handler accepts: a share arriving for round r+1 BEFORE our
        # train set latches (len=0) fell back to the 1024 floor, so a
        # legitimate index from a >1025-member federation was dropped,
        # while junk indices up to 1024 sailed through a 5-member round.
        n_holders = (len(args) - 1) // 3
        for i in range(1, len(args), 3):
            holder, x_str, ct_hex = args[i], args[i + 1], args[i + 2]
            if holder != st.addr:
                continue
            try:
                x = int(x_str)
                ct = bytes.fromhex(ct_hex)
                key = secagg.dh_share_key(st.secagg_priv, st.secagg_pubs[source][0], exp)
                y = secagg.decrypt_share(ct, key, round, source, st.addr)
            except (ValueError, SecAggError):
                logger.error(st.addr, f"Malformed secagg_share from {source}")
                return
            if not 1 <= x <= n_holders or not 0 <= y < secagg.SHAMIR_PRIME:
                logger.error(st.addr, f"Out-of-range secagg_share from {source} — rejected")
                return
            st.secagg_shares_held[(round, source)] = (x, y)
            return


class SecAggRevealCommand(Command):
    """A share-reveal for a contributor's per-round self-mask seed.

    Args: ``[experiment, owner, x, y_hex]``. ``x == 0`` is the owner's
    DIRECT disclosure (y = b^r itself, only accepted from the owner);
    ``x >= 1`` is a holder revealing its Shamir share. Stored under
    (round, owner, revealer), first value wins — the finalize routine
    reconstructs once ``share_threshold`` distinct x's are present.
    """

    def __init__(self, state: "NodeState") -> None:
        self._state = state

    @staticmethod
    def get_name() -> str:
        return "secagg_reveal"

    def execute(self, source: str, round: int, *args, **kwargs) -> None:  # noqa: A002
        from p2pfl_tpu.learning import secagg

        st = self._state
        if len(args) < 4:
            logger.error(st.addr, f"Malformed secagg_reveal from {source}")
            return
        exp = st.experiment_name or ""
        if args[0] != exp:
            return
        owner = args[1]
        try:
            x = int(args[2])
            y = int(args[3], 16)
        except ValueError:
            logger.error(st.addr, f"Malformed secagg_reveal values from {source}")
            return
        if x < 0 or not 0 <= y < secagg.SHAMIR_PRIME:
            # no fixed upper cap on x: the exact assigned-index check below
            # is the real gate, and any constant cap (the old
            # ``max(2*len(train_set), 1024)``) silently dropped legitimate
            # early shares in federations larger than the constant while
            # the local train set hadn't latched yet
            logger.error(st.addr, f"Out-of-range secagg_reveal from {source} — rejected")
            return
        if x == 0 and (source != owner or y >= (1 << 256)):
            # direct seed disclosures only from the owner, and only
            # seed-sized (an oversized value would blow up _leaf_mask's
            # to_bytes(32) mid-finalize on every node)
            logger.error(st.addr, f"Invalid direct secagg_reveal from {source} — rejected")
            return
        if st.round is None or round not in (st.round - 1, st.round, st.round + 1):
            # one round AHEAD is legitimate: reveals are latched send-once,
            # and a fast peer already finalizing round r+1 broadcasts its
            # direct reveal while we are still resolving round r — dropping
            # it would permanently starve OUR r+1 finalize. st.round None
            # (idle) accepts nothing: fabricated round numbers would
            # otherwise grow secagg_share_reveals without bound (same
            # rationale as SecAggShareCommand's window)
            return
        if x >= 1:
            if round > st.round:
                # the share is for a round whose train set THIS node has
                # not latched yet — judging it against the current round's
                # set would reject legitimate early arrivals (and latch
                # nothing, since reveals are send-once). Stash it;
                # promote_early_reveals re-validates at consume time, once
                # the set for that round is the live one. Bounded: the
                # round window above pins ``round``, and one slot per
                # (round, owner, source) triple.
                if len(st.secagg_early_reveals) < 4 * max(len(st.train_set), 64) ** 2:
                    st.secagg_early_reveals.setdefault((round, owner, source), (x, y))
                return
            # Shamir-share reveals: only train-set members have standing,
            # and each holder's share index is DETERMINED by the sorted
            # holder list (TrainStage zips sorted(peers) with x = 1..n) —
            # enforcing it means a forger cannot inject a bogus point at an
            # unused x and poison every honest node's Lagrange
            # reconstruction into a permanent no-op round
            train = set(st.train_set)
            if source not in train or owner not in train or source == owner:
                logger.debug(st.addr, f"secagg_reveal share from {source} without standing — ignored")
                return
            holders = sorted(m for m in st.train_set if m != owner)
            if source not in holders or x != holders.index(source) + 1:
                logger.error(
                    st.addr,
                    f"secagg_reveal share from {source} with index {x} != its "
                    "assigned share index — rejected (forgery or stale train set)",
                )
                return
        st.secagg_share_reveals.setdefault((round, owner, source), (x, y))


def promote_early_reveals(state: "NodeState") -> None:
    """Re-validate stashed ahead-of-round share reveals against the now-
    latched train set and promote the legitimate ones.

    :class:`SecAggRevealCommand` cannot judge a share for round ``r+1``
    while the node is still in round ``r`` — the holder list (and with it
    every assigned share index) is only determined once ``r+1``'s train
    set latches. Early arrivals are stashed instead; the finalize routine
    (``stages/learning_stages.py``) calls this right before reading
    ``secagg_share_reveals``, so by then ``state.train_set`` IS the set the
    shares were cut against and the same standing + exact-index checks
    apply. Entries for rounds already passed are pruned.
    """
    st = state
    if st.round is None or not st.secagg_early_reveals:
        return
    train = set(st.train_set)
    for key in list(st.secagg_early_reveals):
        r, owner, source = key
        if r < st.round:
            del st.secagg_early_reveals[key]
            continue
        if r > st.round:
            continue  # still early — keep waiting
        x, y = st.secagg_early_reveals.pop(key)
        if source not in train or owner not in train or source == owner:
            logger.debug(st.addr, f"early secagg_reveal from {source} without standing — dropped")
            continue
        holders = sorted(m for m in st.train_set if m != owner)
        if source not in holders or x != holders.index(source) + 1:
            logger.error(
                st.addr,
                f"early secagg_reveal from {source} with index {x} != its "
                "assigned share index — rejected (forgery or stale train set)",
            )
            continue
        st.secagg_share_reveals.setdefault(key, (x, y))


class VoteTrainSetCommand(Command):
    """Train-set vote: flat ``[name, weight, name, weight, ...]`` pairs.

    Accepted for the current round or the next one (peers may be one round
    ahead), mirroring the reference's tolerance.
    """

    def __init__(self, state: "NodeState") -> None:
        self._state = state

    @staticmethod
    def get_name() -> str:
        return "vote_train_set"

    def execute(self, source: str, round: int, *args, **kwargs) -> None:  # noqa: A002
        st = self._state
        if st.round is not None and round not in (st.round, st.round + 1):
            logger.debug(st.addr, f"Vote from {source} for stale round {round} (at {st.round}) — ignored")
            return
        if len(args) % 2 != 0:
            logger.error(st.addr, f"Malformed vote from {source}: odd arg count")
            return
        votes = {args[i]: int(args[i + 1]) for i in range(0, len(args), 2)}
        with st.train_set_votes_lock:
            st.train_set_votes[source] = votes
        st.votes_ready_event.set()


class ModelsAggregatedCommand(Command):
    """Peer reports which contributors it has folded in this round.

    Under Bonawitz double masking this is also the earliest SAFE moment to
    reveal our own per-round self-mask seed: a peer's coverage naming us
    means our masked update is irreversibly folded into the round's
    aggregation, and waiting until our OWN finalize would make the slowest
    node's aggregation timeout starve every peer's seed resolution. The
    reveal stays gated on the at-most-one-of-{pair,self} invariant
    (``secagg_round_dropped``); while we are alive, peers refuse to
    disclose our pair seeds anyway (SecAggNeedCommand's liveness check).
    """

    def __init__(self, node) -> None:  # "Node"; untyped to avoid the import cycle
        self._node = node

    @staticmethod
    def get_name() -> str:
        return "models_aggregated"

    def execute(self, source: str, round: int, *args, **kwargs) -> None:  # noqa: A002
        node = self._node
        st = node.state
        # capture the coverage dict BEFORE the round check: increase_round()
        # bumps st.round and THEN replaces st.models_aggregated with a fresh
        # dict, so under this ordering every interleaving is safe — if we
        # captured the NEW dict the bump already happened and the round
        # check below rejects; if the swap lands after our check, we write
        # into the discarded OLD dict (harmless). Re-reading
        # st.models_aggregated at write time instead would let a round-N
        # entry race into round N+1's dict, where the union-merge would pin
        # it as a stale full-coverage superset into the next round.
        coverage = st.models_aggregated
        if st.round is None or round != st.round:
            return
        # UNION-merge, never overwrite: within a round a peer's real
        # coverage only grows (aggregator.add_model returns monotonically
        # growing contributor sets), but its broadcasts can be re-delivered
        # out of order — TTL relays and stalled-peer requeues keep old
        # copies alive long past the bounded dedup ring
        # (AMOUNT_LAST_MESSAGES_SAVED), and a stale copy re-accepted after
        # ring overflow used to OVERWRITE the newer view. That regression
        # re-opened the partial-gossip loop's convergence detector (status
        # kept changing, phantom "incomplete" candidates reappeared) and is
        # the root cause of the 8-node slow-peer round-0 wedge: one storm
        # of stale redeliveries could hold six nodes in TrainStage
        # indefinitely. Coverage views form a lattice; merges must be
        # monotone. Regression-tested in tests/test_chaos.py. The lock
        # makes the read-merge-write atomic — handlers run on whatever
        # thread delivered the message, and two unlocked merges for the
        # same source could clobber each other (losing a sender's FINAL
        # announcement, which its exited push loop never repeats).
        with st.status_merge_lock:
            prev = coverage.get(source)
            coverage[source] = sorted(set(prev) | set(args)) if prev else list(args)
        from p2pfl_tpu.settings import Settings

        if not (Settings.SECURE_AGGREGATION and Settings.SECAGG_DOUBLE_MASK):
            return
        if st.addr in args:
            from p2pfl_tpu.learning.secagg import maybe_reveal_self_seed

            maybe_reveal_self_seed(self._node, round)


class ModelsReadyCommand(Command):
    """Peer finished a round: ``nei_status[source] = round`` (round-1 tolerated)."""

    def __init__(self, state: "NodeState") -> None:
        self._state = state

    @staticmethod
    def get_name() -> str:
        return "models_ready"

    def execute(self, source: str, round: int, *args, **kwargs) -> None:  # noqa: A002
        st = self._state
        if st.round is not None and round in (st.round - 1, st.round):
            # max-merge: a stale redelivery of an older round's announcement
            # must not regress the peer's status (same lattice discipline —
            # and the same merge lock, the read-max-write must be atomic —
            # as models_aggregated: the round-0 wedge fix)
            with st.status_merge_lock:
                st.nei_status[source] = max(st.nei_status.get(source, -1), round)
        else:
            logger.debug(st.addr, f"models_ready from {source} for round {round} (at {st.round}) — ignored")


class MetricsCommand(Command):
    """Peer evaluation metrics → global metric store, keyed by the peer."""

    def __init__(self, state: "NodeState") -> None:
        self._state = state

    @staticmethod
    def get_name() -> str:
        return "metrics"

    def execute(self, source: str, round: int, *args, **kwargs) -> None:  # noqa: A002
        for i in range(0, len(args) - 1, 2):
            logger.log_metric(
                source,
                args[i],
                float(args[i + 1]),
                round=round,
                experiment=self._state.experiment_name,
            )
