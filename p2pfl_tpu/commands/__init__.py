"""Wire-protocol verbs (SURVEY §2.3).

Each command is a named handler registered into a transport's dispatch map;
``execute(source, round, *args)`` for control messages, or
``execute(source, round, update=ModelUpdate)`` for weight payloads. Same ten
verbs as the reference's ``p2pfl/commands/``.
"""

from p2pfl_tpu.commands.command import Command
from p2pfl_tpu.commands.control import (
    MetricsCommand,
    ModelInitializedCommand,
    ModelsAggregatedCommand,
    ModelsReadyCommand,
    SecAggPubCommand,
    SecAggNeedCommand,
    SecAggRecoverCommand,
    SecAggRevealCommand,
    SecAggShareCommand,
    VoteTrainSetCommand,
)
from p2pfl_tpu.commands.dcn import (
    DCN_COMMANDS,
    DcnAbortCommand,
    DcnAcceptCommand,
    DcnDoneCommand,
    DcnNackCommand,
    DcnOfferCommand,
    DcnReadyCommand,
)
from p2pfl_tpu.commands.federation import (
    AsyncDoneCommand,
    AsyncJoinCommand,
    AsyncLeaveCommand,
    AsyncModelCommand,
    AsyncPullCommand,
    AsyncUpdateCommand,
    AsyncViewCommand,
)
from p2pfl_tpu.commands.heartbeat import HeartbeatCommand
from p2pfl_tpu.commands.learning import (
    AddModelCommand,
    InitModelCommand,
    StartLearningCommand,
    StopLearningCommand,
)

__all__ = [
    "AsyncDoneCommand",
    "AsyncJoinCommand",
    "AsyncLeaveCommand",
    "AsyncModelCommand",
    "AsyncPullCommand",
    "AsyncUpdateCommand",
    "AsyncViewCommand",
    "Command",
    "DCN_COMMANDS",
    "DcnAbortCommand",
    "DcnAcceptCommand",
    "DcnDoneCommand",
    "DcnNackCommand",
    "DcnOfferCommand",
    "DcnReadyCommand",
    "HeartbeatCommand",
    "StartLearningCommand",
    "StopLearningCommand",
    "ModelInitializedCommand",
    "VoteTrainSetCommand",
    "ModelsAggregatedCommand",
    "ModelsReadyCommand",
    "MetricsCommand",
    "SecAggPubCommand",
    "SecAggNeedCommand",
    "SecAggRecoverCommand",
    "SecAggRevealCommand",
    "SecAggShareCommand",
    "InitModelCommand",
    "AddModelCommand",
]
