"""The user-facing Node (reference ``p2pfl/node.py:47-341``).

Wires a transport, an aggregator, a learner and the command registry; owns
the learning thread that drives the round FSM. ``Node(None, None)`` is valid
for pure-communication use, matching the reference's communication tests.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Optional, Type, Union

from p2pfl_tpu.commands import (
    AddModelCommand,
    AsyncDoneCommand,
    AsyncJoinCommand,
    AsyncLeaveCommand,
    AsyncModelCommand,
    AsyncPullCommand,
    AsyncUpdateCommand,
    AsyncViewCommand,
    HeartbeatCommand,
    InitModelCommand,
    MetricsCommand,
    ModelInitializedCommand,
    ModelsAggregatedCommand,
    ModelsReadyCommand,
    SecAggPubCommand,
    SecAggNeedCommand,
    SecAggRecoverCommand,
    SecAggRevealCommand,
    SecAggShareCommand,
    StartLearningCommand,
    StopLearningCommand,
    VoteTrainSetCommand,
)
from p2pfl_tpu.communication.memory import InMemoryProtocol
from p2pfl_tpu.communication.protocol import CommunicationProtocol
from p2pfl_tpu.exceptions import NodeRunningException, ZeroRoundsException
from p2pfl_tpu.learning.aggregators.fedavg import FedAvg
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.node_state import NodeState
from p2pfl_tpu.settings import Settings


#: weak registry of every constructed Node — lets harnesses find and stop
#: leaked nodes (a failed test that skips ``stop()`` would otherwise leave
#: live heartbeater/gossiper threads interfering with everything after it)
ALL_NODES: "weakref.WeakSet[Node]" = weakref.WeakSet()


def stop_leaked_nodes() -> list[str]:
    """Stop every still-running Node in the process; returns their addrs."""
    leaked = []
    for node in list(ALL_NODES):
        if getattr(node, "_running", False):
            leaked.append(node.addr)
            try:
                node.stop()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
    return leaked


class Node:
    def __init__(
        self,
        model: Any = None,
        data: Any = None,
        address: Optional[str] = None,
        learner: Any = None,
        aggregator: Any = None,
        protocol: Union[CommunicationProtocol, Type[CommunicationProtocol]] = InMemoryProtocol,
        simulation: bool = False,
    ) -> None:
        # transport (class or ready instance — reference picks by ctor arg, node.py:86)
        self.protocol: CommunicationProtocol = (
            protocol(address) if isinstance(protocol, type) else protocol
        )
        self.addr = self.protocol.get_address()

        self.state = NodeState(self.addr, simulation=simulation)
        # Byzantine defense-in-depth (federation/defense.py): one
        # screen + suspicion tracker shared by BOTH aggregation seams
        # (the sync aggregator below, the async context's buffers);
        # inert until Settings.BYZ_SCREEN. Quarantine drives the same
        # eviction funnel a heartbeat death does (_quarantine_peer).
        from p2pfl_tpu.federation.defense import ByzantineDefense

        self.defense = ByzantineDefense(self.addr, on_quarantine=self._quarantine_peer)
        self.aggregator = aggregator if aggregator is not None else FedAvg(self.addr)
        self.aggregator.node_name = self.addr
        self.aggregator.defense = self.defense

        # learner: instance, or class to instantiate with (model, data)
        if learner is None and model is not None:
            from p2pfl_tpu.learning.learner import JaxLearner

            learner = JaxLearner(model, data)
        elif isinstance(learner, type):
            learner = learner(model, data)
        self.learner = learner
        self.state.learner = learner

        # learning-thread plumbing
        self.experiment_name = "experiment"
        self.total_rounds = 0
        self.epochs = 1
        self.pending_init_update: Optional[ModelUpdate] = None
        # init_model that raced ahead of start_learning (weights plane vs
        # TTL-flooded control broadcast): stashed with its arrival time,
        # consumed by StartLearningStage while still fresh. Deliberately
        # NOT latched into model_initialized_event at arrival — a LATE
        # init (delivered after a graceful timeout abort) must not leak
        # into the next experiment.
        self._early_init_lock = threading.Lock()
        self._early_init: Optional[tuple[float, ModelUpdate]] = None
        # round-start global stash for secagg dropout fallback
        # (stages/learning_stages.py TrainStage / GossipModelStage)
        self.round_start_params: Optional[Any] = None
        # async control plane (Settings.FEDERATION_MODE == "async"):
        # per-experiment AsyncContext (federation/workflow.py) — buffers,
        # version mailbox, topology role. None outside an async experiment;
        # the async_* commands drop their payloads while it is None.
        self.async_ctx: Optional[Any] = None
        # async_updates that raced ahead of this aggregator's context
        # (a fast edge finishes its first local update while we are still
        # in the init gossip push — the async twin of the early-init
        # stash): bounded FIFO, drained right after the context installs,
        # cleared on stop. Guarded by _early_async_lock.
        self._early_async_lock = threading.Lock()
        self._early_async: list = []
        # elastic membership (federation/workflow.py): the experiment id
        # this node will enter its next experiment under (parsed from
        # start_learning / minted by set_start_learning), the join flag
        # consumed by the async workflow (skip init sync, bootstrap-pull
        # instead), and the graceful-leave request latch
        self._pending_xid: Optional[str] = None
        self._async_join = False
        self._async_leave = threading.Event()
        # crash-resurrection (federation/durability.py): an attached
        # NodeJournal makes the async workflow snapshot after every Nth
        # own update; a snapshot recovered by Node.resume waits here for
        # the workflow to consume (restore buffers/counters/membership)
        self.journal: Optional[Any] = None
        self._resume_snapshot: Optional[Any] = None
        # the finished async experiment's canonical result
        # (params, version, xid) — kept until the next experiment starts
        # so async_pull can still be served AFTER the workflow exited (a
        # straggler whose every inbound push targeted a corpse pulls at
        # exit; the servers may already be gone from their contexts)
        self._last_async_global: Optional[tuple] = None
        self._interrupt = threading.Event()
        self._learning_thread: Optional[threading.Thread] = None
        self._running = False
        #: callables invoked as ``hook(node, stage_name)`` on every stage
        #: transition of the learning thread — the fault-injection layer's
        #: crash-at-stage seam (communication/faults.py)
        self.stage_hooks: list = []
        # mid-round train-set repair: heartbeat evictions of train-set
        # members shrink the round's coverage target (aggregator) and the
        # gossip targets (state.train_set) instead of stalling the round
        self.protocol.add_evict_listener(self._on_peer_evicted)
        ALL_NODES.add(self)

        # command registry (reference node.py:110-131)
        for cmd in (
            HeartbeatCommand(self.protocol.heartbeater),
            StartLearningCommand(self),
            StopLearningCommand(self),
            ModelInitializedCommand(self.state),
            VoteTrainSetCommand(self.state),
            ModelsAggregatedCommand(self),
            ModelsReadyCommand(self.state),
            MetricsCommand(self.state),
            SecAggPubCommand(self.state),
            SecAggRecoverCommand(self.state),
            SecAggNeedCommand(self),
            SecAggShareCommand(self.state),
            SecAggRevealCommand(self.state),
            InitModelCommand(self),
            AddModelCommand(self),
            AsyncUpdateCommand(self),
            AsyncModelCommand(self),
            AsyncDoneCommand(self.state),
            AsyncPullCommand(self),
            AsyncJoinCommand(self),
            AsyncViewCommand(self),
            AsyncLeaveCommand(self),
        ):
            self.protocol.add_command(cmd)
        # DCN rendezvous verbs (communication/dcn.py): control-plane half
        # of the cross-process weights plane — registered unconditionally
        # (the plane gates on Settings.WEIGHTS_PLANE + world state per
        # offer, same idiom as the ICI registration below)
        from p2pfl_tpu.commands.dcn import DCN_COMMANDS

        for cmd_cls in DCN_COMMANDS:
            self.protocol.add_command(cmd_cls(self))

    # ---- lifecycle (reference node.py:204-241) ----

    def start(self, wait: bool = False) -> None:
        if self._running:
            raise NodeRunningException(f"Node {self.addr} already running")
        logger.register_node(self.addr, self.state, self.state.simulation)
        from p2pfl_tpu.management.watchdog import StallWatchdog

        StallWatchdog.ensure_started()  # no-op unless Settings.STALL_WATCHDOG_S > 0
        self.protocol.start()
        if self.learner is not None:
            # shard-plane presence (communication/ici.py): co-located peers
            # can move model payloads device-to-device when
            # Settings.WEIGHTS_PLANE="ici"; registration is unconditional
            # and cheap — the plane itself gates on the setting per send
            from p2pfl_tpu.communication.ici import IciEndpoint, ShardPlaneRegistry

            ShardPlaneRegistry.register(self.addr, IciEndpoint(self))
            # world-directory presence (communication/dcn.py): same-world
            # peers in OTHER processes discover this node's placement via
            # the distributed runtime's KV store; no-op outside a
            # multi-process jax.distributed world
            from p2pfl_tpu.communication.dcn import DcnPlane

            DcnPlane.instance().publish_node(self.addr)
        self._running = True
        if wait:
            self.protocol.wait_for_termination()

    def stop(self) -> None:
        if not self._running:
            return
        self._running = False
        from p2pfl_tpu.communication.ici import ShardPlaneRegistry

        ShardPlaneRegistry.unregister(self.addr)
        from p2pfl_tpu.communication.dcn import DcnPlane

        DcnPlane.instance().withdraw_node(self.addr)
        self._stop_learning()
        self.protocol.stop()
        logger.unregister_node(self.addr)

    def stop_async(self) -> None:
        """Stop from a server/command thread without deadlocking it."""
        threading.Thread(target=self.stop, name=f"stop-{self.addr}", daemon=True).start()

    # ---- neighborhood (reference node.py:137-203) ----

    def connect(self, addr: str) -> bool:
        if self.state.round is not None:
            logger.info(self.addr, "Joining a network mid-learning is unsupported")
            return False
        return self.protocol.connect(addr)

    def disconnect(self, addr: str) -> None:
        self.protocol.disconnect(addr)

    def get_neighbors(self, only_direct: bool = False) -> dict:
        return self.protocol.get_neighbors(only_direct)

    def is_running(self) -> bool:
        return self._running

    # ---- learning control (reference node.py:288-341) ----

    def set_start_learning(self, rounds: int = 1, epochs: int = 1) -> None:
        if rounds < 1:
            raise ZeroRoundsException("rounds must be >= 1")
        if self.state.round is not None:
            logger.info(self.addr, "Learning already in progress")
            return
        # mint the fleet-wide experiment identity: it rides the broadcast
        # (optional third arg — old receivers ignore it) and is stamped on
        # every wire frame as the "xp" header so receivers can filter
        # cross-experiment stragglers exactly
        import uuid

        self._pending_xid = uuid.uuid4().hex[:16]
        self.protocol.broadcast(
            self.protocol.build_msg(
                "start_learning", [str(rounds), str(epochs), self._pending_xid]
            )
        )
        # this node is THE initializer: its current weights seed the network
        self.state.model_initialized_event.set()
        self.protocol.broadcast(self.protocol.build_msg("model_initialized"))
        self._start_learning_thread(rounds, epochs)

    def join_async_experiment(self, rounds: int = 1, epochs: int = 1) -> None:
        """Join a RUNNING async experiment mid-stream (elastic membership).

        The joiner must already be connected to the overlay (heartbeats
        advertise it to every member, whose contexts fold it into the
        topology on their next membership refresh). Its workflow skips
        the initial-model sync (that experiment's start_learning is long
        gone) and instead bootstraps by pulling the nearest aggregator's
        current global (``async_pull``) before contributing. Only
        meaningful under ``Settings.FEDERATION_MODE == "async"`` — the
        sync FSM's cohort is fixed by the round-0 vote.
        """
        if rounds < 1:
            raise ZeroRoundsException("rounds must be >= 1")
        if Settings.FEDERATION_MODE != "async":
            logger.error(
                self.addr,
                "join_async_experiment requires FEDERATION_MODE='async' — ignored",
            )
            return
        if self.state.round is not None:
            logger.info(self.addr, "Learning already in progress")
            return
        # a joiner never saw this experiment's start_learning: clear any
        # stale identity from a PREVIOUS experiment (it adopts the running
        # experiment's id from its bootstrap global instead — stamping the
        # old one would get its frames xp-filtered by the whole fleet)
        self._pending_xid = None
        self._async_join = True
        self._start_learning_thread(rounds, epochs)

    def enable_journal(self, directory: str, keep_n: Optional[int] = None) -> None:
        """Attach a crash-resurrection journal (federation/durability.py):
        the async workflow then commits one snapshot every
        ``Settings.JOURNAL_EVERY_N_UPDATES`` of this node's own updates
        (plus a final one at drain), and :meth:`resume` can later bring
        the node back from ``directory`` as itself."""
        from p2pfl_tpu.federation.durability import NodeJournal

        self.journal = NodeJournal(directory, node_name=self.addr, keep_n=keep_n)

    @classmethod
    def resume(
        cls,
        journal_dir: str,
        model: Any = None,
        data: Any = None,
        learner: Any = None,
        protocol: Type[CommunicationProtocol] = InMemoryProtocol,
        bootstrap: Optional[list] = None,
        rounds: Optional[int] = None,
        epochs: int = 1,
        start: bool = True,
        simulation: bool = False,
    ) -> "Node":
        """Resurrect a node from its journal — it comes back as ITSELF.

        Recovers the last committed snapshot, rebuilds a Node under the
        journaled ADDRESS (identity is what makes upstream VersionVectors
        dedup its pre-crash in-flight updates instead of double-merging),
        restores the learner's params/opt_state, and re-enters the
        running experiment through the EXISTING elastic join machinery:
        the workflow sees the join flag, announces ``async_join``, pulls
        a bootstrap global (catching up if the fleet moved past the
        journaled version), and then restores buffers, version vector,
        membership view, suspicion state and sequence counters from the
        snapshot — counters resumed strictly past the journaled
        high-water plus ``Settings.JOURNAL_SEQ_MARGIN``.

        ``bootstrap`` lists peers to connect to (default: the journaled
        live membership view minus self). ``rounds`` is the remaining
        local update budget (default: journaled ``total_rounds`` minus
        updates already done, floor 1). Caller supplies ``model``/
        ``data`` (or a ready ``learner``) exactly as for ``__init__`` —
        datasets are not journaled, only learned state is.

        Raises ``FileNotFoundError`` when the journal has no
        recoverable snapshot (an empty directory is not a node).
        """
        from p2pfl_tpu.federation.durability import NodeJournal
        from p2pfl_tpu.management.telemetry import telemetry

        t0 = time.monotonic()
        journal = NodeJournal(journal_dir)
        snap = journal.recover()
        if snap is None:
            raise FileNotFoundError(f"no recoverable journal snapshot under {journal_dir}")
        journal.node_name = snap.addr
        node = cls(
            model,
            data,
            address=snap.addr,
            learner=learner,
            protocol=protocol(snap.addr) if isinstance(protocol, type) else protocol,
            simulation=simulation,
        )
        if node.learner is not None:
            from p2pfl_tpu.learning.weights import restore_like

            template = node.learner.get_parameters()
            if snap.learner_step is not None:
                import os

                from p2pfl_tpu.learning.checkpoint import restore_learner

                restore_learner(
                    os.path.join(journal.directory, "learner"),
                    node.learner,
                    step=snap.learner_step,
                )
            elif snap.learner_params is not None:
                node.learner.set_parameters(restore_like(template, snap.learner_params))
            # re-materialize the journaled flat dicts as pytrees with the
            # learner's structure (the fleet shares one model structure)
            if snap.global_params is not None:
                snap.global_params = restore_like(template, snap.global_params)
            for bj in snap.buffers:
                bj.pending = [
                    (o, s, b, c, n, restore_like(template, p))
                    for o, s, b, c, n, p in bj.pending
                ]
        node.journal = journal
        node._resume_snapshot = snap
        # the elastic join path, with the journaled identity: KEEP the
        # experiment id (a joiner nulls it — it never saw start_learning;
        # a resurrectee DID, and stamping the journaled xid keeps its
        # frames inside the experiment's xp filter from the first push)
        node._pending_xid = snap.xid
        node._async_join = True
        if start:
            node.start()
            peers = bootstrap if bootstrap is not None else [
                a for a in snap.members if a != snap.addr and a not in snap.dead
            ]
            for peer in peers:
                node.connect(peer)
            budget = rounds if rounds is not None else max(
                snap.total_rounds - snap.updates_done, 1
            )
            logger.log_comm_metric(snap.addr, "node_resumed")
            telemetry.event(
                snap.addr,
                "node_resumed",
                kind="stage",
                attrs={
                    "snap": snap.snap,
                    "version": snap.global_version,
                    "updates_done": snap.updates_done,
                    "resume_ms": round((time.monotonic() - t0) * 1000.0, 3),
                },
            )
            node._start_learning_thread(budget, epochs)
        return node

    def consume_resume_snapshot(self) -> Optional[Any]:
        """Pop the recovered snapshot (the workflow restores from it
        exactly once — a later experiment must start clean)."""
        snap, self._resume_snapshot = self._resume_snapshot, None
        return snap

    def request_async_leave(self) -> None:
        """Ask the running async workflow to leave GRACEFULLY: it stops
        training after the current local update, forwards any partial
        aggregation buffers to the successor tiers (nothing buffered is
        lost), broadcasts ``async_leave`` + ``async_done`` so survivors
        re-derive the topology around the hole without waiting for
        eviction, and finishes its experiment locally. A no-op outside an
        async experiment."""
        self._async_leave.set()

    def async_leave_requested(self) -> bool:
        return self._async_leave.is_set()

    def consume_async_join(self) -> bool:
        """Pop the join flag (the workflow reads it exactly once)."""
        joining, self._async_join = self._async_join, False
        return joining

    def set_stop_learning(self) -> None:
        if self.state.round is None:
            logger.info(self.addr, "Learning is not running")
            return
        self.protocol.broadcast(self.protocol.build_msg("stop_learning"))
        self._stop_learning()

    def learning_interrupted(self) -> bool:
        return self._interrupt.is_set()

    def learning_active(self) -> bool:
        """True while a learning thread is running — from the moment this
        node processed ``start_learning`` until the workflow returned
        (including graceful aborts). Commands that only make sense inside
        an experiment (``init_model``) gate on this."""
        t = self._learning_thread
        return t is not None and t.is_alive()

    # ---- internals (called by commands too) ----

    def _start_learning_thread(self, rounds: int, epochs: int) -> None:
        with self.state.start_thread_lock:
            if self._learning_thread is not None and self._learning_thread.is_alive():
                logger.debug(self.addr, "Learning thread already running")
                return
            self.total_rounds = rounds
            self.epochs = epochs
            self._interrupt.clear()
            self._async_leave.clear()
            self._learning_thread = threading.Thread(
                target=self._run_learning, name=f"learning-{self.addr}", daemon=True
            )
            self._learning_thread.start()

    def _run_learning(self) -> None:
        # suspicion/quarantine are per-experiment state: a new experiment
        # re-admits every origin (the overlay-level eviction a previous
        # run drove has its own re-admission rules)
        self.defense.reset()
        # control-plane selection: the sync round FSM (the reference
        # semantics) or the async bounded-staleness plane (ROADMAP 3)
        if Settings.FEDERATION_MODE == "async":
            from p2pfl_tpu.federation.workflow import AsyncLearningWorkflow

            AsyncLearningWorkflow().run(self)
            return
        from p2pfl_tpu.stages.workflow import LearningWorkflow

        LearningWorkflow().run(self)

    def stash_early_init(self, update: ModelUpdate) -> None:
        """Hold an init_model that arrived before start_learning was
        processed (InitModelCommand) for StartLearningStage to consume.

        The TTL is also enforced by a timer, not only at take time: a node
        that never starts an experiment (a pure-communication overlay
        member, or a straggler init after an aborted run) must not hold a
        full model's parameters for the life of the process."""
        slot = (time.monotonic(), update)
        with self._early_init_lock:
            self._early_init = slot

        def _expire() -> None:
            with self._early_init_lock:
                if self._early_init is slot:  # not consumed/replaced meanwhile
                    self._early_init = None
                    logger.debug(self.addr, "Early init_model stash expired unconsumed")

        t = threading.Timer(Settings.EARLY_INIT_TTL, _expire)
        t.daemon = True
        t.start()

    def take_early_init(self) -> Optional[ModelUpdate]:
        """Pop the pre-start init_model stash if it belongs to THIS
        experiment.

        When both the stash and this node carry an experiment identity
        (the wire's optional "xp" header), the comparison is EXACT: a
        matching init is consumed regardless of age, a mismatched one —
        a leftover from a previous (aborted) experiment that would
        shadow the real init — is dropped. Frames from pre-xp senders
        fall back to the ``Settings.EARLY_INIT_TTL`` freshness heuristic.
        """
        with self._early_init_lock:
            slot, self._early_init = self._early_init, None
        if slot is None:
            return None
        stashed_at, update = slot
        xid = self.state.experiment_xid
        if update.xp is not None and xid is not None:
            if update.xp != xid:
                logger.debug(
                    self.addr, "Discarding early init_model stash from another experiment"
                )
                return None
            return update
        if time.monotonic() - stashed_at > Settings.EARLY_INIT_TTL:
            logger.debug(self.addr, "Discarding stale early init_model stash")
            return None
        return update

    def stash_async_update(self, update: ModelUpdate, source: Optional[str] = None) -> None:
        """Hold an async_update that beat the AsyncContext's creation
        (commands/federation.py) for the workflow to drain — bounded: in
        async-land a superseded update is droppable by design, so overflow
        evicts the oldest instead of growing. ``source`` (the delivering
        peer) rides along so the drain's Byzantine screen attributes a
        poisoned stashed payload to whoever DELIVERED it, exactly like a
        direct delivery (federation/defense.py framing contract)."""
        with self._early_async_lock:
            self._early_async.append(
                (self.state.experiment_epoch, time.monotonic(), update, source)
            )
            while len(self._early_async) > 64:
                self._early_async.pop(0)

    def take_async_stash(self) -> list:
        """Pop the stash, keeping only THIS experiment's entries.

        When an entry and this node both carry an experiment identity
        (the wire's optional "xp" header, stamped by the start_learning
        initiator), the filter is EXACT: a matching entry is kept, a
        mismatched one — a previous experiment's retried/duplicated tail
        update that would drain into fresh buffers at τ=0 full weight —
        is dropped. Entries from pre-xp senders fall back to the two
        heuristics that closed the residual window before the wire
        carried identity: the ``experiment_epoch`` stamped at stash time
        and the ``EARLY_INIT_TTL`` freshness window.
        """
        with self._early_async_lock:
            entries, self._early_async = self._early_async, []
        now = time.monotonic()
        epoch = self.state.experiment_epoch
        xid = self.state.experiment_xid
        fresh = []
        for e, t, u, src in entries:
            if u.xp is not None and xid is not None:
                if u.xp == xid:
                    fresh.append((u, src))
                continue
            if e == epoch and now - t <= Settings.EARLY_INIT_TTL:
                fresh.append((u, src))
        if len(fresh) < len(entries):
            logger.debug(self.addr, "Discarded stale early async_update stash entries")
        return fresh

    def _quarantine_peer(self, addr: str) -> None:
        """Byzantine quarantine (federation/defense.py): drive the SAME
        eviction path a corpse takes — ``Neighbors.evict`` fires the
        protocol's eviction listeners, which run sync train-set repair /
        the async ``TierRouter`` re-derivation — with the quarantine flag
        set so the attacker's (perfectly healthy) heartbeats cannot
        immediately re-admit it. Runs on the defense's daemon thread,
        never under an aggregator or buffer lock.
        """
        logger.warning(
            self.addr, f"Evicting {addr} from the overlay (Byzantine quarantine)"
        )
        self.protocol.neighbors.evict(addr, quarantine=True)

    def _on_peer_evicted(self, addr: str) -> None:
        """Mid-round train-set repair (ISSUE 5): a train-set member was
        heartbeat-evicted. If it has not contributed, shrink the round's
        coverage target to the survivors and re-announce our coverage so
        peers' partial-gossip loops converge on the repaired target too —
        ``wait_and_get_aggregation`` then resolves to the survivors'
        partial instead of burning the full ``AGGREGATION_TIMEOUT``.

        Inert under ``SECURE_AGGREGATION``: a survivors-only early close
        would apply an aggregate still carrying the dead member's
        uncancelled pair masks — secagg's seed-recovery machinery owns
        dropouts there (stages/learning_stages.py).
        """
        st = self.state
        # wake a vote-collection loop blocked on the evicted peer's vote:
        # the loop re-derives the live candidate set per iteration
        # (VoteTrainSetStage), so the wake alone lets it stop waiting for
        # a corpse without burning the remaining VOTE_TIMEOUT
        st.votes_ready_event.set()
        ctx = self.async_ctx
        if ctx is not None:
            # async control plane: an eviction is a MEMBERSHIP event —
            # the context re-derives the topology with the corpse as a
            # hole (federation/workflow.py AsyncContext.mark_dead):
            # successor regionals/roots self-elect, K clamps shrink to
            # the live fan-in (possibly firing the flush the corpse was
            # blocking), and this node's buffers migrate to its new
            # role. The listener runs on the HEARTBEATER thread, and the
            # repair may flush a buffer — a jitted merge plus full-model
            # pushes whose dispatch can block up to GOSSIP_SEND_TIMEOUT
            # (≈ a whole HEARTBEAT_TIMEOUT): doing that inline would
            # silence our own beats exactly during a failure window and
            # get THIS live node evicted, cascading the fault — so the
            # repair runs on its own daemon thread (sends outside every
            # context/buffer lock, per the deadlock contract).
            def _repair(ctx=ctx, addr=addr) -> None:
                try:
                    ctx.execute_actions(ctx.mark_dead(addr))
                    if ctx.accepting and ctx.take_stash_dirty():
                        # a role change may make stashed updates routable
                        from p2pfl_tpu.commands.federation import drain_async_stash

                        drain_async_stash(self, ctx)
                except Exception as exc:  # noqa: BLE001 — repair is best-effort
                    logger.error(self.addr, f"Async eviction repair failed for {addr}: {exc!r}")

            threading.Thread(
                target=_repair, name=f"async-repair-{self.addr}", daemon=True
            ).start()
            return
        if not Settings.TRAIN_SET_REPAIR or Settings.SECURE_AGGREGATION:
            return
        with st.train_set_lock:
            # check-and-record under the lock: the vote tally
            # (VoteTrainSetStage) replaces both fields concurrently on the
            # learning thread — unsynchronized, one write silently wins.
            # train_set itself is left INTACT (see NodeState.train_set_evicted:
            # the aggregator must keep accepting this member's contributions
            # that reached peers); only gossip targeting subtracts the set.
            if st.round is None or addr == self.addr:
                return
            if addr not in st.train_set or addr in st.train_set_evicted:
                return
            st.train_set_evicted = st.train_set_evicted | {addr}
            survivors = [n for n in st.train_set if n not in st.train_set_evicted]
        logger.warning(
            self.addr,
            f"Train-set member {addr} evicted mid-round — gossip targets "
            f"repaired to {survivors}",
        )
        covered = self.aggregator.discard_member(addr)
        if covered:
            self.protocol.broadcast(
                self.protocol.build_msg("models_aggregated", covered, round=st.round or 0)
            )

    def _stop_learning(self) -> None:
        self._interrupt.set()
        with self._early_init_lock:
            self._early_init = None
        with self._early_async_lock:
            self._early_async = []
        if self.learner is not None:
            self.learner.interrupt_fit()
        self.aggregator.clear()
        self.aggregator.reset_experiment()
        self.state.clear()
        self.state.votes_ready_event.set()
