"""The user-facing Node (reference ``p2pfl/node.py:47-341``).

Wires a transport, an aggregator, a learner and the command registry; owns
the learning thread that drives the round FSM. ``Node(None, None)`` is valid
for pure-communication use, matching the reference's communication tests.
"""

from __future__ import annotations

import threading
import weakref
from typing import Any, Optional, Type, Union

from p2pfl_tpu.commands import (
    AddModelCommand,
    HeartbeatCommand,
    InitModelCommand,
    MetricsCommand,
    ModelInitializedCommand,
    ModelsAggregatedCommand,
    ModelsReadyCommand,
    SecAggPubCommand,
    SecAggNeedCommand,
    SecAggRecoverCommand,
    SecAggRevealCommand,
    SecAggShareCommand,
    StartLearningCommand,
    StopLearningCommand,
    VoteTrainSetCommand,
)
from p2pfl_tpu.communication.memory import InMemoryProtocol
from p2pfl_tpu.communication.protocol import CommunicationProtocol
from p2pfl_tpu.exceptions import NodeRunningException, ZeroRoundsException
from p2pfl_tpu.learning.aggregators.fedavg import FedAvg
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.node_state import NodeState


#: weak registry of every constructed Node — lets harnesses find and stop
#: leaked nodes (a failed test that skips ``stop()`` would otherwise leave
#: live heartbeater/gossiper threads interfering with everything after it)
ALL_NODES: "weakref.WeakSet[Node]" = weakref.WeakSet()


def stop_leaked_nodes() -> list[str]:
    """Stop every still-running Node in the process; returns their addrs."""
    leaked = []
    for node in list(ALL_NODES):
        if getattr(node, "_running", False):
            leaked.append(node.addr)
            try:
                node.stop()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
    return leaked


class Node:
    def __init__(
        self,
        model: Any = None,
        data: Any = None,
        address: Optional[str] = None,
        learner: Any = None,
        aggregator: Any = None,
        protocol: Union[CommunicationProtocol, Type[CommunicationProtocol]] = InMemoryProtocol,
        simulation: bool = False,
    ) -> None:
        # transport (class or ready instance — reference picks by ctor arg, node.py:86)
        self.protocol: CommunicationProtocol = (
            protocol(address) if isinstance(protocol, type) else protocol
        )
        self.addr = self.protocol.get_address()

        self.state = NodeState(self.addr, simulation=simulation)
        self.aggregator = aggregator if aggregator is not None else FedAvg(self.addr)
        self.aggregator.node_name = self.addr

        # learner: instance, or class to instantiate with (model, data)
        if learner is None and model is not None:
            from p2pfl_tpu.learning.learner import JaxLearner

            learner = JaxLearner(model, data)
        elif isinstance(learner, type):
            learner = learner(model, data)
        self.learner = learner
        self.state.learner = learner

        # learning-thread plumbing
        self.experiment_name = "experiment"
        self.total_rounds = 0
        self.epochs = 1
        self.pending_init_update: Optional[ModelUpdate] = None
        # round-start global stash for secagg dropout fallback
        # (stages/learning_stages.py TrainStage / GossipModelStage)
        self.round_start_params: Optional[Any] = None
        self._interrupt = threading.Event()
        self._learning_thread: Optional[threading.Thread] = None
        self._running = False
        ALL_NODES.add(self)

        # command registry (reference node.py:110-131)
        for cmd in (
            HeartbeatCommand(self.protocol.heartbeater),
            StartLearningCommand(self),
            StopLearningCommand(self),
            ModelInitializedCommand(self.state),
            VoteTrainSetCommand(self.state),
            ModelsAggregatedCommand(self),
            ModelsReadyCommand(self.state),
            MetricsCommand(self.state),
            SecAggPubCommand(self.state),
            SecAggRecoverCommand(self.state),
            SecAggNeedCommand(self),
            SecAggShareCommand(self.state),
            SecAggRevealCommand(self.state),
            InitModelCommand(self),
            AddModelCommand(self),
        ):
            self.protocol.add_command(cmd)

    # ---- lifecycle (reference node.py:204-241) ----

    def start(self, wait: bool = False) -> None:
        if self._running:
            raise NodeRunningException(f"Node {self.addr} already running")
        logger.register_node(self.addr, self.state, self.state.simulation)
        from p2pfl_tpu.management.watchdog import StallWatchdog

        StallWatchdog.ensure_started()  # no-op unless Settings.STALL_WATCHDOG_S > 0
        self.protocol.start()
        self._running = True
        if wait:
            self.protocol.wait_for_termination()

    def stop(self) -> None:
        if not self._running:
            return
        self._running = False
        self._stop_learning()
        self.protocol.stop()
        logger.unregister_node(self.addr)

    def stop_async(self) -> None:
        """Stop from a server/command thread without deadlocking it."""
        threading.Thread(target=self.stop, name=f"stop-{self.addr}", daemon=True).start()

    # ---- neighborhood (reference node.py:137-203) ----

    def connect(self, addr: str) -> bool:
        if self.state.round is not None:
            logger.info(self.addr, "Joining a network mid-learning is unsupported")
            return False
        return self.protocol.connect(addr)

    def disconnect(self, addr: str) -> None:
        self.protocol.disconnect(addr)

    def get_neighbors(self, only_direct: bool = False) -> dict:
        return self.protocol.get_neighbors(only_direct)

    def is_running(self) -> bool:
        return self._running

    # ---- learning control (reference node.py:288-341) ----

    def set_start_learning(self, rounds: int = 1, epochs: int = 1) -> None:
        if rounds < 1:
            raise ZeroRoundsException("rounds must be >= 1")
        if self.state.round is not None:
            logger.info(self.addr, "Learning already in progress")
            return
        self.protocol.broadcast(
            self.protocol.build_msg("start_learning", [str(rounds), str(epochs)])
        )
        # this node is THE initializer: its current weights seed the network
        self.state.model_initialized_event.set()
        self.protocol.broadcast(self.protocol.build_msg("model_initialized"))
        self._start_learning_thread(rounds, epochs)

    def set_stop_learning(self) -> None:
        if self.state.round is None:
            logger.info(self.addr, "Learning is not running")
            return
        self.protocol.broadcast(self.protocol.build_msg("stop_learning"))
        self._stop_learning()

    def learning_interrupted(self) -> bool:
        return self._interrupt.is_set()

    # ---- internals (called by commands too) ----

    def _start_learning_thread(self, rounds: int, epochs: int) -> None:
        with self.state.start_thread_lock:
            if self._learning_thread is not None and self._learning_thread.is_alive():
                logger.debug(self.addr, "Learning thread already running")
                return
            self.total_rounds = rounds
            self.epochs = epochs
            self._interrupt.clear()
            self._learning_thread = threading.Thread(
                target=self._run_learning, name=f"learning-{self.addr}", daemon=True
            )
            self._learning_thread.start()

    def _run_learning(self) -> None:
        from p2pfl_tpu.stages.workflow import LearningWorkflow

        LearningWorkflow().run(self)

    def _stop_learning(self) -> None:
        self._interrupt.set()
        if self.learner is not None:
            self.learner.interrupt_fit()
        self.aggregator.clear()
        self.aggregator.reset_experiment()
        self.state.clear()
        self.state.votes_ready_event.set()
