"""Federated LoRA fine-tuning of a causal LM (BASELINE config 5 shape).

Nodes train and exchange ONLY low-rank adapters; ``--spmd`` runs the whole
federation as one mesh program, otherwise gossip nodes over the in-memory
transport. Synthetic Markov-chain text stands in for a real corpus.
"""

from __future__ import annotations

import argparse
import time


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--nodes", type=int, default=4)
    parser.add_argument("--rounds", type=int, default=3)
    parser.add_argument("--epochs", type=int, default=1)
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--dim", type=int, default=256)
    parser.add_argument("--layers", type=int, default=4)
    parser.add_argument("--rank", type=int, default=16)
    parser.add_argument("--batch-size", type=int, default=16)
    parser.add_argument("--lr", type=float, default=1e-2)
    parser.add_argument("--spmd", action="store_true", help="one-program mesh mode")
    parser.add_argument(
        "--big-model", action="store_true",
        help="per-block remat + lax.scan over layers (the 1B-scale recipe: "
             "memory bounded at one block, compile size independent of depth)",
    )
    parser.add_argument("--measure_time", action="store_true")
    args = parser.parse_args(argv)

    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer

    cfg = TransformerConfig(
        dim=args.dim,
        n_layers=args.layers,
        n_heads=max(args.dim // 64, 2),
        n_kv_heads=max(args.dim // 128, 1),
        ffn_hidden=args.dim * 8 // 3,
        lora_rank=args.rank,
        lora_mlp=True,
        remat=args.big_model,
        scan_layers=args.big_model,
    )
    data = FederatedDataset.synthetic_lm(vocab_size=cfg.vocab_size, seq_len=args.seq_len)
    t0 = time.monotonic()

    if args.spmd:
        from p2pfl_tpu.parallel import SpmdLoraFederation

        model = tiny_transformer(seq_len=args.seq_len, cfg=cfg)
        fed = SpmdLoraFederation.from_dataset(
            model, data, n_nodes=args.nodes, batch_size=args.batch_size,
            learning_rate=args.lr, vote=False,
        )
        for _ in range(args.rounds):
            entry = fed.run_round(epochs=args.epochs)
            metrics = fed.evaluate()
            print(
                f"round {entry['round']}: loss={float(entry['train_loss']):.4f} "
                f"next-token acc={metrics['test_acc']:.4f}"
            )
    else:
        from p2pfl_tpu.learning.lora import LoRALearner
        from p2pfl_tpu.simulation import Simulation

        sim = Simulation(
            args.nodes,
            lambda i, shard: LoRALearner(
                tiny_transformer(seq_len=args.seq_len, cfg=cfg),
                shard,
                batch_size=args.batch_size,
                learning_rate=args.lr,
            ),
            data,
            topology="full",
        )
        sim.start().learn(rounds=args.rounds, epochs=args.epochs)
        for addr, metrics in sim.evaluate().items():
            print(f"{addr}: {metrics}")
        sim.stop()

    if args.measure_time:
        print(f"elapsed: {time.monotonic() - t0:.2f}s")


if __name__ == "__main__":
    main()
