"""MNIST federated learning, SPMD mode: the whole federation as one program.

The TPU-native fast path: N logical nodes over a device mesh, FedAvg as an
ICI all-reduce. Use ``--nodes 64`` to reproduce the BASELINE north-star
configuration.
"""

from __future__ import annotations

import argparse
import time


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--nodes", type=int, default=8)
    parser.add_argument("--rounds", type=int, default=3)
    parser.add_argument("--epochs", type=int, default=1)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--aggregator", default="fedavg",
                        choices=["fedavg", "median", "trimmed_mean", "krum", "bulyan"])
    parser.add_argument("--partition", default="iid", choices=["iid", "sorted", "dirichlet"])
    parser.add_argument("--vote", action="store_true", help="elect a train set (round 0)")
    parser.add_argument("--measure_time", action="store_true")
    parser.add_argument("--dp-clip", type=float, default=0.0, help="DP-SGD clip norm (0 = off)")
    parser.add_argument("--dp-noise", type=float, default=0.0, help="DP-SGD noise multiplier")
    parser.add_argument(
        "--plot",
        nargs="?",
        const="spmd_mnist_metrics.png",
        default=None,
        metavar="PNG",
        help="render the per-round loss/accuracy curves to PNG",
    )
    args = parser.parse_args(argv)

    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.models import mlp
    from p2pfl_tpu.parallel import SpmdFederation

    data = FederatedDataset.mnist()
    fed = SpmdFederation.from_dataset(
        mlp(),
        data,
        n_nodes=args.nodes,
        strategy=args.partition,
        batch_size=args.batch_size,
        aggregator=args.aggregator,
        vote=args.vote,
        dp_clip=args.dp_clip,
        dp_noise=args.dp_noise,
    )
    t0 = time.monotonic()
    history = []
    for r in range(args.rounds):
        entry = fed.run_round(epochs=args.epochs)
        metrics = fed.evaluate()
        print(f"round {entry['round']}: loss={entry['train_loss']:.4f} acc={metrics['test_acc']:.4f}")
        history.append({**entry, "test_acc": float(metrics["test_acc"])})
    if args.plot:
        from p2pfl_tpu.management.plotting import plot_history

        path = plot_history(history, args.plot, title=f"spmd {args.nodes} nodes")
        print(f"metric curves: {path or 'nothing to plot'}")
    if args.measure_time:
        print(f"elapsed: {time.monotonic() - t0:.2f}s ({args.nodes} nodes)")
    if fed.accountant is not None:
        print(f"privacy spent: eps={fed.accountant.epsilon(1e-5):.2f} (delta=1e-5)")


if __name__ == "__main__":
    main()
