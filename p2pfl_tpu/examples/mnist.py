"""MNIST federated learning: N nodes in one process, line topology.

Parity with the reference example (``p2pfl/examples/mnist.py:22-187``):
``--nodes``, ``--rounds``, ``--epochs``, ``--protocol {memory,grpc}``
(reference ``--use_local_protocol``), ``--measure_time``. Runs the gossip
Node mode — see ``spmd_mnist.py`` for the one-program SPMD mode.
"""

from __future__ import annotations

import argparse
import time


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--nodes", type=int, default=2)
    parser.add_argument("--rounds", type=int, default=2)
    parser.add_argument("--epochs", type=int, default=1)
    parser.add_argument("--protocol", choices=["memory", "grpc"], default="memory")
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--samples", type=int, default=8192, help="total training samples")
    parser.add_argument("--measure_time", action="store_true")
    parser.add_argument(
        "--plot",
        nargs="?",
        const="mnist_metrics.png",
        default=None,
        metavar="PNG",
        help="render global metric curves to PNG (reference mnist.py:133-161)",
    )
    args = parser.parse_args(argv)

    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.learning.learner import JaxLearner
    from p2pfl_tpu.models import mlp
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.utils import connect_line, wait_convergence, wait_to_finish

    t0 = time.monotonic()
    data = FederatedDataset.mnist(n_train=args.samples, n_test=max(args.samples // 8, 256))

    nodes = []
    for i in range(args.nodes):
        learner = JaxLearner(mlp(seed=i), data.partition(i, args.nodes), batch_size=args.batch_size)
        if args.protocol == "grpc":
            from p2pfl_tpu.communication.grpc_transport import GrpcProtocol

            node = Node(learner=learner, protocol=GrpcProtocol("127.0.0.1:0"))
        else:
            node = Node(learner=learner)
        node.start()
        nodes.append(node)

    connect_line(nodes)
    wait_convergence(nodes, args.nodes - 1, only_direct=False, wait=30)

    nodes[0].set_start_learning(rounds=args.rounds, epochs=args.epochs)
    wait_to_finish(nodes, timeout=600)

    for node in nodes:
        print(f"{node.addr}: {node.learner.evaluate()}")
        node.stop()
    if args.plot:
        import os

        from p2pfl_tpu.management.plotting import plot_global_metrics, plot_local_metrics

        path = plot_global_metrics(args.plot)
        print(f"global metric curves: {path or 'nothing to plot'}")
        stem, ext = os.path.splitext(args.plot)
        local = plot_local_metrics(f"{stem}_local{ext or '.png'}")
        if local:
            print(f"local metric curves: {local}")
    if args.measure_time:
        print(f"elapsed: {time.monotonic() - t0:.2f}s")


if __name__ == "__main__":
    main()
