"""The async learning workflow: train → push → merge, no round barrier.

Selected by ``Settings.FEDERATION_MODE == "async"`` in
``Node._run_learning`` — the learning thread runs this instead of the
stages FSM. Control flow per node:

1. **Init sync** — identical to the sync plane
   (``stages.learning_stages.sync_initial_model``): everyone starts from
   the initiator's weights, version 0.
2. **Topology** — every node derives the same
   :class:`~p2pfl_tpu.federation.topology.HierarchicalTopology` from the
   sorted overlay membership (``Settings.HIER_CLUSTER_SIZE``).
3. **Local loop** — each node trains ``total_rounds`` local updates
   (reusing the fused-round learner path where the learner supports it),
   stamps each with its version triple, and pushes it to its cluster's
   regional aggregator. Between updates it adopts the freshest global
   model that arrived (``async_model`` push) — it never *waits* for one.
4. **Aggregation duties** — regional/global buffers
   (:class:`~p2pfl_tpu.federation.buffer.BufferedAggregator`) run inside
   the receive handlers (``commands/federation.py``): a flush at a
   regional pushes ONE aggregate up; a flush at the global root mints a
   new global version and pushes it down the tiers.
5. **Drain** — a node that finished its budget broadcasts ``async_done``;
   aggregators keep serving until every member is done or dead (bounded
   by ``Settings.ASYNC_DRAIN_TIMEOUT``), so slow members' tails still
   merge.

Every push rides ``protocol.send`` / the gossiper's concurrent dispatch
pool over the single ``_do_send`` seam — FaultPlan chaos, breaker-fed
eviction, retry accounting and telemetry send spans all apply unchanged.
Fan-outs (a fresh global to N children) go through
``Gossiper._dispatch_sends`` so one slow child costs a worker slot, not
the push.

Not composed in this control plane (guarded loudly at start):
``SECURE_AGGREGATION`` (pairwise masks need a fixed cohort per merge —
a buffer of whoever-arrived breaks cancellation) and
``WIRE_COMPRESSION="topk8"`` (delta anchors are pinned per sync round;
the async plane has no shared round to anchor on). Dense and ``int8``
wire compression work as-is.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import TYPE_CHECKING, Any, List, Optional, Tuple

from p2pfl_tpu.federation.buffer import BufferedAggregator, FlushResult
from p2pfl_tpu.federation.staleness import as_version
from p2pfl_tpu.federation.topology import HierarchicalTopology
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.management.telemetry import telemetry
from p2pfl_tpu.settings import Settings

if TYPE_CHECKING:
    from p2pfl_tpu.node import Node

Pytree = Any

#: outbound action: (weights command, target address, update)
Action = Tuple[str, str, ModelUpdate]




class AsyncContext:
    """Per-experiment async state attached to the node (``node.async_ctx``).

    Owns the node's aggregation buffers (by topology role) and the
    freshest-global mailbox. The locking contract that keeps the
    in-memory transport's synchronous delivery chains deadlock-free:
    **no lock is ever held across a send** — handlers compute under
    locks, collect :data:`Action` tuples, and :meth:`execute_actions`
    runs outside every lock.
    """

    def __init__(self, node: "Node", topo: HierarchicalTopology, params: Pytree) -> None:
        self.node = node
        self.topo = topo
        self.addr = node.addr
        self.lock = threading.Lock()
        self.accepting = True
        #: the newest global version this node KNOWS about (its learner
        #: may lag until the loop adopts pending_global)
        self.global_version = 0
        #: the version the learner's current params came from — what the
        #: node stamps as base_version on its next update
        self.base_version = 0
        self.pending_global: Optional[Tuple[Pytree, int]] = None
        #: last adopted/minted global (params, version) — what the drain's
        #: final-sync re-pushes carry
        self.last_global: Optional[Tuple[Pytree, int]] = None
        #: encode-once for drain re-pushes: one ModelUpdate per version,
        #: reused across ticks/children so byte transports serialize the
        #: full model once per version, not once per re-push fan-out
        self._final_push: Optional[Tuple[int, ModelUpdate]] = None
        #: members this node observed evicted (K-repair bookkeeping)
        self._dead: set = set()
        #: per-node monotone counters: training updates vs upward
        #: regional aggregates are deduped in DIFFERENT version vectors,
        #: but each stream must be monotone on its own
        self.train_seq = itertools.count(1)
        self._up_seq = itertools.count(1)
        self.rbuf: Optional[BufferedAggregator] = None
        self.gbuf: Optional[BufferedAggregator] = None
        k = Settings.FEDBUFF_K
        tier = topo.tier(node.addr)
        if tier == "global":
            if topo.is_flat():
                self.gbuf = BufferedAggregator(
                    node.addr, params, k=min(k, len(topo.members))
                )
            else:
                self.rbuf = BufferedAggregator(
                    node.addr, params, k=min(k, len(topo.cluster_of(node.addr))),
                    bump_on_flush=False,
                )
                self.gbuf = BufferedAggregator(
                    node.addr, params, k=min(k, len(topo.regionals))
                )
        elif tier == "regional":
            self.rbuf = BufferedAggregator(
                node.addr, params, k=min(k, len(topo.cluster_of(node.addr))),
                bump_on_flush=False,
            )

    @property
    def is_aggregator(self) -> bool:
        return self.rbuf is not None or self.gbuf is not None

    # ---- mailbox ----

    def take_pending_global(self) -> Optional[Tuple[Pytree, int]]:
        with self.lock:
            pend, self.pending_global = self.pending_global, None
        return pend

    def _adopt(self, params: Pytree, version: int) -> bool:
        """Record a newer global: mailbox for the learner + regional
        buffer re-base. False for stale pushes."""
        with self.lock:
            if version <= self.global_version:
                return False
            self.global_version = version
            self.pending_global = (params, version)
            self.last_global = (params, version)
        if self.rbuf is not None:
            self.rbuf.set_global(params, version)
        return True

    # ---- receive paths (commands + local offers) ----

    def handle_update(self, update: ModelUpdate) -> List[Action]:
        """Route a contribution into the right buffer; returns the sends
        its flush (if any) produced."""
        if self.gbuf is not None and self.topo.is_flat():
            res = self.gbuf.offer(update)
            return self._global_flush(res) if res else []
        ver = as_version(update.version)
        if (
            self.gbuf is not None
            and ver is not None
            and ver.origin != self.addr
            and ver.origin in self.topo.regionals
        ):
            # a peer regional's aggregate reaching the global tier
            res = self.gbuf.offer(update)
            return self._global_flush(res) if res else []
        if self.rbuf is None:
            logger.log_comm_metric(self.addr, "async_misrouted_drop")
            logger.debug(
                self.addr, "async_update received by a non-aggregator — dropped"
            )
            return []
        res = self.rbuf.offer(update)
        return self._regional_flush(res) if res else []

    def live_children(self) -> List[str]:
        """This node's push-down fan-out, membership-repaired: dead
        children are dropped, and the global root ADOPTS the edges of a
        dead regional's cluster (they re-route their updates to the root
        — see ``push_target`` — and must keep receiving fresh globals, or
        a regional crash would orphan its whole cluster for the rest of
        the run). Root failover itself stays open (ROADMAP 3)."""
        with self.lock:
            dead = set(self._dead)
        children = [c for c in self.topo.children_of(self.addr) if c not in dead]
        if self.addr == self.topo.global_root:
            for r in self.topo.regionals:
                if r != self.addr and r in dead:
                    children += [
                        m for m in self.topo.cluster_of(r) if m != r and m not in dead
                    ]
        return children

    def push_target(self) -> str:
        """Where this node's training updates go: its regional — or the
        global root once that regional is known dead (the update then
        folds into the root's own cluster buffer: the orphaned edges
        effectively join the root's cluster)."""
        target = self.topo.aggregator_for(self.addr)
        if target != self.addr:
            with self.lock:
                if target in self._dead:
                    return self.topo.global_root
        return target

    def handle_model(self, update: ModelUpdate, source: str) -> List[Action]:
        """A fresh global pushed down from above: adopt + forward one
        tier further down."""
        ver = as_version(update.version)
        version = ver.base_version if ver is not None else 0
        if not self._adopt(update.params, version):
            logger.log_comm_metric(self.addr, "async_model_stale")
            return []
        logger.log_comm_metric(self.addr, "async_model_adopt")
        telemetry.event(
            self.addr, "async_model_adopt", kind="stage", attrs={"version": version}
        )
        return [
            ("async_model", child, update)
            for child in self.live_children()
            if child != source
        ]

    # ---- flush propagation ----

    def _regional_flush(self, res: FlushResult) -> List[Action]:
        """A regional buffer filled: one merged aggregate goes UP."""
        upd = ModelUpdate(res.params, res.contributors, res.num_samples)
        upd.version = (self.addr, next(self._up_seq), res.version)
        if self.gbuf is not None:  # the root's own cluster feeding its global tier
            gres = self.gbuf.offer(upd)
            return self._global_flush(gres) if gres else []
        return [("async_update", self.topo.global_root, upd)]

    def _global_flush(self, res: FlushResult) -> List[Action]:
        """The global buffer filled: a new global version exists — adopt
        locally and push it down every child tier."""
        self._adopt(res.params, res.version)
        upd = ModelUpdate(res.params, [self.addr], 1)
        upd.version = (self.addr, res.version, res.version)
        return [("async_model", child, upd) for child in self.live_children()]

    # ---- repair + drain support ----

    def on_peer_evicted(self, addr: str) -> List[Action]:
        """A member died: shrink the affected tiers' K to the live fan-in
        (the async twin of mid-round train-set repair) — a dead edge must
        not leave its cluster's buffer permanently under-filled. May
        trigger the flush the corpse was blocking; returns its sends."""
        if addr not in self.topo._cluster_of:
            return []
        with self.lock:
            if addr in self._dead:
                return []
            self._dead.add(addr)
            dead = set(self._dead)
        actions: List[Action] = []
        if self.rbuf is not None and addr in self.topo.cluster_of(self.addr):
            live = [m for m in self.topo.cluster_of(self.addr) if m not in dead]
            res = self.rbuf.set_k(min(self.rbuf.k, max(1, len(live))))
            if res:
                actions += self._regional_flush(res)
        if self.gbuf is not None:
            fan = (
                [m for m in self.topo.members if m not in dead]
                if self.topo.is_flat()
                else [r for r in self.topo.regionals if r not in dead]
            )
            res = self.gbuf.set_k(min(self.gbuf.k, max(1, len(fan))))
            if res:
                actions += self._global_flush(res)
        if actions:
            logger.log_comm_metric(self.addr, "async_k_repair")
            logger.warning(
                self.addr,
                f"Async K-repair: {addr} evicted — flushed the buffer it was blocking",
            )
        return actions

    def final_sync_actions(self) -> List[Action]:
        """Re-push the last-known global to this node's children (drain
        phase): a fresh-global push is fire-and-forget — superseded by the
        next merge in steady state — but at the END of a run there is no
        next merge, so a single dropped push would strand a subtree on an
        old version. Children already at the version ignore it."""
        children = self.live_children()
        with self.lock:
            lg = self.last_global
            if lg is None or not children:
                return []
            params, version = lg
            if self._final_push is not None and self._final_push[0] == version:
                upd = self._final_push[1]  # encode-once: reuse across ticks
            else:
                upd = ModelUpdate(params, [self.addr], 1)
                upd.version = (self.addr, version, version)
                self._final_push = (version, upd)
        return [("async_model", child, upd) for child in children]

    # ---- outbound ----

    def execute_actions(self, actions: List[Action]) -> None:
        """Send the collected pushes through the gossiper's concurrent
        dispatch pool (stalled-peer skip, per-send budget, breaker
        feedback) — one slow child must not serialize a global push."""
        if not actions:
            return
        proto = self.node.protocol
        sends = []
        for cmd, target, upd in actions:
            ver = as_version(upd.version)
            sends.append((target, proto.build_weights(cmd, ver.seq if ver else 0, upd)))
        results, skipped = proto.gossiper._dispatch_sends(sends, create_connection=True)
        for ok in results:
            if ok is False:
                logger.log_comm_metric(self.addr, "async_push_fail")
        if skipped:
            logger.log_comm_metric(self.addr, "async_push_skipped", len(skipped))


class AsyncLearningWorkflow:
    """Drives one node's async experiment end to end (see module docs)."""

    def run(self, node: "Node") -> None:
        from p2pfl_tpu.communication.faults import FaultCrash
        from p2pfl_tpu.stages.learning_stages import (
            RoundFinishedStage,
            sync_initial_model,
        )

        state = node.state
        state.set_experiment(node.experiment_name, node.total_rounds)
        logger.experiment_started(node.addr)
        node.learner.set_epochs(node.epochs)
        node.learner.set_addr(node.addr)
        node.learner.pop_round_metrics()

        if Settings.SECURE_AGGREGATION:
            logger.error(
                node.addr,
                "FEDERATION_MODE='async' does not compose with "
                "SECURE_AGGREGATION (pairwise masks need a fixed cohort "
                "per merge; a staleness-weighted buffer breaks exact "
                "cancellation) — aborting the experiment",
            )
            state.clear()
            return
        if Settings.WIRE_COMPRESSION == "topk8":
            logger.error(
                node.addr,
                "FEDERATION_MODE='async' does not support topk8 wire "
                "compression (delta anchors are pinned per sync round; "
                "the async plane has no shared round) — aborting; use "
                "'none' or 'int8'",
            )
            state.clear()
            return

        ctx: Optional[AsyncContext] = None
        try:
            if not sync_initial_model(node):
                return
            # let heartbeats flood so every node derives the topology from
            # the same membership (agreement on membership IS agreement on
            # topology — the deterministic-derivation trick)
            time.sleep(Settings.WAIT_HEARTBEATS_CONVERGENCE)
            members = sorted(
                set(node.protocol.get_neighbors(only_direct=False)) | {node.addr}
            )
            topo = HierarchicalTopology(members, Settings.HIER_CLUSTER_SIZE)
            ctx = AsyncContext(node, topo, node.learner.get_parameters())
            node.async_ctx = ctx
            logger.info(
                node.addr,
                f"Async federation: tier={topo.tier(node.addr)} "
                f"topology={topo.describe()}",
            )
            # drain updates that raced ahead of the context (fast edges
            # finishing their first local update during our init gossip);
            # the stash's epoch/TTL filters already dropped a previous
            # experiment's retried stragglers
            from p2pfl_tpu.commands.federation import drain_async_stash

            drain_async_stash(node, ctx)
            self._local_loop(node, ctx)
            if node.learning_interrupted():
                return
            node.protocol.broadcast(node.protocol.build_msg("async_done"))
            self._drain(node, ctx)
            # the experiment's RESULT is the latest global model this node
            # knows — not its local tail update (which it already pushed;
            # whether that merged or was discarded with a partial buffer,
            # the canonical fleet model is the last minted version), so
            # every node's final evaluation measures the same model modulo
            # lost pushes
            with ctx.lock:
                lg = ctx.last_global
            if lg is not None and not node.learning_interrupted():
                node.learner.set_parameters(lg[0])
        except FaultCrash as exc:
            # injected hard crash: stop executing like a killed process —
            # no drain, no metrics flush, no state.clear
            if node.learner is not None:
                node.learner.pop_round_metrics()
            logger.info(node.addr, f"{exc}")
            return
        except Exception as exc:  # noqa: BLE001 — workflow failure ends learning, not the node
            if node.learning_interrupted():
                logger.info(node.addr, "Async learning interrupted")
            else:
                logger.error(node.addr, f"Async workflow failed: {exc!r}")
                state.clear()
            return
        finally:
            if ctx is not None:
                ctx.accepting = False
                node.async_ctx = None
            # a straggler stashed during teardown must not sit until the
            # next experiment (its TTL bounds the damage; this bounds the
            # memory)
            node.take_async_stash()
            try:
                RoundFinishedStage._flush_round_metrics(node)
            except Exception:  # noqa: BLE001 — abort-path flush never masks the exit
                pass
        # natural finish: final evaluation, clear state (mirrors
        # RoundFinishedStage's experiment-over path)
        metrics = node.learner.evaluate()
        for k, v in (metrics or {}).items():
            logger.log_metric(
                node.addr, k, float(v), round=state.round, experiment=state.experiment_name
            )
        logger.experiment_finished(node.addr)
        state.clear()

    # ---- phases ----

    def _local_loop(self, node: "Node", ctx: AsyncContext) -> None:
        from p2pfl_tpu.stages.learning_stages import RoundFinishedStage

        state = node.state
        budget = node.total_rounds
        for i in range(budget):
            if node.learning_interrupted():
                return
            # stall-watchdog + crash-at-stage seams, same as the FSM loop
            state.current_stage = "AsyncTrainStage"
            state.last_transition = time.monotonic()
            for hook in node.stage_hooks:
                hook(node, "AsyncTrainStage")
            # adopt the freshest global that arrived while training — the
            # pull happens HERE, on the learning thread, so the learner is
            # never mutated mid-fit by a handler thread
            pend = ctx.take_pending_global()
            if pend is not None:
                params, version = pend
                node.learner.set_parameters(params)
                ctx.base_version = version
            trace_id = (
                f"{state.experiment_name or 'exp'}:"
                f"{state.experiment_epoch}:u{i}"
            )
            with telemetry.span(
                node.addr,
                "AsyncTrainStage",
                kind="stage",
                attrs={
                    "round": i,
                    "experiment": state.experiment_name,
                    "base_version": ctx.base_version,
                },
                trace_id=trace_id,
            ):
                own = None
                if Settings.ROUND_FUSED and not node.learning_interrupted():
                    own = node.learner.fused_round()
                if own is None:
                    if node.learning_interrupted():
                        return
                    node.learner.fit()
                    own = node.learner.get_model_update()
                # the fused path's device-resident partial fold belongs to
                # the sync FedAvg seam; the buffer folds staleness-weighted
                own.partial_acc = None
                own.version = (node.addr, next(ctx.train_seq), ctx.base_version)
            if node.learning_interrupted():
                return
            # one batched metric flush per local update (fused path stash)
            RoundFinishedStage._flush_round_metrics(node)
            state.round = i + 1
            # the regular target is this node's regional; once that
            # regional is known dead the update re-routes to the global
            # root instead of feeding a corpse for the rest of the run
            target = ctx.push_target()
            if target == node.addr:
                ctx.execute_actions(ctx.handle_update(own))
            else:
                env = node.protocol.build_weights("async_update", i, own)
                ok = node.protocol.send(target, env, create_connection=True)
                # protocol.send skips breaker feedback on the
                # create_connection path — feed it explicitly, or a dead
                # aggregator's edges would never accelerate its eviction
                # (and with it the K-repair and re-route above)
                node.protocol._record_send_outcome(target, ok)
                if not ok:
                    # dropped, not retried: the next local update
                    # supersedes this one anyway
                    logger.log_comm_metric(node.addr, "async_push_fail")

    def _drain(self, node: "Node", ctx: AsyncContext) -> None:
        """Every node serves until the whole fleet is done or dead:
        aggregators keep merging slower members' tails, edges keep
        adopting the globals those tail merges mint — so in the common
        case the run ends with everyone holding the latest version.
        Bounded by ``ASYNC_DRAIN_TIMEOUT``; a dead member (eviction took
        it out of the overlay) releases the wait. Buffered-but-unflushed
        updates at exit are discarded — FedBuff semantics, a partial
        buffer is not a merge."""
        state = node.state
        others = set(ctx.topo.members) - {node.addr}
        deadline = time.monotonic() + Settings.ASYNC_DRAIN_TIMEOUT
        graceful = False
        tick = 0
        pushed_version = -1
        with telemetry.span(node.addr, "async_drain", kind="stage"):
            while time.monotonic() < deadline and not node.learning_interrupted():
                self._adopt_pending(node, ctx)
                # aggregators re-push the latest global so a dropped push
                # cannot strand a subtree at run end — when the VERSION
                # CHANGED since the last re-push, plus a slow (~2 s)
                # fallback cadence covering the dropped-re-push case
                # (every tick would fan the full model out 20×/s for
                # children that just drop it as stale)
                with ctx.lock:
                    current = ctx.last_global[1] if ctx.last_global else -1
                if current != pushed_version or tick % 40 == 0:
                    ctx.execute_actions(ctx.final_sync_actions())
                    pushed_version = current
                tick += 1
                with state.status_merge_lock:
                    done = set(state.async_done_peers)
                live = set(node.protocol.get_neighbors(only_direct=False))
                waiting = {m for m in others if m not in done and m in live}
                if not waiting:
                    graceful = True
                    break
                time.sleep(0.05)
            if graceful:
                # grace window: merges triggered by the LAST members' final
                # updates are still propagating down the tiers
                time.sleep(min(0.5, Settings.ASYNC_DRAIN_TIMEOUT / 10))
                ctx.execute_actions(ctx.final_sync_actions())
                time.sleep(0.1)
            else:
                logger.info(
                    node.addr,
                    "Async drain window closed with members still pending — exiting",
                )
            self._adopt_pending(node, ctx)

    @staticmethod
    def _adopt_pending(node: "Node", ctx: AsyncContext) -> None:
        pend = ctx.take_pending_global()
        if pend is not None:
            params, version = pend
            node.learner.set_parameters(params)
            ctx.base_version = version
