"""The async learning workflow: train → push → merge, no round barrier.

Selected by ``Settings.FEDERATION_MODE == "async"`` in
``Node._run_learning`` — the learning thread runs this instead of the
stages FSM. Control flow per node:

1. **Init sync** — identical to the sync plane
   (``stages.learning_stages.sync_initial_model``): everyone starts from
   the initiator's weights, version 0. A node *joining* a running
   experiment (``Node.join_async_experiment``) skips this and instead
   bootstraps by pulling the nearest aggregator's current global
   (``async_pull``) before contributing.
2. **Topology** — every node derives the same
   :class:`~p2pfl_tpu.federation.routing.TierRouter` from its sorted
   membership view (``Settings.HIER_CLUSTER_SIZE``) — and RE-derives it
   on every membership event: a join, a graceful leave (``async_leave``)
   or an eviction is a topology change, handled by migrating buffer
   state (promotion seeds from the version high-water mark, demotion
   flushes-or-forwards its partial buffer) rather than restarting.
3. **Local loop** — each node trains ``total_rounds`` local updates
   (reusing the fused-round learner path where the learner supports it),
   stamps each with its version triple, and pushes it to its cluster's
   regional aggregator. Between updates it adopts the freshest global
   model that arrived (``async_model`` push) — it never *waits* for one.
4. **Aggregation duties** — regional/global buffers
   (:class:`~p2pfl_tpu.federation.buffer.BufferedAggregator`) run inside
   the receive handlers (``commands/federation.py``): a flush at a
   regional pushes ONE aggregate up; a flush at the global root mints a
   new global version and pushes it down the tiers. When the root dies,
   the next-sorted live regional self-elects as successor root (the same
   zero-coordination derivation) and resumes minting above the high-water
   mark carried in the "vv" triples, so versions never regress.
5. **Drain** — a node that finished its budget broadcasts ``async_done``;
   aggregators keep serving until every member is done or dead (bounded
   by ``Settings.ASYNC_DRAIN_TIMEOUT``), so slow members' tails still
   merge.

Every push rides ``protocol.send`` / the gossiper's concurrent dispatch
pool over the single ``_do_send`` seam — FaultPlan chaos, breaker-fed
eviction, retry accounting and telemetry send spans all apply unchanged.
Fan-outs (a fresh global to N children) go through
``Gossiper._dispatch_sends`` so one slow child costs a worker slot, not
the push.

Not composed in this control plane (guarded loudly at start):
``SECURE_AGGREGATION`` (pairwise masks need a fixed cohort per merge —
a buffer of whoever-arrived breaks cancellation) and
``WIRE_COMPRESSION="topk8"`` (delta anchors are pinned per sync round;
the async plane has no shared round to anchor on). Dense and ``int8``
wire compression work as-is.
"""

from __future__ import annotations

import threading
import time
from typing import TYPE_CHECKING, Any, List, Optional, Tuple

from p2pfl_tpu.federation.buffer import BufferedAggregator, FlushResult
from p2pfl_tpu.federation.durability import SeqCounter, rebuild_updates
from p2pfl_tpu.federation.routing import TierRouter, VersionHighWater
from p2pfl_tpu.federation.staleness import as_version, xp_mismatch
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.management.telemetry import telemetry
from p2pfl_tpu.settings import Settings

if TYPE_CHECKING:
    from p2pfl_tpu.node import Node

Pytree = Any

#: outbound action: (weights command, target address, update)
Action = Tuple[str, str, ModelUpdate]




class AsyncContext:
    """Per-experiment async state attached to the node (``node.async_ctx``).

    Owns the node's aggregation buffers (placed by the
    :class:`~p2pfl_tpu.federation.routing.TierRouter`'s buffer plan) and
    the freshest-global mailbox. The router is swapped — never mutated —
    on every membership event, and :meth:`_reconcile_locked` migrates the
    buffers to the new plan. The locking contract that keeps the
    in-memory transport's synchronous delivery chains deadlock-free:
    **no lock is ever held across a send** — handlers compute under
    locks, collect :data:`Action` tuples, and :meth:`execute_actions`
    runs outside every lock (the context lock is an RLock so flush
    propagation can nest under a reconcile).
    """

    def __init__(
        self,
        node: "Node",
        router: TierRouter,
        params: Pytree,
        xid: Optional[str] = None,
        joining: bool = False,
    ) -> None:
        self.node = node
        self.addr = node.addr
        self.lock = threading.RLock()
        self.accepting = True
        self.router = router
        #: every member ever observed (monotone — dead members keep their
        #: cluster slots as holes, the bounded-disruption contract)
        self.members = set(router.topo.members)
        self._dead = set(router.dead)
        #: experiment identity stamped on the wire ("xp" header); a joiner
        #: starts without one and adopts it from its bootstrap global
        self.xid = xid
        #: the newest global version this node KNOWS about (its learner
        #: may lag until the loop adopts pending_global). A joiner starts
        #: at -1 so a version-0 bootstrap global still passes the adopt
        #: gate (an experiment whose root has not minted yet).
        self.global_version = -1 if joining else 0
        #: the version the learner's current params came from — what the
        #: node stamps as base_version on its next update
        self.base_version = 0
        #: highest global version ever OBSERVED (adoptions + the
        #: base_version of every "vv" triple passing through) — what a
        #: successor root seeds its minting from (routing.py docs)
        self.high_water = VersionHighWater()
        self.pending_global: Optional[Tuple[Pytree, int]] = None
        #: last adopted/minted global (params, version) — what the drain's
        #: final-sync re-pushes carry
        self.last_global: Optional[Tuple[Pytree, int]] = None
        #: encode-once for drain re-pushes: one ModelUpdate per version,
        #: reused across ticks/children so byte transports serialize the
        #: full model once per version, not once per re-push fan-out
        self._final_push: Optional[Tuple[int, ModelUpdate]] = None
        #: experiment-start params — seeds promoted buffers before any
        #: global exists
        self._init_params = params
        #: set by a membership re-derivation; the workflow drains the
        #: async stash when it observes it (a stashed update may be
        #: routable under the new roles)
        self._stash_dirty = False
        #: counts every async_model that passed the experiment gates —
        #: lets a pull's wait loop stop as soon as the reply ARRIVED,
        #: even when its version is one the adopt gate rejects as held
        self.models_seen = 0
        #: this node's most recent own training update / upward
        #: aggregate: when a re-derivation CHANGES the push target (the
        #: old aggregator died), the last push may have died with it —
        #: mid-run the next update supersedes it, but near the run's end
        #: nothing does, so the re-derivation re-pushes it to the
        #: successor (the update-plane twin of the drain's final-sync
        #: model re-push; the successor's version vector drops the copy
        #: if the original somehow also arrived)
        self.last_own_update: Optional[ModelUpdate] = None
        self.last_up_push: Optional[ModelUpdate] = None
        #: the aggregator a joiner is pulling its bootstrap global from —
        #: while set, async_model is accepted ONLY from it (and the
        #: experiment identity is adopted only from it): a previous
        #: experiment's redelivered straggler must not seed the joiner's
        #: model or bind it to the wrong xid while its adopt gate is at
        #: -1. Cleared when the bootstrap window closes.
        self._bootstrap_from: Optional[str] = None
        #: per-node monotone counters: training updates vs upward
        #: regional aggregates are deduped in DIFFERENT version vectors,
        #: but each stream must be monotone on its own — and must survive
        #: role changes (a re-promoted aggregator continuing at seq 1
        #: would be rejected as a replay by its parent's version vector).
        #: SeqCounter (not itertools.count) so the journal can read the
        #: stream position and a resurrection can resume strictly past it
        self.train_seq = SeqCounter(1)
        self._up_seq = SeqCounter(1)
        self.rbuf: Optional[BufferedAggregator] = None
        self.gbuf: Optional[BufferedAggregator] = None
        self._apply_initial_plan()

    def _apply_initial_plan(self) -> None:
        plan = self.router.buffer_plan(self.addr, Settings.FEDBUFF_K)
        defense = self.node.defense
        if plan.regional_k is not None:
            self.rbuf = BufferedAggregator(
                self.addr, self._init_params, k=plan.regional_k,
                bump_on_flush=False, defense=defense,
            )
        if plan.global_k is not None:
            self.gbuf = BufferedAggregator(
                self.addr, self._init_params, k=plan.global_k, defense=defense
            )

    @property
    def is_aggregator(self) -> bool:
        return self.rbuf is not None or self.gbuf is not None

    # ---- mailbox ----

    def take_pending_global(self) -> Optional[Tuple[Pytree, int]]:
        with self.lock:
            pend, self.pending_global = self.pending_global, None
        return pend

    def _adopt(self, params: Pytree, version: int) -> bool:
        """Record a newer global: mailbox for the learner + regional
        buffer re-base. False for stale pushes."""
        with self.lock:
            if version <= self.global_version:
                return False
            self.global_version = version
            self.high_water.observe(version)
            self.pending_global = (params, version)
            self.last_global = (params, version)
            rbuf = self.rbuf
        if rbuf is not None:
            rbuf.set_global(params, version)
        return True

    # ---- membership events (joins, leaves, evictions) ----

    def add_member(self, addr: str) -> List[Action]:
        """A joiner ANNOUNCED itself (``async_join``, TTL-flooded): fold
        it into the membership and re-derive.

        Membership is MONOTONE: joiners are added on their announcement
        (mere overlay presence is NOT membership — a monitor or a
        not-yet-joined node connecting mid-run must not be elected
        aggregator and blackhole a tier), departures are handled by
        :meth:`mark_dead` (eviction / ``async_leave``) so dead members
        keep their cluster slots as holes. Returns the buffer-migration
        sends the re-derivation produced."""
        with self.lock:
            if addr in self.members:
                return []
            self.members.add(addr)
            return self._rederive_locked("join", {"joined": [addr]})

    def merge_view(self, members, dead) -> List[Action]:
        """Fold a peer's ``(members, dead)`` view in (monotone union) —
        the ``async_view`` reply a bootstrap pull carries.

        A joiner's own heartbeat view lacks the dead members every
        survivor keeps as cluster HOLES (a corpse evicted before the
        join never enters the joiner's overlay view), so deriving only
        from its live view would chunk clusters differently from the
        rest of the fleet — permanently. Merging the serving
        aggregator's view restores the shared derivation."""
        with self.lock:
            new_members = set(members) - self.members
            new_dead = {
                d for d in dead if d != self.addr and d not in self._dead
            }
            if not new_members and not new_dead:
                return []
            self.members |= new_members | set(new_dead)
            self._dead |= new_dead
            return self._rederive_locked(
                "view_merge",
                {"joined": sorted(new_members), "dead": sorted(new_dead)},
            )

    def mark_dead(self, addr: str, reason: str = "evicted") -> List[Action]:
        """A member died or left: re-derive the topology with it as a
        hole. Successor roles self-elect in the re-derivation (the next
        live member of a dead regional's cluster; the next live regional
        for a dead root), K clamps shrink to the live fan-in (may fire
        the flush the corpse was blocking — the eviction-repair
        contract), and this node's own buffers migrate to its new plan.
        Returns the sends all of that produced."""
        with self.lock:
            if addr == self.addr or addr in self._dead or addr not in self.members:
                return []
            self._dead.add(addr)
            return self._rederive_locked(reason, {"member": addr})

    def _rederive_locked(self, event: str, attrs: dict) -> List[Action]:
        old = self.router
        self.router = TierRouter(self.members, old.cluster_size, dead=self._dead)
        new = self.router
        logger.log_comm_metric(self.addr, "membership_changed")
        telemetry.event(
            self.addr,
            "membership_changed",
            kind="stage",
            attrs={
                "event": event,
                "members": len(self.members),
                "dead": len(self._dead),
                **attrs,
            },
        )
        old_role, new_role = old.role(self.addr), new.role(self.addr)
        if old_role != new_role:
            logger.log_comm_metric(self.addr, "role_changed")
            telemetry.event(
                self.addr,
                "role_changed",
                kind="stage",
                attrs={"from": old_role, "to": new_role},
            )
            logger.info(self.addr, f"Async role change: {old_role} → {new_role} ({event})")
        if new.root == self.addr and old.root != self.addr:
            floor = max(self.global_version, self.high_water.mark)
            logger.log_comm_metric(self.addr, "root_failover")
            telemetry.event(
                self.addr,
                "root_failover",
                kind="stage",
                attrs={"old_root": old.root, "seed_version": floor},
            )
            logger.warning(
                self.addr,
                f"Global-root failover: {old.root} → {self.addr} "
                f"(minting resumes above v{floor})",
            )
        self._stash_dirty = True
        actions = self._reconcile_locked(new)
        # the update-plane twin of the final-sync re-push: a changed push
        # target means the old aggregator (and whatever of ours it held)
        # is gone — hand the successor our freshest contribution; its
        # version vector dedups any copy that survived
        if (
            old.push_target(self.addr) != new.push_target(self.addr)
            and self.last_own_update is not None
        ):
            target = new.push_target(self.addr)
            if target is not None:
                actions.append(("async_update", target, self.last_own_update))
        if (
            old.root != new.root
            and self.last_up_push is not None
            and new.root is not None
            and new.root != self.addr
        ):
            actions.append(("async_update", new.root, self.last_up_push))
        return actions

    def _global_snapshot_locked(self) -> Tuple[Pytree, int]:
        if self.last_global is not None:
            return self.last_global
        return self._init_params, 0

    def _reconcile_locked(self, router: TierRouter) -> List[Action]:
        """Migrate this node's buffers to the new router's plan by
        executing the SHARED reconcile contract
        (:meth:`TierRouter.reconcile_ops` — the simulator executes the
        same ops, so promotion seeding, demotion forwarding and K
        re-clamps cannot drift between drivers)."""
        actions: List[Action] = []
        ops = router.reconcile_ops(
            self.addr,
            Settings.FEDBUFF_K,
            self.rbuf is not None,
            self.gbuf is not None,
        )
        for op in ops:
            regional = op.tier == "regional"
            if op.op == "forward":
                buf = self.rbuf if regional else self.gbuf
                pending = buf.take_pending()
                if regional:
                    self.rbuf = None
                else:
                    self.gbuf = None
                if pending and op.target is not None and op.target != self.addr:
                    logger.log_comm_metric(
                        self.addr, "async_buffer_migrated", len(pending)
                    )
                    actions += [("async_update", op.target, u) for u in pending]
            elif op.op == "create":
                params, version = self._global_snapshot_locked()
                if regional:
                    self.rbuf = BufferedAggregator(
                        self.addr, params, k=op.k, bump_on_flush=False,
                        defense=self.node.defense,
                    )
                    if version > 0:
                        self.rbuf.set_global(params, version)
                else:
                    floor = max(version, self.global_version, self.high_water.mark)
                    self.gbuf = BufferedAggregator(
                        self.addr, params, k=op.k, defense=self.node.defense
                    )
                    if floor > 0:
                        self.gbuf.set_global(params, floor)
            else:  # resize
                buf = self.rbuf if regional else self.gbuf
                res = buf.set_k(op.k)
                if res:
                    logger.log_comm_metric(self.addr, "async_k_repair")
                    actions += (
                        self._regional_flush(res) if regional else self._global_flush(res)
                    )
        return actions

    def take_stash_dirty(self) -> bool:
        with self.lock:
            dirty, self._stash_dirty = self._stash_dirty, False
        return dirty

    # ---- crash-resurrection (federation/durability.py) ----

    def restore_from_journal(self, snap) -> List[Action]:
        """Re-arm this context from a recovered journal snapshot — the
        resurrection's second half, run on the learning thread right
        after the stash drain and BEFORE the elastic bootstrap join.

        Restores, in order: the journaled ``(members, dead)`` view
        (monotone union + re-derive, exactly like ``merge_view`` — the
        resurrectee's fresh heartbeat view lacks the dead members every
        survivor keeps as cluster holes); the version state (high-water,
        adopted global pre-seeded into the mailbox so ``_bootstrap_join``
        returns instantly and the pull only fetches anything NEWER the
        fleet minted meanwhile); the own-sequence counters, resumed
        strictly past the journaled position plus
        ``Settings.JOURNAL_SEQ_MARGIN`` (covers updates minted after the
        last snapshot but before the crash — upstream VersionVectors
        treat the gap as lost updates, never as replays); each journaled
        buffer tier (version floor + VV marks + pending re-buffered, or
        — when the restart's re-derivation demoted this node — the
        pending successor-forwarded raw with original triples, the PR-11
        migration idiom); and the Byzantine suspicion/quarantine state.
        Returns the actions all of that produced (possible flushes,
        migration forwards) for the caller to execute outside the lock.
        """
        actions: List[Action] = []
        with self.lock:
            new_members = set(snap.members) - self.members
            new_dead = {
                d for d in snap.dead if d != self.addr and d not in self._dead
            }
            if new_members or new_dead:
                self.members |= new_members | set(new_dead)
                self._dead |= new_dead
                actions += self._rederive_locked(
                    "journal_recover",
                    {"joined": sorted(new_members), "dead": sorted(new_dead)},
                )
            if (
                snap.global_params is not None
                and snap.global_version > self.global_version
            ):
                self.global_version = snap.global_version
                self.pending_global = (snap.global_params, snap.global_version)
                self.last_global = (snap.global_params, snap.global_version)
            self.base_version = max(self.base_version, snap.base_version)
            self.high_water.observe(snap.high_water)
            margin = max(0, int(Settings.JOURNAL_SEQ_MARGIN))
            self.train_seq = SeqCounter(
                max(self.train_seq.next_value, snap.train_seq + margin)
            )
            self._up_seq = SeqCounter(
                max(self._up_seq.next_value, snap.up_seq + margin)
            )
            rbuf = self.rbuf
            if rbuf is not None and self.last_global is not None:
                rbuf.set_global(*self.last_global)
            for bj in snap.buffers:
                regional = bj.tier == "regional"
                buf = self.rbuf if regional else self.gbuf
                updates = rebuild_updates(bj, self.xid)
                if buf is not None:
                    res = buf.restore_journal(bj.version, bj.vv, updates)
                    if res:
                        actions += (
                            self._regional_flush(res)
                            if regional
                            else self._global_flush(res)
                        )
                elif updates:
                    # the restart landed this node in a smaller role than
                    # it died in: forward the journaled pending raw to the
                    # successor tier, original triples intact — its own
                    # version vector re-dedups any copy that also reached
                    # it directly while we were dead
                    target = (
                        self.router.push_target(self.addr)
                        if regional
                        else self.router.root
                    )
                    if target is not None:
                        logger.log_comm_metric(
                            self.addr, "async_buffer_migrated", len(updates)
                        )
                        actions += [("async_update", target, u) for u in updates]
            restored_pending = sum(len(b.pending) for b in snap.buffers)
        self.node.defense.restore(snap.suspicion, snap.quarantined)
        logger.log_comm_metric(self.addr, "journal_restored")
        telemetry.event(
            self.addr,
            "journal_restored",
            kind="stage",
            attrs={
                "snap": snap.snap,
                "version": snap.global_version,
                "pending": restored_pending,
                "train_seq": snap.train_seq,
                "up_seq": snap.up_seq,
            },
        )
        return actions

    # ---- receive paths (commands + local offers) ----

    def handle_update(self, update: ModelUpdate, source: Optional[str] = None) -> List[Action]:
        """Route a contribution into the buffer the router names; returns
        the sends its flush (if any) produced. An update this node holds
        no buffer for in its CURRENT view is stashed, not dropped — the
        sender's view may be ahead of ours (we are about to observe the
        death that promotes us).

        ``source`` is the DELIVERING peer (the wire envelope's sender;
        None only for this node's own local offers). The Byzantine screen
        attributes rejections to it, NOT to the in-payload version
        origin: the origin is attacker-controlled, and keying suspicion
        on it would let a lying sender frame (and get evicted) an honest
        node. Origin != source legitimately only on buffer-migration
        forwards — which the forwarder already screened at its own offer,
        so clean forwards indict nobody and a poisoned forward indicts
        the forwarder (federation/defense.py threat model)."""
        ver = as_version(update.version)
        with self.lock:
            # cross-experiment straggler (a retried/duplicated tail from
            # a previous run): the buffer's version vector has never seen
            # its (origin, seq), so without this gate it would merge
            # stale-experiment params at full weight — the exact residual
            # the "xp" header was minted to close
            if xp_mismatch(self.addr, update.xp, self.xid):
                return []
            in_origin = ver.origin if ver is not None else (
                update.contributors[0] if update.contributors else None
            )
            defense = self.node.defense
            if (source is not None and defense.is_quarantined(source)) or (
                in_origin is not None and defense.is_quarantined(in_origin)
            ):
                # a quarantined attacker keeps talking (its control plane
                # is healthy): drop whatever it DELIVERS (source) and
                # whatever claims to ORIGINATE from it (its content is
                # suspect even when an honest aggregator forwards it)
                # before it can stash, inflate the high-water or reach a
                # buffer
                logger.log_comm_metric(self.addr, "byz_quarantined_drop")
                return []
            if (
                ver is not None
                and ver.base_version - self.global_version
                <= Settings.ASYNC_MAX_STALENESS
            ):
                # the promotion floor only trusts base_versions within the
                # staleness bound of our own view — an unvalidated triple
                # from a pre-xp cross-experiment straggler must not poison
                # a future successor's minting floor (same bound as the
                # buffer's counter jump)
                self.high_water.observe(ver.base_version)
            origin = ver.origin if ver is not None else (
                update.contributors[0] if update.contributors else self.addr
            )
            sink = self.router.update_sink(self.addr, origin)
            if sink == "global" and self.gbuf is not None:
                res = self.gbuf.offer(update, screen_origin=source)
                return self._global_flush(res) if res else []
            if sink == "regional" and self.rbuf is not None:
                res = self.rbuf.offer(update, screen_origin=source)
                return self._regional_flush(res) if res else []
        self.node.stash_async_update(update, source)
        logger.log_comm_metric(self.addr, "async_routed_stash")
        logger.debug(
            self.addr,
            "async_update received with no matching buffer in the current "
            "view — stashed for a role change",
        )
        return []

    def live_children(self) -> List[str]:
        """This node's push-down fan-out under the current view (the
        router already removed dead members and re-elected successors)."""
        with self.lock:
            return self.router.live_children(self.addr)

    def push_target(self) -> str:
        """Where this node's training updates go: its cluster's live
        regional (possibly itself — offer locally then). Successor
        regionals/roots are already folded into the router's view."""
        with self.lock:
            target = self.router.push_target(self.addr)
        return target if target is not None else self.addr

    def handle_model(self, update: ModelUpdate, source: str) -> List[Action]:
        """A fresh global pushed down from above: adopt + forward one
        tier further down."""
        ver = as_version(update.version)
        version = ver.base_version if ver is not None else 0
        with self.lock:
            # cross-experiment global (see handle_update's gate)
            if xp_mismatch(self.addr, update.xp, self.xid):
                return []
            if self._bootstrap_from is not None and source != self._bootstrap_from:
                # bootstrap window: the joiner's adopt gate sits at -1,
                # so ANY straggler (e.g. a previous experiment's
                # redelivered async_model, which a still-None xid cannot
                # filter) would win — accept only the pulled aggregator's
                # reply until the window closes
                logger.log_comm_metric(self.addr, "async_model_dropped")
                return []
            if (
                self.xid is None
                and update.xp is not None
                and (self._bootstrap_from is None or self._bootstrap_from == source)
            ):
                # a joiner adopts the running experiment's identity from
                # its bootstrap global (it never saw start_learning) — or,
                # when the bootstrap pull failed entirely (both targets
                # were corpses mid-failover), from the first global that
                # passes the gates after the window: staying id-less for
                # the whole run would leave this node's frames unfiltered
                # and, if later promoted, reopen the cross-experiment
                # residual at its aggregation tier
                self.xid = update.xp
                self.node.state.experiment_xid = update.xp
                self.node.protocol.experiment_xid = update.xp
            self.models_seen += 1
        if not self._adopt(update.params, version):
            logger.log_comm_metric(self.addr, "async_model_stale")
            return []
        logger.log_comm_metric(self.addr, "async_model_adopt")
        telemetry.event(
            self.addr, "async_model_adopt", kind="stage", attrs={"version": version}
        )
        return [
            ("async_model", child, update)
            for child in self.live_children()
            if child != source
        ]

    # ---- flush propagation ----

    def _regional_flush(self, res: FlushResult) -> List[Action]:
        """A regional buffer filled: one merged aggregate goes UP."""
        with self.lock:
            upd = ModelUpdate(res.params, res.contributors, res.num_samples)
            upd.version = (self.addr, next(self._up_seq), res.version)
            upd.xp = self.xid
            if self.gbuf is not None:  # the root's own cluster feeding its global tier
                gres = self.gbuf.offer(upd)
                return self._global_flush(gres) if gres else []
            self.last_up_push = upd
            root = self.router.root
        if root is None or root == self.addr:
            return []
        return [("async_update", root, upd)]

    def _global_flush(self, res: FlushResult) -> List[Action]:
        """The global buffer filled: a new global version exists — adopt
        locally and push it down every child tier."""
        self._adopt(res.params, res.version)
        with self.lock:
            upd = ModelUpdate(res.params, [self.addr], 1)
            upd.version = (self.addr, res.version, res.version)
            upd.xp = self.xid
        return [("async_model", child, upd) for child in self.live_children()]

    # ---- join / leave support ----

    def pull_target(self) -> Optional[str]:
        """Who a joiner bootstraps from: the global root, or (when the
        joiner itself re-derived as root) any other live member."""
        with self.lock:
            root = self.router.root
            if root is not None and root != self.addr:
                return root
            others = [m for m in self.router.live_members if m != self.addr]
        return others[0] if others else None

    def bootstrap_reply(self, requester: str) -> List[Action]:
        """Answer an ``async_pull``: push the current global (or the
        experiment-start params at version 0 when nothing was minted yet
        — a joiner's adopt gate starts at -1, so even that seeds it).
        Reuses the drain's encode-once per-version update, so a whole
        fleet's exit pulls serialize the model once per version, not once
        per reply."""
        with self.lock:
            params, version = self._global_snapshot_locked()
            if self._final_push is not None and self._final_push[0] == version:
                upd = self._final_push[1]
            else:
                upd = ModelUpdate(params, [self.addr], 1)
                upd.version = (self.addr, version, version)
                upd.xp = self.xid
                self._final_push = (version, upd)
        return [("async_model", requester, upd)]

    def view_snapshot(self):
        """The ``(members, dead)`` lists an ``async_view`` reply ships —
        the one public reader of the membership state (the command layer
        must not reach into the context's privates)."""
        with self.lock:
            return sorted(self.members), sorted(self._dead)

    def graceful_leave_actions(self) -> List[Action]:
        """Everything this node must hand off before leaving: partial
        buffers forward raw to the successor tiers derived from the
        post-leave view (the same self-election every survivor will
        derive once the ``async_leave`` lands)."""
        with self.lock:
            post = TierRouter(
                self.members, self.router.cluster_size, dead=self._dead | {self.addr}
            )
            actions: List[Action] = []
            if self.rbuf is not None:
                pending = self.rbuf.take_pending()
                self.rbuf = None
                target = post.push_target(self.addr)
                if pending and target is not None:
                    logger.log_comm_metric(
                        self.addr, "async_buffer_migrated", len(pending)
                    )
                    actions += [("async_update", target, u) for u in pending]
            if self.gbuf is not None:
                pending = self.gbuf.take_pending()
                self.gbuf = None
                if pending and post.root is not None:
                    logger.log_comm_metric(
                        self.addr, "async_buffer_migrated", len(pending)
                    )
                    actions += [("async_update", post.root, u) for u in pending]
            # hand the successor tiers the freshest global we hold: the
            # leaver may be the only node that adopted the last mint
            lg = self.last_global
            if lg is not None:
                params, version = lg
                upd = ModelUpdate(params, [self.addr], 1)
                upd.version = (self.addr, version, version)
                upd.xp = self.xid
                targets = set(post.regionals) | set(
                    self.router.live_children(self.addr)
                )
                targets.discard(self.addr)
                actions += [("async_model", t, upd) for t in sorted(targets)]
        return actions

    # ---- repair + drain support ----

    def final_sync_actions(self) -> List[Action]:
        """Re-push the last-known global to this node's children (drain
        phase): a fresh-global push is fire-and-forget — superseded by the
        next merge in steady state — but at the END of a run there is no
        next merge, so a single dropped push would strand a subtree on an
        old version. Children already at the version ignore it."""
        children = self.live_children()
        with self.lock:
            lg = self.last_global
            if lg is None or not children:
                return []
            params, version = lg
            if self._final_push is not None and self._final_push[0] == version:
                upd = self._final_push[1]  # encode-once: reuse across ticks
            else:
                upd = ModelUpdate(params, [self.addr], 1)
                upd.version = (self.addr, version, version)
                upd.xp = self.xid
                self._final_push = (version, upd)
        return [("async_model", child, upd) for child in children]

    # ---- outbound ----

    def execute_actions(self, actions: List[Action]) -> None:
        """Send the collected pushes through the gossiper's concurrent
        dispatch pool (stalled-peer skip, per-send budget, breaker
        feedback) — one slow child must not serialize a global push.
        Actions targeting THIS node (a buffer migration whose successor
        is the migrating node's other tier) feed back through
        :meth:`handle_update` instead of the wire."""
        proto = self.node.protocol
        while actions:
            sends, local = [], []
            for cmd, target, upd in actions:
                if target == self.addr:
                    local.append(upd)
                    continue
                ver = as_version(upd.version)
                sends.append(
                    (target, proto.build_weights(cmd, ver.seq if ver else 0, upd))
                )
            if sends:
                results, skipped = proto.gossiper._dispatch_sends(
                    sends, create_connection=True
                )
                for ok in results:
                    if ok is False:
                        logger.log_comm_metric(self.addr, "async_push_fail")
                if skipped:
                    logger.log_comm_metric(self.addr, "async_push_skipped", len(skipped))
            actions = []
            for upd in local:
                # self-delivery (a migration whose successor is this
                # node's other tier): already screened when first
                # admitted — attribute to self, never to the in-payload
                # origin (the screen's self-exemption)
                actions += self.handle_update(upd, source=self.addr)


class AsyncLearningWorkflow:
    """Drives one node's async experiment end to end (see module docs)."""

    def run(self, node: "Node") -> None:
        from p2pfl_tpu.communication.faults import FaultCrash
        from p2pfl_tpu.stages.learning_stages import (
            RoundFinishedStage,
            sync_initial_model,
        )

        state = node.state
        joining = node.consume_async_join()
        node._last_async_global = None  # the previous experiment's result
        state.set_experiment(
            node.experiment_name, node.total_rounds, xid=node._pending_xid
        )
        node.protocol.experiment_xid = state.experiment_xid
        logger.experiment_started(node.addr)
        node.learner.set_epochs(node.epochs)
        node.learner.set_addr(node.addr)
        node.learner.pop_round_metrics()

        if Settings.SECURE_AGGREGATION:
            logger.error(
                node.addr,
                "FEDERATION_MODE='async' does not compose with "
                "SECURE_AGGREGATION (pairwise masks need a fixed cohort "
                "per merge; a staleness-weighted buffer breaks exact "
                "cancellation) — aborting the experiment",
            )
            state.clear()
            return
        if Settings.WIRE_COMPRESSION == "topk8":
            logger.error(
                node.addr,
                "FEDERATION_MODE='async' does not support topk8 wire "
                "compression (delta anchors are pinned per sync round; "
                "the async plane has no shared round) — aborting; use "
                "'none' or 'int8'",
            )
            state.clear()
            return

        ctx: Optional[AsyncContext] = None
        left = False
        try:
            if not joining and not sync_initial_model(node):
                return
            # let heartbeats flood so every node derives the topology from
            # the same membership (agreement on membership IS agreement on
            # topology — the deterministic-derivation trick)
            time.sleep(Settings.WAIT_HEARTBEATS_CONVERGENCE)
            members = sorted(
                set(node.protocol.get_neighbors(only_direct=False)) | {node.addr}
            )
            router = TierRouter(members, Settings.HIER_CLUSTER_SIZE)
            ctx = AsyncContext(
                node,
                router,
                node.learner.get_parameters(),
                xid=state.experiment_xid,
                joining=joining,
            )
            node.async_ctx = ctx
            logger.info(
                node.addr,
                f"Async federation: role={router.role(node.addr)} "
                f"topology={router.describe()}",
            )
            # drain updates that raced ahead of the context (fast edges
            # finishing their first local update during our init gossip);
            # the stash's xp/epoch/TTL filters already dropped a previous
            # experiment's retried stragglers
            from p2pfl_tpu.commands.federation import drain_async_stash

            drain_async_stash(node, ctx)
            # crash-resurrection: restore buffers/counters/membership from
            # the recovered journal BEFORE the bootstrap join — the
            # journaled global pre-seeds the mailbox, so the join's pull
            # wait returns instantly and only fetches anything newer
            snap = node.consume_resume_snapshot()
            if snap is not None:
                ctx.execute_actions(ctx.restore_from_journal(snap))
            if joining:
                self._bootstrap_join(node, ctx)
            self._local_loop(node, ctx)
            if node.learning_interrupted():
                return
            if node.async_leave_requested():
                # graceful leave: hand off buffers + the freshest global,
                # announce, and skip the drain — survivors re-derive the
                # topology around the hole and keep going
                left = True
                ctx.execute_actions(ctx.graceful_leave_actions())
                node.protocol.broadcast(node.protocol.build_msg("async_leave"))
                node.protocol.broadcast(node.protocol.build_msg("async_done"))
                logger.log_comm_metric(node.addr, "async_left")
                logger.info(node.addr, "Left the async experiment gracefully")
            else:
                node.protocol.broadcast(node.protocol.build_msg("async_done"))
                self._drain(node, ctx)
            # final snapshot: the journal's recovery point covers the
            # drain's late adoptions too (a crash after this line resumes
            # with the experiment's end state, not one update behind)
            if node.journal is not None:
                self._journal_snapshot(node, ctx)
            # the experiment's RESULT is the latest global model this node
            # knows — not its local tail update (which it already pushed;
            # whether that merged or was discarded with a partial buffer,
            # the canonical fleet model is the last minted version), so
            # every node's final evaluation measures the same model modulo
            # lost pushes
            with ctx.lock:
                lg = ctx.last_global
            if lg is not None and not node.learning_interrupted():
                node.learner.set_parameters(lg[0])
                # keep the result servable after this context dies: a
                # peer's exit pull (async_pull after ITS drain found no
                # global) may arrive once we are already torn down
                node._last_async_global = (lg[0], lg[1], ctx.xid)
        except FaultCrash as exc:
            # injected hard crash: stop executing like a killed process —
            # no drain, no metrics flush, no state.clear
            if node.learner is not None:
                node.learner.pop_round_metrics()
            logger.info(node.addr, f"{exc}")
            return
        except Exception as exc:  # noqa: BLE001 — workflow failure ends learning, not the node
            if node.learning_interrupted():
                logger.info(node.addr, "Async learning interrupted")
            else:
                logger.error(node.addr, f"Async workflow failed: {exc!r}")
                state.clear()
            return
        finally:
            if ctx is not None:
                ctx.accepting = False
                node.async_ctx = None
            # a straggler stashed during teardown must not sit until the
            # next experiment (its xp/TTL bounds the damage; this bounds
            # the memory)
            node.take_async_stash()
            try:
                RoundFinishedStage._flush_round_metrics(node)
            except Exception:  # noqa: BLE001 — abort-path flush never masks the exit
                pass
        # natural finish (or graceful leave): final evaluation, clear
        # state (mirrors RoundFinishedStage's experiment-over path)
        metrics = node.learner.evaluate()
        for k, v in (metrics or {}).items():
            logger.log_metric(
                node.addr, k, float(v), round=state.round, experiment=state.experiment_name
            )
        logger.experiment_finished(node.addr)
        state.clear()
        if left:
            node._async_leave.clear()

    # ---- phases ----

    def _bootstrap_join(self, node: "Node", ctx: AsyncContext) -> None:
        """A joiner announces itself (``async_join`` — members fold it
        into the topology on that announcement, not on mere overlay
        presence) and pulls the nearest aggregator's current global
        before contributing, so its first update trains from the fleet's
        state instead of its own cold init. While the pull is in flight,
        ``async_model`` is accepted only from the pulled aggregator (the
        joiner's adopt gate sits at -1 — see ``_bootstrap_from``)."""
        node.protocol.broadcast(node.protocol.build_msg("async_join"))
        # up to two pull attempts: the first target may be a corpse the
        # joiner has not evicted yet (it can join DURING a failover — the
        # dead root is still in its fresh heartbeat view); by the second
        # attempt the eviction has usually landed and pull_target resolves
        # to the successor
        per_attempt = max(0.5, Settings.ASYNC_JOIN_TIMEOUT / 2)
        tried: set = set()
        for _attempt in range(2):
            target = ctx.pull_target()
            if target is None or target in tried:
                break
            tried.add(target)
            with ctx.lock:
                ctx._bootstrap_from = target
            node.protocol.send(
                target, node.protocol.build_msg("async_pull"), create_connection=True
            )
            deadline = time.monotonic() + per_attempt
            while time.monotonic() < deadline and not node.learning_interrupted():
                with ctx.lock:
                    if ctx.pending_global is not None:
                        break
                time.sleep(0.05)
            with ctx.lock:
                if ctx.pending_global is not None:
                    break
        with ctx.lock:
            bootstrapped = ctx.pending_global is not None
            ctx._bootstrap_from = None  # window closed: normal adoption
            if ctx.global_version < 0:
                ctx.global_version = 0  # nothing arrived: train from own init
        logger.log_comm_metric(node.addr, "async_join")
        telemetry.event(
            node.addr,
            "async_join",
            kind="stage",
            attrs={"bootstrapped": bootstrapped, "from": target},
        )
        if not bootstrapped:
            logger.warning(
                node.addr,
                "Join bootstrap pull produced no global within "
                "ASYNC_JOIN_TIMEOUT — contributing from local init",
            )

    def _local_loop(self, node: "Node", ctx: AsyncContext) -> None:
        from p2pfl_tpu.commands.federation import drain_async_stash
        from p2pfl_tpu.stages.learning_stages import RoundFinishedStage

        state = node.state
        budget = node.total_rounds
        for i in range(budget):
            if node.learning_interrupted() or node.async_leave_requested():
                return
            # membership events land on handler threads (async_join →
            # add_member, async_leave / eviction → mark_dead); here we
            # only drain the stash a role change may have made routable
            if ctx.take_stash_dirty():
                drain_async_stash(node, ctx)
            # stall-watchdog + crash-at-stage seams, same as the FSM loop
            state.current_stage = "AsyncTrainStage"
            state.last_transition = time.monotonic()
            for hook in node.stage_hooks:
                hook(node, "AsyncTrainStage")
            # adopt the freshest global that arrived while training — the
            # pull happens HERE, on the learning thread, so the learner is
            # never mutated mid-fit by a handler thread
            pend = ctx.take_pending_global()
            if pend is not None:
                params, version = pend
                node.learner.set_parameters(params)
                ctx.base_version = version
            trace_id = (
                f"{state.experiment_name or 'exp'}:"
                f"{state.experiment_epoch}:u{i}"
            )
            with telemetry.span(
                node.addr,
                "AsyncTrainStage",
                kind="stage",
                attrs={
                    "round": i,
                    "experiment": state.experiment_name,
                    "base_version": ctx.base_version,
                },
                trace_id=trace_id,
            ):
                own = None
                if Settings.ROUND_FUSED and not node.learning_interrupted():
                    own = node.learner.fused_round()
                if own is None:
                    if node.learning_interrupted():
                        return
                    node.learner.fit()
                    own = node.learner.get_model_update()
                # the fused path's device-resident partial fold belongs to
                # the sync FedAvg seam; the buffer folds staleness-weighted
                own.partial_acc = None
                own.version = (node.addr, next(ctx.train_seq), ctx.base_version)
                own.xp = ctx.xid
                with ctx.lock:
                    ctx.last_own_update = own
            if node.learning_interrupted():
                return
            # one batched metric flush per local update (fused path stash)
            RoundFinishedStage._flush_round_metrics(node)
            state.round = i + 1
            # the target is this node's cluster's LIVE regional under the
            # current view — a dead aggregator's successor (or, for a
            # fully dead cluster, the global root) is already folded in
            target = ctx.push_target()
            if target == node.addr:
                ctx.execute_actions(ctx.handle_update(own))
            else:
                env = node.protocol.build_weights("async_update", i, own)
                ok = node.protocol.send(target, env, create_connection=True)
                # protocol.send skips breaker feedback on the
                # create_connection path — feed it explicitly, or a dead
                # aggregator's edges would never accelerate its eviction
                # (and with it the successor election above)
                node.protocol._record_send_outcome(target, ok)
                if not ok:
                    # dropped, not retried: the next local update
                    # supersedes this one anyway
                    logger.log_comm_metric(node.addr, "async_push_fail")
            # durable recovery point AFTER the push: the journaled
            # train_seq then already counts the update just sent, so a
            # resurrection's seq margin only has to cover in-flight
            # duplicates, never a whole un-journaled update
            if (
                node.journal is not None
                and (i + 1) % max(1, int(Settings.JOURNAL_EVERY_N_UPDATES)) == 0
            ):
                self._journal_snapshot(node, ctx)

    @staticmethod
    def _journal_snapshot(node: "Node", ctx: AsyncContext) -> None:
        """Capture under the locks, commit OUTSIDE them (commit_snapshot
        is blocking disk I/O — p2pfl-check holds it to the same
        no-lock-across rule as a send). A failed snapshot is a logged
        gap in durability, never a crashed learning thread."""
        from p2pfl_tpu.federation.durability import capture_snapshot

        try:
            snap = capture_snapshot(node, ctx)
            node.journal.commit_snapshot(snap, learner=node.learner)
        except Exception as exc:  # noqa: BLE001 — durability must not take the node down
            logger.error(node.addr, f"Journal snapshot failed: {exc!r}")

    def _drain(self, node: "Node", ctx: AsyncContext) -> None:
        """Every node serves until the whole fleet is done or dead:
        aggregators keep merging slower members' tails, edges keep
        adopting the globals those tail merges mint — so in the common
        case the run ends with everyone holding the latest version.
        Bounded by ``ASYNC_DRAIN_TIMEOUT``; a dead member (eviction took
        it out of the overlay) or a graceful leaver releases the wait,
        and a member joining DURING the drain is waited on like anyone
        else (its updates still merge). Buffered-but-unflushed updates at
        exit are discarded — FedBuff semantics, a partial buffer is not a
        merge."""
        from p2pfl_tpu.commands.federation import drain_async_stash

        state = node.state
        deadline = time.monotonic() + Settings.ASYNC_DRAIN_TIMEOUT
        graceful = False
        tick = 0
        pushed_version = -1
        with telemetry.span(node.addr, "async_drain", kind="stage"):
            while time.monotonic() < deadline and not node.learning_interrupted():
                live = set(node.protocol.get_neighbors(only_direct=False))
                if ctx.take_stash_dirty():
                    drain_async_stash(node, ctx)
                self._adopt_pending(node, ctx)
                # aggregators re-push the latest global so a dropped push
                # cannot strand a subtree at run end — when the VERSION
                # CHANGED since the last re-push, plus a slow (~2 s)
                # fallback cadence covering the dropped-re-push case
                # (every tick would fan the full model out 20×/s for
                # children that just drop it as stale)
                with ctx.lock:
                    current = ctx.last_global[1] if ctx.last_global else -1
                if current != pushed_version or tick % 40 == 0:
                    ctx.execute_actions(ctx.final_sync_actions())
                    pushed_version = current
                tick += 1
                with state.status_merge_lock:
                    done = set(state.async_done_peers)
                with ctx.lock:
                    others = ctx.members - {node.addr} - ctx._dead
                waiting = {m for m in others if m not in done and m in live}
                if not waiting:
                    graceful = True
                    break
                time.sleep(0.05)
            if graceful:
                # fold every member that vanished from the overlay into the
                # dead set BEFORE the last fan-out: the eviction listener's
                # repair runs on its own daemon thread, so the drain can
                # observe the corpse gone from the neighbor view while this
                # node's router still names it regional — and a final push
                # routed to a corpse's stale role would strand its
                # promoted successor's subtree on an old version
                live = set(node.protocol.get_neighbors(only_direct=False))
                with ctx.lock:
                    vanished = ctx.members - ctx._dead - live - {node.addr}
                for m in sorted(vanished):
                    ctx.execute_actions(ctx.mark_dead(m))
                if ctx.take_stash_dirty():
                    drain_async_stash(node, ctx)
                # grace window: merges triggered by the LAST members' final
                # updates are still propagating down the tiers
                time.sleep(min(0.5, Settings.ASYNC_DRAIN_TIMEOUT / 10))
                ctx.execute_actions(ctx.final_sync_actions())
                time.sleep(0.1)
            else:
                logger.info(
                    node.addr,
                    "Async drain window closed with members still pending — exiting",
                )
            self._adopt_pending(node, ctx)
            # push-based final sync can still miss a node: exit timing is
            # jittered across the fleet by per-node eviction clocks, so
            # the last minted version's push can land after a child's
            # grace window closed (worst under failover, where a node's
            # every earlier global came through a corpse). Before leaving,
            # every non-root node PULLS the current global once — the
            # bootstrap verb reused; servable even by peers that already
            # exited (Node._last_async_global) — bounded by one
            # round-trip. A reply at the version we already hold is
            # ignored by the adopt gate.
            with ctx.lock:
                is_root = ctx.router.root == node.addr
            if not is_root and not node.learning_interrupted():
                # pull until STABLE (two consecutive pulls at the same
                # version, max 3): the first reply can race the root's
                # last tail merge — a second pull then lands either on the
                # root's drain (newer version) or, after its exit, on the
                # kept result (Node._last_async_global), which IS final
                prev_version = None
                for _attempt in range(3):
                    target = ctx.pull_target()
                    if target is None:
                        break
                    with ctx.lock:
                        seen_before = ctx.models_seen
                    logger.log_comm_metric(node.addr, "async_exit_pull")
                    node.protocol.send(
                        target,
                        node.protocol.build_msg("async_pull"),
                        create_connection=True,
                    )
                    pull_deadline = time.monotonic() + min(
                        2.0, Settings.ASYNC_DRAIN_TIMEOUT / 5
                    )
                    while time.monotonic() < pull_deadline:
                        with ctx.lock:
                            if ctx.models_seen > seen_before:
                                break
                        time.sleep(0.05)
                    self._adopt_pending(node, ctx)
                    with ctx.lock:
                        got = ctx.models_seen > seen_before
                        version = ctx.last_global[1] if ctx.last_global else -1
                    if not got or version == prev_version:
                        break  # no reply (bounded exit) or stable
                    prev_version = version

    @staticmethod
    def _adopt_pending(node: "Node", ctx: AsyncContext) -> None:
        pend = ctx.take_pending_global()
        if pend is not None:
            params, version = pend
            node.learner.set_parameters(params)
            ctx.base_version = version
