"""FedBuff-style buffered aggregation: merge when K arrive, weight by age.

The sync :class:`~p2pfl_tpu.learning.aggregators.aggregator.Aggregator`
opens a *collection window* per round and blocks until a coverage target
is met — the barrier that lets one straggler gate the fleet. The
:class:`BufferedAggregator` has no window and no target: contributions are
accepted **as they arrive** (deduped by a version vector, down-weighted by
staleness, dropped past the staleness bound), and once ``K`` are buffered
the global model advances one version:

    P̄      = Σᵢ wᵢ·paramsᵢ / Σᵢ wᵢ        wᵢ = num_samplesᵢ · w(τᵢ)
    global ← (1−η)·global + η·P̄            (``ops/aggregation.server_merge``)

Nobody ever waits: a slow node's update merges late (with a smaller
weight) into whatever version the fleet has reached meanwhile.

The P̄ fold is one of the :func:`~p2pfl_tpu.ops.aggregation.
buffered_robust_merge` kernels, selected by ``Settings.ASYNC_ROBUST_AGG``
— ``fedavg`` (the formula above, the default), ``trimmed-mean`` /
``median`` (per-coordinate rank rules, Byzantine-robust, weight-free by
construction) or ``krum-screen`` (Krum drops the ``BYZ_F`` most outlying
contributions, the staleness-weighted mean folds the survivors). An
optional admission screen (``defense`` —
:class:`~p2pfl_tpu.federation.defense.ByzantineDefense`) additionally
gates every :meth:`~BufferedAggregator.offer` against the tier's current
params before buffering.

Determinism contract: given the same *sequence* of ``offer``/``set_global``
calls, results are bit-identical — the flush sorts its buffer by
``(origin, seq)`` so the fold order never depends on arrival interleaving
within a buffer window, and the reduction is the same jitted kernel every
time. The event-driven :mod:`~p2pfl_tpu.federation.simfleet` makes the
call sequence itself a pure function of the seed, which is what the
replay tests pin.

Thread-safe: command handlers deliver from whatever thread carries the
message (sender gossip workers, duplicate timers). The internal lock is
never held across anything that can send — flush results are *returned*
and the caller propagates them outside the lock (lock-ordering with peers'
handlers would otherwise deadlock the in-memory transport's synchronous
delivery chains).
"""

from __future__ import annotations

import threading
from typing import Any, List, NamedTuple, Optional, Tuple

from p2pfl_tpu.federation.staleness import (
    UpdateVersion,
    VersionVector,
    as_version,
    staleness_weight,
)
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.management.telemetry import telemetry
from p2pfl_tpu.settings import Settings

Pytree = Any


class FlushResult(NamedTuple):
    """One merge's outcome, handed to the caller for propagation."""

    params: Pytree  #: the post-merge model
    version: int  #: this tier's model version after the merge
    contributors: List[str]  #: union of the merged updates' contributors
    num_samples: int  #: summed RAW sample counts (pre-staleness-discount)
    taus: List[int]  #: per-merged-update staleness, fold order


class BufferedAggregator:
    """Bounded-staleness buffer around one model tier.

    ``bump_on_flush`` distinguishes the two tiers of the hierarchy:

    - the **global** tier owns the version counter — every flush IS a new
      global version (``bump_on_flush=True``, the default);
    - a **regional** tier merges its cluster's updates but its version is
      the *global* version it tracks via :meth:`set_global` — a regional
      flush produces an aggregate to push upward, not a new global
      (``bump_on_flush=False``), so edge staleness is still measured in
      global versions end to end.
    """

    def __init__(
        self,
        node_name: str,
        params: Pytree,
        *,
        k: Optional[int] = None,
        alpha: Optional[float] = None,
        server_lr: Optional[float] = None,
        max_staleness: Optional[int] = None,
        bump_on_flush: bool = True,
        defense: Optional[Any] = None,
    ) -> None:
        self.node_name = node_name
        #: optional admission screen (federation/defense.py
        #: ByzantineDefense): every offered contribution is checked
        #: against this tier's current params before it may buffer
        self.defense = defense
        self.k = max(1, int(Settings.FEDBUFF_K if k is None else k))
        self.alpha = float(Settings.FEDBUFF_ALPHA if alpha is None else alpha)
        self.server_lr = float(
            Settings.FEDBUFF_SERVER_LR if server_lr is None else server_lr
        )
        self.max_staleness = int(
            Settings.ASYNC_MAX_STALENESS if max_staleness is None else max_staleness
        )
        self.bump_on_flush = bump_on_flush
        self._lock = threading.Lock()
        self._params = params
        self._version = 0
        self._vv = VersionVector()
        # buffered (version triple, update, effective weight, accept-time
        # staleness) — flushed in (origin, seq) order, NOT arrival order
        # (determinism contract)
        self._pending: List[Tuple[UpdateVersion, ModelUpdate, float, int]] = []
        self.merges = 0

    # ---- views ----

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def snapshot(self) -> Tuple[Pytree, int]:
        """The current ``(params, version)`` pair, atomically."""
        with self._lock:
            return self._params, self._version

    def pending(self) -> int:
        with self._lock:
            return len(self._pending)

    def version_vector(self) -> dict:
        return self._vv.snapshot()

    # ---- upstream adoption (regional tiers / restarts) ----

    def set_global(self, params: Pytree, version: int) -> bool:
        """Adopt a newer upstream global. Returns False for stale pushes.

        Buffered-but-unflushed contributions are kept: their staleness
        simply grows (and the bound may later drop them) — exactly the
        semantics their producers signed up for.
        """
        with self._lock:
            if version <= self._version:
                return False
            self._params = params
            self._version = version
            return True

    # ---- the hot path ----

    def offer(
        self, update: ModelUpdate, screen_origin: Optional[str] = None
    ) -> Optional[FlushResult]:
        """Accept a contribution; returns a :class:`FlushResult` when this
        acceptance completed a buffer of K, else None.

        ``screen_origin`` is who the Byzantine screen blames for a
        rejection — the DELIVERING peer when the caller knows it (the
        in-payload ``(origin, seq)`` triple is attacker-controlled and
        must not be a framing vector); None falls back to the version
        origin, which equals the sender for every direct push.

        Rejections (all counted in the comm metrics, never raising):

        - ``async_dup_drop`` — the version vector already saw an equal or
          newer ``(origin, seq)`` (duplicate / reordered delivery);
        - ``async_stale_drop`` — ``τ > max_staleness`` (bounded
          staleness: too old to merge at any weight).

        An update with no version triple (a sync-mode producer poking the
        buffer directly) is treated as fresh from its first contributor
        with an auto-assigned seq — counted ``async_unversioned`` so a
        misconfigured fleet is visible in the metrics.
        """
        ver = as_version(update.version)
        with self._lock:
            if ver is None:
                origin = update.contributors[0] if update.contributors else "?"
                ver = UpdateVersion(origin, self._vv.last(origin) + 1, self._version)
                logger.log_comm_metric(self.node_name, "async_unversioned")
            if not self._vv.observe(ver.origin, ver.seq):
                logger.log_comm_metric(self.node_name, "async_dup_drop")
                telemetry.event(
                    self.node_name,
                    "async_dup_drop",
                    kind="gossip",
                    attrs={"origin": ver.origin, "seq": ver.seq},
                )
                return None
            if self.defense is not None and not self.defense.admit(
                screen_origin if screen_origin is not None else ver.origin,
                update.params,
                self._params,
            ):
                # screened out (federation/defense.py): counted there as
                # screen_reject; the (origin, seq) mark above stays — a
                # replay of the rejected payload is a dup either way
                return None
            if (
                self.bump_on_flush
                and ver.base_version > self._version
                and ver.base_version - self._version <= self.max_staleness
            ):
                # version high-water handover (root failover): a successor
                # root that missed the corpse's last minted globals still
                # sees their versions inside the updates trained FROM them
                # — jump the counter so the next flush mints strictly
                # above anything any live node already adopted. A no-op in
                # steady state (nodes can only train from versions this
                # tier minted, so base <= version at the minting tier).
                # The jump is BOUNDED by max_staleness: an unvalidated
                # base_version from a cross-experiment straggler (pre-xp
                # sender — the identity gate cannot filter it) must not
                # inflate the counter so far that every legitimate update
                # mass-drops as over-stale; beyond the bound the frame
                # merges once at clamped τ=0 instead — the pre-elastic
                # bounded damage. A real handover gap larger than the
                # staleness bound is a partition whose updates would be
                # dropped anyway.
                self._version = ver.base_version
            tau = max(self._version - ver.base_version, 0)
            if tau > self.max_staleness:
                logger.log_comm_metric(self.node_name, "async_stale_drop")
                telemetry.event(
                    self.node_name,
                    "async_stale_drop",
                    kind="gossip",
                    attrs={"origin": ver.origin, "tau": tau},
                )
                return None
            weight = float(update.num_samples) * staleness_weight(tau, self.alpha)
            self._pending.append((ver, update, weight, tau))
            logger.log_comm_metric(self.node_name, "async_update_buffered")
            result = self._maybe_flush_locked()
        return self._finish_flush(result)

    def set_k(self, k: int) -> Optional[FlushResult]:
        """Adjust the buffer size mid-run — the eviction repair hook.

        A tier's K is clamped to its fan-in at creation, but members die:
        a cluster of 3 with K=3 and one corpse would never flush again —
        the async twin of the sync plane's mid-round train-set repair.
        The workflow's eviction listener shrinks K to the live fan-in;
        if the buffer already holds that many, the merge fires HERE and
        the result is returned for propagation.
        """
        with self._lock:
            self.k = max(1, int(k))
            result = self._maybe_flush_locked()
        return self._finish_flush(result)

    # ---- durability (federation/durability.py) ----

    def journal_state(self, tier: str):
        """Copy this tier's journalable state under the lock — version,
        version-vector marks, and every pending contribution with its
        ORIGINAL version triple (so a resurrection that lands in a
        different role can successor-forward them verbatim)."""
        from p2pfl_tpu.federation.durability import BufferJournal

        with self._lock:
            pending = [
                (
                    v.origin,
                    v.seq,
                    v.base_version,
                    list(u.contributors),
                    int(u.num_samples),
                    u.params,
                )
                for v, u, _w, _t in sorted(
                    self._pending, key=lambda e: (e[0].origin, e[0].seq)
                )
            ]
            return BufferJournal(
                tier=tier,
                version=self._version,
                vv=self._vv.snapshot(),
                pending=pending,
            )

    def restore_journal(
        self, version: int, vv: dict, updates: List[ModelUpdate]
    ) -> Optional[FlushResult]:
        """Re-arm this tier from a journal: merge the version-vector
        marks (so a network re-delivery of a pre-crash in-flight update
        dedups instead of double-merging), lift the version floor, and
        re-buffer the journaled pending contributions. The entries
        bypass :meth:`offer`'s dedup — the restored marks already
        include them (they were observed at original admission) — but
        staleness is re-checked against the restored version: age that
        accrued while the node was dead may push an entry past the
        bound, which drops it exactly as it would have been dropped
        live. May complete a buffer of K — the flush result is returned
        for propagation, exactly like :meth:`set_k`."""
        with self._lock:
            for origin, seq in vv.items():
                self._vv.observe(origin, seq)
            if version > self._version:
                self._version = version
            for upd in updates:
                ver = as_version(upd.version)
                if ver is None:
                    continue
                tau = max(self._version - ver.base_version, 0)
                if tau > self.max_staleness:
                    logger.log_comm_metric(self.node_name, "async_stale_drop")
                    continue
                weight = float(upd.num_samples) * staleness_weight(tau, self.alpha)
                self._pending.append((ver, upd, weight, tau))
                logger.log_comm_metric(self.node_name, "async_update_buffered")
            result = self._maybe_flush_locked()
        return self._finish_flush(result)

    def take_pending(self) -> List[ModelUpdate]:
        """Drain buffered-but-unflushed contributions without merging —
        the buffer-migration hook for elastic membership.

        An aggregator whose role changes (demoted by a join's re-chunk,
        or leaving gracefully) must not discard a partial buffer: the
        contributions are FORWARDED raw, in ``(origin, seq)`` order, to
        the successor tier, whose own version vector re-dedups any copy
        that also reached it directly. The local version vector keeps its
        marks (this buffer may be re-promoted later and must still reject
        replays of what it already accepted).
        """
        with self._lock:
            entries = sorted(self._pending, key=lambda e: (e[0].origin, e[0].seq))
            self._pending = []
        return [u for _v, u, _w, _t in entries]

    def _maybe_flush_locked(self) -> Optional[FlushResult]:
        if len(self._pending) < self.k:
            return None
        entries = sorted(self._pending, key=lambda e: (e[0].origin, e[0].seq))
        self._pending = []
        return self._merge_locked(entries)

    def _finish_flush(self, result: Optional[FlushResult]) -> Optional[FlushResult]:
        if result is None:
            return None
        # telemetry outside the lock: the staleness histogram is fed per
        # MERGED update (drops counted separately in offer)
        for tau in result.taus:
            telemetry.observe_value(self.node_name, "staleness", tau)
        logger.log_comm_metric(self.node_name, "async_merge")
        return result

    def _merge_locked(self, entries) -> FlushResult:
        import jax
        import jax.numpy as jnp

        from p2pfl_tpu.ops.aggregation import buffered_robust_merge, server_merge

        with telemetry.span(
            self.node_name,
            "async_merge",
            kind="stage",
            attrs={
                "k": len(entries),
                "version": self._version,
                "kernel": Settings.ASYNC_ROBUST_AGG,
            },
        ):
            weights = jnp.asarray([w for _v, _u, w, _t in entries], dtype="float32")
            params_list = [u.params for _v, u, _w, _t in entries]
            stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *params_list)
            # kernel selected by Settings.ASYNC_ROBUST_AGG ("fedavg" is the
            # pre-robustness staleness-weighted mean, bit-identical); all
            # kernels fold the same (origin, seq)-sorted stack, so the
            # arrival-order determinism contract is kernel-independent
            avg = buffered_robust_merge(
                stacked,
                weights,
                Settings.ASYNC_ROBUST_AGG,
                trim=Settings.ASYNC_TRIM,
                f=Settings.BYZ_F,
                agg_dtype=Settings.AGG_DTYPE,
            )
            self._params = server_merge(
                self._params, avg, lr=self.server_lr, agg_dtype=Settings.AGG_DTYPE
            )
            if self.bump_on_flush:
                self._version += 1
            self.merges += 1
            contributors = sorted({c for _v, u, _w, _t in entries for c in u.contributors})
            num_samples = int(sum(u.num_samples for _v, u, _w, _t in entries))
            taus = [t for _v, _u, _w, t in entries]
            return FlushResult(self._params, self._version, contributors, num_samples, taus)
