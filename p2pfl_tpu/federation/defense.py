"""Byzantine defense-in-depth: admission screening + attacker quarantine.

Robust merge kernels (``ops/aggregation.buffered_robust_merge``, the sync
Krum/Bulyan/trimmed-mean strategies) bound what one poisoned contribution
can do to ONE fold — but they re-pay that cost every flush, forever, and
say nothing about the attacker itself. This module adds the other half of
the production answer: a cheap per-contribution **admission screen** whose
rejections feed a per-origin **suspicion EWMA**, which past a threshold
drives the EXISTING quarantine path — ``Neighbors.evict`` → eviction
listeners → sync train-set repair / async ``TierRouter`` re-derivation —
so a persistent semantic attacker is removed from the federation by the
same machinery that removes a corpse.

The screen (``Settings.BYZ_SCREEN``) checks every contribution against
the receiving tier's current global with one fused device reduction
(:func:`~p2pfl_tpu.ops.aggregation.screen_stats`):

- **norm gate** — reject when ``‖update‖ / ‖global‖`` leaves
  ``[1/BYZ_NORM_GATE, BYZ_NORM_GATE]`` (scale attacks, exploding updates);
- **cosine gate** — reject when ``cos(update, global) < BYZ_COS_GATE``
  (sign flips sit at −1, heavy noise near 0; honest weights-space updates
  that trained FROM the global stay near +1).

Threat model — what this does and does NOT claim (docs/design.md):
screening is a cheap statistical filter over weights-space updates, not a
proof. It catches the high-signal attacks (sign-flip, large scale, heavy
noise, most equivocation) and it rate-limits everything else through the
EWMA; a carefully-scaled attacker inside both gates still lands inside
the robust kernels' breakdown bound, which is why the kernels and the
screen ship together. The screen can false-positive on extreme non-IID
clients — it is opt-in, its gates are knobs, and a rejection never drops
a node by itself (only sustained rejection crosses the EWMA threshold).

Both aggregator seams consult one per-node instance (``node.defense``):
the sync :meth:`~p2pfl_tpu.learning.aggregators.aggregator.Aggregator.
add_model` (reference = the round-start params the stage pins) and the
async :meth:`~p2pfl_tpu.federation.buffer.BufferedAggregator.offer`
(reference = the buffer's current params). On BOTH seams suspicion
attributes to the DELIVERING peer, never to an identity named inside the
payload: sync gossip relays other nodes' models verbatim and the async
version triple's origin is attacker-controlled — keying suspicion on
either would let a lying sender frame (and get evicted) an honest node.
Screen-enabled receivers never store or buffer a rejected payload, so
honest nodes never relay poison and attribution converges on the
attacker. Quarantine fires ONCE per origin, on a daemon thread — the
decision lands under aggregator/buffer locks and the eviction path
broadcasts, and no lock may be held across a send (the PR-9 deadlock
contract, enforced by p2pfl-check).

Every decision is observable: ``screen_reject`` / ``byz_suspect`` /
``byz_evicted`` comm metrics plus flight-recorder events, so a Perfetto
timeline shows who flagged whom when.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional

from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.management.telemetry import telemetry
from p2pfl_tpu.settings import Settings

Pytree = Any

#: below this reference norm the screen abstains: there is no meaningful
#: direction to compare against (a zero-initialized global, version 0)
_MIN_REF_NORM = 1e-6


class ByzantineDefense:
    """Per-node screening + suspicion state, shared by both control planes.

    ``on_quarantine(addr)`` is invoked AT MOST ONCE per origin, on a
    dedicated daemon thread (see module docs); drivers that need
    deterministic synchronous handling (the simulator) pass no callback
    and poll :meth:`take_quarantined` instead.
    """

    def __init__(
        self,
        node_name: str,
        on_quarantine: Optional[Callable[[str], None]] = None,
    ) -> None:
        self.node_name = node_name
        self.on_quarantine = on_quarantine
        self._lock = threading.Lock()
        #: per-origin suspicion EWMA in [0, 1]
        self._suspicion: dict[str, float] = {}
        #: origins past the threshold — monotone within an experiment
        self._quarantined: set[str] = set()
        #: quarantined origins not yet collected by a polling driver
        self._pending_quarantine: List[str] = []
        self.screen_rejects = 0

    # ---- lifecycle ----

    def reset(self) -> None:
        """Experiment boundary: suspicion and quarantine are per-run
        state (a new experiment re-admits everyone; the overlay-level
        eviction the previous run drove has its own re-admission rules).
        """
        with self._lock:
            self._suspicion.clear()
            self._quarantined.clear()
            self._pending_quarantine.clear()
            self.screen_rejects = 0

    # ---- durability (federation/durability.py) ----

    def journal_state(self):
        """``(suspicion, quarantined)`` copies for the node journal —
        suspicion decays slowly by design, so losing it to a restart
        would hand every persistent attacker a free EWMA reset."""
        with self._lock:
            return dict(self._suspicion), sorted(self._quarantined)

    def restore(self, suspicion: dict, quarantined: List[str]) -> None:
        """Re-arm from a journal (max-merge: concurrent observations
        since the snapshot are never lowered). Quarantine is NOT
        re-fired — the pre-crash eviction already broadcast, and the
        restored set keeps :meth:`admit` dropping those origins."""
        with self._lock:
            for origin, s in suspicion.items():
                if s > self._suspicion.get(origin, 0.0):
                    self._suspicion[origin] = float(s)
            self._quarantined.update(quarantined)

    # ---- the screen ----

    @staticmethod
    def enabled() -> bool:
        return bool(Settings.BYZ_SCREEN)

    def is_quarantined(self, origin: str) -> bool:
        with self._lock:
            return origin in self._quarantined

    def admit(self, origin: str, params: Pytree, ref: Pytree) -> bool:
        """Screen one contribution from ``origin`` against ``ref`` (the
        receiving tier's current global). True = admit.

        Self-contributions are never screened (a node poisoning itself is
        out of scope — it could lie in its aggregates directly), already-
        quarantined origins are dropped without paying the device
        reduction, and the screen abstains when the reference has no
        meaningful direction (near-zero norm) or the stats cannot be
        computed (shape drift is the codec's problem, not the screen's).
        """
        if origin == self.node_name:
            return True
        if self.is_quarantined(origin):
            logger.log_comm_metric(self.node_name, "byz_quarantined_drop")
            return False
        if not self.enabled():
            return True
        try:
            ok, norm_ratio, cos = self._screen_stats(params, ref)
        except Exception as exc:  # noqa: BLE001 — screening must never take a tier down
            logger.debug(self.node_name, f"screen abstained for {origin}: {exc!r}")
            return True
        if ok is None:
            return True  # abstained (no reference direction)
        if not ok:
            self.screen_rejects += 1
            logger.log_comm_metric(self.node_name, "screen_reject")
            telemetry.event(
                self.node_name,
                "screen_reject",
                kind="gossip",
                attrs={
                    "origin": origin,
                    "norm_ratio": round(norm_ratio, 4),
                    "cos": round(cos, 4),
                },
            )
        self._observe(origin, rejected=not ok)
        return bool(ok)

    def _screen_stats(self, params: Pytree, ref: Pytree):
        """(verdict, norm_ratio, cos) — verdict None = abstain."""
        import jax

        from p2pfl_tpu.ops.aggregation import screen_stats

        if jax.tree.structure(params) != jax.tree.structure(ref):
            return None, 0.0, 0.0
        pn, rn, cos = screen_stats(params, ref)
        rn = float(rn)
        if rn < _MIN_REF_NORM:
            return None, 0.0, 0.0
        ratio = float(pn) / rn
        cos = float(cos)
        gate = float(Settings.BYZ_NORM_GATE)
        ok = (1.0 / gate) <= ratio <= gate and cos >= float(Settings.BYZ_COS_GATE)
        return ok, ratio, cos

    # ---- suspicion / quarantine ----

    def suspicion(self, origin: str) -> float:
        with self._lock:
            return self._suspicion.get(origin, 0.0)

    def _observe(self, origin: str, rejected: bool) -> None:
        beta = float(Settings.BYZ_SUSPICION_BETA)
        fire = False
        with self._lock:
            s = self._suspicion.get(origin, 0.0)
            s = (1.0 - beta) * s + (beta if rejected else 0.0)
            self._suspicion[origin] = s
            if rejected:
                logger.log_comm_metric(self.node_name, "byz_suspect")
                telemetry.event(
                    self.node_name,
                    "byz_suspect",
                    kind="gossip",
                    attrs={"origin": origin, "suspicion": round(s, 4)},
                )
            if (
                s >= float(Settings.BYZ_SUSPICION_THRESHOLD)
                and origin not in self._quarantined
            ):
                self._quarantined.add(origin)
                self._pending_quarantine.append(origin)
                fire = True
        if fire:
            logger.log_comm_metric(self.node_name, "byz_evicted")
            telemetry.event(
                self.node_name,
                "byz_evicted",
                kind="gossip",
                attrs={"origin": origin},
            )
            logger.warning(
                self.node_name,
                f"Byzantine quarantine: {origin} crossed the suspicion "
                "threshold — driving the eviction path",
            )
            if self.on_quarantine is not None:
                # the decision lands under an aggregator/buffer lock and
                # the eviction path broadcasts — fire on a daemon thread
                # so no lock is ever held across a send (PR-9 contract)
                threading.Thread(
                    target=self._fire_quarantine,
                    args=(origin,),
                    name=f"byz-quarantine-{self.node_name}",
                    daemon=True,
                ).start()

    def _fire_quarantine(self, origin: str) -> None:
        try:
            self.on_quarantine(origin)
        except Exception as exc:  # noqa: BLE001 — quarantine is best-effort
            logger.error(
                self.node_name, f"Byzantine quarantine of {origin} failed: {exc!r}"
            )

    def take_quarantined(self) -> List[str]:
        """Drain origins quarantined since the last call — the polling
        seam for drivers with no callback (the simulator turns these into
        deterministic evict events on its virtual clock)."""
        with self._lock:
            out, self._pending_quarantine = self._pending_quarantine, []
        return out
