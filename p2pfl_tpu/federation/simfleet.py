"""Deterministic event-driven async-fleet simulator (1k–10k virtual nodes).

Real threaded nodes cannot replay bit-identically — the OS scheduler
decides which K updates share a buffer window. This driver replaces
threads with a **virtual clock**: every train completion, update arrival
and model push is an event on one heap, popped in ``(time, insertion
seq)`` order, so the entire run — including which updates land in which
merge, every staleness value, every fault verdict — is a pure function of
``(seed, fault plan, fleet shape)``. That purity is what the replay test
pins (same inputs ⇒ bit-identical final global), and what makes 1k-node
hierarchical convergence drives affordable: no sockets, no sleeps, the
only real compute is the buffers' jitted merges.

The simulated fleet shares the production plane's *state machines*: the
same :class:`~p2pfl_tpu.federation.buffer.BufferedAggregator` instances,
the same :class:`~p2pfl_tpu.federation.topology.HierarchicalTopology`
derivation, the same version triples and staleness arithmetic. The
tier-routing glue (which buffer an arrival feeds, upward stamping,
downward forwarding) is MIRRORED from ``workflow.AsyncContext`` rather
than shared — the threaded context is entangled with Node/transport;
extracting a node-free routing core both drivers consume is an open
refactor (ROADMAP 3) — so a routing change in one must be mirrored in
the other. The transport (heap events instead of ``_do_send``) and the
learner (a seeded consensus task instead of a jitted epoch scan) are
deliberate stand-ins. Faults reuse :class:`FaultPlan` semantics at
the same conceptual seam: per-edge drop/duplicate verdicts from the
plan's per-edge streams, ``slow_nodes`` as inbound-weights latency,
``CrashSpec(stage="AsyncTrainStage", round_no=k)`` as "dies starting its
k-th local update".

The default workload is a consensus least-squares task: node ``i`` pulls
its model toward a seeded private target ``tᵢ``; the fleet's fixed point
is the weighted target mean, and ``loss(global) = ‖w − t̄‖²`` measures
convergence — enough structure to show time-to-target beating a
barrier-synchronized fleet under stragglers, with zero ML runtime cost.
"""

from __future__ import annotations

import heapq
import itertools
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from p2pfl_tpu.federation.buffer import BufferedAggregator
from p2pfl_tpu.federation.topology import HierarchicalTopology
from p2pfl_tpu.learning.weights import ModelUpdate

Pytree = Any


@dataclass
class FleetResult:
    """What a simulated drive produced (the determinism-test surface)."""

    params: Pytree  #: final global model
    version: int  #: final global version
    virtual_time: float  #: when the last event fired
    time_to_target: Optional[float]  #: first global-flush time with loss < target
    loss_curve: List[Tuple[float, int, float]]  #: (virtual t, version, loss)
    updates_sent: int = 0
    updates_delivered: int = 0
    updates_dropped_wire: int = 0
    duplicates_injected: int = 0
    crashed: List[str] = field(default_factory=list)
    merges: int = 0

    def final_loss(self) -> float:
        return self.loss_curve[-1][2] if self.loss_curve else float("inf")


class _SimNode:
    __slots__ = (
        "addr", "idx", "model", "base_version", "known_version",
        "pending_global", "seq", "updates_done", "crashed", "num_samples",
        "duration",
    )

    def __init__(self, addr: str, idx: int, model: Pytree, num_samples: int, duration: float) -> None:
        self.addr = addr
        self.idx = idx
        self.model = model
        self.base_version = 0
        self.known_version = 0
        self.pending_global: Optional[Tuple[Pytree, int]] = None
        self.seq = itertools.count(1)
        self.updates_done = 0
        self.crashed = False
        self.num_samples = num_samples
        self.duration = duration


class SimulatedAsyncFleet:
    """One simulated fleet; :meth:`run` drives it to completion.

    ``train_fn(idx, params, rng) -> params`` and ``loss_fn(params) ->
    float`` default to the consensus task. ``plan`` (a
    :class:`~p2pfl_tpu.communication.faults.FaultPlan`) injects
    drop/duplicate/slow/crash exactly as the threaded chaos suite would;
    ``slow_frac``/``slow_factor`` additionally stretch a deterministic
    subset of nodes' train durations (the straggler population the async
    plane exists for).
    """

    def __init__(
        self,
        n_nodes: int,
        *,
        seed: int = 0,
        cluster_size: int = 0,
        k: Optional[int] = None,
        alpha: Optional[float] = None,
        server_lr: Optional[float] = None,
        max_staleness: Optional[int] = None,
        updates_per_node: int = 4,
        base_duration: float = 1.0,
        link_delay: float = 0.01,
        slow_frac: float = 0.0,
        slow_factor: float = 10.0,
        plan=None,
        dim: int = 16,
        local_lr: float = 0.5,
        target_loss: float = 0.0,
        train_fn: Optional[Callable] = None,
        loss_fn: Optional[Callable] = None,
        init_params: Optional[Pytree] = None,
    ) -> None:
        self.seed = int(seed)
        self.n = int(n_nodes)
        self.updates_per_node = int(updates_per_node)
        self.link_delay = float(link_delay)
        self.plan = plan
        self.target_loss = float(target_loss)
        addrs = [f"sim-{i:04d}" for i in range(self.n)]
        self.topo = HierarchicalTopology(addrs, cluster_size)

        # seeded consensus task (see module docs): every node's target is
        # a SHARED offset plus private noise — the fleet's fixed point is
        # ≈ the offset, so a zero-initialized global has an O(dim) loss to
        # close and "converged" is a real statement even at n=1000 (pure
        # zero-mean targets would average to a fixed point at the origin)
        base = np.random.default_rng([self.seed, 5]).normal(size=dim).astype(np.float32) * 2.0
        self._targets = {
            i: base
            + np.random.default_rng([self.seed, 7, i]).normal(size=dim).astype(np.float32)
            for i in range(self.n)
        }
        self._local_lr = float(local_lr)
        if init_params is None:
            init_params = {"w": np.zeros(dim, dtype=np.float32)}
        self.train_fn = train_fn or self._default_train
        self.loss_fn = loss_fn or self._default_loss

        # per-node deterministic shape: duration jitter, slow membership,
        # sample weights — each from its own stream, FaultPlan-style
        self.nodes: Dict[str, _SimNode] = {}
        for i, addr in enumerate(addrs):
            rng = np.random.default_rng([self.seed, 11, i])
            dur = base_duration * (0.8 + 0.4 * float(rng.random()))
            if slow_frac > 0.0 and float(rng.random()) < slow_frac:
                dur *= slow_factor
            self.nodes[addr] = _SimNode(
                addr, i, _copy_tree(init_params), 1 + i % 3, dur
            )

        kk = k
        self._buffers: Dict[str, Dict[str, BufferedAggregator]] = {}
        for regional in self.topo.regionals:
            bufs: Dict[str, BufferedAggregator] = {}
            if regional == self.topo.global_root and self.topo.is_flat():
                bufs["global"] = BufferedAggregator(
                    regional, _copy_tree(init_params),
                    k=_clamp_k(kk, len(self.topo.members)), alpha=alpha,
                    server_lr=server_lr, max_staleness=max_staleness,
                )
            else:
                bufs["regional"] = BufferedAggregator(
                    regional, _copy_tree(init_params),
                    k=_clamp_k(kk, len(self.topo.cluster_of(regional))), alpha=alpha,
                    server_lr=server_lr, max_staleness=max_staleness,
                    bump_on_flush=False,
                )
                if regional == self.topo.global_root:
                    bufs["global"] = BufferedAggregator(
                        regional, _copy_tree(init_params),
                        k=_clamp_k(kk, len(self.topo.regionals)), alpha=alpha,
                        server_lr=server_lr, max_staleness=max_staleness,
                    )
            self._buffers[regional] = bufs
        self._up_seq = {r: itertools.count(1) for r in self.topo.regionals}

        # event heap: (time, insertion seq, kind, payload) — the seq makes
        # pop order total and therefore the whole run deterministic
        self._heap: list = []
        self._evseq = itertools.count()
        self.result = FleetResult(
            params=_copy_tree(init_params), version=0, virtual_time=0.0,
            time_to_target=None, loss_curve=[],
        )

    # ---- default workload ----

    def _default_train(self, idx: int, params: Pytree, rng: np.random.Generator) -> Pytree:
        t = self._targets[idx]
        w = params["w"]
        return {"w": (w + self._local_lr * (t - np.asarray(w, np.float32))).astype(np.float32)}

    def _default_loss(self, params: Pytree) -> float:
        weights = np.asarray([self.nodes[a].num_samples for a in self.topo.members], np.float32)
        targets = np.stack([self._targets[self.nodes[a].idx] for a in self.topo.members])
        t_mean = (weights[:, None] * targets).sum(0) / weights.sum()
        diff = np.asarray(params["w"], np.float32) - t_mean
        return float(diff @ diff)

    # ---- fault plumbing (FaultPlan semantics on the virtual wire) ----

    def _edge_verdict(self, src: str, dst: str) -> Tuple[bool, bool, float]:
        """(dropped, duplicated, extra inbound latency) for one delivery."""
        slow = 0.0
        if self.plan is None:
            return False, False, slow
        slow = float(self.plan.slow_nodes.get(dst, 0.0))
        if self.plan.partitioned(src, dst):
            return True, False, slow
        fault = self.plan.edge_fault(src, dst)
        rng = self.plan.rng(src, dst)
        drop_u, dup_u, _jit_u = rng.random(), rng.random(), rng.random()
        dropped = bool(fault.drop) and drop_u < fault.drop
        dup = (not dropped) and bool(fault.duplicate) and dup_u < fault.duplicate
        return dropped, dup, slow + fault.delay

    def _crash_spec(self, addr: str):
        if self.plan is None:
            return None
        return self.plan.crashes.get(addr)

    # ---- event loop ----

    def _push(self, t: float, kind: str, payload: tuple) -> None:
        heapq.heappush(self._heap, (t, next(self._evseq), kind, payload))

    def run(self) -> FleetResult:
        for addr, node in self.nodes.items():
            self._push(node.duration, "train_done", (addr,))
        while self._heap:
            t, _seq, kind, payload = heapq.heappop(self._heap)
            self.result.virtual_time = t
            if kind == "train_done":
                self._on_train_done(t, *payload)
            elif kind == "update_arrive":
                self._on_update_arrive(t, *payload)
            elif kind == "model_arrive":
                self._on_model_arrive(t, *payload)
        gbuf = self._buffers[self.topo.global_root].get("global")
        if gbuf is not None:
            self.result.params, self.result.version = gbuf.snapshot()
            self.result.merges = gbuf.merges
        return self.result

    def _on_train_done(self, t: float, addr: str) -> None:
        node = self.nodes[addr]
        if node.crashed:
            return
        spec = self._crash_spec(addr)
        if (
            spec is not None
            and spec.stage == "AsyncTrainStage"
            and (spec.round_no is None or spec.round_no == node.updates_done)
        ):
            node.crashed = True
            self.result.crashed.append(addr)
            return
        # adopt the freshest global that arrived while "training"
        if node.pending_global is not None:
            params, version = node.pending_global
            node.model = params
            node.base_version = version
            node.pending_global = None
        rng = np.random.default_rng([self.seed, 13, node.idx, node.updates_done])
        node.model = self.train_fn(node.idx, node.model, rng)
        node.updates_done += 1
        upd = ModelUpdate(_copy_tree(node.model), [addr], node.num_samples)
        upd.version = (addr, next(node.seq), node.base_version)
        self.result.updates_sent += 1
        target = self.topo.aggregator_for(addr)
        self._deliver_update(t, addr, target, upd)
        if node.updates_done < self.updates_per_node:
            self._push(t + node.duration, "train_done", (addr,))

    def _deliver_update(self, t: float, src: str, dst: str, upd: ModelUpdate) -> None:
        if src == dst:
            self._push(t, "update_arrive", (dst, upd))
            return
        dropped, dup, extra = self._edge_verdict(src, dst)
        if dropped:
            self.result.updates_dropped_wire += 1
            return
        self._push(t + self.link_delay + extra, "update_arrive", (dst, upd))
        if dup:
            self.result.duplicates_injected += 1
            fault = self.plan.edge_fault(src, dst)
            self._push(
                t + self.link_delay + extra + max(fault.duplicate_delay, 1e-6),
                "update_arrive",
                (dst, upd),
            )

    def _on_update_arrive(self, t: float, dst: str, upd: ModelUpdate) -> None:
        if self.nodes[dst].crashed:
            return
        bufs = self._buffers.get(dst)
        if bufs is None:
            return  # mis-route: only aggregators hold buffers
        self.result.updates_delivered += 1
        origin = str(upd.version[0]) if upd.version else ""
        if "global" in bufs and (
            self.topo.is_flat() or (origin in self.topo.regionals and origin != dst)
        ):
            res = bufs["global"].offer(upd)
            if res:
                self._on_global_flush(t, res)
            return
        res = bufs["regional"].offer(upd)
        if res:
            up = ModelUpdate(res.params, res.contributors, res.num_samples)
            up.version = (dst, next(self._up_seq[dst]), res.version)
            if dst == self.topo.global_root:
                gres = bufs["global"].offer(up)
                if gres:
                    self._on_global_flush(t, gres)
            else:
                self._deliver_update(t, dst, self.topo.global_root, up)

    def _on_global_flush(self, t: float, res) -> None:
        loss = float(self.loss_fn(res.params))
        self.result.loss_curve.append((t, res.version, loss))
        if self.result.time_to_target is None and loss <= self.target_loss:
            self.result.time_to_target = t
        root = self.topo.global_root
        self._adopt(t, root, res.params, res.version, forward=False)
        for child in self.topo.children_of(root):
            self._deliver_model(t, root, child, res.params, res.version)

    def _deliver_model(self, t: float, src: str, dst: str, params: Pytree, version: int) -> None:
        dropped, dup, extra = self._edge_verdict(src, dst)
        if dropped:
            return
        self._push(t + self.link_delay + extra, "model_arrive", (dst, params, version, src))
        if dup:
            fault = self.plan.edge_fault(src, dst)
            self._push(
                t + self.link_delay + extra + max(fault.duplicate_delay, 1e-6),
                "model_arrive",
                (dst, params, version, src),
            )

    def _on_model_arrive(self, t: float, dst: str, params: Pytree, version: int, src: str) -> None:
        self._adopt(t, dst, params, version, forward=True, source=src)

    def _adopt(
        self, t: float, addr: str, params: Pytree, version: int,
        forward: bool, source: Optional[str] = None,
    ) -> None:
        node = self.nodes[addr]
        if node.crashed or version <= node.known_version:
            return
        node.known_version = version
        node.pending_global = (params, version)
        bufs = self._buffers.get(addr)
        if bufs is not None and "regional" in bufs:
            bufs["regional"].set_global(params, version)
        if forward:
            for child in self.topo.children_of(addr):
                if child != source:
                    self._deliver_model(t, addr, child, params, version)


def _copy_tree(tree: Pytree) -> Pytree:
    return {k: np.array(v, copy=True) for k, v in tree.items()}


def _clamp_k(k: Optional[int], fan_in: int):
    from p2pfl_tpu.settings import Settings

    base = Settings.FEDBUFF_K if k is None else int(k)
    return max(1, min(base, fan_in))
