"""Deterministic event-driven async-fleet simulator (1k–10k virtual nodes).

Real threaded nodes cannot replay bit-identically — the OS scheduler
decides which K updates share a buffer window. This driver replaces
threads with a **virtual clock**: every train completion, update arrival,
model push and membership event is an event on one heap, popped in
``(time, insertion seq)`` order, so the entire run — including which
updates land in which merge, every staleness value, every fault verdict,
every join/leave/failover — is a pure function of ``(seed, fault plan,
fleet shape)``. That purity is what the replay tests pin (same inputs ⇒
bit-identical final global), and what makes 1k-node hierarchical churn
drives affordable: no sockets, no sleeps, the only real compute is the
buffers' jitted merges.

The simulated fleet shares the production plane's *state machines*: the
same :class:`~p2pfl_tpu.federation.buffer.BufferedAggregator` instances,
the same version triples and staleness arithmetic, and — since the
node-free routing core landed — the SAME
:class:`~p2pfl_tpu.federation.routing.TierRouter` the production
``workflow.AsyncContext`` consumes: tier derivation, buffer placement,
update sinks, push-down fan-outs, successor election on death and the
version high-water handover are one implementation exercised by both
drivers. Only the transport (heap events instead of ``_do_send``) and the
learner (a seeded consensus task instead of a jitted epoch scan) are
deliberate stand-ins. Faults reuse :class:`FaultPlan` semantics at the
same conceptual seam: per-edge drop/duplicate verdicts from the plan's
per-edge streams, ``slow_nodes`` as inbound latency,
``CrashSpec(stage="AsyncTrainStage", round_no=k)`` as "dies starting its
k-th local update" — and the elastic churn events ride the same plan:
``JoinSpec(at_s)`` adds a member mid-run (it bootstraps from its
aggregator's current global), ``LeaveSpec(at_s, graceful=True)`` removes
one (a graceful aggregator forwards its partial buffer to the successor
tier before exiting; an abrupt one is discovered like a crash, after
``evict_delay``), ``RestartSpec`` kills a node like a CrashSpec and
``resume_after_s`` later resurrects it from its (virtual) journal — same
address, retained sequence counters and adopted global, catching up via
a bootstrap pull — so kill-and-resurrect replays bit-exact on the
virtual clock, and ``ByzantineSpec`` attackers corrupt their payloads
on the virtual wire through the SAME ``byz_corrupt_update`` helper the
live injector runs — with ``Settings.BYZ_SCREEN`` on, each aggregator's
:class:`~p2pfl_tpu.federation.defense.ByzantineDefense` screens arrivals
and a crossed suspicion threshold becomes a deterministic evict event
(the virtual stand-in for the production quarantine → eviction path).

The default workload is a consensus least-squares task: node ``i`` pulls
its model toward a seeded private target ``tᵢ``; the fleet's fixed point
is the weighted target mean over the LIVE membership, and
``loss(global) = ‖w − t̄‖²`` measures convergence — enough structure to
show time-to-target beating a barrier-synchronized fleet under
stragglers (and bounded disruption under churn), with zero ML runtime
cost.
"""

from __future__ import annotations

import heapq
import itertools
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from p2pfl_tpu.federation.buffer import BufferedAggregator
from p2pfl_tpu.federation.routing import TierRouter
from p2pfl_tpu.learning.weights import ModelUpdate

Pytree = Any


@dataclass
class FleetResult:
    """What a simulated drive produced (the determinism-test surface)."""

    params: Pytree  #: final global model
    version: int  #: final global version
    virtual_time: float  #: when the last event fired
    time_to_target: Optional[float]  #: first global-flush time with loss < target
    loss_curve: List[Tuple[float, int, float]]  #: (virtual t, version, loss)
    updates_sent: int = 0
    updates_delivered: int = 0
    updates_dropped_wire: int = 0
    duplicates_injected: int = 0
    crashed: List[str] = field(default_factory=list)
    merges: int = 0
    joined: List[str] = field(default_factory=list)
    left: List[str] = field(default_factory=list)
    failovers: int = 0  #: how many times the global root changed hands
    byz_corrupted: int = 0  #: payloads corrupted by ByzantineSpec attackers
    screen_rejects: int = 0  #: contributions the admission screen refused
    quarantined: List[str] = field(default_factory=list)  #: evicted attackers
    restarted: List[str] = field(default_factory=list)  #: RestartSpec resurrections

    def final_loss(self) -> float:
        return self.loss_curve[-1][2] if self.loss_curve else float("inf")


class _SimNode:
    __slots__ = (
        "addr", "idx", "model", "base_version", "known_version", "high_water",
        "global_params", "pending_global", "seq", "updates_done", "crashed",
        "num_samples", "duration",
    )

    def __init__(self, addr: str, idx: int, model: Pytree, num_samples: int, duration: float) -> None:
        self.addr = addr
        self.idx = idx
        self.model = model
        self.base_version = 0
        self.known_version = 0
        #: highest global version observed (adoptions + arriving triples)
        #: — the seed for a promoted aggregator's version counter
        self.high_water = 0
        #: last adopted global params — what a promoted buffer seeds from
        self.global_params: Optional[Pytree] = None
        self.pending_global: Optional[Tuple[Pytree, int]] = None
        self.seq = itertools.count(1)
        self.updates_done = 0
        self.crashed = False
        self.num_samples = num_samples
        self.duration = duration


class SimulatedAsyncFleet:
    """One simulated fleet; :meth:`run` drives it to completion.

    ``train_fn(idx, params, rng) -> params`` and ``loss_fn(params) ->
    float`` default to the consensus task. ``plan`` (a
    :class:`~p2pfl_tpu.communication.faults.FaultPlan`) injects
    drop/duplicate/slow/crash — and the churn events ``plan.joins`` /
    ``plan.leaves`` — exactly as the threaded chaos suite would;
    ``slow_frac``/``slow_factor`` additionally stretch a deterministic
    subset of nodes' train durations (the straggler population the async
    plane exists for). ``evict_delay`` is the virtual stand-in for the
    heartbeat eviction window: how long after a crash/abrupt leave the
    survivors re-derive the topology around the corpse.

    **Ownership contract (copy-on-write):** params trees on the virtual
    wire are immutable and pass by REFERENCE — deliveries, adoptions,
    buffer seeds and bootstrap pulls alias the producer's tree instead
    of deep-copying it per event (the pre-megafleet per-delivery
    ``_copy_tree`` was the 1k-heap drives' hottest line). The sites that
    *change* a tree already produce fresh ones: ``train_fn`` must return
    a new tree (the default does — mutating its input in place is a
    contract violation that would corrupt aliased buffer snapshots),
    ``BufferedAggregator`` merges build new params via the jitted
    kernels, and ``byz_corrupt_update`` corrupts a fresh copy, never the
    honest original.
    """

    def __init__(
        self,
        n_nodes: int,
        *,
        seed: int = 0,
        cluster_size: int = 0,
        k: Optional[int] = None,
        alpha: Optional[float] = None,
        server_lr: Optional[float] = None,
        max_staleness: Optional[int] = None,
        updates_per_node: int = 4,
        base_duration: float = 1.0,
        link_delay: float = 0.01,
        slow_frac: float = 0.0,
        slow_factor: float = 10.0,
        plan=None,
        dim: int = 16,
        local_lr: float = 0.5,
        target_loss: float = 0.0,
        evict_delay: float = 0.5,
        train_fn: Optional[Callable] = None,
        loss_fn: Optional[Callable] = None,
        init_params: Optional[Pytree] = None,
    ) -> None:
        from p2pfl_tpu.settings import Settings

        self.seed = int(seed)
        self.n = int(n_nodes)
        self.updates_per_node = int(updates_per_node)
        self.link_delay = float(link_delay)
        self.plan = plan
        self.target_loss = float(target_loss)
        self.evict_delay = float(evict_delay)
        self.cluster_size = cluster_size
        self._base_duration = float(base_duration)
        self._slow_frac = float(slow_frac)
        self._slow_factor = float(slow_factor)
        self._base_k = max(1, int(Settings.FEDBUFF_K if k is None else k))
        self._alpha = alpha
        self._server_lr = server_lr
        self._max_staleness = max_staleness
        addrs = [f"sim-{i:04d}" for i in range(self.n)]
        self._members: set = set(addrs)
        self._dead: set = set()
        self.router = TierRouter(addrs, cluster_size)

        # seeded consensus task (see module docs): every node's target is
        # a SHARED offset plus private noise — the fleet's fixed point is
        # ≈ the offset, so a zero-initialized global has an O(dim) loss to
        # close and "converged" is a real statement even at n=1000 (pure
        # zero-mean targets would average to a fixed point at the origin)
        self._dim = int(dim)
        self._target_base = (
            np.random.default_rng([self.seed, 5]).normal(size=dim).astype(np.float32) * 2.0
        )
        self._targets: Dict[int, np.ndarray] = {}
        self._local_lr = float(local_lr)
        if init_params is None:
            init_params = {"w": np.zeros(dim, dtype=np.float32)}
        self._init = init_params
        self.train_fn = train_fn or self._default_train
        self.loss_fn = loss_fn or self._default_loss

        # per-node deterministic shape: duration jitter, slow membership,
        # sample weights — each from its own stream, FaultPlan-style.
        # Joiners continue the idx sequence, so their streams are as
        # deterministic as the founders'.
        self.nodes: Dict[str, _SimNode] = {}
        self._next_idx = 0
        for addr in addrs:
            self._make_node(addr)

        self._up_seq: Dict[str, Any] = {}
        #: per-node death generation for RestartSpec resurrections: a
        #: pending evict event carries the epoch of the death that armed
        #: it, so an evict that was overtaken by a resurrection (or a
        #: later second death) is a no-op instead of evicting a LIVE node
        self._death_epoch: Dict[str, int] = {}
        self._buffers: Dict[str, Dict[str, BufferedAggregator]] = {}
        #: per-aggregator admission screens (federation/defense.py) —
        #: created lazily, only under Settings.BYZ_SCREEN; no callback:
        #: quarantines are POLLED after each offer and turned into
        #: deterministic evict events on the virtual clock
        self._defenses: Dict[str, Any] = {}
        self._reconcile(0.0)

        # event heap: (time, insertion seq, kind, payload) — the seq makes
        # pop order total and therefore the whole run deterministic
        self._heap: list = []
        self._evseq = itertools.count()
        self.result = FleetResult(
            params=init_params, version=0, virtual_time=0.0,
            time_to_target=None, loss_curve=[],
        )

    @property
    def topo(self):
        """Full-membership cluster chunking (routing.TierRouter view)."""
        return self.router.topo

    def _draw_duration(self, idx: int) -> float:
        rng = np.random.default_rng([self.seed, 11, idx])
        dur = self._base_duration * (0.8 + 0.4 * float(rng.random()))
        if self._slow_frac > 0.0 and float(rng.random()) < self._slow_frac:
            dur *= self._slow_factor
        return dur

    def _make_node(self, addr: str) -> _SimNode:
        idx = self._next_idx
        self._next_idx += 1
        node = _SimNode(addr, idx, self._init, 1 + idx % 3, self._draw_duration(idx))
        self.nodes[addr] = node
        return node

    def _target(self, idx: int) -> np.ndarray:
        t = self._targets.get(idx)
        if t is None:
            t = self._targets[idx] = self._target_base + np.random.default_rng(
                [self.seed, 7, idx]
            ).normal(size=self._dim).astype(np.float32)
        return t

    def _next_up(self, addr: str) -> int:
        # persistent per-node upward counter: a re-promoted aggregator
        # continuing at seq 1 would be rejected as a replay by its
        # parent's version vector
        c = self._up_seq.get(addr)
        if c is None:
            c = self._up_seq[addr] = itertools.count(1)
        return next(c)

    def export_spec(self, extra: int = 0, allow_custom: bool = False) -> Dict[str, Any]:
        """Dense-array export of this fleet's population — the megafleet
        parity hook: :meth:`p2pfl_tpu.federation.megafleet.FleetSpec.
        from_sim` builds the vectorized engine's population from exactly
        these arrays (sorted-address order == index order, so the two
        drivers' fold keys agree), which is what lets the 1k parity
        tests drive the SAME fleet through both engines.

        ``extra`` appends that many PENDING-JOINER rows past the current
        population — drawn from the same per-idx counter streams a later
        :meth:`inject_join` would use, so a churn plan's joiners carry
        identical durations/samples/targets in both drivers before they
        exist in the heap. ``allow_custom`` skips only the
        train_fn/loss_fn check: the gradient-task parity pin drives the
        heap with a vectorized-twin closure and exports the same
        population shape."""
        if set(self._init) != {"w"}:
            raise ValueError(
                "export_spec supports the consensus-task layout "
                "({'w': [dim]}) — custom workloads have no vectorized twin"
            )
        if not allow_custom and (
            getattr(self.train_fn, "__func__", None)
            is not SimulatedAsyncFleet._default_train
            or getattr(self.loss_fn, "__func__", None)
            is not SimulatedAsyncFleet._default_loss
        ):
            raise ValueError(
                "export_spec supports the default consensus workload — "
                "a custom train_fn/loss_fn has no vectorized twin"
            )
        if self.n + extra > 10_000:
            # simfleet pads addresses to 4 digits; past 10k its
            # lexicographic order no longer equals index order and the
            # two drivers' address schemes diverge — the parity hook
            # covers the heap's reachable scale, megafleet-native
            # populations use FleetSpec.synth
            raise ValueError(
                "export_spec is the <=10k parity hook (4-digit address "
                "regime); use FleetSpec.synth for larger populations"
            )
        addrs = sorted(self.nodes)
        nodes = [self.nodes[a] for a in addrs]
        # (idx, addr, samples, duration) rows: live nodes then pending
        # joiners continuing the idx sequence (same streams inject_join
        # will draw from)
        table = [(n.idx, n.addr, n.num_samples, n.duration) for n in nodes]
        for idx in range(self._next_idx, self._next_idx + extra):
            table.append(
                (idx, f"sim-{idx:04d}", 1 + idx % 3, self._draw_duration(idx))
            )
        addrs = [t[1] for t in table]
        slow = np.zeros(len(addrs), np.float64)
        if self.plan is not None:
            for j, a in enumerate(addrs):
                slow[j] = float(self.plan.slow_nodes.get(a, 0.0))
        return {
            "durations": np.asarray([t[3] for t in table], np.float64),
            "num_samples": np.asarray([t[2] for t in table], np.float32),
            "targets": np.stack(
                [self._target(t[0]) for t in table]
            ).astype(np.float32),
            "slow": slow,
            "init": np.asarray(self._init["w"], np.float32),
            "seed": self.seed,
            "link_delay": self.link_delay,
        }

    # ---- default workload ----

    def _default_train(self, idx: int, params: Pytree, rng: np.random.Generator) -> Pytree:
        t = self._target(idx)
        w = params["w"]
        return {"w": (w + self._local_lr * (t - np.asarray(w, np.float32))).astype(np.float32)}

    def _default_loss(self, params: Pytree) -> float:
        live = [a for a in self.router.live_members if a in self.nodes]
        weights = np.asarray([self.nodes[a].num_samples for a in live], np.float32)
        targets = np.stack([self._target(self.nodes[a].idx) for a in live])
        t_mean = (weights[:, None] * targets).sum(0) / weights.sum()
        diff = np.asarray(params["w"], np.float32) - t_mean
        return float(diff @ diff)

    # ---- fault plumbing (FaultPlan semantics on the virtual wire) ----

    def _edge_verdict(self, src: str, dst: str) -> Tuple[bool, bool, float]:
        """(dropped, duplicated, extra inbound latency) for one delivery."""
        slow = 0.0
        if self.plan is None:
            return False, False, slow
        slow = float(self.plan.slow_nodes.get(dst, 0.0))
        if self.plan.partitioned(src, dst):
            return True, False, slow
        fault = self.plan.edge_fault(src, dst)
        rng = self.plan.rng(src, dst)
        drop_u, dup_u, _jit_u = rng.random(), rng.random(), rng.random()
        dropped = bool(fault.drop) and drop_u < fault.drop
        dup = (not dropped) and bool(fault.duplicate) and dup_u < fault.duplicate
        return dropped, dup, slow + fault.delay

    def _crash_spec(self, addr: str):
        if self.plan is None:
            return None
        return self.plan.crashes.get(addr)

    def _restart_spec(self, addr: str):
        """The node's kill-and-resurrect spec, fire-once (the plan's
        ``_crashed`` set — the same latch the live stage hook uses, so a
        resumed node re-reaching the trigger round does not die again)."""
        if self.plan is None or addr in self.plan._crashed:
            return None
        return getattr(self.plan, "restarts", {}).get(addr)

    def _defense_for(self, addr: str):
        """The aggregator's admission screen (None when screening is off)."""
        from p2pfl_tpu.settings import Settings

        if not Settings.BYZ_SCREEN:
            return None
        d = self._defenses.get(addr)
        if d is None:
            from p2pfl_tpu.federation.defense import ByzantineDefense

            d = self._defenses[addr] = ByzantineDefense(addr)
        return d

    def _drain_quarantines(self, t: float, addr: str) -> None:
        """Turn an aggregator's fresh quarantine decisions into evict
        events — the virtual stand-in for the production path (defense →
        ``Neighbors.evict`` → eviction listeners → re-derivation). The
        attacker keeps training and pushing (its control plane is
        healthy); its arrivals are dropped by the quarantine gate and the
        topology re-derives around it like around any other hole."""
        d = self._defenses.get(addr)
        if d is None:
            return
        for origin in d.take_quarantined():
            if origin not in self.result.quarantined:
                self.result.quarantined.append(origin)
            self._push(t, "evict", (origin,))

    # ---- membership events (the elastic seam) ----

    def _rederive(self, t: float) -> None:
        old_root = self.router.root
        self.router = TierRouter(self._members, self.cluster_size, dead=self._dead)
        if self.router.root != old_root:
            self.result.failovers += 1
        self._reconcile(t)

    def _agg_snapshot(self, addr: str) -> Tuple[Pytree, int]:
        """An aggregator's current global view (bootstrap-pull stand-in)."""
        bufs = self._buffers.get(addr, {})
        for tier in ("global", "regional"):
            if tier in bufs:
                return bufs[tier].snapshot()
        node = self.nodes.get(addr)
        if node is not None and node.global_params is not None:
            return node.global_params, node.known_version
        return self._init, 0

    def _reconcile(self, t: float) -> None:
        """Migrate every live node's buffers to the new router's plan by
        executing the SHARED reconcile contract
        (:meth:`TierRouter.reconcile_ops`) — the same ops the production
        ``AsyncContext._reconcile_locked`` executes, so promotion
        seeding, demotion forwarding and K re-clamps cannot drift
        between the drivers."""
        for addr in sorted(self.nodes):
            node = self.nodes[addr]
            if node.crashed or addr in self._dead:
                # a corpse's buffers die with it (graceful leavers already
                # forwarded theirs before this point)
                self._buffers.pop(addr, None)
                continue
            bufs = self._buffers.get(addr, {})
            ops = self.router.reconcile_ops(
                addr, self._base_k, "regional" in bufs, "global" in bufs
            )
            for op in ops:
                if op.op == "forward":
                    self._forward_pending(t, addr, bufs.pop(op.tier), op.target)
                elif op.op == "create":
                    params, version = (
                        (node.global_params, node.known_version)
                        if node.global_params is not None
                        else (self._init, 0)
                    )
                    regional = op.tier == "regional"
                    floor = version if regional else max(version, node.high_water)
                    b = BufferedAggregator(
                        addr, params, k=op.k,
                        alpha=self._alpha, server_lr=self._server_lr,
                        max_staleness=self._max_staleness, bump_on_flush=not regional,
                        defense=self._defense_for(addr),
                    )
                    if floor > 0:
                        b.set_global(params, floor)
                    bufs[op.tier] = b
                else:  # resize
                    res = bufs[op.tier].set_k(op.k)
                    if res:
                        if op.tier == "global":
                            self._on_global_flush(t, res, addr)
                        else:
                            self._propagate_regional_flush(t, addr, res)
                        self._drain_quarantines(t, addr)
            if bufs:
                self._buffers[addr] = bufs
            else:
                self._buffers.pop(addr, None)

    def _forward_pending(
        self, t: float, src: str, buf: BufferedAggregator, dst: Optional[str]
    ) -> None:
        if dst is None or dst == src:
            return
        for upd in buf.take_pending():
            self._deliver_update(t, src, dst, upd)

    def _on_join(self, t: float, addr: str) -> None:
        if addr in self.nodes:
            return
        node = self._make_node(addr)
        self._members.add(addr)
        self.result.joined.append(addr)
        self._rederive(t)
        # bootstrap: pull the aggregator's current global (async_pull) —
        # the joiner's first update then trains from the fleet's state
        target = self.router.push_target(addr)
        if target is not None and target != addr:
            params, version = self._agg_snapshot(target)
            if version > 0:
                self._push(
                    t + self.link_delay, "model_arrive",
                    (addr, params, version, target),
                )
        self._push(t + self.link_delay + node.duration, "train_done", (addr,))

    def _on_leave(self, t: float, addr: str, graceful: bool) -> None:
        node = self.nodes.get(addr)
        if node is None or node.crashed or addr in self._dead:
            return
        node.crashed = True  # stops training and arrivals
        self.result.left.append(addr)
        if not graceful:
            # abrupt: discovered like a crash, one eviction window later
            self._push(t + self.evict_delay, "evict", (addr,))
            return
        # graceful: capture the partial buffers (and the pre-leave
        # fan-out) BEFORE the re-derivation drops them, announce
        # (everyone re-derives instantly in sim), then forward the
        # partials to the successor tiers
        bufs = self._buffers.pop(addr, {})
        pre_children = self.router.live_children(addr)
        self._dead.add(addr)
        self._rederive(t)
        b = bufs.get("regional")
        if b is not None:
            self._forward_pending(t, addr, b, self.router.push_target(addr))
        b = bufs.get("global")
        if b is not None:
            self._forward_pending(t, addr, b, self.router.root)
        # hand the successor tiers the freshest global the leaver holds —
        # the same handoff as production's graceful_leave_actions (the
        # leaver may be the only node that adopted the last mint)
        if node.global_params is not None and node.known_version > 0:
            targets = (set(self.router.regionals) | set(pre_children)) - {addr}
            for tgt in sorted(targets):
                if tgt not in self._dead:
                    self._deliver_model(
                        t, addr, tgt, node.global_params, node.known_version
                    )

    def _on_evict(self, t: float, addr: str, epoch: Optional[int] = None) -> None:
        # epoch-guarded evicts come from RestartSpec deaths: if the node
        # resurrected (or died again) since this evict was armed, the
        # epoch moved on and this event is about a corpse that no longer
        # exists. Un-epoched evicts (quarantine, abrupt leave, CrashSpec)
        # stay unconditional — their targets never come back.
        if epoch is not None and self._death_epoch.get(addr, 0) != epoch:
            return
        if addr in self._dead:
            return
        self._dead.add(addr)
        self._buffers.pop(addr, None)  # a corpse's pending dies with it
        self._rederive(t)

    def _on_resurrect(self, t: float, addr: str) -> None:
        """A RestartSpec node comes back FROM ITS JOURNAL: same address,
        retained ``seq`` counter / ``high_water`` / model and adopted
        global (the :class:`_SimNode`'s in-memory retention is the
        virtual stand-in for a perfect :class:`~p2pfl_tpu.federation.
        durability.NodeJournal`), re-entering through the same elastic
        seam a joiner uses — re-derivation plus a bootstrap pull that
        catches it up on any global minted while it was dead. Because
        ``seq`` continues where it stopped, upstream version vectors
        accept its first post-resurrection push and dedup any pre-crash
        in-flight duplicate — the property the live drill pins."""
        node = self.nodes.get(addr)
        if node is None or not node.crashed:
            return
        # invalidate this death's pending evict whether or not it fired
        self._death_epoch[addr] = self._death_epoch.get(addr, 0) + 1
        node.crashed = False
        self.result.restarted.append(addr)
        if addr in self._dead:
            self._dead.discard(addr)
            self._rederive(t)
        # bootstrap pull (the _on_join idiom): adopt anything newer than
        # the journaled global; _adopt's version gate drops a stale reply
        target = self.router.push_target(addr)
        if target is not None and target != addr:
            params, version = self._agg_snapshot(target)
            if version > 0:
                self._push(
                    t + self.link_delay, "model_arrive",
                    (addr, params, version, target),
                )
        if node.updates_done < self.updates_per_node:
            self._push(t + node.duration, "train_done", (addr,))

    # ---- event loop ----

    def _push(self, t: float, kind: str, payload: tuple) -> None:
        heapq.heappush(self._heap, (t, next(self._evseq), kind, payload))

    def run(self) -> FleetResult:
        for addr in sorted(self.nodes):
            self._push(self.nodes[addr].duration, "train_done", (addr,))
        if self.plan is not None:
            for addr in sorted(getattr(self.plan, "joins", {})):
                self._push(self.plan.joins[addr].at_s, "join", (addr,))
            for addr in sorted(getattr(self.plan, "leaves", {})):
                spec = self.plan.leaves[addr]
                self._push(spec.at_s, "leave", (addr, bool(spec.graceful)))
        while self._heap:
            t, _seq, kind, payload = heapq.heappop(self._heap)
            self.result.virtual_time = t
            if kind == "train_done":
                self._on_train_done(t, *payload)
            elif kind == "update_arrive":
                self._on_update_arrive(t, *payload)
            elif kind == "model_arrive":
                self._on_model_arrive(t, *payload)
            elif kind == "join":
                self._on_join(t, *payload)
            elif kind == "leave":
                self._on_leave(t, *payload)
            elif kind == "evict":
                self._on_evict(t, *payload)
            elif kind == "resurrect":
                self._on_resurrect(t, *payload)
        root = self.router.root
        gbuf = self._buffers.get(root, {}).get("global") if root else None
        if gbuf is not None:
            self.result.params, self.result.version = gbuf.snapshot()
            self.result.merges = gbuf.merges
        self.result.screen_rejects = sum(
            d.screen_rejects for d in self._defenses.values()
        )
        return self.result

    def _on_train_done(self, t: float, addr: str) -> None:
        node = self.nodes[addr]
        if node.crashed:
            return
        spec = self._crash_spec(addr)
        if (
            spec is not None
            and spec.stage == "AsyncTrainStage"
            and (spec.round_no is None or spec.round_no == node.updates_done)
        ):
            node.crashed = True
            self.result.crashed.append(addr)
            # survivors discover the corpse one eviction window later and
            # re-derive the topology around the hole (successor election,
            # K repair) — the heartbeat plane's virtual stand-in
            self._push(t + self.evict_delay, "evict", (addr,))
            return
        rspec = self._restart_spec(addr)
        if (
            rspec is not None
            and rspec.stage == "AsyncTrainStage"
            and (rspec.round_no is None or rspec.round_no == node.updates_done)
        ):
            self.plan._crashed.add(addr)
            node.crashed = True
            self.result.crashed.append(addr)
            ep = self._death_epoch.get(addr, 0) + 1
            self._death_epoch[addr] = ep
            # the evict carries this death's epoch: a resurrection that
            # lands before the eviction window closes invalidates it
            self._push(t + self.evict_delay, "evict", (addr, ep))
            self._push(t + max(rspec.resume_after_s, 1e-6), "resurrect", (addr,))
            return
        # adopt the freshest global that arrived while "training"
        if node.pending_global is not None:
            params, version = node.pending_global
            node.model = params
            node.base_version = version
            node.pending_global = None
        rng = np.random.default_rng([self.seed, 13, node.idx, node.updates_done])
        node.model = self.train_fn(node.idx, node.model, rng)
        node.updates_done += 1
        upd = ModelUpdate(node.model, [addr], node.num_samples)
        upd.version = (addr, next(node.seq), node.base_version)
        self.result.updates_sent += 1
        target = self.router.push_target(addr)
        if target is not None:
            self._deliver_update(t, addr, target, upd)
        if node.updates_done < self.updates_per_node:
            self._push(t + node.duration, "train_done", (addr,))

    def _deliver_update(self, t: float, src: str, dst: str, upd: ModelUpdate) -> None:
        if src == dst:
            self._push(t, "update_arrive", (dst, upd, src))
            return
        if self.plan is not None and self.plan.byzantine:
            # the virtual wire's _do_send seam: the SAME corruption helper
            # the live FaultInjector runs, so a plan's attack replays
            # bit-exact on the virtual clock (self-pushes above stay
            # honest, matching production where they skip the send seam)
            from p2pfl_tpu.communication.faults import byz_corrupt_update

            bad = byz_corrupt_update(self.plan, src, dst, upd, "async_update")
            if bad is not None:
                self.result.byz_corrupted += 1
                upd = bad
        dropped, dup, extra = self._edge_verdict(src, dst)
        if dropped:
            self.result.updates_dropped_wire += 1
            return
        self._push(t + self.link_delay + extra, "update_arrive", (dst, upd, src))
        if dup:
            self.result.duplicates_injected += 1
            fault = self.plan.edge_fault(src, dst)
            self._push(
                t + self.link_delay + extra + max(fault.duplicate_delay, 1e-6),
                "update_arrive",
                (dst, upd, src),
            )

    def _on_update_arrive(self, t: float, dst: str, upd: ModelUpdate, src: str) -> None:
        node = self.nodes.get(dst)
        if node is None or node.crashed:
            return
        if upd.version:
            node.high_water = max(node.high_water, int(upd.version[2]))
        origin = str(upd.version[0]) if upd.version else ""
        sink = self.router.update_sink(dst, origin)
        bufs = self._buffers.get(dst)
        if sink is None or bufs is None or sink not in bufs:
            return  # mis-route under the current view (sender ahead of an event)
        self.result.updates_delivered += 1
        # screen attribution = the delivering peer (production parity:
        # the in-payload origin is attacker-controlled, a framing vector)
        if sink == "global":
            res = bufs["global"].offer(upd, screen_origin=src)
            if res:
                self._on_global_flush(t, res, dst)
        else:
            res = bufs["regional"].offer(upd, screen_origin=src)
            if res:
                self._propagate_regional_flush(t, dst, res)
        # an offer may have crossed an origin's suspicion threshold:
        # quarantine = an evict event, deterministically placed at t
        self._drain_quarantines(t, dst)

    def _propagate_regional_flush(self, t: float, addr: str, res) -> None:
        up = ModelUpdate(res.params, res.contributors, res.num_samples)
        up.version = (addr, self._next_up(addr), res.version)
        bufs = self._buffers.get(addr, {})
        if "global" in bufs:  # the root's own cluster feeding its global tier
            gres = bufs["global"].offer(up)
            if gres:
                self._on_global_flush(t, gres, addr)
            return
        root = self.router.root
        if root is not None and root != addr:
            self._deliver_update(t, addr, root, up)

    def _on_global_flush(self, t: float, res, root: str) -> None:
        loss = float(self.loss_fn(res.params))
        self.result.loss_curve.append((t, res.version, loss))
        if self.result.time_to_target is None and loss <= self.target_loss:
            self.result.time_to_target = t
        self._adopt(t, root, res.params, res.version, forward=False)
        for child in self.router.live_children(root):
            self._deliver_model(t, root, child, res.params, res.version)

    def _deliver_model(self, t: float, src: str, dst: str, params: Pytree, version: int) -> None:
        dropped, dup, extra = self._edge_verdict(src, dst)
        if dropped:
            return
        self._push(t + self.link_delay + extra, "model_arrive", (dst, params, version, src))
        if dup:
            fault = self.plan.edge_fault(src, dst)
            self._push(
                t + self.link_delay + extra + max(fault.duplicate_delay, 1e-6),
                "model_arrive",
                (dst, params, version, src),
            )

    def _on_model_arrive(self, t: float, dst: str, params: Pytree, version: int, src: str) -> None:
        self._adopt(t, dst, params, version, forward=True, source=src)

    def _adopt(
        self, t: float, addr: str, params: Pytree, version: int,
        forward: bool, source: Optional[str] = None,
    ) -> None:
        node = self.nodes.get(addr)
        if node is None or node.crashed:
            return
        node.high_water = max(node.high_water, version)
        if version <= node.known_version:
            return
        node.known_version = version
        node.global_params = params
        node.pending_global = (params, version)
        bufs = self._buffers.get(addr)
        if bufs is not None and "regional" in bufs:
            bufs["regional"].set_global(params, version)
        if forward:
            for child in self.router.live_children(addr):
                if child != source:
                    self._deliver_model(t, addr, child, params, version)


