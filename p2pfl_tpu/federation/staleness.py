"""Staleness weighting and per-node version vectors.

The async control plane has no rounds, so "how old is this update?" can't
be a round delta. Instead every aggregator tier counts **global model
versions** (one merge = one version), and every update carries the version
it was trained *from* (``UpdateVersion.base_version``). Staleness is then

    τ = version_at_merge − base_version      (≥ 0, no global clock needed)

and the update's effective weight is ``num_samples · w(τ)`` with the
FedBuff polynomial weight ``w(τ) = 1/(1+τ)^α`` (Nguyen et al. 2022 §5).
``Settings.ASYNC_MAX_STALENESS`` bounds τ: beyond it the update is dropped
outright — a wedged straggler's ancient update must never touch the model,
however small its weight (bounded staleness, not merely decayed).

:class:`VersionVector` is the dedup half: one monotone per-origin sequence
counter. The data plane has no dedup ring (weights envelopes are
re-deliverable by design — FaultPlan duplicates, send retries, TTL relays),
and in the sync FSM the aggregator's contributor-overlap checks absorb
replays. The async buffer has no contributor algebra, so the version
vector is what keeps a duplicated or reordered delivery from ever merging
twice: an ``(origin, seq)`` at or below the vector's entry is a replay.
"""

from __future__ import annotations

import threading
from typing import Dict, NamedTuple, Optional


class UpdateVersion(NamedTuple):
    """The wire version triple riding ``ModelUpdate.version``.

    Serialized as the optional ``"vv"`` key of the gRPC weights-envelope
    header (absent → old frames decode unchanged; the protobuf interop
    schema never carries it — same compatibility contract as the
    telemetry ``"tc"`` field).
    """

    origin: str  #: producing node (or regional aggregator) address
    seq: int  #: monotone per-origin update counter (dedup key)
    base_version: int  #: global model version the update was trained from


def as_version(value) -> Optional[UpdateVersion]:
    """Normalize a wire tuple/list (or None) into an :class:`UpdateVersion`."""
    if value is None:
        return None
    origin, seq, base = value
    return UpdateVersion(str(origin), int(seq), int(base))


def xp_mismatch(addr: str, frame_xp: Optional[str], local_xid: Optional[str]) -> bool:
    """True when a frame's experiment identity contradicts ours — the ONE
    filtering rule every async plane shares (weights handlers, the
    done/join/leave control gates, the stash filters' exact branch).

    Only a definite contradiction filters: frames from pre-"xp" senders
    (``frame_xp is None``) and nodes without an identity yet (a joiner
    before its bootstrap) fall through to each caller's fallback
    heuristics. Counts ``async_xp_filtered`` so filtered cross-experiment
    stragglers are visible in the comm metrics.
    """
    if frame_xp is None or local_xid is None or frame_xp == local_xid:
        return False
    from p2pfl_tpu.management.logger import logger

    logger.log_comm_metric(addr, "async_xp_filtered")
    return True


def staleness_weight(tau: float, alpha: float) -> float:
    """FedBuff polynomial staleness weight ``w(τ) = 1/(1+τ)^α``.

    ``w(0) = 1`` always; ``alpha = 0`` disables down-weighting (every
    update counts at full weight regardless of age); larger α discounts
    stale updates harder. Negative τ (an update trained from a version
    the merging tier has not reached — possible transiently when a
    regional's global view lags a fast edge) clamps to 0: "from the
    future" is simply fresh.
    """
    tau = max(float(tau), 0.0)
    if alpha == 0.0:
        return 1.0
    return 1.0 / (1.0 + tau) ** float(alpha)


class VersionVector:
    """Per-origin high-water marks: ``origin → highest seq accepted``.

    ``observe`` is the single gate: it returns True exactly once per
    ``(origin, seq)`` *at or above* the current mark — duplicates and
    anything at/below the mark are rejected. Out-of-order arrivals
    *ahead* of the mark are accepted (seq 3 after seq 1 when seq 2 was
    dropped on the wire: the update is real and newer, the gap is a
    lost update, not a protocol error); the mark then jumps, so the
    late seq-2 straggler is rejected as stale. That asymmetry is
    deliberate: the buffer wants the newest state of every node, not an
    exactly-once ledger.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._seen: Dict[str, int] = {}

    def observe(self, origin: str, seq: int) -> bool:
        """Accept-and-advance; False for duplicates / superseded seqs."""
        with self._lock:
            if seq <= self._seen.get(origin, 0):
                return False
            self._seen[origin] = seq
            return True

    def last(self, origin: str) -> int:
        with self._lock:
            return self._seen.get(origin, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._seen)

    def merge(self, other: Dict[str, int]) -> None:
        """Pointwise max-merge (monotone, like every control-plane merge
        since the round-0 wedge fix — version vectors form a lattice)."""
        with self._lock:
            for origin, seq in other.items():
                if seq > self._seen.get(origin, 0):
                    self._seen[origin] = seq
