"""Crash-consistent node journals: a node comes back as ITSELF.

Every robustness layer so far (FaultPlan crashes, breakers, elastic
failover, Byzantine quarantine) treats a crashed node as permanently
dead. Production FL is a continuous service where device restarts are
weather, not funerals (Bonawitz et al., MLSys 2019) — and the FedBuff
async plane already has the dedup machinery (per-origin
:class:`~p2pfl_tpu.federation.staleness.VersionVector`, bounded
staleness) that makes safe re-entry *provable*. What was missing is the
state that feeds it surviving the process.

A :class:`NodeJournal` snapshots everything a node needs to resurrect:

- the adopted global model + its version, the ``base_version`` the
  learner trained from, and the version high-water mark;
- the node's own monotone ``train_seq`` / ``up_seq`` counters — resumed
  STRICTLY PAST the journaled value plus ``Settings.JOURNAL_SEQ_MARGIN``,
  so the resurrected node's first push can never be rejected as a replay
  by an upstream version vector, while its pre-crash in-flight updates
  dedup instead of double-merging (the VersionVector accepts seq gaps by
  design: a gap is a lost update, not a protocol error);
- each :class:`~p2pfl_tpu.federation.buffer.BufferedAggregator` tier's
  pending contributions with their ORIGINAL version triples intact (so
  the PR-11 successor-forward idiom applies verbatim when the restart
  re-derives the node into a different role) plus the tier's version
  vector and version counter;
- the membership ``(members, dead)`` view, the Byzantine suspicion
  EWMAs + quarantine set, and the ``xp`` experiment identity;
- the learner's params/opt_state — through orbax
  (:mod:`~p2pfl_tpu.learning.checkpoint`, with the ``keep_n`` retention
  knob) when the learner exposes ``params``/``opt_state``, or as a
  codec blob otherwise.

Crash consistency is the native-codec idiom hardened with a manifest:
every snapshot is written to a private temp file and promoted with
``os.replace`` (atomic on POSIX), carries a whole-file CRC32, and only
THEN does the ``MANIFEST`` (itself tmp+replace) name it committed. A
kill at any byte offset therefore leaves either the previous committed
snapshot (manifest still names it) or a torn temp file nobody reads; a
corrupted manifest falls back to scanning for the newest snapshot whose
CRC verifies, and a corrupted snapshot falls back to the previous one.
The torture test (``tests/test_durability.py``) kills writes at random
offsets ≥50 times and asserts recovery always lands on a committed
snapshot, never a torn one.

Model payloads inside a snapshot ride the wire codec
(:func:`~p2pfl_tpu.learning.weights.encode_params` /
``decode_params`` — self-describing binary with per-tensor CRC32C, no
pickle), so the journal format is exactly as forward-compatible as the
wire. Pytrees are rebuilt with ``restore_like`` against the learner's
parameter structure (the same model structure fleet-wide).

Nothing here runs under a context or buffer lock:
:func:`capture_snapshot` copies state under the locks and returns, and
``commit_snapshot`` does its disk I/O outside them — a journal fsync
held under the context lock would stall every handler thread exactly
like a send would, so p2pfl-check's send-under-lock rule lists
``commit_snapshot`` among the calls no lock may be held across.
"""

from __future__ import annotations

import json
import os
import re
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from p2pfl_tpu.learning.weights import ModelUpdate, decode_params, encode_params
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.management.telemetry import telemetry
from p2pfl_tpu.settings import Settings

Pytree = Any

#: journal frame magic + format version (bump on layout change)
_MAGIC = b"P2PJ1"
_MANIFEST = "MANIFEST"
_SNAP_RE = re.compile(r"^snap-(\d+)\.p2pj$")


class SeqCounter:
    """A ``next()``-able monotone counter whose NEXT value is readable —
    ``itertools.count`` with a journalable position. The async context's
    ``train_seq``/``up_seq`` use this so a snapshot can record exactly
    where the stream stood (and a resurrection can resume strictly past
    it)."""

    __slots__ = ("_next",)

    def __init__(self, start: int = 1) -> None:
        self._next = int(start)

    def __iter__(self) -> "SeqCounter":
        return self

    def __next__(self) -> int:
        v = self._next
        self._next = v + 1
        return v

    @property
    def next_value(self) -> int:
        """The value the next ``next()`` will return (never issued yet)."""
        return self._next


@dataclass
class BufferJournal:
    """One aggregation tier's journaled state. ``pending`` keeps the
    ORIGINAL ``(origin, seq, base_version)`` triples so a restart that
    re-derives this node into a different role can forward them raw to
    the successor tier (the PR-11 buffer-migration idiom, verbatim)."""

    tier: str  #: "regional" | "global"
    version: int
    vv: Dict[str, int]
    #: [(origin, seq, base_version, contributors, num_samples, params)]
    pending: List[Tuple[str, int, int, List[str], int, Any]]


@dataclass
class JournalSnapshot:
    """Everything :meth:`NodeJournal.commit_snapshot` persists and
    :meth:`NodeJournal.recover` rebuilds. ``*_params`` fields hold
    pytrees on capture; after a template-less recover they hold flat
    ``{path: ndarray}`` dicts (see :meth:`NodeJournal.recover`)."""

    addr: str
    snap: int = 0
    xid: Optional[str] = None
    members: List[str] = field(default_factory=list)
    dead: List[str] = field(default_factory=list)
    global_version: int = 0
    base_version: int = 0
    high_water: int = 0
    train_seq: int = 1  #: NEXT unissued training-update seq at capture
    up_seq: int = 1  #: NEXT unissued upward-aggregate seq at capture
    total_rounds: int = 0
    updates_done: int = 0
    suspicion: Dict[str, float] = field(default_factory=dict)
    quarantined: List[str] = field(default_factory=list)
    global_params: Optional[Any] = None
    buffers: List[BufferJournal] = field(default_factory=list)
    #: orbax step of the learner checkpoint riding in ``<dir>/learner``
    #: (None = the learner was journaled as a codec blob instead)
    learner_step: Optional[int] = None
    learner_params: Optional[Any] = None
    #: wall-clock milliseconds :meth:`NodeJournal.recover` spent — the
    #: death→resurrection gap's journal-read component, re-emitted as the
    #: ``journal_recovery_ms`` comm metric by the resuming node
    recovery_ms: float = 0.0


def capture_snapshot(node: Any, ctx: Any) -> JournalSnapshot:
    """Copy everything a resurrection needs, under the context/buffer
    locks — the caller commits the returned snapshot OUTSIDE them."""
    with ctx.lock:
        snap = JournalSnapshot(
            addr=node.addr,
            xid=ctx.xid,
            members=sorted(ctx.members),
            dead=sorted(ctx._dead),
            global_version=ctx.global_version,
            base_version=ctx.base_version,
            high_water=ctx.high_water.mark,
            train_seq=ctx.train_seq.next_value,
            up_seq=ctx._up_seq.next_value,
            total_rounds=node.total_rounds,
            updates_done=int(node.state.round or 0),
            global_params=ctx.last_global[0] if ctx.last_global else None,
        )
        if ctx.last_global is not None:
            # the adopted global's version, not the newest merely KNOWN
            # one: the learner's params came from (at most) this
            snap.global_version = ctx.last_global[1]
        rbuf, gbuf = ctx.rbuf, ctx.gbuf
    for tier, buf in (("regional", rbuf), ("global", gbuf)):
        if buf is not None:
            snap.buffers.append(buf.journal_state(tier))
    suspicion, quarantined = node.defense.journal_state()
    snap.suspicion = suspicion
    snap.quarantined = quarantined
    return snap


class NodeJournal:
    """Durable snapshot store for one node (one directory per node).

    Not thread-safe against concurrent commits — snapshots are taken on
    the learning thread only (the workflow's cadence hook), which also
    matches the crash model: one writer, killed at an arbitrary byte.
    """

    def __init__(
        self,
        directory: str,
        node_name: str = "",
        keep_n: Optional[int] = None,
    ) -> None:
        self.directory = os.path.abspath(os.path.expanduser(directory))
        self.node_name = node_name
        self.keep_n = int(Settings.JOURNAL_KEEP_N if keep_n is None else keep_n)
        os.makedirs(self.directory, exist_ok=True)
        self._next_snap = self._scan_highest() + 1

    # ---- write path ----

    def commit_snapshot(self, snap: JournalSnapshot, learner: Any = None) -> str:
        """Atomically persist ``snap`` (+ the learner) and commit it in
        the manifest. Returns the committed snapshot filename.

        Write order is the whole crash-consistency argument: (1) learner
        checkpoint (orbax's own atomic finalize, or a blob inside the
        frame), (2) snapshot frame to ``.tmp`` → fsync → ``os.replace``,
        (3) manifest to ``.tmp`` → fsync → ``os.replace``. A kill before
        (3) leaves the manifest naming the PREVIOUS snapshot; a kill
        inside any write leaves only a torn temp file nobody reads.
        """
        n = self._next_snap
        snap.snap = n
        if learner is not None:
            if hasattr(learner, "params") and hasattr(learner, "opt_state"):
                from p2pfl_tpu.learning.checkpoint import save_learner

                save_learner(
                    os.path.join(self.directory, "learner"),
                    learner,
                    round=n,
                    keep_n=max(self.keep_n, 1) if self.keep_n else None,
                )
                snap.learner_step = n
                snap.learner_params = None
            else:
                snap.learner_step = None
                snap.learner_params = learner.get_parameters()
        payload = self._encode(snap)
        name = f"snap-{n}.p2pj"
        self._write_atomic(name, payload)
        manifest = json.dumps(
            {"snapshot": name, "snap": n, "crc": zlib.crc32(payload) & 0xFFFFFFFF}
        ).encode("utf-8")
        self._write_atomic(_MANIFEST, manifest)
        self._next_snap = n + 1
        self._gc(keep_through=n)
        owner = self.node_name or snap.addr
        logger.log_comm_metric(owner, "journal_snapshot")
        logger.log_comm_metric(owner, "journal_bytes", float(len(payload)))
        telemetry.event(
            owner,
            "journal_snapshot",
            kind="stage",
            attrs={
                "snap": n,
                "bytes": len(payload),
                "pending": sum(len(b.pending) for b in snap.buffers),
                "version": snap.global_version,
            },
        )
        return name

    def _write_atomic(self, name: str, payload: bytes) -> None:
        """The native-codec idiom: private temp file, fsync, promote with
        ``os.replace`` — readers see the old bytes or the new bytes,
        never a prefix."""
        final = os.path.join(self.directory, name)
        tmp = f"{final}.tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, final)
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass

    def _gc(self, keep_through: int) -> None:
        """Drop snapshots older than the newest ``keep_n`` (0 = keep
        all). The committed snapshot is always kept."""
        if self.keep_n <= 0:
            return
        snaps = sorted(self._snapshots())
        for n in snaps[: -self.keep_n]:
            if n == keep_through:
                continue
            try:
                os.remove(os.path.join(self.directory, f"snap-{n}.p2pj"))
            except OSError:
                pass

    # ---- read path ----

    def recover(
        self, template: Optional[Pytree] = None, learner: Any = None
    ) -> Optional[JournalSnapshot]:
        """Load the last COMMITTED snapshot, or None when the journal is
        empty/unrecoverable. Integrity is checked both ways: the
        manifest's CRC must match the frame it names AND the frame's own
        trailing CRC must verify; on any mismatch the scan falls back to
        the newest snapshot that self-verifies (then the next, …).

        With ``template`` (a pytree with the fleet's model structure —
        the resuming learner's parameters), params are rebuilt as full
        pytrees; without one they stay flat ``{path: ndarray}`` dicts
        (enough for the torture tests' byte-level comparisons). With
        ``learner``, an orbax learner checkpoint is restored into it.
        """
        t0 = time.monotonic()
        candidates: List[str] = []
        committed = self._read_manifest()
        if committed is not None:
            candidates.append(committed)
        for n in sorted(self._snapshots(), reverse=True):
            name = f"snap-{n}.p2pj"
            if name not in candidates:
                candidates.append(name)
        for name in candidates:
            snap = self._try_load(name, template)
            if snap is None:
                continue
            if learner is not None and snap.learner_step is not None:
                from p2pfl_tpu.learning.checkpoint import restore_learner

                restore_learner(
                    os.path.join(self.directory, "learner"),
                    learner,
                    step=snap.learner_step,
                )
            self._next_snap = max(self._next_snap, snap.snap + 1)
            snap.recovery_ms = (time.monotonic() - t0) * 1000.0
            owner = self.node_name or snap.addr
            logger.log_comm_metric(owner, "journal_recovered")
            logger.log_comm_metric(
                owner, "journal_recovery_ms", round(snap.recovery_ms, 3)
            )
            telemetry.event(
                owner,
                "journal_recovered",
                kind="stage",
                attrs={
                    "snap": snap.snap,
                    "from": name,
                    "recovery_ms": round(snap.recovery_ms, 3),
                    "version": snap.global_version,
                },
            )
            return snap
        return None

    def _read_manifest(self) -> Optional[str]:
        try:
            with open(os.path.join(self.directory, _MANIFEST), "rb") as f:
                doc = json.loads(f.read().decode("utf-8"))
            name = doc["snapshot"]
            with open(os.path.join(self.directory, name), "rb") as f:
                payload = f.read()
            if zlib.crc32(payload) & 0xFFFFFFFF != int(doc["crc"]):
                logger.warning(
                    self.node_name or self.directory,
                    f"journal manifest CRC mismatch for {name} — falling "
                    "back to snapshot scan",
                )
                return None
            return name
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def _try_load(
        self, name: str, template: Optional[Pytree]
    ) -> Optional[JournalSnapshot]:
        try:
            with open(os.path.join(self.directory, name), "rb") as f:
                payload = f.read()
            return self._decode(payload, template)
        except Exception as exc:  # noqa: BLE001 — a torn frame is expected, not fatal
            logger.warning(
                self.node_name or self.directory,
                f"journal snapshot {name} unreadable ({exc!r}) — trying older",
            )
            return None

    def _snapshots(self) -> List[int]:
        out = []
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return out
        for entry in entries:
            m = _SNAP_RE.match(entry)
            if m:
                out.append(int(m.group(1)))
        return out

    def _scan_highest(self) -> int:
        snaps = self._snapshots()
        return max(snaps) if snaps else 0

    # ---- frame codec ----

    def _encode(self, snap: JournalSnapshot) -> bytes:
        blobs: List[bytes] = []

        def blob(tree: Any) -> int:
            blobs.append(encode_params(tree))
            return len(blobs) - 1

        header: Dict[str, Any] = {
            "addr": snap.addr,
            "snap": snap.snap,
            "xid": snap.xid,
            "members": snap.members,
            "dead": snap.dead,
            "global_version": snap.global_version,
            "base_version": snap.base_version,
            "high_water": snap.high_water,
            "train_seq": snap.train_seq,
            "up_seq": snap.up_seq,
            "total_rounds": snap.total_rounds,
            "updates_done": snap.updates_done,
            "suspicion": snap.suspicion,
            "quarantined": snap.quarantined,
            "learner_step": snap.learner_step,
            "global_blob": (
                blob(snap.global_params) if snap.global_params is not None else None
            ),
            "learner_blob": (
                blob(snap.learner_params) if snap.learner_params is not None else None
            ),
            "buffers": [
                {
                    "tier": b.tier,
                    "version": b.version,
                    "vv": b.vv,
                    "pending": [
                        {
                            "origin": origin,
                            "seq": seq,
                            "base": base,
                            "contributors": contributors,
                            "num_samples": num_samples,
                            "blob": blob(params),
                        }
                        for origin, seq, base, contributors, num_samples, params in b.pending
                    ],
                }
                for b in snap.buffers
            ],
        }
        header["blob_lens"] = [len(b) for b in blobs]
        hdr = json.dumps(header, separators=(",", ":")).encode("utf-8")
        frame = bytearray(_MAGIC)
        frame += len(hdr).to_bytes(4, "little")
        frame += hdr
        for b in blobs:
            frame += b
        frame += (zlib.crc32(bytes(frame)) & 0xFFFFFFFF).to_bytes(4, "little")
        return bytes(frame)

    def _decode(self, payload: bytes, template: Optional[Pytree]) -> JournalSnapshot:
        if len(payload) < len(_MAGIC) + 8:
            raise ValueError("journal frame truncated")
        if payload[: len(_MAGIC)] != _MAGIC:
            raise ValueError("bad journal magic")
        body, crc = payload[:-4], int.from_bytes(payload[-4:], "little")
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            raise ValueError("journal frame CRC mismatch (torn write?)")
        off = len(_MAGIC)
        hdr_len = int.from_bytes(payload[off : off + 4], "little")
        off += 4
        header = json.loads(payload[off : off + hdr_len].decode("utf-8"))
        off += hdr_len
        blobs: List[Any] = []
        for blen in header["blob_lens"]:
            flat = decode_params(payload[off : off + blen])
            off += blen
            if template is not None:
                from p2pfl_tpu.learning.weights import restore_like

                blobs.append(restore_like(template, flat))
            else:
                blobs.append(flat)
        snap = JournalSnapshot(
            addr=header["addr"],
            snap=int(header["snap"]),
            xid=header["xid"],
            members=list(header["members"]),
            dead=list(header["dead"]),
            global_version=int(header["global_version"]),
            base_version=int(header["base_version"]),
            high_water=int(header["high_water"]),
            train_seq=int(header["train_seq"]),
            up_seq=int(header["up_seq"]),
            total_rounds=int(header["total_rounds"]),
            updates_done=int(header["updates_done"]),
            suspicion={k: float(v) for k, v in header["suspicion"].items()},
            quarantined=list(header["quarantined"]),
            learner_step=header["learner_step"],
        )
        if header["global_blob"] is not None:
            snap.global_params = blobs[header["global_blob"]]
        if header.get("learner_blob") is not None:
            snap.learner_params = blobs[header["learner_blob"]]
        for b in header["buffers"]:
            snap.buffers.append(
                BufferJournal(
                    tier=b["tier"],
                    version=int(b["version"]),
                    vv={k: int(v) for k, v in b["vv"].items()},
                    pending=[
                        (
                            p["origin"],
                            int(p["seq"]),
                            int(p["base"]),
                            list(p["contributors"]),
                            int(p["num_samples"]),
                            blobs[p["blob"]],
                        )
                        for p in b["pending"]
                    ],
                )
            )
        return snap


def rebuild_updates(bj: BufferJournal, xid: Optional[str]) -> List[ModelUpdate]:
    """Reconstitute a journaled tier's pending entries as wire-shaped
    :class:`ModelUpdate` objects with their ORIGINAL version triples —
    ready to re-offer locally or forward raw to a successor tier."""
    out: List[ModelUpdate] = []
    for origin, seq, base, contributors, num_samples, params in bj.pending:
        upd = ModelUpdate(params, list(contributors), num_samples)
        upd.version = (origin, seq, base)
        upd.xp = xid
        out.append(upd)
    return out
