"""Async bounded-staleness federation (ROADMAP item 3).

The sync round FSM (``stages/learning_stages.py``) advances at the speed
of the slowest peer — every round is a barrier, which is why PR 5 had to
grow repair machinery. This package is the control plane that advances at
the speed of the **median** instead:

- :mod:`~p2pfl_tpu.federation.staleness` — the staleness weight
  ``w(τ) = 1/(1+τ)^α`` and per-node version vectors (dedup + staleness
  with no global clock);
- :mod:`~p2pfl_tpu.federation.buffer` — the FedBuff-style
  :class:`BufferedAggregator` (Nguyen et al., AISTATS 2022): apply
  contributions as they arrive, merge once K are buffered;
- :mod:`~p2pfl_tpu.federation.topology` — :class:`HierarchicalTopology`
  (HierFAVG, Liu et al., ICC 2020): edge clusters → elected regional
  aggregators → a global tier;
- :mod:`~p2pfl_tpu.federation.routing` — the node-free
  :class:`TierRouter`: tier/role derivation, buffer placement, update
  sinks and successor election as a pure function of
  ``(membership, dead set, cluster size)`` — consumed by BOTH the
  production workflow and the simulator, which is what makes elastic
  membership (joins, graceful leaves, root failover) testable at 10k
  simulated nodes before it touches a wire;
- :mod:`~p2pfl_tpu.federation.workflow` — the async learning workflow
  real nodes run when ``Settings.FEDERATION_MODE == "async"`` (selected
  in ``Node._run_learning``; all sends ride the ``_do_send`` seam, so
  FaultPlan, retries, breakers and telemetry wrap it for free);
- :mod:`~p2pfl_tpu.federation.simfleet` — a deterministic event-driven
  fleet simulator (1k–10k virtual nodes, virtual clock) for scale drives
  and bit-identical replay tests;
- :mod:`~p2pfl_tpu.federation.megafleet` — the simulator vectorized
  into one jitted array program (``ops/fleet_kernels.py``): ≥1M
  simulated clients with the heap driver as the bit-parity anchor at
  1k, plus the Bonawitz fleet-scale knobs (pace steering, selection
  over-provisioning, per-tier rate limits) as array-level controls;
- :mod:`~p2pfl_tpu.federation.defense` — Byzantine defense-in-depth:
  the per-contribution admission screen, the per-origin suspicion EWMA
  and the quarantine hook into the existing eviction path (robust merge
  kernels live in ``ops/aggregation``);
- :mod:`~p2pfl_tpu.federation.durability` — crash-resurrection: the
  crash-consistent :class:`NodeJournal` (atomic frame + manifest + CRC
  snapshots of everything a node needs to come back as itself) behind
  ``Node.enable_journal`` / ``Node.resume``.
"""

from p2pfl_tpu.federation.buffer import BufferedAggregator
from p2pfl_tpu.federation.defense import ByzantineDefense
from p2pfl_tpu.federation.durability import JournalSnapshot, NodeJournal, SeqCounter
from p2pfl_tpu.federation.megafleet import FleetSpec, MegaFleet, MegaFleetResult
from p2pfl_tpu.federation.routing import BufferPlan, TierRouter, VersionHighWater
from p2pfl_tpu.federation.simfleet import FleetResult, SimulatedAsyncFleet
from p2pfl_tpu.federation.staleness import UpdateVersion, VersionVector, staleness_weight
from p2pfl_tpu.federation.topology import HierarchicalTopology
from p2pfl_tpu.federation.workflow import AsyncLearningWorkflow

__all__ = [
    "AsyncLearningWorkflow",
    "BufferPlan",
    "BufferedAggregator",
    "ByzantineDefense",
    "FleetResult",
    "FleetSpec",
    "HierarchicalTopology",
    "JournalSnapshot",
    "MegaFleet",
    "NodeJournal",
    "SeqCounter",
    "MegaFleetResult",
    "SimulatedAsyncFleet",
    "TierRouter",
    "UpdateVersion",
    "VersionHighWater",
    "VersionVector",
    "staleness_weight",
]
