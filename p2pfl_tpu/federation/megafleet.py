"""Megafleet: the async fleet simulator vectorized to ≥1M clients.

:class:`~p2pfl_tpu.federation.simfleet.SimulatedAsyncFleet` is a Python
event heap pushing real per-node buffers through ``heapq`` — exact and
churn/adversary-capable, but ~10⁴ events/sec caps it at 1k–10k nodes.
This module re-expresses the same run as dense arrays advanced by one
jitted ``lax.scan`` (:mod:`~p2pfl_tpu.ops.fleet_kernels`): per-client
``(params, adopted version, train schedule, fault stream)`` state, the
regional tier as vectorized scatter-addressed windows, and the REAL math
as inner functions — the FedBuff ``w(τ)`` weighting, the
``(origin, seq)``-sorted K-flush fold (the very
:func:`~p2pfl_tpu.ops.aggregation.fedavg` /
:func:`~p2pfl_tpu.ops.aggregation.server_merge` kernels the live
:class:`~p2pfl_tpu.federation.buffer.BufferedAggregator` calls), and
:class:`~p2pfl_tpu.federation.routing.TierRouter`'s membership→tier
derivation (clusters, regional election, K clamps come from a real
router over the same addresses).

**The heap driver stays the bit-parity anchor.** At 1k nodes on the
consensus task, the flat vectorized engine reproduces the heap's merge
count, version sequence and staleness decisions EXACTLY (the scan's
chronological order is the heap's pop order — see
``ops/fleet_kernels.py``), with the loss trajectory matching to float
reassociation tolerance (the heap weights in Python f64, the scan in
f32; XLA may fuse the consensus step's multiply-add). The hierarchical
engine additionally approximates aggregate-arrival interleaving within
one ``link_delay`` window (documented in ``docs/design.md``); its parity
anchor pins merge counts exactly under a staleness bound wide enough
that boundary reorderings cannot flip an admission.

**Fault contract.** A :class:`~p2pfl_tpu.communication.faults.FaultPlan`
is consumed through counter-based seed-derived streams — dense verdict
grids indexed by ``(edge, send index)`` and generated in one vectorized
draw from ``(plan.seed, stream id)`` — instead of the heap's per-edge
Python ``random.Random`` streams, so a plan replays bit-exact from
``(seed, plan)`` without a million generator objects (the verdict
streams therefore differ from the heap's: plan-parity between the
drivers is statistical, not per-send). Supported: ``default``
drop/delay/jitter on upward sends — both the client→aggregator hop and
the regional→root aggregate hop, each from its own stream (downward
model pushes are delivered reliably with delay only; the heap can also
drop those — a documented divergence under drop plans),
``slow_nodes`` (inbound latency of the aggregator / the push-down hops),
``crashes`` (``AsyncTrainStage`` → the client stops producing after
``round_no`` updates; megafleet does NOT model the eviction/K-repair
that follows — at fleet scale K ≪ cluster fan-in and no buffer wedges).
Churn (joins/leaves), Byzantine specs, per-edge overrides, partitions
and duplicate injection raise loudly: the heap driver remains the
authority for membership and adversarial dynamics; megafleet exists for the phenomena that only
appear at fleet scale (Bonawitz et al., MLSys'19) — staleness
distributions, pace steering, selection over-provisioning, per-tier
rate limits — which it exposes as array-level controls no per-edge
Python loop could sweep.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from p2pfl_tpu.federation.routing import TierRouter
from p2pfl_tpu.federation.simfleet import FleetResult

Pytree = Any

#: dedicated stream ids for the counter-based draws — one sub-seed per
#: concern, FaultPlan-style, so arming one knob never shifts another's
#: verdicts (e.g. enabling selection must not move drop outcomes)
_STREAM_POP = 17  #: population shape (durations, slow membership)
_STREAM_TARGET = 7  #: per-client consensus targets (matches simfleet)
_STREAM_SELECT = 19
_STREAM_DROP = 23
_STREAM_JITTER = 29
_STREAM_PACE = 31
_STREAM_AGG_DROP = 37  #: regional→root aggregate send verdicts
_STREAM_AGG_JIT = 41


@dataclass
class FleetSpec:
    """The dense edge population: everything per-client as one array.

    Built two ways: :meth:`from_sim` exports a live heap fleet's exact
    population (durations, sample weights, targets — the parity hook:
    both drivers then simulate the SAME fleet), and :meth:`synth`
    derives a population of any size from vectorized counter-based
    streams (the ≥1M path — deterministic in ``(n, seed)``, but not the
    heap's per-idx streams, which would cost one Python generator per
    client).
    """

    durations: np.ndarray  #: [N] f64 — per-update train duration
    num_samples: np.ndarray  #: [N] f32 — sample weights (FedAvg numerators)
    targets: np.ndarray  #: [N, dim] f32 — consensus-task private targets
    slow: np.ndarray  #: [N] f64 — extra inbound latency when aggregator
    init: np.ndarray  #: [dim] f32 — shared initial model
    seed: int
    #: the exporting fleet's wire latency (None: engine default). Carried
    #: so a from_sim spec drives the vectorized twin with the SAME clock
    #: without the caller re-passing it.
    link_delay: Optional[float] = None

    @property
    def n(self) -> int:
        return int(self.durations.shape[0])

    @property
    def dim(self) -> int:
        return int(self.targets.shape[1])

    def target_mean(self) -> np.ndarray:
        """The fleet's consensus fixed point: the sample-weighted target
        mean (the heap's ``_default_loss`` reference over full
        membership)."""
        w = self.num_samples.astype(np.float32)
        return (w[:, None] * self.targets).sum(0) / w.sum()

    def loss(self, params: np.ndarray) -> float:
        d = np.asarray(params, np.float32) - self.target_mean()
        return float((d * d).sum())

    @classmethod
    def from_sim(cls, fleet) -> "FleetSpec":
        """Export a :class:`SimulatedAsyncFleet`'s population via its
        :meth:`~p2pfl_tpu.federation.simfleet.SimulatedAsyncFleet.
        export_spec` hook (sorted address order == index order — the two
        drivers' fold keys agree)."""
        d = fleet.export_spec()
        return cls(
            durations=d["durations"],
            num_samples=d["num_samples"],
            targets=d["targets"],
            slow=d["slow"],
            init=d["init"],
            seed=d["seed"],
            link_delay=d["link_delay"],
        )

    @classmethod
    def synth(
        cls,
        n: int,
        *,
        seed: int = 0,
        dim: int = 16,
        base_duration: float = 1.0,
        slow_frac: float = 0.0,
        slow_factor: float = 10.0,
    ) -> "FleetSpec":
        """A megafleet-native population: same statistics as the heap's
        (duration jitter U[0.8, 1.2]·base, a ``slow_frac`` straggler
        population at ``slow_factor``×, samples ``1 + i mod 3``, targets
        = shared offset + private noise), drawn in three vectorized
        batches instead of N per-idx streams."""
        rng = np.random.default_rng([seed, _STREAM_POP])
        durations = base_duration * (0.8 + 0.4 * rng.random(n))
        if slow_frac > 0.0:
            durations = np.where(
                rng.random(n) < slow_frac, durations * slow_factor, durations
            )
        base = np.random.default_rng([seed, 5]).normal(size=dim).astype(np.float32) * 2.0
        noise = np.random.default_rng([seed, _STREAM_TARGET, n]).normal(
            size=(n, dim)
        ).astype(np.float32)
        return cls(
            durations=durations.astype(np.float64),
            num_samples=(1 + np.arange(n) % 3).astype(np.float32),
            targets=base[None, :] + noise,
            slow=np.zeros(n, np.float64),
            init=np.zeros(dim, np.float32),
            seed=int(seed),
        )


@dataclass
class MegaFleetResult(FleetResult):
    """A :class:`FleetResult` (the heap drivers' determinism-test
    surface — parity tests compare the shared fields directly) plus the
    array engine's fleet-scale statistics."""

    regional_merges: int = 0
    buffered: int = 0  #: client contributions admitted into a window
    stale_dropped: int = 0  #: τ > max_staleness at any admission gate
    rate_limited: int = 0  #: rejected by a per-tier rate limit
    unselected: int = 0  #: update slots skipped by selection
    staleness_hist_edge: List[int] = field(default_factory=list)
    staleness_hist_global: List[int] = field(default_factory=list)
    n_events: int = 0  #: scan length (trained updates incl. dropped sends)
    wall_s: float = 0.0  #: host wall-clock of the whole run
    clients_per_sec: float = 0.0  #: n_clients / wall_s


class MegaFleet:
    """One vectorized fleet; :meth:`run` compiles and drives it.

    Mirrors :class:`SimulatedAsyncFleet`'s constructor surface where the
    semantics coincide (seed/cluster_size/k/alpha/server_lr/
    max_staleness/updates_per_node/link_delay/local_lr/target_loss/plan)
    and adds the Bonawitz array-level production knobs:

    - ``pace_window`` — pace steering: each client's whole schedule is
      offset by a seeded uniform draw in ``[0, pace_window)``, spreading
      the thundering-herd first wave (and with it the staleness
      distribution — the histograms make the effect measurable);
    - ``select_frac`` — selection: each ``(client, update)`` slot
      participates with this probability (an unselected device idles
      that period, Bonawitz §4). Over-provisioning is selecting more
      than the buffers need and measuring the wasted work;
    - ``rate_limit_regional`` / ``rate_limit_global`` — per-tier rate
      limits: a tier refuses offers arriving within the gap of its last
      accepted one (counted, never raising).

    Defaults for the knobs come from ``Settings.MEGAFLEET_*`` at
    construction time (never read inside the program — the
    jit-staleness contract).
    """

    def __init__(
        self,
        spec: FleetSpec,
        *,
        cluster_size: int = 0,
        k: Optional[int] = None,
        alpha: Optional[float] = None,
        server_lr: Optional[float] = None,
        max_staleness: Optional[int] = None,
        updates_per_node: int = 4,
        link_delay: Optional[float] = None,
        local_lr: float = 0.5,
        target_loss: float = 0.0,
        plan=None,
        pace_window: Optional[float] = None,
        select_frac: Optional[float] = None,
        rate_limit_regional: Optional[float] = None,
        rate_limit_global: Optional[float] = None,
        unroll: Optional[int] = None,
    ) -> None:
        from p2pfl_tpu.settings import Settings

        self.spec = spec
        self.n = spec.n
        self.dim = spec.dim
        self.seed = int(spec.seed)
        self.cluster_size = int(cluster_size)
        self.updates_per_node = int(updates_per_node)
        if link_delay is None:
            link_delay = spec.link_delay if spec.link_delay is not None else 0.01
        self.link_delay = float(link_delay)
        self.local_lr = float(local_lr)
        self.target_loss = float(target_loss)
        self.k = max(1, int(Settings.FEDBUFF_K if k is None else k))
        self.alpha = float(Settings.FEDBUFF_ALPHA if alpha is None else alpha)
        self.server_lr = float(
            Settings.FEDBUFF_SERVER_LR if server_lr is None else server_lr
        )
        self.max_staleness = int(
            Settings.ASYNC_MAX_STALENESS if max_staleness is None else max_staleness
        )
        self.pace_window = float(
            Settings.MEGAFLEET_PACE_WINDOW if pace_window is None else pace_window
        )
        self.select_frac = float(
            Settings.MEGAFLEET_SELECT_FRAC if select_frac is None else select_frac
        )
        self.rate_limit_regional = float(
            Settings.MEGAFLEET_REGIONAL_RATE_S
            if rate_limit_regional is None
            else rate_limit_regional
        )
        self.rate_limit_global = float(
            Settings.MEGAFLEET_GLOBAL_RATE_S
            if rate_limit_global is None
            else rate_limit_global
        )
        self.unroll = max(1, int(Settings.MEGAFLEET_SCAN_UNROLL if unroll is None else unroll))
        self.plan = plan
        self._check_plan(plan)

        # membership → tiers through the REAL router: clusters, regional
        # election and K clamps are TierRouter's derivation, not a
        # re-implementation (sorted zero-padded addresses == index order,
        # so cluster slices are contiguous index ranges)
        width = max(4, len(str(self.n - 1)))
        self.addrs = [f"sim-{i:0{width}d}" for i in range(self.n)]
        self.router = TierRouter(self.addrs, self.cluster_size)
        self._addr_idx = {a: j for j, a in enumerate(self.addrs)}
        self.hier = not self.router.topo.is_flat()

    def _check_plan(self, plan) -> None:
        if plan is None:
            return
        unsupported = [
            name
            for name, val in (
                ("edges", plan.edges),
                ("partitions", plan.partitions),
                ("joins", plan.joins),
                ("leaves", plan.leaves),
                ("byzantine", plan.byzantine),
                ("default.duplicate", plan.default.duplicate),
            )
            if val
        ]
        if unsupported:
            raise ValueError(
                "MegaFleet supports FaultPlan default drop/delay/jitter, "
                "slow_nodes and AsyncTrainStage crashes; "
                f"{'/'.join(unsupported)} need the heap driver "
                "(SimulatedAsyncFleet — megafleet is the steady-state "
                "fleet-scale engine, not the churn/adversary one)"
            )

    # ---- array derivation (host, vectorized numpy) ----

    def _tier_arrays(self):
        """Per-client and per-regional routing arrays from the router."""
        n, L = self.n, self.link_delay
        plan_delay = float(self.plan.default.delay) if self.plan is not None else 0.0
        slow = self.spec.slow
        if self.plan is not None and self.plan.slow_nodes:
            # fold the plan's inbound latencies into the population by
            # max: idempotent whether or not the spec already carries
            # them (export_spec folds the same plan; synth exports zeros)
            plan_slow = np.zeros(n, np.float64)
            for addr, extra in self.plan.slow_nodes.items():
                j = self._addr_idx.get(addr)
                if j is not None:
                    plan_slow[j] = float(extra)
            slow = np.maximum(slow, plan_slow)
        clusters = self.router.topo.clusters
        regionals = self.router.regionals
        root = self.router.root
        regional_of = np.zeros(n, np.int32)
        for ci, cluster in enumerate(clusters):
            for a in cluster:
                regional_of[self._addr_idx[a]] = ci
        reg_idx = np.asarray([self._addr_idx[a] for a in regionals], np.int32)
        is_regional = np.zeros(n, bool)
        is_regional[reg_idx] = True
        root_i = self._addr_idx[root]

        hop_reg = L + plan_delay + slow[reg_idx[regional_of]]  # [N] edge→its regional
        hop_down_self = L + plan_delay + slow  # [N] aggregator→this client
        # arrival of a client's own update at its aggregator: regionals
        # (incl. the root) self-offer at t exactly (the heap's src==dst
        # bypass — no delay, no fault verdict)
        arr_delay = np.where(is_regional, 0.0, hop_reg)
        # adoption: how long a fresh global takes to reach this client
        # (root 0; regionals one hop; root-cluster edges one hop; other
        # edges two hops — each hop pays the receiver's slow_nodes latency)
        reg_adopt = np.where(reg_idx == root_i, 0.0, L + plan_delay + slow[reg_idx])
        adopt_delay = np.where(
            regional_of == regional_of[root_i],
            hop_down_self,
            reg_adopt[regional_of] + hop_down_self,
        )
        adopt_delay[reg_idx] = reg_adopt
        adopt_delay[root_i] = 0.0
        # regional→root aggregate delay (0: the root's own cluster offers
        # its regional flush into the global window directly)
        agg_delay = np.where(
            reg_idx == root_i, 0.0, L + plan_delay + slow[root_i]
        )
        k_reg = np.asarray(
            [
                self.router.buffer_plan(a, self.k).regional_k or 1
                for a in regionals
            ],
            np.int32,
        )
        k_global = self.router.buffer_plan(root, self.k).global_k or 1
        return {
            "regional_of": regional_of,
            "is_regional": is_regional,
            "arr_delay": arr_delay,
            "adopt_delay": adopt_delay,
            "reg_adopt": reg_adopt,
            "agg_delay": agg_delay,
            "is_root_reg": reg_idx == root_i,
            "k_reg": k_reg,
            "k_global": int(k_global),
        }

    def _agg_grids(self, tiers, stride: int):
        """Per-(regional, up_seq) drop verdicts and jitter for the
        regional→root aggregate sends — the heap routes these through
        ``_edge_verdict`` too, so the plan's default drop/jitter must
        reach this seam (counter-based streams; the root's own cluster
        offers directly and bypasses the wire, heap semantics)."""
        r = len(tiers["k_reg"])
        ok = np.ones((r, stride), bool)
        jit = np.zeros((r, stride), np.float32)
        plan = self.plan
        if plan is not None and self.hier:
            if plan.default.drop > 0.0:
                ok = (
                    np.random.default_rng([self.seed, _STREAM_AGG_DROP]).random(
                        (r, stride)
                    )
                    >= plan.default.drop
                )
                ok[tiers["is_root_reg"], :] = True
            if plan.default.jitter > 0.0:
                jit = (
                    np.random.default_rng([self.seed, _STREAM_AGG_JIT])
                    .random((r, stride))
                    .astype(np.float32)
                    * np.float32(plan.default.jitter)
                )
                jit[tiers["is_root_reg"], :] = 0.0
        return ok, jit

    def _events(self, tiers) -> Dict[str, np.ndarray]:
        """The sorted arrival rows + verdict grids (counter-based)."""
        n, M = self.n, self.updates_per_node
        d = self.spec.durations
        seed = self.seed
        crash_limit = np.full(n, M, np.int64)
        if self.plan is not None:
            for addr, spec in self.plan.crashes.items():
                j = self._addr_idx.get(addr)
                if j is not None and spec.stage == "AsyncTrainStage":
                    crash_limit[j] = min(M, spec.round_no or 0)
        pace = np.zeros(n, np.float64)
        if self.pace_window > 0.0:
            pace = (
                np.random.default_rng([seed, _STREAM_PACE]).random(n)
                * self.pace_window
            )
        m = np.arange(1, M + 1)
        alive = m[None, :] <= crash_limit[:, None]  # [N, M]
        selected = np.ones((n, M), bool)
        if self.select_frac < 1.0:
            selected = (
                np.random.default_rng([seed, _STREAM_SELECT]).random((n, M))
                < self.select_frac
            )
        unselected = int((alive & ~selected).sum())
        mask = alive & selected
        t_train = pace[:, None] + m[None, :] * d[:, None]  # [N, M]
        t_arr = t_train + tiers["arr_delay"][:, None]
        plan = self.plan
        if plan is not None and plan.default.jitter > 0.0:
            jit = (
                np.random.default_rng([seed, _STREAM_JITTER]).random((n, M))
                * plan.default.jitter
            )
            # regionals self-offer — no wire, no jitter (src==dst bypass;
            # keyed on the explicit mask, not arr_delay, which collapses
            # to 0 for everyone at link_delay=0)
            jit[tiers["is_regional"], :] = 0.0
            t_arr = t_arr + jit
        send_ok = np.ones((n, M), bool)
        if plan is not None and plan.default.drop > 0.0:
            dropped = (
                np.random.default_rng([seed, _STREAM_DROP]).random((n, M))
                < plan.default.drop
            )
            dropped[tiers["is_regional"], :] = False  # src==dst bypass
            send_ok = ~dropped
        ii, mm = np.nonzero(mask)
        tt, ta = t_train[ii, mm], t_arr[ii, mm]
        ok = send_ok[ii, mm]
        order = np.lexsort((mm, ii, ta))
        key = (ii * (M + 1) + (mm + 1)).astype(np.int64)
        if key.size and key.max() >= np.iinfo(np.int32).max:
            raise ValueError("fold-key overflow: n_clients * updates too large")
        return {
            "client": ii[order].astype(np.int32),
            "key": key[order].astype(np.int32),
            "t_train": tt[order].astype(np.float32),
            "t_arr": ta[order].astype(np.float32),
            "send_ok": ok[order],
            "_unselected": unselected,
        }

    # ---- the drive ----

    def run(self) -> MegaFleetResult:
        import jax.numpy as jnp

        from p2pfl_tpu.ops import fleet_kernels as fk

        t0 = time.monotonic()
        tiers = self._tier_arrays()
        ev = self._events(tiers)
        unselected = ev.pop("_unselected")
        E = int(ev["client"].shape[0])
        dropped_wire = int((~ev["send_ok"]).sum())

        # capacity bounds (exact: every flush consumes K distinct
        # accepted events / aggregates)
        if self.hier:
            counts = np.bincount(
                tiers["regional_of"][ev["client"]], minlength=len(tiers["k_reg"])
            )
            per_reg = counts // np.maximum(tiers["k_reg"], 1)
            agg_cap = int(per_reg.sum()) + 1
            v_cap = agg_cap // tiers["k_global"] + 2
            stride = int(per_reg.max(initial=0)) + 2
            if stride * len(tiers["k_reg"]) >= np.iinfo(np.int32).max:
                raise ValueError("aggregate fold-key overflow")
        else:
            v_cap = E // tiers["k_global"] + 2
            stride = 2
        cfg = fk.FleetConfig(
            hier=self.hier,
            n_clients=self.n,
            dim=self.dim,
            n_regionals=len(self.router.regionals),
            k_global=tiers["k_global"],
            k_reg_max=int(tiers["k_reg"].max(initial=1)) if self.hier else 1,
            v_cap=max(v_cap, 2),
            alpha=self.alpha,
            server_lr=self.server_lr,
            local_lr=self.local_lr,
            max_staleness=self.max_staleness,
            rate_gap_reg=self.rate_limit_regional,
            rate_gap_glob=self.rate_limit_global,
            hist_bins=self.max_staleness + 2,
            agg_key_stride=stride,
            unroll=self.unroll,
        )
        events = {
            "client": jnp.asarray(ev["client"]),
            "key": jnp.asarray(ev["key"]),
            "t_train": jnp.asarray(ev["t_train"]),
            "t_arr": jnp.asarray(ev["t_arr"]),
            "send_ok": jnp.asarray(ev["send_ok"]),
        }
        clients = {
            "targets": jnp.asarray(self.spec.targets, jnp.float32),
            "samples": jnp.asarray(self.spec.num_samples, jnp.float32),
            "adopt_delay": jnp.asarray(tiers["adopt_delay"], jnp.float32),
            "regional_of": jnp.asarray(tiers["regional_of"]),
        }
        agg_ok, agg_jit = self._agg_grids(tiers, stride)
        reg = {
            "k": jnp.asarray(tiers["k_reg"]),
            "adopt_delay": jnp.asarray(tiers["reg_adopt"], jnp.float32),
            "agg_delay": jnp.asarray(tiers["agg_delay"], jnp.float32),
            "send_ok": jnp.asarray(agg_ok),
            "jit": jnp.asarray(agg_jit),
        }
        init = jnp.asarray(self.spec.init, jnp.float32)
        out = fk.run_fleet_program(cfg, events, clients, reg, init)

        version = int(out["version"])
        G = np.asarray(out["G"][: version + 1])
        mint = np.asarray(out["mint"][:version], np.float64)
        t_mean = self.spec.target_mean()
        diffs = G - t_mean[None, :]
        losses = (diffs * diffs).sum(axis=1).astype(np.float64)
        curve = [(float(mint[v - 1]), v, float(losses[v])) for v in range(1, version + 1)]
        ttt = next(
            (t for t, _v, loss in curve if loss <= self.target_loss), None
        )
        wall = time.monotonic() - t0
        res = MegaFleetResult(
            params={"w": G[version].copy()},
            version=version,
            virtual_time=float(ev["t_arr"][-1]) if E else 0.0,
            time_to_target=ttt,
            loss_curve=curve,
            updates_sent=E,
            updates_delivered=E - dropped_wire,
            # the heap's counter includes dropped regional→root aggregates
            updates_dropped_wire=dropped_wire + int(out.get("agg_drop", 0)),
            merges=int(out["merges"]),
            regional_merges=int(out.get("rmerges", 0)),
            buffered=int(np.asarray(out["hist_edge"]).sum()),
            stale_dropped=int(out["stale_edge"]) + int(out["stale_agg"]),
            rate_limited=int(out["rate_edge"]) + int(out["rate_agg"]),
            unselected=unselected,
            staleness_hist_edge=[int(x) for x in np.asarray(out["hist_edge"])],
            staleness_hist_global=[int(x) for x in np.asarray(out["hist_glob"])],
            n_events=E,
            wall_s=wall,
            clients_per_sec=self.n / wall if wall > 0 else 0.0,
        )
        if self.plan is not None:
            # heap parity: only crashes that actually FIRE are recorded —
            # a round_no past the schedule never enters AsyncTrainStage
            res.crashed = [
                a
                for a, s in self.plan.crashes.items()
                if a in self._addr_idx
                and s.stage == "AsyncTrainStage"
                and (s.round_no or 0) < self.updates_per_node
            ]
        return res
