"""Megafleet: the async fleet simulator vectorized to ≥1M clients.

:class:`~p2pfl_tpu.federation.simfleet.SimulatedAsyncFleet` is a Python
event heap pushing real per-node buffers through ``heapq`` — exact and
churn/adversary-capable, but ~10⁴ events/sec caps it at 1k–10k nodes.
This module re-expresses the same run as dense arrays advanced by one
jitted ``lax.scan`` (:mod:`~p2pfl_tpu.ops.fleet_kernels`): per-client
``(params, adopted version, train schedule, fault stream)`` state, the
regional tier as vectorized scatter-addressed windows, and the REAL math
as inner functions — the FedBuff ``w(τ)`` weighting, the
``(origin, seq)``-sorted K-flush fold (the very
:func:`~p2pfl_tpu.ops.aggregation.fedavg` /
:func:`~p2pfl_tpu.ops.aggregation.server_merge` kernels the live
:class:`~p2pfl_tpu.federation.buffer.BufferedAggregator` calls), and
:class:`~p2pfl_tpu.federation.routing.TierRouter`'s membership→tier
derivation (clusters, regional election, K clamps come from a real
router over the same addresses).

The default engine processes events in fixed-size CHUNKS
(``Settings.MEGAFLEET_CHUNK`` events per scan step — the
``run_fleet_program_chunked`` four-pass decomposition documented in
``docs/design.md``), amortizing XLA:CPU's per-op dispatch over a whole
chunk; ``chunk=1`` selects the per-event reference scan, and the two
engines are BIT-IDENTICAL on flat topologies (a pinned invariant).

**The heap driver stays the bit-parity anchor.** At 1k nodes on the
consensus task, the flat vectorized engine reproduces the heap's merge
count, version sequence and staleness decisions EXACTLY (the scan's
chronological order is the heap's pop order — see
``ops/fleet_kernels.py``), with the loss trajectory matching to float
reassociation tolerance (the heap weights in Python f64, the scan in
f32; XLA may fuse the consensus step's multiply-add). The hierarchical
engine additionally approximates aggregate-arrival interleaving within
one ``link_delay`` window (documented in ``docs/design.md``); its parity
anchor pins merge counts exactly under a staleness bound wide enough
that boundary reorderings cannot flip an admission.

**Fault contract.** A :class:`~p2pfl_tpu.communication.faults.FaultPlan`
is consumed through counter-based seed-derived streams — dense verdict
grids indexed by ``(node, send index)`` and generated in one vectorized
draw from ``(plan.seed, stream id)`` — instead of the heap's per-edge
Python ``random.Random`` streams, so a plan replays bit-exact from
``(seed, plan)`` without a million generator objects (the verdict
streams therefore differ from the heap's: plan-parity between the
drivers is statistical, not per-send). Supported: ``default``
drop/delay/jitter/duplicate on upward sends — both the client→aggregator
hop and the regional→root aggregate hop, each from its own stream
(downward model pushes are delivered reliably with delay only; the heap
can also drop those — a documented divergence under drop plans),
``slow_nodes`` (inbound latency of the aggregator / the push-down hops),
``crashes`` (``AsyncTrainStage`` → the client stops producing after
``round_no`` updates; megafleet does NOT model the eviction/K-repair
that follows — at fleet scale K ≪ cluster fan-in and no buffer wedges),
``byzantine`` payload attacks for the stateless vectorized kinds
(``sign_flip``/``scale``/``noise`` — applied to the SENT copy at both
send seams, never the honest local model; stateful per-edge kinds raise
toward the heap), and ``joins``/``leaves`` churn as time-indexed
liveness: the schedule is windowed by per-client ``(start, stop)``
times, and a real :class:`TierRouter` is re-derived at every membership
boundary, so election, K clamps and failovers come from the production
derivation (joiners must occupy the top address block; duplicate
injection is a counted no-op at the edge — the version vector dedups it
— and a counted verdict grid at the aggregate seam). Combinations that
interact statefully (churn × byzantine, churn × robust folds,
churn × ``slow_nodes``) and per-edge ``edges`` overrides / ``partitions``
raise loudly: the heap driver remains the authority there; megafleet
exists for the phenomena that only appear at fleet scale (Bonawitz et
al., MLSys'19) — staleness distributions, pace steering, selection
over-provisioning, per-tier rate limits, robust-aggregation sweeps
under attack — which it exposes as array-level controls no per-edge
Python loop could sweep.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from p2pfl_tpu.federation.routing import TierRouter
from p2pfl_tpu.federation.simfleet import FleetResult

Pytree = Any

#: dedicated stream ids for the counter-based draws — one sub-seed per
#: concern, FaultPlan-style, so arming one knob never shifts another's
#: verdicts (e.g. enabling selection must not move drop outcomes)
_STREAM_POP = 17  #: population shape (durations, slow membership)
_STREAM_TARGET = 7  #: per-client consensus targets (matches simfleet)
_STREAM_SELECT = 19
_STREAM_DROP = 23
_STREAM_JITTER = 29
_STREAM_PACE = 31
_STREAM_AGG_DROP = 37  #: regional→root aggregate send verdicts
_STREAM_AGG_JIT = 41
_STREAM_DUP = 43  #: edge duplicate verdicts (counted; version-vector no-op)
_STREAM_BYZ = 47  #: byzantine "noise" payload rows for edge sends
_STREAM_AGG_NOISE = 53  #: byzantine "noise" rows at the aggregate seam
_STREAM_AGG_DUP = 59  #: aggregate duplicate verdicts (counted)

#: window folds megafleet can run in-array (krum-screen scores each
#: contribution against the others' pairwise distances — stateful per
#: contribution set, heap-only)
_VECTOR_FOLDS = ("fedavg", "trimmed-mean", "median")


@dataclass(frozen=True)
class GradTask:
    """The vmapped real-gradient workload: every client trains a tiny
    model (``linear``: one dense layer; ``mlp``: dense→relu→dense) with
    REAL ``jax.grad`` SGD steps on softmax cross-entropy, batched inside
    the chunk body by :func:`~p2pfl_tpu.ops.fleet_kernels.make_grad_fns`.

    Data is counter-keyed per ``(client, round)`` — a Gaussian cloud
    around the client's private ``mu`` (the ``hetero`` non-IID knob)
    labeled by a fixed teacher — so the heap twin and the scan derive
    identical batches from the fold key alone, and the per-client update
    is bit-identical to :class:`~p2pfl_tpu.learning.learner.JaxLearner`'s
    ``optax.sgd`` epoch (the parity pin). The global loss curve is the
    teacher-labeled eval set's cross-entropy.
    """

    kind: str = "linear"  #: "linear" | "mlp"
    d_in: int = 8
    n_out: int = 4
    hidden: int = 0  #: MLP hidden width (0 for linear)
    batch: int = 8
    steps: int = 2  #: SGD steps per local round
    data_seed: int = 0
    hetero: float = 1.0  #: client-mean spread (0 = IID)
    n_eval: int = 256

    def param_dim(self) -> int:
        from p2pfl_tpu.ops.fleet_kernels import grad_param_dim

        return grad_param_dim(self.kind, self.d_in, self.n_out, self.hidden)

    def arrays(self, n: int):
        """Host draws: ``(mu [n, d_in], tw, tb, x_eval, y_eval)`` — the
        client means, the labeling teacher and the eval set, each from
        its own counter stream of ``data_seed``."""
        mu = (
            np.random.default_rng([self.data_seed, 3, n])
            .normal(size=(n, self.d_in))
            .astype(np.float32)
            * np.float32(self.hetero)
        )
        trng = np.random.default_rng([self.data_seed, 1])
        tw = trng.normal(size=(self.d_in, self.n_out)).astype(np.float32)
        tb = trng.normal(size=(self.n_out,)).astype(np.float32)
        erng = np.random.default_rng([self.data_seed, 2])
        xe = erng.normal(size=(self.n_eval, self.d_in)).astype(np.float32)
        ye = np.argmax(xe @ tw + tb, axis=-1).astype(np.int32)
        return mu, tw, tb, xe, ye


@dataclass
class FleetSpec:
    """The dense edge population: everything per-client as one array.

    Built two ways: :meth:`from_sim` exports a live heap fleet's exact
    population (durations, sample weights, targets — the parity hook:
    both drivers then simulate the SAME fleet), and :meth:`synth`
    derives a population of any size from vectorized counter-based
    streams (the ≥1M path — deterministic in ``(n, seed)``, but not the
    heap's per-idx streams, which would cost one Python generator per
    client).
    """

    durations: np.ndarray  #: [N] f64 — per-update train duration
    num_samples: np.ndarray  #: [N] f32 — sample weights (FedAvg numerators)
    targets: np.ndarray  #: [N, dim] f32 — consensus-task private targets
    slow: np.ndarray  #: [N] f64 — extra inbound latency when aggregator
    init: np.ndarray  #: [dim] f32 — shared initial model
    seed: int
    #: the exporting fleet's wire latency (None: engine default). Carried
    #: so a from_sim spec drives the vectorized twin with the SAME clock
    #: without the caller re-passing it.
    link_delay: Optional[float] = None

    @property
    def n(self) -> int:
        return int(self.durations.shape[0])

    @property
    def dim(self) -> int:
        return int(self.targets.shape[1])

    def target_mean(self) -> np.ndarray:
        """The fleet's consensus fixed point: the sample-weighted target
        mean (the heap's ``_default_loss`` reference over full
        membership)."""
        w = self.num_samples.astype(np.float32)
        return (w[:, None] * self.targets).sum(0) / w.sum()

    def loss(self, params: np.ndarray) -> float:
        d = np.asarray(params, np.float32) - self.target_mean()
        return float((d * d).sum())

    @classmethod
    def from_sim(cls, fleet, extra: int = 0, allow_custom: bool = False) -> "FleetSpec":
        """Export a :class:`SimulatedAsyncFleet`'s population via its
        :meth:`~p2pfl_tpu.federation.simfleet.SimulatedAsyncFleet.
        export_spec` hook (sorted address order == index order — the two
        drivers' fold keys agree). ``extra`` appends pending-joiner rows
        (churn parity: the vectorized twin needs the joiners' population
        before they exist in the heap); ``allow_custom`` admits a heap
        fleet driven by a vectorized-twin ``train_fn`` (the gradient-task
        parity pin)."""
        d = fleet.export_spec(extra=extra, allow_custom=allow_custom)
        return cls(
            durations=d["durations"],
            num_samples=d["num_samples"],
            targets=d["targets"],
            slow=d["slow"],
            init=d["init"],
            seed=d["seed"],
            link_delay=d["link_delay"],
        )

    @classmethod
    def synth(
        cls,
        n: int,
        *,
        seed: int = 0,
        dim: int = 16,
        base_duration: float = 1.0,
        slow_frac: float = 0.0,
        slow_factor: float = 10.0,
    ) -> "FleetSpec":
        """A megafleet-native population: same statistics as the heap's
        (duration jitter U[0.8, 1.2]·base, a ``slow_frac`` straggler
        population at ``slow_factor``×, samples ``1 + i mod 3``, targets
        = shared offset + private noise), drawn in three vectorized
        batches instead of N per-idx streams."""
        rng = np.random.default_rng([seed, _STREAM_POP])
        durations = base_duration * (0.8 + 0.4 * rng.random(n))
        if slow_frac > 0.0:
            durations = np.where(
                rng.random(n) < slow_frac, durations * slow_factor, durations
            )
        base = np.random.default_rng([seed, 5]).normal(size=dim).astype(np.float32) * 2.0
        noise = np.random.default_rng([seed, _STREAM_TARGET, n]).normal(
            size=(n, dim)
        ).astype(np.float32)
        return cls(
            durations=durations.astype(np.float64),
            num_samples=(1 + np.arange(n) % 3).astype(np.float32),
            targets=base[None, :] + noise,
            slow=np.zeros(n, np.float64),
            init=np.zeros(dim, np.float32),
            seed=int(seed),
        )


@dataclass
class MegaFleetResult(FleetResult):
    """A :class:`FleetResult` (the heap drivers' determinism-test
    surface — parity tests compare the shared fields directly) plus the
    array engine's fleet-scale statistics."""

    regional_merges: int = 0
    buffered: int = 0  #: client contributions admitted into a window
    stale_dropped: int = 0  #: τ > max_staleness at any admission gate
    rate_limited: int = 0  #: rejected by a per-tier rate limit
    unselected: int = 0  #: update slots skipped by selection
    staleness_hist_edge: List[int] = field(default_factory=list)
    staleness_hist_global: List[int] = field(default_factory=list)
    n_events: int = 0  #: scan length (trained updates incl. dropped sends)
    wall_s: float = 0.0  #: host wall-clock of the whole run
    clients_per_sec: float = 0.0  #: n_clients / wall_s


class MegaFleet:
    """One vectorized fleet; :meth:`run` compiles and drives it.

    Mirrors :class:`SimulatedAsyncFleet`'s constructor surface where the
    semantics coincide (seed/cluster_size/k/alpha/server_lr/
    max_staleness/updates_per_node/link_delay/local_lr/target_loss/plan/
    evict_delay) and adds the Bonawitz array-level production knobs:

    - ``pace_window`` — pace steering: each client's whole schedule is
      offset by a seeded uniform draw in ``[0, pace_window)``, spreading
      the thundering-herd first wave (and with it the staleness
      distribution — the histograms make the effect measurable);
    - ``select_frac`` — selection: each ``(client, update)`` slot
      participates with this probability (an unselected device idles
      that period, Bonawitz §4). Over-provisioning is selecting more
      than the buffers need and measuring the wasted work;
    - ``rate_limit_regional`` / ``rate_limit_global`` — per-tier rate
      limits: a tier refuses offers arriving within the gap of its last
      accepted one (counted, never raising);
    - ``chunk`` — events per scan step (1 = the per-event reference
      engine; >1 = the chunked engine, bit-identical on flat
      topologies; 0 or ``"auto"`` = measure the
      :data:`~p2pfl_tpu.ops.fleet_autotune.DEFAULT_CANDIDATES` once on
      the live device and replay the winner from the fleet-tune cache);
    - ``shards`` — partition the chunked engine's client state over a
      1-D device mesh (:func:`~p2pfl_tpu.parallel.fleet_mesh.
      fleet_clients_mesh`); admission stays replicated, so results are
      bit-identical to the single-device chunked engine at any shard
      count (0/1 = single device);
    - ``task`` — a :class:`GradTask` swaps the consensus step for real
      vmapped-gradient local rounds;
    - ``fold`` / ``trim`` — the window fold family (``fedavg`` /
      ``trimmed-mean`` / ``median``), the robust-aggregation sweep knob.

    Defaults for the knobs come from ``Settings.MEGAFLEET_*`` (and
    ``Settings.ASYNC_ROBUST_AGG`` / ``ASYNC_TRIM`` for the fold) at
    construction time (never read inside the program — the
    jit-staleness contract).
    """

    def __init__(
        self,
        spec: FleetSpec,
        *,
        cluster_size: int = 0,
        k: Optional[int] = None,
        alpha: Optional[float] = None,
        server_lr: Optional[float] = None,
        max_staleness: Optional[int] = None,
        updates_per_node: int = 4,
        link_delay: Optional[float] = None,
        local_lr: float = 0.5,
        target_loss: float = 0.0,
        plan=None,
        pace_window: Optional[float] = None,
        select_frac: Optional[float] = None,
        rate_limit_regional: Optional[float] = None,
        rate_limit_global: Optional[float] = None,
        unroll: Optional[int] = None,
        chunk: Optional[int] = None,
        shards: Optional[int] = None,
        task: Optional[GradTask] = None,
        fold: Optional[str] = None,
        trim: Optional[int] = None,
        evict_delay: float = 0.5,
    ) -> None:
        from p2pfl_tpu.settings import Settings

        self.spec = spec
        self.n = spec.n
        self.dim = spec.dim
        self.seed = int(spec.seed)
        self.cluster_size = int(cluster_size)
        self.updates_per_node = int(updates_per_node)
        if link_delay is None:
            link_delay = spec.link_delay if spec.link_delay is not None else 0.01
        self.link_delay = float(link_delay)
        self.local_lr = float(local_lr)
        self.target_loss = float(target_loss)
        self.k = max(1, int(Settings.FEDBUFF_K if k is None else k))
        self.alpha = float(Settings.FEDBUFF_ALPHA if alpha is None else alpha)
        self.server_lr = float(
            Settings.FEDBUFF_SERVER_LR if server_lr is None else server_lr
        )
        self.max_staleness = int(
            Settings.ASYNC_MAX_STALENESS if max_staleness is None else max_staleness
        )
        self.pace_window = float(
            Settings.MEGAFLEET_PACE_WINDOW if pace_window is None else pace_window
        )
        self.select_frac = float(
            Settings.MEGAFLEET_SELECT_FRAC if select_frac is None else select_frac
        )
        self.rate_limit_regional = float(
            Settings.MEGAFLEET_REGIONAL_RATE_S
            if rate_limit_regional is None
            else rate_limit_regional
        )
        self.rate_limit_global = float(
            Settings.MEGAFLEET_GLOBAL_RATE_S
            if rate_limit_global is None
            else rate_limit_global
        )
        self.unroll = max(1, int(Settings.MEGAFLEET_SCAN_UNROLL if unroll is None else unroll))
        chunk_val = Settings.MEGAFLEET_CHUNK if chunk is None else chunk
        # chunk="auto"/0: resolve through the fleet-tune cache at run()
        # (measured once per device kind × shard count × workload key);
        # until then self.chunk holds the un-tuned fallback
        self._chunk_auto = chunk_val == "auto" or (
            not isinstance(chunk_val, str) and int(chunk_val) == 0
        )
        self.chunk = 256 if self._chunk_auto else max(1, int(chunk_val))
        self.shards = max(0, int(Settings.MEGAFLEET_SHARDS if shards is None else shards))
        self.shard_slack = max(1.0, float(Settings.MEGAFLEET_SHARD_SLACK))
        self.task = task
        self.fold = str(Settings.ASYNC_ROBUST_AGG if fold is None else fold)
        self.trim = int(Settings.ASYNC_TRIM if trim is None else trim)
        self.evict_delay = float(evict_delay)
        if self.fold not in _VECTOR_FOLDS:
            raise ValueError(
                f"megafleet folds are {'/'.join(_VECTOR_FOLDS)}; {self.fold!r} "
                "scores contributions statefully and needs the heap driver"
            )
        if task is not None:
            pd = task.param_dim()
            if self.dim != pd:
                raise ValueError(
                    f"GradTask({task.kind!r}) flattens to {pd} parameters; "
                    f"the spec carries dim={self.dim} — build the spec with "
                    "dim=task.param_dim()"
                )
        self.plan = plan

        # membership → tiers through the REAL router: clusters, regional
        # election and K clamps are TierRouter's derivation, not a
        # re-implementation (sorted zero-padded addresses == index order,
        # so cluster slices are contiguous index ranges)
        width = max(4, len(str(self.n - 1)))
        self.addrs = [f"sim-{i:0{width}d}" for i in range(self.n)]
        self.router = TierRouter(self.addrs, self.cluster_size)
        self._addr_idx = {a: j for j, a in enumerate(self.addrs)}
        self.hier = not self.router.topo.is_flat()
        self._byz: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None
        self._task_cache = None
        self._check_plan(plan)
        self._churn = self._derive_churn()

    def _check_plan(self, plan) -> None:
        if plan is None:
            return
        unsupported = [
            name
            for name, val in (("edges", plan.edges), ("partitions", plan.partitions))
            if val
        ]
        if unsupported:
            raise ValueError(
                "MegaFleet's fault algebra is counter-grid based — verdict "
                "streams are keyed by (node, send index), so per-edge "
                f"overrides and pairwise cuts ({'/'.join(unsupported)}) "
                "need the heap driver (SimulatedAsyncFleet)"
            )
        if plan.byzantine:
            from p2pfl_tpu.communication.faults import byz_payload_grid

            # raises toward the heap for the stateful per-edge kinds
            # (equivocate / random_scale)
            self._byz = byz_payload_grid(plan, self.addrs)
        churn = bool(plan.joins or plan.leaves)
        if churn:
            if plan.byzantine:
                raise ValueError(
                    "churn × byzantine re-elects attackers mid-run (the "
                    "aggregate corruption grid would go stale); the "
                    "combination needs the heap driver"
                )
            if self.fold != "fedavg":
                raise ValueError(
                    "churn × robust folds shrinks windows mid-run (rank "
                    "statistics over a re-clamped K); the combination "
                    "needs the heap driver"
                )
            if plan.slow_nodes or bool(np.any(self.spec.slow != 0.0)):
                raise ValueError(
                    "churn × slow_nodes re-prices every hop per election; "
                    "the combination needs the heap driver"
                )

    def _derive_churn(self) -> Optional[Dict[str, Any]]:
        """The time-indexed liveness table: per-client ``(start, stop)``
        schedule windows plus one REAL :class:`TierRouter` per membership
        boundary (election, K clamps and failovers come from the
        production derivation, not a re-implementation)."""
        plan = self.plan
        if plan is None or not (plan.joins or plan.leaves):
            return None
        n = self.n
        join_at: Dict[int, float] = {}
        for a in sorted(plan.joins):
            j = self._addr_idx.get(a)
            if j is not None:
                join_at[j] = float(plan.joins[a].at_s)
        founders = n - len(join_at)
        if join_at and sorted(join_at) != list(range(founders, n)):
            raise ValueError(
                "megafleet joiners must occupy the top address block "
                "(sorted-address order == index order keeps founder "
                "clusters stable as they arrive); scattered join "
                "addresses need the heap driver"
            )
        ats = [join_at[j] for j in range(founders, n)]
        if any(b < a for a, b in zip(ats, ats[1:])):
            raise ValueError(
                "megafleet join times must be nondecreasing in address "
                "order (the heap assigns population streams in join "
                "order; reordered joins need the heap driver)"
            )
        start = np.zeros(n, np.float64)
        stop = np.full(n, np.inf, np.float64)
        joined: List[str] = []
        for j in range(founders, n):
            # the heap joiner's first training completes at
            # at_s + link_delay + duration (bootstrap hop, then train)
            start[j] = join_at[j] + self.link_delay
            joined.append(self.addrs[j])
        dead_at: Dict[int, float] = {}
        left: List[str] = []
        for a in sorted(plan.leaves):
            j = self._addr_idx.get(a)
            if j is None:
                continue
            sp = plan.leaves[a]
            stop[j] = min(stop[j], float(sp.at_s))
            # graceful: announced, topology re-derives at at_s; abrupt:
            # discovered like a crash, one eviction window later
            dead_at[j] = float(sp.at_s) + (0.0 if sp.graceful else self.evict_delay)
            left.append(a)
        bounds = sorted({0.0} | set(join_at.values()) | set(dead_at.values()))
        routers: List[Tuple[float, TierRouter]] = []
        failovers = 0
        prev_root: Optional[str] = None
        for T in bounds:
            members = [
                self.addrs[j]
                for j in range(n)
                if j < founders or join_at[j] <= T
            ]
            dead = [self.addrs[j] for j, td in dead_at.items() if td <= T]
            rt = TierRouter(members, self.cluster_size, dead=dead)
            if prev_root is not None and rt.root != prev_root:
                failovers += 1
            prev_root = rt.root
            routers.append((T, rt))
        return {
            "routers": routers,
            "start": start,
            "stop": stop,
            "joined": joined,
            "left": left,
            "failovers": failovers,
        }

    # ---- array derivation (host, vectorized numpy) ----

    def _tier_arrays(self):
        """Per-client and per-regional routing arrays, one row per churn
        epoch (a single row when the plan has no churn). Cluster geometry
        is the FULL population's (joiners occupy the top address block,
        so an epoch's clusters are a prefix of it); what varies per epoch
        is the election, the hop prices and the K clamps."""
        n, L = self.n, self.link_delay
        plan_delay = float(self.plan.default.delay) if self.plan is not None else 0.0
        slow = self.spec.slow
        if self.plan is not None and self.plan.slow_nodes:
            # fold the plan's inbound latencies into the population by
            # max: idempotent whether or not the spec already carries
            # them (export_spec folds the same plan; synth exports zeros)
            plan_slow = np.zeros(n, np.float64)
            for addr, extra in self.plan.slow_nodes.items():
                j = self._addr_idx.get(addr)
                if j is not None:
                    plan_slow[j] = float(extra)
            slow = np.maximum(slow, plan_slow)
        clusters = self.router.topo.clusters
        R = len(clusters)
        regional_of = np.zeros(n, np.int32)
        for ci, cluster in enumerate(clusters):
            for a in cluster:
                regional_of[self._addr_idx[a]] = ci
        epoch_routers = (
            self._churn["routers"] if self._churn is not None else [(0.0, self.router)]
        )
        bounds = np.asarray([t for t, _ in epoch_routers], np.float64)
        n_ep = len(epoch_routers)
        reg_node = np.full((n_ep, R), -1, np.int32)
        k_reg = np.ones((n_ep, R), np.int32)
        reg_adopt = np.zeros((n_ep, R), np.float64)
        is_regional = np.zeros((n_ep, n), bool)
        arr_delay = np.zeros((n_ep, n), np.float64)
        adopt_delay = np.zeros((n_ep, n), np.float64)
        root_is = np.zeros(n_ep, np.int64)
        k_globals: List[int] = []
        idx_arange = np.arange(n)
        for e_i, (_, rt) in enumerate(epoch_routers):
            root_i = self._addr_idx[rt.root]
            root_is[e_i] = root_i
            for ci, cluster in enumerate(rt.topo.clusters):
                a = next((m for m in cluster if m not in rt.dead), None)
                if a is None:
                    continue  # fully dead cluster: no live events route here
                reg_node[e_i, ci] = self._addr_idx[a]
                k_reg[e_i, ci] = rt.buffer_plan(a, self.k).regional_k or 1
            k_globals.append(int(rt.buffer_plan(rt.root, self.k).global_k or 1))
            rn = reg_node[e_i]
            rsafe = np.clip(rn, 0, None)
            reg_adopt[e_i] = np.where(
                (rn >= 0) & (rn != root_i), L + plan_delay + slow[rsafe], 0.0
            )
            my_reg = rn[regional_of]  # [n] my cluster's elected regional
            is_reg = idx_arange == my_reg
            is_regional[e_i] = is_reg
            hop_reg = L + plan_delay + slow[np.clip(my_reg, 0, None)]
            arr_delay[e_i] = np.where(is_reg, 0.0, hop_reg)
            hop_down_self = L + plan_delay + slow
            root_cluster = regional_of[root_i]
            ad = np.where(
                regional_of == root_cluster,
                hop_down_self,
                reg_adopt[e_i][regional_of] + hop_down_self,
            )
            ad = np.where(is_reg, reg_adopt[e_i][regional_of], ad)
            ad[root_i] = 0.0
            adopt_delay[e_i] = ad
        k_global = k_globals[0]
        if any(kg != k_global for kg in k_globals):
            raise ValueError(
                "churn re-clamps the global K mid-run; that repair path "
                "needs the heap driver"
            )
        root_cluster0 = int(regional_of[root_is[0]])
        if any(int(regional_of[ri]) != root_cluster0 for ri in root_is):
            raise ValueError(
                "churn moved the global root to another cluster (a fully "
                "dead root cluster); that failover needs the heap driver"
            )
        is_root_reg = np.arange(R) == root_cluster0
        agg_delay = np.where(
            is_root_reg, 0.0, L + plan_delay + slow[root_is[0]]
        )
        return {
            "bounds": bounds,
            "n_ep": n_ep,
            "regional_of": regional_of,
            "reg_node": reg_node,
            "is_regional": is_regional,
            "arr_delay": arr_delay,
            "adopt_delay": adopt_delay,
            "reg_adopt": reg_adopt,
            "agg_delay": agg_delay,
            "is_root_reg": is_root_reg,
            "k_reg": k_reg,
            "k_global": int(k_global),
        }

    def _agg_grids(self, tiers, stride: int) -> Dict[str, np.ndarray]:
        """Per-(regional, up_seq) verdict grids for the regional→root
        aggregate sends — the heap routes these through ``_edge_verdict``
        (and ``byz_corrupt_update``) too, so the plan's default
        drop/jitter/duplicate and a regional attacker's corruption must
        reach this seam (counter-based streams; the root's own cluster
        offers directly and bypasses the wire, heap semantics)."""
        R = tiers["k_reg"].shape[1]
        out: Dict[str, np.ndarray] = {
            "ok": np.ones((R, stride), bool),
            "jit": np.zeros((R, stride), np.float32),
            "dup": np.zeros((R, stride), bool),
        }
        plan = self.plan
        if plan is None or not self.hier:
            return out
        irr = tiers["is_root_reg"]
        if plan.default.drop > 0.0:
            ok = (
                np.random.default_rng([self.seed, _STREAM_AGG_DROP]).random(
                    (R, stride)
                )
                >= plan.default.drop
            )
            ok[irr, :] = True
            out["ok"] = ok
        if plan.default.jitter > 0.0:
            jit = (
                np.random.default_rng([self.seed, _STREAM_AGG_JIT])
                .random((R, stride))
                .astype(np.float32)
                * np.float32(plan.default.jitter)
            )
            jit[irr, :] = 0.0
            out["jit"] = jit
        if plan.default.duplicate > 0.0:
            dup = (
                np.random.default_rng([self.seed, _STREAM_AGG_DUP]).random(
                    (R, stride)
                )
                < plan.default.duplicate
            )
            dup[irr, :] = False
            out["dup"] = dup
        if self._byz is not None:
            # churn × byzantine raises in _check_plan, so the election is
            # static: epoch 0's elected regionals are THE regionals
            code, lam, std = self._byz
            rn = tiers["reg_node"][0]
            rsafe = np.clip(rn, 0, None)
            akind = np.where((rn >= 0) & ~irr, code[rsafe], 0).astype(np.int32)
            alam = np.where(akind > 0, lam[rsafe], 1.0).astype(np.float32)
            out["akind"] = akind
            out["alam"] = alam
            att_r = np.nonzero(akind == 3)[0]
            nrow = int(att_r.shape[0]) * stride
            agg_noise = np.zeros((nrow + 1, self.dim), np.float32)
            idxg = np.zeros((R, stride), np.int64)
            if nrow:
                draws = (
                    np.random.default_rng([self.seed, _STREAM_AGG_NOISE])
                    .normal(size=(nrow, self.dim))
                    .astype(np.float32)
                )
                agg_noise[1:] = draws * std[rn[att_r]].repeat(stride)[:, None]
                idxg[att_r] = 1 + np.arange(nrow).reshape(-1, stride)
            out["agg_noise_idx"] = idxg.astype(np.int32)
            out["agg_noise"] = agg_noise
        return out

    def _events(self, tiers) -> Dict[str, Any]:
        """The sorted arrival rows + verdict columns (counter-based).

        Fold keys are TWO int32 words — ``key_hi`` the origin index,
        ``key_lo`` the 1-based update seq — lexsorted ``(hi, lo)`` inside
        the fold, which IS the heap's ``(origin addr, seq)`` tuple sort
        (zero-padded addresses sort as indices). No product key, so
        ``n_clients × updates`` can never overflow the fold ordering.
        """
        n, M = self.n, self.updates_per_node
        d = self.spec.durations
        seed = self.seed
        crash_limit = np.full(n, M, np.int64)
        if self.plan is not None:
            for addr, spec in self.plan.crashes.items():
                j = self._addr_idx.get(addr)
                if j is not None and spec.stage == "AsyncTrainStage":
                    crash_limit[j] = min(M, spec.round_no or 0)
        pace = np.zeros(n, np.float64)
        if self.pace_window > 0.0:
            pace = (
                np.random.default_rng([seed, _STREAM_PACE]).random(n)
                * self.pace_window
            )
        churn = self._churn
        start = churn["start"] if churn is not None else np.zeros(n, np.float64)
        stop = churn["stop"] if churn is not None else np.full(n, np.inf)
        m = np.arange(1, M + 1)
        alive = m[None, :] <= crash_limit[:, None]  # [N, M]
        t_train = start[:, None] + pace[:, None] + m[None, :] * d[:, None]
        alive &= t_train < stop[:, None]  # a leaver stops producing at at_s
        selected = np.ones((n, M), bool)
        if self.select_frac < 1.0:
            selected = (
                np.random.default_rng([seed, _STREAM_SELECT]).random((n, M))
                < self.select_frac
            )
        unselected = int((alive & ~selected).sum())
        mask = alive & selected
        plan = self.plan
        ii, mm = np.nonzero(mask)
        tt = t_train[ii, mm]
        ep = np.searchsorted(tiers["bounds"], tt, side="right") - 1
        ep = np.clip(ep, 0, tiers["n_ep"] - 1)
        isreg = tiers["is_regional"][ep, ii]
        ta = tt + tiers["arr_delay"][ep, ii]
        if plan is not None and plan.default.jitter > 0.0:
            jit = (
                np.random.default_rng([seed, _STREAM_JITTER]).random((n, M))
                * plan.default.jitter
            )
            # regionals self-offer — no wire, no jitter (src==dst bypass;
            # keyed on the election mask, not arr_delay, which collapses
            # to 0 for everyone at link_delay=0)
            ta = ta + np.where(isreg, 0.0, jit[ii, mm])
        ok = np.ones(ii.shape[0], bool)
        if plan is not None and plan.default.drop > 0.0:
            dropped = (
                np.random.default_rng([seed, _STREAM_DROP]).random((n, M))
                < plan.default.drop
            )
            ok = ~(dropped[ii, mm] & ~isreg)  # src==dst bypass
        wire_dropped = int((~ok).sum())
        lost = 0
        if churn is not None:
            # arrivals at an aggregator that stopped before t_arr are
            # discarded (the heap's crashed-node arrival gate) — in-flight
            # updates to a not-yet-evicted leaver die with it
            tgt = tiers["reg_node"][ep, tiers["regional_of"][ii]]
            dead_arrival = ~isreg & (ta >= stop[np.clip(tgt, 0, None)])
            lost = int((ok & dead_arrival).sum())
            ok = ok & ~dead_arrival
        order = np.lexsort((mm, ii, ta))
        ii, mm, tt, ta, ok, ep, isreg = (
            x[order] for x in (ii, mm, tt, ta, ok, ep, isreg)
        )
        tt32 = tt.astype(np.float32)
        out: Dict[str, Any] = {
            "client": ii.astype(np.int32),
            "key_hi": ii.astype(np.int32),
            "key_lo": (mm + 1).astype(np.int32),
            "t_train": tt32,
            "t_arr": ta.astype(np.float32),
            # f32 subtraction of the f32 operands — exactly the per-event
            # kernel's in-scan arithmetic, so both engines see identical
            # adoption thresholds
            "t_adopt": tt32 - tiers["adopt_delay"][ep, ii].astype(np.float32),
            "send_ok": ok,
            "ep": ep.astype(np.int32),
            "is_reg": isreg,
            "_unselected": unselected,
            "_wire_dropped": wire_dropped,
            "_lost": lost,
        }
        if self._byz is not None:
            code, lam, std = self._byz
            bkind = np.where(isreg, 0, code[ii]).astype(np.int32)
            out["bkind"] = bkind
            out["blam"] = lam[ii].astype(np.float32)
            out["bstd"] = std[ii].astype(np.float32)
            # the heap counts corruption at the send seam, BEFORE the
            # drop verdict — every attacker wire send counts
            out["_byz_edge"] = int((bkind > 0).sum())
        if plan is not None and plan.default.duplicate > 0.0:
            du = np.random.default_rng([seed, _STREAM_DUP]).random((n, M))
            dup_e = ok & ~isreg & (du[ii, mm] < plan.default.duplicate)
            # duplicates never reach the math: the receiver's version
            # vector dedups the replayed (origin, seq) triple — counted
            # here, exactly the heap's injected-then-rejected semantics
            out["_dup_edge"] = int(dup_e.sum())
        return out

    # ---- chunk layout (host) ----

    def _chunk_layout(self, client: np.ndarray, C: int) -> np.ndarray:
        """``[S, C]`` row indices into the sorted event columns (−1 =
        pad). Fast path: a straight reshape when no client repeats inside
        any aligned group — the fleet-scale regime, where a chunk spans
        far less virtual time than one train period. Fallback: greedy
        chunking that closes the chunk at the first repeated client (the
        pass-A scatter needs each client at most once per chunk)."""
        E = int(client.shape[0])
        S = -(-E // C)
        rows = np.full(S * C, -1, np.int64)
        rows[:E] = np.arange(E)
        gid = np.arange(S * C) // C
        cl = np.where(rows >= 0, client[np.clip(rows, 0, None)], -1)
        o = np.lexsort((cl, gid))
        gs, cs = gid[o], cl[o]
        collide = (gs[1:] == gs[:-1]) & (cs[1:] == cs[:-1]) & (cs[1:] >= 0)
        if not collide.any():
            return rows.reshape(S, C)
        out: List[int] = []
        cur: List[int] = []
        seen: set = set()
        for j in range(E):
            cj = int(client[j])
            if cj in seen or len(cur) == C:
                cur.extend([-1] * (C - len(cur)))
                out.extend(cur)
                cur, seen = [], set()
            cur.append(j)
            seen.add(cj)
        if cur:
            cur.extend([-1] * (C - len(cur)))
            out.extend(cur)
        return np.asarray(out, np.int64).reshape(-1, C)

    @staticmethod
    def _chain_cols(rows: np.ndarray, r_e: np.ndarray, R: int):
        """Per-event chunk-local regional chains: ``prev_r`` links an
        event to the previous same-regional event's chunk offset (−1 =
        none — read the carry), ``last_r`` marks each regional's final
        in-chunk event (whose state the writeback scatters)."""
        S, C = rows.shape
        flat = rows.ravel()
        valid = flat >= 0
        rcol = np.where(valid, r_e[np.clip(flat, 0, None)], R)
        cid = np.repeat(np.arange(S), C)
        off = np.tile(np.arange(C), S)
        o = np.lexsort((off, rcol, cid))
        vv = valid[o]
        same = (
            (cid[o][1:] == cid[o][:-1])
            & (rcol[o][1:] == rcol[o][:-1])
            & vv[1:]
            & vv[:-1]
        )
        prev = np.full(S * C, -1, np.int32)
        prev[o[1:][same]] = off[o[:-1][same]].astype(np.int32)
        last = valid.copy()
        last[o[:-1][same]] = False
        return prev.reshape(S, C), last.reshape(S, C)

    def _task_arrays(self):
        if self._task_cache is None:
            self._task_cache = self.task.arrays(self.n)
        return self._task_cache

    def _grad_losses(self, G: np.ndarray) -> np.ndarray:
        """Eval-set cross-entropy per global version (the gradient task's
        loss curve — the heap twin's custom ``loss_fn`` computes the
        same quantity)."""
        import jax
        import jax.numpy as jnp
        import optax

        from p2pfl_tpu.ops import fleet_kernels as fk

        t = self.task
        _, _, _, xe, ye = self._task_arrays()
        xs, ys = jnp.asarray(xe), jnp.asarray(ye)

        def ce(g):
            lg = fk.grad_logits(t.kind, t.d_in, t.n_out, t.hidden, g, xs)
            return optax.softmax_cross_entropy_with_integer_labels(lg, ys).mean()

        return np.asarray(jax.vmap(ce)(jnp.asarray(G)), np.float64)

    def _shard_layout(
        self, client: np.ndarray, C: int, P: int, cp: int, ncap: int
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """The sharded engine's chunk layout: like :meth:`_chunk_layout`
        but each chunk is ALSO partitioned into per-shard segments of
        ``cp`` lanes (shard = owner ``client // ncap``, lanes in
        chronological order). Returns ``(rows [S, C], seg_ev [S, P·cp],
        invperm [S, C])`` — ``rows`` is the chronological grid the
        replicated passes consume (−1 = pad), ``seg_ev`` maps each
        shard-segment lane to its event (−1 = dead lane), and
        ``invperm`` maps a chunk's chronological position to its
        segment slot, which is how the device program unpermutes the
        per-chunk ``all_gather``. Fast path: the aligned-group reshape
        whenever no client repeats in a group AND every (group, shard)
        count fits the ``cp`` quota (slack sizes ``cp`` so this is the
        fleet-scale regime); fallback: greedy chunking that closes on a
        repeat OR a full segment."""
        E = int(client.shape[0])
        S = -(-E // C) if E else 0
        sh = client.astype(np.int64) // ncap
        rows = np.full(S * C, -1, np.int64)
        rows[:E] = np.arange(E)
        gid_full = np.arange(S * C) // C
        cl = np.where(rows >= 0, client[np.clip(rows, 0, None)], -1)
        o = np.lexsort((cl, gid_full))
        gs, cs = gid_full[o], cl[o]
        collide = (gs[1:] == gs[:-1]) & (cs[1:] == cs[:-1]) & (cs[1:] >= 0)
        gid = np.arange(E) // C
        key = gid * P + sh
        counts = np.bincount(key, minlength=S * P)
        if not collide.any() and (counts <= cp).all():
            order = np.lexsort((np.arange(E), sh, gid))
            sk = key[order]
            starts = np.r_[0, 1 + np.flatnonzero(np.diff(sk))]
            lens = np.diff(np.r_[starts, E])
            within = np.arange(E) - np.repeat(starts, lens)
            slot = sh[order] * cp + within
            seg_slot = np.empty(E, np.int64)
            seg_slot[order] = slot
            seg_ev = np.full((S, P * cp), -1, np.int64)
            seg_ev[gid, seg_slot] = np.arange(E)
            invperm = np.zeros((S, C), np.int32)
            invperm[gid, np.arange(E) - gid * C] = seg_slot
            return rows.reshape(S, C), seg_ev, invperm
        row_chunks: List[List[int]] = []
        seg_chunks: List[np.ndarray] = []
        inv_chunks: List[np.ndarray] = []
        cur: List[int] = []
        slots: List[int] = []
        seen: set = set()
        cnt = np.zeros(P, np.int64)

        def close() -> None:
            row_chunks.append(cur + [-1] * (C - len(cur)))
            seg_row = np.full(P * cp, -1, np.int64)
            seg_row[np.asarray(slots, np.int64)] = np.asarray(cur, np.int64)
            inv_row = np.zeros(C, np.int32)
            inv_row[: len(slots)] = np.asarray(slots, np.int32)
            seg_chunks.append(seg_row)
            inv_chunks.append(inv_row)

        for j in range(E):
            cj = int(client[j])
            sj = int(sh[j])
            if cj in seen or len(cur) == C or cnt[sj] == cp:
                close()
                cur, slots, seen = [], [], set()
                cnt[:] = 0
            cur.append(j)
            slots.append(sj * cp + int(cnt[sj]))
            seen.add(cj)
            cnt[sj] += 1
        if cur:
            close()
        return (
            np.asarray(row_chunks, np.int64).reshape(-1, C),
            np.stack(seg_chunks),
            np.stack(inv_chunks),
        )

    def _chunk_grids(self, fk, jnp, cfg, tiers, ev, clients, agg, rows):
        """Build the ``[S, C]`` chronological event grids + per-regional
        grids from a chunk layout (pads carry trash values that every
        in-kernel gate masks: client=N, PAD keys, live=False). Shared by
        the chunked and sharded drivers — the layouts differ, the grid
        semantics do not."""
        C = cfg.chunk
        PAD = int(fk.PAD_KEY)
        live = rows >= 0

        def col(vals, pad, dtype):
            grid = np.full(rows.shape, pad, dtype)
            grid[live] = np.asarray(vals)[rows[live]].astype(dtype)
            return jnp.asarray(grid)

        events = {
            "client": col(ev["client"], self.n, np.int32),
            "key_hi": col(ev["key_hi"], PAD, np.int32),
            "key_lo": col(ev["key_lo"], PAD, np.int32),
            "t_adopt": col(ev["t_adopt"], -np.inf, np.float32),
            "t_arr": col(ev["t_arr"], 0.0, np.float32),
            "send_ok": col(ev["send_ok"], False, bool),
            "live": jnp.asarray(live),
        }
        R = cfg.n_regionals
        if cfg.hier:
            r_e = tiers["regional_of"][ev["client"]]
            k_e = tiers["k_reg"][ev["ep"], r_e]
            t_rad = ev["t_arr"] - tiers["reg_adopt"][ev["ep"], r_e].astype(np.float32)
            events["r"] = col(r_e, R, np.int32)
            events["k_r"] = col(k_e, 1, np.int32)
            events["t_radopt"] = col(t_rad, -np.inf, np.float32)
            prev_r, last_r = self._chain_cols(rows, r_e, R)
            events["prev_r"] = jnp.asarray(prev_r)
            events["last_r"] = jnp.asarray(last_r)
        if cfg.byz:
            events["bkind"] = col(ev["bkind"], 0, np.int32)
            events["blam"] = col(ev["blam"], 1.0, np.float32)
            att = ev["bkind"] == 3
            if att.any():
                nz = int(att.sum())
                noise = np.zeros((nz + 1, cfg.dim), np.float32)
                noise[1:] = (
                    np.random.default_rng([self.seed, _STREAM_BYZ])
                    .normal(size=(nz, cfg.dim))
                    .astype(np.float32)
                    * ev["bstd"][att][:, None]
                )
                bn = np.zeros(ev["bkind"].shape[0], np.int64)
                bn[att] = 1 + np.arange(nz)
                events["bnoise"] = col(bn, 0, np.int32)
                clients["noise"] = jnp.asarray(noise)
        reg = {}
        if cfg.hier:

            def pad_row(a, v):
                return np.concatenate(
                    [a, np.full((1,) + a.shape[1:], v, a.dtype)], axis=0
                )

            # one trash row per grid: pad lanes gather r=R harmlessly
            reg = {
                "send_ok": jnp.asarray(pad_row(agg["ok"], True)),
                "jit": jnp.asarray(pad_row(agg["jit"], 0.0)),
                "agg_delay": jnp.asarray(
                    pad_row(tiers["agg_delay"].astype(np.float32), 0.0)
                ),
            }
            if cfg.dup:
                reg["dup"] = jnp.asarray(pad_row(agg["dup"], False))
            if cfg.byz:
                reg["akind"] = jnp.asarray(pad_row(agg["akind"], 0))
                reg["alam"] = jnp.asarray(pad_row(agg["alam"], 1.0))
                reg["agg_noise_idx"] = jnp.asarray(pad_row(agg["agg_noise_idx"], 0))
                reg["agg_noise"] = jnp.asarray(agg["agg_noise"])
        return events, reg

    def _run_chunked(self, fk, jnp, cfg, tiers, ev, clients, agg, init):
        """Chunked single-device drive: chronological layout → grids →
        :func:`run_fleet_program_chunked`."""
        rows = self._chunk_layout(ev["client"], cfg.chunk)
        events, reg = self._chunk_grids(fk, jnp, cfg, tiers, ev, clients, agg, rows)
        return fk.run_fleet_program_chunked(cfg, events, clients, reg, init)

    def _run_sharded(self, fk, jnp, cfg, tiers, ev, clients, agg, init):
        """Sharded drive: segment layout → chronological grids + shard
        grids → :func:`run_fleet_program_sharded` on a ``(clients,)``
        mesh of ``self.shards`` devices."""
        from p2pfl_tpu.parallel.fleet_mesh import fleet_clients_mesh, shard_capacity

        P = self.shards
        mesh = fleet_clients_mesh(P)
        ncap = shard_capacity(self.n, P)
        cp = max(1, int(np.ceil(self.shard_slack * cfg.chunk / P)))
        rows, seg_ev, invperm = self._shard_layout(
            ev["client"], cfg.chunk, P, cp, ncap
        )
        events, reg = self._chunk_grids(fk, jnp, cfg, tiers, ev, clients, agg, rows)
        # chronological position of each event inside its chunk — segment
        # lanes forward-gather the replicated chronological grids with it
        E = int(ev["client"].shape[0])
        pos = np.zeros(E, np.int64)
        sidx, cidx = np.nonzero(rows >= 0)
        pos[rows[sidx, cidx]] = cidx
        seg_live = seg_ev >= 0
        safe = np.clip(seg_ev, 0, None)
        events["seg_fwd"] = jnp.asarray(
            np.where(seg_live, pos[safe], 0).astype(np.int32)
        )
        events["seg_loc"] = jnp.asarray(
            np.where(seg_live, ev["client"][safe] % ncap, ncap).astype(np.int32)
        )
        events["seg_live"] = jnp.asarray(seg_live)
        events["invperm"] = jnp.asarray(invperm)
        return fk.run_fleet_program_sharded(cfg, events, clients, reg, init, mesh)

    def _autotune_chunk(self, fk, jnp, make_cfg, tiers, ev, clients, agg, init):
        """Resolve ``chunk="auto"``: measure the engine over a bounded
        event prefix for each candidate, once per (device kind, shard
        count, workload) key — cached on disk so replays are free."""
        import jax

        from p2pfl_tpu.ops import fleet_autotune as ft

        n_sh = self.shards if self.shards > 1 else 1
        extra = (
            f"task={self.task.kind if self.task else 'consensus'}"
            f"|dim={self.dim}|hier={int(self.hier)}|k={self.k}"
            f"|n~1e{len(str(max(1, self.n))) - 1}"
        )
        got = ft.get_fleet_chunk(n_shards=n_sh, extra=extra)
        if got is not None:
            return got
        cands = ft.DEFAULT_CANDIDATES
        E = int(ev["client"].shape[0])
        budget = max(min(E, 8 * max(cands)), 1)
        ev_cut = {
            k: (v[:budget] if isinstance(v, np.ndarray) else v)
            for k, v in ev.items()
        }
        runner = self._run_sharded if n_sh > 1 else self._run_chunked

        def measure(c: int) -> float:
            cfg_c = make_cfg(c)
            runner(fk, jnp, cfg_c, tiers, ev_cut, dict(clients), agg, init)
            t0 = time.monotonic()
            out = runner(fk, jnp, cfg_c, tiers, ev_cut, dict(clients), agg, init)
            jax.block_until_ready(out["G"])
            return time.monotonic() - t0

        return ft.autotune_fleet_chunk(measure, cands, n_shards=n_sh, extra=extra)

    # ---- the drive ----

    def run(self) -> MegaFleetResult:
        import jax.numpy as jnp

        from p2pfl_tpu.ops import fleet_kernels as fk

        t0 = time.monotonic()
        tiers = self._tier_arrays()
        ev = self._events(tiers)
        unselected = ev.pop("_unselected")
        dropped_wire = ev.pop("_wire_dropped")
        lost = ev.pop("_lost")
        dup_edge = ev.pop("_dup_edge", 0)
        byz_edge = ev.pop("_byz_edge", 0)
        E = int(ev["client"].shape[0])
        plan = self.plan

        # capacity bounds (exact: every flush consumes K distinct
        # accepted events / aggregates; churn shrinks K, never grows it
        # past the epoch-min clamp)
        R = int(tiers["k_reg"].shape[1])
        k_glob = tiers["k_global"]
        if self.hier:
            k_min = np.maximum(tiers["k_reg"].min(axis=0), 1)
            counts = np.bincount(
                tiers["regional_of"][ev["client"]], minlength=R
            )
            per_reg = counts // k_min
            agg_cap = int(per_reg.sum()) + 1
            v_cap = agg_cap // k_glob + 2
            stride = int(per_reg.max(initial=0)) + 2
        else:
            v_cap = E // k_glob + 2
            stride = 2
        use_chunked = (
            self.chunk > 1
            or self._chunk_auto
            or self.shards > 1
            or self.task is not None
            or self.fold != "fedavg"
            or self._byz is not None
            or self._churn is not None
            or (self.hier and plan is not None and plan.default.duplicate > 0.0)
        )
        task = self.task

        def make_cfg(C):
            return fk.FleetConfig(
                hier=self.hier,
                n_clients=self.n,
                dim=self.dim,
                n_regionals=R,
                k_global=k_glob,
                k_reg_max=int(tiers["k_reg"].max(initial=1)) if self.hier else 1,
                v_cap=max(v_cap, 2),
                alpha=self.alpha,
                server_lr=self.server_lr,
                local_lr=self.local_lr,
                max_staleness=self.max_staleness,
                rate_gap_reg=self.rate_limit_regional,
                rate_gap_glob=self.rate_limit_global,
                hist_bins=self.max_staleness + 2,
                agg_key_stride=stride,
                unroll=self.unroll,
                chunk=C,
                gf_cap=(C // k_glob + 2) if use_chunked else 0,
                fold_kind=self.fold,
                trim=self.trim,
                task=(task.kind if task is not None else "consensus"),
                t_din=(task.d_in if task is not None else 0),
                t_nout=(task.n_out if task is not None else 0),
                t_hidden=(task.hidden if task is not None else 0),
                t_bs=(task.batch if task is not None else 0),
                t_steps=(task.steps if task is not None else 0),
                data_seed=(task.data_seed if task is not None else 0),
                byz=bool("bkind" in ev and use_chunked),
                dup=bool(
                    self.hier
                    and plan is not None
                    and plan.default.duplicate > 0.0
                    and use_chunked
                ),
            )
        clients = {
            "targets": jnp.asarray(self.spec.targets, jnp.float32),
            "samples": jnp.asarray(self.spec.num_samples, jnp.float32),
        }
        if task is not None:
            mu, tw, tb, _, _ = self._task_arrays()
            clients["mu"] = jnp.asarray(mu)
            clients["tw"] = jnp.asarray(tw)
            clients["tb"] = jnp.asarray(tb)
        agg = self._agg_grids(tiers, stride)
        init = jnp.asarray(self.spec.init, jnp.float32)
        if self._chunk_auto and use_chunked:
            self.chunk = self._autotune_chunk(
                fk, jnp, make_cfg, tiers, ev, clients, agg, init
            )
        cfg = make_cfg(self.chunk if use_chunked else 1)
        if use_chunked and self.shards > 1:
            out = self._run_sharded(fk, jnp, cfg, tiers, ev, clients, agg, init)
        elif use_chunked:
            out = self._run_chunked(fk, jnp, cfg, tiers, ev, clients, agg, init)
        else:
            events = {
                "client": jnp.asarray(ev["client"]),
                "key_hi": jnp.asarray(ev["key_hi"]),
                "key_lo": jnp.asarray(ev["key_lo"]),
                "t_train": jnp.asarray(ev["t_train"]),
                "t_arr": jnp.asarray(ev["t_arr"]),
                "send_ok": jnp.asarray(ev["send_ok"]),
            }
            clients["adopt_delay"] = jnp.asarray(
                tiers["adopt_delay"][0], jnp.float32
            )
            clients["regional_of"] = jnp.asarray(tiers["regional_of"])
            reg = {
                "k": jnp.asarray(tiers["k_reg"][0]),
                "adopt_delay": jnp.asarray(tiers["reg_adopt"][0], jnp.float32),
                "agg_delay": jnp.asarray(tiers["agg_delay"], jnp.float32),
                "send_ok": jnp.asarray(agg["ok"]),
                "jit": jnp.asarray(agg["jit"]),
            }
            out = fk.run_fleet_program(cfg, events, clients, reg, init)

        version = int(out["version"])
        G = np.asarray(out["G"][: version + 1])
        mint = np.asarray(out["mint"][:version], np.float64)
        if task is not None:
            losses = self._grad_losses(G)
        else:
            t_mean = self.spec.target_mean()
            diffs = G - t_mean[None, :]
            losses = (diffs * diffs).sum(axis=1).astype(np.float64)
        curve = [(float(mint[v - 1]), v, float(losses[v])) for v in range(1, version + 1)]
        ttt = next(
            (t for t, _v, loss in curve if loss <= self.target_loss), None
        )
        wall = time.monotonic() - t0
        res = MegaFleetResult(
            params={"w": G[version].copy()},
            version=version,
            virtual_time=float(ev["t_arr"][-1]) if E else 0.0,
            time_to_target=ttt,
            loss_curve=curve,
            updates_sent=E,
            updates_delivered=E - dropped_wire - lost,
            # the heap's counter includes dropped regional→root aggregates
            updates_dropped_wire=dropped_wire + int(out.get("agg_drop", 0)),
            duplicates_injected=dup_edge + int(out.get("dup_agg", 0)),
            byz_corrupted=byz_edge + int(out.get("byz_agg", 0)),
            merges=int(out["merges"]),
            regional_merges=int(out.get("rmerges", 0)),
            buffered=int(np.asarray(out["hist_edge"]).sum()),
            stale_dropped=int(out["stale_edge"]) + int(out["stale_agg"]),
            rate_limited=int(out["rate_edge"]) + int(out["rate_agg"]),
            unselected=unselected,
            staleness_hist_edge=[int(x) for x in np.asarray(out["hist_edge"])],
            staleness_hist_global=[int(x) for x in np.asarray(out["hist_glob"])],
            n_events=E,
            wall_s=wall,
            clients_per_sec=self.n / wall if wall > 0 else 0.0,
        )
        if self._churn is not None:
            res.joined = list(self._churn["joined"])
            res.left = list(self._churn["left"])
            res.failovers = int(self._churn["failovers"])
        if plan is not None:
            # heap parity: only crashes that actually FIRE are recorded —
            # a round_no past the schedule never enters AsyncTrainStage
            res.crashed = [
                a
                for a, s in plan.crashes.items()
                if a in self._addr_idx
                and s.stage == "AsyncTrainStage"
                and (s.round_no or 0) < self.updates_per_node
            ]
        return res
