"""Hierarchical aggregation topology: edge clusters → regional → global.

HierFAVG (Liu et al., ICC 2020) shows that inserting an edge-aggregation
tier between clients and the cloud cuts global communication by an order
of magnitude: clients talk to a *nearby* regional aggregator every local
round, and only the regionals' already-merged aggregates cross the
expensive tier. Composed with FedBuff buffering, each tier merges at its
own cadence — a slow edge delays nothing but its own contribution.

The topology is a **pure function of the sorted member list** (plus the
cluster size), so every node derives the identical assignment with zero
coordination — the same trick as the deterministic per-round trace ids:
agreement on membership (which the heartbeat plane provides) IS agreement
on topology. The elastic layer builds on exactly that property: the
:class:`~p2pfl_tpu.federation.routing.TierRouter` chunks the FULL
membership (live and dead) through this class and overlays dead members
as *holes* — a death re-elects roles only within its own cluster plus the
root chain instead of re-chunking everyone (the bounded-disruption
contract), while a join re-derives the whole assignment.

Roles nest rather than exclude: the global root is also the regional
aggregator of its own cluster and trains like any edge — aggregation is a
*duty*, not a node type. ``cluster_size <= 1`` (or ≥ the fleet) collapses
to the flat FedBuff shape: one cluster, one aggregator, no regional tier.
"""

from __future__ import annotations

from typing import Dict, List, Optional


class HierarchicalTopology:
    """Deterministic cluster assignment + aggregator election.

    ``members`` may arrive in any order; it is sorted once and chunked
    into clusters of ``cluster_size``. The first member of each cluster
    is its **regional aggregator**; the first regional is the **global
    root**. (Election by sort order is deliberate: it needs no extra
    wire traffic and re-derives identically everywhere. A production
    deployment would sort by a locality key — the mechanism is the
    point, not the key.)
    """

    def __init__(self, members: List[str], cluster_size: int = 0) -> None:
        self.members = sorted(set(members))
        if not self.members:
            raise ValueError("topology needs at least one member")
        n = len(self.members)
        if cluster_size is None or cluster_size <= 1 or cluster_size >= n:
            cluster_size = n  # flat: one cluster, one aggregator
        self.cluster_size = cluster_size
        self.clusters: List[List[str]] = [
            self.members[i : i + cluster_size] for i in range(0, n, cluster_size)
        ]
        # a trailing 1-member "cluster" would make that member its own
        # regional with no edges — fold it into the previous cluster
        if len(self.clusters) > 1 and len(self.clusters[-1]) == 1:
            self.clusters[-2].extend(self.clusters.pop())
        self.regionals: List[str] = [c[0] for c in self.clusters]
        self.global_root: str = self.regionals[0]
        self._cluster_of: Dict[str, int] = {
            addr: i for i, cluster in enumerate(self.clusters) for addr in cluster
        }

    # ---- roles ----

    def tier(self, addr: str) -> str:
        """``"global" | "regional" | "edge"`` — the node's HIGHEST duty."""
        if addr == self.global_root:
            return "global"
        if addr in self._cluster_of and addr == self.regionals[self._cluster_of[addr]]:
            return "regional"
        return "edge"

    def is_flat(self) -> bool:
        return len(self.clusters) == 1

    def cluster_index(self, addr: str) -> Optional[int]:
        """The index of ``addr``'s cluster, or None for a non-member —
        the routing layer's membership probe."""
        return self._cluster_of.get(addr)

    def cluster_of(self, addr: str) -> List[str]:
        return list(self.clusters[self._cluster_of[addr]])

    def aggregator_for(self, addr: str) -> str:
        """Where ``addr`` pushes its training updates: its cluster's
        regional (which may be ``addr`` itself — offer locally then)."""
        return self.regionals[self._cluster_of[addr]]

    def parent_of(self, addr: str) -> Optional[str]:
        """The next tier up: edge → its regional, regional → the global
        root, global root → None."""
        if addr == self.global_root:
            return None
        regional = self.aggregator_for(addr)
        return self.global_root if addr == regional else regional

    def children_of(self, addr: str) -> List[str]:
        """Who ``addr`` pushes fresh global models to (one tier down):
        the global root reaches the other regionals plus its own cluster;
        a regional reaches its cluster's edges; an edge reaches nobody."""
        out: List[str] = []
        if addr == self.global_root:
            out.extend(r for r in self.regionals if r != addr)
        if addr in self._cluster_of and addr == self.regionals[self._cluster_of[addr]]:
            out.extend(m for m in self.cluster_of(addr) if m != addr)
        return out

    def describe(self) -> dict:
        return {
            "members": len(self.members),
            "clusters": [len(c) for c in self.clusters],
            "regionals": list(self.regionals),
            "global_root": self.global_root,
            "flat": self.is_flat(),
        }
