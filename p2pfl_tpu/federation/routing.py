"""The node-free tier-routing core: one state machine for sim and production.

Before this module, the tier-routing glue (which buffer an arriving
update feeds, where a node pushes its training updates, who receives a
freshly minted global, which aggregation duties a node holds) lived
TWICE: threaded inside ``workflow.AsyncContext`` and mirrored by hand in
``simfleet.SimulatedAsyncFleet`` — a routing change in one had to be
re-implemented in the other, so elastic behavior could not be validated
at 10k simulated nodes before it touched a real wire. :class:`TierRouter`
is that logic extracted into a pure function of

    ``(sorted_membership, dead_set, cluster_size)``

with no Node, no transport, no threads: both drivers construct one,
re-construct it on every membership event (join, graceful leave,
eviction), and read routing decisions from it. Because the derivation is
deterministic and order-invariant, every node that agrees on the
membership view agrees on the whole topology — the same zero-coordination
trick as the deterministic trace ids.

**Membership change IS topology change.** The full membership list (live
AND dead) is chunked into clusters exactly like
:class:`~p2pfl_tpu.federation.topology.HierarchicalTopology`; dead
members keep their cluster slots as *holes* instead of re-chunking, so a
death disturbs only the affected cluster's role assignments plus the
root chain (the bounded-disruption contract the property tests pin). A
join grows the membership and re-chunks — the buffer-migration machinery
(flush-or-forward on demotion, seeded creation on promotion) makes that
safe.

**Roles with holes.** A cluster's regional aggregator is its first LIVE
member; the global root is the first live regional in cluster order. So
when a regional dies, the next-sorted live member of its cluster
self-elects as successor regional, and when the global root dies, the
next-sorted live regional self-elects as successor root — zero
coordination, no election traffic. Version monotonicity across a root
handover is the successor's responsibility: it seeds its global buffer
from :class:`VersionHighWater` (the highest global version it ever
observed, including ``base_version`` fields of in-flight "vv" triples),
and :class:`~p2pfl_tpu.federation.buffer.BufferedAggregator` jumps its
counter past any later-observed base version, so a minted version can
never regress below what any live node already adopted.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, NamedTuple, Optional

from p2pfl_tpu.federation.topology import HierarchicalTopology


class BufferPlan(NamedTuple):
    """Which aggregation buffers a node should hold, and their K clamps.

    ``None`` means "no buffer of that tier" — an edge holds neither, a
    regional holds a cluster buffer, the global root holds a global
    buffer (plus a cluster buffer when the topology is hierarchical).
    K is clamped to the LIVE fan-in of the tier so a cluster that lost
    members still flushes (the eviction-repair contract).
    """

    regional_k: Optional[int]
    global_k: Optional[int]


class BufferOp(NamedTuple):
    """One buffer-migration step (see :meth:`TierRouter.reconcile_ops`)."""

    op: str  #: "forward" (demotion) | "create" (promotion) | "resize" (K re-clamp)
    tier: str  #: "regional" | "global"
    k: Optional[int]  #: the tier's K clamp (create/resize)
    target: Optional[str]  #: where a demoted buffer's pending forwards (forward)


class VersionHighWater:
    """The highest global model version a node has ever *observed*.

    Fed from two sources: versions the node adopted (``async_model``
    pushes / minted flushes) and the ``base_version`` field of every
    version triple that passes through it. The second source is what
    makes root failover version-safe when the successor itself missed
    the last minted globals (a partition, a dropped push): the corpse's
    freshest version still reaches the successor *inside the updates
    trained from it*, and the successor mints strictly above the mark.
    Thread-safe (production handlers feed it from delivery threads).
    """

    def __init__(self, initial: int = 0) -> None:
        self._lock = threading.Lock()
        self._mark = int(initial)

    def observe(self, version: Optional[int]) -> None:
        if version is None:
            return
        with self._lock:
            if version > self._mark:
                self._mark = int(version)

    @property
    def mark(self) -> int:
        with self._lock:
            return self._mark


class TierRouter:
    """Routing decisions for one membership view (immutable once built).

    ``members`` is the FULL membership ever observed (live and dead —
    dead members keep their cluster slots as holes, which is what bounds
    the disruption of a death); ``dead`` marks evicted/left members;
    ``cluster_size`` is the HierFAVG cluster width (0/1 = flat FedBuff).
    Membership events never mutate a router — drivers build a new one
    and reconcile their buffers against its :meth:`buffer_plan`.
    """

    def __init__(
        self, members: Iterable[str], cluster_size: int = 0, dead: Iterable[str] = ()
    ) -> None:
        self.topo = HierarchicalTopology(sorted(set(members)), cluster_size)
        self.cluster_size = cluster_size
        self.dead = frozenset(dead) & set(self.topo.members)
        # per-cluster live regional (None = the whole cluster is dead)
        self._regional: List[Optional[str]] = [
            next((m for m in cluster if m not in self.dead), None)
            for cluster in self.topo.clusters
        ]
        #: live regionals in cluster order — the global tier's fan-in
        self.regionals: List[str] = [r for r in self._regional if r is not None]
        # membership probe for the per-arrival update_sink hot path (the
        # router is immutable — never rebuild this per message)
        self._regional_set = frozenset(self.regionals)
        #: the first live regional self-elects as global root (successor
        #: election = the same rule applied to the post-death view)
        self.root: Optional[str] = self.regionals[0] if self.regionals else None

    # ---- views ----

    @property
    def live_members(self) -> List[str]:
        return [m for m in self.topo.members if m not in self.dead]

    def is_live(self, addr: str) -> bool:
        return self.topo.cluster_index(addr) is not None and addr not in self.dead

    def role(self, addr: str) -> Optional[str]:
        """``"global" | "regional" | "edge" | "dead"`` — None for a
        non-member (an address this view has never seen)."""
        if self.topo.cluster_index(addr) is None:
            return None
        if addr in self.dead:
            return "dead"
        if addr == self.root:
            return "global"
        if self._regional[self.topo.cluster_index(addr)] == addr:
            return "regional"
        return "edge"

    def roles(self) -> Dict[str, str]:
        """Every member's role — the property-test surface."""
        return {m: self.role(m) for m in self.topo.members}

    # ---- routing decisions ----

    def push_target(self, addr: str) -> Optional[str]:
        """Where ``addr``'s training updates go: its cluster's live
        regional (possibly ``addr`` itself — offer locally then). A
        not-yet-chunked joiner or a fully dead cluster falls back to the
        global root."""
        ci = self.topo.cluster_index(addr)
        if ci is None:
            return self.root
        regional = self._regional[ci]
        return regional if regional is not None else self.root

    def live_children(self, addr: str) -> List[str]:
        """``addr``'s push-down fan-out for fresh globals: the root
        reaches the other live regionals; a cluster's live regional
        reaches its cluster's live members (the root is also its own
        cluster's regional — roles nest)."""
        out: List[str] = []
        if addr == self.root:
            out.extend(r for r in self.regionals if r != addr)
        ci = self.topo.cluster_index(addr)
        if ci is not None and self._regional[ci] == addr:
            out.extend(
                m for m in self.topo.clusters[ci] if m != addr and m not in self.dead
            )
        return out

    def update_sink(self, addr: str, origin: str) -> Optional[str]:
        """Which buffer an ``async_update`` arriving at ``addr`` feeds:
        ``"global"`` (a peer regional's aggregate reaching the root, or
        any arrival in a flat topology), ``"regional"`` (cluster
        contributions — at the root this also ABSORBS updates from
        demoted/orphaned producers whose aggregator died, the PR-9
        orphan-adoption semantics), or None (``addr`` holds no buffer in
        this view — the caller stashes for a possible role change)."""
        if addr == self.root:
            if self.topo.is_flat():
                return "global"
            if origin != addr and origin in self._regional_set:
                return "global"
            return "regional"
        ci = self.topo.cluster_index(addr)
        if ci is not None and self._regional[ci] == addr:
            return "regional"
        return None

    def buffer_plan(self, addr: str, k: int) -> BufferPlan:
        """The aggregation duties ``addr`` holds in this view (K clamped
        to live fan-in; see :class:`BufferPlan`)."""
        if self.topo.is_flat():
            if addr == self.root:
                return BufferPlan(None, max(1, min(k, len(self.live_members))))
            return BufferPlan(None, None)
        regional_k = None
        ci = self.topo.cluster_index(addr)
        if ci is not None and self._regional[ci] == addr:
            live = [m for m in self.topo.clusters[ci] if m not in self.dead]
            regional_k = max(1, min(k, len(live)))
        global_k = (
            max(1, min(k, len(self.regionals))) if addr == self.root else None
        )
        return BufferPlan(regional_k, global_k)

    def reconcile_ops(
        self, addr: str, k: int, has_regional: bool, has_global: bool
    ) -> List["BufferOp"]:
        """The buffer-migration steps a driver must apply to move ``addr``
        from its current buffer set to this view's :meth:`buffer_plan` —
        the SHARED reconcile contract (one more piece both drivers consume
        instead of mirroring):

        - ``forward``: the tier is no longer held (demotion / leave) —
          drain the buffer raw (``BufferedAggregator.take_pending``) and
          forward each update, version triple intact, to ``op.target``
          (the successor tier: the cluster's live regional for a regional
          buffer, the global root for a global buffer). The successor's
          version vector re-dedups replays.
        - ``create``: the tier is newly held (promotion) — build the
          buffer seeded with the node's last adopted global (params AND
          version); a GLOBAL buffer additionally seeds its counter from
          the node's version high-water mark so minting never regresses
          across a root handover.
        - ``resize``: same tier, live fan-in changed — re-clamp K
          (``set_k``), which may fire the flush a dead member was
          blocking (the eviction-repair contract); the driver propagates
          the returned flush.
        """
        plan = self.buffer_plan(addr, k)
        ops: List[BufferOp] = []
        if plan.regional_k is None:
            if has_regional:
                ops.append(BufferOp("forward", "regional", None, self.push_target(addr)))
        elif not has_regional:
            ops.append(BufferOp("create", "regional", plan.regional_k, None))
        else:
            ops.append(BufferOp("resize", "regional", plan.regional_k, None))
        if plan.global_k is None:
            if has_global:
                ops.append(BufferOp("forward", "global", None, self.root))
        elif not has_global:
            ops.append(BufferOp("create", "global", plan.global_k, None))
        else:
            ops.append(BufferOp("resize", "global", plan.global_k, None))
        return ops

    def describe(self) -> dict:
        d = self.topo.describe()
        d.update(
            {
                "dead": sorted(self.dead),
                "live_regionals": list(self.regionals),
                "root": self.root,
            }
        )
        return d
