"""BASELINE.md configs 2-5, measured (config 1 anchor included).

Each config prints ONE JSON line (5 lines total). The headline driver
metric stays in ``bench.py``; this suite fills in the BASELINE table:

1. MNIST MLP, 2 nodes, FedAvg, in-memory Node mode (reference CI anchor)
2. CIFAR-10-shaped ResNet-18, 8 nodes, FedAvg, SPMD (+ MFU)
3. CIFAR-100-shaped ResNet-50, 64 nodes, Dirichlet(0.5) non-IID, SPMD
4. Krum + TrimmedMean with 20% Byzantine nodes, CIFAR-10 ResNet-18
5. LoRA transformer federation, 32 nodes, FedAvg on LoRA deltas

Data is the synthetic stand-in everywhere (no download egress); provenance
is recorded per line. All accuracy numbers are real multi-round
convergence trajectories, not single-dispatch saturation.

Usage: ``python bench_suite.py [config ...]`` (default: all).
"""

from __future__ import annotations

import json
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from p2pfl_tpu.management.profiling import force_execution


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def emit(payload: dict) -> None:
    print(json.dumps(payload), flush=True)


def _steady_state(fed, rounds: int = 3) -> float:
    t0 = time.monotonic()
    for _ in range(rounds):
        fed.run_round(epochs=1)
    force_execution(fed.params)
    return (time.monotonic() - t0) / rounds


def _spmd_mfu(fed, sec_per_round: float):
    from p2pfl_tpu.management.profiling import mfu

    flops = fed.round_flops()
    n_dev = len(set(fed.mesh.devices.flat))
    return flops, mfu(flops, sec_per_round, n_devices=n_dev)


def _mfu_from(flops, seconds: float):
    from p2pfl_tpu.management.profiling import mfu

    return mfu(flops, seconds)


def _reexec(config_key: str, timeout: int = 900, cpu: bool = True, virtual_devices: int = 0):
    """Run one config in a child process and forward its JSON.

    Single place for the child-env hygiene that previously diverged across
    copies: ``cpu=True`` forces the CPU backend AND scrubs
    PALLAS_AXON_POOL_IPS (the image's sitecustomize otherwise claims the
    real chip in every python child — if the parent already holds it the
    child aborts with a C++ exception); ``virtual_devices`` adds the
    host-platform device-count flag for virtual-mesh children.
    """
    import os
    import subprocess

    env = dict(os.environ)
    if cpu:
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
    if virtual_devices:
        flags = [
            f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        env["XLA_FLAGS"] = " ".join(
            flags + [f"--xla_force_host_platform_device_count={virtual_devices}"]
        )
    proc = subprocess.run(
        [sys.executable, __file__, config_key], env=env,
        capture_output=True, text=True, timeout=timeout,
    )
    sys.stderr.write(proc.stderr[-2000:])
    if proc.returncode == 0 and proc.stdout.strip():
        sys.stdout.write(proc.stdout)
        sys.stdout.flush()
    else:
        emit({
            "metric": f"config{config_key}",
            "error": f"re-exec rc={proc.returncode}: {proc.stderr[-300:]}",
        })


def config1_mnist_2node() -> None:
    """Reference CI anchor: 2 Node objects, in-memory transport, 1 epoch.

    This row is the CPU reference (BASELINE table: "in-memory comm (CPU
    ref)", mirroring the reference's own CI test which runs on CPU) — it
    measures the protocol stack, not an accelerator. Round-2 ran it
    through the axon-tunneled TPU backend, where every one of the ~10
    device dispatches per round pays a tunnel round trip: the 6.6 s/round
    (5.7–17.7 s variance) it reported was tunnel latency, not protocol
    waits. The round-3 profiling breakdown (emitted below) shows the
    stack is COMPUTE-dominated on CPU: fit + evaluate account for most of
    the wall clock and gossip/aggregation waits are sub-second with the
    documented low-latency profile (``set_low_latency_settings``).
    """
    if jax.default_backend() != "cpu":
        # re-exec on the CPU backend this row is defined on; the parent
        # (possibly holding the TPU) just forwards the child's JSON
        _reexec("1", timeout=600)
        return

    import collections
    import functools

    from p2pfl_tpu.communication.gossiper import Gossiper
    from p2pfl_tpu.learning.aggregators.aggregator import Aggregator
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.learning.learner import JaxLearner
    from p2pfl_tpu.models import mlp
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.settings import set_low_latency_settings
    from p2pfl_tpu.utils import wait_to_finish

    # per-primitive wall-clock accounting (summed across both node threads)
    acc: collections.Counter = collections.Counter()

    def timed(name, fn):
        @functools.wraps(fn)
        def wrapper(*a, **k):
            t0 = time.monotonic()
            try:
                return fn(*a, **k)
            finally:
                acc[name] += time.monotonic() - t0

        return wrapper

    Gossiper.gossip_weights = timed("gossip_s", Gossiper.gossip_weights)
    Aggregator.wait_and_get_aggregation = timed("agg_wait_s", Aggregator.wait_and_get_aggregation)
    JaxLearner.fit = timed("fit_s", JaxLearner.fit)
    JaxLearner.evaluate = timed("eval_s", JaxLearner.evaluate)

    from p2pfl_tpu.management.profiling import (
        mfu,
        snapshot_and_reset_dispatch_counts,
    )
    from p2pfl_tpu.settings import Settings

    set_low_latency_settings()
    full = FederatedDataset.synthetic_mnist(n_train=4096, n_test=1024)
    n_nodes = 2

    def run_overlay(rounds: int, epochs: int, fused: bool, telemetry_on: bool = True) -> dict:
        """One fresh 2-node federation; returns sec/round + dispatch split.

        ``dispatches_per_round`` counts MODEL-PLANE device dispatches per
        node per round (management/profiling.py record_dispatch sites:
        eval/train/fused-round programs + aggregate kernels), excluding
        the per-node experiment-end evaluation which is outside the round
        loop on both paths. ``telemetry_on=False`` disables the flight
        recorder (ISSUE 7 overhead split — counters stay on either way,
        so the dispatch accounting is unaffected).
        """
        prev = Settings.ROUND_FUSED
        prev_telemetry = Settings.TELEMETRY_ENABLED
        Settings.ROUND_FUSED = fused
        Settings.TELEMETRY_ENABLED = telemetry_on
        nodes = []
        try:
            # compile warm-up OUTSIDE the timer: the mode's round programs
            # (same module/tx/shapes => shared jit cache) would otherwise
            # bill one XLA compile to whichever mode runs its shape first
            warm = JaxLearner(
                mlp(seed=99), full.partition(0, n_nodes), batch_size=64, epochs=epochs
            )
            if fused:
                warm.fused_round()
            else:
                warm.evaluate()
                warm.fit()
            for i in range(n_nodes):
                learner = JaxLearner(mlp(seed=i), full.partition(i, n_nodes), batch_size=64)
                n = Node(learner=learner)
                n.start()
                nodes.append(n)
            nodes[0].connect(nodes[1].addr)
            time.sleep(0.5)
            snapshot_and_reset_dispatch_counts()  # atomic clear of warm-up counts
            acc_before = dict(acc)  # primitive-timing snapshot (breakdown
            t0 = time.monotonic()   # must exclude warm-up and final eval)
            nodes[0].set_start_learning(rounds=rounds, epochs=epochs)
            wait_to_finish(nodes, timeout=300)
            elapsed = time.monotonic() - t0
            # atomic harvest: the nodes' threads are still live here — a
            # get+reset pair would lose dispatches landing in the gap
            counts = snapshot_and_reset_dispatch_counts()
            run_breakdown = {
                k: round(v - acc_before.get(k, 0.0), 2)
                for k, v in sorted(acc.items())
                if v - acc_before.get(k, 0.0) > 0
            }
            final_acc = nodes[0].learner.evaluate()["test_acc"]
        finally:
            Settings.ROUND_FUSED = prev
            Settings.TELEMETRY_ENABLED = prev_telemetry
            for n in nodes:
                n.stop()
        in_round = sum(counts.values()) - n_nodes  # minus experiment-end evals
        return {
            "sec_per_round": round(elapsed / rounds, 4),
            "dispatches_per_round": round(in_round / (rounds * n_nodes), 2),
            "dispatch_counts": {k: int(v) for k, v in sorted(counts.items())},
            "final_acc": round(float(final_acc), 4),
            "breakdown": run_breakdown,
        }

    rounds = 3
    # anchor pair at the historical config (1 local epoch): staged first —
    # the timed-primitive breakdown wrappers above only fire on the staged
    # path — then the fused default the headline value now reports
    staged1 = run_overlay(rounds, epochs=1, fused=False)
    breakdown = staged1["breakdown"]
    fused1 = run_overlay(rounds, epochs=1, fused=True)

    # dispatch-tax split at 5 local epochs (ISSUE 6 flagship row): the
    # staged path pays 1 eval + 5 train + aggregate dispatches per node
    # per round; the fused path one program + aggregate — the ≥ 3×
    # reduction guarded by tests/test_fused_round.py in round_bench.yml
    split_epochs = 5
    staged5 = run_overlay(rounds, epochs=split_epochs, fused=False)
    fused5 = run_overlay(rounds, epochs=split_epochs, fused=True)

    # ISSUE 7 overhead split: the flight recorder (stage/gossip/dispatch
    # spans, wire trace ctx, per-span histogram feed) must stay ≤5% on
    # this round loop. Longer runs than the headline pair because the
    # on/off delta is small against protocol-tick noise; the headline
    # value above already INCLUDES telemetry (it is on by default).
    tel_rounds = 6
    tel_on = run_overlay(tel_rounds, epochs=1, fused=True)
    tel_off = run_overlay(tel_rounds, epochs=1, fused=True, telemetry_on=False)
    telemetry_overhead_pct = round(
        (tel_on["sec_per_round"] - tel_off["sec_per_round"])
        / tel_off["sec_per_round"]
        * 100,
        2,
    )

    # model FLOPs of one overlay round (all nodes, scan-free single-step
    # probe x steps — the same scan-trip-count correction every SPMD
    # round_flops applies), so the overlay round gets a first-class MFU
    # row (null off-TPU like every other row's)
    import jax.numpy as jnp
    import optax

    from p2pfl_tpu.learning.learner import _loss
    from p2pfl_tpu.management.profiling import compiled_flops

    probe = JaxLearner(mlp(seed=0), full.partition(0, n_nodes), batch_size=64)

    def one_step(p, o, bx, by):
        loss, grads = jax.value_and_grad(
            lambda p_: _loss(p_, probe.model.module, bx, by)[0]
        )(p)
        updates, o = probe.tx.update(grads, o, p)
        return optax.apply_updates(p, updates), o

    bx = jnp.zeros((64, *full.x_train.shape[1:]), jnp.float32)
    by = jnp.zeros((64,), jnp.int32)
    step_flops = compiled_flops(jax.jit(one_step), probe.params, probe.opt_state, bx, by)
    nb = probe.data.num_samples // 64
    flops_round = (
        step_flops * split_epochs * nb * n_nodes if step_flops is not None else None
    )
    overlay_mfu = (
        mfu(flops_round, fused5["sec_per_round"]) if flops_round is not None else None
    )

    emit({
        "metric": "config1_mnist_mlp_2node_memory",
        "value": fused1["sec_per_round"],
        "unit": "sec_per_round",
        "rounds": rounds,
        "final_acc": fused1["final_acc"],
        "staged_sec_per_round": staged1["sec_per_round"],
        "data": "synthetic",
        "transport": "memory (full Node stack: gossip+vote+heartbeat)",
        "backend": "cpu (this row is the CPU reference anchor)",
        "settings_profile": "low_latency",
        # thread-summed primitive totals over the staged anchor run (2
        # node threads run concurrently, so these can exceed wall clock)
        "breakdown_thread_totals_s": breakdown,
        # ISSUE 6 first-class rows: model-plane device dispatches per node
        # per round, staged vs fused, at the 5-local-epoch split config
        "dispatches_per_round": {
            "staged": staged5["dispatches_per_round"],
            "fused": fused5["dispatches_per_round"],
            "reduction_x": round(
                staged5["dispatches_per_round"]
                / max(fused5["dispatches_per_round"], 1e-9),
                2,
            ),
        },
        "overlay_split_epochs5": {
            "staged": {k: staged5[k] for k in ("sec_per_round", "dispatches_per_round")},
            "fused": {k: fused5[k] for k in ("sec_per_round", "dispatches_per_round")},
            "note": "CPU anchor: at 5 local epochs the round is "
            "compute-dominated so staged/fused wall-clock converge here; "
            "the dispatch cut is the accelerator-facing win (each overlay "
            "dispatch pays a tunnel round trip on remote-attached TPUs — "
            "see the config1 docstring's round-2 measurement)",
        },
        "flops_per_round_overlay": flops_round,
        "overlay_mfu": round(overlay_mfu, 4) if overlay_mfu is not None else None,
        # ISSUE 7 acceptance row: flight-recorder overhead on the fused
        # round loop (spans + wire trace ctx + histograms vs all off)
        "telemetry": {
            "on_sec_per_round": tel_on["sec_per_round"],
            "off_sec_per_round": tel_off["sec_per_round"],
            "overhead_pct": telemetry_overhead_pct,
            "budget_pct": 5.0,
            "rounds": tel_rounds,
        },
    })


def config2_resnet18_8node() -> None:
    """Two halves of the north-star metric (BASELINE.md:19-21):

    1. TIME-TO-TARGET-ACCURACY (VERDICT r2 #1): 8-node ResNet-18 FedAvg on
       synthetic-hard CIFAR-10 to ≥70%. Round 2's recipe (constant Adam
       1e-3, per-round moment reset, 6-round budget) flatlined at 15% —
       starved, not unlearnable (a centrally trained ResNet-18 reaches 92%
       by step 200 with a warmup schedule). The fixed federated recipe:
       warmup-cosine LR with ``keep_opt_state=True`` so the schedule and
       Adam moments survive round boundaries.
    2. SEC/ROUND + MFU at throughput settings. The MFU lever found in
       round 3: amortize the round's fixed dispatch/aggregation cost over
       more local steps (bigger shard × multi-epoch rounds) — convs were
       already bf16, buffers already donated.
    """
    import optax

    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.models import resnet18
    from p2pfl_tpu.parallel import SpmdFederation

    data = FederatedDataset.synthetic_mnist(
        n_train=8 * 1024, n_test=1024, dim=(32, 32, 3), modes=8, noise=0.7, proto_scale=0.5
    )
    # --- half 1: time to target accuracy ---
    cap, spr_steps, target = 25, 16, 0.70
    sched = optax.warmup_cosine_decay_schedule(
        0.0, 3e-3, warmup_steps=2 * spr_steps, decay_steps=cap * spr_steps, end_value=1e-4
    )
    fed = SpmdFederation.from_dataset(
        resnet18(), data, n_nodes=8, batch_size=64, vote=False, seed=3,
        tx=optax.adam(sched), keep_opt_state=True,
    )
    curve = []
    rounds_to_target = None
    time_to_target = None
    t0 = time.monotonic()
    for r in range(cap):
        acc = float(fed.run_round(epochs=1, eval=True)["test_acc"])
        curve.append(round(acc, 4))
        if rounds_to_target is None and acc >= target:
            rounds_to_target = r + 1
            time_to_target = time.monotonic() - t0
            break
    log(f"config2: target {target} at round {rounds_to_target} ({time_to_target})")
    del fed
    jax.clear_caches()

    # --- half 2: throughput + MFU (2048-sample shards, batch 256) ---
    data_big = FederatedDataset.synthetic_mnist(
        n_train=8 * 2048, n_test=1024, dim=(32, 32, 3), modes=8, noise=0.7, proto_scale=0.5
    )
    fed_big = SpmdFederation.from_dataset(
        resnet18(), data_big, n_nodes=8, batch_size=256, vote=False, seed=3
    )
    fed_big.run_round(epochs=1)
    force_execution(fed_big.params)
    sec_per_round = _steady_state(fed_big)
    flops, round_mfu = _spmd_mfu(fed_big, sec_per_round)
    # multi-epoch rounds amortize the fixed per-round cost further
    fed_big.run_round(epochs=4)
    force_execution(fed_big.params)
    t0 = time.monotonic()
    for _ in range(3):
        fed_big.run_round(epochs=4)
    force_execution(fed_big.params)
    sec_ep4 = (time.monotonic() - t0) / 3
    flops_ep4 = fed_big.round_flops(epochs=4)
    from p2pfl_tpu.management.profiling import mfu as _mfu

    # same per-device normalization as the sibling mfu field
    mfu_ep4 = _mfu(flops_ep4, sec_ep4, n_devices=len(set(fed_big.mesh.devices.flat)))

    emit({
        "metric": "config2_resnet18_cifar10_8node_fedavg",
        "value": round(sec_per_round, 4),
        "unit": "sec_per_round",
        "target_acc": target,
        "rounds_to_target": rounds_to_target,
        "time_to_target_s": round(time_to_target, 2) if time_to_target else None,
        "accuracy_curve": curve,
        "recipe": "adam warmup-cosine peak 3e-3, keep_opt_state, batch 64",
        "throughput_point": "batch 256, 2048 samples/node",
        "flops_per_round": flops,
        "mfu": round(round_mfu, 4) if round_mfu is not None else None,
        "epochs4": {
            "sec_per_round": round(sec_ep4, 4),
            "mfu": round(mfu_ep4, 4) if mfu_ep4 is not None else None,
        },
        "data": "synthetic-hard (CIFAR-10 shaped)",
        "devices": len(jax.devices()),
    })


def config3_resnet50_64node_dirichlet() -> None:
    # 64-node ResNet-50 state is 64 × (params + 2 Adam moments) ≈ 19.6 GB —
    # sized for the v4-128 pod target, over one chip's HBM resident. The
    # STATED 64 nodes run anyway by time-sharing the chip in 16-node chunks
    # (ChunkedFederation, VERDICT r3 #3); resident folds remain as
    # fallbacks. Each attempt probes in a FRESH subprocess (a failed
    # attempt leaves the backend's allocator in an unusable state).
    import os
    import subprocess

    if os.environ.get("P2PFL_CONFIG3_NODES"):
        _config3_measure(int(os.environ["P2PFL_CONFIG3_NODES"]))
        return
    for n_nodes in (64, 32, 16):
        env = dict(os.environ, P2PFL_CONFIG3_NODES=str(n_nodes))
        proc = subprocess.run(
            [sys.executable, __file__, "3"], env=env,
            capture_output=True, text=True, timeout=2400,
        )
        sys.stderr.write(proc.stderr[-1500:])
        if proc.returncode == 0 and proc.stdout.strip():
            sys.stdout.write(proc.stdout)
            sys.stdout.flush()
            return
        log(f"config3: n={n_nodes} attempt failed (rc={proc.returncode})")
    raise RuntimeError("config3 failed at every fold")


def _config3_measure(n_nodes: int) -> None:
    """ResNet-50 / CIFAR-100-shaped / Dirichlet(0.5) non-IID, at the
    STATED 64 nodes via chip time-sharing.

    Round-3 recipe (VERDICT r2 #1): warmup-cosine + kept optimizer state —
    at 64 nodes "kept" means the ChunkedFederation moment-averaging
    divergence (per-node moments are exactly the state that doesn't fit;
    see ``parallel/chunked.py``), with the schedule's step count surviving
    rounds. Resident SpmdFederation folds (32/16) remain the fallback
    path and the apples-to-apples comparison.
    """
    import optax

    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.models import resnet50
    from p2pfl_tpu.parallel import ChunkedFederation, SpmdFederation

    data = FederatedDataset.synthetic_mnist(
        n_train=64 * 256, n_test=1024, dim=(32, 32, 3), num_classes=100,
        modes=2, noise=0.5, proto_scale=0.7,
    )
    cap, target = 60, 0.50
    chunked = n_nodes >= 64
    # chunked batch: the round-5 chunk×batch sweep measured (chunk16)
    # 2.63 s/round at b32, 2.10 at b64, 1.95 at b128 (15.9% model-MFU);
    # chunk 32 OOMs. But the larger batches trade away convergence on the
    # Dirichlet task (b128: 0.04 acc at the 60-round cap, b64: 0.47 —
    # 2 resp. 4 optimizer steps/round starve the recipe), so the row keeps
    # the b32 recipe that reaches target; per-chunk data pre-staging
    # (chunked.py) already cut b32 from round-4's 3.48 to 2.63 s/round
    batch = 32
    spr_steps = (64 * 256 // n_nodes) // batch
    sched = optax.warmup_cosine_decay_schedule(
        0.0, 3e-3, warmup_steps=2 * spr_steps, decay_steps=40 * spr_steps, end_value=1e-4
    )
    if chunked:
        fed = ChunkedFederation.from_dataset(
            resnet50(), data, n_nodes=n_nodes, chunk_size=16,
            strategy="dirichlet", alpha=0.5, batch_size=batch, vote=False,
            seed=3, remat=True, tx=optax.adam(sched), keep_opt_state=True,
        )
    else:
        fed = SpmdFederation.from_dataset(
            resnet50(), data, n_nodes=n_nodes, strategy="dirichlet", alpha=0.5,
            batch_size=32, vote=False, seed=3, remat=True,
            tx=optax.adam(sched), keep_opt_state=True,
        )
    fed.run_round(epochs=1)  # warm-up + OOM probe
    force_execution(fed.params)
    fed.evaluate()  # probe the eval path's memory too
    fed.reset(seed=3)
    curve = []
    rounds_to_target = None
    time_to_target = None
    t0 = time.monotonic()
    for r in range(cap):
        acc = float(fed.run_round(epochs=1, eval=True)["test_acc"])
        curve.append(round(acc, 4))
        if rounds_to_target is None and acc >= target:
            rounds_to_target = r + 1
            time_to_target = time.monotonic() - t0
            break
    sec_per_round = _steady_state(fed)
    mfu_hw = None
    staging_split = None
    if chunked:
        flops = fed.round_flops()
        round_mfu = _mfu_from(flops, sec_per_round)
        # EXECUTED flops (remat recompute included) — the numerator the
        # resident SpmdFederation probes report; chunked-vs-resident MFU
        # is only comparable on this one (VERDICT r4 #4: the round-4 "2×
        # MFU gap" compared chunked model-flops against resident hw-flops)
        flops_hw = fed.round_flops(hw=True)
        mfu_hw = _mfu_from(flops_hw, sec_per_round)
        # before/after split for the round-pipeline overhaul: the SERIAL
        # path (host-side per-leaf reduce between chunks, stage-then-
        # dispatch order) vs the OVERLAPPED path (fused on-device
        # accumulators + staged-ahead inputs) on the same warm executables
        from p2pfl_tpu.settings import Settings

        prior = (Settings.CHUNK_FUSED_REDUCE, Settings.CHUNK_STAGING_DEPTH)
        try:
            Settings.CHUNK_FUSED_REDUCE = False
            Settings.CHUNK_STAGING_DEPTH = 1
            fed.run_round(epochs=1)  # warm the serial-path executable
            force_execution(fed.params)
            sec_serial = _steady_state(fed)
        finally:
            # a mid-measurement failure must not leave the de-optimized
            # serial path enabled for every later config in this process
            Settings.CHUNK_FUSED_REDUCE, Settings.CHUNK_STAGING_DEPTH = prior
        staging_split = {
            "serial_sec_per_round": round(sec_serial, 4),
            "overlapped_sec_per_round": round(sec_per_round, 4),
            "overlap_speedup": round(sec_serial / sec_per_round, 3),
            "overlapped_mfu": round(round_mfu, 4) if round_mfu is not None else None,
            "serial_mfu": round(_mfu_from(flops, sec_serial) or 0, 4),
        }
    else:
        flops, round_mfu = _spmd_mfu(fed, sec_per_round)
    emit({
        "metric": "config3_resnet50_cifar100_64node_dirichlet",
        "value": round(sec_per_round, 4),
        "unit": "sec_per_round",
        "n_nodes": n_nodes,
        "execution": (
            "chunked time-sharing (16 nodes resident/chunk, aggregated "
            "moments — parallel/chunked.py)" if chunked else "resident SPMD"
        ),
        "target_acc": target,
        "rounds_to_target": rounds_to_target,
        "time_to_target_s": round(time_to_target, 2) if time_to_target else None,
        "accuracy_curve": curve,
        "recipe": f"adam warmup-cosine peak 3e-3, kept opt state "
                  f"(moment-averaged when chunked), batch {batch}, remat",
        "flops_per_round": flops,
        "mfu": round(round_mfu, 4) if round_mfu is not None else None,
        # executed-flops utilization (remat recompute counted), the number
        # comparable with the resident folds' probes
        "mfu_hw": round(mfu_hw, 4) if mfu_hw is not None else None,
        # serial vs overlapped chunk pipeline (the round-6 overhaul:
        # fused on-device accumulators + staged-ahead chunk inputs)
        "staging_split": staging_split,
        "gap_attribution": (
            "round-4's '2x MFU gap' vs the 16-node resident proxy was "
            "mostly accounting (chunked reported model flops, resident "
            "executed flops incl. remat): executed-basis this row runs "
            "~20% vs resident 21%. The per-chunk staging delta (broadcast "
            "aggregate + fp32 reduce serialized behind compute) is now "
            "measured directly by staging_split: the overlapped path folds "
            "the reduce into the chunk program (donated accumulators) and "
            "stages chunk k+1's inputs during chunk k's compute; throughput-"
            "optimal point (chunk16/b128) reaches 1.95 s/round, 15.9% "
            "model-MFU, but starves the convergence recipe (see batch "
            "comment in _config3_measure)" if chunked else None
        ),
        "partition": "dirichlet(0.5)",
        "data": "synthetic (CIFAR-100 shaped)",
        "devices": len(jax.devices()),
    })


def config4_byzantine_robust() -> None:
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.models import resnet18
    from p2pfl_tpu.parallel import SpmdFederation

    n, byz, rounds = 10, 2, 10  # 20% Byzantine
    data = FederatedDataset.synthetic_mnist(
        n_train=n * 512, n_test=1024, dim=(32, 32, 3), modes=2, noise=0.5, proto_scale=0.7
    )
    results = {}
    key = jax.random.PRNGKey(0)
    # fedavg is the non-robust control: same attack, no defense
    for agg in ("krum", "trimmed_mean", "clip", "fedavg"):
        fed = SpmdFederation.from_dataset(
            resnet18(), data, n_nodes=n, batch_size=64, vote=False,
            aggregator=agg, trim=byz, clip_tau=3.0, seed=3, remat=True,
        )
        t_rounds = []
        for _ in range(rounds):
            # Byzantine nodes: overwrite their slots with large Gaussian noise
            # before the round — they train from (and contribute) garbage
            fed.params = jax.tree.map(
                lambda x: x.at[:byz].set(
                    jax.random.normal(key, x.shape[1:], x.dtype) * 10.0
                ),
                fed.params,
            )
            t0 = time.monotonic()
            fed.run_round(epochs=1)
            force_execution(fed.params)
            t_rounds.append(time.monotonic() - t0)
        results[agg] = {
            "acc": round(float(fed.evaluate()["test_acc"]), 4),
            "sec_per_round": round(float(np.mean(t_rounds[1:])), 4),
        }
    emit({
        "metric": "config4_byzantine_robust_cifar10",
        "value": results["krum"]["sec_per_round"],
        "unit": "sec_per_round",
        "byzantine_fraction": byz / n,
        "rounds": rounds,
        "krum": results["krum"],
        "trimmed_mean": results["trimmed_mean"],
        "centered_clip": results["clip"],
        "fedavg_under_attack": results["fedavg"],
        "data": "synthetic (CIFAR-10 shaped)",
        "devices": len(jax.devices()),
    })


def config5_lora_32node() -> None:
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.learning.lora import split_lora
    from p2pfl_tpu.models.transformer import tiny_transformer
    from p2pfl_tpu.parallel import SpmdLoraFederation

    import optax

    n = 32
    model = tiny_transformer(seq_len=128)
    # shifted-domain protocol (same as the 104M/1B rows): pretrain the base
    # on the SOURCE chain, federate adapters on a 15%-shifted successor
    # table — the adapters must close a real gap (the previous same-domain
    # row saturated at the base's 0.90 and measured a no-op)
    pretrain_data = FederatedDataset.synthetic_lm(n_train=2048, n_test=256)
    data = FederatedDataset.synthetic_lm(n_train=n * 64, n_test=256, shift_frac=0.15)

    # the real LoRA use case is adapting a PRETRAINED base: briefly pretrain
    # the full model centrally, then federate only the adapters on top
    tx = optax.adam(1e-3)
    params, opt = model.params, None

    @jax.jit
    def pre_step(params, opt, x, y):
        def loss_fn(p):
            logits = model.module.apply({"params": p}, x)
            return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, updates), opt, loss

    opt = tx.init(params)
    rng = np.random.default_rng(0)
    for step in range(300):
        idx = rng.integers(0, len(pretrain_data.y_train), size=16)
        params, opt, loss = pre_step(
            params, opt,
            jnp.asarray(pretrain_data.x_train[idx]),
            jnp.asarray(pretrain_data.y_train[idx]),
        )
    model.params = params
    log(f"config5: base pretrained (loss {float(loss):.3f})")

    fed = SpmdLoraFederation.from_dataset(
        model, data, n_nodes=n, batch_size=8, vote=False, seed=3, remat=True
    )
    base_acc = fed.evaluate()["test_acc"]  # pretrained base on the SHIFTED domain
    fed.run_round(epochs=1)  # warm-up
    fed.run_fused(4, epochs=1)  # warm the fused executable too
    fed.reset(seed=3)
    sec_per_round = _steady_state(fed, rounds=4)
    acc = fed.evaluate()["test_acc"]  # BEFORE the fused span: 4-round acc
    # fused span: 4 rounds in ONE dispatch — adapters are tiny, so the
    # per-round cost is dispatch-dominated and fusing amortizes it
    t0 = time.monotonic()
    fed.run_fused(4, epochs=1)
    force_execution(fed.params)
    sec_fused = (time.monotonic() - t0) / 4
    lora, base = split_lora(model.params)
    n_lora = sum(x.size for x in jax.tree.leaves(lora))
    n_base = sum(x.size for x in jax.tree.leaves(base))
    from p2pfl_tpu.management.profiling import mfu as _mfu

    flops = fed.round_flops()
    emit({
        "metric": "config5_lora_transformer_32node",
        "value": round(sec_per_round, 4),
        "unit": "sec_per_round",
        "sec_per_round_fused": round(sec_fused, 4),
        "flops_per_round": flops,
        # MFU on the UNFUSED round (VERDICT r2 #2); the 3.4M-param
        # stand-in is dispatch-dominated (that's what fusing fixes), so
        # this is a lower bound for the TinyLlama-scale target
        "mfu": round(_mfu(flops, sec_per_round) or 0, 4) if flops else None,
        "mfu_fused": round(_mfu(flops, sec_fused) or 0, 4) if flops else None,
        "pretrained_base_acc": round(float(base_acc), 4),
        "next_token_acc_after_4_rounds": round(float(acc), 4),
        "adapter_params": n_lora,
        "base_params": n_base,
        "payload_shrink": round(n_base / n_lora, 1),
        "data": "synthetic-lm (markov, 15% shifted domain)",
        "devices": len(jax.devices()),
    })


def _lora_step_flops_by_depth(
    dim, n_heads, n_kv, ffn, vocab, n_layers, tokens_per_step, seq_len=1024,
    lora_mlp=False,
):
    """XLA-counted LoRA train-step FLOPs, extrapolated linearly in depth.

    The deep programs cannot be cost-analyzed directly here — the axon
    compile tunnel rejects request bodies above its size limit (HTTP 413)
    for explicit ``.lower().compile()`` of the big models — but per-layer
    cost is EXACTLY linear in depth, so probe 1- and 2-layer clones and
    extrapolate ``f(L) = f(1) + (f(2) − f(1))·(L−1)``, scaled by the real
    step's token count (flops are linear in batch at fixed seq_len). The
    probes use DENSE attention so the attention core is IN the count (the
    big model's Pallas kernel is invisible to cost analysis regardless).
    """
    import optax

    from p2pfl_tpu.learning.lora import merge_params, split_lora
    from p2pfl_tpu.management.profiling import compiled_flops
    from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer

    def f(layers):
        cfg = TransformerConfig(
            vocab_size=vocab, dim=dim, n_layers=layers, n_heads=n_heads,
            n_kv_heads=n_kv, ffn_hidden=ffn, lora_rank=8, lora_mlp=lora_mlp,
        )
        m = tiny_transformer(seq_len=seq_len, cfg=cfg, attn="dense")
        lora, base = split_lora(m.params)

        def loss(lo, base_, bx, by):
            p = merge_params(lo, base_)
            logits = m.module.apply({"params": p}, bx)
            return optax.softmax_cross_entropy_with_integer_labels(logits, by).mean()

        bx = jnp.zeros((2, seq_len), jnp.int32)
        return compiled_flops(jax.jit(jax.value_and_grad(loss)), lora, base, bx, bx)

    f1, f2 = f(1), f(2)
    if f1 is None or f2 is None:
        return None
    return (f1 + (f2 - f1) * (n_layers - 1)) * (tokens_per_step / (2 * seq_len))


def config5_scale_lm() -> None:
    """Config 5 grown toward nameplate (VERDICT r3 #2), step 1 of 2: a
    104M-param Llama-recipe transformer (16L/768d, 12 heads / 4 KV heads,
    SwiGLU 2048, vocab 4096, seq 1024, bf16, Pallas flash attention,
    selective remat (mlp_qkv policy, 16-node chunks — round 5; was
    blanket per-block) + lax.scan over the block stack), 32 federated nodes
    training LoRA adapters on a briefly-pretrained base — the LEARNING row
    (real next-token improvement through the federation). The 0.98B
    ``config5_nameplate_1b`` row is the throughput/MFU headline; the toy
    3.4M row stays as the dispatch-bound honesty point.

    MFU is measured on the FEDERATED ROUND program (vmapped node epochs +
    masked FedAvg in one dispatch), not a bare train step.
    """
    import optax

    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.learning.lora import split_lora
    from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer
    from p2pfl_tpu.parallel import SpmdLoraFederation

    n = 32
    cfg = TransformerConfig(
        vocab_size=4096, dim=768, n_layers=16, n_heads=12, n_kv_heads=4,
        ffn_hidden=2048, lora_rank=8, lora_mlp=True, remat=True, scan_layers=True,
        remat_policy="mlp_qkv",  # selective remat (round 5): ~11 GB of
        # saved activations at 32 nodes x batch 2 in flight — node_chunk
        # halves the in-flight set to fit (same recipe as the 1B row)
    )
    model = tiny_transformer(seq_len=1024, cfg=cfg, attn="flash")
    n_params = sum(x.size for x in jax.tree.leaves(model.params))
    log(f"config5_scale: {n_params/1e6:.1f}M params")
    # the real LoRA task is DOMAIN ADAPTATION: pretrain the base on the
    # source chain, federate adapters on a 15%-shifted successor table —
    # the base scores ~0.9·0.85 there and the adapters close the gap
    pretrain_data = FederatedDataset.synthetic_lm(
        vocab_size=4096, seq_len=1024, n_train=512, n_test=64
    )
    data = FederatedDataset.synthetic_lm(
        vocab_size=4096, seq_len=1024, n_train=n * 16, n_test=64, shift_frac=0.15
    )

    # the LoRA use case adapts a PRETRAINED base (same shape as the toy
    # row): brief central pretraining, then the federation trains only
    # adapters on top. Base params ride as ARGUMENTS, never closures — a
    # closed-over 104M tree becomes 400MB of MLIR constants and the
    # compile tunnel rejects the body (HTTP 413).
    tx = optax.adam(3e-4)

    @jax.jit
    def pre_step(params, opt, x, y):
        def loss_fn(p):
            logits = model.module.apply({"params": p}, x)
            return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, updates), opt, loss

    params, opt = model.params, tx.init(model.params)
    rng = np.random.default_rng(0)
    for step in range(300):
        idx = rng.integers(0, len(pretrain_data.y_train), size=8)
        params, opt, loss = pre_step(
            params, opt,
            jnp.asarray(pretrain_data.x_train[idx]),
            jnp.asarray(pretrain_data.y_train[idx]),
        )
    force_execution(loss)
    model.params = params
    log(f"config5_scale: base pretrained (loss {float(loss):.3f})")
    del opt

    fed = SpmdLoraFederation.from_dataset(
        model, data, n_nodes=n, batch_size=2, vote=False, seed=3, node_chunk=16,
    )
    fed.run_round(epochs=1)  # compile warm-up
    force_execution(fed.params)  # async dispatch: let it FINISH before timing
    fed.reset(seed=3)
    acc0 = fed.evaluate()["test_acc"]  # pretrained base on the SHIFTED domain
    sec_per_round = _steady_state(fed, rounds=3)
    accs = []
    for _ in range(5):
        fed.run_round(epochs=1)
        accs.append(round(fed.evaluate()["test_acc"], 4))

    # MODEL flops (remat recompute is real work but not useful flops);
    # the depth-extrapolated XLA count — see _lora_step_flops_by_depth
    step_flops = _lora_step_flops_by_depth(
        768, 12, 4, 2048, 4096, 16, tokens_per_step=n * 2 * 1024, lora_mlp=True
    )
    flops = (fed._nb * step_flops) if step_flops else None
    lora, base = split_lora(model.params)
    n_lora = sum(x.size for x in jax.tree.leaves(lora))
    emit({
        "metric": "config5_scale_lm_104m",
        "value": round(sec_per_round, 4),
        "unit": "sec_per_round",
        "model": "16L/768d/12h(kv4) SwiGLU-2048 vocab-4096 seq-1024 bf16 "
                 "flash-attn selective-remat(mlp_qkv) node-chunk-16 "
                 "scan-layers",
        "n_params": n_params,
        "n_nodes": n,
        "batch_per_node": 2,
        "flops_per_round": flops,
        "mfu": round(_mfu_from(flops, sec_per_round) or 0, 4),
        "pretrained_base_acc": round(float(acc0), 4),
        "next_token_acc_curve": accs,
        "adapter_params": n_lora,
        "payload_shrink": round((n_params - n_lora) / n_lora, 1),
        "data": "synthetic-lm (markov, vocab 4096)",
        "devices": len(jax.devices()),
    })


def config5_nameplate_1b() -> None:
    """Config 5 at NAMEPLATE scale: the TinyLlama-1.1B architecture
    (22L/2048d, 32 heads / 4 KV heads GQA, SwiGLU 5632 — vocab 4096
    instead of 32000, sized to the synthetic markov task) = 0.98B params,
    32 federated LoRA nodes on one v5e chip.

    VERDICT r4 #1 rebuilt this row twice over:

    - **it learns now.** Same recipe as the 104M row: central pretrain of
      the base (Adafactor — full-param Adam moments alone are 8 GB, over
      budget with the 4 GB f32 params) until loss is far below the
      ln(4096)=8.32 random floor, then 32 LoRA nodes federate adapters on
      a 15%-shifted successor table — next-token accuracy climbs from the
      pretrained base's shifted-domain score toward the 0.9 determinism
      ceiling, and the federated train loss falls.
    - **selective remat replaces blanket per-block remat.** remat_policy
      ``mlp_qkv`` saves FFN gate/up + post-RoPE q/k/v, so the backward
      recomputes only the flash-kernel forward (~5% of a block) instead of
      the whole block (~75% after XLA DCE). The saved activations don't
      fit with 32 nodes in flight, so ``node_chunk=4`` scans the nodes 4
      at a time (measured ladder, s/round: blanket remat 8.99 → mlp@8
      7.21 → mlp_qkv@8 6.92 → mlp_qkv@4 6.30; mlp@16 OOMs — the sweep
      that proves the policy×chunk choice).

    Two honest numerators, as before: ``mfu`` counts model flops
    (fwd+dgrad, depth-extrapolated), ``mfu_hw`` adds the policy's actual
    recompute (flash fwd ≈ 2·T_causal·dim per token vs the full 2·P
    re-forward the old blanket policy paid).

    Round 6 put the row in BASELINE metric form: 8 steps/round (n·8
    sequences at batch 1) converging to a stated next-token target (0.65)
    on the shifted domain, with ``rounds_to_target`` / ``time_to_target_s``
    like configs 2/3/10.
    """
    import optax

    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.learning.lora import split_lora
    from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer
    from p2pfl_tpu.parallel import SpmdLoraFederation

    import dataclasses

    n = 32
    cfg = TransformerConfig(
        vocab_size=4096, dim=2048, n_heads=32, n_kv_heads=4, n_layers=22,
        ffn_hidden=5632, lora_rank=8, lora_mlp=True, remat=True,
        scan_layers=True, remat_policy="mlp_qkv",
    )
    pretrain_data = FederatedDataset.synthetic_lm(
        vocab_size=4096, seq_len=1024, n_train=512, n_test=64
    )
    # n*8 sequences → 8 steps/round at batch 1: the BASELINE-metric floor
    # (≥8 optimizer steps/round) for the rounds-to-target run below
    data = FederatedDataset.synthetic_lm(
        vocab_size=4096, seq_len=1024, n_train=n * 8, n_test=32, shift_frac=0.15
    )

    # central pretrain: Adafactor fits where Adam's 8 GB of moments don't.
    # Donation is mandatory (4 GB f32 params in undonated in/out/grads
    # copies OOMed), and the pretrain uses a FULL-remat twin of the module
    # (same param tree, remat_policy=None): full-param training has no HBM
    # room for the saved mlp_qkv activations the adapter federation enjoys
    pre_model = tiny_transformer(
        seq_len=1024, cfg=dataclasses.replace(cfg, remat_policy=None), attn="flash"
    )
    n_params = sum(x.size for x in jax.tree.leaves(pre_model.params))
    log(f"config5_1b: {n_params/1e9:.3f}B params")
    tx = optax.adafactor(learning_rate=3e-3)

    @partial(jax.jit, donate_argnums=(0, 1))
    def pre_step(params, opt, x, y):
        def loss_fn(p):
            logits = pre_model.module.apply({"params": p}, x)
            return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, updates), opt, loss

    params, opt = pre_model.params, tx.init(pre_model.params)
    pre_model.params = None  # donated into the step; drop the stale handle
    rng = np.random.default_rng(0)
    pre_curve = []
    for step in range(400):
        idx = rng.integers(0, len(pretrain_data.y_train), size=8)
        params, opt, loss = pre_step(
            params, opt,
            jnp.asarray(pretrain_data.x_train[idx]),
            jnp.asarray(pretrain_data.y_train[idx]),
        )
        if step % 50 == 0:
            pre_curve.append(round(float(loss), 4))
    force_execution(loss)
    pre_curve.append(round(float(loss), 4))
    log(f"config5_1b: base pretrained, loss curve {pre_curve} "
        f"(random floor ln(4096) = 8.318)")
    del opt
    jax.clear_caches()  # the pretrain executable holds workspace HBM

    # the federation's module carries the selective-remat policy; its fresh
    # init is transient (replaced by the pretrained tree immediately)
    model = tiny_transformer(seq_len=1024, cfg=cfg, attn="flash")
    model.params = params
    fed = SpmdLoraFederation.from_dataset(
        model, data, n_nodes=n, batch_size=1, vote=False, seed=3, node_chunk=4,
    )
    fed.run_round(epochs=1)  # compile warm-up
    force_execution(fed.params)  # async dispatch: let it FINISH before timing
    fed.reset(seed=3)
    acc0 = fed.evaluate()["test_acc"]  # pretrained base on the SHIFTED domain
    fed.run_round(epochs=1)  # settling round: eval-to-steady transition
    force_execution(fed.params)
    sec_per_round = _steady_state(fed, rounds=3)
    fed.reset(seed=3)
    # BASELINE metric form (like configs 2/3/10): converge to a stated
    # next-token target on the shifted domain, report rounds/time to it
    target = 0.65
    cap = 16
    loss_curve, accs = [], []
    rounds_to_target = None
    time_to_target = None
    t0 = time.monotonic()
    for r in range(cap):
        loss_curve.append(float(fed.run_round(epochs=1)["train_loss"]))
        accs.append(round(fed.evaluate()["test_acc"], 4))
        if rounds_to_target is None and accs[-1] >= target:
            rounds_to_target = r + 1
            time_to_target = time.monotonic() - t0
            break

    tokens_per_step = n * 1 * 1024
    step_flops = _lora_step_flops_by_depth(
        2048, 32, 4, 5632, 4096, 22, tokens_per_step=tokens_per_step, lora_mlp=True
    )
    flops = (fed._nb * step_flops) if step_flops else None
    # executed flops add the policy's recompute: only the flash forward
    # re-runs (2 causal matmuls ≈ 2·2·(T/2)·dim per token) + cheap glue
    recompute_per_token = 2.0 * 2.0 * (1024 / 2) * 2048 * 22  # 2 causal matmuls x 22 layers
    flops_hw = (
        flops + fed._nb * recompute_per_token * tokens_per_step if flops else None
    )
    lora, _ = split_lora(model.params)
    n_lora = sum(x.size for x in jax.tree.leaves(lora))
    emit({
        "metric": "config5_nameplate_1b",
        "value": round(sec_per_round, 4),
        "unit": "sec_per_round",
        "model": "22L/2048d/32h(kv4) SwiGLU-5632 vocab-4096 seq-1024 bf16 "
                 "flash-attn selective-remat(mlp_qkv) node-chunk-4 "
                 "scan-layers (TinyLlama-1.1B arch at task vocab)",
        "n_params": n_params,
        "n_nodes": n,
        "batch_per_node": 1,
        "steps_per_round": fed._nb,
        "flops_per_round": flops,
        "flops_per_round_hw": flops_hw,
        "mfu": round(_mfu_from(flops, sec_per_round) or 0, 4),
        "mfu_hw": round(_mfu_from(flops_hw, sec_per_round) or 0, 4),
        "remat_note": "selective remat (save ffn gate/up + post-rope qkv, "
                      "recompute only the flash fwd) + 4-node chunking "
                      "replaces the blanket per-block remat: the eval-free "
                      "policy-ladder sweep measured 8.99 -> 6.30 s/round; "
                      "this row's headline value is the steady state "
                      "inside the federation's eval cadence (settling "
                      "round + eval-adjacent dispatch). No-remat still "
                      "OOMs (21.6G needed, 15.75G HBM), mlp-policy at 16 "
                      "nodes in flight OOMs — the ladder is "
                      "HBM-constrained",
        "pretrain_loss_curve": pre_curve,
        "random_floor_loss": 8.318,
        "pretrained_base_acc": round(float(acc0), 4),
        "target_acc": target,
        "rounds_to_target": rounds_to_target,
        "time_to_target_s": round(time_to_target, 2) if time_to_target else None,
        "next_token_acc_curve": accs,
        "train_loss_curve": [round(l, 4) for l in loss_curve],
        "adapter_params": n_lora,
        "payload_shrink": round((n_params - n_lora) / n_lora, 1),
        "data": "synthetic-lm (markov, vocab 4096, 15% shifted domain)",
        "devices": len(jax.devices()),
    })


def _sharded_1b_hbm_projection() -> dict:
    """Per-device params+Adam-moments bytes for the 1B nameplate tree under
    the default partition rules, at model_parallel 1/4/8.

    Pure accounting: the tree comes from ``jax.eval_shape`` (nothing is
    allocated) and the per-device share from the rule engine's specs +
    divisibility logic — exact on any backend, which is what makes a
    per-node HBM column honest from a CPU-only bench container.
    """
    import jax.numpy as jnp
    import optax

    from p2pfl_tpu.models.transformer import (
        CausalLM, TransformerConfig, resolve_attention,
    )
    from p2pfl_tpu.parallel.mesh import node_slices, submesh_federation_mesh
    from p2pfl_tpu.parallel.sharding import DEFAULT_TRANSFORMER_RULES, tree_shardings

    cfg = TransformerConfig(
        vocab_size=4096, dim=2048, n_heads=32, n_kv_heads=4, n_layers=22,
        ffn_hidden=5632, lora_rank=8, lora_mlp=True,
    )
    module = CausalLM(cfg, resolve_attention("dense"))
    params = jax.eval_shape(
        module.init, jax.random.PRNGKey(0), jnp.zeros((1, 1024), jnp.int32)
    )["params"]
    opt = jax.eval_shape(optax.adam(1e-3).init, params)
    out = {"n_params": int(sum(np.prod(s.shape) for s in jax.tree.leaves(params)))}
    for m in (1, 4, 8):
        # the same engine that PLACES tensors computes the share: build the
        # one-node (data=1, model=m) slice and ask each NamedSharding for
        # its per-device shard shape — no hand-rolled divisibility copy
        slice_mesh = node_slices(
            submesh_federation_mesh(1, m, devices=jax.devices()[:m])
        )[0]
        total = 0
        for tree in (params, opt):
            shardings = tree_shardings(
                slice_mesh, tree, DEFAULT_TRANSFORMER_RULES, on_unmatched="replicate"
            )

            def bytes_one(sharding, leaf):
                shard = sharding.shard_shape(tuple(leaf.shape))
                size = int(np.prod(shard)) if shard else 1
                return size * np.dtype(leaf.dtype).itemsize

            total += sum(jax.tree.leaves(jax.tree.map(bytes_one, shardings, tree)))
        out[f"bytes_per_device_m{m}"] = int(total)
        out[f"gb_per_device_m{m}"] = round(total / 2**30, 3)
    return out


def config5_sharded() -> None:
    """Config 5's SHARDED-NODE row (ISSUE 10): one federation node = a
    pjit submesh, cross-slice FedAvg fold — vs the single-chip path on
    the same task, same steps/round, same target.

    Two honest parts:

    - an EXECUTED anchor on this container's backend: a small dense LM
      (the nameplate architecture family) federated 2 nodes x
      model_parallel=4 (8 virtual CPU devices) against the single-chip
      SpmdFederation, identical seeds/steps-per-round/target, reporting
      sec/round, rounds-to-target and the measured per-device live bytes
      (the no-full-model-anywhere contract, measured not asserted). On
      the CPU anchor ``mfu`` is null like every CPU row and wall-clock
      favors the single-chip path (GSPMD partitioning overhead without
      real ICI) — the dispatch structure, parity and memory split are
      what transfer;
    - the 1B NAMEPLATE projection: exact per-device params+opt bytes for
      the 0.98B tree under the default partition rules at model_parallel
      1/4/8 (``jax.eval_shape`` + the rule engine — no allocation, no
      chip needed). m=1 is the single-chip row's footprint; m=4/8 is what
      a v4/v5 slice per node buys.
    """
    if jax.default_backend() != "cpu" or len(jax.devices()) < 8:
        _reexec("5sharded", timeout=1500, virtual_devices=8)
        return

    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer
    from p2pfl_tpu.parallel import ShardedNodeFederation, SpmdFederation
    from p2pfl_tpu.parallel.submesh import per_device_bytes

    n = 2
    target = 0.50
    cap = 12
    cfg = TransformerConfig(
        vocab_size=256, dim=128, n_layers=2, n_heads=4, n_kv_heads=2, ffn_hidden=344
    )
    data = FederatedDataset.synthetic_lm(
        vocab_size=256, seq_len=64, n_train=64, n_test=32, seed=7
    )

    sharded = ShardedNodeFederation.from_dataset(
        tiny_transformer(seq_len=64, cfg=cfg), data, n_nodes=n,
        model_parallel=4, batch_size=4, vote=False, seed=3,
    )
    # steady state measured on a fresh object (no reset on the sharded
    # driver yet); rounds-to-target measured from round 0 on a new one
    sharded.run_round(epochs=1)
    sec_sharded = _steady_state(sharded, rounds=3)
    sharded2 = ShardedNodeFederation.from_dataset(
        tiny_transformer(seq_len=64, cfg=cfg), data, n_nodes=n,
        model_parallel=4, batch_size=4, vote=False, seed=3,
    )
    accs_sh, r2t_sh = [], None
    for r in range(cap):
        sharded2.run_round(epochs=1)
        accs_sh.append(round(sharded2.evaluate()["test_acc"], 4))
        if accs_sh[-1] >= target:
            r2t_sh = r + 1
            break
    hbm = per_device_bytes(sharded2.params, sharded2.opt_state)
    max_dev_bytes = max(hbm.values())
    full_bytes = sum(
        int(np.prod(x.shape)) * np.dtype(x.dtype).itemsize
        for x in jax.tree.leaves(sharded2.model.params)
    )
    log(f"config5_sharded: sharded {sec_sharded:.3f} s/round, "
        f"target {target} in {r2t_sh} rounds, max dev bytes {max_dev_bytes}")

    single = SpmdFederation.from_dataset(
        tiny_transformer(seq_len=64, cfg=cfg), data, n_nodes=n,
        batch_size=4, vote=False, seed=3,
    )
    single.run_round(epochs=1)
    force_execution(single.params)
    sec_single = _steady_state(single, rounds=3)
    single.reset(seed=3)
    accs_si, r2t_si = [], None
    for r in range(cap):
        single.run_round(epochs=1)
        accs_si.append(round(single.evaluate()["test_acc"], 4))
        if accs_si[-1] >= target:
            r2t_si = r + 1
            break
    log(f"config5_sharded: single-chip {sec_single:.3f} s/round, "
        f"target {target} in {r2t_si} rounds")

    emit({
        "metric": "config5_sharded",
        "value": round(sec_sharded, 4),
        "unit": "sec_per_round",
        "cpu_anchor": True,
        "model": "2L/128d/4h(kv2) SwiGLU-344 vocab-256 seq-64 (nameplate "
                 "architecture family at CPU-anchor scale)",
        "n_nodes": n,
        "model_parallel": 4,
        "steps_per_round": sharded2._nb,
        "target_acc": target,
        "rounds_to_target": r2t_sh,
        "rounds_to_target_single_chip": r2t_si,
        "next_token_acc_curve": accs_sh,
        "next_token_acc_curve_single_chip": accs_si,
        "sec_per_round_single_chip": round(sec_single, 4),
        "mfu": None,
        "max_device_bytes": int(max_dev_bytes),
        "full_model_bytes": int(full_bytes),
        "device_bytes_fraction": round(max_dev_bytes / (3 * full_bytes), 3),
        "nameplate_1b_projection": _sharded_1b_hbm_projection(),
        "note": "CPU anchor: same seeds/steps-per-round/target as the "
                "single-chip comparison; GSPMD partitioning overhead "
                "without real ICI makes sharded wall-clock LOSE on CPU — "
                "the per-device memory split (max_device_bytes vs 3x "
                "full_model_bytes for params+adam) and the 1B projection "
                "are the accelerator-facing result. The 1B projection "
                "uses the exact config5_nameplate_1b tree (same "
                "steps/round and 0.65 target apply when run on hardware).",
        "data": "synthetic-lm (markov, vocab 256)",
        "devices": len(jax.devices()),
    })


def config6_heterogeneous_algorithms() -> None:
    """Beyond-reference breadth: FedAvg vs FedProx vs SCAFFOLD vs FedAdam on
    Dirichlet(0.3) non-IID shards (the reference ships FedAvg only).

    SCAFFOLD is an SGD-family correction (its control-variate update is
    coupled to the SGD step size, Karimireddy et al. 2020 eq. 4), so its
    honest baseline is FedAvg with the SAME local SGD — the ``fedavg_sgd``
    row. Round 4 compared it against FedAvg-with-Adam and concluded
    SCAFFOLD "loses on the setting it exists for"; the 3-seed matched
    sweep (2026-07-31) shows SCAFFOLD > FedAvg-SGD at every seed at
    lr 0.02 (mean 0.679 vs 0.433 at 1 epoch; 0.976 vs 0.934 at 2), and
    that the correction destabilizes when K·η grows (lr 0.05 × 2 epochs:
    0.922 vs 0.995) — the known large-step regime, not a bug.
    ``tests/test_fedopt_scaffold.py`` pins the matched-pair ordering.
    """
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.models import mlp
    from p2pfl_tpu.parallel import SpmdFederation

    n_nodes, rounds = 8, 10
    results = {}
    times = {}
    data = FederatedDataset.mnist(None, modes=8, noise=0.7, proto_scale=0.5)
    for algo, kwargs in {
        "fedavg": {},
        "fedprox": {"prox_mu": 0.1},
        "fedavg_sgd": {"optimizer": "sgd", "learning_rate": 0.02},
        "scaffold": {"scaffold": True, "optimizer": "sgd", "learning_rate": 0.02},
        "fedadam": {"server_opt": "adam", "server_lr": 0.01},
    }.items():
        fed = SpmdFederation.from_dataset(
            mlp(), data, n_nodes=n_nodes, strategy="dirichlet", alpha=0.3,
            batch_size=64, vote=False, seed=7, **kwargs,
        )
        # warm BOTH fused input layouts (fresh + evolved) and materialize —
        # one unmaterialized warm call leaves a compile inside the timer
        # (the r1 fedavg row measured 2.3 s/round vs 0.13 for its peers
        # because of exactly this)
        [float(e["test_acc"]) for e in fed.run_fused(rounds, epochs=1, eval=True)]
        [float(e["test_acc"]) for e in fed.run_fused(rounds, epochs=1, eval=True)]
        fed.reset(seed=7)
        t0 = time.monotonic()
        entries = fed.run_fused(rounds, epochs=1, eval=True)
        accs = [round(float(e["test_acc"]), 4) for e in entries]
        force_execution(fed.params)
        times[algo] = round((time.monotonic() - t0) / rounds, 4)
        results[algo] = accs
        log(f"config6 {algo}: {accs}")
        del fed
        jax.clear_caches()

    # --- scaffold fast path: before/after + per-phase profile (round 6) ---
    # same federation timed under the legacy anchor-based ci⁺ and the fused
    # grad-mean ci⁺ (Settings.SCAFFOLD_FUSED_CI — a traced-program knob, so
    # each setting gets its own warmed executable), plus the per-phase
    # breakdown that attributes whatever overhead remains
    from p2pfl_tpu.settings import Settings

    sc_kwargs = {"scaffold": True, "optimizer": "sgd", "learning_rate": 0.02}
    scaffold_split = {}
    fed = SpmdFederation.from_dataset(
        mlp(), data, n_nodes=n_nodes, strategy="dirichlet", alpha=0.3,
        batch_size=64, vote=False, seed=7, **sc_kwargs,
    )
    prior_fused_ci = Settings.SCAFFOLD_FUSED_CI
    try:
        for label, fused_ci in (("legacy_ci", False), ("fused_ci", True)):
            Settings.SCAFFOLD_FUSED_CI = fused_ci
            fed.reset(seed=7)
            [float(e["test_acc"]) for e in fed.run_fused(rounds, epochs=1, eval=True)]
            fed.reset(seed=7)
            t0 = time.monotonic()
            fed.run_fused(rounds, epochs=1, eval=True)
            force_execution(fed.params)
            scaffold_split[f"{label}_sec_per_round"] = round((time.monotonic() - t0) / rounds, 4)
    finally:
        # never leave the legacy path enabled for later configs on failure
        Settings.SCAFFOLD_FUSED_CI = prior_fused_ci
    scaffold_split["fast_path_speedup"] = round(
        scaffold_split["legacy_ci_sec_per_round"] / scaffold_split["fused_ci_sec_per_round"], 3
    )
    scaffold_split["vs_matched_fedavg_x"] = round(
        scaffold_split["fused_ci_sec_per_round"] / times["fedavg_sgd"], 3
    )
    scaffold_profile = fed.profile_round(epochs=1)
    log(f"config6 scaffold split {scaffold_split} profile {scaffold_profile}")
    del fed
    jax.clear_caches()

    # --- 5 local epochs: the regime where drift accumulates and SCAFFOLD's
    # correction should WIN on accuracy, not just cost less (with lr scaled
    # down to keep K·η in the stable regime the 3-seed sweep mapped) ---
    ep5 = {}
    for algo in ("fedavg_sgd", "scaffold"):
        kw = {"optimizer": "sgd", "learning_rate": 0.01}
        if algo == "scaffold":
            kw["scaffold"] = True
        fed = SpmdFederation.from_dataset(
            mlp(), data, n_nodes=n_nodes, strategy="dirichlet", alpha=0.3,
            batch_size=64, vote=False, seed=7, **kw,
        )
        [float(e["test_acc"]) for e in fed.run_fused(rounds, epochs=5, eval=True)]
        fed.reset(seed=7)
        t0 = time.monotonic()
        entries = fed.run_fused(rounds, epochs=5, eval=True)
        accs5 = [round(float(e["test_acc"]), 4) for e in entries]
        force_execution(fed.params)
        ep5[algo] = {
            "curve": accs5,
            "sec_per_round": round((time.monotonic() - t0) / rounds, 4),
        }
        log(f"config6 {algo} @5 epochs: {ep5[algo]}")
        del fed
        jax.clear_caches()

    emit({
        "metric": "config6_heterogeneous_dirichlet03",
        "value": max(r[-1] for r in results.values()),
        "unit": "best_final_acc",
        "curves": results,
        "sec_per_round": times,
        "n_nodes": n_nodes,
        "partition": "dirichlet(0.3)",
        "data": "synthetic-hard",
        "scaffold_vs_matched_fedavg": round(
            results["scaffold"][-1] - results["fedavg_sgd"][-1], 4
        ),
        # SCAFFOLD hot-path overhaul: legacy vs fused ci⁺ cost, residual
        # attribution (train / correction / aggregate), and the 5-local-
        # epoch drift regime where the correction earns its keep
        "scaffold_fast_path": scaffold_split,
        "scaffold_profile": scaffold_profile,
        "local_epochs_5": {
            **ep5,
            "scaffold_vs_fedavg_sgd_final": round(
                ep5["scaffold"]["curve"][-1] - ep5["fedavg_sgd"]["curve"][-1], 4
            ),
            "recipe": "lr 0.01 (K·η kept in the stable regime at 5x steps)",
        },
        "scaffold_note": (
            "scaffold's baseline is fedavg_sgd (same local SGD, lr 0.02) — "
            "the control-variate update is coupled to the SGD step; "
            "adam rows are a different local optimizer family"
        ),
        "devices": len(jax.devices()),
    })


def _fused_timer(fn, args, iters=30):
    """Time ``fn`` with the repeat loop fused into ONE device dispatch.

    Per-dispatch measurement through the axon tunnel carries a ~100 ms
    fixed round-trip (measured: a jitted 4096³ matmul "takes" 73 ms
    dispatched per-call but 0.98 ms amortized over a 400-iteration
    in-program scan). ``fn(*args) -> carry_pytree`` must return its own
    inputs' update so iterations chain data-dependently and XLA cannot
    CSE the loop body.

    The fixed cost is removed by a two-point SLOPE, not a guessed
    subtraction (a constant 0.1 s estimate swallowed sub-ms steps whole —
    round-4's first T=512 row read 0.0 ms): the loop bound is a TRACED
    ``lax.fori_loop`` bound, so one executable runs at both ``iters`` and
    ``3·iters`` and the per-iteration time is the difference over 2·iters.
    """
    from jax import lax

    @jax.jit
    def many(a, n):
        def body(_i, c):
            out = fn(*c)
            return out if isinstance(out, tuple) else (out,)

        return lax.fori_loop(0, n, body, a)

    def run(n):
        t0 = time.monotonic()
        out = many(args, n)
        force_execution(out)
        return time.monotonic() - t0

    run(2)  # compile + warm
    # tunnel latency is variable run to run (measured ±20% on the same
    # kernel); the median of repeated slopes is stable where one is not
    slopes = []
    for _ in range(3):
        t_lo = run(iters)
        t_hi = run(3 * iters)
        slopes.append(max(t_hi - t_lo, 1e-9) / (2 * iters))
    slopes.sort()
    return slopes[1]


def config7_long_context_flash() -> None:
    """Long-context single-chip path: Pallas flash attention vs fused dense
    XLA attention across sequence lengths, fwd and train-step (fwd+bwd)
    measured separately (VERDICT r3 #6).

    Two structural facts this row documents:

    - timing is amortized inside one dispatch (``_fused_timer``) — the
      round-3 numbers carried a ~100 ms/dispatch axon-tunnel tax that made
      every step look 10-80 ms slower than the chip was;
    - the 4L/256d/8h model's head_dim = 32 fills only 32 of the MXU's 128
      contraction/output lanes, so NO attention kernel can exceed ~25% MFU
      at this width — the ``head_dim_scaling`` sub-row shows the same
      kernel at D=64/128 (the config-5-scale and production widths), where
      it reaches >35% fwd / >50% bwd.
    """
    import optax

    from p2pfl_tpu.models.transformer import (
        TransformerConfig,
        pick_attention,
        resolve_attention,
        tiny_transformer,
    )
    from p2pfl_tpu.settings import Settings

    cfg_kw = dict(
        vocab_size=1024, dim=256, n_layers=4, n_heads=8, n_kv_heads=8,
        ffn_hidden=688, lora_rank=0,
    )

    def measure(seq_len, attn, block=128, cfg=None):
        # dense → attn_fn None (fused XLA path); flash → explicit kernel
        # with the swept block size (attn_fn overrides tiny_transformer's
        # own block choice)
        from p2pfl_tpu.management.profiling import compiled_flops

        attn_fn = resolve_attention("flash", block=block) if attn == "flash" else None
        m = tiny_transformer(
            seq_len=seq_len, cfg=cfg or TransformerConfig(**cfg_kw), attn_fn=attn_fn
        )
        tokens = jax.random.randint(jax.random.PRNGKey(0), (8, seq_len), 0, 1024)
        targets = jnp.roll(tokens, -1, axis=1)

        def loss(p, m=m, tokens=tokens, targets=targets):
            logits = m.apply(p, tokens)
            return optax.softmax_cross_entropy_with_integer_labels(logits, targets).mean()

        grad_fn = jax.value_and_grad(loss)

        def train_step(p):
            _l, g = grad_fn(p)
            return jax.tree.map(lambda a, b: a - 1e-4 * b.astype(a.dtype), p, g)

        def fwd_step(p):
            # chain iterations through a negligible param nudge so the scan
            # body stays data-dependent (a *0.0 chain gets algebraically
            # folded to identity and the whole loop DCE'd — measured 0.0 ms)
            l = loss(p)
            return jax.tree.map(lambda a: a + (l * 1e-30).astype(a.dtype), p)

        # no scan in the step → cost analysis counts everything exactly once.
        # Pallas kernel FLOPs may be invisible to XLA's analysis, so MFU is
        # comparable only via the DENSE program's count (reported per row).
        train_flops = compiled_flops(jax.jit(grad_fn), m.params)
        fwd_flops = compiled_flops(jax.jit(loss), m.params)
        sec_train = _fused_timer(train_step, (m.params,))
        sec_fwd = _fused_timer(fwd_step, (m.params,))
        del m
        jax.clear_caches()
        return sec_fwd, sec_train, fwd_flops, train_flops

    results = {}
    for seq_len in (512, 1024, 2048, 4096):
        d_fwd, d_train, fwd_flops, train_flops = measure(seq_len, "dense")
        row = {
            "dense_fwd_ms": round(d_fwd * 1e3, 3),
            "dense_train_ms": round(d_train * 1e3, 3),
        }
        for mfu_key, fl, sec in (
            ("dense_fwd_mfu", fwd_flops, d_fwd),
            ("dense_train_mfu", train_flops, d_train),
        ):
            v = _mfu_from(fl, sec)
            if v is not None:
                row[mfu_key] = round(v, 4)
        blocks = [b for b in (256, 512) if seq_len % b == 0] or [seq_len]
        sweep = {}
        for b in blocks:
            f_fwd, f_train, _, _ = measure(seq_len, "flash", block=b)
            sweep[b] = {"fwd_ms": round(f_fwd * 1e3, 3), "train_ms": round(f_train * 1e3, 3)}
        best_block = min(sweep, key=lambda b: sweep[b]["train_ms"])
        row["flash_block_sweep"] = sweep
        row["flash_fwd_ms"] = sweep[best_block]["fwd_ms"]
        row["flash_train_ms"] = sweep[best_block]["train_ms"]
        row["flash_best_block"] = best_block
        # flash MFU from the DENSE program's model-FLOP count (the Pallas
        # kernel's internal FLOPs are invisible to XLA's cost analysis;
        # using the same numerator keeps dense/flash comparable)
        for mfu_key, fl, ms in (
            ("flash_fwd_mfu", fwd_flops, row["flash_fwd_ms"]),
            ("flash_train_mfu", train_flops, row["flash_train_ms"]),
        ):
            v = _mfu_from(fl, ms / 1e3)
            if v is not None:
                row[mfu_key] = round(v, 4)
        row["speedup_train"] = round(d_train / (row["flash_train_ms"] / 1e3), 2)
        row["auto_picks"] = pick_attention(seq_len)
        results[f"T{seq_len}"] = row
        log(f"config7 T={seq_len}: {row}")

    # head-dim scaling of the BARE kernel at T=4096 (same total flops per
    # row: H·D = 256): shows the D=32 rows above sit on the MXU-width
    # roofline (32/128 lanes ⇒ ≤25% ceiling), not a kernel defect
    from p2pfl_tpu.ops.flash_attention import flash_attention

    head_dim_scaling = {}
    T = 4096
    for h, d in ((8, 32), (4, 64), (2, 128)):
        q, k, v = (
            jax.random.normal(jax.random.PRNGKey(i), (8, T, h, d), jnp.bfloat16)
            for i in range(3)
        )
        fwd = partial(flash_attention, causal=True, block_q=512, block_k=512)
        fl_fwd = 0.5 * 2 * 2 * 8 * h * T * T * d  # causal: 2 matmuls over T²/2
        fl_bwd = 2.5 * fl_fwd  # 5 block matmuls in the bwd kernels vs 2

        def fwd_chain(q, k, v):
            o = fwd(q, k, v)
            return q + (jnp.sum(o.astype(jnp.float32)) * 1e-30).astype(q.dtype), k, v

        def train_chain(q, k, v):
            # all three grads must feed the carry or XLA dead-code-eliminates
            # the dkv backward kernel entirely
            dq, dk, dv = jax.grad(
                lambda q_, k_, v_: jnp.sum(fwd(q_, k_, v_).astype(jnp.float32)),
                argnums=(0, 1, 2),
            )(q, k, v)
            return (
                q + (dq * 1e-9).astype(q.dtype),
                k + (dk * 1e-9).astype(k.dtype),
                v + (dv * 1e-9).astype(v.dtype),
            )

        s_fwd = _fused_timer(lambda q, k, v: fwd_chain(q, k, v), (q, k, v), iters=100)
        s_all = _fused_timer(lambda q, k, v: train_chain(q, k, v), (q, k, v), iters=100)
        s_bwd = max(s_all - s_fwd, 1e-9)
        head_dim_scaling[f"D{d}"] = {
            "fwd_ms": round(s_fwd * 1e3, 3),
            "fwd_mfu": round(_mfu_from(fl_fwd, s_fwd) or 0, 4),
            "bwd_ms": round(s_bwd * 1e3, 3),
            "bwd_mfu": round(_mfu_from(fl_bwd, s_bwd) or 0, 4),
        }
    log(f"config7 head_dim_scaling: {head_dim_scaling}")

    # model-level proof of the head-width ceiling: the SAME 4L/256d model
    # with 2 heads (D=128) instead of 8 (D=32) — identical params and
    # matmul FLOPs (2·128 = 8·32 per projection), only the attention head
    # shape changes. Measured (round 5, fused bwd): train step 66.0 ->
    # 17.5 ms, model MFU 20.6% -> ~68% at T=4096. The D=32 row's sub-25%
    # train MFU is the 32/128-lane geometry, not the kernel or the model
    # family. The numerator must come from the variant's OWN dense twin —
    # the 8-head dense count is ~14% higher because XLA's softmax/mask
    # bookkeeping scales with head count (verified: reusing it reads 77%).
    from p2pfl_tpu.management.profiling import compiled_flops

    cfgv = TransformerConfig(**{**cfg_kw, "n_heads": 2, "n_kv_heads": 2})
    _fv, secv, _flf, _flt = measure(4096, "flash", block=512, cfg=cfgv)
    mdv = tiny_transformer(seq_len=4096, cfg=cfgv)
    tokens_v = jax.random.randint(jax.random.PRNGKey(0), (8, 4096), 0, 1024)

    def loss_vd(p):
        logits = mdv.apply(p, tokens_v)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, jnp.roll(tokens_v, -1, axis=1)
        ).mean()

    flv = compiled_flops(jax.jit(jax.value_and_grad(loss_vd)), mdv.params)
    variant = {
        "model": "same 4L/256d, 2 heads (D=128)",
        "train_ms": round(secv * 1e3, 1),
        "train_mfu": round(_mfu_from(flv, secv) or 0, 4),
    }
    log(f"config7 head_width_variant: {variant}")
    del mdv
    jax.clear_caches()

    emit({
        "metric": "config7_long_context_flash_vs_dense",
        "value": results["T4096"]["speedup_train"],
        "unit": "x_speedup_at_4096",
        "ms_per_train_step": results,
        "head_dim_scaling_T4096": head_dim_scaling,
        "head_width_variant_T4096": variant,
        "mxu_note": (
            "head_dim 32 fills 32/128 MXU lanes -> <=25% MFU ceiling for any "
            "attention kernel at this width; D=64/128 rows show the kernel "
            "scaling when the shape fills the array, and the head_width "
            f"variant shows the MODEL clearing 25% "
            f"({variant['train_mfu']:.0%} measured) once the heads do"
        ),
        "auto_threshold_seq_len": Settings.FLASH_MIN_SEQ_LEN,
        "batch": 8,
        "model": "4L/256d/8h transformer, bf16",
        "devices": len(jax.devices()),
    })


def config8_wire_compression() -> None:
    """(beyond reference) Gossip egress under the three wire codecs.

    The same 4-node federation over real gRPC sockets, 2 rounds × 1 epoch,
    under WIRE_COMPRESSION none / int8 / topk8 — reporting actual bytes
    that crossed the weight plane (GrpcProtocol.wire_stats) and the final
    accuracy, so the compression claims rest on measured egress, not
    per-payload arithmetic. The reference ships raw pickled float32 only.
    """
    from p2pfl_tpu.communication.grpc_transport import GrpcProtocol
    from p2pfl_tpu.communication.memory import MemoryRegistry
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.learning.learner import JaxLearner
    from p2pfl_tpu.models import mlp
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.settings import Settings, set_test_settings
    from p2pfl_tpu.utils import full_connection, wait_convergence, wait_to_finish

    set_test_settings()
    results = {}
    for mode in ("none", "int8", "topk8"):
        MemoryRegistry.reset()
        Settings.WIRE_COMPRESSION = mode
        full = FederatedDataset.synthetic_mnist(n_train=2048, n_test=512)
        nodes = []
        for i in range(4):
            learner = JaxLearner(mlp(seed=i), full.partition(i, 4), batch_size=64)
            n = Node(learner=learner, protocol=GrpcProtocol("127.0.0.1:0"))
            n.start()
            nodes.append(n)
        for n in nodes:
            full_connection(n, nodes)
        wait_convergence(nodes, 3, only_direct=True)
        nodes[0].set_start_learning(rounds=2, epochs=1)
        wait_to_finish(nodes, timeout=180)
        acc = min(float(n.learner.evaluate()["test_acc"]) for n in nodes)
        wb = sum(n.protocol.wire_stats["weights_bytes"] for n in nodes)
        wm = sum(n.protocol.wire_stats["weights_msgs"] for n in nodes)
        for n in nodes:
            n.stop()
        results[mode] = {
            "weights_MB": round(wb / 1e6, 3),
            "weights_msgs": wm,
            "min_final_acc": round(acc, 4),
        }
        log(f"config8 {mode}: {results[mode]}")
    Settings.WIRE_COMPRESSION = "none"
    emit({
        "metric": "config8_wire_compression_egress",
        "value": round(results["none"]["weights_MB"] / max(results["topk8"]["weights_MB"], 1e-9), 2),
        "unit": "x_egress_shrink_topk8_vs_float32",
        "modes": results,
        "n_nodes": 4,
        "rounds": 2,
        "transport": "grpc loopback",
        "data": "synthetic",
    })


def _moe_step_at_scale() -> dict:
    """Grad-step hardware-MFU of the MoE transformer at MXU-filling dims
    (the federation row's 4L/128d model is dispatch-bound, like config 5's
    toy row). Dense-dispatch/combine einsums execute every [E, C] expert
    slot, so XLA's FLOP count is the executed work — the standard TPU MoE
    cost model (GShard/Switch)."""
    import optax

    from p2pfl_tpu.management.profiling import compiled_flops
    from p2pfl_tpu.models.base import apply_with_aux
    from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer

    dim, ffn, e, layers, t, b = 512, 1408, 8, 6, 512, 16
    cfg = TransformerConfig(
        vocab_size=4096, dim=dim, n_layers=layers, n_heads=dim // 64,
        n_kv_heads=max(2, dim // 256), ffn_hidden=ffn, lora_rank=0,
        n_experts=e, moe_top_k=2,
    )
    m = tiny_transformer(seq_len=t, cfg=cfg)
    n_params = sum(x.size for x in jax.tree.leaves(m.params))
    tokens = jax.random.randint(jax.random.PRNGKey(0), (b, t), 0, 4096)
    targets = jnp.roll(tokens, -1, axis=1)

    def loss(p, bx, by):
        logits, aux = apply_with_aux(m.module, p, bx)
        return optax.softmax_cross_entropy_with_integer_labels(logits, by).mean() + aux

    flops = compiled_flops(jax.jit(jax.value_and_grad(loss)), m.params, tokens, targets)

    def train_step(p, bx, by):
        _l, g = jax.value_and_grad(loss)(p, bx, by)
        return jax.tree.map(lambda a, gr: a - 1e-4 * gr.astype(a.dtype), p, g), bx, by

    sec = _fused_timer(train_step, (m.params, tokens, targets), iters=20)
    return {
        "model": f"{layers}L/{dim}d MoE, {e} experts top-2, ffn {ffn}, seq {t}, batch {b}",
        "n_params": n_params,
        "step_ms": round(sec * 1e3, 1),
        "flops_per_step": flops,
        "mfu_hw": round(_mfu_from(flops, sec) or 0, 4),
        "note": "executed flops incl. all dense-dispatch expert slots",
    }


def config10_moe_gpipe_federation() -> None:
    """(beyond reference) Federations training THROUGH MoE and GPipe.

    VERDICT r2 weak #3: the ep/pp axes compiled but no federation trained
    through them. Two rows:

    - MoE: 8 nodes federate a switch-style MoE transformer (8 experts,
      top-2, aux balance losses riding the federated loss) via
      ``SpmdLmFederation`` — accuracy trajectory to a stated target plus
      steady-state sec/round. Expert parallelism is mesh-width-bound: on
      the single bench chip the ``model`` axis is 1 (the 2-way-ep layout
      is proven on the 8-device virtual mesh in tests + dryrun).
    - GPipe: pipeline stages need >1 device, so the pipelined federation
      re-execs onto the virtual 8-device CPU mesh (4 stages × 2 nodes
      time-sharing them) — provenance recorded; real-chip pp numbers need
      real multi-chip hardware.
    """
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer
    from p2pfl_tpu.parallel import SpmdLmFederation

    n = 8
    cfg = TransformerConfig(
        vocab_size=512, dim=128, n_layers=4, n_heads=8, n_kv_heads=8,
        ffn_hidden=256, lora_rank=0, n_experts=8, moe_top_k=2,
    )
    model = tiny_transformer(seq_len=128, cfg=cfg)
    data = FederatedDataset.synthetic_lm(vocab_size=512, n_train=n * 256, n_test=512)
    fed = SpmdLmFederation.from_dataset(
        model, data, n_nodes=n, batch_size=16, vote=False, seed=3
    )
    target = 0.60
    curve = []
    rounds_to_target = None
    t0 = time.monotonic()
    for r in range(12):
        fed.run_round(epochs=1)
        acc = fed.evaluate()["test_acc"]
        curve.append(round(float(acc), 4))
        log(f"config10 moe round {r + 1}: acc {acc:.4f}")
        if rounds_to_target is None and acc >= target:
            rounds_to_target = r + 1
            time_to_target = time.monotonic() - t0
            break
    # one un-timed settling round: the transition out of the eval-interleaved
    # curve loop costs a ~1.4 s round (measured) that is not steady state
    fed.run_round(epochs=1)
    force_execution(fed.params)
    sec_per_round = _steady_state(fed, rounds=3)
    flops, round_mfu = _spmd_mfu(fed, sec_per_round)
    # the 4L/128d federation model is dispatch/toy-scale-bound (like the
    # config-5 toy row); the AT-SCALE step probe shows what the MoE layer's
    # dense-dispatch formulation sustains when the shapes fill the MXU.
    # NOTE the numerator is XLA-counted EXECUTED flops: dense dispatch
    # computes every [E, C] expert slot (only top-k combine per token) —
    # the standard TPU MoE cost model, reported as hardware utilization.
    moe_scale = _moe_step_at_scale()
    log(f"config10 moe_step_at_scale: {moe_scale}")

    # NOT fused: measured on the chip, run_fused SLOWS this federation
    # (0.78 -> 3.4 s/round) — full-param MoE rounds are compute-bound, so
    # the fused scan's carry costs more than the one dispatch it saves
    # (see SpmdLmFederation.run_fused's docstring; fusing pays only for
    # dispatch-dominated tiny-state rounds like config 5's adapters)
    emit({
        "metric": "config10_moe_federation",
        "moe_step_at_scale": moe_scale,
        "value": round(sec_per_round, 4),
        "unit": "sec_per_round",
        "flops_per_round": flops,
        "mfu": round(round_mfu, 4) if round_mfu is not None else None,
        "n_nodes": n,
        "model": "4L/128d MoE transformer, 8 experts top-2, seq 128",
        "acc_curve": curve,
        "target_acc": target,
        "rounds_to_target": rounds_to_target,
        "time_to_target_s": round(time_to_target, 2) if rounds_to_target else None,
        "expert_parallel": int(fed.mesh.shape.get("model", 1)),
        "data": "synthetic_lm",
        "devices": len(jax.devices()),
    })

    # GPipe federation: re-exec on a virtual multi-device mesh when the
    # current backend cannot host >1 pipeline stage
    if len(jax.devices()) >= 4:
        _config10_gpipe_body()
    else:
        # pipeline stages need >1 device: virtual 8-device CPU mesh
        _reexec("10pipe", timeout=1500, virtual_devices=8)


def _config10_gpipe_body() -> None:
    """GPipe federation, profiled and tuned (VERDICT r3 #5).

    Round 3 reported 59.6 s/round with no breakdown. The profile (emitted
    per row) shows where it goes on this 1-core CPU-mesh simulation:

    - per-node pipelined epochs are ~all of it; host FedAvg is ~ms;
    - the pipelined step costs ≈ (M+P−1)/M × the monolithic step (every
      virtual device executes every schedule slot SERIALLY on one core —
      on real chips the P stages run in parallel, so chip time/round ≈
      serialized/P plus bubbles);
    - bf16 is software-emulated on CPU (measured 1.76× on the monolithic
      step), so this CPU row runs f32 — the dtype is a backend artifact,
      not part of the config (real-chip pp stays bf16).

    Tuning applied (round-5 ablation, VERDICT r4 #6): batch 32 with
    n_micro = 16 (mb 2) — bubble fraction (P−1)/(M+P−1) = 3/19 = 16%, and
    the measured pipe tax drops to ~1.39× (from 1.78× at b16/m8 in round
    4, of which ~0.18× was the per-node profiling sync since made opt-in).
    The ablation (ppermute→identity, no-output-collect, and a plain-scan
    "floor" running the full (M+P−1)·P schedule slots without shard_map)
    attributes the non-bubble overhead: boundary transfers ≈ 0, output
    collect ≈ 0.04×, residual ≈ scan/shard_map machinery — the serialized
    bubble/garbage floor itself measures at the GPipe bound, so the
    real-chip projection (pipe_step/P + bubbles) stands.
    """
    import optax

    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer
    from p2pfl_tpu.parallel import PipelineFederation
    from p2pfl_tpu.parallel.pipeline import pipelined_lm_apply

    dtype = jnp.float32 if jax.default_backend() == "cpu" else jnp.bfloat16
    cfg = TransformerConfig(
        vocab_size=512, dim=128, n_layers=4, n_heads=8, n_kv_heads=8,
        ffn_hidden=344, lora_rank=0, dtype=dtype,
    )
    model = tiny_transformer(seq_len=128, cfg=cfg)
    data = FederatedDataset.synthetic_lm(vocab_size=512, n_train=2 * 512, n_test=256)
    shards = [data.partition(i, 2) for i in range(2)]
    n_micro = 16
    fed = PipelineFederation(
        model, shards, n_stages=4, batch_size=32, n_micro=n_micro, seed=3
    )
    target = 0.60
    curve = []
    rounds_to_target = None
    time_to_target = None
    t0 = time.monotonic()
    for r in range(10):
        fed.run_round(epochs=1, profile=True)
        acc = fed.evaluate()["test_acc"]
        curve.append(round(float(acc), 4))
        log(f"config10 gpipe round {r + 1}: acc {acc:.4f} profile {fed.last_profile}")
        if rounds_to_target is None and acc >= target:
            rounds_to_target = r + 1
            time_to_target = time.monotonic() - t0
        if rounds_to_target is not None and r + 1 >= 5:
            break  # >=5-round curve even when the target falls early
    profile = fed.last_profile  # breakdown from the profiled curve loop above
    # steady-state timing runs UNPROFILED: per-node block_until_ready would
    # serialize dispatch and inflate the headline sec/round
    t0 = time.monotonic()
    for _ in range(2):
        fed.run_round(epochs=1)
    force_execution(fed.params)
    sec_per_round = (time.monotonic() - t0) / 2

    # pipeline tax reference points: the SAME model/batch as one monolithic
    # (unpipelined) train step vs one pipelined step on this backend
    tokens = jnp.asarray(shards[0].x_train[:32])
    targets = jnp.asarray(shards[0].y_train[:32])
    mesh = fed.mesh

    def mono_loss(p):
        logits = model.module.apply({"params": p}, tokens)
        return optax.softmax_cross_entropy_with_integer_labels(logits, targets).mean()

    def pipe_loss(p):
        logits, aux = pipelined_lm_apply(
            p, tokens, cfg, mesh, fed.axis, n_micro=n_micro, return_aux=True
        )
        return optax.softmax_cross_entropy_with_integer_labels(logits, targets).mean() + aux

    def t_step(fn):
        g = jax.jit(jax.value_and_grad(fn))
        out = g(model.params)
        jax.block_until_ready(out)
        t0 = time.monotonic()
        for _ in range(3):
            out = g(model.params)
        jax.block_until_ready(out)
        return (time.monotonic() - t0) / 3

    mono_ms = round(t_step(mono_loss) * 1e3, 1)
    pipe_ms = round(t_step(pipe_loss) * 1e3, 1)
    n_stages = 4
    emit({
        "metric": "config10_gpipe_federation",
        "value": round(sec_per_round, 4),
        "unit": "sec_per_round",
        "n_nodes": 2,
        "pipeline_stages": n_stages,
        "n_micro": n_micro,
        "model": f"4L/128d transformer, GPipe 4-stage, seq 128, "
                 f"{'f32 (bf16 is CPU-emulated, 1.76x)' if dtype == jnp.float32 else 'bf16'}",
        "acc_curve": curve,
        "target_acc": target,
        "rounds_to_target": rounds_to_target,
        "time_to_target_s": round(time_to_target, 2) if rounds_to_target else None,
        "breakdown": {
            "per_node_epoch_s": profile["node_epoch_s"],
            "host_fedavg_s": profile["fedavg_s"],
            "mono_step_ms": mono_ms,
            "pipe_step_ms": pipe_ms,
            "pipe_tax_measured": round(pipe_ms / mono_ms, 2),
            "bubble_fraction": round((n_stages - 1) / (n_micro + n_stages - 1), 3),
            "note": "1-core CPU mesh serializes the P stages; real-chip "
                    "projection ~ pipe_step/P + bubbles",
        },
        "data": "synthetic_lm",
        "devices": len(jax.devices()),
        "backend": jax.default_backend(),
    })


def config9_personalization() -> None:
    """(beyond reference) FedPer vs plain FedAvg under CONCEPT SHIFT.

    4 nodes share the input distribution but each maps features to its OWN
    label semantics (a node-specific label permutation — think region-
    specific class taxonomies). One global head cannot fit contradictory
    conditionals; FedPer federates the feature body and keeps each node's
    head local. Metric: mean per-node accuracy on the node's OWN test
    shard. (Under plain label-FREQUENCY skew the global model wins — we
    measured that too; personalization is for shifted conditionals, and
    this row shows exactly that regime.)
    """
    from p2pfl_tpu.communication.memory import MemoryRegistry
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.learning.learner import JaxLearner
    from p2pfl_tpu.learning.personalization import PersonalizedLearner
    from p2pfl_tpu.models import mlp
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.settings import Settings, set_test_settings
    from p2pfl_tpu.utils import full_connection, wait_convergence, wait_to_finish

    set_test_settings()
    Settings.TRAIN_SET_SIZE = 4
    results = {}
    for label in ("fedavg_global", "fedper_personal"):
        MemoryRegistry.reset()
        full = FederatedDataset.synthetic_mnist(
            n_train=4096, n_test=1024, modes=4, noise=0.6, proto_scale=0.6
        )
        nodes = []
        for i in range(4):
            shard = full.partition(i, 4)
            # concept shift: node i relabels classes by its own permutation
            perm = np.random.default_rng(100 + i).permutation(shard.num_classes)
            shard.y_train = perm[shard.y_train]
            shard.y_test = perm[shard.y_test]
            if label == "fedper_personal":
                learner = PersonalizedLearner(
                    mlp(seed=i), shard, batch_size=64, personal=("Dense_2",)
                )
            else:
                learner = JaxLearner(mlp(seed=i), shard, batch_size=64)
            n = Node(learner=learner)
            n.start()
            nodes.append(n)
        for n in nodes:
            full_connection(n, nodes)
        wait_convergence(nodes, 3, only_direct=True)
        t0 = time.monotonic()
        nodes[0].set_start_learning(rounds=5, epochs=2)
        wait_to_finish(nodes, timeout=300)
        elapsed = time.monotonic() - t0
        accs = [float(n.learner.evaluate()["test_acc"]) for n in nodes]
        for n in nodes:
            n.stop()
        results[label] = {
            "mean_local_acc": round(float(np.mean(accs)), 4),
            "per_node": [round(a, 4) for a in accs],
            "wall_s": round(elapsed, 1),
        }
        log(f"config9 {label}: {results[label]}")
    emit({
        "metric": "config9_fedper_vs_global_concept_shift",
        "value": results["fedper_personal"]["mean_local_acc"],
        "unit": "mean_local_acc",
        "fedper_personal": results["fedper_personal"],
        "fedavg_global": results["fedavg_global"],
        "n_nodes": 4,
        "rounds": 5,
        "setting": "concept shift (node-specific label permutations)",
        "data": "synthetic",
    })


def config10_moe_scale() -> None:
    """MoE federation AT SCALE (VERDICT r4 #2): the 6L/512d/8-expert 110M
    model — previously only a bare grad-step probe (``_moe_step_at_scale``,
    64% hw-MFU) — run as an actual multi-round federation: N nodes,
    accuracy curve to target, steady-state sec/round, MFU. The exact
    treatment the dense 104M model got in config5_scale_lm_104m.

    Sizing: node-stacked f32 params + Adam moments are 12 B/param·node →
    4 nodes × 113M ≈ 5.4 GB; with the GShard dense-dispatch [S, E, C]
    tensors per layer the total-token budget matches the probe's
    (4 nodes × batch 4 × seq 512 = 8192 tokens in flight).

    MFU numerator is XLA-counted EXECUTED flops (dense dispatch computes
    every expert slot — the standard TPU MoE cost model, same accounting
    as the probe row), so this is hardware utilization.
    """
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer
    from p2pfl_tpu.parallel import SpmdLmFederation

    n = 4
    dim, ffn, e, layers, t = 512, 1408, 8, 6, 512
    cfg = TransformerConfig(
        vocab_size=4096, dim=dim, n_layers=layers, n_heads=dim // 64,
        n_kv_heads=max(2, dim // 256), ffn_hidden=ffn, lora_rank=0,
        n_experts=e, moe_top_k=2,
    )
    model = tiny_transformer(seq_len=t, cfg=cfg)
    n_params = sum(x.size for x in jax.tree.leaves(model.params))
    log(f"config10_moe_scale: {n_params/1e6:.1f}M params")
    data = FederatedDataset.synthetic_lm(
        vocab_size=4096, seq_len=t, n_train=n * 64, n_test=32
    )
    fed = SpmdLmFederation.from_dataset(
        model, data, n_nodes=n, batch_size=4, vote=False, seed=3
    )
    # the vocab-4096 chain needs ~400 optimizer steps to lock in (the dense
    # 104M base took a 300-step central pretrain); at nb=16 steps/round a
    # 3-epoch local pass gives 48 steps/round — rounds_to_target measures
    # the FEDERATED path doing that work, no central pretrain here
    target = 0.60
    epochs_per_round = 3
    curve = []
    rounds_to_target = None
    time_to_target = None
    t0 = time.monotonic()
    for r in range(15):
        fed.run_round(epochs=epochs_per_round)
        acc = fed.evaluate()["test_acc"]
        curve.append(round(float(acc), 4))
        log(f"config10_moe_scale round {r + 1}: acc {acc:.4f}")
        if rounds_to_target is None and acc >= target:
            rounds_to_target = r + 1
            time_to_target = time.monotonic() - t0
            break
    # settling round: the eval-to-steady transition is not steady state
    fed.run_round(epochs=1)
    force_execution(fed.params)
    sec_per_round = _steady_state(fed, rounds=3)
    flops, round_mfu = _spmd_mfu(fed, sec_per_round)
    emit({
        "metric": "config10_moe_scale",
        "value": round(sec_per_round, 4),
        "unit": "sec_per_round",
        "model": f"{layers}L/{dim}d MoE, {e} experts top-2, ffn {ffn}, "
                 f"seq {t}, vocab 4096",
        "n_params": n_params,
        "n_nodes": n,
        "batch_per_node": 4,
        "steps_per_round": fed._nb,
        "epochs_per_round": epochs_per_round,
        "flops_per_round": flops,
        "mfu_hw": round(round_mfu, 4) if round_mfu is not None else None,
        "mfu_note": "XLA-counted executed flops: dense dispatch computes "
                    "every [E, C] expert slot (GShard/Switch cost model); "
                    "sec_per_round and mfu are the 1-epoch steady state",
        "acc_curve": curve,
        "target_acc": target,
        "rounds_to_target": rounds_to_target,
        "time_to_target_s": round(time_to_target, 2) if time_to_target else None,
        "data": "synthetic_lm (markov, vocab 4096)",
        "devices": len(jax.devices()),
    })


def config_async_federation() -> None:
    """ISSUE 9 row: sync round FSM vs async FedBuff vs hierarchical on the
    mnist fleet under the seeded straggler/crash plan (full measurement +
    JSON artifact live in ``bench_async.py`` / BENCH_ASYNC.json; this row
    is the suite-resident summary and CI guard)."""
    if jax.default_backend() != "cpu":
        _reexec("async", timeout=900)
        return
    import bench_async

    rows = [bench_async.run_threaded(m, rounds=2) for m in ("sync", "async", "hier")]
    sync_wall = next(r["wall_s"] for r in rows if r["mode"] == "sync")
    emit(
        {
            "metric": "async_federation_time_to_target",
            "provenance": "synthetic mnist, 10 nodes, seeded 1-slow/1-crash plan "
            "(bench_async.py; BENCH_ASYNC.json has the full row + 1k-node sim)",
            "target_acc": bench_async.TARGET_ACC,
            "rows": {
                r["mode"]: {
                    "wall_s": r["wall_s"],
                    "reached_target": r["reached_target"],
                    "speedup_vs_sync": round(sync_wall / r["wall_s"], 2),
                }
                for r in rows
            },
        }
    )


CONFIGS = {
    "1": config1_mnist_2node,
    "async": config_async_federation,
    "2": config2_resnet18_8node,
    "3": config3_resnet50_64node_dirichlet,
    "4": config4_byzantine_robust,
    "5": config5_lora_32node,
    "5scale": config5_scale_lm,
    "5b": config5_nameplate_1b,
    "5sharded": config5_sharded,
    "6": config6_heterogeneous_algorithms,
    "7": config7_long_context_flash,
    "8": config8_wire_compression,
    "9": config9_personalization,
    "10": config10_moe_gpipe_federation,
    "10moe": config10_moe_scale,
    "10pipe": _config10_gpipe_body,  # internal: config10's multi-device re-exec
}


def main() -> None:
    wanted = sys.argv[1:] or [k for k in sorted(CONFIGS, key=lambda s: (len(s), s)) if not k.endswith("pipe")]
    if len(wanted) == 1:
        CONFIGS[wanted[0]]()
        return
    # one subprocess per config: an OOM (or any backend poisoning) in one
    # config must not contaminate the next measurement
    import subprocess

    for key in wanted:
        log(f"=== config {key} ===")
        t0 = time.monotonic()
        proc = subprocess.run(
            [sys.executable, __file__, key], capture_output=True, text=True, timeout=1800
        )
        sys.stderr.write(proc.stderr[-2000:])
        if proc.returncode == 0 and proc.stdout.strip():
            sys.stdout.write(proc.stdout)
            sys.stdout.flush()
        else:
            emit({"metric": f"config{key}", "error": f"rc={proc.returncode}: {proc.stderr[-300:]}"})
        log(f"=== config {key} done in {time.monotonic() - t0:.1f}s ===")


if __name__ == "__main__":
    main()
