"""BASELINE.md configs 2-5, measured (config 1 anchor included).

Each config prints ONE JSON line (5 lines total). The headline driver
metric stays in ``bench.py``; this suite fills in the BASELINE table:

1. MNIST MLP, 2 nodes, FedAvg, in-memory Node mode (reference CI anchor)
2. CIFAR-10-shaped ResNet-18, 8 nodes, FedAvg, SPMD (+ MFU)
3. CIFAR-100-shaped ResNet-50, 64 nodes, Dirichlet(0.5) non-IID, SPMD
4. Krum + TrimmedMean with 20% Byzantine nodes, CIFAR-10 ResNet-18
5. LoRA transformer federation, 32 nodes, FedAvg on LoRA deltas

Data is the synthetic stand-in everywhere (no download egress); provenance
is recorded per line. All accuracy numbers are real multi-round
convergence trajectories, not single-dispatch saturation.

Usage: ``python bench_suite.py [config ...]`` (default: all).
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from p2pfl_tpu.management.profiling import force_execution


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def emit(payload: dict) -> None:
    print(json.dumps(payload), flush=True)


def _steady_state(fed, rounds: int = 3) -> float:
    t0 = time.monotonic()
    for _ in range(rounds):
        fed.run_round(epochs=1)
    force_execution(fed.params)
    return (time.monotonic() - t0) / rounds


def _spmd_mfu(fed, sec_per_round: float):
    from p2pfl_tpu.management.profiling import mfu

    flops = fed.round_flops()
    n_dev = len(set(fed.mesh.devices.flat))
    return flops, mfu(flops, sec_per_round, n_devices=n_dev)


def _mfu_from(flops, seconds: float):
    from p2pfl_tpu.management.profiling import mfu

    return mfu(flops, seconds)


def _reexec(config_key: str, timeout: int = 900, cpu: bool = True, virtual_devices: int = 0):
    """Run one config in a child process and forward its JSON.

    Single place for the child-env hygiene that previously diverged across
    copies: ``cpu=True`` forces the CPU backend AND scrubs
    PALLAS_AXON_POOL_IPS (the image's sitecustomize otherwise claims the
    real chip in every python child — if the parent already holds it the
    child aborts with a C++ exception); ``virtual_devices`` adds the
    host-platform device-count flag for virtual-mesh children.
    """
    import os
    import subprocess

    env = dict(os.environ)
    if cpu:
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
    if virtual_devices:
        flags = [
            f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        env["XLA_FLAGS"] = " ".join(
            flags + [f"--xla_force_host_platform_device_count={virtual_devices}"]
        )
    proc = subprocess.run(
        [sys.executable, __file__, config_key], env=env,
        capture_output=True, text=True, timeout=timeout,
    )
    sys.stderr.write(proc.stderr[-2000:])
    if proc.returncode == 0 and proc.stdout.strip():
        sys.stdout.write(proc.stdout)
        sys.stdout.flush()
    else:
        emit({
            "metric": f"config{config_key}",
            "error": f"re-exec rc={proc.returncode}: {proc.stderr[-300:]}",
        })


def config1_mnist_2node() -> None:
    """Reference CI anchor: 2 Node objects, in-memory transport, 1 epoch.

    This row is the CPU reference (BASELINE table: "in-memory comm (CPU
    ref)", mirroring the reference's own CI test which runs on CPU) — it
    measures the protocol stack, not an accelerator. Round-2 ran it
    through the axon-tunneled TPU backend, where every one of the ~10
    device dispatches per round pays a tunnel round trip: the 6.6 s/round
    (5.7–17.7 s variance) it reported was tunnel latency, not protocol
    waits. The round-3 profiling breakdown (emitted below) shows the
    stack is COMPUTE-dominated on CPU: fit + evaluate account for most of
    the wall clock and gossip/aggregation waits are sub-second with the
    documented low-latency profile (``set_low_latency_settings``).
    """
    if jax.default_backend() != "cpu":
        # re-exec on the CPU backend this row is defined on; the parent
        # (possibly holding the TPU) just forwards the child's JSON
        _reexec("1", timeout=600)
        return

    import collections
    import functools

    from p2pfl_tpu.communication.gossiper import Gossiper
    from p2pfl_tpu.learning.aggregators.aggregator import Aggregator
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.learning.learner import JaxLearner
    from p2pfl_tpu.models import mlp
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.settings import set_low_latency_settings
    from p2pfl_tpu.utils import wait_to_finish

    # per-primitive wall-clock accounting (summed across both node threads)
    acc: collections.Counter = collections.Counter()

    def timed(name, fn):
        @functools.wraps(fn)
        def wrapper(*a, **k):
            t0 = time.monotonic()
            try:
                return fn(*a, **k)
            finally:
                acc[name] += time.monotonic() - t0

        return wrapper

    Gossiper.gossip_weights = timed("gossip_s", Gossiper.gossip_weights)
    Aggregator.wait_and_get_aggregation = timed("agg_wait_s", Aggregator.wait_and_get_aggregation)
    JaxLearner.fit = timed("fit_s", JaxLearner.fit)
    JaxLearner.evaluate = timed("eval_s", JaxLearner.evaluate)

    set_low_latency_settings()
    full = FederatedDataset.synthetic_mnist(n_train=4096, n_test=1024)
    nodes = []
    for i in range(2):
        learner = JaxLearner(mlp(seed=i), full.partition(i, 2), batch_size=64)
        n = Node(learner=learner)
        n.start()
        nodes.append(n)
    nodes[0].connect(nodes[1].addr)
    time.sleep(0.5)
    rounds = 3
    t0 = time.monotonic()
    nodes[0].set_start_learning(rounds=rounds, epochs=1)
    wait_to_finish(nodes, timeout=120)
    elapsed = time.monotonic() - t0
    breakdown = {k: round(v, 2) for k, v in sorted(acc.items())}  # pre final-eval
    final_acc = nodes[0].learner.evaluate()["test_acc"]
    for n in nodes:
        n.stop()
    emit({
        "metric": "config1_mnist_mlp_2node_memory",
        "value": round(elapsed / rounds, 4),
        "unit": "sec_per_round",
        "rounds": rounds,
        "final_acc": round(float(final_acc), 4),
        "data": "synthetic",
        "transport": "memory (full Node stack: gossip+vote+heartbeat)",
        "backend": "cpu (this row is the CPU reference anchor)",
        "settings_profile": "low_latency",
        # thread-summed primitive totals over the whole run (2 node
        # threads run concurrently, so these can exceed wall clock)
        "breakdown_thread_totals_s": breakdown,
    })


def config2_resnet18_8node() -> None:
    """Two halves of the north-star metric (BASELINE.md:19-21):

    1. TIME-TO-TARGET-ACCURACY (VERDICT r2 #1): 8-node ResNet-18 FedAvg on
       synthetic-hard CIFAR-10 to ≥70%. Round 2's recipe (constant Adam
       1e-3, per-round moment reset, 6-round budget) flatlined at 15% —
       starved, not unlearnable (a centrally trained ResNet-18 reaches 92%
       by step 200 with a warmup schedule). The fixed federated recipe:
       warmup-cosine LR with ``keep_opt_state=True`` so the schedule and
       Adam moments survive round boundaries.
    2. SEC/ROUND + MFU at throughput settings. The MFU lever found in
       round 3: amortize the round's fixed dispatch/aggregation cost over
       more local steps (bigger shard × multi-epoch rounds) — convs were
       already bf16, buffers already donated.
    """
    import optax

    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.models import resnet18
    from p2pfl_tpu.parallel import SpmdFederation

    data = FederatedDataset.synthetic_mnist(
        n_train=8 * 1024, n_test=1024, dim=(32, 32, 3), modes=8, noise=0.7, proto_scale=0.5
    )
    # --- half 1: time to target accuracy ---
    cap, spr_steps, target = 25, 16, 0.70
    sched = optax.warmup_cosine_decay_schedule(
        0.0, 3e-3, warmup_steps=2 * spr_steps, decay_steps=cap * spr_steps, end_value=1e-4
    )
    fed = SpmdFederation.from_dataset(
        resnet18(), data, n_nodes=8, batch_size=64, vote=False, seed=3,
        tx=optax.adam(sched), keep_opt_state=True,
    )
    curve = []
    rounds_to_target = None
    time_to_target = None
    t0 = time.monotonic()
    for r in range(cap):
        acc = float(fed.run_round(epochs=1, eval=True)["test_acc"])
        curve.append(round(acc, 4))
        if rounds_to_target is None and acc >= target:
            rounds_to_target = r + 1
            time_to_target = time.monotonic() - t0
            break
    log(f"config2: target {target} at round {rounds_to_target} ({time_to_target})")
    del fed
    jax.clear_caches()

    # --- half 2: throughput + MFU (2048-sample shards, batch 256) ---
    data_big = FederatedDataset.synthetic_mnist(
        n_train=8 * 2048, n_test=1024, dim=(32, 32, 3), modes=8, noise=0.7, proto_scale=0.5
    )
    fed_big = SpmdFederation.from_dataset(
        resnet18(), data_big, n_nodes=8, batch_size=256, vote=False, seed=3
    )
    fed_big.run_round(epochs=1)
    force_execution(fed_big.params)
    sec_per_round = _steady_state(fed_big)
    flops, round_mfu = _spmd_mfu(fed_big, sec_per_round)
    # multi-epoch rounds amortize the fixed per-round cost further
    fed_big.run_round(epochs=4)
    force_execution(fed_big.params)
    t0 = time.monotonic()
    for _ in range(3):
        fed_big.run_round(epochs=4)
    force_execution(fed_big.params)
    sec_ep4 = (time.monotonic() - t0) / 3
    flops_ep4 = fed_big.round_flops(epochs=4)
    from p2pfl_tpu.management.profiling import mfu as _mfu

    # same per-device normalization as the sibling mfu field
    mfu_ep4 = _mfu(flops_ep4, sec_ep4, n_devices=len(set(fed_big.mesh.devices.flat)))

    emit({
        "metric": "config2_resnet18_cifar10_8node_fedavg",
        "value": round(sec_per_round, 4),
        "unit": "sec_per_round",
        "target_acc": target,
        "rounds_to_target": rounds_to_target,
        "time_to_target_s": round(time_to_target, 2) if time_to_target else None,
        "accuracy_curve": curve,
        "recipe": "adam warmup-cosine peak 3e-3, keep_opt_state, batch 64",
        "throughput_point": "batch 256, 2048 samples/node",
        "flops_per_round": flops,
        "mfu": round(round_mfu, 4) if round_mfu is not None else None,
        "epochs4": {
            "sec_per_round": round(sec_ep4, 4),
            "mfu": round(mfu_ep4, 4) if mfu_ep4 is not None else None,
        },
        "data": "synthetic-hard (CIFAR-10 shaped)",
        "devices": len(jax.devices()),
    })


def config3_resnet50_64node_dirichlet() -> None:
    # 64-node ResNet-50 state is 64 × (params + 2 Adam moments) ≈ 18 GB —
    # sized for the v4-128 pod target. On a single chip, fold down until the
    # HBM fits; each fold probes in a FRESH subprocess (a failed attempt
    # leaves the backend's allocator in an unusable state).
    import os
    import subprocess

    if os.environ.get("P2PFL_CONFIG3_NODES"):
        _config3_measure(int(os.environ["P2PFL_CONFIG3_NODES"]))
        return
    for n_nodes in (64, 32, 16):
        env = dict(os.environ, P2PFL_CONFIG3_NODES=str(n_nodes))
        proc = subprocess.run(
            [sys.executable, __file__, "3"], env=env,
            capture_output=True, text=True, timeout=1200,
        )
        if proc.returncode == 0 and proc.stdout.strip():
            sys.stdout.write(proc.stdout)
            sys.stdout.flush()
            return
        log(f"config3: n={n_nodes} does not fit this chip (rc={proc.returncode})")
    raise RuntimeError("config3 does not fit this chip at any fold")


def _config3_measure(n_nodes: int) -> None:
    """ResNet-50 / CIFAR-100-shaped / Dirichlet(0.5) non-IID.

    Round-3 recipe fix (VERDICT r2 #1): same warmup-cosine +
    ``keep_opt_state`` treatment as config 2 — round 2 measured 4 flat
    rounds at chance (0.98% on 100 classes); with the schedule the
    non-IID federation climbs to the 50% target (measured: round ~28).
    """
    import optax

    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.models import resnet50
    from p2pfl_tpu.parallel import SpmdFederation

    data = FederatedDataset.synthetic_mnist(
        n_train=64 * 256, n_test=1024, dim=(32, 32, 3), num_classes=100,
        modes=2, noise=0.5, proto_scale=0.7,
    )
    cap, target = 45, 0.50
    spr_steps = (64 * 256 // n_nodes) // 32
    sched = optax.warmup_cosine_decay_schedule(
        0.0, 3e-3, warmup_steps=2 * spr_steps, decay_steps=40 * spr_steps, end_value=1e-4
    )
    fed = SpmdFederation.from_dataset(
        resnet50(), data, n_nodes=n_nodes, strategy="dirichlet", alpha=0.5,
        batch_size=32, vote=False, seed=3, remat=True,
        tx=optax.adam(sched), keep_opt_state=True,
    )
    fed.run_round(epochs=1)  # warm-up + OOM probe
    force_execution(fed.params)
    fed.evaluate()  # probe the eval path's memory too
    fed.reset(seed=3)
    curve = []
    rounds_to_target = None
    time_to_target = None
    t0 = time.monotonic()
    for r in range(cap):
        acc = float(fed.run_round(epochs=1, eval=True)["test_acc"])
        curve.append(round(acc, 4))
        if rounds_to_target is None and acc >= target:
            rounds_to_target = r + 1
            time_to_target = time.monotonic() - t0
            break
    sec_per_round = _steady_state(fed)
    flops, round_mfu = _spmd_mfu(fed, sec_per_round)
    emit({
        "metric": "config3_resnet50_cifar100_64node_dirichlet",
        "value": round(sec_per_round, 4),
        "unit": "sec_per_round",
        "n_nodes": n_nodes,
        "target_acc": target,
        "rounds_to_target": rounds_to_target,
        "time_to_target_s": round(time_to_target, 2) if time_to_target else None,
        "accuracy_curve": curve,
        "recipe": "adam warmup-cosine peak 3e-3, keep_opt_state, batch 32, remat",
        "flops_per_round": flops,
        # NOTE: remat recompute counts as executed FLOPs in the probe, so
        # this is hardware utilization, slightly above model-FLOPs MFU
        "mfu": round(round_mfu, 4) if round_mfu is not None else None,
        "partition": "dirichlet(0.5)",
        "data": "synthetic (CIFAR-100 shaped)",
        "devices": len(jax.devices()),
    })


def config4_byzantine_robust() -> None:
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.models import resnet18
    from p2pfl_tpu.parallel import SpmdFederation

    n, byz, rounds = 10, 2, 10  # 20% Byzantine
    data = FederatedDataset.synthetic_mnist(
        n_train=n * 512, n_test=1024, dim=(32, 32, 3), modes=2, noise=0.5, proto_scale=0.7
    )
    results = {}
    key = jax.random.PRNGKey(0)
    # fedavg is the non-robust control: same attack, no defense
    for agg in ("krum", "trimmed_mean", "clip", "fedavg"):
        fed = SpmdFederation.from_dataset(
            resnet18(), data, n_nodes=n, batch_size=64, vote=False,
            aggregator=agg, trim=byz, clip_tau=3.0, seed=3, remat=True,
        )
        t_rounds = []
        for _ in range(rounds):
            # Byzantine nodes: overwrite their slots with large Gaussian noise
            # before the round — they train from (and contribute) garbage
            fed.params = jax.tree.map(
                lambda x: x.at[:byz].set(
                    jax.random.normal(key, x.shape[1:], x.dtype) * 10.0
                ),
                fed.params,
            )
            t0 = time.monotonic()
            fed.run_round(epochs=1)
            force_execution(fed.params)
            t_rounds.append(time.monotonic() - t0)
        results[agg] = {
            "acc": round(float(fed.evaluate()["test_acc"]), 4),
            "sec_per_round": round(float(np.mean(t_rounds[1:])), 4),
        }
    emit({
        "metric": "config4_byzantine_robust_cifar10",
        "value": results["krum"]["sec_per_round"],
        "unit": "sec_per_round",
        "byzantine_fraction": byz / n,
        "rounds": rounds,
        "krum": results["krum"],
        "trimmed_mean": results["trimmed_mean"],
        "centered_clip": results["clip"],
        "fedavg_under_attack": results["fedavg"],
        "data": "synthetic (CIFAR-10 shaped)",
        "devices": len(jax.devices()),
    })


def config5_lora_32node() -> None:
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.learning.lora import split_lora
    from p2pfl_tpu.models.transformer import tiny_transformer
    from p2pfl_tpu.parallel import SpmdLoraFederation

    import optax

    n = 32
    model = tiny_transformer(seq_len=128)
    data = FederatedDataset.synthetic_lm(n_train=n * 64, n_test=256)

    # the real LoRA use case is adapting a PRETRAINED base: briefly pretrain
    # the full model centrally, then federate only the adapters on top
    tx = optax.adam(1e-3)
    params, opt = model.params, None

    @jax.jit
    def pre_step(params, opt, x, y):
        def loss_fn(p):
            logits = model.module.apply({"params": p}, x)
            return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, updates), opt, loss

    opt = tx.init(params)
    rng = np.random.default_rng(0)
    for step in range(300):
        idx = rng.integers(0, len(data.y_train), size=16)
        params, opt, loss = pre_step(
            params, opt, jnp.asarray(data.x_train[idx]), jnp.asarray(data.y_train[idx])
        )
    model.params = params
    log(f"config5: base pretrained (loss {float(loss):.3f})")

    fed = SpmdLoraFederation.from_dataset(
        model, data, n_nodes=n, batch_size=8, vote=False, seed=3, remat=True
    )
    base_acc = fed.evaluate()["test_acc"]
    fed.run_round(epochs=1)  # warm-up
    fed.run_fused(4, epochs=1)  # warm the fused executable too
    fed.reset(seed=3)
    sec_per_round = _steady_state(fed, rounds=4)
    acc = fed.evaluate()["test_acc"]  # BEFORE the fused span: 4-round acc
    # fused span: 4 rounds in ONE dispatch — adapters are tiny, so the
    # per-round cost is dispatch-dominated and fusing amortizes it
    t0 = time.monotonic()
    fed.run_fused(4, epochs=1)
    force_execution(fed.params)
    sec_fused = (time.monotonic() - t0) / 4
    lora, base = split_lora(model.params)
    n_lora = sum(x.size for x in jax.tree.leaves(lora))
    n_base = sum(x.size for x in jax.tree.leaves(base))
    from p2pfl_tpu.management.profiling import mfu as _mfu

    flops = fed.round_flops()
    emit({
        "metric": "config5_lora_transformer_32node",
        "value": round(sec_per_round, 4),
        "unit": "sec_per_round",
        "sec_per_round_fused": round(sec_fused, 4),
        "flops_per_round": flops,
        # MFU on the UNFUSED round (VERDICT r2 #2); the 3.4M-param
        # stand-in is dispatch-dominated (that's what fusing fixes), so
        # this is a lower bound for the TinyLlama-scale target
        "mfu": round(_mfu(flops, sec_per_round), 4) if flops else None,
        "mfu_fused": round(_mfu(flops, sec_fused), 4) if flops else None,
        "pretrained_base_acc": round(float(base_acc), 4),
        "next_token_acc_after_4_rounds": round(float(acc), 4),
        "adapter_params": n_lora,
        "base_params": n_base,
        "payload_shrink": round(n_base / n_lora, 1),
        "data": "synthetic-lm (markov)",
        "devices": len(jax.devices()),
    })


def config6_heterogeneous_algorithms() -> None:
    """Beyond-reference breadth: FedAvg vs FedProx vs SCAFFOLD vs FedAdam on
    Dirichlet(0.3) non-IID shards (the reference ships FedAvg only)."""
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.models import mlp
    from p2pfl_tpu.parallel import SpmdFederation

    n_nodes, rounds = 8, 10
    results = {}
    times = {}
    data = FederatedDataset.mnist(None, modes=8, noise=0.7, proto_scale=0.5)
    for algo, kwargs in {
        "fedavg": {},
        "fedprox": {"prox_mu": 0.1},
        "scaffold": {"scaffold": True, "optimizer": "sgd", "learning_rate": 0.05},
        "fedadam": {"server_opt": "adam", "server_lr": 0.01},
    }.items():
        fed = SpmdFederation.from_dataset(
            mlp(), data, n_nodes=n_nodes, strategy="dirichlet", alpha=0.3,
            batch_size=64, vote=False, seed=7, **kwargs,
        )
        # warm BOTH fused input layouts (fresh + evolved) and materialize —
        # one unmaterialized warm call leaves a compile inside the timer
        # (the r1 fedavg row measured 2.3 s/round vs 0.13 for its peers
        # because of exactly this)
        [float(e["test_acc"]) for e in fed.run_fused(rounds, epochs=1, eval=True)]
        [float(e["test_acc"]) for e in fed.run_fused(rounds, epochs=1, eval=True)]
        fed.reset(seed=7)
        t0 = time.monotonic()
        entries = fed.run_fused(rounds, epochs=1, eval=True)
        accs = [round(float(e["test_acc"]), 4) for e in entries]
        force_execution(fed.params)
        times[algo] = round((time.monotonic() - t0) / rounds, 4)
        results[algo] = accs
        log(f"config6 {algo}: {accs}")
        del fed
        jax.clear_caches()

    emit({
        "metric": "config6_heterogeneous_dirichlet03",
        "value": max(r[-1] for r in results.values()),
        "unit": "best_final_acc",
        "curves": results,
        "sec_per_round": times,
        "n_nodes": n_nodes,
        "partition": "dirichlet(0.3)",
        "data": "synthetic-hard",
        "devices": len(jax.devices()),
    })


def config7_long_context_flash() -> None:
    """Long-context single-chip path: Pallas flash attention vs fused dense
    XLA attention, training-step time across sequence lengths.

    Sweeps the flash kernel's block size per length (VERDICT r2 #8): the
    128-block default was chosen for divisibility, not speed; larger
    blocks amortize the Pallas grid/bookkeeping overhead that makes dense
    win at short lengths. Also reports which backend ``attn="auto"``
    (``pick_attention``) selects per length so the policy can be checked
    against the measurements.
    """
    import optax

    from p2pfl_tpu.models.transformer import (
        TransformerConfig,
        pick_attention,
        resolve_attention,
        tiny_transformer,
    )
    from p2pfl_tpu.settings import Settings

    cfg_kw = dict(
        vocab_size=1024, dim=256, n_layers=4, n_heads=8, n_kv_heads=8,
        ffn_hidden=688, lora_rank=0,
    )

    def measure(seq_len, attn, block=128):
        # dense → attn_fn None (fused XLA path); flash → explicit kernel
        # with the swept block size (attn_fn overrides tiny_transformer's
        # own block choice)
        from p2pfl_tpu.management.profiling import compiled_flops, mfu as _mfu

        attn_fn = resolve_attention("flash", block=block) if attn == "flash" else None
        m = tiny_transformer(
            seq_len=seq_len, cfg=TransformerConfig(**cfg_kw), attn_fn=attn_fn
        )
        tokens = jax.random.randint(jax.random.PRNGKey(0), (8, seq_len), 0, 1024)
        targets = jnp.roll(tokens, -1, axis=1)

        def loss(p, m=m, tokens=tokens, targets=targets):
            logits = m.apply(p, tokens)
            return optax.softmax_cross_entropy_with_integer_labels(logits, targets).mean()

        step = jax.jit(jax.value_and_grad(loss))
        # no scan in the step → cost analysis counts everything exactly once.
        # Pallas kernel FLOPs may be invisible to XLA's analysis, so MFU is
        # comparable only via the DENSE program's count (reported per row).
        flops = compiled_flops(step, m.params)
        _l, g = step(m.params)
        force_execution(g)  # compile barrier (real D2H fetch)
        t0 = time.monotonic()
        for _ in range(10):
            _l, g = step(m.params)
        force_execution(g)
        sec = (time.monotonic() - t0) / 10
        ms = round(sec * 1000, 2)
        del m, step, g
        jax.clear_caches()
        return ms, flops, _mfu(flops, sec)

    results = {}
    for seq_len in (1024, 2048, 4096):
        dense_ms, dense_flops, dense_mfu = measure(seq_len, "dense")
        row = {"dense": dense_ms}
        if dense_mfu is not None:
            row["dense_mfu"] = round(dense_mfu, 4)
        blocks = [b for b in (128, 256, 512) if seq_len % b == 0]
        sweep = {b: measure(seq_len, "flash", block=b)[0] for b in blocks}
        best_block = min(sweep, key=sweep.get)
        row["flash_block_sweep_ms"] = sweep
        row["flash"] = sweep[best_block]
        row["flash_best_block"] = best_block
        # flash MFU from the DENSE program's model-FLOP count (the Pallas
        # kernel's internal FLOPs are invisible to XLA's cost analysis;
        # using the same numerator keeps dense/flash comparable)
        flash_mfu = _mfu_from(dense_flops, sweep[best_block] / 1000.0)
        if flash_mfu is not None:
            row["flash_mfu"] = round(flash_mfu, 4)
        row["speedup"] = round(row["dense"] / row["flash"], 2)
        row["auto_picks"] = pick_attention(seq_len)
        results[f"T{seq_len}"] = row
        log(f"config7 T={seq_len}: {row}")

    emit({
        "metric": "config7_long_context_flash_vs_dense",
        "value": results["T4096"]["speedup"],
        "unit": "x_speedup_at_4096",
        "ms_per_train_step": results,
        "auto_threshold_seq_len": Settings.FLASH_MIN_SEQ_LEN,
        "batch": 8,
        "model": "4L/256d/8h transformer, bf16",
        "devices": len(jax.devices()),
    })


def config8_wire_compression() -> None:
    """(beyond reference) Gossip egress under the three wire codecs.

    The same 4-node federation over real gRPC sockets, 2 rounds × 1 epoch,
    under WIRE_COMPRESSION none / int8 / topk8 — reporting actual bytes
    that crossed the weight plane (GrpcProtocol.wire_stats) and the final
    accuracy, so the compression claims rest on measured egress, not
    per-payload arithmetic. The reference ships raw pickled float32 only.
    """
    from p2pfl_tpu.communication.grpc_transport import GrpcProtocol
    from p2pfl_tpu.communication.memory import MemoryRegistry
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.learning.learner import JaxLearner
    from p2pfl_tpu.models import mlp
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.settings import Settings, set_test_settings
    from p2pfl_tpu.utils import full_connection, wait_convergence, wait_to_finish

    set_test_settings()
    results = {}
    for mode in ("none", "int8", "topk8"):
        MemoryRegistry.reset()
        Settings.WIRE_COMPRESSION = mode
        full = FederatedDataset.synthetic_mnist(n_train=2048, n_test=512)
        nodes = []
        for i in range(4):
            learner = JaxLearner(mlp(seed=i), full.partition(i, 4), batch_size=64)
            n = Node(learner=learner, protocol=GrpcProtocol("127.0.0.1:0"))
            n.start()
            nodes.append(n)
        for n in nodes:
            full_connection(n, nodes)
        wait_convergence(nodes, 3, only_direct=True)
        nodes[0].set_start_learning(rounds=2, epochs=1)
        wait_to_finish(nodes, timeout=180)
        acc = min(float(n.learner.evaluate()["test_acc"]) for n in nodes)
        wb = sum(n.protocol.wire_stats["weights_bytes"] for n in nodes)
        wm = sum(n.protocol.wire_stats["weights_msgs"] for n in nodes)
        for n in nodes:
            n.stop()
        results[mode] = {
            "weights_MB": round(wb / 1e6, 3),
            "weights_msgs": wm,
            "min_final_acc": round(acc, 4),
        }
        log(f"config8 {mode}: {results[mode]}")
    Settings.WIRE_COMPRESSION = "none"
    emit({
        "metric": "config8_wire_compression_egress",
        "value": round(results["none"]["weights_MB"] / max(results["topk8"]["weights_MB"], 1e-9), 2),
        "unit": "x_egress_shrink_topk8_vs_float32",
        "modes": results,
        "n_nodes": 4,
        "rounds": 2,
        "transport": "grpc loopback",
        "data": "synthetic",
    })


def config10_moe_gpipe_federation() -> None:
    """(beyond reference) Federations training THROUGH MoE and GPipe.

    VERDICT r2 weak #3: the ep/pp axes compiled but no federation trained
    through them. Two rows:

    - MoE: 8 nodes federate a switch-style MoE transformer (8 experts,
      top-2, aux balance losses riding the federated loss) via
      ``SpmdLmFederation`` — accuracy trajectory to a stated target plus
      steady-state sec/round. Expert parallelism is mesh-width-bound: on
      the single bench chip the ``model`` axis is 1 (the 2-way-ep layout
      is proven on the 8-device virtual mesh in tests + dryrun).
    - GPipe: pipeline stages need >1 device, so the pipelined federation
      re-execs onto the virtual 8-device CPU mesh (4 stages × 2 nodes
      time-sharing them) — provenance recorded; real-chip pp numbers need
      real multi-chip hardware.
    """
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer
    from p2pfl_tpu.parallel import SpmdLmFederation

    n = 8
    cfg = TransformerConfig(
        vocab_size=512, dim=128, n_layers=4, n_heads=8, n_kv_heads=8,
        ffn_hidden=256, lora_rank=0, n_experts=8, moe_top_k=2,
    )
    model = tiny_transformer(seq_len=128, cfg=cfg)
    data = FederatedDataset.synthetic_lm(vocab_size=512, n_train=n * 256, n_test=512)
    fed = SpmdLmFederation.from_dataset(
        model, data, n_nodes=n, batch_size=16, vote=False, seed=3
    )
    target = 0.60
    curve = []
    rounds_to_target = None
    t0 = time.monotonic()
    for r in range(12):
        fed.run_round(epochs=1)
        acc = fed.evaluate()["test_acc"]
        curve.append(round(float(acc), 4))
        log(f"config10 moe round {r + 1}: acc {acc:.4f}")
        if rounds_to_target is None and acc >= target:
            rounds_to_target = r + 1
            time_to_target = time.monotonic() - t0
            break
    sec_per_round = _steady_state(fed, rounds=3)
    flops, round_mfu = _spmd_mfu(fed, sec_per_round)
    # NOT fused: measured on the chip, run_fused SLOWS this federation
    # (0.78 -> 3.4 s/round) — full-param MoE rounds are compute-bound, so
    # the fused scan's carry costs more than the one dispatch it saves
    # (see SpmdLmFederation.run_fused's docstring; fusing pays only for
    # dispatch-dominated tiny-state rounds like config 5's adapters)
    emit({
        "metric": "config10_moe_federation",
        "value": round(sec_per_round, 4),
        "unit": "sec_per_round",
        "flops_per_round": flops,
        "mfu": round(round_mfu, 4) if round_mfu is not None else None,
        "n_nodes": n,
        "model": "4L/128d MoE transformer, 8 experts top-2, seq 128",
        "acc_curve": curve,
        "target_acc": target,
        "rounds_to_target": rounds_to_target,
        "time_to_target_s": round(time_to_target, 2) if rounds_to_target else None,
        "expert_parallel": int(fed.mesh.shape.get("model", 1)),
        "data": "synthetic_lm",
        "devices": len(jax.devices()),
    })

    # GPipe federation: re-exec on a virtual multi-device mesh when the
    # current backend cannot host >1 pipeline stage
    if len(jax.devices()) >= 4:
        _config10_gpipe_body()
    else:
        # pipeline stages need >1 device: virtual 8-device CPU mesh
        _reexec("10pipe", timeout=1500, virtual_devices=8)


def _config10_gpipe_body() -> None:
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer
    from p2pfl_tpu.parallel import PipelineFederation

    cfg = TransformerConfig(
        vocab_size=512, dim=128, n_layers=4, n_heads=8, n_kv_heads=8,
        ffn_hidden=344, lora_rank=0,
    )
    model = tiny_transformer(seq_len=128, cfg=cfg)
    data = FederatedDataset.synthetic_lm(vocab_size=512, n_train=2 * 512, n_test=256)
    shards = [data.partition(i, 2) for i in range(2)]
    fed = PipelineFederation(model, shards, n_stages=4, batch_size=16, seed=3)
    target = 0.60
    curve = []
    rounds_to_target = None
    t0 = time.monotonic()
    for r in range(10):
        fed.run_round(epochs=1)
        acc = fed.evaluate()["test_acc"]
        curve.append(round(float(acc), 4))
        log(f"config10 gpipe round {r + 1}: acc {acc:.4f}")
        if rounds_to_target is None and acc >= target:
            rounds_to_target = r + 1
            time_to_target = time.monotonic() - t0
            break
    t0 = time.monotonic()
    for _ in range(2):
        fed.run_round(epochs=1)
    force_execution(fed.params)
    sec_per_round = (time.monotonic() - t0) / 2
    emit({
        "metric": "config10_gpipe_federation",
        "value": round(sec_per_round, 4),
        "unit": "sec_per_round",
        "n_nodes": 2,
        "pipeline_stages": 4,
        "model": "4L/128d transformer, GPipe 4-stage, seq 128",
        "acc_curve": curve,
        "target_acc": target,
        "rounds_to_target": rounds_to_target,
        "time_to_target_s": round(time_to_target, 2) if rounds_to_target else None,
        "data": "synthetic_lm",
        "devices": len(jax.devices()),
        "backend": jax.default_backend(),
    })


def config9_personalization() -> None:
    """(beyond reference) FedPer vs plain FedAvg under CONCEPT SHIFT.

    4 nodes share the input distribution but each maps features to its OWN
    label semantics (a node-specific label permutation — think region-
    specific class taxonomies). One global head cannot fit contradictory
    conditionals; FedPer federates the feature body and keeps each node's
    head local. Metric: mean per-node accuracy on the node's OWN test
    shard. (Under plain label-FREQUENCY skew the global model wins — we
    measured that too; personalization is for shifted conditionals, and
    this row shows exactly that regime.)
    """
    from p2pfl_tpu.communication.memory import MemoryRegistry
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.learning.learner import JaxLearner
    from p2pfl_tpu.learning.personalization import PersonalizedLearner
    from p2pfl_tpu.models import mlp
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.settings import Settings, set_test_settings
    from p2pfl_tpu.utils import full_connection, wait_convergence, wait_to_finish

    set_test_settings()
    Settings.TRAIN_SET_SIZE = 4
    results = {}
    for label in ("fedavg_global", "fedper_personal"):
        MemoryRegistry.reset()
        full = FederatedDataset.synthetic_mnist(
            n_train=4096, n_test=1024, modes=4, noise=0.6, proto_scale=0.6
        )
        nodes = []
        for i in range(4):
            shard = full.partition(i, 4)
            # concept shift: node i relabels classes by its own permutation
            perm = np.random.default_rng(100 + i).permutation(shard.num_classes)
            shard.y_train = perm[shard.y_train]
            shard.y_test = perm[shard.y_test]
            if label == "fedper_personal":
                learner = PersonalizedLearner(
                    mlp(seed=i), shard, batch_size=64, personal=("Dense_2",)
                )
            else:
                learner = JaxLearner(mlp(seed=i), shard, batch_size=64)
            n = Node(learner=learner)
            n.start()
            nodes.append(n)
        for n in nodes:
            full_connection(n, nodes)
        wait_convergence(nodes, 3, only_direct=True)
        t0 = time.monotonic()
        nodes[0].set_start_learning(rounds=5, epochs=2)
        wait_to_finish(nodes, timeout=300)
        elapsed = time.monotonic() - t0
        accs = [float(n.learner.evaluate()["test_acc"]) for n in nodes]
        for n in nodes:
            n.stop()
        results[label] = {
            "mean_local_acc": round(float(np.mean(accs)), 4),
            "per_node": [round(a, 4) for a in accs],
            "wall_s": round(elapsed, 1),
        }
        log(f"config9 {label}: {results[label]}")
    emit({
        "metric": "config9_fedper_vs_global_concept_shift",
        "value": results["fedper_personal"]["mean_local_acc"],
        "unit": "mean_local_acc",
        "fedper_personal": results["fedper_personal"],
        "fedavg_global": results["fedavg_global"],
        "n_nodes": 4,
        "rounds": 5,
        "setting": "concept shift (node-specific label permutations)",
        "data": "synthetic",
    })


CONFIGS = {
    "1": config1_mnist_2node,
    "2": config2_resnet18_8node,
    "3": config3_resnet50_64node_dirichlet,
    "4": config4_byzantine_robust,
    "5": config5_lora_32node,
    "6": config6_heterogeneous_algorithms,
    "7": config7_long_context_flash,
    "8": config8_wire_compression,
    "9": config9_personalization,
    "10": config10_moe_gpipe_federation,
    "10pipe": _config10_gpipe_body,  # internal: config10's multi-device re-exec
}


def main() -> None:
    wanted = sys.argv[1:] or [k for k in sorted(CONFIGS, key=lambda s: (len(s), s)) if not k.endswith("pipe")]
    if len(wanted) == 1:
        CONFIGS[wanted[0]]()
        return
    # one subprocess per config: an OOM (or any backend poisoning) in one
    # config must not contaminate the next measurement
    import subprocess

    for key in wanted:
        log(f"=== config {key} ===")
        t0 = time.monotonic()
        proc = subprocess.run(
            [sys.executable, __file__, key], capture_output=True, text=True, timeout=1800
        )
        sys.stderr.write(proc.stderr[-2000:])
        if proc.returncode == 0 and proc.stdout.strip():
            sys.stdout.write(proc.stdout)
            sys.stdout.flush()
        else:
            emit({"metric": f"config{key}", "error": f"rc={proc.returncode}: {proc.stderr[-300:]}"})
        log(f"=== config {key} done in {time.monotonic() - t0:.1f}s ===")


if __name__ == "__main__":
    main()
