"""BENCH_ASYNC: time-to-accuracy, sync round FSM vs async vs hierarchical.

The ISSUE-9 acceptance measurement: the same mnist fleet, the same seeded
straggler/crash FaultPlan, the same total local-training budget — driven
three ways:

- ``sync``  — the barrier-synchronized round FSM (stages/learning_stages),
- ``async`` — flat FedBuff (one global BufferedAggregator, no barrier),
- ``hier``  — FedBuff + HierarchicalTopology (edge clusters → regional →
  global).

Each threaded row reports wall-clock to complete the budget with the
final global model at/above the target accuracy — the async rows must
beat the sync row on the same fleet, because the sync barrier pays the
slow peer's inbound-weights latency (and the crash's eviction window)
once per round while the async planes pay it only on that node's own
contributions.

The threaded fleet is small (10 real nodes), so the "10% slow / 1%
crash" plan quantizes to 1 slow node and 1 crash; the 1k-node SIMULATED
section runs the exact fractions through
:class:`p2pfl_tpu.federation.simfleet.SimulatedAsyncFleet` (virtual
clock, bit-identical replay) and compares against the sync fleet's
analytic floor — a barrier fleet cannot finish a round faster than its
slowest member trains.

The ``churn_1k`` section (ISSUE 11) drives the same 1k-node simulated
fleet under a seeded elastic-churn plan — 5% leaves (graceful + abrupt),
5% joins, one mid-convergence GLOBAL-ROOT kill — against the static
fleet, so the disruption cost of membership churn is a measured
time-to-target ratio, not a claim.

The ``byzantine_1k`` section (ISSUE 14) sweeps ADVERSARIES instead of
failures: 5/10/20% of the fleet running ``ByzantineSpec`` attacks
(sign-flip, scale, noise) against the hierarchical plane, defense off
(the FedBuff weighted mean folds whatever arrives) vs on
(``ASYNC_ROBUST_AGG="trimmed-mean"`` + the admission screen +
suspicion-EWMA quarantine) — time-to-target, final loss, and how many
attackers the eviction machinery removed, per cell.

The ``megafleet_1m`` section (ISSUE 15) drives the VECTORIZED engine
(:class:`p2pfl_tpu.federation.megafleet.MegaFleet` — the simulator as one
jitted ``lax.scan``) at ≥1M clients through the hierarchical plane, with
the Bonawitz production knobs (pace steering, selection
over-provisioning, per-tier rate limits) swept as array-level controls —
a parameter sweep no Python event loop could produce — plus honest
wall-clock/clients-per-second rows for the heap driver at 1k/10k next to
the vectorized engine at the same and at 1M, and the 1k heap-parity
check (merge count + version sequence exact).

The ``megafleet_chunks`` section (ISSUE 16) sweeps the chunked engine's
``MEGAFLEET_CHUNK`` knob at the 1M scale against the per-event reference
scan — clients/second per chunk size, with an inline bit-identity check
(flat chunked results must equal the per-event scan EXACTLY) — and the
``megafleet_robust`` section runs the full-fault-algebra sweep the array
engine exists for: attacker fraction (5–20%) × corruption kind
(sign_flip/scale/noise) × window fold (fedavg/trimmed-mean/median) at
1M clients, with one cell tolerance-pinned against the heap driver at
1k.

Usage: ``JAX_PLATFORMS=cpu python bench_async.py [--smoke]
[--sections a,b,...] [--out BENCH_ASYNC.json]``

``--sections`` (any of ``threaded,simulated,churn,restart,byzantine,
megafleet,megafleet_chunks,megafleet_robust,megafleet_sharded``) runs a
subset and MERGES it into
the existing ``--out`` document, leaving the other sections' rows
untouched — so CI can refresh one section without paying for the full
grid.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np

SEED = 1905
TARGET_ACC = 0.80


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _fleet_settings():
    from p2pfl_tpu.settings import Settings, set_low_latency_settings

    set_low_latency_settings()
    Settings.TRAIN_SET_SIZE = 10
    Settings.VOTE_TIMEOUT = 30.0
    Settings.AGGREGATION_TIMEOUT = 60.0
    Settings.FEDBUFF_K = 4
    Settings.FEDBUFF_ALPHA = 0.5
    Settings.FEDBUFF_SERVER_LR = 1.0
    Settings.ASYNC_MAX_STALENESS = 16
    Settings.ASYNC_DRAIN_TIMEOUT = 20.0


def _make_plan(addrs: list, slow_s: float, async_mode: bool):
    """1 slow + 1 crash over a 10-node fleet (the small-fleet quantization
    of the 10%/1% plan; the simulated section runs the exact fractions).
    Deterministic: same seed, same victim indices in every mode."""
    from p2pfl_tpu.communication.faults import CrashSpec, EdgeFault, FaultPlan

    slow_addr = addrs[-1]
    crash_addr = addrs[-2]
    stage = "AsyncTrainStage" if async_mode else "TrainStage"
    return FaultPlan(
        seed=SEED,
        default=EdgeFault(drop=0.01),
        slow_nodes={slow_addr: slow_s},
        crashes={crash_addr: CrashSpec(stage=stage, round_no=1)},
    )


def run_threaded(mode: str, *, n_nodes: int = 10, rounds: int = 4, slow_s: float = 0.5) -> dict:
    """One fresh federation in the given mode; returns the row dict.

    ``rounds`` is the per-node local-update budget in every mode (sync
    rounds == async local updates: identical total training work).
    """
    from p2pfl_tpu.communication.memory import MemoryRegistry
    from p2pfl_tpu.communication.faults import install_fault_plan, remove_fault_plan
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.learning.learner import JaxLearner, eval_step
    from p2pfl_tpu.management.logger import logger
    from p2pfl_tpu.management.telemetry import telemetry
    from p2pfl_tpu.models import mlp
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.settings import Settings
    from p2pfl_tpu.utils import full_connection, wait_convergence, wait_to_finish

    MemoryRegistry.reset()
    logger.reset_comm_metrics()
    telemetry.reset()
    _fleet_settings()
    Settings.FEDERATION_MODE = "async" if mode != "sync" else "sync"
    Settings.HIER_CLUSTER_SIZE = 4 if mode == "hier" else 0

    full = FederatedDataset.synthetic_mnist(n_train=8192, n_test=2048, seed=3)
    x_test, y_test = full.test_arrays()

    # jit warm-up outside the timers (shared cache: same module/shapes)
    warm = JaxLearner(mlp(seed=99), full.partition(0, n_nodes), batch_size=64, epochs=1)
    warm.fused_round()
    warm.evaluate()

    nodes = []
    for i in range(n_nodes):
        learner = JaxLearner(mlp(seed=i), full.partition(i, n_nodes), batch_size=64)
        nodes.append(Node(learner=learner))
    for n in nodes:
        n.start()
    for n in nodes:
        full_connection(n, nodes)
    wait_convergence(nodes, n_nodes - 1, only_direct=True, wait=15)
    plan = _make_plan([n.addr for n in nodes], slow_s, mode != "sync")
    install_fault_plan(nodes, plan)
    victim_addr = [n.addr for n in nodes][-2]
    survivors = [n for n in nodes if n.addr != victim_addr]
    try:
        t0 = time.monotonic()
        nodes[0].set_start_learning(rounds=rounds, epochs=1)
        wait_to_finish(survivors, timeout=300)
        wall = time.monotonic() - t0
        # final accuracy of the fleet model (survivor consensus / latest
        # global), evaluated on the full held-out test set
        accs = []
        for n in survivors:
            _loss, acc = eval_step(
                n.learner.get_parameters(), np.asarray(x_test), np.asarray(y_test),
                n.learner.model.module,
            )
            accs.append(float(acc))
        comm = {}
        for d in logger.get_comm_metrics().values():
            for k, v in d.items():
                if k.startswith("async") or k in ("train_set_repair",):
                    comm[k] = comm.get(k, 0) + v
        stale = {
            k.split("/")[0]: v
            for k, v in telemetry.value_histograms().items()
            if k.endswith("/staleness")
        }
        return {
            "mode": mode,
            "wall_s": round(wall, 3),
            "final_acc_min": round(min(accs), 4),
            "final_acc_max": round(max(accs), 4),
            "reached_target": min(accs) >= TARGET_ACC,
            "comm": {k: int(v) for k, v in sorted(comm.items())},
            "staleness": stale,
        }
    finally:
        remove_fault_plan(nodes)
        for n in nodes:
            n.stop()
        MemoryRegistry.reset()


def run_simulated(n: int = 1000, updates: int = 6, smoke: bool = False) -> dict:
    """Exact 10% slow / 1% crash at 1k nodes on the virtual clock.

    Time-to-loss-target is the comparison (makespan would unfairly bill
    the async planes for stragglers finishing their own budgets after
    the model already converged). The sync baseline is an EXACT
    simulation of barrier rounds on the same task and population: every
    round, all live nodes train from the global, the fleet averages all
    of them, and the round's wall-clock is the slowest live member's
    train duration — the barrier's defining cost.
    """
    from p2pfl_tpu.communication.faults import CrashSpec, EdgeFault, FaultPlan
    from p2pfl_tpu.federation.simfleet import SimulatedAsyncFleet

    if smoke:
        n, updates = 100, 4
    base, slow_factor = 1.0, 10.0
    addrs = [f"sim-{i:04d}" for i in range(n)]
    plan = FaultPlan(
        seed=SEED,
        default=EdgeFault(drop=0.01),
        slow_nodes={},  # slow durations modeled via slow_frac (train time)
        crashes={
            a: CrashSpec(stage="AsyncTrainStage", round_no=2)
            for a in addrs[7::100][: max(1, n // 100)]
        },
    )

    def make_fleet(cluster_size: int) -> SimulatedAsyncFleet:
        return SimulatedAsyncFleet(
            n,
            seed=SEED,
            cluster_size=cluster_size,
            updates_per_node=updates,
            base_duration=base,
            slow_frac=0.10,
            slow_factor=slow_factor,
            plan=plan,
            local_lr=0.7,
        )

    # the loss target every mode must reach: 5% of the cold-start loss
    probe = make_fleet(0)
    dim = len(np.asarray(probe.nodes[addrs[0]].model["w"]))
    start_loss = probe.loss_fn({"w": np.zeros(dim, np.float32)})
    target = float(start_loss) * 0.05

    def drive(cluster_size: int) -> dict:
        fleet = make_fleet(cluster_size)
        fleet.target_loss = target
        res = fleet.run()
        return {
            "time_to_target_s": round(res.time_to_target, 3) if res.time_to_target else None,
            "makespan_virtual_s": round(res.virtual_time, 3),
            "global_versions": res.version,
            "merges": res.merges,
            "updates_sent": res.updates_sent,
            "updates_dropped_wire": res.updates_dropped_wire,
            "crashed": len(res.crashed),
            "final_loss": round(res.final_loss(), 5),
        }

    def sync_baseline() -> dict:
        """Exact barrier rounds on the same task/population/faults."""
        params = {"w": np.zeros(dim, np.float32)}
        durations = {a: probe.nodes[a].duration for a in addrs}
        weights = {a: probe.nodes[a].num_samples for a in addrs}
        crashed = set()
        t, rounds, t_target = 0.0, 0, None
        loss = float(start_loss)
        while rounds < updates:
            live = [a for a in addrs if a not in crashed]
            trained, w = [], []
            for a in live:
                node = probe.nodes[a]
                rng = np.random.default_rng([SEED, 13, node.idx, rounds])
                trained.append(np.asarray(
                    probe.train_fn(node.idx, params, rng)["w"], np.float32))
                w.append(float(weights[a]))
            w = np.asarray(w, np.float32)
            params = {"w": (w[:, None] * np.stack(trained)).sum(0) / w.sum()}
            t += max(durations[a] for a in live)  # the barrier
            rounds += 1
            loss = float(probe.loss_fn(params))
            if t_target is None and loss <= target:
                t_target = t
            if rounds == 2:  # same crash schedule as the async plan
                crashed |= set(plan.crashes)
        return {
            "time_to_target_s": round(t_target, 3) if t_target else None,
            "rounds": rounds,
            "final_loss": round(loss, 5),
        }

    flat = drive(0)
    hier = drive(32)
    sync = sync_baseline()

    def speedup(row):
        if row["time_to_target_s"] and sync["time_to_target_s"]:
            return round(sync["time_to_target_s"] / row["time_to_target_s"], 2)
        return None

    return {
        "n_nodes": n,
        "updates_per_node": updates,
        "plan": {"slow_frac": 0.10, "slow_factor": slow_factor, "crash_frac": 0.01,
                 "drop": 0.01, "seed": SEED},
        "start_loss": round(float(start_loss), 5),
        "target_loss": round(target, 5),
        "sync_barrier": sync,
        "async_flat": flat,
        "hier_cluster32": hier,
        "speedup_vs_sync": {
            "async_flat": speedup(flat),
            "hier_cluster32": speedup(hier),
        },
    }


def run_byzantine(n: int = 1000, updates: int = 6, smoke: bool = False) -> dict:
    """ISSUE 14: the cost of lying nodes, and what the defenses buy back.

    Every cell is the same seeded 1k-node hierarchical consensus fleet
    (cluster 32, K=4) with ``frac`` of the members armed with one
    ``ByzantineSpec`` attack, driven twice: defenses OFF (the stock
    FedBuff weighted merge — one poisoned update lands at full staleness
    weight) and ON (``ASYNC_ROBUST_AGG="trimmed-mean"`` + the admission
    screen whose suspicion EWMA drives quarantine-by-eviction). Replay
    is bit-exact per cell — the attack rides the plan's per-edge streams.
    """
    from p2pfl_tpu.communication.faults import ByzantineSpec, FaultPlan
    from p2pfl_tpu.federation.simfleet import SimulatedAsyncFleet
    from p2pfl_tpu.settings import Settings

    if smoke:
        n, updates = 100, 4
    fracs = [0.10] if smoke else [0.05, 0.10, 0.20]
    kinds = ["sign_flip"] if smoke else ["sign_flip", "scale", "noise"]
    cluster = 32

    def make_fleet():
        return SimulatedAsyncFleet(
            n, seed=SEED, cluster_size=cluster, updates_per_node=updates,
            local_lr=0.7,
        )

    probe = make_fleet()
    dim = len(np.asarray(probe.nodes["sim-0000"].model["w"]))
    start_loss = float(probe.loss_fn({"w": np.zeros(dim, np.float32)}))
    target = start_loss * 0.05

    old = (Settings.BYZ_SCREEN, Settings.ASYNC_ROBUST_AGG)
    rows = []
    try:
        for kind in kinds:
            for frac in fracs:
                stride = max(1, int(round(1 / frac)))
                attackers = {
                    f"sim-{i:04d}": ByzantineSpec(kind=kind, lam=10.0, noise_std=20.0)
                    for i in range(0, n, stride)
                }
                cell = {"kind": kind, "attacker_frac": frac, "attackers": len(attackers)}
                for defend in (False, True):
                    Settings.BYZ_SCREEN = defend
                    Settings.ASYNC_ROBUST_AGG = "trimmed-mean" if defend else "fedavg"
                    fleet = make_fleet()
                    fleet.plan = FaultPlan(seed=SEED, byzantine=attackers)
                    fleet.target_loss = target
                    res = fleet.run()
                    final = res.final_loss()
                    cell["defended" if defend else "undefended"] = {
                        "time_to_target_s": round(res.time_to_target, 3)
                        if res.time_to_target
                        else None,
                        # a scale attack through the undefended mean can
                        # blow the consensus to inf: keep the JSON strict
                        "final_loss": round(final, 5) if np.isfinite(final) else None,
                        "diverged": not np.isfinite(final),
                        "merges": res.merges,
                        "corrupted_payloads": res.byz_corrupted,
                        "screen_rejects": res.screen_rejects,
                        "quarantined": len(res.quarantined),
                    }
                log(json.dumps(cell))
                rows.append(cell)
    finally:
        Settings.BYZ_SCREEN, Settings.ASYNC_ROBUST_AGG = old

    return {
        "n_nodes": n,
        "updates_per_node": updates,
        "cluster_size": cluster,
        "start_loss": round(start_loss, 5),
        "target_loss": round(target, 5),
        "attack": {"lam": 10.0, "noise_std": 20.0, "seed": SEED},
        "defense_on": {
            "robust_agg": "trimmed-mean",
            "screen": {
                "norm_gate": 4.0,
                "cos_gate": 0.5,
                "suspicion_beta": 0.5,
                "suspicion_threshold": 0.7,
            },
        },
        "rows": rows,
    }


def run_churn(n: int = 1000, updates: int = 6, smoke: bool = False) -> dict:
    """ISSUE 11: the disruption cost of elastic churn as a number.

    The same 1k-node hierarchical consensus fleet driven twice — static
    membership vs a seeded churn plan (5% graceful+abrupt leaves, 5%
    joins, one GLOBAL-ROOT kill) — comparing time-to-loss-target and
    merge counts. The churn fleet must still reach the target: successor
    roots self-elect, buffers migrate, joiners bootstrap from the
    current global, and version minting stays monotone through the
    failover (federation/routing.py).
    """
    from p2pfl_tpu.communication.faults import FaultPlan, JoinSpec, LeaveSpec
    from p2pfl_tpu.federation.simfleet import SimulatedAsyncFleet

    if smoke:
        n, updates = 100, 4
    addrs = [f"sim-{i:04d}" for i in range(n)]
    n_churn = max(2, n // 20)  # 5%
    leaves = {
        a: LeaveSpec(at_s=0.4 + 0.02 * j, graceful=(j % 2 == 0))
        for j, a in enumerate(addrs[3 :: max(1, n // n_churn)][:n_churn])
    }
    # the ROOT KILL, time-targeted mid-convergence: an abrupt
    # (graceful=False) leave is a killed process — no announcement,
    # survivors discover it a full evict_delay later. t=0.9 lands in the
    # middle of the first convergence waterfall while the root is the
    # only node minting globals, so the measured disruption is the real
    # failover cost: a stall of ~evict_delay, then the successor root
    # resumes minting from the version high-water mark.
    leaves[addrs[0]] = LeaveSpec(at_s=0.9, graceful=False)
    plan = FaultPlan(
        seed=SEED,
        leaves=leaves,
        joins={f"sim-j{j:03d}": JoinSpec(at_s=0.6 + 0.02 * j) for j in range(n_churn)},
    )

    def make_fleet(churn: bool) -> SimulatedAsyncFleet:
        # local_lr 0.3 (vs run_simulated's 0.7): convergence then takes
        # several merge generations instead of one wave, so the churn
        # window (leaves/joins from 0.4s, the root kill at 0.9s) sits
        # INSIDE the measured time-to-target interval — at 0.7 every
        # target tight enough to matter is hit in the first wave and the
        # disruption ratio is vacuously 1.0
        return SimulatedAsyncFleet(
            n, seed=SEED, cluster_size=32, updates_per_node=updates,
            local_lr=0.3, plan=plan if churn else None,
        )

    probe = make_fleet(False)
    dim = len(np.asarray(probe.nodes[addrs[0]].model["w"]))
    start_loss = probe.loss_fn({"w": np.zeros(dim, np.float32)})
    target = float(start_loss) * 0.05

    def drive(churn: bool) -> dict:
        fleet = make_fleet(churn)
        fleet.target_loss = target
        res = fleet.run()
        versions = [v for _t, v, _l in res.loss_curve]
        return {
            "time_to_target_s": round(res.time_to_target, 3) if res.time_to_target else None,
            "makespan_virtual_s": round(res.virtual_time, 3),
            "global_versions": res.version,
            "merges": res.merges,
            "final_loss": round(res.final_loss(), 5),
            "joined": len(res.joined),
            "left": len(res.left),
            "crashed": len(res.crashed),
            "root_failovers": res.failovers,
            "version_monotone": versions == sorted(versions) and len(set(versions)) == len(versions),
        }

    static, churn = drive(False), drive(True)
    disruption = None
    if static["time_to_target_s"] and churn["time_to_target_s"]:
        disruption = round(churn["time_to_target_s"] / static["time_to_target_s"], 3)
    return {
        "n_nodes": n,
        "updates_per_node": updates,
        "plan": {"leave_frac": 0.05, "join_frac": 0.05, "root_kill": True, "seed": SEED},
        "start_loss": round(float(start_loss), 5),
        "target_loss": round(target, 5),
        "static": static,
        "churn": churn,
        "disruption_time_to_target_ratio": disruption,
    }


def run_restart(n: int = 1000, updates: int = 6, smoke: bool = False) -> dict:
    """ISSUE 20: what crash-resurrection buys, as a number.

    The same 1k-node hierarchical consensus fleet driven three ways —
    static membership, 5% of nodes crashed mid-run (CrashSpec: the
    pre-durability world, their remaining update budget forfeited), and
    the same 5% crashed then RESURRECTED after a restart delay
    (RestartSpec: each victim re-enters with its retained state and
    finishes its budget) — comparing time-to-loss-target and how many of
    the crash-forfeited merges the restart path recovers. The restart
    drive is run twice from the same ``(seed, plan)`` and must replay
    bit-exact (same loss curve, same restart order, identical final
    params), the determinism contract every chaos feature carries.
    """
    from p2pfl_tpu.communication.faults import CrashSpec, FaultPlan, RestartSpec
    from p2pfl_tpu.federation.simfleet import SimulatedAsyncFleet

    if smoke:
        n, updates = 100, 4
    addrs = [f"sim-{i:04d}" for i in range(n)]
    n_victims = max(2, n // 20)  # 5%
    victims = addrs[3 :: max(1, n // n_victims)][:n_victims]
    restart_plan = lambda: FaultPlan(  # noqa: E731 — plans hold run RNG state
        seed=SEED,
        restarts={
            a: RestartSpec(round_no=1, resume_after_s=1.0 + 0.05 * (j % 7))
            for j, a in enumerate(victims)
        },
    )
    crash_plan = lambda: FaultPlan(  # noqa: E731
        seed=SEED,
        crashes={a: CrashSpec("AsyncTrainStage", round_no=1) for a in victims},
    )

    def make_fleet(plan) -> SimulatedAsyncFleet:
        # local_lr 0.3 for the same reason as run_churn: the crash window
        # must sit INSIDE the measured time-to-target interval
        return SimulatedAsyncFleet(
            n, seed=SEED, cluster_size=32, updates_per_node=updates,
            local_lr=0.3, plan=plan,
        )

    probe = make_fleet(None)
    dim = len(np.asarray(probe.nodes[addrs[0]].model["w"]))
    start_loss = probe.loss_fn({"w": np.zeros(dim, np.float32)})
    target = float(start_loss) * 0.05

    def drive(plan) -> tuple:
        fleet = make_fleet(plan)
        fleet.target_loss = target
        res = fleet.run()
        versions = [v for _t, v, _l in res.loss_curve]
        return res, {
            "time_to_target_s": round(res.time_to_target, 3) if res.time_to_target else None,
            "makespan_virtual_s": round(res.virtual_time, 3),
            "global_versions": res.version,
            "merges": res.merges,
            "updates_sent": res.updates_sent,
            "final_loss": round(res.final_loss(), 5),
            "crashed": len(res.crashed),
            "restarted": len(res.restarted),
            "version_monotone": versions == sorted(versions) and len(set(versions)) == len(versions),
        }

    _res_static, static = drive(None)
    _res_crash, crash = drive(crash_plan())
    res_a, restart = drive(restart_plan())
    res_b, _restart_b = drive(restart_plan())
    replay_exact = bool(
        res_a.loss_curve == res_b.loss_curve
        and res_a.restarted == res_b.restarted
        and np.array_equal(np.asarray(res_a.params["w"]), np.asarray(res_b.params["w"]))
    )
    # the headline: of the update budget a crash-only fleet forfeits,
    # how much does crash-and-restart claw back?
    forfeited = static["updates_sent"] - crash["updates_sent"]
    recovered = restart["updates_sent"] - crash["updates_sent"]
    return {
        "n_nodes": n,
        "updates_per_node": updates,
        "plan": {"crash_frac": 0.05, "restart_delay_s": [1.0, 1.3], "seed": SEED},
        "start_loss": round(float(start_loss), 5),
        "target_loss": round(target, 5),
        "static": static,
        "crash_only": crash,
        "crash_and_restart": restart,
        "updates_forfeited_by_crash": forfeited,
        "updates_recovered_by_restart": recovered,
        "recovery_frac": round(recovered / forfeited, 3) if forfeited else None,
        "restart_replay_bit_exact": replay_exact,
    }


def run_megafleet(smoke: bool = False) -> dict:
    """ISSUE 15: the vectorized engine at fleet scale.

    Three parts: (a) honest wall-clock rows — the heap driver at 1k and
    10k vs the vectorized engine at 1k, 10k, 100k and 1M clients (heap
    events grow as merges × fan-out, which is why its wall-clock
    explodes where the scan's per-event cost stays flat). The mega rows
    are megafleet-native ``FleetSpec.synth`` populations with matching
    STATISTICS, not the heap's exported population, so compare
    throughput across rows, not losses; (b) the same-task anchor is the
    inline 1k heap-parity check (``from_sim`` export, merge count +
    version sequence exact, final loss within the documented tolerance)
    run against the event-exact driver in the same process; (c) the
    ≥1M-client hierarchical drive with Bonawitz-knob sweeps — pace
    steering and selection over-provisioning against time-to-target and
    the staleness profile, a grid only an array engine can afford.
    """
    from p2pfl_tpu.federation.megafleet import FleetSpec, MegaFleet
    from p2pfl_tpu.federation.simfleet import SimulatedAsyncFleet

    heap_sizes = [1000] if smoke else [1000, 10_000]
    mega_sizes = [1000, 20_000] if smoke else [1000, 10_000, 100_000, 1_000_000]
    big_n = mega_sizes[-1]
    updates = 4

    def heap_fleet(n):
        return SimulatedAsyncFleet(
            n, seed=SEED, cluster_size=32, updates_per_node=updates,
            slow_frac=0.10, local_lr=0.7,
        )

    # -- heap rows + the 1k parity anchor --
    heap_rows, parity = [], None
    for n in heap_sizes:
        fleet = heap_fleet(n)
        t0 = time.monotonic()
        heap = fleet.run()
        wall = time.monotonic() - t0
        heap_rows.append({
            "driver": "heap", "n_clients": n, "wall_s": round(wall, 2),
            "clients_per_sec": int(n / wall), "merges": heap.merges,
            "final_loss": round(heap.final_loss(), 5),
        })
        log(json.dumps(heap_rows[-1]))
        if n == 1000:
            mega = MegaFleet(
                FleetSpec.from_sim(fleet), cluster_size=32,
                updates_per_node=updates, local_lr=0.7,
            ).run()
            hl = heap.final_loss()
            parity = {
                "merge_count_exact": mega.merges == heap.merges,
                "version_sequence_exact": [v for _t, v, _l in mega.loss_curve]
                == [v for _t, v, _l in heap.loss_curve],
                "final_loss_rel_diff": round(
                    abs(mega.final_loss() - hl) / max(hl, 1e-12), 6
                ),
            }
            log(json.dumps({"parity_1k": parity}))

    # -- vectorized rows (megafleet-native population at every scale);
    # the sweep below reuses the big row's run as its pace=0 baseline,
    # so target_loss is threaded through (host-side post-processing
    # only: the scan is identical) --
    big_spec = FleetSpec.synth(big_n, seed=SEED, slow_frac=0.10)
    start_loss = big_spec.loss(big_spec.init)
    target = start_loss * 0.05
    mega_rows, big_res, big_cluster, big_k = [], None, 0, None
    for n in mega_sizes:
        spec = big_spec if n == big_n else FleetSpec.synth(
            n, seed=SEED, slow_frac=0.10
        )
        cluster = 32 if n <= 10_000 else 1024
        k = None if n <= 10_000 else 64
        res = MegaFleet(
            spec, cluster_size=cluster, k=k, updates_per_node=updates,
            local_lr=0.7, target_loss=target if n == big_n else 0.0,
        ).run()
        if n == big_n:
            big_res, big_cluster, big_k = res, cluster, k
        mega_rows.append({
            "driver": "megafleet", "n_clients": n, "cluster_size": cluster,
            "wall_s": round(res.wall_s, 2),
            "clients_per_sec": int(res.clients_per_sec),
            "events": res.n_events, "merges": res.merges,
            "regional_merges": res.regional_merges,
            "final_loss": round(res.final_loss(), 6),
        })
        log(json.dumps(mega_rows[-1]))

    # -- the 1M knob sweep: pace steering × selection, plus a rate-limit
    # cell — time-to-target (5% of cold-start loss) per cell, every cell
    # at the big row's exact (cluster, k) config --

    def cell_stats(res, **kw):
        hist = res.staleness_hist_edge
        tot = max(sum(hist), 1)
        mean_tau = sum(i * c for i, c in enumerate(hist)) / tot
        return {
            **kw,
            "time_to_target_s": round(res.time_to_target, 3)
            if res.time_to_target
            else None,
            "final_loss": round(res.final_loss(), 6),
            "merges": res.merges,
            "mean_staleness": round(mean_tau, 3),
            "stale_dropped": res.stale_dropped,
            "rate_limited": res.rate_limited,
            "unselected": res.unselected,
            "wall_s": round(res.wall_s, 2),
        }

    def cell(**kw):
        return cell_stats(
            MegaFleet(
                big_spec, cluster_size=big_cluster, k=big_k,
                updates_per_node=updates, local_lr=0.7, target_loss=target,
                **kw,
            ).run(),
            **kw,
        )

    # pace=0 is the big wall-clock row's exact config — reuse its run
    sweep = [cell_stats(big_res, pace_window=0.0)]
    log(json.dumps(sweep[-1]))
    for pace in [0.5] if smoke else [0.5, 1.0]:
        sweep.append(cell(pace_window=pace))
        log(json.dumps(sweep[-1]))
    for frac in ([0.5] if smoke else [0.75, 0.5]):
        sweep.append(cell(select_frac=frac))
        log(json.dumps(sweep[-1]))
    sweep.append(cell(rate_limit_regional=0.02, rate_limit_global=0.005))
    log(json.dumps(sweep[-1]))

    return {
        "engine": "federation/megafleet.py (one jitted lax.scan, "
                  "ops/fleet_kernels.py)",
        "task": "consensus least-squares, hierarchical FedBuff, "
                f"{updates} updates/client, 10% stragglers at 10x",
        "parity_1k": parity,
        "parity_note": "flat merge count/version sequence/staleness "
                       "decisions are event-exact vs the heap; "
                       "hierarchical merge counts exact with loss "
                       "trajectory tolerance-bounded (aggregate "
                       "interleaving within one link_delay window) — "
                       "see docs/design.md 'megafleet'",
        "wall_clock": {"heap": heap_rows, "megafleet": mega_rows},
        "sweep_1m": {
            "n_clients": big_n,
            "start_loss": round(start_loss, 5),
            "target_loss": round(target, 5),
            "cells": sweep,
        },
        "smoke": smoke,
    }


def run_megafleet_chunks(smoke: bool = False) -> dict:
    """ISSUE 16: the chunked-event engine vs the per-event reference.

    Two parts: (a) an inline BIT-IDENTITY check on a flat fleet — the
    chunked engine's batched gather → segment-fold → predicated scatter
    must reproduce the per-event scan's every float (this is the pinned
    invariant, run here at a scale the test suite doesn't pay for); (b)
    the chunk-size sweep at the big hierarchical scale: clients/second
    per ``MEGAFLEET_CHUNK``, including the ``chunk=1`` per-event
    baseline row the ≥2× acceptance is measured against.
    """
    from p2pfl_tpu.federation.megafleet import FleetSpec, MegaFleet

    big_n = 50_000 if smoke else 1_000_000
    updates = 4

    # -- (a) flat bit-identity at 20k --
    pn = 5000 if smoke else 20_000
    pspec = FleetSpec.synth(pn, seed=SEED, slow_frac=0.10)

    def flat(chunk):
        return MegaFleet(
            pspec, cluster_size=0, k=32, updates_per_node=updates,
            local_lr=0.7, chunk=chunk,
        ).run()

    ref, got = flat(1), flat(256)
    identity = {
        "n_clients": pn,
        "merges_equal": got.merges == ref.merges,
        "loss_curve_bit_equal": got.loss_curve == ref.loss_curve,
        "params_bit_equal": bool(
            np.array_equal(got.params["w"], ref.params["w"])
        ),
    }
    log(json.dumps({"chunked_bit_identity": identity}))

    # -- (b) the chunk sweep at scale --
    spec = FleetSpec.synth(big_n, seed=SEED, slow_frac=0.10)
    rows = []
    chunks = [1, 64, 256] if smoke else [1, 64, 256, 512]
    for chunk in chunks:
        res = MegaFleet(
            spec, cluster_size=1024, k=64, updates_per_node=updates,
            local_lr=0.7, chunk=chunk,
        ).run()
        rows.append({
            "chunk": chunk, "n_clients": big_n,
            "wall_s": round(res.wall_s, 2),
            "clients_per_sec": int(res.clients_per_sec),
            "events_per_sec": int(res.n_events / max(res.wall_s, 1e-9)),
            "merges": res.merges, "regional_merges": res.regional_merges,
        })
        log(json.dumps(rows[-1]))
    base = rows[0]["clients_per_sec"]
    best = max(rows[1:], key=lambda r: r["clients_per_sec"])
    return {
        "engine": "run_fleet_program_chunked (ops/fleet_kernels.py)",
        "bit_identity_flat": identity,
        "sweep": rows,
        "speedup_best_vs_per_event": round(
            best["clients_per_sec"] / max(base, 1), 2
        ),
        "smoke": smoke,
    }


def run_megafleet_robust(smoke: bool = False) -> dict:
    """ISSUE 16: the robust-aggregation attacker sweep at fleet scale.

    Attacker fraction × corruption kind × window fold, every cell a full
    1M-client hierarchical drive with the attackers spread across
    clusters (stride placement, so elected regionals corrupt their
    aggregate sends too). The defense claim is measured, not asserted:
    trimmed-mean/median final losses vs fedavg's under the same attack.
    One cell re-runs at 1k against the heap driver (which flushes
    through ``Settings.ASYNC_ROBUST_AGG``) as the tolerance pin.
    """
    from p2pfl_tpu.communication.faults import ByzantineSpec, FaultPlan
    from p2pfl_tpu.federation.megafleet import FleetSpec, MegaFleet
    from p2pfl_tpu.federation.simfleet import SimulatedAsyncFleet
    from p2pfl_tpu.settings import Settings

    big_n = 20_000 if smoke else 1_000_000
    updates = 4
    width = max(4, len(str(big_n - 1)))
    spec = FleetSpec.synth(big_n, seed=SEED, slow_frac=0.10)

    def attack_plan(frac, kind):
        step = max(1, round(1.0 / frac))
        spec_kw = {"scale": {"lam": 50.0}, "noise": {"noise_std": 5.0}}.get(
            kind, {}
        )
        byz = {
            f"sim-{i:0{width}d}": ByzantineSpec(kind=kind, **spec_kw)
            for i in range(0, big_n, step)
        }
        return FaultPlan(seed=SEED, byzantine=byz)

    fracs = [0.10] if smoke else [0.05, 0.10, 0.20]
    kinds = ["sign_flip"] if smoke else ["sign_flip", "scale", "noise"]
    folds = ["fedavg", "median"] if smoke else [
        "fedavg", "trimmed-mean", "median"
    ]
    cells = []
    for frac in fracs:
        for kind in kinds:
            plan = attack_plan(frac, kind)
            for fold in folds:
                res = MegaFleet(
                    spec, cluster_size=1024, k=64, updates_per_node=updates,
                    local_lr=0.7, plan=plan, fold=fold,
                ).run()
                fl = res.final_loss()
                cells.append({
                    "attacker_frac": frac, "kind": kind, "fold": fold,
                    "final_loss": round(fl, 6) if np.isfinite(fl) else None,
                    "diverged": not bool(np.isfinite(fl)),
                    "merges": res.merges,
                    "byz_corrupted": res.byz_corrupted,
                    "wall_s": round(res.wall_s, 2),
                    "clients_per_sec": int(res.clients_per_sec),
                })
                log(json.dumps(cells[-1]))

    # -- the 1k heap pin: one cell, both drivers, same plan+fold --
    pin_kind, pin_fold, pin_frac = kinds[0], folds[-1], fracs[0]
    step = max(1, round(1.0 / pin_frac))
    pin_byz = {
        f"sim-{i:04d}": ByzantineSpec(kind=pin_kind)
        for i in range(0, 1000, step)
    }
    pin_plan = FaultPlan(seed=SEED, byzantine=pin_byz)
    old_fold = Settings.ASYNC_ROBUST_AGG
    try:
        Settings.ASYNC_ROBUST_AGG = pin_fold
        fleet = SimulatedAsyncFleet(
            1000, seed=SEED, cluster_size=32, updates_per_node=updates,
            slow_frac=0.10, local_lr=0.7, plan=pin_plan,
        )
        pspec = FleetSpec.from_sim(fleet)
        heap = fleet.run()
        mega = MegaFleet(
            pspec, cluster_size=32, updates_per_node=updates, local_lr=0.7,
            plan=pin_plan, fold=pin_fold,
        ).run()
    finally:
        Settings.ASYNC_ROBUST_AGG = old_fold
    hl = heap.final_loss()
    pin = {
        "n_clients": 1000, "kind": pin_kind, "fold": pin_fold,
        "attacker_frac": pin_frac,
        "merge_count_exact": mega.merges == heap.merges,
        "byz_corrupted_exact": mega.byz_corrupted == heap.byz_corrupted,
        "final_loss_rel_diff": round(
            abs(mega.final_loss() - hl) / max(hl, 1e-12), 6
        ),
    }
    log(json.dumps({"robust_pin_1k": pin}))
    return {
        "engine": "fold_window kind=trimmed-mean/median "
                  "(ops/fleet_kernels.py) == ops/aggregation."
                  "buffered_robust_merge's rank statistics",
        "attack": "stride-placed attackers (regionals corrupt aggregate "
                  "sends), scale lam=50, noise std=5",
        "cells": cells,
        "heap_pin_1k": pin,
        "smoke": smoke,
    }


def run_megafleet_sharded(smoke: bool = False) -> dict:
    """ISSUE 17: the device-mesh sharded engine vs single-device chunked.

    Three parts: (a) an inline BIT-IDENTITY check, flat and
    hierarchical — the sharded engine's only collective is a tiled
    ``all_gather`` (pure concatenation, no float reassociation), so
    every counter and every float must equal the single-device chunked
    engine's; (b) the device-count sweep at the big scale: clients/s
    for 1 (single-device chunked baseline) / 2 / 4 / 8 host devices;
    (c) the autotuned-vs-default chunk delta through
    ``ops/fleet_autotune.py``.

    Honesty note: host devices come from
    ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` and SHARE
    the machine's cores and memory bandwidth, so the sweep is a LOWER
    bound for real chips — the replicated admission scan runs once per
    device, and on a 1-core container the sweep measures pure sharding
    overhead (speedup < 1). ``cpu_count`` is recorded with the rows so
    the ratio can be read in context.
    """
    import jax

    from p2pfl_tpu.federation.megafleet import FleetSpec, MegaFleet
    from p2pfl_tpu.ops import fleet_autotune as ft
    from p2pfl_tpu.settings import Settings

    n_dev = jax.device_count()
    big_n = 50_000 if smoke else 1_000_000
    updates = 4

    # -- (a) bit-identity, flat and hierarchical --
    pn = 2000 if smoke else 20_000
    pspec = FleetSpec.synth(pn, seed=SEED, slow_frac=0.10)

    def parity_cell(cluster, shards):
        kw = dict(cluster_size=cluster, k=32, updates_per_node=updates,
                  local_lr=0.7, chunk=256)
        ref = MegaFleet(pspec, **kw).run()
        got = MegaFleet(pspec, shards=shards, **kw).run()
        cell = {
            "n_clients": pn, "cluster_size": cluster, "shards": shards,
            "merges_equal": got.merges == ref.merges,
            "loss_curve_bit_equal": got.loss_curve == ref.loss_curve,
            "params_bit_equal": bool(
                np.array_equal(got.params["w"], ref.params["w"])
            ),
        }
        log(json.dumps({"sharded_bit_identity": cell}))
        return cell

    parity = [parity_cell(0, min(2, n_dev))]
    if n_dev >= 8:
        parity.append(parity_cell(64, 8))

    # -- (b) device-count sweep at scale --
    spec = FleetSpec.synth(big_n, seed=SEED, slow_frac=0.10)

    def big(shards, chunk=256):
        return MegaFleet(
            spec, cluster_size=1024, k=64, updates_per_node=updates,
            local_lr=0.7, chunk=chunk, shards=shards,
        )

    rows = []
    for p in [None, 2, 4, 8]:
        if p is not None and p > n_dev:
            continue
        res = big(p).run()
        rows.append({
            "devices": 1 if p is None else p,
            "engine": "chunked" if p is None else "sharded",
            "n_clients": big_n,
            "wall_s": round(res.wall_s, 2),
            "clients_per_sec": int(res.clients_per_sec),
            "merges": res.merges,
        })
        log(json.dumps(rows[-1]))
    base = rows[0]["clients_per_sec"]
    for r in rows:
        r["speedup_vs_1dev"] = round(r["clients_per_sec"] / max(base, 1), 2)

    # -- (c) autotuned vs default chunk (scratch cache: measured fresh) --
    old_cache = Settings.FLEET_TUNE_CACHE
    Settings.FLEET_TUNE_CACHE = os.path.join(
        tempfile.mkdtemp(prefix="fleet_tune_"), "tune.json"
    )
    ft.clear_memory_cache()
    try:
        p_auto = min(2, n_dev) if n_dev > 1 else None
        auto = big(p_auto, chunk=0)
        res_auto = auto.run()
        res_def = big(p_auto, chunk=256).run()
        autotune = {
            "devices": 1 if p_auto is None else p_auto,
            "tuned_chunk": auto.chunk,
            "default_chunk": 256,
            "tuned_clients_per_sec": int(res_auto.clients_per_sec),
            "default_clients_per_sec": int(res_def.clients_per_sec),
            "delta": round(
                res_auto.clients_per_sec / max(res_def.clients_per_sec, 1e-9),
                2,
            ),
            "note": "tuned_clients_per_sec includes the one-time candidate "
                    "sweep on a bounded event prefix; a cached key replays "
                    "with zero measurements",
        }
    finally:
        Settings.FLEET_TUNE_CACHE = old_cache
        ft.clear_memory_cache()
    log(json.dumps({"autotune": autotune}))

    return {
        "engine": "run_fleet_program_sharded (ops/fleet_kernels.py, "
                  "shard_map over Settings.MESH_CLIENTS_AXIS)",
        "bit_identity": parity,
        "sweep": rows,
        "speedup_8dev_vs_1dev": next(
            (r["speedup_vs_1dev"] for r in rows if r["devices"] == 8), None
        ),
        "autotune": autotune,
        "cpu_count": os.cpu_count(),
        "scaling_note": "forced host devices share cores and memory "
                        "bandwidth — a LOWER bound for real chips; on a "
                        "1-core container the replicated admission scan "
                        "runs once PER device and the sweep measures pure "
                        "sharding overhead (speedup < 1); the bitwise "
                        "parity rows are the unconditional claim",
        "smoke": smoke,
    }


ALL_SECTIONS = (
    "threaded", "simulated", "churn", "restart", "byzantine", "megafleet",
    "megafleet_chunks", "megafleet_robust", "megafleet_sharded",
)


def main() -> int:
    smoke = "--smoke" in sys.argv
    out_path = "BENCH_ASYNC.json"
    if "--out" in sys.argv:
        out_path = sys.argv[sys.argv.index("--out") + 1]
    sections = ALL_SECTIONS
    if "--sections" in sys.argv:
        sections = tuple(sys.argv[sys.argv.index("--sections") + 1].split(","))
        unknown = set(sections) - set(ALL_SECTIONS)
        if unknown:
            log(f"unknown sections: {sorted(unknown)} (known: {ALL_SECTIONS})")
            return 2

    # partial runs merge into the existing document instead of dropping
    # the sections they didn't pay for
    doc = {}
    if sections != ALL_SECTIONS:
        try:
            with open(out_path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            doc = {}
    doc["bench"] = "async_federation_time_to_accuracy"
    if sections == ALL_SECTIONS:
        # partial runs must not relabel the merged document's untouched
        # sections; section_smoke below records each section's own grid
        doc["smoke"] = smoke
    for s in sections:
        doc.setdefault("section_smoke", {})[s] = smoke

    if "threaded" in sections:
        rows = []
        for mode in ("sync", "async", "hier"):
            log(f"=== threaded {mode} ===")
            row = run_threaded(mode, rounds=2 if smoke else 4)
            log(json.dumps(row))
            rows.append(row)
        sync_wall = next(r["wall_s"] for r in rows if r["mode"] == "sync")
        for r in rows:
            r["speedup_vs_sync"] = round(sync_wall / r["wall_s"], 2)
        doc["fleet"] = {
            "n_nodes": 10, "rounds": 2 if smoke else 4, "epochs": 1,
            "model": "mnist mlp (synthetic_mnist 8192/2048)",
            "plan": "seed=1905: 1 slow node (0.5s inbound weights), 1 crash "
                    "(round 1), 1% drop — small-fleet quantization of 10%/1%",
            "target_acc": TARGET_ACC,
            "budget_note": "rounds == async local updates: identical total "
                           "local training in every mode",
        }
        doc["threaded"] = rows

    if "simulated" in sections:
        log("=== simulated 1k ===")
        doc["simulated_1k"] = run_simulated(smoke=smoke)

    if "churn" in sections:
        log("=== churn 1k ===")
        doc["churn_1k"] = run_churn(smoke=smoke)

    if "restart" in sections:
        log("=== restart 1k ===")
        doc["restart_1k"] = run_restart(smoke=smoke)

    if "byzantine" in sections:
        log("=== byzantine 1k ===")
        doc["byzantine_1k"] = run_byzantine(smoke=smoke)

    if "megafleet" in sections:
        log("=== megafleet ===")
        doc["megafleet_1m"] = run_megafleet(smoke=smoke)

    if "megafleet_chunks" in sections:
        log("=== megafleet chunk sweep ===")
        doc["megafleet_chunks"] = run_megafleet_chunks(smoke=smoke)

    if "megafleet_robust" in sections:
        log("=== megafleet robust-agg attacker sweep ===")
        doc["megafleet_robust"] = run_megafleet_robust(smoke=smoke)

    if "megafleet_sharded" in sections:
        log("=== megafleet sharded device sweep ===")
        doc["megafleet_sharded"] = run_megafleet_sharded(smoke=smoke)

    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    log(f"wrote {out_path}")
    summary = {"metric": "bench_async", "sections": list(sections)}
    if "threaded" in doc:
        summary.update({r["mode"]: r["wall_s"] for r in doc["threaded"]})
    if "megafleet_1m" in doc:
        mrows = doc["megafleet_1m"]["wall_clock"]["megafleet"]
        summary["megafleet_clients_per_sec"] = mrows[-1]["clients_per_sec"]
    if "megafleet_chunks" in doc:
        summary["chunked_speedup"] = (
            doc["megafleet_chunks"]["speedup_best_vs_per_event"]
        )
    if "megafleet_robust" in doc:
        summary["robust_cells"] = len(doc["megafleet_robust"]["cells"])
    if "megafleet_sharded" in doc:
        summary["sharded_speedup_8dev"] = (
            doc["megafleet_sharded"]["speedup_8dev_vs_1dev"]
        )
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
