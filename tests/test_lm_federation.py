"""Federations that train THROUGH MoE expert parallelism and GPipe.

VERDICT r2 weak #3: the ep/pp axes compiled (unit tests + dryrun grad
steps) but no federation trained through them end to end. These tests run
real multi-round federated training on the 8-device virtual mesh:
``SpmdLmFederation`` (dp × ep in one dispatch) and ``PipelineFederation``
(nodes time-sharing a GPipe mesh, host FedAvg between rounds).
"""

import jax
import numpy as np
import pytest

from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer
from p2pfl_tpu.parallel import PipelineFederation, SpmdLmFederation
from p2pfl_tpu.settings import Settings


def _moe_cfg(**kw):
    base = dict(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=4,
        ffn_hidden=128, lora_rank=0, n_experts=4, moe_top_k=2,
    )
    base.update(kw)
    return TransformerConfig(**base)


def test_moe_federation_expert_leaves_shard_over_model_axis():
    """dp × ep state layout: expert stacks [N, E, ...] carry
    P(nodes, model); routers and norms replicate over model."""
    m = tiny_transformer(seq_len=32, cfg=_moe_cfg())
    data = FederatedDataset.synthetic_lm(n_train=4 * 64, n_test=64, seq_len=32, vocab_size=256)
    fed = SpmdLmFederation.from_dataset(
        m, data, n_nodes=4, batch_size=16, vote=False, expert_parallel=2
    )
    assert dict(fed.mesh.shape) == {
        Settings.MESH_NODES_AXIS: 4,
        Settings.MESH_MODEL_AXIS: 2,
    }
    specs = {
        "/".join(str(getattr(k, "key", k)) for k in path): leaf.sharding.spec
    for path, leaf in jax.tree_util.tree_flatten_with_path(fed.params)[0]}
    nodes, model = Settings.MESH_NODES_AXIS, Settings.MESH_MODEL_AXIS
    assert specs["layer_0/mlp/w1"][:2] == (nodes, model)  # experts sharded
    assert specs["layer_0/mlp/w2"][:2] == (nodes, model)
    assert tuple(specs["layer_0/mlp/router"]) == (nodes,)  # router replicated
    assert tuple(specs["layer_0/attn_norm/scale"]) == (nodes,)
    # the Megatron TP rules apply to the DENSE weights too — attention
    # projections are column-parallel over the same model axis, so this
    # runtime is really dp × tp × ep in one program
    assert tuple(specs["layer_0/attn/wq/kernel"]) == (nodes, None, model)
    assert tuple(specs["layer_0/attn/wo/kernel"]) == (nodes, model, None)


@pytest.mark.slow
def test_moe_federation_trains_with_expert_parallelism():
    """4 nodes × 2-way expert parallelism, 4 federated rounds: the loss
    trajectory falls and next-token accuracy clears the floor — the MoE
    routers learn THROUGH the federation (aux balance loss included)."""
    m = tiny_transformer(seq_len=32, cfg=_moe_cfg())
    data = FederatedDataset.synthetic_lm(n_train=4 * 128, n_test=128, seq_len=32, vocab_size=256)
    fed = SpmdLmFederation.from_dataset(
        m, data, n_nodes=4, batch_size=16, vote=False, expert_parallel=2, seed=0
    )
    losses = [float(fed.run_round(epochs=1)["train_loss"]) for _ in range(4)]
    assert losses[-1] < losses[0] - 0.3, losses
    acc = fed.evaluate()["test_acc"]
    assert acc > 0.3, acc  # vocab 256 → chance is ~0.004


@pytest.mark.slow
def test_moe_federation_nodes_stay_synchronized():
    """After a round every node's slot holds the SAME aggregated params
    (broadcast over the node axis) — the mesh analogue of
    check_equal_models."""
    m = tiny_transformer(seq_len=32, cfg=_moe_cfg())
    data = FederatedDataset.synthetic_lm(n_train=4 * 64, n_test=64, seq_len=32, vocab_size=256)
    fed = SpmdLmFederation.from_dataset(
        m, data, n_nodes=4, batch_size=16, vote=False, expert_parallel=2
    )
    fed.run_round(epochs=1)
    leaf = np.asarray(jax.tree.leaves(fed.params)[0])
    for i in range(1, leaf.shape[0]):
        np.testing.assert_allclose(leaf[i], leaf[0], atol=1e-6)


@pytest.mark.slow
def test_lm_fused_matches_sequential():
    """R fused rounds (one dispatch) must reproduce R sequential rounds
    exactly — same perms, same aggregation, just amortized dispatch."""
    m = tiny_transformer(seq_len=32, cfg=_moe_cfg())
    data = FederatedDataset.synthetic_lm(n_train=4 * 64, n_test=64, seq_len=32, vocab_size=256)
    kw = dict(n_nodes=4, batch_size=16, vote=False, expert_parallel=2, seed=5)
    fed_a = SpmdLmFederation.from_dataset(m, data, **kw)
    fed_b = SpmdLmFederation.from_dataset(m, data, **kw)
    for _ in range(3):
        fed_a.run_round(epochs=1)
    fed_b.run_fused(3, epochs=1)
    for a, b in zip(jax.tree.leaves(fed_a.params), jax.tree.leaves(fed_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


@pytest.mark.slow
def test_lm_federation_checkpoint_roundtrip(tmp_path):
    """save/restore carries the MoE federation's params + opt state; a
    fresh federation restored from the checkpoint continues identically."""
    m = tiny_transformer(seq_len=32, cfg=_moe_cfg())
    data = FederatedDataset.synthetic_lm(n_train=4 * 64, n_test=64, seq_len=32, vocab_size=256)
    kw = dict(n_nodes=4, batch_size=16, vote=False, expert_parallel=2, seed=5)
    fed = SpmdLmFederation.from_dataset(m, data, **kw)
    fed.run_round(epochs=1)
    fed.save(str(tmp_path / "lmfed"))

    fed2 = SpmdLmFederation.from_dataset(
        tiny_transformer(seq_len=32, cfg=_moe_cfg(), seed=9), data, **kw
    )
    fed2.restore(str(tmp_path / "lmfed"))
    assert fed2.round == 1
    for a, b in zip(jax.tree.leaves(fed.params), jax.tree.leaves(fed2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_pipeline_federation_trains():
    """2 nodes × 4-stage GPipe pipeline: rounds reduce the loss and the
    post-federation model beats the initial one."""
    cfg = TransformerConfig(
        vocab_size=256, dim=64, n_layers=4, n_heads=4, n_kv_heads=4,
        ffn_hidden=128, lora_rank=0,
    )
    m = tiny_transformer(seq_len=32, cfg=cfg)
    data = FederatedDataset.synthetic_lm(n_train=2 * 128, n_test=64, seq_len=32, vocab_size=256)
    shards = [data.partition(i, 2) for i in range(2)]
    fed = PipelineFederation(m, shards, n_stages=4, batch_size=8, seed=0)
    acc0 = fed.evaluate()["test_acc"]
    losses = [fed.run_round(epochs=1)["train_loss"] for _ in range(3)]
    assert losses[-1] < losses[0] - 0.2, losses
    acc = fed.evaluate()["test_acc"]
    assert acc > acc0 + 0.05, (acc0, acc)


@pytest.mark.slow
def test_pipelined_moe_federation_trains():
    """The full composition: MoE blocks inside a GPipe pipeline inside a
    federation — router aux losses ride the pipeline (return_aux) and the
    federation still learns."""
    cfg = _moe_cfg(n_layers=4)
    m = tiny_transformer(seq_len=32, cfg=cfg)
    data = FederatedDataset.synthetic_lm(n_train=2 * 96, n_test=64, seq_len=32, vocab_size=256)
    shards = [data.partition(i, 2) for i in range(2)]
    fed = PipelineFederation(m, shards, n_stages=4, batch_size=8, seed=0)
    losses = [fed.run_round(epochs=1)["train_loss"] for _ in range(3)]
    assert losses[-1] < losses[0] - 0.15, losses


def test_pipeline_federation_zero_batch_round_is_safe():
    """A round that yields zero batches (epochs=0) must not let a None loss
    reach the mean/`block_until_ready` (ADVICE r5: spmd_lm.py run_round);
    the round records NaN and the params stay the untouched global."""
    import math

    cfg = TransformerConfig(
        vocab_size=256, dim=32, n_layers=2, n_heads=2, n_kv_heads=2,
        ffn_hidden=64, lora_rank=0,
    )
    m = tiny_transformer(seq_len=16, cfg=cfg)
    data = FederatedDataset.synthetic_lm(n_train=2 * 16, n_test=16, seq_len=16, vocab_size=256)
    shards = [data.partition(i, 2) for i in range(2)]
    fed = PipelineFederation(m, shards, n_stages=2, batch_size=8, seed=0)
    entry = fed.run_round(epochs=0, profile=True)
    assert math.isnan(entry["train_loss"])
    # undersized shards are still rejected loudly at construction
    with pytest.raises(ValueError, match="batch size"):
        PipelineFederation(m, [data.partition(0, 2)], n_stages=2, batch_size=64, seed=0)
