"""DCN weights plane (ISSUE 18): cross-process model diffusion as device
arrays over XLA cross-host collectives — ``Settings.WEIGHTS_PLANE="dcn"``.

Two layers of coverage:

- **Fast unit tests** on the wire-metadata codecs, the world directory's
  TTL cache, the ``try_dcn_send`` eligibility ladder, the receiver's nack
  ladder, verb-command robustness and the analyzer's scope over the new
  modules — all in-process, no distributed runtime.
- **Slow 2-process witnesses** (subprocess workers, like
  ``test_multihost.py``): a real federation whose model payloads cross the
  process boundary with ZERO pickled weight bytes on gRPC and whose final
  params match a byte-plane control fleet bit-close; direct transfer
  parity (raw fp32/bf16 bit-exact, int8/topk8 codec vs the byte decoder);
  the per-edge ICI → DCN → bytes selection matrix with a
  directory-withdrawn node; and a hard process kill of the async global
  root, exercising TierRouter failover while the plane's rendezvous
  timeouts degrade the dead edges loudly.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from pathlib import Path
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import p2pfl_tpu
from p2pfl_tpu.communication import dcn
from p2pfl_tpu.communication.message import WeightsEnvelope
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.parallel import dcn_plane
from p2pfl_tpu.parallel.ici_plane import SliceInfo, slice_info_of
from p2pfl_tpu.settings import Settings

PKG = Path(p2pfl_tpu.__file__).parent


# ---- wire metadata codecs ----


def test_spec_wire_roundtrip():
    for spec in (P(), P("m"), P(None, "m"), P(("a", "b"), None), P("a", None, "b")):
        wire = dcn_plane.spec_to_wire(spec)
        json.dumps(wire)  # must be JSON-serializable as-is
        assert dcn_plane.spec_from_wire(wire) == spec


def test_mesh_wire_roundtrip_and_unknown_ids():
    devs = jax.devices()[:2]
    mesh = Mesh(np.array(devs), ("m",))
    info = SliceInfo(mesh=mesh, specs=())
    meta = dcn_plane.mesh_wire_meta(info)
    json.dumps(meta)
    back = dcn_plane.mesh_from_ids(meta["ids"], meta["shape"], meta["axes"])
    assert back is not None
    assert list(back.devices.flat) == list(mesh.devices.flat)
    assert back.axis_names == mesh.axis_names
    # an id outside this world's device list must refuse, not crash
    assert dcn_plane.mesh_from_ids([10**9], [1], ["m"]) is None
    # a single-process world: every local slice is process-local
    assert dcn_plane.process_local(info)


def test_spec_to_wire_key_hashable():
    k = dcn.spec_to_wire_key(P(("a", "b"), None, "c"))
    assert k == (("a", "b"), None, "c")
    hash(k)


# ---- world directory ----


class _FakeKV:
    def __init__(self):
        self.store = {}
        self.dir_reads = 0

    def key_value_set(self, key, val):
        if key in self.store:
            raise RuntimeError("key exists")
        self.store[key] = val

    def key_value_delete(self, key):
        if key not in self.store:
            raise KeyError(key)
        del self.store[key]

    def key_value_dir_get(self, prefix):
        self.dir_reads += 1
        return [(k, v) for k, v in self.store.items() if k.startswith(prefix)]


def test_world_directory_publish_lookup_ttl(monkeypatch):
    fake = _FakeKV()
    monkeypatch.setattr(dcn, "kv_client", lambda: fake)
    monkeypatch.setattr(dcn, "world_active", lambda: True)
    d = dcn.WorldDirectory()
    d.publish("n1:100")
    assert d.lookup("n1:100") == {"pi": int(jax.process_index())}
    reads = fake.dir_reads
    # served from the TTL snapshot: no second directory read
    assert d.lookup("n1:100") is not None
    assert d.lookup("missing:1") is None
    assert fake.dir_reads == reads
    # withdraw invalidates the snapshot — the next lookup re-reads and
    # no longer sees the entry
    d.withdraw("n1:100")
    assert d.lookup("n1:100") is None
    assert fake.dir_reads == reads + 1
    # re-publish over a stale entry (restarted node) must not raise even
    # though the fake's set is not an upsert
    d.publish("n1:100")
    d.publish("n1:100")
    assert d.lookup("n1:100") is not None


def test_world_directory_tolerates_bad_entries(monkeypatch):
    fake = _FakeKV()
    fake.store[dcn._DIR_PREFIX + "good:1"] = json.dumps({"pi": 0})
    fake.store[dcn._DIR_PREFIX + "bad:1"] = "{not json"
    monkeypatch.setattr(dcn, "kv_client", lambda: fake)
    d = dcn.WorldDirectory()
    assert d.lookup("good:1") == {"pi": 0}
    assert d.lookup("bad:1") is None


# ---- try_dcn_send eligibility ladder ----


def _env(params):
    return WeightsEnvelope(
        "src:1", 0, "add_model", ModelUpdate(params, ["src:1"], 1)
    )


def test_try_dcn_send_silent_when_plane_off():
    dcn.reset_dcn_stats()
    proto = SimpleNamespace(get_address=lambda: "src:1")
    assert Settings.WEIGHTS_PLANE == "bytes"  # set_test_settings default
    assert dcn.try_dcn_send(proto, "peer:2", _env({"w": jnp.ones((4,))})) is None
    # not an eligibility failure — the plane simply isn't on
    assert dcn.dcn_stats()["fallback_bytes"] == 0


def test_try_dcn_send_loud_fallback_without_world():
    dcn.reset_dcn_stats()
    proto = SimpleNamespace(get_address=lambda: "src:1")
    Settings.WEIGHTS_PLANE = "dcn"
    # this test process runs no jax.distributed world: the edge must fall
    # back LOUDLY (counted), not silently
    assert dcn.try_dcn_send(proto, "peer:2", _env({"w": jnp.ones((4,))})) is None
    assert dcn.dcn_stats()["fallback_bytes"] == 1
    # pre-encoded relay frames (no live params) stay silent — bytes is
    # their only possible transport
    env = WeightsEnvelope("src:1", 0, "add_model", ModelUpdate(None, ["src:1"], 1))
    assert dcn.try_dcn_send(proto, "peer:2", env) is None
    assert dcn.dcn_stats()["fallback_bytes"] == 1


# ---- receiver-side nack ladder ----


class _VerbTap:
    """A protocol stub that records the rendezvous verbs sent through it."""

    def __init__(self, addr):
        self.addr = addr
        self.sent = []

    def get_address(self):
        return self.addr

    def _do_send(self, nei, msg, create_connection=False):
        self.sent.append((nei, msg))
        return True


def _offer_to(node, meta=None):
    plane = dcn.DcnPlane.instance()
    plane.on_offer(node, "peer:9", {"tid": "t-test", **(meta or {})})
    nei, msg = node.protocol.sent[-1]
    assert nei == "peer:9"
    return msg.cmd, json.loads(msg.args[0])


def test_on_offer_nack_ladder(monkeypatch):
    dcn.DcnPlane.reset()
    dcn.reset_dcn_stats()
    node = SimpleNamespace(
        protocol=_VerbTap("me:1"), addr="me:1", _running=True, learner=None
    )
    try:
        # plane off
        assert Settings.WEIGHTS_PLANE == "bytes"
        cmd, meta = _offer_to(node)
        assert (cmd, meta["reason"]) == ("dcn_nack", "plane_off")
        # no distributed world (real: this process runs none)
        Settings.WEIGHTS_PLANE = "dcn"
        cmd, meta = _offer_to(node)
        assert (cmd, meta["reason"]) == ("dcn_nack", "no_distributed_world")
        # world up, but no learner on the target node
        monkeypatch.setattr(dcn, "world_active", lambda: True)
        cmd, meta = _offer_to(node)
        assert (cmd, meta["reason"]) == ("dcn_nack", "peer_not_ready")
        # architecture mismatch: shapes in the offer differ from ours
        node.learner = SimpleNamespace(
            get_parameters=lambda: {"w": jnp.ones((4,), jnp.float32)}
        )
        cmd, meta = _offer_to(node, {"model": [["w", [8], "float32"]]})
        assert (cmd, meta["reason"]) == ("dcn_nack", "architecture_mismatch")
        # a "peer" claiming our own devices: same process is ICI territory
        info = slice_info_of({"w": jax.device_put(jnp.ones((4,), jnp.float32))})
        cmd, meta = _offer_to(
            node,
            {
                "model": [["w", [4], "float32"]],
                "mesh": dcn_plane.mesh_wire_meta(info),
            },
        )
        assert (cmd, meta["reason"]) == ("dcn_nack", "same_process")
        assert dcn.dcn_stats()["nacks"] == 5
        # every refusal stayed on the control plane: nack verbs only
        assert all(m.cmd == "dcn_nack" for _n, m in node.protocol.sent)
        assert all(m.ttl == 1 for _n, m in node.protocol.sent)
    finally:
        dcn.DcnPlane.reset()


def test_on_accept_unknown_tid_aborts_peer():
    dcn.DcnPlane.reset()
    try:
        tap = _VerbTap("me:1")
        node = SimpleNamespace(protocol=tap, addr="me:1")
        dcn.DcnPlane.instance().on_accept(node, "peer:9", {"tid": "never-offered"})
        nei, msg = tap.sent[-1]
        assert msg.cmd == "dcn_abort"
        assert json.loads(msg.args[0])["reason"] == "unknown_tid"
        # late verbs for unknown transfers are ignored, never raise
        plane = dcn.DcnPlane.instance()
        for h in (plane.on_nack, plane.on_done, plane.on_ready, plane.on_abort):
            h(node, "peer:9", {"tid": "never-offered"})
    finally:
        dcn.DcnPlane.reset()


# ---- verb command robustness ----


def test_verb_commands_tolerate_malformed_metadata():
    from p2pfl_tpu.commands.dcn import DCN_COMMANDS, DcnOfferCommand

    node = SimpleNamespace(addr="me:1", protocol=None)
    cmd = DcnOfferCommand(node)
    # none of these may raise or reach the plane
    cmd.execute("peer:9", 0)  # no metadata arg
    cmd.execute("peer:9", 0, "{not json")
    cmd.execute("peer:9", 0, json.dumps([1, 2, 3]))  # not a dict
    cmd.execute("peer:9", 0, json.dumps({"no": "tid"}))
    names = sorted(c.get_name() for c in DCN_COMMANDS)
    assert names == sorted(dcn.DCN_VERBS)


# ---- analyzer scope over the new modules ----


def test_hostgather_covers_dcn_modules():
    """The no-host-gather contract extends to the DCN plane: both shipped
    modules are clean, and re-introducing a host gather into either is
    caught — same teeth idiom as test_analysis.py's ICI coverage."""
    from p2pfl_tpu.analysis import analyze
    from p2pfl_tpu.analysis.rules import NoHostGatherRule

    src = (PKG / "communication" / "dcn.py").read_text()
    assert analyze([], [NoHostGatherRule], sources={"communication/dcn.py": src}) == []
    needle = "    plane = DcnPlane.instance()\n"
    mutated = src.replace(
        needle,
        needle + "    _probe = np.asarray(jax.tree.leaves(update.params)[0])\n",
        1,
    )
    assert mutated != src
    found = analyze([], [NoHostGatherRule], sources={"communication/dcn.py": mutated})
    assert any(f.rule == "no-host-gather" and "np.asarray" in f.message for f in found)

    glue = (PKG / "parallel" / "dcn_plane.py").read_text()
    assert analyze([], [NoHostGatherRule], sources={"parallel/dcn_plane.py": glue}) == []
    gneedle = "    leaves = jax.tree.leaves(local_tree)\n"
    gmut = glue.replace(
        gneedle, gneedle + "    _host = [x.tobytes() for x in leaves]\n", 1
    )
    assert gmut != glue
    gfound = analyze([], [NoHostGatherRule], sources={"parallel/dcn_plane.py": gmut})
    assert any(".tobytes()" in f.message for f in gfound)


# ---- 2-process witnesses (subprocess workers, gloo CPU collectives) ----

_PROLOGUE = r"""
import os, sys, time, threading
os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the chip tunnel
os.environ["JAX_PLATFORMS"] = "cpu"
pid = int(sys.argv[1])
os.environ["JAX_COORDINATOR_ADDRESS"] = "127.0.0.1:%PORT%"
os.environ["JAX_NUM_PROCESSES"] = "2"
os.environ["JAX_PROCESS_ID"] = str(pid)

from p2pfl_tpu.parallel.distributed import init_multihost, kv_client

info = init_multihost()
assert info["initialized"] and info["process_count"] == 2, info

import jax
import jax.numpy as jnp
import numpy as np

from p2pfl_tpu.settings import Settings, set_test_settings

set_test_settings()

from p2pfl_tpu.communication.dcn import DcnPlane, dcn_stats, reset_dcn_stats, try_dcn_send
from p2pfl_tpu.communication.grpc_transport import GrpcProtocol
from p2pfl_tpu.communication.message import WeightsEnvelope
from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import JaxLearner
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.models import mlp
from p2pfl_tpu.node import Node
from p2pfl_tpu.utils import wait_to_finish

base = %PORT%
_client = kv_client()

def barrier(name):
    _client.wait_at_barrier("dcn_t_" + name, 120_000)

def connect_retry(node, addr, tries=150):
    for _ in range(tries):
        # connect() refuses an ALREADY-connected peer — when both ends of
        # an edge dial (or the peer's handshake beat us to it), membership
        # is the success condition, not the dial
        if node.connect(addr) or addr in node.get_neighbors(only_direct=True):
            return
        time.sleep(0.1)
    raise RuntimeError(f"never connected to {addr}")

def wait_neighbors(nodes, n, wait=30):
    deadline = time.time() + wait
    while any(len(x.get_neighbors(only_direct=True)) < n for x in nodes):
        if time.time() > deadline:
            raise RuntimeError("neighbor convergence timeout")
        time.sleep(0.1)

def worst_diff(a_tree, b_tree):
    worst = 0.0
    for a, b in zip(jax.tree.leaves(a_tree), jax.tree.leaves(b_tree)):
        a32 = np.asarray(a, dtype=np.float32)
        b32 = np.asarray(b, dtype=np.float32)
        worst = max(worst, float(np.max(np.abs(a32 - b32))))
    return worst
"""


_FED_WORKER = _PROLOGUE + r"""
def run_fleet(tag, plane, port_off):
    Settings.WEIGHTS_PLANE = plane
    my_addr = f"127.0.0.1:{base + port_off + pid}"
    peer_addr = f"127.0.0.1:{base + port_off + 1 - pid}"
    full = FederatedDataset.synthetic_mnist(n_train=256, n_test=64, seed=7)
    learner = JaxLearner(mlp(seed=pid), full.partition(pid, 2), batch_size=32)
    node = Node(learner=learner, protocol=GrpcProtocol(my_addr))
    node.start()
    barrier(tag + "_up")
    if pid == 0:
        connect_retry(node, peer_addr)
    wait_neighbors([node], 1)
    if pid == 0:
        node.set_start_learning(rounds=2, epochs=1)
    wait_to_finish([node], timeout=180)
    params = jax.tree.map(lambda x: np.asarray(x), learner.get_parameters())
    wire = dict(node.protocol.wire_stats)
    node.stop()
    barrier(tag + "_down")
    return params, wire

reset_dcn_stats()
dcn_params, dcn_wire = run_fleet("dcn", "dcn", 1)
stats = dcn_stats()
print(f"STATS {pid}: dcn={stats} wire_weights_bytes={dcn_wire.get('weights_bytes', 0)}")
# the tentpole claims, per process: device payloads moved both ways, ZERO
# pickled model bytes on gRPC, and no silent per-edge fallback
assert stats["dcn_sends"] > 0 and stats["dcn_recvs"] > 0, stats
assert stats["bytes_moved"] > 0, stats
assert stats["fallback_bytes"] == 0, stats
assert dcn_wire.get("weights_bytes", 0) == 0, dcn_wire

# control fleet: same overlay, same seeds, same rounds, byte transport
byte_params, byte_wire = run_fleet("bytes", "bytes", 3)
assert dcn_stats()["dcn_sends"] == stats["dcn_sends"], "byte fleet leaked onto the DCN plane"
assert byte_wire.get("weights_bytes", 0) > 0, byte_wire

# transport equivalence: the two fleets must land bit-close
worst = worst_diff(dcn_params, byte_params)
assert worst <= 1e-4, f"DCN vs byte fleet diverged: {worst}"

# and BOTH processes hold the same diffused aggregate
from jax.experimental.multihost_utils import process_allgather
fp = sum(float(np.sum(np.abs(x))) for x in jax.tree.leaves(dcn_params))
got = process_allgather(jnp.float32(fp))
assert float(got[0]) == float(got[1]), got
print(f"OK fed process {pid}: parity worst {worst:.2e} fingerprint {fp:.6f}")
"""


_XFER_WORKER = _PROLOGUE + r"""
Settings.WEIGHTS_PLANE = "dcn"
my_addr = f"127.0.0.1:{base + 1 + pid}"
peer_addr = f"127.0.0.1:{base + 2 - pid}"
data = FederatedDataset.synthetic_mnist(n_train=64, n_test=16, seed=3)
learner = JaxLearner(mlp(seed=0), data.partition(pid, 2), batch_size=16)
node = Node(learner=learner, protocol=GrpcProtocol(my_addr))

captured = []
evt = threading.Event()

class CaptureCommand:
    # a pass-through data-plane command: records what the DCN plane
    # DELIVERED, outside any experiment gating
    @staticmethod
    def get_name():
        return "dcn_capture"

    def execute(self, source, round, update=None, xp=None, **kw):
        captured.append(update)
        evt.set()

node.protocol.add_command(CaptureCommand())
node.start()
barrier("xfer_up")

tmpl = learner.get_parameters()

def filled(scale, dtype=None):
    leaves, treedef = jax.tree.flatten(tmpl)
    out = []
    for i, x in enumerate(leaves):
        v = (jnp.arange(x.size, dtype=jnp.float32).reshape(x.shape) + i) * scale
        out.append(v.astype(dtype or x.dtype))
    return jax.tree.unflatten(treedef, out)

def send(anchor=None, tag=None):
    upd = ModelUpdate(learner.get_parameters(), [my_addr], 1)
    if anchor is not None:
        upd.anchor = anchor
        upd.anchor_tag = tag
    env = WeightsEnvelope(my_addr, 0, "dcn_capture", upd)
    return try_dcn_send(node.protocol, peer_addr, env)

def received():
    assert evt.wait(30), "transfer never delivered"
    evt.clear()
    return captured[-1].params

# case 1: raw fp32 — bit-exact across the collective
exp = filled(1e-3)
learner.set_parameters(exp)
barrier("c1_set")
if pid == 0:
    assert send() is True
    s = dcn_stats()
    assert s["dcn_sends"] == 1 and s["bytes_moved"] > 0, s
else:
    assert worst_diff(received(), exp) == 0.0
barrier("c1_done")

# case 2: bf16 — dtype survives end to end, still bit-exact
exp = filled(2e-3, jnp.bfloat16)
learner.set_parameters(exp)
barrier("c2_set")
if pid == 0:
    assert send() is True
else:
    got = received()
    assert all(x.dtype == jnp.bfloat16 for x in jax.tree.leaves(got)
               if jnp.issubdtype(x.dtype, jnp.floating)), "dtype lost in transfer"
    assert worst_diff(got, exp) == 0.0
barrier("c2_done")

# case 3: dense int8 codec on the DCN leg — quantization-bounded
Settings.WIRE_COMPRESSION = "int8"
exp = filled(1e-3)
learner.set_parameters(exp)
barrier("c3_set")
if pid == 0:
    assert send() is True
else:
    got = received()
    for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(exp)):
        b32 = np.asarray(b, dtype=np.float32)
        tol = float(np.max(np.abs(b32))) / 127.0 + 1e-7
        np.testing.assert_allclose(np.asarray(a, dtype=np.float32), b32, atol=tol)
barrier("c3_done")

# case 4: topk8 with a MISMATCHED receiver anchor — the offer is nacked
# (anchor_round_mismatch) and the sender falls back loudly
Settings.WIRE_COMPRESSION = "topk8"
exp = filled(3e-3)
anchor = jax.tree.map(jnp.zeros_like, tmpl)
learner.set_parameters(exp)
if pid == 1:
    learner.set_wire_anchor(anchor, "9:9")
barrier("c4_set")
if pid == 0:
    before = dcn_stats()["fallback_bytes"]
    assert send(anchor=anchor, tag="0:7") is None
    s = dcn_stats()
    assert s["fallback_bytes"] == before + 1, s
barrier("c4_done")
if pid == 1:
    assert dcn_stats()["nacks"] >= 1, dcn_stats()

# case 5: topk8 with matching anchors — parity with the byte codec's
# decode of the same update (the one shared decoder contract)
from p2pfl_tpu.learning import weights as W
if pid == 1:
    learner.set_wire_anchor(anchor, "0:7")
barrier("c5_set")
if pid == 0:
    assert send(anchor=anchor, tag="0:7") is True
else:
    got = received()
    blob = W.encode_params(exp, compression="topk8", anchor=anchor, anchor_tag="0:7")
    ref = W.decode_params(blob, anchor=anchor, anchor_tag="0:7")
    assert worst_diff(got, ref) <= 1e-6
barrier("c5_done")

node.stop()
print(f"OK xfer process {pid}")
"""


_MATRIX_WORKER = _PROLOGUE + r"""
Settings.WEIGHTS_PLANE = "dcn"
from p2pfl_tpu.communication.ici import ici_stats

# four nodes, two per process: A,B on p0; C,D on p1. Every edge class in
# one fleet — co-resident (ICI), cross-process same-world (DCN), and a
# directory-withdrawn node whose inbound edges must fall back to bytes.
addrs = [f"127.0.0.1:{base + 1 + i}" for i in range(4)]
mine = addrs[2 * pid: 2 * pid + 2]
data = FederatedDataset.synthetic_mnist(n_train=256, n_test=32, seed=7)
nodes = []
for j, addr in enumerate(mine):
    idx = 2 * pid + j
    learner = JaxLearner(mlp(seed=idx), data.partition(idx, 4), batch_size=32)
    n = Node(learner=learner, protocol=GrpcProtocol(addr))
    n.start()
    nodes.append(n)
barrier("matrix_up")
for n in nodes:
    for other in addrs:
        if other > n.addr:  # one dialer per edge; links are bidirectional
            connect_retry(n, other)
wait_neighbors(nodes, 3)

# D (addrs[3]) leaves the world directory: senders can no longer place it
# and must degrade those edges to bytes — loudly, per edge
if pid == 1:
    DcnPlane.instance().withdraw_node(addrs[3])
barrier("matrix_withdrawn")
time.sleep(2 * Settings.DCN_DIR_TTL_S)  # let cached snapshots expire

if pid == 0:
    nodes[0].set_start_learning(rounds=1, epochs=1)
wait_to_finish(nodes, timeout=180)

s = dcn_stats()
ici = ici_stats()
wire = sum(dict(n.protocol.wire_stats).get("weights_bytes", 0) for n in nodes)
print(f"MATRIX {pid}: dcn={s} ici_shard_sends={ici['shard_sends']} wire_weights_bytes={wire}")
assert ici["shard_sends"] > 0, ici  # the co-resident pair rode ICI
assert s["dcn_sends"] > 0, s        # cross-process peers rode DCN
if pid == 0:
    assert s["fallback_bytes"] > 0, s  # edges to the withdrawn node fell back...
    assert wire > 0, wire              # ...and actually moved pickled bytes

# mixed transports, one outcome: all four nodes hold the same aggregate
fps = [sum(float(np.sum(np.abs(np.asarray(x, dtype=np.float32))))
           for x in jax.tree.leaves(n.learner.get_parameters())) for n in nodes]
assert abs(fps[0] - fps[1]) <= 1e-3 * max(1.0, abs(fps[0])), fps
from jax.experimental.multihost_utils import process_allgather
got = process_allgather(jnp.float32(fps[0]))
assert abs(float(got[0]) - float(got[1])) <= 1e-3 * max(1.0, abs(float(got[0]))), got
for n in nodes:
    n.stop()
print(f"OK matrix process {pid}")
"""


_KILL_WORKER = _PROLOGUE + r"""
Settings.WEIGHTS_PLANE = "dcn"
Settings.FEDERATION_MODE = "async"
Settings.FEDBUFF_K = 2

# the victim (pid 1) takes the LOWER-sorting address: federation/routing.py
# elects the first live member in address order as global root, so killing
# that process forces the survivor through TierRouter root failover while
# the DCN plane's rendezvous timeouts degrade the dead edges
my_addr = f"127.0.0.1:{base + 2 - pid}"
peer_addr = f"127.0.0.1:{base + 1 + pid}"
full = FederatedDataset.synthetic_mnist(n_train=256, n_test=64, seed=7)
learner = JaxLearner(mlp(seed=pid), full.partition(pid, 2), batch_size=32)
node = Node(learner=learner, protocol=GrpcProtocol(my_addr))
node.start()
barrier("kill_up")
if pid == 0:
    connect_retry(node, peer_addr)
wait_neighbors([node], 1)
if pid == 0:
    node.set_start_learning(rounds=3, epochs=1)
if pid == 1:
    deadline = time.time() + 60
    while node.state.round is None and time.time() < deadline:
        time.sleep(0.05)
    assert node.state.round is not None, "experiment never reached the victim"
    node.state.model_initialized_event.wait(30)
    time.sleep(0.5)  # let at least one DCN payload land while both live
    print("DYING 1", flush=True)
    os._exit(9)

wait_to_finish([node], timeout=150)
assert node.state.round is None, "survivor never finished the experiment"
s = dcn_stats()
from p2pfl_tpu.management.logger import logger
failovers = sum(
    d.get("root_failover", 0.0) for d in logger.get_comm_metrics().values()
)
print(f"KILL {pid}: dcn={s} failovers={failovers}")
assert s["dcn_sends"] >= 1, s  # the init-model broadcast rode DCN pre-kill
assert failovers >= 1, "survivor never took over the dead global root"
node.stop()
print(f"OK kill process {pid}", flush=True)
# skip atexit: jax.distributed's shutdown barrier LOG(FATAL)s (SIGABRT)
# when a world member died mid-run — which is this test's whole point
os._exit(0)
"""


def _launch(tmp_path, worker_src, ok_marker, timeout=300, expect_rc=None):
    """The test_multihost runner, generalized: per-pid expected return
    codes (a killed worker exits nonzero ON PURPOSE) and OK markers only
    for pids expected to survive."""
    import socket

    expect_rc = expect_rc or {}
    with socket.socket() as s:  # a free localhost port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "worker.py"
    script.write_text(worker_src.replace("%PORT%", str(port)))
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "PALLAS_AXON_POOL_IPS")
    }
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (os.getcwd(), env.get("PYTHONPATH")) if p
    )
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process runtime hung (coordinator never formed)")
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == expect_rc.get(pid, 0), out[-3000:]
        if expect_rc.get(pid, 0) == 0:
            assert f"{ok_marker} {pid}" in out, out[-3000:]
    return outs


@pytest.mark.slow
def test_two_process_dcn_federation_zero_pickled_bytes_and_parity(tmp_path):
    """The acceptance witness: a 2-process federation over WEIGHTS_PLANE=
    "dcn" completes with device payloads crossing the process boundary,
    ZERO pickled model bytes on gRPC, no silent fallback — and its final
    model matches a byte-plane control fleet bit-close."""
    _launch(tmp_path, _FED_WORKER, "OK fed process", timeout=420)


@pytest.mark.slow
def test_two_process_dcn_transfer_codec_matrix(tmp_path):
    """Direct transfer parity: raw fp32 and bf16 land bit-exact; int8
    within quantization bounds; topk8 matches the byte decoder; a
    mismatched receiver anchor nacks into a loud byte fallback."""
    _launch(tmp_path, _XFER_WORKER, "OK xfer process", timeout=300)


@pytest.mark.slow
def test_two_process_mixed_plane_selection_matrix(tmp_path):
    """Per-edge ladder in one fleet: co-resident pairs ride ICI,
    cross-process same-world peers ride DCN, and a directory-withdrawn
    node's inbound edges fall back to bytes — counted and loud — while
    the fleet still converges to one aggregate."""
    _launch(tmp_path, _MATRIX_WORKER, "OK matrix process", timeout=420)


@pytest.mark.slow
def test_two_process_dcn_root_kill_failover(tmp_path):
    """Hard process kill under async federation: the dead process hosted
    the global root; the survivor rides TierRouter failover, the DCN
    plane's rendezvous timeouts degrade the dead edges without hanging,
    and the experiment still completes."""
    _launch(
        tmp_path, _KILL_WORKER, "OK kill process", timeout=300, expect_rc={1: 9}
    )
