"""p2pfl-check rule engine: teeth fixtures, suppressions, baseline, self-run.

Every rule gets a flag/no-flag matrix: the *bad* fixture reproduces the
historical bug shape (PR-9 lock-across-send, PR-6 donation reuse, PR-5
unlocked lattice overwrite, the tc/vv/xp wire-compat breaks, the PR-2
BWD_MODE staleness) and MUST flag; the *good* fixture is the shipped fix
shape and MUST pass. On top of the minimal fixtures, the "shipped module
teeth" tests re-introduce each bug into the REAL source files in memory
and assert the analyzer catches it there too — so a rule cannot silently
stop seeing the code it was written for. The self-run test makes tier-1
fail if a future PR introduces a violation without a pragma.
"""

from __future__ import annotations

import re
from pathlib import Path

import pytest

import p2pfl_tpu
from p2pfl_tpu.analysis import (
    Finding,
    Severity,
    analyze,
    load_baseline,
    new_findings,
    write_baseline,
)
from p2pfl_tpu.analysis.__main__ import main as cli_main
from p2pfl_tpu.analysis.rules import (
    ALL_RULES,
    DonationReuseRule,
    JitStalenessRule,
    MonotoneMergeRule,
    SendUnderLockRule,
    WireHeaderCompatRule,
)

FIXTURES = Path(__file__).parent / "fixtures" / "analysis"
PKG = Path(p2pfl_tpu.__file__).parent


def rules_of(findings):
    return sorted({f.rule for f in findings})


def run_fixture(name, rule=None):
    return analyze([str(FIXTURES / name)], [rule] if rule else ALL_RULES)


# ---- per-rule flag / no-flag matrices on the teeth fixtures ----


def test_send_under_lock_teeth():
    bad = run_fixture("send_under_lock_bad.py", SendUnderLockRule)
    assert len(bad) == 2  # ctx.lock send + status_merge_lock broadcast
    assert rules_of(bad) == ["send-under-lock"]
    assert "no lock may be held across a send" in bad[0].message
    assert run_fixture("send_under_lock_good.py", SendUnderLockRule) == []


def test_donation_reuse_teeth():
    bad = run_fixture("donation_reuse_bad.py", DonationReuseRule)
    assert rules_of(bad) == ["donation-reuse"]
    assert any("self.params" in f.message and "spmd_round" in f.message for f in bad)
    assert run_fixture("donation_reuse_good.py", DonationReuseRule) == []


def test_donation_one_statement_rebind_is_clean():
    # `x = donated_fn(x)` rebinds in the same statement — the canonical
    # safe shape must not need a pragma (review regression)
    src = (
        "import jax\n"
        "from functools import partial\n\n"
        "@partial(jax.jit, donate_argnums=(0,))\n"
        "def step(params, x):\n"
        "    return params\n\n"
        "class F:\n"
        "    def run(self, x):\n"
        "        self.params = step(self.params, x)\n"
        "        return self.encode(self.params)\n"
    )
    assert analyze([], [DonationReuseRule], sources={"a.py": src}) == []


def test_monotone_merge_teeth():
    bad = run_fixture("monotone_merge_bad.py", MonotoneMergeRule)
    # coverage overwrite (aliased), nei_status write, async_done add
    assert len(bad) == 3
    assert rules_of(bad) == ["monotone-merge"]
    assert run_fixture("monotone_merge_good.py", MonotoneMergeRule) == []


def test_jit_staleness_teeth():
    bad = run_fixture("jit_staleness_bad.py", JitStalenessRule)
    assert rules_of(bad) == ["jit-staleness"]
    msgs = "\n".join(f.message for f in bad)
    assert "BWD_MODE" in msgs  # mutable global in @jax.jit body
    assert "Settings.AGG_DTYPE" in msgs  # Settings read in jit
    assert "float(…)" in msgs  # host sync
    assert "np.asarray" in msgs  # host materialization
    # the pallas kernel (reached through kernel = partial(_kernel)) too
    assert any(f.context == "_kernel" for f in bad)
    assert run_fixture("jit_staleness_good.py", JitStalenessRule) == []


def test_jit_staleness_sees_through_shard_map():
    # shard_map bodies are device programs: decorator form AND
    # jit(shard_map(body, …)) call form must both be traced through
    bad = run_fixture("jit_shard_map_bad.py", JitStalenessRule)
    assert rules_of(bad) == ["jit-staleness"]
    msgs = "\n".join(f.message for f in bad)
    assert "Settings.FEDBUFF_ALPHA" in msgs  # @partial(shard_map, …) form
    assert "CHUNK_OVERRIDE" in msgs  # mutable global in the call form
    assert "np.asarray" in msgs  # host sync in the shard body
    assert {f.context for f in bad} == {"shard_body", "body"}
    assert run_fixture("jit_shard_map_good.py", JitStalenessRule) == []


def test_donation_reuse_sees_through_shard_map():
    # partial(jax.jit, donate_argnums=…)(shard_map(…)): the donation is
    # declared on the inner partial call — the sharded-engine wrapping
    bad = run_fixture("donation_shard_map_bad.py", DonationReuseRule)
    assert rules_of(bad) == ["donation-reuse"]
    assert any("self.w" in f.message and "fleet_step" in f.message for f in bad)
    assert run_fixture("donation_shard_map_good.py", DonationReuseRule) == []


def test_wire_header_compat_teeth():
    bad = analyze([str(FIXTURES / "wire_bad")], [WireHeaderCompatRule])
    assert rules_of(bad) == ["wire-header-compat"]
    msgs = "\n".join(f.message for f in bad)
    assert "serialized unconditionally" in msgs  # xp without the None guard
    assert "read with []" in msgs  # d["xp"] decode
    assert "without copying 'version'" in msgs  # memory ModelUpdate re-wrap
    assert "without copying 'xp'" in msgs
    assert "protobuf interop codec" in msgs  # out.vv schema leak
    assert analyze([str(FIXTURES / "wire_good")], [WireHeaderCompatRule]) == []


def test_no_host_gather_teeth():
    from p2pfl_tpu.analysis.rules import NoHostGatherRule

    bad = analyze([str(FIXTURES / "ici_bad")], [NoHostGatherRule])
    assert rules_of(bad) == ["no-host-gather"]
    msgs = "\n".join(f.message for f in bad)
    assert "np.asarray" in msgs          # full-gather of a device leaf
    assert ".tobytes()" in msgs          # byte materialization
    assert "jax.device_get" in msgs      # explicit host pull
    assert ".item()" in msgs             # scalar host sync
    assert "np.frombuffer" in msgs       # byte-codec shape sneaking back
    assert analyze([str(FIXTURES / "ici_good")], [NoHostGatherRule]) == []


def test_no_host_gather_is_scope_targeted():
    # the SAME host calls outside the ICI basenames are fine — the byte
    # transports legitimately materialize payloads
    from p2pfl_tpu.analysis.rules import NoHostGatherRule

    src = (FIXTURES / "ici_bad" / "ici_plane.py").read_text()
    assert analyze([], [NoHostGatherRule], sources={"weights.py": src}) == []


def test_wire_codec_sets_are_per_directory():
    # scanning fixtures alongside a real codec must not let one shadow
    # the other (review regression: basename collisions) — the bad
    # directory still produces all its findings, the good one none
    both = analyze(
        [str(FIXTURES / "wire_good"), str(FIXTURES / "wire_bad")],
        [WireHeaderCompatRule],
    )
    assert both and all("wire_bad" in f.path for f in both)
    alone = analyze([str(FIXTURES / "wire_bad")], [WireHeaderCompatRule])
    assert {f.fingerprint for f in both} == {f.fingerprint for f in alone}


# ---- teeth against the SHIPPED modules: re-introduce each incident ----


def _read(rel):
    return (PKG / rel).read_text()


def test_shipped_spmd_flags_when_rebind_removed():
    src = _read("parallel/spmd.py")
    assert analyze([], ALL_RULES, sources={"parallel/spmd.py": src}) == []
    mutated = src.replace(
        "        self.params, self.opt_state, loss = result[:3]\n",
        "        loss = result[2]\n        self._log_norm(self.params)\n",
        1,
    )
    assert mutated != src
    found = analyze([], [DonationReuseRule], sources={"parallel/spmd.py": mutated})
    assert any(f.rule == "donation-reuse" and "spmd_round" in f.message for f in found)


def test_shipped_flash_attention_flags_bwd_mode_global():
    src = _read("ops/flash_attention.py")
    assert analyze([], [JitStalenessRule], sources={"ops/flash_attention.py": src}) == []
    inject = (
        "BWD_MODE = 'flash'\n\n\ndef set_bwd(m):\n"
        "    global BWD_MODE\n    BWD_MODE = m\n\n\ndef _flash_kernel("
    )
    mutated = src.replace("def _flash_kernel(", inject, 1)
    m = re.search(r"def _flash_kernel\(.*?\):\n", mutated, re.S)
    mutated = mutated[: m.end()] + "    _mode = BWD_MODE\n" + mutated[m.end() :]
    found = analyze([], [JitStalenessRule], sources={"ops/flash_attention.py": mutated})
    assert any("BWD_MODE" in f.message and f.context == "_flash_kernel" for f in found)


def test_shipped_control_flags_when_merge_lock_removed():
    # the exact pre-fix shape of ModelInitializedCommand (this PR's triage)
    src = _read("commands/control.py")
    assert analyze([], [MonotoneMergeRule], sources={"commands/control.py": src}) == []
    mutated = src.replace(
        "        with self._state.status_merge_lock:\n"
        "            self._state.nei_status.setdefault(source, -1)",
        "        self._state.nei_status.setdefault(source, -1)",
        1,
    )
    assert mutated != src
    found = analyze([], [MonotoneMergeRule], sources={"commands/control.py": mutated})
    assert any(f.rule == "monotone-merge" and "nei_status" in f.message for f in found)


def test_shipped_federation_command_flags_send_moved_under_lock():
    src = _read("commands/federation.py")
    assert analyze([], [SendUnderLockRule], sources={"commands/federation.py": src}) == []
    # move AsyncDoneCommand's (hypothetical) ack-send inside the merge lock
    mutated = src.replace(
        "        with st.status_merge_lock:\n            st.async_done_peers.add(source)\n",
        "        with st.status_merge_lock:\n"
        "            st.async_done_peers.add(source)\n"
        "            self._node.protocol.broadcast(self._node.protocol.build_msg('ack'))\n",
        1,
    )
    assert mutated != src
    found = analyze([], [SendUnderLockRule], sources={"commands/federation.py": mutated})
    assert any(f.rule == "send-under-lock" for f in found)


def test_shipped_grpc_transport_flags_unguarded_xp():
    src = _read("communication/grpc_transport.py")
    mutated = src.replace(
        "    if msg.xp is not None:\n"
        "        # experiment identity (Node.set_start_learning) — optional like\n"
        "        # \"tc\": old frames decode unchanged, receivers use it to filter\n"
        "        # cross-experiment stragglers exactly\n"
        "        d[\"xp\"] = msg.xp\n",
        "    d[\"xp\"] = msg.xp\n",
        1,
    )
    assert mutated != src
    found = analyze(
        [], [WireHeaderCompatRule], sources={"communication/grpc_transport.py": mutated}
    )
    assert any("serialized unconditionally" in f.message for f in found)


def test_shipped_memory_flags_dropped_version_copy():
    src = _read("communication/memory.py")
    mutated = src.replace("                        version=env.update.version,\n", "", 1)
    assert mutated != src
    found = analyze([], [WireHeaderCompatRule], sources={"communication/memory.py": mutated})
    assert any("without copying 'version'" in f.message for f in found)


def test_shipped_proto_wire_flags_vv_leak():
    src = _read("communication/proto_wire.py")
    mutated = src.replace(
        "        cmd=env.cmd,\n    ).SerializeToString()",
        "        cmd=env.cmd,\n        vv=list(env.update.version or ()),\n    ).SerializeToString()",
        1,
    )
    assert mutated != src
    found = analyze([], [WireHeaderCompatRule], sources={"communication/proto_wire.py": mutated})
    assert any("protobuf interop codec" in f.message for f in found)


def test_shipped_ici_flags_host_gather_reintroduced():
    """The real weights-plane module with the contract broken in memory:
    an innocent-looking np.asarray shape probe (the exact way the
    zero-host-bytes promise would rot) must flag."""
    from p2pfl_tpu.analysis.rules import NoHostGatherRule

    src = _read("communication/ici.py")
    assert analyze([], [NoHostGatherRule], sources={"communication/ici.py": src}) == []
    needle = "    src = proto.get_address()\n"
    mutated = src.replace(
        needle,
        needle + "    _shape_probe = np.asarray(jax.tree.leaves(update.params)[0])\n",
        1,
    )
    assert mutated != src
    found = analyze(
        [], [NoHostGatherRule], sources={"communication/ici.py": mutated}
    )
    assert any(
        f.rule == "no-host-gather" and "np.asarray" in f.message for f in found
    )
    # the glue module is in scope too
    glue = _read("parallel/ici_plane.py")
    assert analyze([], [NoHostGatherRule], sources={"parallel/ici_plane.py": glue}) == []
    gneedle = "    leaves = jax.tree.leaves(tree)\n"
    gmut = glue.replace(
        gneedle, gneedle + "    _host = [x.tobytes() for x in leaves]\n", 1
    )
    assert gmut != glue
    gfound = analyze([], [NoHostGatherRule], sources={"parallel/ici_plane.py": gmut})
    assert any(".tobytes()" in f.message for f in gfound)


# ---- suppression semantics ----

BAD_SEND = """
class H:
    def f(self):
        with self.lock:
            self.protocol.send(self.peer, self.env){pragma}
"""


def test_inline_suppression_same_line():
    src = BAD_SEND.format(pragma="  # p2pfl: allow(send-under-lock) — teeth test")
    assert analyze([], ALL_RULES, sources={"a.py": src}) == []


def test_inline_suppression_line_above():
    src = (
        "class H:\n"
        "    def f(self):\n"
        "        with self.lock:\n"
        "            # p2pfl: allow(send-under-lock) — justified\n"
        "            self.protocol.send(self.peer, self.env)\n"
    )
    assert analyze([], ALL_RULES, sources={"a.py": src}) == []


def test_suppression_is_rule_specific():
    src = BAD_SEND.format(pragma="  # p2pfl: allow(jit-staleness)")
    found = analyze([], ALL_RULES, sources={"a.py": src})
    assert rules_of(found) == ["send-under-lock"]


def test_suppression_wildcard():
    src = BAD_SEND.format(pragma="  # p2pfl: allow(*) — drive harness")
    assert analyze([], ALL_RULES, sources={"a.py": src}) == []


def test_unsuppressed_flags():
    found = analyze([], ALL_RULES, sources={"a.py": BAD_SEND.format(pragma="")})
    assert rules_of(found) == ["send-under-lock"]


# ---- baseline semantics ----


def test_baseline_accepts_old_findings_only(tmp_path):
    src = BAD_SEND.format(pragma="")
    found = analyze([], ALL_RULES, sources={"a.py": src})
    assert len(found) == 1
    baseline_file = tmp_path / "baseline.json"
    write_baseline(str(baseline_file), found)
    baseline = load_baseline(str(baseline_file))
    assert new_findings(found, baseline) == []
    # a NEW violation (different function) is not masked by the baseline
    src2 = src + (
        "\n"
        "    def g(self):\n"
        "        with self.lock:\n"
        "            self.protocol.broadcast(self.env)\n"
    )
    found2 = analyze([], ALL_RULES, sources={"a.py": src2})
    fresh = new_findings(found2, baseline)
    assert [f.context for f in fresh] == ["H.g"]


def test_fingerprint_survives_line_shifts():
    src = BAD_SEND.format(pragma="")
    shifted = "# a new header comment\n\n" + src
    (f1,) = analyze([], ALL_RULES, sources={"a.py": src})
    (f2,) = analyze([], ALL_RULES, sources={"a.py": shifted})
    assert f1.line != f2.line
    assert f1.fingerprint == f2.fingerprint


def test_missing_baseline_is_empty():
    assert load_baseline("/nonexistent/baseline.json") == {}


# ---- CLI ----


def test_cli_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(BAD_SEND.format(pragma=""))
    good = tmp_path / "good.py"
    good.write_text("def f():\n    return 1\n")
    assert cli_main([str(good)]) == 0
    assert cli_main([str(bad)]) == 1
    assert cli_main([str(tmp_path / "missing.py")]) == 2
    assert cli_main(["--select", "not-a-rule", str(good)]) == 2
    # baseline the debt: gate goes green, then a clean tree stays green
    baseline = tmp_path / "b.json"
    assert cli_main([str(bad), "--baseline", str(baseline), "--update-baseline"]) == 0
    assert cli_main([str(bad), "--baseline", str(baseline)]) == 0
    # a rule-filtered rewrite would drop other rules' accepted entries
    assert cli_main([str(bad), "--select", "jit-staleness", "--update-baseline"]) == 2


def test_cli_list_rules(capsys):
    assert cli_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in ALL_RULES:
        assert rule.id in out


# ---- the self-run gate: the shipped tree must stay clean ----


def test_self_run_is_green():
    """tier-1 fails if a future PR introduces a violation without a pragma
    — the same gate CI runs (`python -m p2pfl_tpu.analysis p2pfl_tpu`)."""
    found = analyze([str(PKG)], ALL_RULES)
    gating = [f for f in found if f.severity is Severity.ERROR]
    assert gating == [], "p2pfl-check found new violations:\n" + "\n".join(
        f.format() for f in gating
    )


# ---- shared finding types: the partition-rule lint speaks them too ----


def test_partition_lint_reports_shared_findings():
    jnp = pytest.importorskip("jax.numpy")
    from p2pfl_tpu.parallel.sharding import lint_partition_rules

    tree = {"w": jnp.zeros((4, 5)), "odd": jnp.zeros((2, 2))}
    rules = (
        (r"w", (None, "model")),
        (r"typo_never_matches", ("model", None)),
    )
    report = lint_partition_rules(rules, tree)
    findings = report.findings()
    assert all(isinstance(f, Finding) for f in findings)
    by_rule = {f.rule for f in findings}
    assert "partition-unmatched" in by_rule
    assert "partition-dead-rule" in by_rule
    # errors property mirrors the error-severity findings verbatim
    assert report.errors == [f.message for f in findings if f.severity is Severity.ERROR]
    # one shared one-line format across the lint and the analyzer
    assert findings[0].format().startswith("partition-rules:0:0: error[partition-")


def test_partition_lint_indivisible_is_info():
    jnp = pytest.importorskip("jax.numpy")
    import jax
    from p2pfl_tpu.parallel.mesh import node_slices, submesh_federation_mesh
    from p2pfl_tpu.parallel.sharding import lint_partition_rules

    mesh = node_slices(submesh_federation_mesh(1, 2, devices=jax.devices()[:2]))[0]
    tree = {"Dense_0": {"kernel": jnp.zeros((8, 5)), "bias": jnp.zeros((3,))}}
    rules = ((r"kernel", (None, "model")), (r".*", ()))
    report = lint_partition_rules(rules, tree, mesh)
    infos = [f for f in report.findings() if f.severity is Severity.INFO]
    assert report.ok()  # indivisible is informational, not an error
    assert infos and all(f.rule == "partition-indivisible" for f in infos)


# ---- regression for this PR's triage fix (commands/control.py) ----


def test_model_initialized_merge_holds_lock_and_keeps_semantics():
    from p2pfl_tpu.commands.control import ModelInitializedCommand, ModelsReadyCommand
    from p2pfl_tpu.node_state import NodeState

    st = NodeState("me")
    st.round = 0
    ModelInitializedCommand(st).execute("peer", -1)
    assert st.nei_status == {"peer": -1}
    # monotone: a later round report wins, a stale re-init cannot regress it
    ModelsReadyCommand(st).execute("peer", 0)
    assert st.nei_status == {"peer": 0}
    ModelInitializedCommand(st).execute("peer", -1)
    assert st.nei_status == {"peer": 0}
    # the merge must run under the shared lock (the monotone-merge rule
    # pins the source shape; this pins the runtime behavior: holding the
    # lock elsewhere must not deadlock the handler — i.e. it really uses
    # status_merge_lock, briefly and reentrantly-safely)
    import threading

    done = threading.Event()

    def blocked_merge():
        ModelInitializedCommand(st).execute("other", -1)
        done.set()

    with st.status_merge_lock:
        t = threading.Thread(target=blocked_merge, daemon=True)
        t.start()
        assert not done.wait(0.2)  # handler waits for the lock → it takes it
    assert done.wait(2.0)
    assert st.nei_status["other"] == -1


# ---- wire registry sanity ----


def test_wire_header_registry_is_consistent():
    from p2pfl_tpu.communication.wire_headers import OPTIONAL_WIRE_HEADERS

    keys = [h.key for h in OPTIONAL_WIRE_HEADERS]
    assert len(keys) == len(set(keys))
    for h in OPTIONAL_WIRE_HEADERS:
        assert h.planes and set(h.planes) <= {"message", "weights"}
        assert h.doc
        for ctor, kwarg in h.memory_copies:
            assert ctor in {"ModelUpdate", "WeightsEnvelope"} and kwarg
