"""Flight-recorder suite (ISSUE 7): spans, wire trace context, RoundReport,
Chrome-trace export, the unified counter registry, and the thread-safety
satellites (Stopwatch, snapshot_and_reset, GlobalMetricStorage dedup).
"""

import json
import threading
import time

import pytest

from p2pfl_tpu.communication.faults import (
    CrashSpec,
    EdgeFault,
    FaultPlan,
    install_fault_plan,
    remove_fault_plan,
)
from p2pfl_tpu.communication.memory import MemoryRegistry
from p2pfl_tpu.learning.learner import DummyLearner
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.management.telemetry import (
    telemetry,
    validate_chrome_trace,
)
from p2pfl_tpu.node import Node
from p2pfl_tpu.settings import Settings
from p2pfl_tpu.utils import full_connection, wait_convergence, wait_to_finish


@pytest.fixture(autouse=True)
def _clean():
    MemoryRegistry.reset()
    telemetry.reset()
    yield
    MemoryRegistry.reset()
    telemetry.reset()
    Settings.TELEMETRY_RING_SPANS = 4096


def _mk_nodes(n: int) -> list:
    nodes = [Node(learner=DummyLearner(value=float(i))) for i in range(n)]
    for node in nodes:
        node.start()
    for node in nodes:
        full_connection(node, nodes)
    wait_convergence(nodes, n - 1, only_direct=True, wait=10)
    return nodes


def _stop_all(nodes):
    for n in nodes:
        n.stop()


# ---------------------------------------------------------------------------
# span API
# ---------------------------------------------------------------------------


def test_span_nesting_and_context():
    with telemetry.span("n1", "outer", kind="stage") as outer:
        assert telemetry.current_ctx() == (outer.trace_id, outer.span_id)
        with telemetry.span("n1", "inner", kind="gossip") as inner:
            # nesting: same trace, parent chain through the stack
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
            assert telemetry.current_ctx() == (inner.trace_id, inner.span_id)
        assert telemetry.current_ctx() == (outer.trace_id, outer.span_id)
    assert telemetry.current_ctx() is None
    spans = telemetry.spans("n1")
    assert [s.name for s in spans] == ["outer", "inner"]
    for s in spans:
        assert s.t1_ns >= s.t0_ns


def test_explicit_parent_overrides_stack():
    """A wire ``trace_ctx`` wins over the thread-local stack — the receive
    path links to the SENDER's span, not whatever the delivering thread
    happens to be inside."""
    with telemetry.span("n1", "local", kind="stage"):
        with telemetry.span("n2", "recv", kind="gossip", parent=("tX", "sX")) as sp:
            assert sp.trace_id == "tX"
            assert sp.parent_id == "sX"


def test_span_disabled_records_nothing():
    Settings.TELEMETRY_ENABLED = False
    try:
        with telemetry.span("n1", "x") as sp:
            assert sp is None
        telemetry.event("n1", "boom")
        assert telemetry.spans() == []
        assert telemetry.current_ctx() is None
    finally:
        Settings.TELEMETRY_ENABLED = True


def test_ring_bounded_under_concurrent_writers():
    Settings.TELEMETRY_RING_SPANS = 128
    telemetry.reset_spans()
    n_threads, per_thread = 8, 200
    errors = []

    def hammer(i):
        try:
            for k in range(per_thread):
                with telemetry.span("ring-node", f"w{i}", kind="gossip", attrs={"k": k}):
                    pass
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=hammer, args=(i,)) for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    spans = telemetry.spans("ring-node")
    # bounded: only the most recent TELEMETRY_RING_SPANS survive
    assert len(spans) == 128
    # and the survivors are the tail of the stream, not a random sample:
    # every thread's final span (k = per_thread - 1) postdates at least
    # n_threads * 128 earlier commits, so the retained k's skew high
    assert max(s.attrs["k"] for s in spans) == per_thread - 1
    assert min(s.attrs["k"] for s in spans) >= per_thread - 1 - 128


def test_histogram_percentiles_ordered():
    for ms in (1, 2, 3, 5, 8, 13, 100, 400):
        telemetry.observe("h-node", "lat", ms * 1_000_000)
    h = telemetry.histograms("h-node")["lat"]
    assert h["count"] == 8
    assert h["p50_ms"] <= h["p95_ms"] <= h["p99_ms"] <= 2 * h["max_ms"]
    # log2 buckets: p50 within 2x of the true median (5.5 ms)
    assert 2 <= h["p50_ms"] <= 12


# ---------------------------------------------------------------------------
# unified counter registry + atomic snapshot_and_reset (satellite)
# ---------------------------------------------------------------------------


def test_comm_metrics_view_backed_by_registry():
    logger.log_comm_metric("cnode", "m", 2.0)
    logger.log_comm_metric("cnode", "m", 3.0)
    assert logger.get_comm_metrics("cnode") == {"m": 5.0}
    assert telemetry.counters("comm", "cnode") == {"m": 5.0}
    logger.reset_comm_metrics()
    assert logger.get_comm_metrics("cnode") == {}


def test_snapshot_and_reset_loses_no_increments():
    """Concurrent incrementer + repeated snapshot_and_reset: the sum of all
    snapshots plus the residue equals exactly what was written — the
    get+reset pair this replaces could drop increments in the gap."""
    total_writes = 4000
    done = threading.Event()

    def writer():
        for _ in range(total_writes):
            logger.log_comm_metric("atomic-node", "hits")
        done.set()

    t = threading.Thread(target=writer)
    t.start()
    harvested = 0.0
    while not done.is_set():
        harvested += logger.snapshot_and_reset_comm_metrics("atomic-node").get("hits", 0.0)
    t.join()
    harvested += logger.snapshot_and_reset_comm_metrics("atomic-node").get("hits", 0.0)
    assert harvested == total_writes


def test_dispatch_counts_snapshot_and_reset():
    from p2pfl_tpu.management.profiling import (
        get_dispatch_counts,
        record_dispatch,
        reset_dispatch_counts,
        snapshot_and_reset_dispatch_counts,
    )

    reset_dispatch_counts()
    record_dispatch("site_a")
    record_dispatch("site_a")
    record_dispatch("site_b")
    snap = snapshot_and_reset_dispatch_counts()
    assert snap == {"site_a": 2, "site_b": 1}
    assert get_dispatch_counts() == {}


def test_stopwatch_thread_safe():
    from p2pfl_tpu.management.profiling import Stopwatch

    sw = Stopwatch()
    n_threads, per_thread = 8, 300

    def hammer():
        for _ in range(per_thread):
            with sw.section("hot"):
                pass

    threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # the racy read-modify-write would lose counts here
    assert sw.counts["hot"] == n_threads * per_thread
    s = sw.summary()
    assert s["hot"]["calls"] == n_threads * per_thread
    assert "p95_ms" in s["hot"]


# ---------------------------------------------------------------------------
# GlobalMetricStorage round-dedup satellite
# ---------------------------------------------------------------------------


def test_global_metric_storage_dedup_and_sorted():
    from p2pfl_tpu.management.metric_storage import GlobalMetricStorage

    store = GlobalMetricStorage()
    # out-of-order rounds, duplicate round 1: first write wins, list sorted
    store.add_log("e", 3, "acc", "n", 0.3)
    store.add_log("e", 1, "acc", "n", 0.1)
    store.add_log("e", 1, "acc", "n", 0.999)  # dup — dropped
    store.add_log("e", 2, "acc", "n", 0.2)
    series = store.get_all_logs()["e"]["n"]["acc"]
    assert series == [(1, 0.1), (2, 0.2), (3, 0.3)]
    # independent series do not share dedup state
    store.add_log("e", 1, "loss", "n", 9.0)
    assert store.get_all_logs()["e"]["n"]["loss"] == [(1, 9.0)]


# ---------------------------------------------------------------------------
# wire trace context
# ---------------------------------------------------------------------------


def test_trace_ctx_grpc_codec_roundtrip():
    from p2pfl_tpu.communication.grpc_transport import (
        decode_message,
        decode_weights,
        encode_message,
        encode_weights,
    )
    from p2pfl_tpu.communication.message import Message, WeightsEnvelope
    from p2pfl_tpu.learning.weights import ModelUpdate

    import numpy as np

    msg = Message("a:1", "vote", ("x", "1"), round=2, trace_ctx=("tid9", "sid7"))
    back = decode_message(encode_message(msg))
    assert back.trace_ctx == ("tid9", "sid7")
    assert (back.source, back.cmd, back.args) == (msg.source, msg.cmd, msg.args)

    # absent field (old wire format) still decodes — trace_ctx None
    old = json.loads(encode_message(msg).decode())
    del old["tc"]
    legacy = decode_message(json.dumps(old).encode())
    assert legacy.trace_ctx is None
    assert legacy.msg_id == msg.msg_id

    update = ModelUpdate({"w": np.ones(4, np.float32)}, ["a:1"], 10)
    env = WeightsEnvelope("a:1", 1, "add_model", update, trace_ctx=("tw", "sw"))
    wire = encode_weights(env)
    back_env = decode_weights(wire)
    assert back_env.trace_ctx == ("tw", "sw")
    # old weights frame (no tc in header) also decodes
    hlen = int.from_bytes(wire[:4], "little")
    header = json.loads(wire[4 : 4 + hlen].decode())
    del header["tc"]
    raw = json.dumps(header).encode()
    legacy_wire = b"".join((len(raw).to_bytes(4, "little"), raw, wire[4 + hlen :]))
    assert decode_weights(legacy_wire).trace_ctx is None


def test_trace_ctx_links_sender_and_receiver_in_memory():
    """A message built under a sender span produces a receiver recv-span
    whose parent is the sender's span — one causal edge across nodes."""
    nodes = _mk_nodes(2)
    try:
        a, b = nodes
        telemetry.reset_spans()
        with telemetry.span(a.addr, "probe_stage", kind="stage") as sp:
            msg = a.protocol.build_msg("metrics", ["test_acc", "1.0"], round=0)
            assert msg.trace_ctx == (sp.trace_id, sp.span_id)
            assert a.protocol.send(b.addr, msg)
        recv = [
            s
            for s in telemetry.spans(b.addr)
            if s.name == "recv:metrics" and s.node == b.addr
        ]
        assert recv, "receiver recorded no recv span"
        assert recv[0].trace_id == sp.trace_id
        assert recv[0].parent_id == sp.span_id
    finally:
        _stop_all(nodes)


# ---------------------------------------------------------------------------
# RoundReport + Chrome trace export on a real federation
# ---------------------------------------------------------------------------


@pytest.fixture()
def _slow_peer_federation():
    nodes = _mk_nodes(4)
    slow = nodes[-1]
    plan = FaultPlan(seed=42, slow_nodes={slow.addr: 0.25})
    install_fault_plan(nodes, plan)
    telemetry.reset_spans()
    yield nodes, slow
    remove_fault_plan(nodes)
    _stop_all(nodes)


def test_round_report_names_slow_peer(_slow_peer_federation):
    nodes, slow = _slow_peer_federation
    Settings.TRAIN_SET_SIZE = 4
    nodes[0].set_start_learning(rounds=1, epochs=1)
    wait_to_finish(nodes, timeout=45)
    report = telemetry.round_report(0)
    assert report.per_node, "no stage spans attributed to round 0"
    assert set(report.per_node) == {n.addr for n in nodes}
    # every inbound weights delivery to the slow peer pays 0.25 s inside
    # the sender's send span — the critical edge must point at it
    assert report.critical_edge is not None
    assert report.critical_edge["dst"] == slow.addr
    assert report.critical_edge["busy_s"] >= 0.25
    assert report.faults.get("fault_slow", 0) >= 1
    # the report walks a tree whose stage split covers the round wall
    for info in report.per_node.values():
        assert info["wall_s"] > 0
        assert info["stages_s"]


def test_chrome_trace_export_schema(tmp_path, _slow_peer_federation):
    nodes, _slow = _slow_peer_federation
    Settings.TRAIN_SET_SIZE = 4
    nodes[0].set_start_learning(rounds=1, epochs=1)
    wait_to_finish(nodes, timeout=45)
    out = tmp_path / "trace.json"
    doc = telemetry.export_chrome_trace(path=str(out))
    n_events = validate_chrome_trace(doc)
    assert n_events > 20
    # the file round-trips and validates identically (what Perfetto loads)
    reloaded = json.loads(out.read_text())
    assert validate_chrome_trace(reloaded) == n_events
    events = reloaded["traceEvents"]
    # one pid per node, named via process_name metadata
    proc_names = {
        e["pid"]: e["args"]["name"] for e in events if e.get("name") == "process_name"
    }
    assert set(proc_names.values()) >= {n.addr for n in nodes}
    # spans land on per-plane tids with stage + gossip lanes populated
    lanes = {(e["pid"], e["tid"]) for e in events if e.get("ph") == "X"}
    from p2pfl_tpu.management.telemetry import PLANES

    tids = {tid for _pid, tid in lanes}
    assert PLANES["stage"] in tids and PLANES["gossip"] in tids
    # X events carry the wire-propagated trace identity
    x_events = [e for e in events if e.get("ph") == "X"]
    assert all("trace_id" in e["args"] and "span_id" in e["args"] for e in x_events)


def test_validate_chrome_trace_rejects_malformed():
    with pytest.raises(ValueError):
        validate_chrome_trace({"no": "traceEvents"})
    with pytest.raises(ValueError):
        validate_chrome_trace({"traceEvents": [{"ph": "X", "name": "x", "pid": 1, "tid": 1}]})
    with pytest.raises(ValueError):
        validate_chrome_trace(
            {"traceEvents": [{"ph": "?", "name": "x", "pid": 1, "tid": 1}]}
        )


def test_deterministic_round_trace_id_across_nodes():
    """Every node derives the same trace id for the same round, so one
    round's spans across all nodes form one trace without coordination."""
    nodes = _mk_nodes(2)
    try:
        telemetry.reset_spans()
        nodes[0].set_start_learning(rounds=1, epochs=1)
        wait_to_finish(nodes, timeout=30)
        by_node = {}
        for s in telemetry.spans():
            if s.kind == "stage" and s.attrs.get("round") == 0 and s.name in (
                "TrainStage",
                "GossipModelStage",
            ):
                by_node.setdefault(s.node, set()).add(s.trace_id)
        assert len(by_node) == 2
        ids = set().union(*by_node.values())
        assert len(ids) == 1, f"round 0 split into traces: {ids}"
    finally:
        _stop_all(nodes)


# ---------------------------------------------------------------------------
# the acceptance scenario: 6-node chaos federation, flight recorder on
# ---------------------------------------------------------------------------


def test_flight_recorder_chaos_federation(tmp_path):
    """Seeded 6-node chaos run (5% drop + slow peer + mid-round crash):
    the exported trace validates against the Chrome schema and the
    RoundReport names the slow peer (critical edge) and the crashed peer
    (failure ranking) — a chaos failure is self-explaining."""
    Settings.TRAIN_SET_SIZE = 6
    Settings.AGGREGATION_TIMEOUT = 60.0
    nodes = _mk_nodes(6)
    victim, slow = nodes[3], nodes[-1]
    plan = FaultPlan(
        seed=1905,
        default=EdgeFault(drop=0.05),
        slow_nodes={slow.addr: 0.3},
        crashes={victim.addr: CrashSpec(stage="TrainStage", round_no=0)},
    )
    install_fault_plan(nodes, plan)
    telemetry.reset_spans()
    survivors = [n for n in nodes if n is not victim]
    try:
        nodes[0].set_start_learning(rounds=2, epochs=1)
        wait_to_finish(survivors, timeout=45)
        assert not victim._running

        from p2pfl_tpu.management.telemetry import dump_flight_record

        paths = dump_flight_record(str(tmp_path))
        trace = json.loads(open(paths[0]).read())
        assert validate_chrome_trace(trace) > 50
        reports = json.loads(open(paths[1]).read())
        affected = [r for r in reports if r["round"] == 0]
        assert affected, "round 0 produced no report"
        rep = affected[0]
        # the round was gated by injected chaos, and the report names the
        # culprits: the critical edge (send time + retry backoff) points
        # at the slow peer or the corpse (retries to a crashed peer can
        # out-burn a straggler's latency — both are the critical path)...
        assert rep["critical_path"]["edge"]["dst"] in (slow.addr, victim.addr)
        # ...the edge that burned the most raw send time is the slow
        # peer's (every weights delivery to it pays 0.3 s)...
        busiest = max(rep["edges"].items(), key=lambda kv: kv[1]["busy_s"])
        assert busiest[0].endswith(f"->{slow.addr}")
        # ...and the crash is visible twice: as an injected-fault event
        # and as the most-failed peer (every send to the corpse fails
        # until eviction)
        assert rep["faults"].get("fault_crash", 0) >= 1
        assert rep["critical_path"]["most_failed_peer"] == victim.addr
        # cross-node causality survived the chaos: some receiver span's
        # parent is a span recorded on ANOTHER node
        spans = telemetry.spans()
        by_id = {s.span_id: s for s in spans}
        cross = [
            s
            for s in spans
            if s.name.startswith("recv:")
            and s.parent_id in by_id
            and by_id[s.parent_id].node != s.node
        ]
        assert cross, "no cross-node parent links recorded"
    finally:
        remove_fault_plan(nodes)
        _stop_all(nodes)


# ---------------------------------------------------------------------------
# overhead guard (micro): the disabled path must be near-free
# ---------------------------------------------------------------------------


def test_disabled_span_is_cheap():
    """The off switch must actually switch off: creating a disabled span
    handle allocates nothing and is an order of magnitude cheaper than a
    recorded span (the real ≤5% bound is measured by bench_suite config1
    and guarded in CI — this is the unit-level sanity check)."""
    n = 20_000
    Settings.TELEMETRY_ENABLED = False
    try:
        t0 = time.perf_counter()
        for _ in range(n):
            with telemetry.span("x", "s"):
                pass
        off = time.perf_counter() - t0
    finally:
        Settings.TELEMETRY_ENABLED = True
    t0 = time.perf_counter()
    for _ in range(n):
        with telemetry.span("x", "s"):
            pass
    on = time.perf_counter() - t0
    assert off < on
    # and even the enabled path stays in the microseconds-per-span regime
    assert on / n < 200e-6
