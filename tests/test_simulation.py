"""Simulation builder tests: topologies, metric flow, API convenience."""

import pytest

from p2pfl_tpu.communication.memory import MemoryRegistry
from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import DummyLearner, JaxLearner
from p2pfl_tpu.models import mlp
from p2pfl_tpu.simulation import Simulation


@pytest.fixture(autouse=True)
def _clean():
    MemoryRegistry.reset()
    yield
    MemoryRegistry.reset()


@pytest.mark.parametrize("topology", ["line", "ring", "full", "star"])
def test_topologies_converge(topology):
    data = FederatedDataset.synthetic_mnist(n_train=256, n_test=64)
    sim = Simulation(3, lambda i, s: DummyLearner(value=float(i)), data, topology=topology)
    sim.start().learn(rounds=1, timeout=60)
    sim.stop()


def test_simulation_metrics_flow():
    """Peer eval metrics must reach the global store via the metrics verb."""
    data = FederatedDataset.synthetic_mnist(n_train=512, n_test=128)
    sim = Simulation(
        2,
        lambda i, s: JaxLearner(mlp(seed=i), s, batch_size=64),
        data,
        topology="full",
    )
    sim.start().learn(rounds=1, epochs=0, timeout=90)
    evals = sim.evaluate()
    assert all("test_acc" in m for m in evals.values())
    # the metrics command routed peers' broadcast metrics into the store;
    # the store is a process singleton, so search across all experiments
    logs = sim.metrics()
    assert logs, "global metric store is empty"
    node_addrs = {n.addr for n in sim.nodes}
    metric_names = {
        name
        for exp in logs.values()
        for node, node_metrics in exp.items()
        if node in node_addrs
        for name in node_metrics
    }
    assert "test_acc" in metric_names
    sim.stop()
