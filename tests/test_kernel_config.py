"""Kernel-config contract tests: FlashConfig numerics across schedules,
jit cache-key participation (the staleness regression the old ``BWD_MODE``
module global could not catch), and the autotune cache chain
(pinned → in-process → on-disk → defaults table). All interpret-mode, CPU
tier-1."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from p2pfl_tpu.ops import autotune
from p2pfl_tpu.ops.attention import causal_attention
from p2pfl_tpu.ops.flash_attention import FlashConfig, flash_attention


def _qkv(b=1, t=64, h=2, d=64, seed=0):
    keys = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, t, h, d), jnp.float32) for k in keys)


def _dense(q, k, v, causal):
    if causal:
        return causal_attention(q, k, v)
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (d**-0.5)
    return jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1), v)


# two deliberately non-default schedules: uneven blocks, wide q ownership,
# and both backward structures
_CONFIGS = [
    FlashConfig(block_q=16, block_k=32, q_span=2, bwd_mode="fused"),
    FlashConfig(block_q=32, block_k=16, bwd_mode="split",
                block_q_bwd=16, block_k_bwd=32),
]


@pytest.mark.parametrize("d", [64, 128])
@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("cfg_i", [0, 1])
def test_forward_parity_across_head_dims(d, causal, cfg_i):
    """Tuned forward == dense reference at the production head widths."""
    q, k, v = _qkv(t=64, d=d)
    want = _dense(q, k, v, causal)
    got = flash_attention(q, k, v, causal, _CONFIGS[cfg_i], True)
    np.testing.assert_allclose(np.asarray(want), np.asarray(got), atol=3e-5)


@pytest.mark.parametrize("t", [48, 96])
def test_forward_parity_ragged_seq(t):
    """Ragged sequence lengths (not a power of two, not a multiple of the
    default blocks): explicit dividing configs still match dense."""
    q, k, v = _qkv(t=t, d=64)
    want = _dense(q, k, v, True)
    got = flash_attention(q, k, v, True, FlashConfig(16, 24), True)
    np.testing.assert_allclose(np.asarray(want), np.asarray(got), atol=3e-5)
    # default-config path must also fit ragged lengths (divisor clamping)
    got_def = flash_attention(q, k, v, True, None, True)
    np.testing.assert_allclose(np.asarray(want), np.asarray(got_def), atol=3e-5)


@pytest.mark.parametrize("d", [64, 128])
@pytest.mark.parametrize("cfg_i", [0, 1])
def test_gradient_parity_across_configs(d, cfg_i):
    """Backward parity vs dense under both backward structures and
    bwd-specific blocks."""
    q, k, v = _qkv(t=32, d=d)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, _CONFIGS[cfg_i], True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(causal_attention(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


def test_config_participates_in_jit_cache_key():
    """THE staleness regression (ADVICE r5): flipping any kernel knob after
    a step has compiled must re-trace. FlashConfig is hashable and compares
    by value, so equal configs hit the compiled program and different ones
    (including a bwd_mode-only change — invisible to the old global) miss.
    """
    q, k, v = _qkv(t=32, d=16)
    traces = []  # appended at TRACE time: its length counts compilations

    step = jax.jit(
        lambda q, k, v, config: (
            traces.append(config),
            flash_attention(q, k, v, True, config, True).sum(),
        )[1],
        static_argnames=("config",),
    )

    base = FlashConfig(block_q=16, block_k=16)
    step(q, k, v, base)
    assert len(traces) == 1
    # an EQUAL but distinct instance: cache hit, no re-trace
    step(q, k, v, FlashConfig(block_q=16, block_k=16))
    assert len(traces) == 1
    # block change: re-trace
    step(q, k, v, FlashConfig(block_q=16, block_k=32))
    assert len(traces) == 2
    # bwd_mode-only change: re-trace (the old BWD_MODE global silently
    # did NOT — the compiled fused/split choice went stale)
    step(q, k, v, dataclasses.replace(base, bwd_mode="fused"))
    assert len(traces) == 3
    step(q, k, v, dataclasses.replace(base, bwd_mode="split"))
    assert len(traces) == 4
    # q_span-only change: re-trace
    step(q, k, v, dataclasses.replace(base, q_span=2))
    assert len(traces) == 5


def test_bwd_mode_retrace_changes_gradients_not_values():
    """jit(grad) keyed on config: both modes compile separately and agree
    numerically — proving the re-trace actually switches kernel structure.
    """
    q, k, v = _qkv(t=32, d=16)

    def grads(config):
        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, True, config, True) ** 2)

        fn = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        return fn(q, k, v)

    gf = grads(FlashConfig(16, 16, bwd_mode="fused"))
    gs = grads(FlashConfig(16, 16, bwd_mode="split"))
    for a, b in zip(gf, gs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_transformer_config_carries_flash_config():
    """cfg.flash_config makes the schedule reachable from the model config:
    it changes the (frozen, hashable) TransformerConfig identity — so any
    jit that treats module/config as static re-traces — and the built model
    actually runs the pinned kernel, matching dense numerics."""
    from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer

    fc_a = FlashConfig(block_q=16, block_k=16)
    fc_b = FlashConfig(block_q=16, block_k=16, bwd_mode="split")
    base = dict(
        vocab_size=64, dim=32, n_layers=1, n_heads=2, n_kv_heads=2,
        ffn_hidden=64, dtype=jnp.float32,
    )
    cfg_a = TransformerConfig(**base, flash_config=fc_a)
    cfg_b = TransformerConfig(**base, flash_config=fc_b)
    assert cfg_a != cfg_b and hash(cfg_a) != hash(cfg_b)

    m_flash = tiny_transformer(seq_len=32, cfg=cfg_a, seed=4)  # no attn= needed
    m_dense = tiny_transformer(seq_len=32, cfg=TransformerConfig(**base), seed=4)
    toks = (jnp.arange(32, dtype=jnp.int32) % 64)[None]
    np.testing.assert_allclose(
        np.asarray(m_flash.apply(m_flash.params, toks)),
        np.asarray(m_dense.apply(m_dense.params, toks)),
        atol=5e-2,
    )


def test_autotune_cache_roundtrip(tmp_path):
    """autotune → disk cache → fresh process state → get_flash_config hit
    (write → reload → hit, the CI smoke invariant)."""
    from p2pfl_tpu.settings import Settings

    cache = tmp_path / "tune.json"
    old = Settings.FLASH_TUNE_CACHE
    Settings.FLASH_TUNE_CACHE = str(cache)
    try:
        autotune.clear_memory_cache()
        cands = [FlashConfig(16, 16), FlashConfig(32, 32)]
        best = autotune.autotune_flash(
            32, 16, dtype=jnp.float32, candidates=cands, repeats=1, tune_bwd=False
        )
        assert best in cands
        assert cache.exists()
        # wipe in-process state: the disk entry must serve the config
        autotune.clear_memory_cache()
        got = autotune.get_flash_config(32, 16, dtype=jnp.float32)
        assert got == best
        # a different shape misses the cache and falls to the defaults table
        other = autotune.get_flash_config(64, 128, dtype=jnp.float32)
        assert other == autotune.default_flash_config(64, 128, jnp.float32)
    finally:
        Settings.FLASH_TUNE_CACHE = old
        autotune.clear_memory_cache()


def test_autotune_cache_hit_skips_sweep(tmp_path):
    """A second autotune for a tuned shape returns the cached winner
    without re-sweeping (FLASH_AUTOTUNE model builds pay once per shape):
    if the sweep ran again it would have to return the new candidate."""
    from p2pfl_tpu.settings import Settings

    old = Settings.FLASH_TUNE_CACHE
    Settings.FLASH_TUNE_CACHE = str(tmp_path / "tune.json")
    try:
        autotune.clear_memory_cache()
        first = autotune.autotune_flash(
            32, 16, dtype=jnp.float32, candidates=[FlashConfig(16, 16)],
            repeats=1, tune_bwd=False,
        )
        again = autotune.autotune_flash(
            32, 16, dtype=jnp.float32, candidates=[FlashConfig(32, 32)],
            repeats=1, tune_bwd=False,
        )
        assert again == first == FlashConfig(16, 16)
        forced = autotune.autotune_flash(
            32, 16, dtype=jnp.float32, candidates=[FlashConfig(32, 32)],
            repeats=1, tune_bwd=False, force=True,
        )
        assert forced == FlashConfig(32, 32)
    finally:
        Settings.FLASH_TUNE_CACHE = old
        autotune.clear_memory_cache()


def test_pins_never_persisted_to_disk(tmp_path):
    """pin_flash_config is a session-only override: a subsequent cache
    write (autotune) must not leak the pin into the on-disk tuning data."""
    import json

    from p2pfl_tpu.settings import Settings

    cache = tmp_path / "tune.json"
    old = Settings.FLASH_TUNE_CACHE
    Settings.FLASH_TUNE_CACHE = str(cache)
    try:
        autotune.clear_memory_cache()
        pin = FlashConfig(block_q=8, block_k=8)
        autotune.pin_flash_config(64, 32, pin, dtype=jnp.float32)
        autotune.autotune_flash(
            32, 16, dtype=jnp.float32, candidates=[FlashConfig(16, 16)],
            repeats=1, tune_bwd=False,
        )
        raw = json.loads(cache.read_text())
        assert not any("d=32|t=64" in k for k in raw), raw
        # the pin still wins in-process
        assert autotune.get_flash_config(64, 32, dtype=jnp.float32) == pin
    finally:
        Settings.FLASH_TUNE_CACHE = old
        autotune.clear_memory_cache()


def test_pinned_config_wins_over_defaults():
    autotune.clear_memory_cache()
    try:
        pin = FlashConfig(block_q=8, block_k=8, q_span=2)
        autotune.pin_flash_config(64, 32, pin, dtype=jnp.float32)
        assert autotune.get_flash_config(64, 32, dtype=jnp.float32) == pin
    finally:
        autotune.clear_memory_cache()


def test_defaults_table_fits_shape():
    """Defaults always divide T, tile on Mosaic (multiple of 8 or T itself)
    and keep q_span dividing the q-block count."""
    for t in (8, 32, 96, 512, 2048):
        for d in (32, 64, 128, 256):
            for kind in ("TPU v4", "TPU v5 lite", "cpu"):
                cfg = autotune.default_flash_config(t, d, kind=kind)
                assert t % cfg.block_q == 0 and t % cfg.block_k == 0
                nq = t // cfg.block_q
                assert nq % cfg.q_span == 0
