"""Robust aggregation over the gossip transport: individual-model shipping.

FedMedian/Krum must not be fed pre-averaged partials
(``SUPPORTS_PARTIALS=False``); in gossip mode nodes ship individual models
one per tick. This covers the reference's ``get_partial_aggregation`` /
models-to-send seam (``aggregator.py:249-281``) for the robust family.
"""

import pytest

from p2pfl_tpu.communication.memory import MemoryRegistry
from p2pfl_tpu.learning.aggregators import FedMedian
from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import JaxLearner
from p2pfl_tpu.models import mlp
from p2pfl_tpu.node import Node
from p2pfl_tpu.utils import full_connection, wait_convergence, wait_to_finish, check_equal_models


@pytest.fixture(autouse=True)
def _clean():
    MemoryRegistry.reset()
    yield
    MemoryRegistry.reset()


def test_fedmedian_gossip_three_nodes():
    full = FederatedDataset.synthetic_mnist(n_train=768, n_test=128)
    nodes = []
    for i in range(3):
        learner = JaxLearner(mlp(seed=i), full.partition(i, 3), batch_size=64)
        nodes.append(Node(learner=learner, aggregator=FedMedian()))
    for n in nodes:
        n.start()
    for n in nodes:
        full_connection(n, nodes)
    wait_convergence(nodes, 2, only_direct=True)
    nodes[0].set_start_learning(rounds=1, epochs=0)
    wait_to_finish(nodes, timeout=90)
    check_equal_models(nodes)
    for n in nodes:
        n.stop()
