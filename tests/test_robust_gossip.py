"""Robust aggregation over the gossip transport: individual-model shipping.

FedMedian/Krum must not be fed pre-averaged partials
(``SUPPORTS_PARTIALS=False``); in gossip mode nodes ship individual models
one per tick. This covers the reference's ``get_partial_aggregation`` /
models-to-send seam (``aggregator.py:249-281``) for the robust family.

Also: message-plane robustness against a stalled neighbor — a control
message whose send is skipped because the neighbor has a send stuck past
``GOSSIP_SEND_TIMEOUT`` must be requeued and redelivered once the stall
clears, and ``stop()``/``start()`` must not leak ``_stalled`` state into
the next run.
"""

import threading
import time

import pytest

from p2pfl_tpu.communication.gossiper import Gossiper
from p2pfl_tpu.communication.memory import MemoryRegistry
from p2pfl_tpu.communication.message import Message
from p2pfl_tpu.settings import Settings
from p2pfl_tpu.learning.aggregators import FedMedian
from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import JaxLearner
from p2pfl_tpu.models import mlp
from p2pfl_tpu.node import Node
from p2pfl_tpu.utils import full_connection, wait_convergence, wait_to_finish, check_equal_models


@pytest.fixture(autouse=True)
def _clean():
    MemoryRegistry.reset()
    yield
    MemoryRegistry.reset()


class _StallableTransport:
    """Fake transport: sends to ``stalled`` neighbors block on an event."""

    def __init__(self):
        self.release = threading.Event()
        self.stall_nei: str = ""
        self.delivered: list[tuple[str, str]] = []  # (nei, cmd)
        self.lock = threading.Lock()

    def __call__(self, nei, env, create_connection=False):
        if nei == self.stall_nei and not self.release.is_set():
            self.release.wait(timeout=10)
        with self.lock:
            self.delivered.append((nei, env.cmd))
        return True

    def got(self, nei, cmd):
        with self.lock:
            return (nei, cmd) in self.delivered


def test_message_requeued_after_stall_clears():
    """A control send skipped for a stalled neighbor is NOT lost: it is
    requeued and delivered once the stuck task completes."""
    old_timeout = Settings.GOSSIP_SEND_TIMEOUT
    Settings.GOSSIP_SEND_TIMEOUT = 0.2
    transport = _StallableTransport()
    transport.stall_nei = "peer"
    g = Gossiper("me", transport)
    g.start()
    try:
        # first message's send blocks → exceeds its budget → peer stalled
        g.add_message(Message("me", "first", ()), ["peer"])
        deadline = time.monotonic() + 5.0
        while "peer" not in g._stalled and time.monotonic() < deadline:
            time.sleep(0.02)
        assert "peer" in g._stalled, "stall was never detected"

        # second message: dispatch must skip (not stack another worker
        # behind the stall) and requeue — and must not mark a failure
        g.add_message(Message("me", "second", ()), ["peer"])
        time.sleep(0.5)
        assert not transport.got("peer", "second")

        # stall clears → the requeued message is redelivered
        transport.release.set()
        deadline = time.monotonic() + 5.0
        while not transport.got("peer", "second") and time.monotonic() < deadline:
            time.sleep(0.02)
        assert transport.got("peer", "first")
        assert transport.got("peer", "second"), "requeued message was lost"
        assert "peer" not in g._stalled
    finally:
        Settings.GOSSIP_SEND_TIMEOUT = old_timeout
        g.stop()


def test_stop_start_clears_stalled():
    """A send hung past stop() must not leave its neighbor excluded after
    a fresh start(): the stalled set gets a clean slate."""
    old_timeout = Settings.GOSSIP_SEND_TIMEOUT
    Settings.GOSSIP_SEND_TIMEOUT = 0.2
    transport = _StallableTransport()
    transport.stall_nei = "peer"
    g = Gossiper("me", transport)
    g.start()
    try:
        g.add_message(Message("me", "first", ()), ["peer"])
        deadline = time.monotonic() + 5.0
        while "peer" not in g._stalled and time.monotonic() < deadline:
            time.sleep(0.02)
        assert "peer" in g._stalled
        g.stop()  # the hung send never runs its done-callback

        g.start()
        assert g._stalled == {}, "stalled state leaked across stop()/start()"
        transport.stall_nei = ""  # peer is healthy in the new run
        g.add_message(Message("me", "after-restart", ()), ["peer"])
        deadline = time.monotonic() + 5.0
        while not transport.got("peer", "after-restart") and time.monotonic() < deadline:
            time.sleep(0.02)
        assert transport.got("peer", "after-restart"), "neighbor still excluded after restart"
    finally:
        Settings.GOSSIP_SEND_TIMEOUT = old_timeout
        transport.release.set()
        g.stop()


def test_late_failure_after_stall_is_retried():
    """A control send that overruns GOSSIP_SEND_TIMEOUT and then FAILS on
    its worker is not silently lost: the late outcome feeds the retry
    queue and the message is redelivered (regression — the late result
    used to be discarded, so only prompt failures were retried)."""
    old_timeout = Settings.GOSSIP_SEND_TIMEOUT
    Settings.GOSSIP_SEND_TIMEOUT = 0.2
    release = threading.Event()
    delivered: list[tuple[str, str]] = []
    lock = threading.Lock()
    calls = {"n": 0}

    def transport(nei, env, create_connection=False):
        with lock:
            calls["n"] += 1
            first = calls["n"] == 1
        if first:
            release.wait(timeout=10)
            return False  # hung past the budget, then definitively failed
        with lock:
            delivered.append((nei, env.cmd))
        return True

    g = Gossiper("me", transport)
    g.start()
    try:
        g.add_message(Message("me", "vote", ()), ["peer"])
        deadline = time.monotonic() + 5.0
        while "peer" not in g._stalled and time.monotonic() < deadline:
            time.sleep(0.02)
        assert "peer" in g._stalled, "stall was never detected"
        release.set()  # the hung send now returns False
        deadline = time.monotonic() + 5.0
        while not delivered and time.monotonic() < deadline:
            time.sleep(0.02)
        assert ("peer", "vote") in delivered, "late-failed send was lost"
    finally:
        Settings.GOSSIP_SEND_TIMEOUT = old_timeout
        release.set()
        g.stop()


def test_fedmedian_gossip_three_nodes():
    full = FederatedDataset.synthetic_mnist(n_train=768, n_test=128)
    nodes = []
    for i in range(3):
        learner = JaxLearner(mlp(seed=i), full.partition(i, 3), batch_size=64)
        nodes.append(Node(learner=learner, aggregator=FedMedian()))
    for n in nodes:
        n.start()
    for n in nodes:
        full_connection(n, nodes)
    wait_convergence(nodes, 2, only_direct=True)
    nodes[0].set_start_learning(rounds=1, epochs=0)
    wait_to_finish(nodes, timeout=90)
    check_equal_models(nodes)
    for n in nodes:
        n.stop()
