"""Sequence-parallel training: gradients flow through ring attention.

The long-context path must be trainable, not just a forward op: autodiff
through ``shard_map`` + ``ppermute`` gives the reverse ring automatically.
"""

from functools import partial

import jax
import pytest
import jax.numpy as jnp
import numpy as np
import optax

from p2pfl_tpu.models.transformer import TransformerConfig, tiny_transformer
from p2pfl_tpu.ops.attention import ring_attention
from p2pfl_tpu.parallel.mesh import federation_mesh

CFG = TransformerConfig(vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=4, ffn_hidden=128)


def _loss_fn(model):
    def loss(params, x, y):
        logits = model.module.apply({"params": params}, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    return loss


@pytest.mark.slow
def test_ring_attention_gradients_match_dense():
    mesh = federation_mesh(model_parallel=4, devices=jax.devices()[:4])
    attn = partial(ring_attention, mesh=mesh, axis_name="model")
    seq = 64

    m_ring = tiny_transformer(seq_len=seq, cfg=CFG, attn_fn=attn, seed=11)
    m_dense = tiny_transformer(seq_len=seq, cfg=CFG, seed=11)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, CFG.vocab_size, size=(2, seq)), jnp.int32)
    y = jnp.asarray(rng.integers(0, CFG.vocab_size, size=(2, seq)), jnp.int32)

    g_ring = jax.grad(_loss_fn(m_ring))(m_ring.params, x, y)
    g_dense = jax.grad(_loss_fn(m_dense))(m_dense.params, x, y)
    leaves_r, leaves_d = jax.tree.leaves(g_ring), jax.tree.leaves(g_dense)
    assert len(leaves_r) == len(leaves_d)
    for a, b in zip(leaves_r, leaves_d):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32), atol=5e-3
        )


@pytest.mark.slow
def test_ring_transformer_train_step_reduces_loss():
    mesh = federation_mesh(model_parallel=8)
    attn = partial(ring_attention, mesh=mesh, axis_name="model")
    seq = 64
    model = tiny_transformer(seq_len=seq, cfg=CFG, attn_fn=attn, seed=1)
    loss_fn = _loss_fn(model)

    tx = optax.adam(1e-2)
    params = model.params
    opt = tx.init(params)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.integers(0, CFG.vocab_size, size=(4, seq)), jnp.int32)
    # learnable: predict the same token (copy task on constant targets)
    y = jnp.tile(jnp.arange(seq, dtype=jnp.int32)[None] % CFG.vocab_size, (4, 1))

    @jax.jit
    def step(params, opt):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, updates), opt, loss

    first = None
    for _ in range(10):
        params, opt, loss = step(params, opt)
        first = float(loss) if first is None else first
    assert float(loss) < first * 0.7
