"""Real-data ingress: ``FederatedDataset.from_idx`` through a federated
round (ISSUE 3 satellite). The committed fixture (tests/fixtures/idx,
~10 KB gzipped, regenerate with tests/fixtures/generate_idx.py) is the
first code path a real-data user hits — previously never executed.
"""

import os

import numpy as np
import pytest

from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.models import mlp
from p2pfl_tpu.parallel import ChunkedFederation, SpmdFederation

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "idx")


def test_from_idx_loads_gzipped_fixture():
    data = FederatedDataset.from_idx(FIXTURE)
    assert data.source == "idx"
    assert data.x_train.shape == (128, 8, 8, 1) and data.x_train.dtype == np.float32
    assert data.y_train.shape == (128,) and data.y_train.dtype == np.int32
    assert data.x_test.shape == (32, 8, 8, 1)
    assert float(data.x_train.max()) <= 1.0 and float(data.x_train.min()) >= 0.0
    assert set(np.unique(data.y_train)) <= set(range(10))


def test_mnist_dispatcher_prefers_idx_dir():
    data = FederatedDataset.mnist(FIXTURE)
    assert data.source == "idx"
    # a directory without IDX files falls back to synthetic
    assert FederatedDataset.mnist(os.path.dirname(FIXTURE), n_train=64, n_test=16).source == "synthetic"


def test_from_idx_through_federated_round():
    """One SPMD round + eval on the IDX data: partitioning, staging, and
    the round program all consume the loader's dtypes/shapes."""
    data = FederatedDataset.from_idx(FIXTURE)
    fed = SpmdFederation.from_dataset(
        mlp(input_shape=(8, 8, 1)), data, n_nodes=2, batch_size=16,
        vote=False, seed=3,
    )
    entry = fed.run_round(epochs=1, eval=True)
    assert np.isfinite(float(entry["train_loss"]))
    assert 0.0 <= float(entry["test_acc"]) <= 1.0


def test_from_idx_through_chunked_round():
    """Same witness through the chunked (time-shared) executor's
    overlapped staging path."""
    data = FederatedDataset.from_idx(FIXTURE)
    fed = ChunkedFederation.from_dataset(
        mlp(input_shape=(8, 8, 1)), data, n_nodes=2, chunk_size=1,
        batch_size=16, vote=False, seed=3,
    )
    entry = fed.run_round(epochs=1, eval=True)
    assert np.isfinite(float(entry["train_loss"]))


@pytest.mark.slow
def test_idx_federation_learns():
    """A few rounds on the fixture beat chance (10 classes → 0.1)."""
    data = FederatedDataset.from_idx(FIXTURE)
    fed = SpmdFederation.from_dataset(
        mlp(input_shape=(8, 8, 1)), data, n_nodes=2, batch_size=16,
        vote=False, seed=3,
    )
    fed.run(rounds=5, epochs=2)
    assert fed.evaluate()["test_acc"] > 0.3
