"""Management layer tests: CLI discovery, monitor, checkpointing, web client."""

import threading
import time

import numpy as np
import pytest


def test_cli_experiment_list(capsys):
    from p2pfl_tpu.cli import main

    assert main(["experiment", "list"]) == 0
    out = capsys.readouterr().out
    assert "mnist" in out and "spmd_mnist" in out
    # piped/non-TTY stdout (pytest capture) keeps the plain parseable
    # two-column form — no box glyphs, no ANSI
    assert "┌" not in out and "\033[" not in out


def test_cli_experiment_list_fancy_on_tty(capsys, monkeypatch):
    """Reference-parity UX (Typer/Rich stand-in, reference cli.py:30-125):
    banner + box-drawing table on an interactive UTF-8 terminal."""
    import p2pfl_tpu.cli as cli

    monkeypatch.setattr(cli, "_fancy", lambda: True)
    assert cli.main(["experiment", "list"]) == 0
    out = capsys.readouterr().out
    assert "┌" in out and "│ experiment" in out and "└" in out
    assert "mnist" in out


def test_cli_table_renders_rows():
    from p2pfl_tpu.cli import _table

    t = _table(["a", "bb"], [["x", "y"], ["longer", "z"]])
    lines = t.splitlines()
    assert lines[0].startswith("┌") and lines[-1].startswith("└")
    assert len({len(line) for line in lines}) == 1  # aligned columns
    assert "longer" in t and "bb" in t


def test_cli_unknown_experiment():
    from p2pfl_tpu.cli import main

    assert main(["experiment", "run", "nope"]) == 1


def test_node_monitor_reports():
    from p2pfl_tpu.management.node_monitor import NodeMonitor
    from p2pfl_tpu.settings import Settings

    Settings.RESOURCE_MONITOR_PERIOD = 0.05
    seen = []
    mon = NodeMonitor("test-node", report_fn=lambda n, m, v: seen.append((m, v)))
    mon.start()
    time.sleep(0.4)
    mon.stop()
    metrics = {m for m, _ in seen}
    assert "cpu_percent" in metrics and "ram_percent" in metrics


def test_web_services_swallow_failures():
    """A dead dashboard must never raise into the caller."""
    from p2pfl_tpu.management.web_services import WebServices

    ws = WebServices("http://127.0.0.1:1", "key", timeout=0.2)
    ws.register_node("n1")  # nothing listening — must not raise
    ws.send_global_metric("e", 0, "acc", "n1", 0.5)


def test_web_services_posts(tmp_path):
    """Round-trip against a local HTTP server: headers + payloads correct."""
    import http.server
    import json

    received = []

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            body = self.rfile.read(int(self.headers["Content-Length"]))
            received.append((self.path, self.headers.get("x-api-key"), json.loads(body)))
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(b'{"node_key": "k1"}')

        def log_message(self, *a):  # silence
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        from p2pfl_tpu.management.web_services import WebServices

        ws = WebServices(f"http://127.0.0.1:{srv.server_port}", "secret")
        ws.register_node("n1", is_simulated=True)
        ws.send_local_metric("exp", 1, "loss", "n1", 5, 0.25)
        assert received[0][0] == "/node" and received[0][1] == "secret"
        assert received[1][2]["metric"] == "loss" and received[1][2]["step"] == 5
        assert ws._node_key == "k1"
    finally:
        srv.shutdown()


def test_learner_checkpoint_roundtrip(tmp_path):
    from p2pfl_tpu.learning.checkpoint import restore_learner, save_learner
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.learning.learner import JaxLearner
    from p2pfl_tpu.models import mlp

    data = FederatedDataset.synthetic_mnist(n_train=256, n_test=64)
    learner = JaxLearner(mlp(), data, batch_size=64)
    learner.fit()
    import jax

    want = jax.tree.leaves(learner.params)

    other = JaxLearner(mlp(seed=9), data, batch_size=64)
    save_learner(str(tmp_path / "ckpt"), learner, round=3)
    restore_learner(str(tmp_path / "ckpt"), other)
    got = jax.tree.leaves(other.params)
    for a, b in zip(want, got):
        np.testing.assert_array_equal(np.asarray(a, np.float32), np.asarray(b, np.float32))


def test_federation_checkpoint_roundtrip(tmp_path):
    import jax

    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.models import mlp
    from p2pfl_tpu.parallel import SpmdFederation

    data = FederatedDataset.synthetic_mnist(n_train=1024, n_test=128)
    fed = SpmdFederation.from_dataset(mlp(), data, n_nodes=4, batch_size=64, vote=False)
    fed.run_round()
    fed.save(str(tmp_path / "fed"))

    fed2 = SpmdFederation.from_dataset(mlp(seed=5), data, n_nodes=4, batch_size=64, vote=False)
    fed2.restore(str(tmp_path / "fed"))
    assert fed2.round == 1
    for a, b in zip(jax.tree.leaves(fed.params), jax.tree.leaves(fed2.params)):
        np.testing.assert_array_equal(np.asarray(a, np.float32), np.asarray(b, np.float32))
