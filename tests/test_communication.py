"""Communication-layer acceptance tests.

Mirrors the reference's ``test/communication_test.py`` scenarios (SURVEY §4):
connect/disconnect pairs, full mesh + star with staged teardown, invalid
addresses, unknown commands, abrupt node death with heartbeat eviction — all
over the in-memory transport with N real Node objects in one process.
"""

import time

import pytest

from p2pfl_tpu.communication.memory import InMemoryProtocol, MemoryRegistry
from p2pfl_tpu.node import Node
from p2pfl_tpu.settings import Settings
from p2pfl_tpu.utils import full_connection, wait_convergence


@pytest.fixture(autouse=True)
def _clean_registry():
    MemoryRegistry.reset()
    yield
    MemoryRegistry.reset()


def _make_nodes(n):
    nodes = [Node() for _ in range(n)]
    for node in nodes:
        node.start()
    return nodes


def _stop_all(nodes):
    for n in nodes:
        n.stop()


def test_connect_disconnect_pair():
    n1, n2 = _make_nodes(2)
    assert n1.connect(n2.addr)
    wait_convergence([n1, n2], 1, only_direct=True)
    n1.disconnect(n2.addr)
    time.sleep(0.1)
    assert len(n1.get_neighbors(only_direct=True)) == 0
    assert len(n2.get_neighbors(only_direct=True)) == 0
    _stop_all([n1, n2])


def test_connect_invalid_address():
    (n1,) = _make_nodes(1)
    assert not n1.connect("nonexistent-node")
    assert len(n1.get_neighbors()) == 0
    _stop_all([n1])


def test_self_connect_rejected():
    (n1,) = _make_nodes(1)
    assert not n1.connect(n1.addr)
    _stop_all([n1])


def test_full_mesh_and_staged_teardown():
    nodes = _make_nodes(4)
    for node in nodes:
        full_connection(node, nodes)
    wait_convergence(nodes, 3, only_direct=True)
    # staged teardown: stop nodes one by one, remaining overlay shrinks
    for i, victim in enumerate(nodes[:-1]):
        victim.stop()
        rest = nodes[i + 1 :]
        wait_convergence(rest, len(rest) - 1, only_direct=True, wait=5)
    nodes[-1].stop()


def test_star_topology_discovery():
    """Non-direct discovery: leaves of a star learn about each other via beats."""
    hub, *leaves = _make_nodes(4)
    for leaf in leaves:
        leaf.connect(hub.addr)
    # every node should discover all 3 others (direct or via TTL-flooded beats)
    wait_convergence([hub, *leaves], 3, only_direct=False, wait=5)
    # but leaves have exactly one DIRECT neighbor
    assert all(len(leaf.get_neighbors(only_direct=True)) == 1 for leaf in leaves)
    _stop_all([hub, *leaves])


def test_unknown_command():
    n1, n2 = _make_nodes(2)
    n1.connect(n2.addr)
    wait_convergence([n1, n2], 1, only_direct=True)
    res = n2.protocol.handle_message(n1.protocol.build_msg("no_such_command"))
    assert not res.ok
    _stop_all([n1, n2])


def test_node_abrupt_down_evicted_by_heartbeat():
    nodes = _make_nodes(3)
    for node in nodes:
        full_connection(node, nodes)
    wait_convergence(nodes, 2, only_direct=True)
    # kill node 0 abruptly: silence its heartbeater + unregister its server
    victim = nodes[0]
    victim.protocol.heartbeater.stop()
    victim.protocol._server_stop()
    deadline = time.monotonic() + Settings.HEARTBEAT_TIMEOUT * 4
    while time.monotonic() < deadline:
        if all(victim.addr not in n.get_neighbors() for n in nodes[1:]):
            break
        time.sleep(0.05)
    assert all(victim.addr not in n.get_neighbors() for n in nodes[1:])
    _stop_all(nodes)  # incl. the half-dead victim: its gossiper thread and
    # node registration would otherwise leak into every later test that
    # reuses the default "node-1" address


def test_send_failure_evicts_neighbor():
    """Send failures no longer evict instantly (the reference's behavior,
    which also silently lost the message): the failed send is retried with
    backoff while consecutive failures open the circuit breaker, and the
    heartbeater evicts the suspect on its accelerated clock — bounded, but
    not synchronous (communication/reliability.py)."""
    import time as _time

    n1, n2 = _make_nodes(2)
    n1.connect(n2.addr)
    wait_convergence([n1, n2], 1, only_direct=True)
    # n2's server vanishes without disconnecting
    n2.protocol._server_stop()
    ok = n1.protocol.send(n2.addr, n1.protocol.build_msg("beat", ["0"]))
    assert not ok
    # still a neighbor right after ONE failure — one transient failure is
    # not death anymore
    assert n2.addr in n1.get_neighbors()
    # ...but sustained failure opens the breaker and eviction follows
    # within the suspect window, well before HEARTBEAT_TIMEOUT would fire
    deadline = _time.monotonic() + 10.0
    while n2.addr in n1.get_neighbors() and _time.monotonic() < deadline:
        _time.sleep(0.05)
    assert n2.addr not in n1.get_neighbors()
    from p2pfl_tpu.management.logger import logger as _logger

    assert _logger.get_comm_metrics(n1.addr).get("breaker_open", 0) >= 1
    _stop_all([n1, n2])


def test_message_dedup_and_ttl_flood():
    """A broadcast floods the overlay exactly once per node (TTL + dedup)."""
    nodes = _make_nodes(3)
    # line topology: 0 - 1 - 2; node 2 is NOT a direct neighbor of 0
    nodes[0].connect(nodes[1].addr)
    nodes[1].connect(nodes[2].addr)
    wait_convergence(nodes, 2, only_direct=False, wait=5)

    seen = []

    class Probe:
        @staticmethod
        def get_name():
            return "probe"

        def execute(self, source, round, *args, **kwargs):  # noqa: A002
            seen.append(args[0])

    for node in nodes:
        node.protocol.add_command(Probe())
    nodes[0].protocol.broadcast(nodes[0].protocol.build_msg("probe", ["x1"]))
    deadline = time.monotonic() + 3
    while len(seen) < 2 and time.monotonic() < deadline:
        time.sleep(0.05)
    time.sleep(0.3)  # allow any duplicate deliveries to surface
    assert seen.count("x1") == 2  # nodes 1 and 2, exactly once each
    _stop_all(nodes)
