"""Core learning-layer tests: codec roundtrip, aggregation kernels, the
partial-aggregation algebra. Parity with reference ``test/learning_test.py``
(encode/decode identity 38-47, FedAvg hand-built + weighted 50-71) plus the
robust aggregators the reference lacks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from p2pfl_tpu.exceptions import DecodingParamsError, ModelNotMatchingError
from p2pfl_tpu.learning.aggregators import FedAvg, FedMedian, Krum, TrimmedMean
from p2pfl_tpu.learning.weights import ModelUpdate, decode_params, encode_params, restore_like
from p2pfl_tpu.ops.tree import tree_allclose, tree_stack, tree_weighted_mean


def params_like(seed: float, dtype="float32"):
    return {
        "dense": {"kernel": jnp.full((4, 3), seed, dtype), "bias": jnp.full((3,), seed, dtype)},
        "out": {"kernel": jnp.full((3, 2), 2 * seed, dtype)},
    }


# ---- codec ----

def test_encode_decode_roundtrip():
    p = params_like(1.5)
    restored = restore_like(p, decode_params(encode_params(p)))
    assert tree_allclose(p, restored, atol=0)
    # re-encode identity (reference learning_test.py:38-47)
    assert encode_params(restored) == encode_params(p)


def test_encode_decode_bfloat16():
    p = params_like(0.25, dtype="bfloat16")
    restored = restore_like(p, decode_params(encode_params(p)))
    assert all(x.dtype == jnp.bfloat16 for x in jax.tree.leaves(restored))
    assert tree_allclose(p, restored, atol=0)


def test_decode_garbage_raises():
    with pytest.raises(DecodingParamsError):
        decode_params(b"not a weights payload at all")


def test_restore_structure_mismatch_raises():
    p = params_like(1.0)
    other = {"different": {"kernel": jnp.ones((4, 3))}}
    with pytest.raises(ModelNotMatchingError):
        restore_like(other, decode_params(encode_params(p)))


def test_restore_shape_mismatch_raises():
    p = params_like(1.0)
    bad = jax.tree.map(lambda x: jnp.zeros(x.shape + (1,), x.dtype), p)
    with pytest.raises(ModelNotMatchingError):
        restore_like(bad, decode_params(encode_params(p)))


# ---- pure aggregation math ----

def test_weighted_mean_hand_values():
    a, b = params_like(1.0), params_like(3.0)
    # equal weights -> plain mean
    out = tree_weighted_mean([a, b], [1.0, 1.0])
    assert tree_allclose(out, params_like(2.0), atol=1e-6)
    # 3:1 weights
    out = tree_weighted_mean([a, b], [3.0, 1.0])
    assert tree_allclose(out, params_like(1.5), atol=1e-6)


def test_fedavg_aggregator_weighted_by_samples():
    agg = FedAvg("n0")
    agg.set_nodes_to_aggregate(["n0", "n1"])
    agg.add_model(ModelUpdate(params_like(0.0), ["n0"], num_samples=1))
    agg.add_model(ModelUpdate(params_like(4.0), ["n1"], num_samples=3))
    result = agg.wait_and_get_aggregation(timeout=1)
    assert tree_allclose(result.params, params_like(3.0), atol=1e-6)
    assert result.contributors == ["n0", "n1"]
    assert result.num_samples == 4


def test_fedmedian_ignores_outlier():
    models = [ModelUpdate(params_like(v), [f"n{i}"]) for i, v in enumerate([1.0, 1.0, 1.0, 1000.0])]
    agg = FedMedian("n0")
    out = agg.aggregate(models)
    assert tree_allclose(out.params, params_like(1.0), atol=1e-5)


def test_trimmed_mean_ignores_outliers():
    vals = [1.0, 1.0, 1.0, 1.0, -500.0, 500.0]
    models = [ModelUpdate(params_like(v), [f"n{i}"]) for i, v in enumerate(vals)]
    out = TrimmedMean("n0", trim=1).aggregate(models)
    assert tree_allclose(out.params, params_like(1.0), atol=1e-5)


def test_krum_picks_clustered_model():
    # 4 honest models near 1.0, 1 byzantine at 100 — krum must pick an honest one
    vals = [1.0, 1.01, 0.99, 1.0, 100.0]
    models = [ModelUpdate(params_like(v), [f"n{i}"]) for i, v in enumerate(vals)]
    out = Krum("n0", n_byzantine=1).aggregate(models)
    assert tree_allclose(out.params, params_like(1.0), atol=0.05)


# ---- partial-aggregation algebra (reference aggregator.py:117-281) ----

def test_partial_accumulation_completes():
    agg = FedAvg("n0")
    agg.set_nodes_to_aggregate(["a", "b", "c"])
    assert agg.add_model(ModelUpdate(params_like(1.0), ["a"])) == ["a"]
    assert agg.add_model(ModelUpdate(params_like(2.0), ["b", "c"], num_samples=2)) == ["a", "b", "c"]
    out = agg.wait_and_get_aggregation(timeout=1)
    assert set(out.contributors) == {"a", "b", "c"}


def test_full_set_replaces_partials():
    agg = FedAvg("n0")
    agg.set_nodes_to_aggregate(["a", "b"])
    agg.add_model(ModelUpdate(params_like(5.0), ["a"]))
    agg.add_model(ModelUpdate(params_like(7.0), ["a", "b"], num_samples=2))
    out = agg.wait_and_get_aggregation(timeout=1)
    # full-coverage model replaced the partial entirely (reference 156-168)
    assert tree_allclose(out.params, params_like(7.0), atol=1e-6)


def test_overlapping_and_foreign_rejected():
    agg = FedAvg("n0")
    agg.set_nodes_to_aggregate(["a", "b", "c"])
    agg.add_model(ModelUpdate(params_like(1.0), ["a", "b"], num_samples=2))
    assert agg.add_model(ModelUpdate(params_like(9.0), ["b"])) == []       # overlap
    assert agg.add_model(ModelUpdate(params_like(9.0), ["zz"])) == []      # foreign
    assert agg.add_model(ModelUpdate(params_like(9.0), [])) == []          # empty
    assert agg.get_aggregated_models() == ["a", "b"]


def test_timeout_aggregates_partial_coverage():
    agg = FedAvg("n0")
    agg.set_nodes_to_aggregate(["a", "b"])
    agg.add_model(ModelUpdate(params_like(2.0), ["a"]))
    out = agg.wait_and_get_aggregation(timeout=0.1)  # 'b' never arrives
    assert tree_allclose(out.params, params_like(2.0), atol=1e-6)
    assert out.contributors == ["a"]


def test_waiting_mode_takes_first_model():
    agg = FedAvg("n0")
    agg.set_waiting_aggregated_model(["a", "b"])
    agg.add_model(ModelUpdate(params_like(3.0), ["a", "b"], num_samples=2))
    out = agg.wait_and_get_aggregation(timeout=1)
    assert tree_allclose(out.params, params_like(3.0), atol=1e-6)


def test_get_partial_aggregation_excludes_covered():
    agg = FedAvg("n0")
    agg.set_nodes_to_aggregate(["a", "b", "c"])
    agg.add_model(ModelUpdate(params_like(1.0), ["a"]))
    agg.add_model(ModelUpdate(params_like(3.0), ["b"]))
    # peer already has 'b' -> partial must only cover 'a'
    partial = agg.get_partial_aggregation(["b"])
    assert partial.contributors == ["a"]
    assert tree_allclose(partial.params, params_like(1.0), atol=1e-6)
    # peer has everything -> nothing to send
    assert agg.get_partial_aggregation(["a", "b"]) is None


def test_waiting_mode_first_update_wins():
    agg = FedAvg("n0")
    agg.set_waiting_aggregated_model(["a", "b"])
    agg.add_model(ModelUpdate(params_like(3.0), ["a", "b"], num_samples=2))
    assert agg.add_model(ModelUpdate(params_like(9.0), ["a"])) == []
    out = agg.wait_and_get_aggregation(timeout=1)
    assert tree_allclose(out.params, params_like(3.0), atol=1e-6)


def test_waiting_mode_rejects_partial_coverage():
    """While waiting, only a full-train-set aggregate is acceptable
    (reference aggregator.py:139-146) — a stray single-model partial must
    not become the node's "aggregated model" (poisoning hole)."""
    agg = FedAvg("n0")
    agg.set_waiting_aggregated_model(["a", "b"])
    assert agg.add_model(ModelUpdate(params_like(9.0), ["a"])) == []
    assert agg.add_model(ModelUpdate(params_like(3.0), ["a", "b"], num_samples=2)) == ["a", "b"]
    out = agg.wait_and_get_aggregation(timeout=1)
    assert tree_allclose(out.params, params_like(3.0), atol=1e-6)


def test_jax_learner_keep_opt_state():
    """Node-mode twin of SpmdFederation(keep_opt_state=True): Adam moments
    survive set_parameters instead of being reset each round."""
    from p2pfl_tpu.learning.dataset import FederatedDataset
    from p2pfl_tpu.learning.learner import JaxLearner
    from p2pfl_tpu.models import mlp

    data = FederatedDataset.synthetic_mnist(n_train=256, n_test=64)

    keeper = JaxLearner(mlp(), data, epochs=1, batch_size=64, keep_opt_state=True)
    keeper.fit()
    trained_opt = keeper.opt_state
    keeper.set_parameters(keeper.get_parameters())
    assert keeper.opt_state is trained_opt  # moments carried across the round

    resetter = JaxLearner(mlp(), data, epochs=1, batch_size=64)
    resetter.fit()
    resetter.set_parameters(resetter.get_parameters())
    mu = jax.tree.leaves(resetter.opt_state[0].mu)
    assert all(float(jnp.abs(m).max()) == 0.0 for m in mu)  # reference reset


def test_robust_aggregator_rejects_partials():
    agg = FedMedian("n0")
    agg.set_nodes_to_aggregate(["a", "b", "c"])
    # a pre-averaged partial would poison the median — must be rejected
    assert agg.add_model(ModelUpdate(params_like(2.0), ["a", "b"], num_samples=2)) == []
    assert agg.add_model(ModelUpdate(params_like(1.0), ["a"])) == ["a"]
    # full coverage (diffusion of the final aggregate) is still accepted
    assert agg.add_model(ModelUpdate(params_like(5.0), ["a", "b", "c"], num_samples=3)) == ["a", "b", "c"]


def test_get_models_to_send_robust_sends_individuals():
    agg = FedMedian("n0")
    agg.set_nodes_to_aggregate(["a", "b", "c"])
    agg.add_model(ModelUpdate(params_like(1.0), ["a"]))
    agg.add_model(ModelUpdate(params_like(3.0), ["b"]))
    sends = agg.get_models_to_send(["c"])
    assert sorted(tuple(m.contributors) for m in sends) == [("a",), ("b",)]
    # fedavg pre-aggregates instead
    agg2 = FedAvg("n0")
    agg2.set_nodes_to_aggregate(["a", "b", "c"])
    agg2.add_model(ModelUpdate(params_like(1.0), ["a"]))
    agg2.add_model(ModelUpdate(params_like(3.0), ["b"]))
    sends2 = agg2.get_models_to_send(["c"])
    assert len(sends2) == 1 and sorted(sends2[0].contributors) == ["a", "b"]


def test_timeout_closes_window_for_next_round():
    agg = FedAvg("n0")
    agg.set_nodes_to_aggregate(["a", "b"])
    agg.add_model(ModelUpdate(params_like(2.0), ["a"]))
    agg.wait_and_get_aggregation(timeout=0.05)
    # late update for the finished round is rejected...
    assert agg.add_model(ModelUpdate(params_like(9.0), ["b"])) == []
    # ...and the next round can start without an explicit clear()
    agg.set_nodes_to_aggregate(["a", "b"])
    agg.clear()


def test_decode_inconsistent_header_raises():
    import json as _json
    import struct as _struct

    p = params_like(1.0)
    payload = bytearray(encode_params(p))
    (hlen,) = _struct.unpack("<I", payload[4:8])
    header = _json.loads(payload[8 : 8 + hlen])
    header["t"][0]["n"] += 4  # corrupt the byte count
    new_header = _json.dumps(header).encode()
    corrupted = payload[:4] + _struct.pack("<I", len(new_header)) + new_header + payload[8 + hlen :]
    with pytest.raises(DecodingParamsError):
        decode_params(bytes(corrupted))


def test_double_start_raises():
    agg = FedAvg("n0")
    agg.set_nodes_to_aggregate(["a"])
    with pytest.raises(Exception):
        agg.set_nodes_to_aggregate(["a"])
    agg.clear()
    agg.set_nodes_to_aggregate(["a"])  # ok after clear


@pytest.mark.slow
def test_vit_forward_and_federated_training():
    """ViT (attention-based vision model — beyond the reference's MLP/CNN):
    forward shape, then an SPMD federation learns on CIFAR-shaped data."""
    import jax.numpy as jnp

    from p2pfl_tpu.models import vit
    from p2pfl_tpu.parallel import SpmdFederation

    # CIFAR-shaped forward at the default size
    m = vit(dim=32, depth=2, heads=2)
    x = jnp.zeros((4, 32, 32, 3))
    assert m.apply(m.params, x).shape == (4, 10)

    from p2pfl_tpu.learning.dataset import FederatedDataset

    # training run kept CPU-mesh-sized: 16x16 images (16 tokens), f32
    # (bf16 is software-emulated on CPU), ~100 local steps with carried
    # Adam moments — a transformer at chance after 2 rounds is expected,
    # not a bug
    data = FederatedDataset.synthetic_mnist(
        n_train=2048, n_test=512, dim=(16, 16, 3), noise=0.5
    )
    m = vit(dim=32, depth=2, heads=2, input_shape=(16, 16, 3), dtype=jnp.float32)
    fed = SpmdFederation.from_dataset(
        m, data, n_nodes=4, batch_size=128, vote=False,
        learning_rate=3e-3, keep_opt_state=True,
    )
    before = fed.evaluate()["test_acc"]
    fed.run(rounds=12, epochs=2)
    after = fed.evaluate()["test_acc"]
    assert after > max(before, 0.5)


@pytest.mark.slow
def test_bulyan_resists_coordinate_attack():
    """Bulyan (Krum select + trimmed mean) survives both large-distance
    outliers AND the 'a little is enough' per-coordinate attack; needs
    N >= 4f + 3."""
    from p2pfl_tpu.learning.aggregators import Bulyan
    from p2pfl_tpu.ops.aggregation import bulyan
    from p2pfl_tpu.ops.tree import tree_stack

    rng = np.random.default_rng(0)
    honest = [
        {"w": jnp.asarray(1.0 + 0.01 * rng.normal(size=8), jnp.float32)} for _ in range(6)
    ]
    # f=1 attacker: close enough to pass Krum, one coordinate poisoned
    atk = {"w": honest[0]["w"].at[3].add(0.5)}
    models = [ModelUpdate(p, [f"n{i}"], 10) for i, p in enumerate(honest + [atk])]

    agg = Bulyan("me", n_byzantine=1)
    result = agg.aggregate(models)
    # the poisoned coordinate is trimmed away: stays near the honest 1.0
    assert abs(float(result.params["w"][3]) - 1.0) < 0.05
    assert result.contributors == [f"n{i}" for i in range(7)]

    with pytest.raises(ValueError, match="4f"):
        bulyan(tree_stack([m.params for m in models[:5]]), n_byzantine=1)


def test_synthetic_lm_domain_shift():
    """shift_frac re-deranges part of the successor table: the shifted
    dataset is a DIFFERENT chain (same seed), and every selected token's
    successor actually changes (cyclic rotation, no fixed points)."""
    from p2pfl_tpu.learning.dataset import FederatedDataset

    base = FederatedDataset.synthetic_lm(vocab_size=64, seq_len=32, n_train=32, n_test=16)
    shifted = FederatedDataset.synthetic_lm(
        vocab_size=64, seq_len=32, n_train=32, n_test=16, shift_frac=0.25
    )
    same = FederatedDataset.synthetic_lm(vocab_size=64, seq_len=32, n_train=32, n_test=16)
    import numpy as np

    assert np.array_equal(base.x_train, same.x_train)  # deterministic
    assert not np.array_equal(base.x_train, shifted.x_train)  # shifted domain
