"""Secure aggregation (pairwise masking, ``learning/secagg.py``).

The reference has no privacy layer; this is a beyond-parity capability:
DH key agreement over the gossip overlay, pairwise Gaussian masks that
cancel in the sample-weighted FedAvg sum, end-to-end federation with
SECURE_AGGREGATION on, and the device-side masking op on the mesh.
"""

import time

import numpy as np
import pytest

from p2pfl_tpu.communication.memory import MemoryRegistry
from p2pfl_tpu.learning import secagg
from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import JaxLearner
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.models import mlp
from p2pfl_tpu.node import Node
from p2pfl_tpu.settings import Settings
from p2pfl_tpu.utils import (
    check_equal_models,
    full_connection,
    wait_convergence,
    wait_to_finish,
)


@pytest.fixture(autouse=True)
def _clean():
    MemoryRegistry.reset()
    yield
    MemoryRegistry.reset()
    Settings.SECURE_AGGREGATION = False


def test_dh_pair_seed_symmetric():
    xa, pa = secagg.dh_keypair()
    xb, pb = secagg.dh_keypair()
    assert secagg.dh_pair_seed(xa, pb, "exp") == secagg.dh_pair_seed(xb, pa, "exp")
    # different experiment context → different seed
    assert secagg.dh_pair_seed(xa, pb, "exp") != secagg.dh_pair_seed(xa, pb, "exp2")


def _mask_for(addr, addrs, privs, pubs, params, num_samples, round_no=0):
    u = ModelUpdate(params, [addr], num_samples)
    return secagg.mask_update(u, addr, addrs, privs[addr], pubs, "exp", round_no)


def test_masks_cancel_in_weighted_fedavg():
    """Σ w_i · masked_i == Σ w_i · p_i once every pair is present."""
    addrs = ["a", "b", "c", "d"]
    keys = {n: secagg.dh_keypair() for n in addrs}
    privs = {n: k[0] for n, k in keys.items()}
    weights = {"a": 10, "b": 20, "c": 30, "d": 40}
    pubs = {n: (keys[n][1], weights[n]) for n in addrs}
    rng = np.random.default_rng(0)
    params = {n: {"w": rng.normal(size=(16, 8)).astype(np.float32)} for n in addrs}

    masked = {
        n: _mask_for(n, addrs, privs, pubs, params[n], weights[n]) for n in addrs
    }
    # individual masked models are far from the raw ones (privacy)
    for n in addrs:
        delta = np.asarray(masked[n].params["w"]) - params[n]["w"]
        assert np.std(delta) > 1.0, np.std(delta)

    w_total = sum(weights.values())
    true_avg = sum(weights[n] * params[n]["w"] for n in addrs) / w_total
    masked_avg = sum(
        weights[n] * np.asarray(masked[n].params["w"], np.float64) for n in addrs
    ) / w_total
    np.testing.assert_allclose(masked_avg, true_avg, atol=1e-3)


def test_mask_fresh_per_round():
    addrs = ["a", "b"]
    keys = {n: secagg.dh_keypair() for n in addrs}
    privs = {n: k[0] for n, k in keys.items()}
    pubs = {n: (k[1], 1) for n, k in keys.items()}
    p = {"w": np.zeros((4, 4), np.float32)}
    m0 = _mask_for("a", addrs, privs, pubs, p, 1, round_no=0)
    m1 = _mask_for("a", addrs, privs, pubs, p, 1, round_no=1)
    assert not np.allclose(np.asarray(m0.params["w"]), np.asarray(m1.params["w"]))


def test_unsafe_masking_raises_never_unmasked():
    """Missing keys / zero weight / non-fp32 params must raise SecAggError —
    an unmasked fallback would leave peers' pair masks uncancelled in a
    full-coverage aggregate, undetected noise."""
    from p2pfl_tpu.exceptions import SecAggError

    addrs = ["a", "b"]
    priv, pub = secagg.dh_keypair()
    priv_b, pub_b = secagg.dh_keypair()
    p32 = {"w": np.ones((2, 2), np.float32)}

    with pytest.raises(SecAggError, match="missing DH"):
        secagg.mask_update(ModelUpdate(p32, ["a"], 5), "a", addrs, priv, {}, "exp", 0)
    with pytest.raises(SecAggError, match="zero sample"):
        secagg.mask_update(ModelUpdate(p32, ["a"], 0), "a", addrs, priv, {"b": (pub_b, 5)}, "exp", 0)
    import jax.numpy as jnp

    p16 = {"w": jnp.ones((2, 2), jnp.bfloat16)}
    with pytest.raises(SecAggError, match="float32"):
        secagg.mask_update(ModelUpdate(p16, ["a"], 5), "a", addrs, priv, {"b": (pub_b, 5)}, "exp", 0)
    # lossy wire compression breaks cancellation — refused up front
    Settings.WIRE_COMPRESSION = "int8"
    try:
        with pytest.raises(SecAggError, match="lossless"):
            secagg.mask_update(ModelUpdate(p32, ["a"], 5), "a", addrs, priv, {"b": (pub_b, 5)}, "exp", 0)
    finally:
        Settings.WIRE_COMPRESSION = "none"


def test_degenerate_dh_keys_rejected():
    """pub ∈ {0, 1, p-1} makes the shared secret computable from public
    info (an active attacker could strip a victim's masks) — rejected at
    both the command layer and seed derivation."""
    from p2pfl_tpu.exceptions import SecAggError
    from p2pfl_tpu.commands.control import SecAggPubCommand
    from p2pfl_tpu.node_state import NodeState

    priv, _ = secagg.dh_keypair()
    for bad in (0, 1, secagg.DH_PRIME - 1, secagg.DH_PRIME):
        assert not secagg.valid_public_key(bad)
        with pytest.raises(SecAggError, match="degenerate"):
            secagg.dh_pair_seed(priv, bad, "exp")

    state = NodeState("me")
    cmd = SecAggPubCommand(state)
    cmd.execute("attacker", 0, "1", "5")  # pub = 1
    assert "attacker" not in state.secagg_pubs
    _, good = secagg.dh_keypair()
    cmd.execute("peer", 0, f"{good:x}", "0")  # degenerate sample count
    assert "peer" not in state.secagg_pubs
    cmd.execute("peer", 0, f"{good:x}", "5")
    assert state.secagg_pubs["peer"] == (good, 5)


def test_secagg_misconfig_aborts_experiment():
    """SecAgg + a robust aggregator (or lossy wire) must abort at
    StartLearning — Krum over masked noise would silently elect garbage."""
    from p2pfl_tpu.learning.aggregators.krum import Krum
    from p2pfl_tpu.learning.learner import DummyLearner
    from p2pfl_tpu.utils import wait_convergence

    Settings.SECURE_AGGREGATION = True
    nodes = [Node(learner=DummyLearner(), aggregator=Krum()) for _ in range(2)]
    for n in nodes:
        n.start()
    nodes[0].connect(nodes[1].addr)
    wait_convergence(nodes, 1, only_direct=True)
    nodes[0].set_start_learning(rounds=1, epochs=1)
    time.sleep(1.5)
    # the learning thread aborted in StartLearningStage: state cleared, no
    # training ran (DummyLearner.fit would have bumped the params)
    for n in nodes:
        assert n.state.round is None
        assert float(np.asarray(n.learner.get_parameters()["w"]).mean()) == 0.0
    for n in nodes:
        n.stop()


def test_secure_federation_end_to_end():
    """4-node memory federation with SECURE_AGGREGATION: every aggregator
    input is masked, yet the federation converges to equal, working models."""
    Settings.SECURE_AGGREGATION = True
    full = FederatedDataset.synthetic_mnist(n_train=1024, n_test=256)
    nodes = []
    for i in range(4):
        learner = JaxLearner(mlp(seed=i), full.partition(i, 4), batch_size=64)
        node = Node(learner=learner)
        node.start()
        nodes.append(node)
    for n in nodes:
        full_connection(n, nodes)
    wait_convergence(nodes, 3, only_direct=True)
    nodes[0].set_start_learning(rounds=2, epochs=1)
    wait_to_finish(nodes, timeout=120)
    check_equal_models(nodes)
    acc = nodes[0].learner.evaluate()["test_acc"]
    assert acc > 0.7, acc  # masks cancelled — model actually works
    for n in nodes:
        n.stop()


def test_mask_stream_is_version_stable():
    """The mask PRG is SHAKE-256 counter mode (ADVICE r2): its byte stream
    is defined by the hash standard, not by NumPy's generator internals.
    Golden values pin the stream; tolerance is a few float32 ulps because
    Box–Muller's log/cos/sin are not correctly rounded across libm builds
    (an ulp-level, bounded divergence — unlike PCG64 version drift)."""
    m = secagg._leaf_mask(123456789, 3, (4,), 1)
    np.testing.assert_allclose(
        m, np.array([0.7085209, 0.7587952, -0.349858, 0.37594432], np.float32),
        rtol=1e-5,
    )
    # and it is a credible standard normal
    big = secagg._leaf_mask(7, 0, (100000,), 0)
    assert abs(float(big.mean())) < 0.02 and abs(float(big.std()) - 1.0) < 0.02


def test_secagg_pub_first_key_latched():
    """ADVICE r2 (medium): the gossip plane is unauthenticated — a later
    secagg_pub claiming an already-known source must NOT replace the
    latched key (an attacker could otherwise swap in a key they control
    and strip the victim's masks). Identical re-delivery is fine."""
    from p2pfl_tpu.commands.control import SecAggPubCommand
    from p2pfl_tpu.node_state import NodeState

    state = NodeState("me")
    cmd = SecAggPubCommand(state)
    _, first = secagg.dh_keypair()
    _, attacker = secagg.dh_keypair()
    cmd.execute("victim", 0, f"{first:x}", "5")
    assert state.secagg_pubs["victim"] == (first, 5)
    cmd.execute("victim", 0, f"{attacker:x}", "5")  # spoofed replacement
    assert state.secagg_pubs["victim"] == (first, 5)
    cmd.execute("victim", 0, f"{first:x}", "7")  # same key, new count: also latched
    assert state.secagg_pubs["victim"] == (first, 5)
    cmd.execute("victim", 0, f"{first:x}", "5")  # identical re-delivery ok
    assert state.secagg_pubs["victim"] == (first, 5)
    # a new experiment clears the latch
    state.clear()
    cmd.execute("victim", 0, f"{attacker:x}", "5")
    assert state.secagg_pubs["victim"] == (attacker, 5)


def test_announced_sample_count_latched():
    """ADVICE r2 (low): peers scale their mask halves with the count we
    ANNOUNCED; masking with a diverged actual count would leave an
    undetectable residual in a full-coverage aggregate — refuse loudly."""
    from p2pfl_tpu.exceptions import SecAggError

    addrs = ["a", "b"]
    priv, _ = secagg.dh_keypair()
    _, pub_b = secagg.dh_keypair()
    p = {"w": np.ones((2, 2), np.float32)}
    with pytest.raises(SecAggError, match="changed since"):
        secagg.mask_update(
            ModelUpdate(p, ["a"], 7), "a", addrs, priv, {"b": (pub_b, 5)}, "exp", 0,
            announced_samples=5,
        )
    # matching count masks fine
    out = secagg.mask_update(
        ModelUpdate(p, ["a"], 5), "a", addrs, priv, {"b": (pub_b, 5)}, "exp", 0,
        announced_samples=5,
    )
    assert out is not None


def test_dropout_correction_recovers_survivor_mean():
    """Bonawitz-style recovery math: with one member missing, subtracting
    dropout_correction/W from the survivors' weighted mean recovers their
    TRUE mean exactly (up to float32 rounding)."""
    addrs = ["a", "b", "c", "d"]
    keys = {n: secagg.dh_keypair() for n in addrs}
    privs = {n: k[0] for n, k in keys.items()}
    weights = {"a": 10, "b": 20, "c": 30, "d": 40}
    pubs = {n: (keys[n][1], weights[n]) for n in addrs}
    rng = np.random.default_rng(1)
    params = {n: {"w": rng.normal(size=(16, 8)).astype(np.float32)} for n in addrs}
    masked = {
        n: _mask_for(n, addrs, privs, pubs, params[n], weights[n]) for n in addrs
    }

    survivors, missing = ["a", "b", "c"], ["d"]
    w_s = sum(weights[n] for n in survivors)
    noised = sum(
        weights[n] * np.asarray(masked[n].params["w"], np.float64) for n in survivors
    ) / w_s
    true_mean = sum(weights[n] * params[n]["w"] for n in survivors) / w_s
    assert np.abs(noised - true_mean).max() > 10  # the dropout DID noise it

    # each survivor re-discloses its pair seed with the dropped node
    seeds = {
        (i, "d"): secagg.dh_pair_seed(privs[i], pubs["d"][0], "exp") for i in survivors
    }
    corr = secagg.dropout_correction(params["a"], survivors, missing, seeds, weights, 0)
    fixed = secagg.apply_dropout_correction(
        {"w": np.asarray(noised, np.float32)}, corr, float(w_s)
    )
    np.testing.assert_allclose(
        np.asarray(fixed["w"], np.float64), true_mean, atol=1e-3
    )


class _SlowFitLearner(JaxLearner):
    """Fit stalls long enough for the test to kill the node mid-round."""

    def fit(self):
        self._interrupt.wait(timeout=30)
        super().fit()


@pytest.mark.slow
def test_secagg_dropout_recovery_end_to_end():
    """Kill a train-set member mid-fit with SECURE_AGGREGATION on: the
    survivors must run seed recovery and converge to a WORKING model (the
    pre-recovery behavior left every node with Gaussian noise)."""
    Settings.SECURE_AGGREGATION = True
    full = FederatedDataset.synthetic_mnist(n_train=1024, n_test=256)
    nodes = []
    for i in range(4):
        cls = _SlowFitLearner if i == 3 else JaxLearner
        learner = cls(mlp(seed=i), full.partition(i, 4), batch_size=64)
        node = Node(learner=learner)
        node.start()
        nodes.append(node)
    try:
        for n in nodes:
            full_connection(n, nodes)
        wait_convergence(nodes, 3, only_direct=True)
        nodes[0].set_start_learning(rounds=1, epochs=1)
        # node 3 dies mid-fit, after announcing its DH key but before
        # contributing
        time.sleep(3.0)
        nodes[3].stop()
        wait_to_finish(nodes[:3], timeout=120)
        check_equal_models(nodes[:3])
        acc = nodes[0].learner.evaluate()["test_acc"]
        assert acc > 0.7, acc  # masks recovered — not noise
    finally:
        for n in nodes:
            n.stop()


def test_secagg_unrecoverable_round_is_noop():
    """ADVICE r2 (medium): when seed disclosures never arrive, the noised
    aggregate must be DISCARDED — the round resolves to the round-start
    global instead of applying and diffusing a destroyed model."""
    from p2pfl_tpu.stages.learning_stages import GossipModelStage
    from p2pfl_tpu.node_state import NodeState

    Settings.SECURE_AGGREGATION = True
    Settings.SECAGG_RECOVERY_TIMEOUT = 0.3

    state = NodeState("a")
    state.set_experiment("exp", 1)
    state.train_set = ["a", "b", "c"]
    priv, pub = secagg.dh_keypair()
    state.secagg_priv = priv
    state.secagg_samples = 10
    for peer in ("b", "c"):
        _, p = secagg.dh_keypair()
        state.secagg_pubs[peer] = (p, 10)

    class _FakeProto:
        def broadcast(self, msg):
            pass

        def build_msg(self, *a, **k):
            return {}

    class _FakeLearner:
        def get_parameters(self):
            return {"w": np.full((2, 2), 7.0, np.float32)}

    class _FakeNode:
        addr = "a"

        def __init__(self):
            self.state = state
            self.protocol = _FakeProto()
            self.learner = _FakeLearner()
            self.round_start_params = {"w": np.full((2, 2), 7.0, np.float32)}

        def learning_interrupted(self):
            return False

    noised = ModelUpdate({"w": np.full((2, 2), 999.0, np.float32)}, ["a", "b"], 20)
    out = GossipModelStage._secagg_finalize(_FakeNode(), noised)
    # "c"'s masks never got disclosed ("b" said nothing): round is a no-op
    np.testing.assert_array_equal(np.asarray(out.params["w"]), 7.0)
    assert set(out.contributors) == {"a", "b", "c"}
    # and the fallback is FLAGGED so GossipModelStage never diffuses the
    # round-start globals as the round's authoritative aggregate (ADVICE r3)
    assert out.noop_round


def test_noop_round_skips_outward_diffusion():
    """ADVICE r3 (low): a failed-recovery no-op round must not advertise
    the round-start globals to behind neighbors as the round's aggregate —
    GossipModelStage finishes the round without calling gossip_weights."""
    from p2pfl_tpu.stages.learning_stages import GossipModelStage, RoundFinishedStage
    from p2pfl_tpu.node_state import NodeState

    Settings.SECURE_AGGREGATION = True
    calls = {"gossip": 0, "broadcast": []}
    params = {"w": np.full((2, 2), 7.0, np.float32)}

    class _Agg:
        def wait_and_get_aggregation(self, timeout=None):
            return ModelUpdate(params, ["a", "b"], 2, noop_round=True)

    class _Proto:
        def broadcast(self, msg):
            calls["broadcast"].append(msg)

        def build_msg(self, cmd, args, round=0):  # noqa: A002
            return (cmd, list(args), round)

        def gossip_weights(self, *a, **k):
            calls["gossip"] += 1

        def get_neighbors(self, only_direct=False):
            return {}

    class _Learner:
        def set_parameters(self, p):
            calls["set"] = p

    class _FakeNode:
        addr = "a"

        def __init__(self):
            self.state = NodeState("a")
            self.state.set_experiment("exp", 1)
            self.state.train_set = ["a", "b"]
            self.protocol = _Proto()
            self.aggregator = _Agg()
            self.learner = _Learner()

        def learning_interrupted(self):
            return False

    node = _FakeNode()
    # monkey-free: the aggregator already returns the flagged no-op update,
    # and a 2-member train set makes _secagg_finalize pass it through
    # untouched (len(train) <= 1 is false but covered == train here)
    nxt = GossipModelStage.execute(node)
    assert nxt is RoundFinishedStage
    assert calls["gossip"] == 0  # NO outward diffusion of stale params
    # the round still terminates for the overlay
    assert any(m[0] == "models_ready" for m in calls["broadcast"])


def test_secagg_need_answered_by_full_coverage_peer():
    """Coverage views can differ at timeout: a peer whose OWN aggregate
    reached full coverage finalizes early and would never disclose on its
    own — it must still answer a recovering peer's secagg_need broadcast
    (and never for a 2-member train set, where the only pair seed IS the
    other member's full mask)."""
    from p2pfl_tpu.commands.control import SecAggNeedCommand
    from p2pfl_tpu.node_state import NodeState

    sent = []

    class _Proto:
        def __init__(self, live):
            self._live = live

        def broadcast(self, msg):
            sent.append(msg)

        def build_msg(self, cmd, args, round=0):  # noqa: A002
            return (cmd, list(args), round)

        def get_neighbors(self, only_direct=False):
            return dict.fromkeys(self._live)

    class _FakeNode:
        def __init__(self, addr, train, live):
            self.addr = addr
            self.state = NodeState(addr)
            self.state.set_experiment("exp", 1)
            self.state.train_set = list(train)
            self.protocol = _Proto(live)

    # b and c still heartbeat; d dropped off the overlay
    node = _FakeNode("a", ["a", "b", "c", "d"], live=["b", "c"])
    priv, _ = secagg.dh_keypair()
    node.state.secagg_priv = priv
    for peer in ("b", "c", "d"):
        _, p = secagg.dh_keypair()
        node.state.secagg_pubs[peer] = (p, 10)

    cmd = SecAggNeedCommand(node)
    cmd.execute("b", 0, "exp", "d")  # b cannot cancel d's masks
    assert len(sent) == 1 and sent[0][0] == "secagg_recover" and sent[0][1][0] == "d"
    expected = secagg.dh_pair_seed(priv, node.state.secagg_pubs["d"][0], "exp")
    assert int(sent[0][1][1], 16) == expected
    # a DIFFERENT requester is RE-answered even though already disclosed
    # (ADVICE r3 medium): requester c may have been a round behind when the
    # first broadcast went out and dropped it (SecAggRecoverCommand round
    # gate) — re-broadcasting the same seed is idempotent, receivers latch
    # first-wins, and a global send-once latch would leave c burning its
    # whole recovery timeout for nothing
    cmd.execute("c", 0, "exp", "d")
    assert len(sent) == 2 and sent[1][0] == "secagg_recover" and sent[1][1][0] == "d"
    assert int(sent[1][1][1], 16) == expected  # the SAME seed, verbatim
    # but the SAME requester replaying (fresh gossip ids) is latched —
    # amplification stays bounded at one answer per member per round
    cmd.execute("c", 0, "exp", "d")
    cmd.execute("b", 0, "exp", "d")
    assert len(sent) == 2
    cmd.execute("b", 0, "exp", "a", "b", "zz")  # self / requester / unknown: ignored
    assert len(sent) == 2
    # a request naming a LIVE member is refused (the requester's claim is
    # not evidence; only heartbeat eviction is)
    cmd.execute("b", 0, "exp", "c")
    assert len(sent) == 2
    # non-member requesters have no standing; wrong experiment is ignored
    cmd.execute("zz", 0, "exp", "d")
    cmd.execute("b", 0, "other_exp", "d")
    assert len(sent) == 2

    # 2-member train set never discloses
    sent.clear()
    pair = _FakeNode("a", ["a", "b"], live=[])
    pair.state.secagg_priv = priv
    pair.state.secagg_pubs["b"] = node.state.secagg_pubs["b"]
    SecAggNeedCommand(pair).execute("b", 0, "exp", "b")
    assert sent == []


@pytest.mark.slow
def test_masked_stack_on_mesh():
    """Device-side op: masking a node-stacked pytree leaves the weighted
    FedAvg unchanged while each slot's params are drowned in noise."""
    import jax
    import jax.numpy as jnp

    from p2pfl_tpu.ops.aggregation import fedavg

    n = 8
    key = jax.random.PRNGKey(0)
    stack = {"w": jax.random.normal(key, (n, 32, 16), jnp.float32)}
    weights = jnp.asarray([10.0, 20.0, 30.0, 40.0, 10.0, 20.0, 30.0, 40.0])

    masked = jax.jit(secagg.masked_stack)(stack, weights, jax.random.PRNGKey(7))
    per_slot_delta = jnp.std(masked["w"] - stack["w"], axis=(1, 2))
    assert bool((per_slot_delta > 0.5).all()), per_slot_delta

    w = weights / weights.sum()
    true_avg = jnp.einsum("n,nij->ij", w, stack["w"])
    masked_avg = jnp.einsum("n,nij->ij", w, masked["w"])
    np.testing.assert_allclose(np.asarray(masked_avg), np.asarray(true_avg), atol=1e-3)
