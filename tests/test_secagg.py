"""Secure aggregation (pairwise masking, ``learning/secagg.py``).

The reference has no privacy layer; this is a beyond-parity capability:
DH key agreement over the gossip overlay, pairwise Gaussian masks that
cancel in the sample-weighted FedAvg sum, end-to-end federation with
SECURE_AGGREGATION on, and the device-side masking op on the mesh.
"""

import time

import numpy as np
import pytest

from p2pfl_tpu.communication.memory import MemoryRegistry
from p2pfl_tpu.learning import secagg
from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import JaxLearner
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.models import mlp
from p2pfl_tpu.node import Node
from p2pfl_tpu.settings import Settings
from p2pfl_tpu.utils import (
    check_equal_models,
    full_connection,
    wait_convergence,
    wait_to_finish,
)


@pytest.fixture(autouse=True)
def _clean():
    MemoryRegistry.reset()
    yield
    MemoryRegistry.reset()
    Settings.SECURE_AGGREGATION = False


def test_dh_pair_seed_symmetric():
    xa, pa = secagg.dh_keypair()
    xb, pb = secagg.dh_keypair()
    assert secagg.dh_pair_seed(xa, pb, "exp") == secagg.dh_pair_seed(xb, pa, "exp")
    # different experiment context → different seed
    assert secagg.dh_pair_seed(xa, pb, "exp") != secagg.dh_pair_seed(xa, pb, "exp2")


def _mask_for(addr, addrs, privs, pubs, params, num_samples, round_no=0):
    u = ModelUpdate(params, [addr], num_samples)
    return secagg.mask_update(u, addr, addrs, privs[addr], pubs, "exp", round_no)


def test_masks_cancel_in_weighted_fedavg():
    """Σ w_i · masked_i == Σ w_i · p_i once every pair is present."""
    addrs = ["a", "b", "c", "d"]
    keys = {n: secagg.dh_keypair() for n in addrs}
    privs = {n: k[0] for n, k in keys.items()}
    weights = {"a": 10, "b": 20, "c": 30, "d": 40}
    pubs = {n: (keys[n][1], weights[n]) for n in addrs}
    rng = np.random.default_rng(0)
    params = {n: {"w": rng.normal(size=(16, 8)).astype(np.float32)} for n in addrs}

    masked = {
        n: _mask_for(n, addrs, privs, pubs, params[n], weights[n]) for n in addrs
    }
    # individual masked models are far from the raw ones (privacy)
    for n in addrs:
        delta = np.asarray(masked[n].params["w"]) - params[n]["w"]
        assert np.std(delta) > 1.0, np.std(delta)

    w_total = sum(weights.values())
    true_avg = sum(weights[n] * params[n]["w"] for n in addrs) / w_total
    masked_avg = sum(
        weights[n] * np.asarray(masked[n].params["w"], np.float64) for n in addrs
    ) / w_total
    np.testing.assert_allclose(masked_avg, true_avg, atol=1e-3)


def test_mask_fresh_per_round():
    addrs = ["a", "b"]
    keys = {n: secagg.dh_keypair() for n in addrs}
    privs = {n: k[0] for n, k in keys.items()}
    pubs = {n: (k[1], 1) for n, k in keys.items()}
    p = {"w": np.zeros((4, 4), np.float32)}
    m0 = _mask_for("a", addrs, privs, pubs, p, 1, round_no=0)
    m1 = _mask_for("a", addrs, privs, pubs, p, 1, round_no=1)
    assert not np.allclose(np.asarray(m0.params["w"]), np.asarray(m1.params["w"]))


def test_unsafe_masking_raises_never_unmasked():
    """Missing keys / zero weight / non-fp32 params must raise SecAggError —
    an unmasked fallback would leave peers' pair masks uncancelled in a
    full-coverage aggregate, undetected noise."""
    from p2pfl_tpu.exceptions import SecAggError

    addrs = ["a", "b"]
    priv, pub = secagg.dh_keypair()
    priv_b, pub_b = secagg.dh_keypair()
    p32 = {"w": np.ones((2, 2), np.float32)}

    with pytest.raises(SecAggError, match="missing DH"):
        secagg.mask_update(ModelUpdate(p32, ["a"], 5), "a", addrs, priv, {}, "exp", 0)
    with pytest.raises(SecAggError, match="zero sample"):
        secagg.mask_update(ModelUpdate(p32, ["a"], 0), "a", addrs, priv, {"b": (pub_b, 5)}, "exp", 0)
    import jax.numpy as jnp

    p16 = {"w": jnp.ones((2, 2), jnp.bfloat16)}
    with pytest.raises(SecAggError, match="float32"):
        secagg.mask_update(ModelUpdate(p16, ["a"], 5), "a", addrs, priv, {"b": (pub_b, 5)}, "exp", 0)
    # lossy wire compression breaks cancellation — refused up front
    Settings.WIRE_COMPRESSION = "int8"
    try:
        with pytest.raises(SecAggError, match="lossless"):
            secagg.mask_update(ModelUpdate(p32, ["a"], 5), "a", addrs, priv, {"b": (pub_b, 5)}, "exp", 0)
    finally:
        Settings.WIRE_COMPRESSION = "none"


def test_degenerate_dh_keys_rejected():
    """pub ∈ {0, 1, p-1} makes the shared secret computable from public
    info (an active attacker could strip a victim's masks) — rejected at
    both the command layer and seed derivation."""
    from p2pfl_tpu.exceptions import SecAggError
    from p2pfl_tpu.commands.control import SecAggPubCommand
    from p2pfl_tpu.node_state import NodeState

    priv, _ = secagg.dh_keypair()
    for bad in (0, 1, secagg.DH_PRIME - 1, secagg.DH_PRIME):
        assert not secagg.valid_public_key(bad)
        with pytest.raises(SecAggError, match="degenerate"):
            secagg.dh_pair_seed(priv, bad, "exp")

    state = NodeState("me")
    cmd = SecAggPubCommand(state)
    cmd.execute("attacker", 0, "1", "5")  # pub = 1
    assert "attacker" not in state.secagg_pubs
    _, good = secagg.dh_keypair()
    cmd.execute("peer", 0, f"{good:x}", "0")  # degenerate sample count
    assert "peer" not in state.secagg_pubs
    cmd.execute("peer", 0, f"{good:x}", "5")
    assert state.secagg_pubs["peer"] == (good, 5)


def test_secagg_misconfig_aborts_experiment():
    """SecAgg + a robust aggregator (or lossy wire) must abort at
    StartLearning — Krum over masked noise would silently elect garbage."""
    from p2pfl_tpu.learning.aggregators.krum import Krum
    from p2pfl_tpu.learning.learner import DummyLearner
    from p2pfl_tpu.utils import wait_convergence

    Settings.SECURE_AGGREGATION = True
    nodes = [Node(learner=DummyLearner(), aggregator=Krum()) for _ in range(2)]
    for n in nodes:
        n.start()
    nodes[0].connect(nodes[1].addr)
    wait_convergence(nodes, 1, only_direct=True)
    nodes[0].set_start_learning(rounds=1, epochs=1)
    time.sleep(1.5)
    # the learning thread aborted in StartLearningStage: state cleared, no
    # training ran (DummyLearner.fit would have bumped the params)
    for n in nodes:
        assert n.state.round is None
        assert float(np.asarray(n.learner.get_parameters()["w"]).mean()) == 0.0
    for n in nodes:
        n.stop()


def test_secure_federation_end_to_end():
    """4-node memory federation with SECURE_AGGREGATION: every aggregator
    input is masked, yet the federation converges to equal, working models."""
    Settings.SECURE_AGGREGATION = True
    full = FederatedDataset.synthetic_mnist(n_train=1024, n_test=256)
    nodes = []
    for i in range(4):
        learner = JaxLearner(mlp(seed=i), full.partition(i, 4), batch_size=64)
        node = Node(learner=learner)
        node.start()
        nodes.append(node)
    for n in nodes:
        full_connection(n, nodes)
    wait_convergence(nodes, 3, only_direct=True)
    nodes[0].set_start_learning(rounds=2, epochs=1)
    wait_to_finish(nodes, timeout=120)
    check_equal_models(nodes)
    acc = nodes[0].learner.evaluate()["test_acc"]
    assert acc > 0.7, acc  # masks cancelled — model actually works
    for n in nodes:
        n.stop()


def test_mask_stream_is_version_stable():
    """The mask PRG is SHAKE-256 counter mode (ADVICE r2): its byte stream
    is defined by the hash standard, not by NumPy's generator internals.
    Golden values pin the stream; tolerance is a few float32 ulps because
    Box–Muller's log/cos/sin are not correctly rounded across libm builds
    (an ulp-level, bounded divergence — unlike PCG64 version drift)."""
    m = secagg._leaf_mask(123456789, 3, (4,), 1)
    np.testing.assert_allclose(
        m, np.array([0.7085209, 0.7587952, -0.349858, 0.37594432], np.float32),
        rtol=1e-5,
    )
    # and it is a credible standard normal
    big = secagg._leaf_mask(7, 0, (100000,), 0)
    assert abs(float(big.mean())) < 0.02 and abs(float(big.std()) - 1.0) < 0.02


def test_secagg_pub_first_key_latched():
    """ADVICE r2 (medium): the gossip plane is unauthenticated — a later
    secagg_pub claiming an already-known source must NOT replace the
    latched key (an attacker could otherwise swap in a key they control
    and strip the victim's masks). Identical re-delivery is fine."""
    from p2pfl_tpu.commands.control import SecAggPubCommand
    from p2pfl_tpu.node_state import NodeState

    state = NodeState("me")
    cmd = SecAggPubCommand(state)
    _, first = secagg.dh_keypair()
    _, attacker = secagg.dh_keypair()
    cmd.execute("victim", 0, f"{first:x}", "5")
    assert state.secagg_pubs["victim"] == (first, 5)
    cmd.execute("victim", 0, f"{attacker:x}", "5")  # spoofed replacement
    assert state.secagg_pubs["victim"] == (first, 5)
    cmd.execute("victim", 0, f"{first:x}", "7")  # same key, new count: also latched
    assert state.secagg_pubs["victim"] == (first, 5)
    cmd.execute("victim", 0, f"{first:x}", "5")  # identical re-delivery ok
    assert state.secagg_pubs["victim"] == (first, 5)
    # a new experiment clears the latch
    state.clear()
    cmd.execute("victim", 0, f"{attacker:x}", "5")
    assert state.secagg_pubs["victim"] == (attacker, 5)


def test_announced_sample_count_latched():
    """ADVICE r2 (low): peers scale their mask halves with the count we
    ANNOUNCED; masking with a diverged actual count would leave an
    undetectable residual in a full-coverage aggregate — refuse loudly."""
    from p2pfl_tpu.exceptions import SecAggError

    addrs = ["a", "b"]
    priv, _ = secagg.dh_keypair()
    _, pub_b = secagg.dh_keypair()
    p = {"w": np.ones((2, 2), np.float32)}
    with pytest.raises(SecAggError, match="changed since"):
        secagg.mask_update(
            ModelUpdate(p, ["a"], 7), "a", addrs, priv, {"b": (pub_b, 5)}, "exp", 0,
            announced_samples=5,
        )
    # matching count masks fine
    out = secagg.mask_update(
        ModelUpdate(p, ["a"], 5), "a", addrs, priv, {"b": (pub_b, 5)}, "exp", 0,
        announced_samples=5,
    )
    assert out is not None


def test_dropout_correction_recovers_survivor_mean():
    """Bonawitz-style recovery math: with one member missing, subtracting
    dropout_correction/W from the survivors' weighted mean recovers their
    TRUE mean exactly (up to float32 rounding)."""
    addrs = ["a", "b", "c", "d"]
    keys = {n: secagg.dh_keypair() for n in addrs}
    privs = {n: k[0] for n, k in keys.items()}
    weights = {"a": 10, "b": 20, "c": 30, "d": 40}
    pubs = {n: (keys[n][1], weights[n]) for n in addrs}
    rng = np.random.default_rng(1)
    params = {n: {"w": rng.normal(size=(16, 8)).astype(np.float32)} for n in addrs}
    masked = {
        n: _mask_for(n, addrs, privs, pubs, params[n], weights[n]) for n in addrs
    }

    survivors, missing = ["a", "b", "c"], ["d"]
    w_s = sum(weights[n] for n in survivors)
    noised = sum(
        weights[n] * np.asarray(masked[n].params["w"], np.float64) for n in survivors
    ) / w_s
    true_mean = sum(weights[n] * params[n]["w"] for n in survivors) / w_s
    assert np.abs(noised - true_mean).max() > 10  # the dropout DID noise it

    # each survivor re-discloses its pair seed with the dropped node
    seeds = {
        (i, "d"): secagg.dh_pair_seed(privs[i], pubs["d"][0], "exp") for i in survivors
    }
    corr = secagg.dropout_correction(params["a"], survivors, missing, seeds, weights, 0)
    fixed = secagg.apply_dropout_correction(
        {"w": np.asarray(noised, np.float32)}, corr, float(w_s)
    )
    np.testing.assert_allclose(
        np.asarray(fixed["w"], np.float64), true_mean, atol=1e-3
    )


class _SlowFitLearner(JaxLearner):
    """Fit stalls long enough for the test to kill the node mid-round."""

    def fit(self):
        self._interrupt.wait(timeout=30)
        super().fit()


@pytest.mark.slow
def test_secagg_dropout_recovery_end_to_end():
    """Kill a train-set member mid-fit with SECURE_AGGREGATION on: the
    survivors must run seed recovery and converge to a WORKING model (the
    pre-recovery behavior left every node with Gaussian noise)."""
    Settings.SECURE_AGGREGATION = True
    full = FederatedDataset.synthetic_mnist(n_train=1024, n_test=256)
    nodes = []
    for i in range(4):
        cls = _SlowFitLearner if i == 3 else JaxLearner
        learner = cls(mlp(seed=i), full.partition(i, 4), batch_size=64)
        node = Node(learner=learner)
        node.start()
        nodes.append(node)
    try:
        for n in nodes:
            full_connection(n, nodes)
        wait_convergence(nodes, 3, only_direct=True)
        nodes[0].set_start_learning(rounds=1, epochs=1)
        # node 3 dies mid-fit, after announcing its DH key but before
        # contributing
        time.sleep(3.0)
        nodes[3].stop()
        wait_to_finish(nodes[:3], timeout=120)
        check_equal_models(nodes[:3])
        acc = nodes[0].learner.evaluate()["test_acc"]
        assert acc > 0.7, acc  # masks recovered — not noise
    finally:
        for n in nodes:
            n.stop()


def test_secagg_unrecoverable_round_is_noop():
    """ADVICE r2 (medium): when seed disclosures never arrive, the noised
    aggregate must be DISCARDED — the round resolves to the round-start
    global instead of applying and diffusing a destroyed model."""
    from p2pfl_tpu.stages.learning_stages import GossipModelStage
    from p2pfl_tpu.node_state import NodeState

    Settings.SECURE_AGGREGATION = True
    Settings.SECAGG_RECOVERY_TIMEOUT = 0.3

    state = NodeState("a")
    state.set_experiment("exp", 1)
    state.train_set = ["a", "b", "c"]
    priv, pub = secagg.dh_keypair()
    state.secagg_priv = priv
    state.secagg_samples = 10
    for peer in ("b", "c"):
        _, p = secagg.dh_keypair()
        state.secagg_pubs[peer] = (p, 10)

    class _FakeProto:
        def broadcast(self, msg):
            pass

        def build_msg(self, *a, **k):
            return {}

        def get_neighbors(self, only_direct=False):
            return {}

    class _FakeLearner:
        def get_parameters(self):
            return {"w": np.full((2, 2), 7.0, np.float32)}

    class _FakeNode:
        addr = "a"

        def __init__(self):
            self.state = state
            self.protocol = _FakeProto()
            self.learner = _FakeLearner()
            self.round_start_params = {"w": np.full((2, 2), 7.0, np.float32)}

        def learning_interrupted(self):
            return False

    noised = ModelUpdate({"w": np.full((2, 2), 999.0, np.float32)}, ["a", "b"], 20)
    out = GossipModelStage._secagg_finalize(_FakeNode(), noised)
    # "c"'s masks never got disclosed ("b" said nothing): round is a no-op
    np.testing.assert_array_equal(np.asarray(out.params["w"]), 7.0)
    assert set(out.contributors) == {"a", "b", "c"}
    # and the fallback is FLAGGED so GossipModelStage never diffuses the
    # round-start globals as the round's authoritative aggregate (ADVICE r3)
    assert out.noop_round


def test_noop_round_skips_outward_diffusion():
    """ADVICE r3 (low): a failed-recovery no-op round must not advertise
    the round-start globals to behind neighbors as the round's aggregate —
    GossipModelStage finishes the round without calling gossip_weights."""
    from p2pfl_tpu.stages.learning_stages import GossipModelStage, RoundFinishedStage
    from p2pfl_tpu.node_state import NodeState

    Settings.SECURE_AGGREGATION = True
    calls = {"gossip": 0, "broadcast": []}
    params = {"w": np.full((2, 2), 7.0, np.float32)}

    class _Agg:
        def wait_and_get_aggregation(self, timeout=None):
            return ModelUpdate(params, ["a", "b"], 2, noop_round=True)

    class _Proto:
        def broadcast(self, msg):
            calls["broadcast"].append(msg)

        def build_msg(self, cmd, args, round=0):  # noqa: A002
            return (cmd, list(args), round)

        def gossip_weights(self, *a, **k):
            calls["gossip"] += 1

        def get_neighbors(self, only_direct=False):
            return {}

    class _Learner:
        def set_parameters(self, p):
            calls["set"] = p

    class _FakeNode:
        addr = "a"

        def __init__(self):
            self.state = NodeState("a")
            self.state.set_experiment("exp", 1)
            self.state.train_set = ["a", "b"]
            self.protocol = _Proto()
            self.aggregator = _Agg()
            self.learner = _Learner()

        def learning_interrupted(self):
            return False

    node = _FakeNode()
    # monkey-free: the aggregator already returns the flagged no-op update,
    # and a 2-member train set makes _secagg_finalize pass it through
    # untouched (len(train) <= 1 is false but covered == train here)
    nxt = GossipModelStage.execute(node)
    assert nxt is RoundFinishedStage
    assert calls["gossip"] == 0  # NO outward diffusion of stale params
    # the round still terminates for the overlay
    assert any(m[0] == "models_ready" for m in calls["broadcast"])


def test_secagg_need_answered_by_full_coverage_peer():
    """Coverage views can differ at timeout: a peer whose OWN aggregate
    reached full coverage finalizes early and would never disclose on its
    own — it must still answer a recovering peer's secagg_need broadcast
    (and never for a 2-member train set, where the only pair seed IS the
    other member's full mask)."""
    from p2pfl_tpu.commands.control import SecAggNeedCommand
    from p2pfl_tpu.node_state import NodeState

    sent = []

    class _Proto:
        def __init__(self, live):
            self._live = live

        def broadcast(self, msg):
            sent.append(msg)

        def build_msg(self, cmd, args, round=0):  # noqa: A002
            return (cmd, list(args), round)

        def get_neighbors(self, only_direct=False):
            return dict.fromkeys(self._live)

    class _FakeNode:
        def __init__(self, addr, train, live):
            self.addr = addr
            self.state = NodeState(addr)
            self.state.set_experiment("exp", 1)
            self.state.train_set = list(train)
            self.protocol = _Proto(live)

    # b and c still heartbeat; d dropped off the overlay
    node = _FakeNode("a", ["a", "b", "c", "d"], live=["b", "c"])
    priv, _ = secagg.dh_keypair()
    node.state.secagg_priv = priv
    for peer in ("b", "c", "d"):
        _, p = secagg.dh_keypair()
        node.state.secagg_pubs[peer] = (p, 10)

    cmd = SecAggNeedCommand(node)
    cmd.execute("b", 0, "exp", "d")  # b cannot cancel d's masks
    assert len(sent) == 1 and sent[0][0] == "secagg_recover" and sent[0][1][0] == "d"
    expected = secagg.dh_pair_seed(priv, node.state.secagg_pubs["d"][0], "exp")
    assert int(sent[0][1][1], 16) == expected
    # a DIFFERENT requester is RE-answered even though already disclosed
    # (ADVICE r3 medium): requester c may have been a round behind when the
    # first broadcast went out and dropped it (SecAggRecoverCommand round
    # gate) — re-broadcasting the same seed is idempotent, receivers latch
    # first-wins, and a global send-once latch would leave c burning its
    # whole recovery timeout for nothing
    cmd.execute("c", 0, "exp", "d")
    assert len(sent) == 2 and sent[1][0] == "secagg_recover" and sent[1][1][0] == "d"
    assert int(sent[1][1][1], 16) == expected  # the SAME seed, verbatim
    # but the SAME requester replaying (fresh gossip ids) is latched —
    # amplification stays bounded at one answer per member per round
    cmd.execute("c", 0, "exp", "d")
    cmd.execute("b", 0, "exp", "d")
    assert len(sent) == 2
    cmd.execute("b", 0, "exp", "a", "b", "zz")  # self / requester / unknown: ignored
    assert len(sent) == 2
    # a request naming a LIVE member is refused (the requester's claim is
    # not evidence; only heartbeat eviction is)
    cmd.execute("b", 0, "exp", "c")
    assert len(sent) == 2
    # non-member requesters have no standing; wrong experiment is ignored
    cmd.execute("zz", 0, "exp", "d")
    cmd.execute("b", 0, "other_exp", "d")
    assert len(sent) == 2

    # 2-member train set never discloses
    sent.clear()
    pair = _FakeNode("a", ["a", "b"], live=[])
    pair.state.secagg_priv = priv
    pair.state.secagg_pubs["b"] = node.state.secagg_pubs["b"]
    SecAggNeedCommand(pair).execute("b", 0, "exp", "b")
    assert sent == []


@pytest.mark.slow
def test_masked_stack_on_mesh():
    """Device-side op: masking a node-stacked pytree leaves the weighted
    FedAvg unchanged while each slot's params are drowned in noise."""
    import jax
    import jax.numpy as jnp

    from p2pfl_tpu.ops.aggregation import fedavg

    n = 8
    key = jax.random.PRNGKey(0)
    stack = {"w": jax.random.normal(key, (n, 32, 16), jnp.float32)}
    weights = jnp.asarray([10.0, 20.0, 30.0, 40.0, 10.0, 20.0, 30.0, 40.0])

    masked = jax.jit(secagg.masked_stack)(stack, weights, jax.random.PRNGKey(7))
    per_slot_delta = jnp.std(masked["w"] - stack["w"], axis=(1, 2))
    assert bool((per_slot_delta > 0.5).all()), per_slot_delta

    w = weights / weights.sum()
    true_avg = jnp.einsum("n,nij->ij", w, stack["w"])
    masked_avg = jnp.einsum("n,nij->ij", w, masked["w"])
    np.testing.assert_allclose(np.asarray(masked_avg), np.asarray(true_avg), atol=1e-3)


# ---- Bonawitz double masking (VERDICT r3 #8) ----


def test_shamir_split_reconstruct_roundtrip():
    secret = int.from_bytes(b"\x42" * 32, "big")
    shares = secagg.shamir_split(secret, n=5, t=3)
    assert len(shares) == 5 and len({x for x, _ in shares}) == 5
    # any t-subset reconstructs
    import itertools

    for combo in itertools.combinations(shares, 3):
        assert secagg.shamir_reconstruct(list(combo)) == secret
    # a t−1 subset gives a (different) field element, not the secret
    assert secagg.shamir_reconstruct(shares[:2]) != secret


def test_shamir_threshold_policy():
    # honest majority, clamped to the n−1 share holders; n=2 degenerates
    assert secagg.share_threshold(2) == 1
    assert secagg.share_threshold(3) == 2
    assert secagg.share_threshold(4) == 3
    assert secagg.share_threshold(9) == 5


def test_share_encryption_roundtrip_and_binding():
    key = 123456789
    y = secagg.SHAMIR_PRIME - 7
    ct = secagg.encrypt_share(y, key, 3, "a", "b")
    assert secagg.decrypt_share(ct, key, 3, "a", "b") == y
    # wrong key, round, or direction decrypts to garbage, not the share
    assert secagg.decrypt_share(ct, key + 1, 3, "a", "b") != y
    assert secagg.decrypt_share(ct, key, 4, "a", "b") != y
    assert secagg.decrypt_share(ct, key, 3, "b", "a") != y
    # the A->B and B->A keystreams differ (no two-time pad): identical
    # plaintexts encrypt to different ciphertexts across directions
    assert secagg.encrypt_share(y, key, 3, "a", "b") != secagg.encrypt_share(y, key, 3, "b", "a")
    # the share key is NOT the (disclosable) pair mask seed: sibling hashes
    # of the same DH secret under different contexts
    priv_a, pub_a = secagg.dh_keypair()
    priv_b, pub_b = secagg.dh_keypair()
    assert secagg.dh_share_key(priv_a, pub_b, "exp") != secagg.dh_pair_seed(priv_a, pub_b, "exp")
    assert secagg.dh_share_key(priv_a, pub_b, "exp") == secagg.dh_share_key(priv_b, pub_a, "exp")


def test_double_mask_cancels_with_self_seed_disclosure():
    """Σ w_i·masked_i − Σ w_i·STD·PRG_self(b_i) == Σ w_i·p_i: pair masks
    cancel pairwise, self masks cancel via the disclosed per-round seeds."""
    import secrets as pysecrets

    addrs = ["a", "b", "c"]
    keys = {n: secagg.dh_keypair() for n in addrs}
    privs = {n: k[0] for n, k in keys.items()}
    weights = {"a": 5, "b": 7, "c": 9}
    pubs = {n: (keys[n][1], weights[n]) for n in addrs}
    self_seeds = {n: pysecrets.randbits(256) for n in addrs}
    rng = np.random.default_rng(1)
    params = {n: {"w": rng.normal(size=(8, 4)).astype(np.float32)} for n in addrs}

    masked = {}
    for n in addrs:
        u = ModelUpdate(params[n], [n], weights[n])
        masked[n] = secagg.mask_update(
            u, n, addrs, privs[n], pubs, "exp", 2, self_seed=self_seeds[n]
        )
    # the self mask makes the double-masked update differ from the
    # pair-only masked one (a snoop with all pair seeds still sees noise)
    pair_only = secagg.mask_update(
        ModelUpdate(params["a"], ["a"], weights["a"]), "a", addrs, privs["a"],
        pubs, "exp", 2,
    )
    assert not np.allclose(
        np.asarray(masked["a"].params["w"]), np.asarray(pair_only.params["w"])
    )

    w_total = sum(weights.values())
    true_avg = sum(weights[n] * params[n]["w"] for n in addrs) / w_total
    masked_avg_tree = {
        "w": sum(
            weights[n] * np.asarray(masked[n].params["w"], np.float64) for n in addrs
        ).astype(np.float32)
        / w_total
    }
    corr = secagg.self_mask_correction(
        masked_avg_tree, addrs, self_seeds, weights, round_no=2
    )
    clean = secagg.apply_dropout_correction(masked_avg_tree, corr, float(w_total))
    np.testing.assert_allclose(np.asarray(clean["w"]), true_avg, atol=1e-2)


def test_double_mask_e2e_share_and_reveal_flow():
    """A 3-node secure federation under SECAGG_DOUBLE_MASK: training
    converges, the wire carries share distributions and reveals, and every
    contributor's aggregate matches across nodes."""
    import jax

    from p2pfl_tpu.settings import set_test_settings

    set_test_settings()
    # 1-core host under a full-tier run: jitted fits from neighboring tests
    # starve the gossip threads; scale the waits with the load so a slow
    # machine cannot turn coverage/seed waits into spurious no-op rounds
    # (same rationale as the round-3 soak deflake)
    Settings.AGGREGATION_TIMEOUT *= 3
    Settings.SECAGG_RECOVERY_TIMEOUT *= 3
    Settings.VOTE_TIMEOUT *= 3
    Settings.SECURE_AGGREGATION = True
    assert Settings.SECAGG_DOUBLE_MASK  # default on
    seen: dict[str, int] = {"secagg_share": 0, "secagg_reveal": 0}
    data = FederatedDataset.synthetic_mnist(n_train=192, n_test=64)
    nodes = []
    for i in range(3):
        learner = JaxLearner(
            mlp(seed=i), data.partition(i, 3), batch_size=32
        )
        n = Node(learner=learner)

        orig_broadcast = n.protocol.broadcast

        def counting_broadcast(msg, _orig=orig_broadcast):
            cmd = getattr(msg, "cmd", None) or (msg[0] if isinstance(msg, tuple) else None)
            if cmd in seen:
                seen[cmd] += 1
            return _orig(msg)

        n.protocol.broadcast = counting_broadcast
        n.start()
        nodes.append(n)
    try:
        for n in nodes:
            full_connection(n, nodes)
        wait_convergence(nodes, 2, only_direct=True)
        nodes[0].set_start_learning(rounds=2, epochs=1)
        wait_to_finish(nodes, timeout=120)
        p0 = nodes[0].learner.get_parameters()
        for n in nodes[1:]:
            for a, b in zip(
                jax.tree.leaves(p0), jax.tree.leaves(n.learner.get_parameters())
            ):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), atol=2e-3
                )
        # every round: each node distributes shares and reveals its seed
        assert seen["secagg_share"] >= 3
        assert seen["secagg_reveal"] >= 3
    finally:
        for n in nodes:
            n.stop()


def test_dropped_node_self_seed_never_revealed():
    """The Bonawitz invariant at the holder level: once a member is treated
    as dropped in a round (need/recover observed), reveals for its self
    seed are refused by _secagg_self_unmask's gate."""
    from p2pfl_tpu.node_state import NodeState

    st = NodeState("a")
    st.set_experiment("exp", 1)
    st.train_set = ["a", "b", "c"]
    st.secagg_shares_held[(0, "b")] = (1, 12345)
    st.secagg_round_dropped.add((0, "b"))
    sent = []

    class _Proto:
        def broadcast(self, msg):
            sent.append(msg)

        def build_msg(self, cmd, args, round=0):  # noqa: A002
            return (cmd, list(args), round)

    class _FakeNode:
        addr = "a"

        def __init__(self):
            self.state = st
            self.protocol = _Proto()

        def learning_interrupted(self):
            return True  # don't wait in the resolve loop

        learner = None

    from p2pfl_tpu.stages.learning_stages import GossipModelStage

    agg = ModelUpdate({"w": np.zeros((2, 2), np.float32)}, ["b", "c"], 2)
    node = _FakeNode()

    class _L:
        def get_parameters(self):
            return {"w": np.zeros((2, 2), np.float32)}

    node.learner = _L()
    out = GossipModelStage._secagg_self_unmask(node, agg)
    # no reveal for b went out (invariant), and the round no-opped rather
    # than applying the still-masked aggregate
    assert not any(m[0] == "secagg_reveal" and m[1][1] == "b" for m in sent)
    assert out.noop_round


def test_self_seed_shamir_reconstruction_for_crashed_contributor():
    """The crash backstop end to end at the state level: contributor 'd'
    double-masked and died before revealing b_d. Node 'a' reconstructs it
    from its OWN held share plus two peers' revealed shares (t=3 of the 3
    holders), resolves every other seed from direct reveals, and strips
    the exact self-mask sum from the aggregate."""
    import secrets as pysecrets

    from p2pfl_tpu.node_state import NodeState
    from p2pfl_tpu.stages.learning_stages import GossipModelStage

    train = ["a", "b", "c", "d"]
    weights = {"a": 3, "b": 5, "c": 7, "d": 9}
    seeds = {n: pysecrets.randbits(256) for n in train}
    round_no = 0
    w_total = float(sum(weights.values()))
    template = {"w": np.zeros((6, 4), np.float32)}

    # the aggregate = clean weighted mean + Σ w_i·STD·PRG_self(b_i)/W
    clean = np.full((6, 4), 0.25, np.float32)
    masked = clean.copy()
    for n in train:
        m = secagg.self_mask(template, seeds[n], round_no)["w"]
        masked = masked + (weights[n] / w_total) * m

    st = NodeState("a")
    st.set_experiment("exp", 1)
    st.round = round_no
    st.train_set = list(train)
    st.secagg_samples = weights["a"]
    st.secagg_pubs = {n: (2, weights[n]) for n in ("b", "c", "d")}
    st.secagg_self_seed[round_no] = seeds["a"]
    # direct reveals from the living contributors b and c
    st.secagg_share_reveals[(round_no, "b", "b")] = (0, seeds["b"])
    st.secagg_share_reveals[(round_no, "c", "c")] = (0, seeds["c"])
    # d's seed: t = 3 of holders [a, b, c]; a holds its own share, b and c
    # revealed theirs — d itself revealed NOTHING (it crashed)
    shares = secagg.shamir_split(seeds["d"], 3, secagg.share_threshold(4))
    st.secagg_shares_held[(round_no, "d")] = shares[0]  # a's (x=1)
    st.secagg_share_reveals[(round_no, "d", "b")] = shares[1]
    st.secagg_share_reveals[(round_no, "d", "c")] = shares[2]

    sent = []

    class _Proto:
        def broadcast(self, msg):
            sent.append(msg)

        def build_msg(self, cmd, args, round=0):  # noqa: A002
            return (cmd, list(args), round)

    class _FakeNode:
        addr = "a"
        protocol = _Proto()
        state = st
        learner = None

        def learning_interrupted(self):
            return False

    agg = ModelUpdate({"w": masked}, list(train), sum(weights.values()))
    out = GossipModelStage._secagg_self_unmask(_FakeNode(), agg)
    assert not out.noop_round
    np.testing.assert_allclose(np.asarray(out.params["w"]), clean, atol=1e-3)
    # 'a' revealed its own seed (it contributed and is not conflicted)
    assert any(m[0] == "secagg_reveal" and m[1][1] == "a" for m in sent)


def test_split_brain_rescue_adopts_finalized_diffusion():
    """Pair recovery with a LIVE missing member (split-brain coverage: it
    contributed to peers, not to us) must skip the futile disclosure wait,
    reopen the aggregator in waiting mode, and adopt a recovered peer's
    finalized (secagg_clean) diffusion instead of no-opping."""
    from p2pfl_tpu.node_state import NodeState
    from p2pfl_tpu.stages.learning_stages import GossipModelStage

    Settings.SECURE_AGGREGATION = True
    Settings.SECAGG_RECOVERY_TIMEOUT = 2.0
    train = ["a", "b", "c"]
    clean = {"w": np.full((2, 2), 3.0, np.float32)}
    calls = {"waiting": None}

    class _Agg:
        def set_waiting_aggregated_model(self, nodes):
            calls["waiting"] = list(nodes)

        def wait_and_get_aggregation(self, timeout=None):
            return ModelUpdate(clean, list(train), 3, secagg_clean=True)

    class _Proto:
        def broadcast(self, msg):
            pass

        def build_msg(self, cmd, args, round=0):  # noqa: A002
            return (cmd, list(args), round)

        def get_neighbors(self, only_direct=False):
            return {"b": None, "c": None}  # the "missing" member c is LIVE

    st = NodeState("a")
    st.set_experiment("exp", 1)
    st.round = 0
    st.train_set = list(train)
    priv, _pub = secagg.dh_keypair()
    st.secagg_priv = priv
    st.secagg_samples = 5
    for n in ("b", "c"):
        _p, pub_n = secagg.dh_keypair()
        st.secagg_pubs[n] = (pub_n, 5)

    class _FakeNode:
        addr = "a"
        state = st
        protocol = _Proto()
        aggregator = _Agg()
        learner = None

        def learning_interrupted(self):
            return False

    # partial aggregate: only a and b contributed; c is missing but live
    agg = ModelUpdate({"w": np.zeros((2, 2), np.float32)}, ["a", "b"], 10)
    out = GossipModelStage._secagg_pair_recovery(_FakeNode(), agg)
    assert sorted(calls["waiting"]) == train  # aggregator reopened in waiting mode
    assert out.secagg_clean and not out.noop_round
    np.testing.assert_array_equal(np.asarray(out.params["w"]), clean["w"])
    # and the finalize wrapper passes the rescued (already clean) update
    # through without a self-unmask pass
    out2 = GossipModelStage._secagg_finalize(_FakeNode(), agg)
    assert out2.secagg_clean


def test_single_member_train_set_double_mask_no_crash():
    """ADVICE r4 regression: a lone train-set member under the default
    SECAGG_DOUBLE_MASK must not hit shamir_split(n=0) — peers=[] made the
    pub-key gate vacuously true and the raised ValueError aborted the
    experiment. mask_update already early-returns unmasked for lone
    members; the share-distribution block must be skipped the same way."""
    from p2pfl_tpu.learning.learner import DummyLearner
    from p2pfl_tpu.settings import set_test_settings

    set_test_settings()
    Settings.SECURE_AGGREGATION = True
    assert Settings.SECAGG_DOUBLE_MASK
    node = Node(learner=DummyLearner(value=3.0))
    node.start()
    try:
        node.set_start_learning(rounds=1, epochs=1)
        wait_to_finish([node], timeout=30)
        # the experiment completed (round advanced) rather than aborting
        assert node.state.round is None or node.state.round >= 1
        # fit() ran (value+1) and the unmasked lone aggregate was adopted
        v = float(np.asarray(node.learner.get_parameters()["w"]).mean())
        assert v == pytest.approx(4.0)
    finally:
        node.stop()


def test_secagg_mask_lone_member_direct_no_shamir_crash():
    """The precise ADVICE r4 repro: _secagg_mask with peers == [] (train
    set shrank to {self} between the call-site gate and the mask) used to
    enter the double-mask block — all() vacuously true — and raise
    ValueError from shamir_split(n=0), which is NOT a SecAggError and
    aborted the workflow. Must return the update unmasked instead."""
    from p2pfl_tpu.node_state import NodeState
    from p2pfl_tpu.stages.learning_stages import TrainStage

    st = NodeState("solo")
    st.train_set = {"solo"}
    st.round = 1
    st.experiment_name = "exp"
    st.secagg_priv, _pub = secagg.dh_keypair()

    class _Proto:
        def broadcast(self, msg):
            raise AssertionError("lone member must not distribute shares")

        def build_msg(self, cmd, args, round=0):  # noqa: A002
            return (cmd, args, round)

    class _FakeNode:
        addr = "solo"
        state = st
        protocol = _Proto()

        def learning_interrupted(self):
            return False

    assert Settings.SECAGG_DOUBLE_MASK
    Settings.SECURE_AGGREGATION = True
    u = ModelUpdate({"w": np.ones((2, 2), np.float32)}, ["solo"], 10)
    out = TrainStage._secagg_mask(_FakeNode(), u)
    assert out is not None
    np.testing.assert_array_equal(np.asarray(out.params["w"]), u.params["w"])


def _share_state(round_no=1):
    from p2pfl_tpu.node_state import NodeState

    st = NodeState("me")
    st.round = round_no
    st.experiment_name = "exp"
    priv_o, pub_o = secagg.dh_keypair()
    st.secagg_priv, my_pub = secagg.dh_keypair()
    st.secagg_pubs["owner"] = (pub_o, 5)
    key = secagg.dh_share_key(priv_o, my_pub, "exp")
    return st, key


def test_share_index_cap_derives_from_message():
    """ISSUE 2 satellite: the share-index sanity cap derives from the
    MESSAGE (one triple per holder in the sender's broadcast — x runs
    1..n_holders over its sorted holder list), not from our instantaneous
    train set. A >1024-member federation's high indices must be stored, and
    an index beyond the sender's own holder count rejected."""
    from p2pfl_tpu.commands.control import SecAggShareCommand

    st, key = _share_state()
    st.train_set = {f"n{i}" for i in range(1500)} | {"me", "owner"}
    cmd = SecAggShareCommand(st)
    ct = secagg.encrypt_share(12345, key, 1, "owner", "me").hex()
    # a 1400-holder broadcast (only our triple is real — foreign holders'
    # ciphertexts are never decrypted) with our index at 1400: stored
    filler = [e for i in range(1399) for e in (f"n{i}", str(i + 1), "00")]
    cmd.execute("owner", 1, "exp", *filler, "me", "1400", ct)
    assert st.secagg_shares_held.get((1, "owner")) == (1400, 12345)
    # an index beyond the sender's own holder list: rejected (not stored) —
    # a forged point at an unused x must not reach Lagrange reconstruction
    st.secagg_shares_held.clear()
    cmd.execute("owner", 1, "exp", *filler, "me", "1401", ct)
    assert (1, "owner") not in st.secagg_shares_held


def test_share_for_next_round_accepted_before_train_set_latches():
    """ISSUE 2 satellite regression: a share for round r+1 arriving from a
    fast peer BEFORE our local train set latches (len(train_set)=0) must be
    judged against the message's holder count, not our empty membership —
    the old instantaneous len(train_set)-vs-1024 cap made acceptance depend
    on arrival timing."""
    from p2pfl_tpu.commands.control import SecAggShareCommand

    st, key = _share_state(round_no=1)
    st.train_set = set()  # round r+1 share lands before our vote resolves
    cmd = SecAggShareCommand(st)
    ct = secagg.encrypt_share(777, key, 2, "owner", "me").hex()
    cmd.execute("owner", 2, "exp", "a", "1", "00", "me", "2", ct, "z", "3", "00")
    assert st.secagg_shares_held.get((2, "owner")) == (2, 777)
    # same early window, index past the 3-holder message: rejected
    st.secagg_shares_held.clear()
    cmd.execute("owner", 2, "exp", "a", "1", "00", "me", "4", ct, "z", "3", "00")
    assert (2, "owner") not in st.secagg_shares_held


def test_reveal_index_uncapped_for_large_federations():
    """ISSUE 18 satellite: the reveal x-range gate is the exact
    assigned-index rule, not a fixed ``max(2·|train_set|, 1024)`` cap — a
    >1024-member federation's high share indices must be stored."""
    from p2pfl_tpu.commands.control import SecAggRevealCommand
    from p2pfl_tpu.node_state import NodeState

    st = NodeState("me")
    st.round = 1
    st.experiment_name = "exp"
    members = sorted(f"n{i:04d}" for i in range(1500))
    st.train_set = list(members)
    owner = members[0]
    holders = sorted(m for m in members if m != owner)
    source = holders[1300]
    cmd = SecAggRevealCommand(st)
    cmd.execute(source, 1, "exp", owner, "1301", "ff")
    assert st.secagg_share_reveals.get((1, owner, source)) == (1301, 0xFF)
    # a wrong index is still rejected — the exact check is the real gate
    wrong = holders[10]
    cmd.execute(wrong, 1, "exp", owner, "99", "ff")
    assert (1, owner, wrong) not in st.secagg_share_reveals


def test_early_reveal_stashed_then_promoted_once_set_latches():
    """ISSUE 18 satellite: a share reveal for round r+1 arriving while this
    node is still in round r cannot be judged (the r+1 holder list hasn't
    latched) — it must be stashed and re-validated at consume time, not
    dropped against the stale round-r membership."""
    from p2pfl_tpu.commands.control import SecAggRevealCommand, promote_early_reveals
    from p2pfl_tpu.node_state import NodeState

    st = NodeState("me")
    st.round = 1
    st.experiment_name = "exp"
    st.train_set = ["me", "x"]  # round-1 set: next round's members absent
    cmd = SecAggRevealCommand(st)
    # legitimate round-2 share from a not-yet-member: stashed, not judged
    cmd.execute("b", 2, "exp", "a", "1", "aa")
    assert (2, "a", "b") not in st.secagg_share_reveals
    assert st.secagg_early_reveals.get((2, "a", "b")) == (1, 0xAA)
    # a forged future index is stashed too — it can only be judged later
    cmd.execute("c", 2, "exp", "a", "7", "bb")
    # round 2 latches: holders for owner "a" are [b, c, me] → b's index is 1
    st.round = 2
    st.train_set = ["a", "b", "c", "me"]
    promote_early_reveals(st)
    assert st.secagg_share_reveals.get((2, "a", "b")) == (1, 0xAA)
    # the index-7 stash fails the exact assigned-index check at promote time
    assert (2, "a", "c") not in st.secagg_share_reveals
    assert not st.secagg_early_reveals  # consumed: promoted or dropped


def test_stale_early_reveals_pruned():
    """Early stashes whose round has already passed are pruned, never
    promoted — the stash cannot grow without bound across rounds."""
    from p2pfl_tpu.commands.control import SecAggRevealCommand, promote_early_reveals
    from p2pfl_tpu.node_state import NodeState

    st = NodeState("me")
    st.round = 1
    st.experiment_name = "exp"
    st.train_set = ["me", "x"]
    cmd = SecAggRevealCommand(st)
    cmd.execute("b", 2, "exp", "a", "1", "aa")
    assert st.secagg_early_reveals
    st.round = 3
    st.train_set = ["a", "b", "me"]
    promote_early_reveals(st)
    assert not st.secagg_early_reveals
    assert (2, "a", "b") not in st.secagg_share_reveals
