"""Secure aggregation (pairwise masking, ``learning/secagg.py``).

The reference has no privacy layer; this is a beyond-parity capability:
DH key agreement over the gossip overlay, pairwise Gaussian masks that
cancel in the sample-weighted FedAvg sum, end-to-end federation with
SECURE_AGGREGATION on, and the device-side masking op on the mesh.
"""

import time

import numpy as np
import pytest

from p2pfl_tpu.communication.memory import MemoryRegistry
from p2pfl_tpu.learning import secagg
from p2pfl_tpu.learning.dataset import FederatedDataset
from p2pfl_tpu.learning.learner import JaxLearner
from p2pfl_tpu.learning.weights import ModelUpdate
from p2pfl_tpu.models import mlp
from p2pfl_tpu.node import Node
from p2pfl_tpu.settings import Settings
from p2pfl_tpu.utils import (
    check_equal_models,
    full_connection,
    wait_convergence,
    wait_to_finish,
)


@pytest.fixture(autouse=True)
def _clean():
    MemoryRegistry.reset()
    yield
    MemoryRegistry.reset()
    Settings.SECURE_AGGREGATION = False


def test_dh_pair_seed_symmetric():
    xa, pa = secagg.dh_keypair()
    xb, pb = secagg.dh_keypair()
    assert secagg.dh_pair_seed(xa, pb, "exp") == secagg.dh_pair_seed(xb, pa, "exp")
    # different experiment context → different seed
    assert secagg.dh_pair_seed(xa, pb, "exp") != secagg.dh_pair_seed(xa, pb, "exp2")


def _mask_for(addr, addrs, privs, pubs, params, num_samples, round_no=0):
    u = ModelUpdate(params, [addr], num_samples)
    return secagg.mask_update(u, addr, addrs, privs[addr], pubs, "exp", round_no)


def test_masks_cancel_in_weighted_fedavg():
    """Σ w_i · masked_i == Σ w_i · p_i once every pair is present."""
    addrs = ["a", "b", "c", "d"]
    keys = {n: secagg.dh_keypair() for n in addrs}
    privs = {n: k[0] for n, k in keys.items()}
    weights = {"a": 10, "b": 20, "c": 30, "d": 40}
    pubs = {n: (keys[n][1], weights[n]) for n in addrs}
    rng = np.random.default_rng(0)
    params = {n: {"w": rng.normal(size=(16, 8)).astype(np.float32)} for n in addrs}

    masked = {
        n: _mask_for(n, addrs, privs, pubs, params[n], weights[n]) for n in addrs
    }
    # individual masked models are far from the raw ones (privacy)
    for n in addrs:
        delta = np.asarray(masked[n].params["w"]) - params[n]["w"]
        assert np.std(delta) > 1.0, np.std(delta)

    w_total = sum(weights.values())
    true_avg = sum(weights[n] * params[n]["w"] for n in addrs) / w_total
    masked_avg = sum(
        weights[n] * np.asarray(masked[n].params["w"], np.float64) for n in addrs
    ) / w_total
    np.testing.assert_allclose(masked_avg, true_avg, atol=1e-3)


def test_mask_fresh_per_round():
    addrs = ["a", "b"]
    keys = {n: secagg.dh_keypair() for n in addrs}
    privs = {n: k[0] for n, k in keys.items()}
    pubs = {n: (k[1], 1) for n, k in keys.items()}
    p = {"w": np.zeros((4, 4), np.float32)}
    m0 = _mask_for("a", addrs, privs, pubs, p, 1, round_no=0)
    m1 = _mask_for("a", addrs, privs, pubs, p, 1, round_no=1)
    assert not np.allclose(np.asarray(m0.params["w"]), np.asarray(m1.params["w"]))


def test_unsafe_masking_raises_never_unmasked():
    """Missing keys / zero weight / non-fp32 params must raise SecAggError —
    an unmasked fallback would leave peers' pair masks uncancelled in a
    full-coverage aggregate, undetected noise."""
    from p2pfl_tpu.exceptions import SecAggError

    addrs = ["a", "b"]
    priv, pub = secagg.dh_keypair()
    priv_b, pub_b = secagg.dh_keypair()
    p32 = {"w": np.ones((2, 2), np.float32)}

    with pytest.raises(SecAggError, match="missing DH"):
        secagg.mask_update(ModelUpdate(p32, ["a"], 5), "a", addrs, priv, {}, "exp", 0)
    with pytest.raises(SecAggError, match="zero sample"):
        secagg.mask_update(ModelUpdate(p32, ["a"], 0), "a", addrs, priv, {"b": (pub_b, 5)}, "exp", 0)
    import jax.numpy as jnp

    p16 = {"w": jnp.ones((2, 2), jnp.bfloat16)}
    with pytest.raises(SecAggError, match="float32"):
        secagg.mask_update(ModelUpdate(p16, ["a"], 5), "a", addrs, priv, {"b": (pub_b, 5)}, "exp", 0)
    # lossy wire compression breaks cancellation — refused up front
    Settings.WIRE_COMPRESSION = "int8"
    try:
        with pytest.raises(SecAggError, match="lossless"):
            secagg.mask_update(ModelUpdate(p32, ["a"], 5), "a", addrs, priv, {"b": (pub_b, 5)}, "exp", 0)
    finally:
        Settings.WIRE_COMPRESSION = "none"


def test_degenerate_dh_keys_rejected():
    """pub ∈ {0, 1, p-1} makes the shared secret computable from public
    info (an active attacker could strip a victim's masks) — rejected at
    both the command layer and seed derivation."""
    from p2pfl_tpu.exceptions import SecAggError
    from p2pfl_tpu.commands.control import SecAggPubCommand
    from p2pfl_tpu.node_state import NodeState

    priv, _ = secagg.dh_keypair()
    for bad in (0, 1, secagg.DH_PRIME - 1, secagg.DH_PRIME):
        assert not secagg.valid_public_key(bad)
        with pytest.raises(SecAggError, match="degenerate"):
            secagg.dh_pair_seed(priv, bad, "exp")

    state = NodeState("me")
    cmd = SecAggPubCommand(state)
    cmd.execute("attacker", 0, "1", "5")  # pub = 1
    assert "attacker" not in state.secagg_pubs
    _, good = secagg.dh_keypair()
    cmd.execute("peer", 0, f"{good:x}", "0")  # degenerate sample count
    assert "peer" not in state.secagg_pubs
    cmd.execute("peer", 0, f"{good:x}", "5")
    assert state.secagg_pubs["peer"] == (good, 5)


def test_secagg_misconfig_aborts_experiment():
    """SecAgg + a robust aggregator (or lossy wire) must abort at
    StartLearning — Krum over masked noise would silently elect garbage."""
    from p2pfl_tpu.learning.aggregators.krum import Krum
    from p2pfl_tpu.learning.learner import DummyLearner
    from p2pfl_tpu.utils import wait_convergence

    Settings.SECURE_AGGREGATION = True
    nodes = [Node(learner=DummyLearner(), aggregator=Krum()) for _ in range(2)]
    for n in nodes:
        n.start()
    nodes[0].connect(nodes[1].addr)
    wait_convergence(nodes, 1, only_direct=True)
    nodes[0].set_start_learning(rounds=1, epochs=1)
    time.sleep(1.5)
    # the learning thread aborted in StartLearningStage: state cleared, no
    # training ran (DummyLearner.fit would have bumped the params)
    for n in nodes:
        assert n.state.round is None
        assert float(np.asarray(n.learner.get_parameters()["w"]).mean()) == 0.0
    for n in nodes:
        n.stop()


def test_secure_federation_end_to_end():
    """4-node memory federation with SECURE_AGGREGATION: every aggregator
    input is masked, yet the federation converges to equal, working models."""
    Settings.SECURE_AGGREGATION = True
    full = FederatedDataset.synthetic_mnist(n_train=1024, n_test=256)
    nodes = []
    for i in range(4):
        learner = JaxLearner(mlp(seed=i), full.partition(i, 4), batch_size=64)
        node = Node(learner=learner)
        node.start()
        nodes.append(node)
    for n in nodes:
        full_connection(n, nodes)
    wait_convergence(nodes, 3, only_direct=True)
    nodes[0].set_start_learning(rounds=2, epochs=1)
    wait_to_finish(nodes, timeout=120)
    check_equal_models(nodes)
    acc = nodes[0].learner.evaluate()["test_acc"]
    assert acc > 0.7, acc  # masks cancelled — model actually works
    for n in nodes:
        n.stop()


def test_masked_stack_on_mesh():
    """Device-side op: masking a node-stacked pytree leaves the weighted
    FedAvg unchanged while each slot's params are drowned in noise."""
    import jax
    import jax.numpy as jnp

    from p2pfl_tpu.ops.aggregation import fedavg

    n = 8
    key = jax.random.PRNGKey(0)
    stack = {"w": jax.random.normal(key, (n, 32, 16), jnp.float32)}
    weights = jnp.asarray([10.0, 20.0, 30.0, 40.0, 10.0, 20.0, 30.0, 40.0])

    masked = jax.jit(secagg.masked_stack)(stack, weights, jax.random.PRNGKey(7))
    per_slot_delta = jnp.std(masked["w"] - stack["w"], axis=(1, 2))
    assert bool((per_slot_delta > 0.5).all()), per_slot_delta

    w = weights / weights.sum()
    true_avg = jnp.einsum("n,nij->ij", w, stack["w"])
    masked_avg = jnp.einsum("n,nij->ij", w, masked["w"])
    np.testing.assert_allclose(np.asarray(masked_avg), np.asarray(true_avg), atol=1e-3)
